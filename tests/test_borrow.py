"""SchedulingBorrow — the cohort-borrowing A/B (ISSUE 19 tentpole d).

Tier-1 runs the small asymmetric-cohort variant on a FakeClock, both arms
(borrowing ON vs OFF — identical caps and arrivals, the OFF arm merely
drops the cohort field), and asserts the acceptance bar: ON raises
lender-pool utilization by a real margin, the lender's e2e p99 stays
within tolerance of the OFF arm, zero borrow-aware oversubscription at
every sampled instant, and the lender wake-up burst is funded by
reclaim-by-preemption.  The reference-size variant is slow-marked.
"""

import pytest

from kubernetes_tpu.perf import TEST_CASES, run_workload
from kubernetes_tpu.utils.clock import FakeClock


def _items_by_name(items, name):
    return [it for it in items if it.labels.get("Name") == name]


def _invariants(items):
    (inv,) = _items_by_name(items, "BorrowInvariants")
    return inv.data


def _tenant_map(items):
    return {it.labels["namespace"]: it.data
            for it in _items_by_name(items, "BorrowTenant")}


def _run(borrowing, **kw):
    params = dict(nodes=16, rounds=6, scale=8, cycles_per_round=60,
                  tick_s=0.05)
    params.update(kw)
    tc = TEST_CASES["SchedulingBorrow"](borrowing=borrowing, **params)
    return run_workload(tc, backend="oracle", now_fn=FakeClock())


class TestSchedulingBorrowSmall:
    """The tier-1 A/B: oracle backend, FakeClock, 16 nodes."""

    def test_borrowing_raises_pool_utilization(self):
        """The headline: with identical arrivals, borrowing lifts mean
        lender-pool utilization by a real margin — the lender's idle
        guaranteed headroom stops being stranded."""
        on = _invariants(_run(borrowing=True))
        off = _invariants(_run(borrowing=False))
        assert on["LoansOutstandingPeak"] > 0      # borrowing engaged
        assert off["LoansOutstandingPeak"] == 0.0  # OFF arm never borrows
        lift = on["PoolUtilizationMean"] - off["PoolUtilizationMean"]
        assert lift > 0.10, (
            f"borrowing ON mean pool utilization "
            f"{on['PoolUtilizationMean']:.3f} vs OFF "
            f"{off['PoolUtilizationMean']:.3f}: lift {lift:.3f} <= 0.10")

    def test_lender_wakeup_reclaims_and_p99_holds(self):
        """The lender's mid-run burst must be funded by reclaiming the
        borrower's loans — and doing so cannot move the lender's e2e p99
        beyond tolerance of the borrow-free arm."""
        items_on = _run(borrowing=True)
        items_off = _run(borrowing=False)
        on = _invariants(items_on)
        assert on["Reclaims"] > 0, "lender burst never triggered a reclaim"
        lender_on = _tenant_map(items_on)["borrow-lender"]
        lender_off = _tenant_map(items_off)["borrow-lender"]
        assert lender_on["E2eCount"] > 0 and lender_off["E2eCount"] > 0
        # reclaim adds at most a couple of housekeeping sweeps + eviction
        # latency (~2.5 FakeClock seconds observed); the fence is absolute
        # FakeClock seconds, generous but real — cooldown starvation of
        # the lender (the bug class this guards) measures ~10s here
        assert lender_on["E2eP99"] <= lender_off["E2eP99"] + 3.0, (
            f"lender e2e p99 moved from {lender_off['E2eP99']:.3f}s to "
            f"{lender_on['E2eP99']:.3f}s under borrowing")
        # every lender arrival eventually admitted: reclaim made the
        # guaranteed capacity real
        assert lender_on["Admitted"] == lender_off["Admitted"]

    def test_zero_oversubscription_both_arms(self):
        """Borrow-aware zero oversubscription at every sampled instant:
        no tenant above its own cap net of recorded loans, no cohort pool
        above its summed guaranteed capacity."""
        for borrowing in (True, False):
            inv = _invariants(_run(borrowing=borrowing))
            assert inv["OversubscriptionViolations"] == 0.0, (
                f"borrowing={borrowing}")

    def test_borrower_loans_attributed(self):
        """The borrower's over-cap admissions are recorded as loans (the
        BorrowedPeak evidence), never silent cap violations."""
        items = _run(borrowing=True)
        tenants = _tenant_map(items)
        assert tenants["borrow-hungry"]["BorrowedPeak"] > 0
        assert tenants["borrow-lender"]["BorrowedPeak"] == 0.0


class TestSchedulingSoakCohort:
    """ISSUE 19 satellite: the soak's borrowing arm — all three tenants in
    one cohort, zero hard+cohort oversubscription at every instant."""

    def test_soak_cohort_zero_oversubscription(self):
        tc = TEST_CASES["SchedulingSoak"](
            nodes=32, rounds=4, scale=6, cycles_per_round=80,
            flap=False, tick_s=0.05, cohort="soak-pool")
        items = run_workload(tc, backend="oracle", now_fn=FakeClock())
        (inv,) = _items_by_name(items, "SoakInvariants")
        assert inv.data["OversubscriptionViolations"] == 0.0
        tenants = {it.labels["namespace"]: it.data
                   for it in _items_by_name(items, "SoakTenant")}
        assert sum(t["Admitted"] for t in tenants.values()) > 0


@pytest.mark.slow
class TestSchedulingBorrowReference:
    def test_reference_size(self):
        on = _invariants(_run(
            borrowing=True, nodes=200, rounds=10, scale=40,
            cycles_per_round=120))
        off = _invariants(_run(
            borrowing=False, nodes=200, rounds=10, scale=40,
            cycles_per_round=120))
        assert on["OversubscriptionViolations"] == 0.0
        assert off["OversubscriptionViolations"] == 0.0
        assert on["Reclaims"] > 0
        assert (on["PoolUtilizationMean"]
                - off["PoolUtilizationMean"]) > 0.10
