"""Driver-gate entrypoint tests.

Round 1 failed both driver gates not in the core programs but in the
entrypoints' environment handling: bench.py crashed on TPU backend-init
failure (BENCH_r01 rc=1) and dryrun_multichip hung under the ambient
`JAX_PLATFORMS=axon` (MULTICHIP_r01 rc=124).  These tests run the real
entrypoints in subprocesses under a deliberately broken ambient platform
(`JAX_PLATFORMS=tpu` on a box with no TPU plugin) and assert they still
succeed — i.e. they self-force / fall back rather than trusting the env.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _broken_ambient_env(**extra):
    env = dict(os.environ)
    # Simulate the driver's ambient env: a platform selection that cannot
    # initialize on this machine, and no virtual-device forcing. "cuda" is
    # guaranteed absent in this image (r2 used "tpu", which stopped being
    # broken the moment the relay came back up), and the axon sitecustomize
    # must come off PYTHONPATH — it force-registers the relay platform no
    # matter what JAX_PLATFORMS says.
    env["JAX_PLATFORMS"] = "cuda"
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in env.get("PYTHONPATH", "").split(os.pathsep)
        if p and "axon" not in p
    )
    env.pop("XLA_FLAGS", None)
    env.pop("KTPU_TEST_PLATFORM", None)
    env.update(extra)
    return env


@pytest.mark.slow
def test_dryrun_multichip_self_forces_virtual_mesh():
    proc = subprocess.run(
        [sys.executable, "-c",
         "import __graft_entry__ as g; g.dryrun_multichip(8); print('dryrun ok')"],
        cwd=REPO, env=_broken_ambient_env(), capture_output=True, text=True,
        timeout=300,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "dryrun ok" in proc.stdout


@pytest.mark.slow
def test_bench_emits_json_under_broken_platform(tmp_path):
    env = _broken_ambient_env(
        BENCH_NODES="64", BENCH_INIT_PODS="8", BENCH_PODS="8",
        BENCH_SEQ_PODS="4", BENCH_BATCH="8", BENCH_PROBE_TIMEOUT="10",
        BENCH_MATRIX="0",  # matrix rows run at full reference sizes
    )
    # Write-once artifacts (VERDICT r4 weak #5): a smoke run must never
    # clobber the round's TREND.*; run from a tmp cwd without BENCH_RECORD
    # and assert the recorded trend is byte-identical afterwards.
    env.pop("BENCH_RECORD", None)
    trend_path = os.path.join(REPO, "TREND.json")
    before = open(trend_path, "rb").read() if os.path.exists(trend_path) else None
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")], cwd=tmp_path, env=env,
        capture_output=True, text=True, timeout=420,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = proc.stdout.strip().splitlines()[-1]
    rec = json.loads(line)
    assert rec["unit"] == "pods/s"
    assert rec["platform"] == "cpu-fallback"
    assert rec["baseline"] == "python-oracle"
    assert rec["value"] > 0, rec
    after = open(trend_path, "rb").read() if os.path.exists(trend_path) else None
    assert after == before, "smoke bench run must not rewrite TREND.json"
