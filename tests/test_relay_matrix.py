"""Relay diagnostics (one blocking device read per batch cycle) and the
full-matrix perf CLI (ROADMAP r3 infra items 9/10)."""

import json
import os
import subprocess
import sys

from kubernetes_tpu.api.wrappers import make_node, make_pod
from kubernetes_tpu.apiserver import ClusterStore
from kubernetes_tpu.backend import TPUScheduler
from kubernetes_tpu.utils import relay


class TestOneSyncInvariant:
    def test_feasible_batches_cost_one_read_each(self):
        store = ClusterStore()
        sched = TPUScheduler(store, batch_size=64)
        for i in range(32):
            store.create_node(
                make_node(f"n{i}").capacity({"cpu": "8", "memory": "16Gi",
                                             "pods": 50}).obj())
        with relay.track() as counts:
            for i in range(128):
                store.create_pod(
                    make_pod(f"p{i}").req({"cpu": "100m", "memory": "128Mi"}).obj())
            sched.run_until_settled()
        assert sched.metrics["scheduled"] == 128
        batches = sched.batch_counter
        assert batches > 0
        # THE invariant: one commit-read per dispatched batch, nothing else
        assert counts["commit-read"] == batches, (dict(counts), batches)
        assert counts.get("diagnosis-read", 0) == 0  # no failures
        assert counts.get("preempt-read", 0) == 0

    def test_failures_add_no_extra_reads(self):
        """THE overlap guard (ISSUE 5 tier-1): failure diagnosis rides the
        packed result block, so a batch with failures still costs exactly
        one blocking sync — a regression reintroducing per-array reads
        (separate first_fail/node_idx materializations) fails here."""
        store = ClusterStore()
        sched = TPUScheduler(store, batch_size=32)
        store.create_node(
            make_node("n0").capacity({"cpu": "1", "memory": "1Gi", "pods": 4}).obj())
        with relay.track() as counts:
            for i in range(8):
                store.create_pod(make_pod(f"big{i}").req({"cpu": "4"}).obj())
            sched.run_until_settled(max_no_progress=3)
        assert sched.batch_counter > 0
        # the packed block covers diagnosis: no separate first_fail read
        assert counts.get("diagnosis-read", 0) == 0, dict(counts)
        # AT MOST one blocking sync per committed batch, in total: the
        # commit-read itself and nothing else (no preempt screen here —
        # the futility shortcut proves no victim could exist)
        assert counts["commit-read"] == sched.batch_counter
        assert sum(counts.values()) == counts["commit-read"], dict(counts)

    def test_mixed_success_failure_batches_one_sync_each(self):
        """Mixed batches (some pods place, some fail): still one blocking
        read per batch — success commits and failure diagnosis land from
        the same packed block."""
        store = ClusterStore()
        sched = TPUScheduler(store, batch_size=16)
        for i in range(2):
            store.create_node(
                make_node(f"n{i}").capacity({"cpu": "4", "memory": "8Gi",
                                             "pods": 50}).obj())
        with relay.track() as counts:
            for i in range(6):
                store.create_pod(
                    make_pod(f"ok{i}").req({"cpu": "100m", "memory": "64Mi"}).obj())
            for i in range(2):
                store.create_pod(make_pod(f"big{i}").req({"cpu": "32"}).obj())
            sched.run_until_settled(max_no_progress=3)
        assert sched.metrics["scheduled"] == 6
        assert counts.get("diagnosis-read", 0) == 0, dict(counts)
        assert counts["commit-read"] == sched.batch_counter
        assert sum(counts.values()) == counts["commit-read"], dict(counts)

    def test_track_is_scoped(self):
        relay.count_sync("outside")  # no active tracker: must be a no-op
        with relay.track() as c:
            relay.count_sync("inside")
        assert dict(c) == {"inside": 1}


class TestPerfMatrixCLI:
    def test_matrix_smoke(self, tmp_path):
        out = tmp_path / "artifacts"
        env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH="/root/repo")
        r = subprocess.run(
            [sys.executable, "-m", "kubernetes_tpu.perf",
             "--backend", "tpu", "--scale", "0.02", "--out", str(out),
             "--cases", "SchedulingBasic,Unschedulable"],
            capture_output=True, text=True, timeout=600, env=env,
            cwd="/root/repo")
        assert r.returncode == 0, r.stderr[-2000:]
        basic = json.loads((out / "SchedulingBasic.json").read_text())
        names = [it["labels"].get("Name") for it in basic["dataItems"]]
        assert "SchedulingThroughput" in names
        summary = json.loads((out / "summary.json").read_text())
        assert summary["failures"] == 0 and summary["cases"] == 2

    def test_probe_platform_forced_cpu(self, monkeypatch):
        monkeypatch.setenv("JAX_PLATFORMS", "cpu")
        platform, diag = relay.probe_platform()
        assert platform == "cpu" and diag["outcome"] == "forced-cpu"
