"""CRD-shaped extension path (VERDICT r4 item 10): dynamic kind registration
on the store, CRUD + watch journal + informers over the generic machinery,
HTTP serving under /apis/{group}/{version}/..., and the scheduler's dynamic
event handlers for plugin-requested GVKs
(reference: staging/src/k8s.io/apiextensions-apiserver, eventhandlers.go:249).
"""

import json
import urllib.request

from kubernetes_tpu.api.types import (
    CustomResource, CustomResourceDefinition, ObjectMeta,
)
from kubernetes_tpu.apiserver import ClusterStore
from kubernetes_tpu.client.informer import SharedInformerFactory


def _crd(kind="TpuTopology", plural="tputopologies", group="ktpu.io",
         namespaced=False):
    return CustomResourceDefinition(
        meta=ObjectMeta(name=f"{plural}.{group}", namespace=""),
        group=group, version="v1", kind=kind, plural=plural,
        namespaced=namespaced)


def _cr(name, **spec):
    return CustomResource(meta=ObjectMeta(name=name, namespace="default"),
                          api_version="ktpu.io/v1", kind="TpuTopology",
                          spec=dict(spec))


class TestStoreDynamicKinds:
    def test_register_and_crud(self):
        store = ClusterStore()
        store.create_crd(_crd())
        store.create_object("TpuTopology", _cr("mesh-a", chips=8))
        got = store.get_object("TpuTopology", "mesh-a")
        assert got.spec["chips"] == 8
        objs, _rv = store.list_objects("TpuTopology")
        assert len(objs) == 1
        store.delete_object("TpuTopology", "mesh-a")
        assert store.get_object("TpuTopology", "mesh-a") is None

    def test_namespaced_custom_kind_keys(self):
        store = ClusterStore()
        store.create_crd(_crd(kind="Widget", plural="widgets", namespaced=True))
        w = CustomResource(meta=ObjectMeta(name="w1", namespace="team-a"),
                           kind="Widget")
        store.create_object("Widget", w)
        assert store.get_object("Widget", "team-a/w1") is not None

    def test_informer_over_custom_kind(self):
        store = ClusterStore()
        store.create_crd(_crd())
        factory = SharedInformerFactory(store)
        seen = []
        inf = factory.informer_for("TpuTopology")
        inf.add_event_handler(lambda e, old, new: seen.append((e, (new or old).meta.name)))
        store.create_object("TpuTopology", _cr("mesh-b", chips=16))
        factory.pump()
        assert ("add", "mesh-b") in seen

    def test_duplicate_kind_conflict(self):
        import pytest

        from kubernetes_tpu.apiserver.store import Conflict

        store = ClusterStore()
        store.create_crd(_crd())
        with pytest.raises(Conflict):
            store.create_crd(_crd())


class TestSchedulerDynamicHandlers:
    def test_custom_gvk_event_reactivates_unschedulable_pods(self):
        """A plugin registering interest in a CRD kind gets failed pods
        re-queued when such an object changes (dynamic informers,
        eventhandlers.go:249)."""
        from kubernetes_tpu.framework.types import (
            ALL, ClusterEvent, GVK, QueuedPodInfo)
        from kubernetes_tpu.scheduler import Scheduler
        from kubernetes_tpu.api.wrappers import make_pod

        store = ClusterStore()
        store.create_crd(_crd())
        sched = Scheduler(store)
        # simulate the plugin-requested GVK in the queue's event map and the
        # dynamic handler wiring
        gvk = GVK("TpuTopology")
        sched.queue.cluster_event_map[ClusterEvent(gvk, ALL)] = {"CustomPlugin"}
        store.add_event_handler(
            "TpuTopology",
            lambda e, old, new: sched.queue.move_all_to_active_or_backoff_queue(
                ClusterEvent(gvk, ALL)))
        qp = QueuedPodInfo(pod=make_pod("stuck").obj())
        qp.unschedulable_plugins = {"CustomPlugin"}
        sched.queue.add_unschedulable_if_not_present(qp, 0)
        assert sched.queue.pending_pods()["unschedulable"] == 1
        store.create_object("TpuTopology", _cr("mesh-c"))
        pending = sched.queue.pending_pods()
        assert pending["unschedulable"] == 0  # moved to active/backoff
        assert pending["active"] + pending["backoff"] == 1


class TestHTTPServing:
    def test_crd_crud_over_http(self):
        from kubernetes_tpu.apiserver.http import serve_api

        store = ClusterStore()
        server, port = serve_api(store)
        base = f"http://127.0.0.1:{port}"
        try:
            # register the CRD over the wire
            crd_doc = {"apiVersion": "apiextensions.k8s.io/v1",
                       "kind": "CustomResourceDefinition",
                       "metadata": {"name": "tputopologies.ktpu.io"},
                       "group": "ktpu.io", "version": "v1",
                       "kind_": "TpuTopology"}
            # the store path registers kinds; HTTP CRD POST goes through the
            # generic object path — register directly for the dynamic route
            store.create_crd(_crd())
            body = json.dumps({
                "apiVersion": "ktpu.io/v1", "kind": "TpuTopology",
                "metadata": {"name": "mesh-h", "namespace": "default"},
                "spec": {"chips": 32},
            }).encode()
            req = urllib.request.Request(
                f"{base}/apis/ktpu.io/v1/tputopologies", data=body,
                headers={"Content-Type": "application/json"}, method="POST")
            with urllib.request.urlopen(req) as resp:
                assert resp.status in (200, 201)
            with urllib.request.urlopen(
                    f"{base}/apis/ktpu.io/v1/tputopologies/mesh-h") as resp:
                doc = json.loads(resp.read())
            assert doc["spec"]["chips"] == 32
            with urllib.request.urlopen(
                    f"{base}/apis/ktpu.io/v1/tputopologies") as resp:
                lst = json.loads(resp.read())
            assert len(lst["items"]) == 1
        finally:
            server.shutdown()


class TestWALRestore:
    def test_crd_and_custom_objects_survive_restore(self, tmp_path):
        """WAL/snapshot restore must re-register dynamic kinds before the
        custom objects that depend on them (etcd durability story, §5.4)."""
        from kubernetes_tpu.apiserver.wal import attach_wal, restore

        path = str(tmp_path / "wal.log")
        store = ClusterStore()
        attach_wal(store, path)
        store.create_crd(_crd())
        store.create_object("TpuTopology", _cr("mesh-w", chips=4))
        back = restore(path)
        got = back.get_object("TpuTopology", "mesh-w")
        assert got is not None and got.spec["chips"] == 4
        # the restored store keeps serving the kind
        back.create_object("TpuTopology", _cr("mesh-w2", chips=2))
        assert back.get_object("TpuTopology", "mesh-w2") is not None

    def test_snapshot_compaction_keeps_dynamic_kinds(self, tmp_path):
        from kubernetes_tpu.apiserver.wal import attach_wal, restore

        path = str(tmp_path / "wal.log")
        store = ClusterStore()
        wal = attach_wal(store, path)
        store.create_crd(_crd())
        store.create_object("TpuTopology", _cr("mesh-s", chips=1))
        wal.snapshot(store)  # compact: objects now live in the snapshot file
        back = restore(path)
        assert back.get_object("TpuTopology", "mesh-s").spec["chips"] == 1
