"""End-to-end scheduler tests against the in-process cluster store —
the analog of test/integration/scheduler/ (real scheduler, real queue/cache,
no kubelet: pods only get bound)."""

from kubernetes_tpu.api.types import LabelSelector
from kubernetes_tpu.api.wrappers import make_node, make_pod
from kubernetes_tpu.apiserver import ClusterStore
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.utils.clock import FakeClock


def mkcluster(n_nodes=4, cpu="4", mem="8Gi", pods=110):
    store = ClusterStore()
    clock = FakeClock()
    sched = Scheduler(store, now_fn=clock)
    sched.clock = clock
    for i in range(n_nodes):
        store.create_node(
            make_node(f"node-{i}").capacity({"cpu": cpu, "memory": mem, "pods": pods})
            .label("zone", f"z{i % 2}").obj()
        )
    return store, sched


def settle(sched, rounds=3):
    """Drain; between rounds advance past the max backoff so moved pods leave
    backoffQ deterministically."""
    for _ in range(rounds):
        sched.run_until_settled()
        sched.clock.advance(10.1)
    sched.run_until_settled()


def bound_pods(store):
    return {k: p.spec.node_name for k, p in store.pods.items() if p.spec.node_name}


class TestBasicScheduling:
    def test_all_pods_bound(self):
        store, sched = mkcluster(4)
        for i in range(12):
            store.create_pod(make_pod(f"p{i}").req({"cpu": "500m", "memory": "512Mi"}).obj())
        settle(sched)
        assert len(bound_pods(store)) == 12
        assert sched.metrics["scheduled"] == 12

    def test_spreads_by_least_allocated(self):
        store, sched = mkcluster(4)
        for i in range(8):
            store.create_pod(make_pod(f"p{i}").req({"cpu": "1"}).obj())
        settle(sched)
        per_node = {}
        for _k, n in bound_pods(store).items():
            per_node[n] = per_node.get(n, 0) + 1
        # LeastAllocated balances: every node gets exactly 2
        assert sorted(per_node.values()) == [2, 2, 2, 2]

    def test_unschedulable_stays_pending(self):
        store, sched = mkcluster(1, cpu="2")
        store.create_pod(make_pod("big").req({"cpu": "4"}).obj())
        settle(sched)
        assert bound_pods(store) == {}
        assert sched.metrics["unschedulable"] >= 1
        assert len(sched.queue) == 1

    def test_node_add_reactivates_unschedulable(self):
        store, sched = mkcluster(1, cpu="2")
        store.create_pod(make_pod("big").req({"cpu": "4"}).obj())
        settle(sched)
        assert bound_pods(store) == {}
        # a new big node fires NodeAdd -> NodeResourcesFit registered interest
        store.create_node(make_node("big-node").capacity({"cpu": "8", "memory": "8Gi", "pods": 10}).obj())
        settle(sched)
        assert bound_pods(store) == {"default/big": "big-node"}

    def test_pod_delete_reactivates(self):
        store, sched = mkcluster(1, cpu="2", pods=10)
        store.create_pod(make_pod("holder").req({"cpu": "2"}).obj())
        settle(sched)
        store.create_pod(make_pod("waiter").req({"cpu": "2"}).obj())
        settle(sched)
        assert "default/waiter" not in bound_pods(store)
        store.delete_pod("default/holder")
        settle(sched)
        assert bound_pods(store).get("default/waiter") == "node-0"

    def test_priority_order(self):
        store, sched = mkcluster(1, cpu="2", pods=10)
        # both pending before any cycle runs; only one fits
        store.create_pod(make_pod("low").priority(1).req({"cpu": "2"}).obj())
        store.create_pod(make_pod("high").priority(100).req({"cpu": "2"}).obj())
        settle(sched)
        assert bound_pods(store).get("default/high") == "node-0"
        assert "default/low" not in bound_pods(store)

    def test_skip_already_bound(self):
        store, sched = mkcluster(1)
        store.create_pod(make_pod("p").node("node-0").obj())  # arrives pre-bound
        settle(sched)
        assert sched.metrics["schedule_attempts"] == 0


class TestPluginsE2E:
    def test_taints_and_tolerations(self):
        store = ClusterStore()
        clock = FakeClock()
        sched = Scheduler(store, now_fn=clock)
        sched.clock = clock
        store.create_node(make_node("tainted").capacity({"cpu": "4", "memory": "8Gi", "pods": 10})
                          .taint("dedicated", "gpu", "NoSchedule").obj())
        store.create_node(make_node("open").capacity({"cpu": "4", "memory": "8Gi", "pods": 10}).obj())
        store.create_pod(make_pod("normal").req({"cpu": "1"}).obj())
        store.create_pod(make_pod("gpu-ok").req({"cpu": "1"})
                         .toleration(key="dedicated", operator="Equal", value="gpu", effect="NoSchedule")
                         .node_selector({"kubernetes.io/hostname": "tainted"}).obj())
        settle(sched)
        b = bound_pods(store)
        assert b["default/normal"] == "open"
        assert b["default/gpu-ok"] == "tainted"

    def test_node_affinity_e2e(self):
        store, sched = mkcluster(4)
        store.create_pod(make_pod("pinned").node_affinity_in("zone", ["z1"]).obj())
        settle(sched)
        node = bound_pods(store)["default/pinned"]
        assert node in ("node-1", "node-3")

    def test_topology_spread_e2e(self):
        store, sched = mkcluster(4)
        sel = LabelSelector(match_labels={"app": "web"})
        for i in range(4):
            store.create_pod(
                make_pod(f"web-{i}").label("app", "web").req({"cpu": "100m"})
                .spread_constraint(1, "zone", selector=sel).obj()
            )
        settle(sched)
        zones = {}
        for _k, n in bound_pods(store).items():
            z = store.nodes[n].meta.labels["zone"]
            zones[z] = zones.get(z, 0) + 1
        assert zones == {"z0": 2, "z1": 2}  # maxSkew 1 forces even split

    def test_pod_anti_affinity_e2e(self):
        store, sched = mkcluster(4)
        sel = LabelSelector(match_labels={"app": "db"})
        for i in range(4):
            store.create_pod(
                make_pod(f"db-{i}").label("app", "db").req({"cpu": "100m"})
                .pod_affinity("kubernetes.io/hostname", sel, anti=True).obj()
            )
        settle(sched)
        nodes = list(bound_pods(store).values())
        assert len(set(nodes)) == 4  # one per node

    def test_pod_affinity_colocation(self):
        store, sched = mkcluster(4)
        store.create_pod(make_pod("db").label("app", "db").req({"cpu": "100m"}).obj())
        settle(sched)
        db_node = bound_pods(store)["default/db"]
        db_zone = store.nodes[db_node].meta.labels["zone"]
        store.create_pod(
            make_pod("web").req({"cpu": "100m"})
            .pod_affinity("zone", LabelSelector(match_labels={"app": "db"})).obj()
        )
        settle(sched)
        web_node = bound_pods(store)["default/web"]
        assert store.nodes[web_node].meta.labels["zone"] == db_zone


class TestCacheBehavior:
    def test_assume_visible_to_next_cycle(self):
        # two pods, one node with capacity for one: the second must see the
        # first's assumed resources and fail
        store, sched = mkcluster(1, cpu="2", pods=10)
        store.create_pod(make_pod("a").req({"cpu": "2"}).obj())
        store.create_pod(make_pod("b").req({"cpu": "2"}).obj())
        settle(sched)
        assert len(bound_pods(store)) == 1

    def test_incremental_snapshot_generation(self):
        store, sched = mkcluster(2)
        store.create_pod(make_pod("a").req({"cpu": "1"}).obj())
        settle(sched)
        sched.cache.update_snapshot(sched.snapshot)  # absorb post-cycle assume/confirm
        g1 = sched.snapshot.generation
        # no changes -> snapshot generation stable
        sched.cache.update_snapshot(sched.snapshot)
        assert sched.snapshot.generation == g1
        store.create_pod(make_pod("b").req({"cpu": "1"}).obj())
        settle(sched)
        sched.cache.update_snapshot(sched.snapshot)
        assert sched.snapshot.generation > g1
