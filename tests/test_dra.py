"""Dynamic Resource Allocation: resource.k8s.io kinds, the resourceclaim
controller, the DynamicResources plugin, and the TPU batched
claim-feasibility mask (oracle↔kernel parity + no-fallback acceptance)."""

import random

import numpy as np
import pytest

from kubernetes_tpu.api import dra
from kubernetes_tpu.api.types import (
    ObjectMeta,
    PodSchedulingContext,
    ResourceClaim,
    ResourceClaimTemplate,
    ResourceClass,
)
from kubernetes_tpu.api.wrappers import make_node, make_pod
from kubernetes_tpu.apiserver.store import ClusterStore
from kubernetes_tpu.client.informer import SharedInformerFactory
from kubernetes_tpu.controllers.resourceclaim import ResourceClaimController
from kubernetes_tpu.scheduler.scheduler import Scheduler


def drive_until(sched, store, pod_key, timeout_s=8.0):
    """Drive a scheduler through backoff-gated retries (real-clock backoff)
    until the pod binds or the timeout passes."""
    import time

    deadline = time.time() + timeout_s
    while time.time() < deadline:
        if store.get_pod(pod_key).spec.node_name:
            return
        time.sleep(0.02)
        sched.queue.flush_backoff_completed()
        sched.run_until_settled()


def mk_store(n_nodes=4, attrs_fn=None):
    store = ClusterStore()
    for i in range(n_nodes):
        nw = make_node(f"node-{i}").capacity(
            {"cpu": "8", "memory": "16Gi", "pods": 32})
        if attrs_fn is not None:
            nw.device_attrs(attrs_fn(i))
        store.create_node(nw.obj())
    return store


def tpu_attrs(i):
    return {"tpu.dev/cores": 8 if i % 2 else 2,
            "tpu.dev/gen": "v5" if i % 2 else "v4"}


def add_class(store, name="tpu.example.com", selectors=None):
    store.create_object("ResourceClass", ResourceClass(
        meta=ObjectMeta(name=name, namespace=""), driver_name=name,
        selectors=dict(selectors or {})))


def add_claim(store, name, cls="tpu.example.com", selectors=None, ns="default"):
    store.create_object("ResourceClaim", ResourceClaim(
        meta=ObjectMeta(name=name, namespace=ns),
        resource_class_name=cls, selectors=dict(selectors or {})))


# ---------------------------------------------------------------------------
# selector model


class TestSelectors:
    def test_parse_ops(self):
        assert dra.parse_selector("k", ">=4").op == dra.OP_GE
        assert dra.parse_selector("k", "<=4").op == dra.OP_LE
        assert dra.parse_selector("k", ">4").op == dra.OP_GT
        assert dra.parse_selector("k", "<4").op == dra.OP_LT
        assert dra.parse_selector("k", "!=v5").op == dra.OP_NE
        assert dra.parse_selector("k", "==v5").op == dra.OP_EQ
        bare = dra.parse_selector("k", "v5")
        assert bare.op == dra.OP_EQ and bare.operand == "v5"
        num = dra.parse_selector("k", 4)
        assert num.operand_kind == dra.KIND_INT and num.operand == 4

    def test_match_semantics(self):
        attrs = {"cores": 8, "gen": "v5"}
        assert dra.parse_selector("cores", ">=4").matches(attrs)
        assert not dra.parse_selector("cores", ">8").matches(attrs)
        assert dra.parse_selector("gen", "v5").matches(attrs)
        assert not dra.parse_selector("gen", "!=v5").matches(attrs)
        assert dra.parse_selector("gen", "!=v4").matches(attrs)
        # absent attribute never matches, any operator
        assert not dra.parse_selector("missing", "!=x").matches(attrs)
        # type mismatch: ordering op on a string attr
        assert not dra.parse_selector("gen", ">=4").matches(attrs)
        # int/string equality never crosses types
        assert not dra.parse_selector("cores", "8x").matches(attrs)


# ---------------------------------------------------------------------------
# WAL round-trip (satellite: every new kind must survive snapshot/restore,
# including an allocated claim's status — the 47c55c3 lesson)


class TestWALRoundTrip:
    def test_all_four_kinds_and_allocated_status(self, tmp_path):
        from kubernetes_tpu.apiserver.wal import attach_wal, restore

        path = str(tmp_path / "store.wal")
        store = ClusterStore()
        attach_wal(store, path)
        add_class(store, selectors={"tpu.dev/gen": "v5"})
        store.create_object("ResourceClaimTemplate", ResourceClaimTemplate(
            meta=ObjectMeta(name="tmpl"), resource_class_name="tpu.example.com",
            selectors={"tpu.dev/cores": ">=4"}))
        add_claim(store, "c1", selectors={"tpu.dev/cores": ">=4"})
        store.allocate_claim("default/c1", "node-7", "default/p1")
        store.create_object("PodSchedulingContext", PodSchedulingContext(
            meta=ObjectMeta(name="p1"), selected_node="node-7",
            potential_nodes=("node-7", "node-8")))

        restored = restore(path)
        rc = restored.get_object("ResourceClass", "tpu.example.com")
        assert rc.selectors == {"tpu.dev/gen": "v5"}
        tmpl = restored.get_object("ResourceClaimTemplate", "default/tmpl")
        assert tmpl.resource_class_name == "tpu.example.com"
        assert tmpl.selectors == {"tpu.dev/cores": ">=4"}
        claim = restored.get_object("ResourceClaim", "default/c1")
        assert claim.allocated_node == "node-7"
        assert claim.reserved_for == ("default/p1",)
        ctx = restored.get_object("PodSchedulingContext", "default/p1")
        assert ctx.selected_node == "node-7"
        assert ctx.potential_nodes == ("node-7", "node-8")

    def test_snapshot_compaction_covers_dra_kinds(self, tmp_path):
        from kubernetes_tpu.apiserver.wal import attach_wal, restore

        path = str(tmp_path / "store.wal")
        store = ClusterStore()
        wal = attach_wal(store, path)
        add_class(store)
        add_claim(store, "c1")
        wal.snapshot(store)  # kinds must survive via the snapshot alone
        restored = restore(path)
        assert restored.get_object("ResourceClass", "tpu.example.com") is not None
        assert restored.get_object("ResourceClaim", "default/c1") is not None

    def test_scheme_wire_roundtrip(self):
        from kubernetes_tpu.api.scheme import default_scheme

        scheme = default_scheme()
        claim = ResourceClaim(
            meta=ObjectMeta(name="c", namespace="ns1"),
            resource_class_name="tpu.example.com",
            selectors={"tpu.dev/cores": ">=4"},
            allocated_node="n3", reserved_for=("ns1/p",))
        doc = scheme.encode(claim)
        assert doc["apiVersion"] == "resource.k8s.io/v1alpha2"
        back = scheme.decode(doc)
        assert back.resource_class_name == "tpu.example.com"
        assert back.allocated_node == "n3"
        assert back.reserved_for == ("ns1/p",)


# ---------------------------------------------------------------------------
# resourceclaim controller


def mk_controller(store):
    factory = SharedInformerFactory(store)
    ctrl = ResourceClaimController(store, factory)
    factory.wait_for_cache_sync()
    return factory, ctrl


def pump(factory, ctrl, rounds=3):
    for _ in range(rounds):
        factory.pump()
        ctrl.sync_once()


class TestResourceClaimController:
    def test_materializes_template_claims(self):
        store = mk_store()
        add_class(store)
        store.create_object("ResourceClaimTemplate", ResourceClaimTemplate(
            meta=ObjectMeta(name="tmpl"), resource_class_name="tpu.example.com",
            selectors={"tpu.dev/cores": ">=4"}))
        factory, ctrl = mk_controller(store)
        store.create_pod(
            make_pod("p").req({"cpu": "1"})
            .resource_claim("dev", template_name="tmpl").obj())
        pump(factory, ctrl)
        claim = store.get_object("ResourceClaim", "default/p-dev")
        assert claim is not None
        assert claim.resource_class_name == "tpu.example.com"
        assert claim.selectors == {"tpu.dev/cores": ">=4"}
        owner = claim.meta.controller_of()
        assert owner.kind == "Pod" and owner.name == "p"

    def test_missing_template_requeues_and_emits_event(self):
        """Satellite: a pod referencing a not-yet-existing template must NOT
        wedge the controller — Warning event + rate-limited requeue, then
        success once the template appears."""
        store = mk_store()
        add_class(store)
        factory, ctrl = mk_controller(store)
        store.create_pod(
            make_pod("early").req({"cpu": "1"})
            .resource_claim("dev", template_name="late-tmpl").obj())
        pump(factory, ctrl, rounds=2)
        assert store.get_object("ResourceClaim", "default/early-dev") is None
        events = [e for e in ctrl.recorder.events
                  if e.reason == "FailedResourceClaimCreation"]
        assert events and "late-tmpl" in events[0].note
        # the key is in backoff, not dropped: template arrives -> claim lands
        store.create_object("ResourceClaimTemplate", ResourceClaimTemplate(
            meta=ObjectMeta(name="late-tmpl"),
            resource_class_name="tpu.example.com"))
        ctrl.queue.flush_waiting()
        pump(factory, ctrl)
        assert store.get_object("ResourceClaim", "default/early-dev") is not None

    def test_pod_delete_gcs_claims_and_reservations(self):
        store = mk_store()
        add_class(store)
        store.create_object("ResourceClaimTemplate", ResourceClaimTemplate(
            meta=ObjectMeta(name="tmpl"), resource_class_name="tpu.example.com"))
        factory, ctrl = mk_controller(store)
        store.create_pod(
            make_pod("p").req({"cpu": "1"})
            .resource_claim("dev", template_name="tmpl").obj())
        # a second, user-managed claim this pod merely reserves
        add_claim(store, "shared")
        pump(factory, ctrl)
        store.allocate_claim("default/shared", "node-1", "default/p")
        store.create_object("PodSchedulingContext", PodSchedulingContext(
            meta=ObjectMeta(name="p"), selected_node="node-1"))
        store.delete_pod("default/p")
        pump(factory, ctrl)
        assert store.get_object("ResourceClaim", "default/p-dev") is None
        shared = store.get_object("ResourceClaim", "default/shared")
        assert shared.reserved_for == ()
        assert shared.allocated_node == ""  # last reservation deallocates
        # the pod's PodSchedulingContext is reaped too (no leaked contexts)
        assert store.get_object("PodSchedulingContext", "default/p") is None


# ---------------------------------------------------------------------------
# DynamicResources plugin on the sequential oracle path


class TestDynamicResourcesOracle:
    def test_filters_to_matching_nodes_and_allocates(self):
        store = mk_store(attrs_fn=tpu_attrs)
        add_class(store, selectors={"tpu.dev/gen": "v5"})
        add_claim(store, "c1", selectors={"tpu.dev/cores": ">=4"})
        s = Scheduler(store)
        store.create_pod(make_pod("p").req({"cpu": "100m"})
                         .resource_claim("dev", claim_name="c1").obj())
        s.run_until_settled()
        pod = store.get_pod("default/p")
        assert pod.spec.node_name in ("node-1", "node-3")
        claim = store.get_object("ResourceClaim", "default/c1")
        assert claim.allocated_node == pod.spec.node_name
        assert claim.reserved_for == (pod.key(),)
        ctx = store.get_object("PodSchedulingContext", "default/p")
        assert ctx is not None and ctx.selected_node == pod.spec.node_name

    def test_missing_claim_parks_until_created(self):
        store = mk_store(attrs_fn=tpu_attrs)
        add_class(store)
        s = Scheduler(store, pod_initial_backoff=0.02, pod_max_backoff=0.1)
        store.create_pod(make_pod("p").req({"cpu": "100m"})
                         .resource_claim("dev", claim_name="ghost").obj())
        s.run_until_settled()
        assert store.get_pod("default/p").spec.node_name == ""
        # unresolvable: parked, no preemption nomination
        assert store.get_pod("default/p").status.nominated_node_name == ""
        # claim creation fires the dynamic ResourceClaim event -> reactivated
        add_claim(store, "ghost")
        drive_until(s, store, "default/p")
        assert store.get_pod("default/p").spec.node_name != ""

    def test_allocated_claim_pins_second_consumer(self):
        store = mk_store(attrs_fn=tpu_attrs)
        add_class(store)
        add_claim(store, "shared", selectors={"tpu.dev/cores": ">=4"})
        s = Scheduler(store)
        store.create_pod(make_pod("p1").req({"cpu": "100m"})
                         .resource_claim("dev", claim_name="shared").obj())
        s.run_until_settled()
        first_node = store.get_pod("default/p1").spec.node_name
        store.create_pod(make_pod("p2").req({"cpu": "100m"})
                         .resource_claim("dev", claim_name="shared").obj())
        s.run_until_settled()
        assert store.get_pod("default/p2").spec.node_name == first_node
        claim = store.get_object("ResourceClaim", "default/shared")
        assert set(claim.reserved_for) == {"default/p1", "default/p2"}

    def test_unschedulable_when_no_node_matches(self):
        store = mk_store(attrs_fn=lambda i: {"tpu.dev/gen": "v4"})
        add_class(store, selectors={"tpu.dev/gen": "v5"})
        add_claim(store, "c1")
        s = Scheduler(store)
        store.create_pod(make_pod("p").req({"cpu": "100m"})
                         .resource_claim("dev", claim_name="c1").obj())
        s.run_until_settled()
        assert store.get_pod("default/p").spec.node_name == ""
        claim = store.get_object("ResourceClaim", "default/c1")
        assert claim.allocated_node == "" and claim.reserved_for == ()


# ---------------------------------------------------------------------------
# batched kernel parity


ATTR_KEYS = ["tpu.dev/cores", "tpu.dev/gen", "tpu.dev/mem", "vendor.io/x"]
STR_VALS = ["v4", "v5", "v5e", "a"]


def random_attrs(rng):
    attrs = {}
    for k in ATTR_KEYS:
        r = rng.random()
        if r < 0.3:
            continue  # absent
        if r < 0.7:
            attrs[k] = rng.randint(0, 16)
        else:
            attrs[k] = rng.choice(STR_VALS)
    return attrs


def random_selectors(rng):
    sels = {}
    for k in rng.sample(ATTR_KEYS, rng.randint(0, 3)):
        op = rng.choice([">=", ">", "<=", "<", "==", "!=", ""])
        if op in ("==", "!=", "") and rng.random() < 0.5:
            sels[k] = op + rng.choice(STR_VALS)
        else:
            sels[k] = op + str(rng.randint(0, 16))
    return sels


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_claim_mask_kernel_parity(seed):
    """claim_feasibility_mask must equal DeviceSelector.matches for every
    (pod, node) pair on randomized attribute tables and selector mixes."""
    from kubernetes_tpu.backend.claim_mask import ClaimMaskBuilder
    from kubernetes_tpu.backend.device_state import DeviceState, caps_for_cluster
    from kubernetes_tpu.cache import Cache, Snapshot

    rng = random.Random(seed)
    n_nodes, n_pods = 12, 8
    store = ClusterStore()
    node_attrs = {}
    for i in range(n_nodes):
        attrs = random_attrs(rng)
        node_attrs[f"node-{i}"] = attrs
        store.create_node(make_node(f"node-{i}").capacity(
            {"cpu": "8", "memory": "16Gi", "pods": 32}).device_attrs(attrs).obj())
    add_class(store, selectors={})
    cache = Cache()
    for node in store.nodes.values():
        cache.add_node(node)
    snapshot = Snapshot()
    cache.update_snapshot(snapshot)
    device = DeviceState(caps_for_cluster(n_nodes, batch=n_pods))
    device.sync(snapshot)

    class QP:  # QueuedPodInfo stand-in: builder only reads .pod
        def __init__(self, pod):
            self.pod = pod

    qps, expected_sels = [], []
    for p in range(n_pods):
        sels = random_selectors(rng)
        add_claim(store, f"c{p}", selectors=sels)
        pod = (make_pod(f"p{p}").req({"cpu": "100m"})
               .resource_claim("dev", claim_name=f"c{p}").obj())
        qps.append(QP(pod))
        expected_sels.append(dra.parse_selectors(sels))

    mask = np.asarray(ClaimMaskBuilder(store).build(qps, device, pad_to=n_pods))
    for p in range(n_pods):
        for i in range(n_nodes):
            slot = device.encoder.node_slots[f"node-{i}"]
            want = all(s.matches(node_attrs[f"node-{i}"])
                       for s in expected_sels[p])
            assert bool(mask[p, slot]) == want, (
                f"seed={seed} pod={p} node={i}: kernel={bool(mask[p, slot])} "
                f"oracle={want} sels={expected_sels[p]} "
                f"attrs={node_attrs[f'node-{i}']}")


# ---------------------------------------------------------------------------
# acceptance: batched path parity + no fallback


def build_dra_cluster(store, n_nodes=8):
    for i in range(n_nodes):
        store.create_node(
            make_node(f"node-{i}").capacity({"cpu": "8", "memory": "16Gi", "pods": 32})
            .device_attrs(tpu_attrs(i)).obj())
    add_class(store, selectors={"tpu.dev/gen": "v5"})


def dra_workload(store, n_claim=6, n_plain=6):
    for i in range(n_claim):
        add_claim(store, f"c{i}", selectors={"tpu.dev/cores": ">=4"})
        store.create_pod(make_pod(f"claim-{i}").req({"cpu": "200m", "memory": "256Mi"})
                         .resource_claim("dev", claim_name=f"c{i}").obj())
        store.create_pod(make_pod(f"plain-{i}").req({"cpu": "200m", "memory": "256Mi"}).obj())


class TestBatchedParity:
    def test_tpu_matches_oracle_and_stays_batched(self):
        """Acceptance: identical pod→node assignments AND identical claim
        allocations between the sequential oracle and the TPU batched path,
        with claim-bearing pods NOT routed to the sequential fallback."""
        from kubernetes_tpu.backend.tpu_scheduler import TPUScheduler

        store_o, store_t = ClusterStore(), ClusterStore()
        for st in (store_o, store_t):
            build_dra_cluster(st)
        oracle = Scheduler(store_o)
        tpu = TPUScheduler(store_t, batch_size=16)
        for st in (store_o, store_t):
            dra_workload(st)
        oracle.run_until_settled()
        tpu.run_until_settled()

        placed_o = {k: p.spec.node_name for k, p in store_o.pods.items()}
        placed_t = {k: p.spec.node_name for k, p in store_t.pods.items()}
        assert placed_o == placed_t
        assert all(placed_t.values())  # everything landed
        claims_o = {k: (c.allocated_node, c.reserved_for)
                    for k, c in store_o.resource_claims.items()}
        claims_t = {k: (c.allocated_node, c.reserved_for)
                    for k, c in store_t.resource_claims.items()}
        assert claims_o == claims_t
        # claim-bearing pods rode the batch (backend counters)
        assert tpu.fallback_scheduled == 0
        assert tpu.batch_scheduled == len(placed_t)
        # every claim allocation counted (one claim per claim pod)
        n_claims = len(store_t.resource_claims)
        assert tpu.smetrics.dra_claim_allocations.labels("allocated") == n_claims
        assert tpu.smetrics.dra_claim_allocations.labels("released") == 0

    def test_diagnosis_attributes_dynamic_resources(self):
        """Satellite: batch-loser Diagnosis blames DynamicResources with the
        'cannot allocate all claims' message, not a later plugin."""
        from kubernetes_tpu.backend.tpu_scheduler import TPUScheduler

        store = ClusterStore()
        for i in range(4):
            store.create_node(
                make_node(f"node-{i}").capacity({"cpu": "8", "memory": "16Gi", "pods": 32})
                .device_attrs({"tpu.dev/gen": "v4"}).obj())
        add_class(store, selectors={"tpu.dev/gen": "v5"})
        add_claim(store, "c1")
        s = TPUScheduler(store, batch_size=8)
        store.create_pod(make_pod("p").req({"cpu": "100m"})
                         .resource_claim("dev", claim_name="c1").obj())
        s.run_until_settled(max_cycles=50)
        assert store.get_pod("default/p").spec.node_name == ""
        qp = s.queue._unschedulable.get("default/p")
        assert qp is not None
        assert "DynamicResources" in qp.unschedulable_plugins

    def test_shared_claim_batch_converges_to_one_node(self):
        """Two pods sharing one unallocated claim in the same batch: the
        first Reserve allocates, the second lands on the same node (same
        batch or after a Reserve-conflict retry)."""
        from kubernetes_tpu.backend.tpu_scheduler import TPUScheduler

        store = ClusterStore()
        build_dra_cluster(store)
        add_claim(store, "shared", selectors={"tpu.dev/cores": ">=4"})
        s = TPUScheduler(store, batch_size=8,
                         pod_initial_backoff=0.02, pod_max_backoff=0.1)
        for i in range(2):
            store.create_pod(make_pod(f"p{i}").req({"cpu": "100m"})
                             .resource_claim("dev", claim_name="shared").obj())
        s.run_until_settled()
        drive_until(s, store, "default/p1")  # Reserve-conflict retry backoff
        n0 = store.get_pod("default/p0").spec.node_name
        n1 = store.get_pod("default/p1").spec.node_name
        assert n0 and n0 == n1
        claim = store.get_object("ResourceClaim", "default/shared")
        assert claim.allocated_node == n0
        assert set(claim.reserved_for) == {"default/p0", "default/p1"}

    def test_unmaterialized_claim_falls_back_then_batches(self):
        """A pod whose template claim hasn't materialized keeps the oracle
        path (batchable gate); once the controller catches up the next pod
        batches."""
        from kubernetes_tpu.backend.tpu_scheduler import TPUScheduler

        store = ClusterStore()
        build_dra_cluster(store)
        store.create_object("ResourceClaimTemplate", ResourceClaimTemplate(
            meta=ObjectMeta(name="tmpl"), resource_class_name="tpu.example.com"))
        factory, ctrl = mk_controller(store)
        s = TPUScheduler(store, batch_size=8,
                         pod_initial_backoff=0.02, pod_max_backoff=0.1)
        store.create_pod(make_pod("p").req({"cpu": "100m"})
                         .resource_claim("dev", template_name="tmpl").obj())
        s.run_until_settled(max_cycles=30)
        assert store.get_pod("default/p").spec.node_name == ""  # parked
        pump(factory, ctrl)  # controller materializes default/p-dev
        assert store.get_object("ResourceClaim", "default/p-dev") is not None
        drive_until(s, store, "default/p")
        assert store.get_pod("default/p").spec.node_name != ""


# ---------------------------------------------------------------------------
# perf harness workload


class TestSchedulingDRAWorkload:
    @pytest.mark.parametrize("backend", ["oracle", "tpu"])
    def test_small_variant_runs(self, backend):
        from kubernetes_tpu.perf import TEST_CASES, run_workload

        tc = TEST_CASES["SchedulingDRA"](nodes=16, init_pods=6, measured=8)
        items = run_workload(tc, backend=backend)
        tput = next(it for it in items
                    if it.labels.get("Name") == "SchedulingThroughput")
        assert tput.data["Average"] > 0

    @pytest.mark.slow
    def test_large_variant(self):
        """The stretch-shaped variant (kept out of tier-1: slow)."""
        from kubernetes_tpu.perf import TEST_CASES, run_workload

        tc = TEST_CASES["SchedulingDRA"]()  # 5000 nodes, reference size
        items = run_workload(tc, backend="tpu")
        tput = next(it for it in items
                    if it.labels.get("Name") == "SchedulingThroughput")
        assert tput.data["Average"] > 0
