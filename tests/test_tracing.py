"""OTel-style span tracing (SURVEY §5.1): nesting, OTLP shape, JSON export,
and the scheduler's cycle-phase spans on both paths."""

import json

from kubernetes_tpu.api.wrappers import make_node, make_pod
from kubernetes_tpu.apiserver import ClusterStore
from kubernetes_tpu.backend import TPUScheduler
from kubernetes_tpu.scheduler.scheduler import Scheduler
from kubernetes_tpu.utils import tracing


class TestTracer:
    def teardown_method(self):
        tracing.disable()

    def test_nesting_and_otlp_shape(self):
        tracer = tracing.enable()
        with tracing.span("parent", cluster="test") as parent:
            with tracing.span("child") as child:
                pass
        exp = tracer.exporter
        assert [s.name for s in exp.spans] == ["child", "parent"]
        c, p = exp.spans
        assert c.trace_id == p.trace_id and c.parent_id == p.span_id
        otlp = p.to_otlp()
        assert otlp["name"] == "parent" and otlp["parentSpanId"] == ""
        assert {"key": "cluster", "value": {"stringValue": "test"}} in otlp["attributes"]
        assert c.duration_s >= 0

    def test_disabled_is_noop(self):
        with tracing.span("nothing") as s:
            assert s is None

    def test_json_file_exporter(self, tmp_path):
        path = str(tmp_path / "spans.jsonl")
        tracing.enable(tracing.JsonFileExporter(path))
        with tracing.span("one"):
            pass
        line = json.loads(open(path).read().strip())
        assert line["name"] == "one" and line["endTimeUnixNano"] > 0

    def test_env_enable(self, tmp_path, monkeypatch):
        monkeypatch.setenv("KTPU_TRACE_FILE", str(tmp_path / "t.jsonl"))
        tracing.maybe_enable_from_env()
        assert tracing.get() is not None


class TestSchedulerSpans:
    def teardown_method(self):
        tracing.disable()

    def test_sequential_cycle_span(self):
        tracer = tracing.enable()
        store = ClusterStore()
        sched = Scheduler(store)
        store.create_node(make_node("n1").capacity(
            {"cpu": "4", "memory": "8Gi", "pods": 10}).obj())
        store.create_pod(make_pod("p").req({"cpu": "100m"}).obj())
        sched.run_until_settled()
        cycles = tracer.exporter.by_name("scheduling.cycle")
        assert cycles and cycles[0].attributes["pod"] == "default/p"

    def test_batch_phase_spans(self):
        tracer = tracing.enable()
        store = ClusterStore()
        sched = TPUScheduler(store, batch_size=8)
        for i in range(4):
            store.create_node(make_node(f"n{i}").capacity(
                {"cpu": "4", "memory": "8Gi", "pods": 10}).obj())
        for i in range(6):
            store.create_pod(make_pod(f"p{i}").req({"cpu": "100m"}).obj())
        sched.run_until_settled()
        names = {s.name for s in tracer.exporter.spans}
        assert {"device.encode", "device.dispatch", "device.commit.wait",
                "host.commit"} <= names
