"""OTel-style span tracing (SURVEY §5.1): nesting, OTLP shape, JSON export,
and the scheduler's cycle-phase spans on both paths."""

import json

from kubernetes_tpu.api.wrappers import make_node, make_pod
from kubernetes_tpu.apiserver import ClusterStore
from kubernetes_tpu.backend import TPUScheduler
from kubernetes_tpu.scheduler.scheduler import Scheduler
from kubernetes_tpu.utils import tracing


class TestTracer:
    def teardown_method(self):
        tracing.disable()

    def test_nesting_and_otlp_shape(self):
        tracer = tracing.enable()
        with tracing.span("parent", cluster="test") as parent:
            with tracing.span("child") as child:
                pass
        exp = tracer.exporter
        assert [s.name for s in exp.spans] == ["child", "parent"]
        c, p = exp.spans
        assert c.trace_id == p.trace_id and c.parent_id == p.span_id
        otlp = p.to_otlp()
        assert otlp["name"] == "parent" and otlp["parentSpanId"] == ""
        assert {"key": "cluster", "value": {"stringValue": "test"}} in otlp["attributes"]
        assert c.duration_s >= 0

    def test_disabled_is_noop(self):
        with tracing.span("nothing") as s:
            assert s is None

    def test_json_file_exporter(self, tmp_path):
        path = str(tmp_path / "spans.jsonl")
        tracing.enable(tracing.JsonFileExporter(path))
        with tracing.span("one"):
            pass
        line = json.loads(open(path).read().strip())
        assert line["name"] == "one" and line["endTimeUnixNano"] > 0

    def test_env_enable(self, tmp_path, monkeypatch):
        monkeypatch.setenv("KTPU_TRACE_FILE", str(tmp_path / "t.jsonl"))
        tracing.maybe_enable_from_env()
        assert tracing.get() is not None


class TestTraceparent:
    def teardown_method(self):
        tracing.disable()

    def test_format_and_parse_roundtrip(self):
        tracing.enable()
        assert tracing.format_traceparent() is None  # no open span
        with tracing.span("outer") as s:
            tp = tracing.format_traceparent()
            assert tp == f"00-{s.trace_id}-{s.span_id}-01"
            assert tracing.parse_traceparent(tp) == (s.trace_id, s.span_id)
        for bad in (None, "", "junk", "00-short-short-01", 42):
            assert tracing.parse_traceparent(bad) is None

    def test_disabled_is_noop(self):
        assert tracing.format_traceparent() is None
        with tracing.span_from_remote("00-" + "a" * 32 + "-" + "b" * 16 + "-01",
                                      "child") as s:
            assert s is None

    def test_span_from_remote_parents_across_boundary(self):
        tracer = tracing.enable()
        with tracing.span("client.op") as parent:
            tp = tracing.format_traceparent()
        with tracing.span_from_remote(tp, "server.op") as child:
            with tracing.span("server.inner") as inner:
                pass
        assert child.trace_id == parent.trace_id
        assert child.parent_id == parent.span_id
        assert inner.trace_id == parent.trace_id and inner.parent_id == child.span_id
        # malformed context falls back to a fresh local trace
        with tracing.span_from_remote("not-a-traceparent", "server.op") as s:
            assert s.parent_id is None and s.trace_id != parent.trace_id

    def test_tail(self):
        tracing.enable()
        for i in range(5):
            with tracing.span(f"s{i}"):
                pass
        assert [s.name for s in tracing.tail(2)] == ["s3", "s4"]
        tracing.disable()
        assert tracing.tail() == []


class TestSchedulerSpans:
    def teardown_method(self):
        tracing.disable()

    def test_sequential_cycle_span(self):
        tracer = tracing.enable()
        store = ClusterStore()
        sched = Scheduler(store)
        store.create_node(make_node("n1").capacity(
            {"cpu": "4", "memory": "8Gi", "pods": 10}).obj())
        store.create_pod(make_pod("p").req({"cpu": "100m"}).obj())
        sched.run_until_settled()
        cycles = tracer.exporter.by_name("scheduling.cycle")
        assert cycles and cycles[0].attributes["pod"] == "default/p"

    def test_batch_phase_spans(self):
        tracer = tracing.enable()
        store = ClusterStore()
        sched = TPUScheduler(store, batch_size=8)
        for i in range(4):
            store.create_node(make_node(f"n{i}").capacity(
                {"cpu": "4", "memory": "8Gi", "pods": 10}).obj())
        for i in range(6):
            store.create_pod(make_pod(f"p{i}").req({"cpu": "100m"}).obj())
        sched.run_until_settled()
        names = {s.name for s in tracer.exporter.spans}
        assert {"device.encode", "device.dispatch", "device.commit.wait",
                "host.commit", "scheduling.cycle"} <= names

    def test_sequential_cycle_has_extension_point_children(self):
        tracer = tracing.enable()
        store = ClusterStore()
        sched = Scheduler(store)
        for i in range(3):
            store.create_node(make_node(f"n{i}").capacity(
                {"cpu": "4", "memory": "8Gi", "pods": 10}).obj())
        store.create_pod(make_pod("p").req({"cpu": "100m"}).obj())
        sched.run_until_settled()
        spans = tracer.exporter.spans
        cycle = tracer.exporter.by_name("scheduling.cycle")[0]
        children = {s.name for s in spans if s.trace_id == cycle.trace_id}
        # the instrumented framework runtime gives the cycle per-point and
        # per-plugin spans (framework.* / plugin.*); bind happens after the
        # cycle span closes and roots its own framework.bind span
        assert {"framework.pre_filter", "framework.filter",
                "framework.score"} <= children
        assert any(n.startswith("plugin.") for n in children)
        assert tracer.exporter.by_name("framework.bind")


class TestCrossBoundaryTrace:
    """Acceptance: after a wire-backend run the JSON-lines export contains a
    trace in which the backend device.commit span's trace_id/parent chain
    resolves to the originating scheduling.cycle span."""

    def teardown_method(self):
        tracing.disable()

    def test_wire_backend_commit_parents_under_cycle(self, tmp_path):
        from kubernetes_tpu.backend.service import (DeviceService,
                                                    WireScheduler, serve)

        path = str(tmp_path / "spans.jsonl")
        tracing.enable(tracing.JsonFileExporter(path))
        store = ClusterStore()
        svc = DeviceService(batch_size=8)
        server, port = serve(svc)
        try:
            sched = WireScheduler(store, endpoint=f"http://127.0.0.1:{port}",
                                  batch_size=8)
            for i in range(4):
                store.create_node(make_node(f"n{i}").capacity(
                    {"cpu": "4", "memory": "8Gi", "pods": 10}).obj())
            for i in range(6):
                store.create_pod(make_pod(f"p{i}").req({"cpu": "100m"}).obj())
            sched.run_until_settled()
        finally:
            server.shutdown()
            server.server_close()
        assert sched.metrics["scheduled"] == 6
        spans = [json.loads(line) for line in open(path)]
        by_id = {s["spanId"]: s for s in spans}
        commits = [s for s in spans if s["name"] == "device.commit"]
        assert commits, {s["name"] for s in spans}
        for c in commits:
            chain = []
            cur = c
            while cur["parentSpanId"]:
                assert cur["parentSpanId"] in by_id, "broken parent chain"
                cur = by_id[cur["parentSpanId"]]
                chain.append(cur["name"])
                assert cur["traceId"] == c["traceId"]
            # device.commit → device.schedule_batch → scheduling.cycle:
            # ONE trace covers scheduler pop → wire hop → device commit
            assert chain[-1] == "scheduling.cycle", chain
