"""Slice-topology packing (ISSUE 16): kernel<->host parity of the torus
planner, all-or-nothing verdicts, best-fit anti-fragmentation tiebreaks,
three-backend placement parity of the SchedulingSlices workload, the
one-blocking-sync guard over slice batches, and slice-atomic drains."""

import types

import numpy as np
import pytest

from kubernetes_tpu.api.types import ObjectMeta, PodGroup
from kubernetes_tpu.api.wrappers import make_node, make_pod
from kubernetes_tpu.apiserver import ClusterStore
from kubernetes_tpu.backend import TPUScheduler
from kubernetes_tpu.ops.slice import (
    SLICE_LABEL,
    TOPO_SLOT_LABEL,
    TOPO_SUPERPOD_LABEL,
    fragmentation_host,
    plan_slices,
    slice_assign_host,
)
from kubernetes_tpu.utils import relay

# ---------------------------------------------------------------------------
# kernel <-> host parity over randomized tori


def _duck_nt(valid, unsched, alloc, requested, topo_sp, topo_pos):
    """plan_slices only touches these six NodeTensors fields."""
    import jax.numpy as jnp

    return types.SimpleNamespace(
        valid=jnp.asarray(valid),
        unschedulable=jnp.asarray(unsched),
        allocatable=jnp.asarray(alloc, jnp.int32),
        requested=jnp.asarray(requested, jnp.int32),
        topo_sp=jnp.asarray(topo_sp, jnp.int32),
        topo_pos=jnp.asarray(topo_pos, jnp.int32))


def _host_fits(req_g, valid, unsched, alloc, requested):
    """[G, N] bool: the scan's fit rule (req==0 always fits)."""
    free = alloc - requested
    fits = np.ones((req_g.shape[0], alloc.shape[0]), bool)
    for g in range(req_g.shape[0]):
        for n in range(alloc.shape[0]):
            ok = valid[n] and not unsched[n]
            for r in range(req_g.shape[1]):
                if req_g[g, r] > 0 and free[n, r] < req_g[g, r]:
                    ok = False
            fits[g, n] = ok
    return fits


@pytest.mark.parametrize("seed", range(8))
def test_plan_slices_matches_host_oracle(seed):
    """Randomized tori: device planner and greedy host oracle agree on
    every verdict and every member target (same windows, same order)."""
    import jax.numpy as jnp

    rng = np.random.default_rng(seed)
    s_pods = int(rng.integers(1, 4))
    ps = int(rng.integers(4, 11))
    cells = s_pods * ps
    n = int(rng.integers(max(2, cells // 2), cells + 1))
    # unique coordinates (production encode guarantees uniqueness)
    chosen = rng.choice(cells, size=n, replace=False)
    topo_sp = (chosen // ps).astype(np.int32)
    topo_pos = (chosen % ps).astype(np.int32)
    valid = rng.random(n) > 0.1
    unsched = rng.random(n) < 0.15
    r_dims = 2
    alloc = rng.integers(4, 11, size=(n, r_dims)).astype(np.int32)
    requested = (alloc * rng.random((n, r_dims)) * 0.7).astype(np.int32)

    g = int(rng.integers(1, 5))
    wants = [int(rng.integers(0, 6)) for _ in range(g)]
    p = max(1, sum(wants))
    req = rng.integers(0, 7, size=(p, r_dims)).astype(np.int32)
    m_cap = max(max(wants), 1)
    member_idx = np.full((g, m_cap), -1, np.int32)
    member_valid = np.zeros((g, m_cap), bool)
    nxt = 0
    for gi, k in enumerate(wants):
        for m in range(k):
            member_idx[gi, m] = nxt % p
            member_valid[gi, m] = True
            nxt += 1

    nt = _duck_nt(valid, unsched, alloc, requested, topo_sp, topo_pos)
    targets, ok = plan_slices(nt, jnp.asarray(req), jnp.asarray(member_idx),
                              jnp.asarray(member_valid), (s_pods, ps))
    targets = np.asarray(targets)
    ok = np.asarray(ok)

    # host twin: per-gang request = max over active members
    req_g = np.zeros((g, r_dims), np.int32)
    for gi in range(g):
        for m in range(m_cap):
            if member_valid[gi, m]:
                req_g[gi] = np.maximum(req_g[gi], req[member_idx[gi, m]])
    fits = _host_fits(req_g, valid, unsched, alloc, requested)
    h_targets, h_ok = slice_assign_host(
        topo_sp, topo_pos, valid, fits, wants, (s_pods, ps))

    for gi, k in enumerate(wants):
        assert bool(ok[gi]) == h_ok[gi], (seed, gi, wants)
        if h_ok[gi]:
            assert list(targets[gi][:k]) == h_targets[gi], (seed, gi)
        else:
            assert all(t == -1 for t in targets[gi]), (seed, gi)


def test_plan_all_or_nothing_reject():
    """A gang larger than any free run is rejected whole: ok False and
    every member target -1 — never a partial placement."""
    import jax.numpy as jnp

    n = 6  # one superpod of 6 slots, middle two occupied -> runs of 2
    alloc = np.full((n, 1), 10, np.int32)
    requested = np.zeros((n, 1), np.int32)
    requested[2, 0] = requested[3, 0] = 10
    nt = _duck_nt([True] * n, [False] * n, alloc, requested,
                  [0] * n, list(range(n)))
    req = np.full((3, 1), 1, np.int32)
    member_idx = np.arange(3, dtype=np.int32).reshape(1, 3)
    member_valid = np.ones((1, 3), bool)
    targets, ok = plan_slices(nt, jnp.asarray(req), jnp.asarray(member_idx),
                              jnp.asarray(member_valid), (1, 6))
    assert not bool(ok[0])
    assert all(int(t) == -1 for t in np.asarray(targets)[0])


def test_plan_prefers_exact_hole_over_splitting_run():
    """Best-fit anti-fragmentation: a 2-gang takes the exact-fit 2-hole
    (leftover 0) instead of shredding the pristine 5-run."""
    import jax.numpy as jnp

    n = 8  # slots 0-1 free, slot 2 full, slots 3-7 free
    alloc = np.full((n, 1), 10, np.int32)
    requested = np.zeros((n, 1), np.int32)
    requested[2, 0] = 10
    nt = _duck_nt([True] * n, [False] * n, alloc, requested,
                  [0] * n, list(range(n)))
    req = np.full((2, 1), 1, np.int32)
    member_idx = np.arange(2, dtype=np.int32).reshape(1, 2)
    member_valid = np.ones((1, 2), bool)
    targets, ok = plan_slices(nt, jnp.asarray(req), jnp.asarray(member_idx),
                              jnp.asarray(member_valid), (1, 8))
    assert bool(ok[0])
    assert list(np.asarray(targets)[0]) == [0, 1]


def test_fragmentation_host_scoring():
    # sp0: 4 slots, free pattern [1, 0, 1, 1] -> free 3, largest 2
    rows = fragmentation_host([0, 0, 0, 0], [0, 1, 2, 3],
                              [True] * 4, [True, False, True, True], (2, 4))
    assert len(rows) == 1  # sp1 has no mapped node -> skipped
    assert rows[0] == {"sp": 0, "free": 3, "used": 1, "largest_run": 2,
                      "frag": pytest.approx(1.0 - 2.0 / 3.0)}
    # exhausted superpod is full, not fragmented
    rows = fragmentation_host([0, 0], [0, 1], [True] * 2,
                              [False, False], (1, 2))
    assert rows[0]["frag"] == 0.0


# ---------------------------------------------------------------------------
# scheduler-level: torus rigs


def _torus_rig(superpods=2, slots=8, cpu="4"):
    """Labeled torus: superpods x slots hosts a slice pod fills whole."""
    store = ClusterStore()
    for sp in range(superpods):
        for s in range(slots):
            store.create_node(
                make_node(f"n{sp}-{s}")
                .capacity({"cpu": cpu, "memory": "16Gi", "pods": 8})
                .label(TOPO_SUPERPOD_LABEL, str(sp))
                .label(TOPO_SLOT_LABEL, str(s)).obj())
    return store


def _slice_gang(store, group, size, prefix=None):
    store.create_object("PodGroup", PodGroup(
        meta=ObjectMeta(name=group), min_member=size))
    prefix = prefix or group
    for i in range(size):
        store.create_pod(
            make_pod(f"{prefix}-{i}")
            .req({"cpu": "3500m", "memory": "12Gi"})
            .pod_group(group).label(SLICE_LABEL, "1").obj())


def _gang_cells(store, group):
    """Sorted (sp, slot) cells of the gang's bound hosts ([] if unbound)."""
    cells = []
    for p in store.pods.values():
        if (p.meta.labels.get("scheduling.x-k8s.io/pod-group") == group
                and p.spec.node_name):
            node = store.nodes[p.spec.node_name]
            cells.append((int(node.meta.labels[TOPO_SUPERPOD_LABEL]),
                          int(node.meta.labels[TOPO_SLOT_LABEL])))
    return sorted(cells)


def _assert_contiguous(cells, size):
    assert len(cells) == size, cells
    assert len({sp for sp, _ in cells}) == 1, cells  # one superpod
    pos = [s for _, s in cells]
    assert len(set(pos)) == len(pos), cells          # one member per host
    assert pos[-1] - pos[0] == len(pos) - 1, cells   # consecutive slots


class TestSliceScheduling:
    def test_slice_gangs_land_contiguously(self):
        store = _torus_rig()
        sched = TPUScheduler(store, batch_size=32)
        _slice_gang(store, "a", 4)
        _slice_gang(store, "b", 3)
        sched.run_until_settled()
        _assert_contiguous(_gang_cells(store, "a"), 4)
        _assert_contiguous(_gang_cells(store, "b"), 3)
        assert sched.fallback_scheduled == 0

    def test_slice_batches_one_blocking_sync(self):
        """The slice verdict rides the packed result block: planning,
        pinning, and gang judgment add ZERO device reads — each batch
        still costs exactly one commit-read (no gang-read)."""
        store = _torus_rig()
        sched = TPUScheduler(store, batch_size=32)
        with relay.track() as counts:
            _slice_gang(store, "a", 4)
            _slice_gang(store, "b", 8)
            sched.run_until_settled()
        assert _gang_cells(store, "a") and _gang_cells(store, "b")
        assert counts["commit-read"] == sched.batch_counter
        assert sum(counts.values()) == counts["commit-read"], dict(counts)

    def test_oversized_slice_gang_rejected_atomically(self):
        """A gang wider than any superpod never binds a single member."""
        store = _torus_rig(superpods=2, slots=4)
        sched = TPUScheduler(store, batch_size=32)
        _slice_gang(store, "wide", 6)  # > 4 slots per superpod
        sched.run_until_settled(max_no_progress=3)
        assert _gang_cells(store, "wide") == []
        assert sched.fallback_scheduled == 0


# ---------------------------------------------------------------------------
# three-backend placement parity on the SchedulingSlices workload


def _small_case():
    from kubernetes_tpu.perf.workloads import scheduling_slices

    return scheduling_slices(nodes=32, slots=8, init_gangs=1,
                             measured_small=2, measured_medium=1,
                             measured_large=0)


def _run_case(backend):
    from kubernetes_tpu.perf.harness import Runner

    r = Runner(backend=backend)
    try:
        r.run_ops(_small_case()["ops"])
        bound = {k: p.spec.node_name for k, p in r.store.pods.items()
                 if p.spec.node_name}
        stats = next(it.data for it in r.data_items
                     if it.labels.get("Name") == "SliceStats")
        return bound, stats
    finally:
        r.close()


class TestSchedulingSlicesParity:
    def test_oracle_tpu_wire_agree(self):
        """ISSUE 16 acceptance: identical pod->node maps across all three
        backends, zero contiguity violations, zero oversubscription, zero
        sequential fallback."""
        results = {b: _run_case(b) for b in ("oracle", "tpu", "wire")}
        bound0, _ = results["oracle"]
        assert bound0, "oracle bound nothing"
        for b, (bound, stats) in results.items():
            assert bound == bound0, f"{b} placement diverges from oracle"
            assert stats["ContiguityViolations"] == 0.0, (b, stats)
            assert stats["FallbackScheduled"] == 0.0, (b, stats)
            assert stats["BoundSliceGangs"] == 4.0, (b, stats)
            # zero oversubscription: hosts are slice-exclusive
            per_node = {}
            for node in bound.values():
                per_node[node] = per_node.get(node, 0) + 1
            assert max(per_node.values()) == 1, (b, per_node)
        # the batched backends observe every gang through the slice
        # verdict metric; none is rejected
        for b in ("tpu", "wire"):
            assert results[b][1]["SliceScheduled"] == 4.0, results[b][1]
            assert results[b][1]["SliceRejected"] == 0.0, results[b][1]


# ---------------------------------------------------------------------------
# slice-atomic drain (chaos): a drain touching ONE member's host mid-run


class TestSliceDrainChaos:
    def test_drain_straddling_slice_gang_repacks_whole(self):
        """Cordon+drain one host of a placed slice gang while another gang
        is still pending: the WHOLE gang is evicted (never a torn slice)
        and re-packs onto a fresh contiguous window; bystander gangs and
        the in-flight gang all finish contiguous."""
        from kubernetes_tpu.controllers.drain import DrainOrchestrator

        store = _torus_rig(superpods=2, slots=8)
        sched = TPUScheduler(store, batch_size=32)
        _slice_gang(store, "a", 4)
        _slice_gang(store, "b", 4)
        sched.run_until_settled()
        a0, b0 = _gang_cells(store, "a"), _gang_cells(store, "b")
        _assert_contiguous(a0, 4)
        _assert_contiguous(b0, 4)

        # in-flight work the drain straddles
        _slice_gang(store, "c", 4)
        victim = next(p.spec.node_name for p in store.pods.values()
                      if p.meta.labels.get(
                          "scheduling.x-k8s.io/pod-group") == "a"
                      and p.spec.node_name)
        drainer = DrainOrchestrator(store, metrics=sched.smetrics,
                                    queue=sched.queue)
        res = drainer.drain_wave([victim])
        # the gang closure evicted all of gang a, nothing of gang b
        assert res["evicted"] == 4, res
        assert _gang_cells(store, "a") == []
        assert _gang_cells(store, "b") == b0

        sched.run_until_settled(max_no_progress=5)
        a1 = _gang_cells(store, "a")
        _assert_contiguous(a1, 4)
        _assert_contiguous(_gang_cells(store, "c"), 4)
        assert _gang_cells(store, "b") == b0
        # the drained (cordoned) host carries nothing
        assert all(p.spec.node_name != victim
                   for p in store.pods.values())
