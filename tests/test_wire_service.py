"""The batched device service over a real localhost socket (SURVEY §5.8
hop 6): codec round-trips, e2e scheduling through HTTP, and parity with the
in-process batch path."""

import numpy as np

from kubernetes_tpu.api.codec import from_wire, to_wire
from kubernetes_tpu.api.types import LabelSelector, Node, Pod
from kubernetes_tpu.api.wrappers import make_node, make_pod
from kubernetes_tpu.apiserver import ClusterStore
from kubernetes_tpu.backend import TPUScheduler
from kubernetes_tpu.backend.service import DeviceService, WireScheduler, serve


def _bound(store):
    objs, _rv = store.list_objects("Pod")
    return {p.meta.name: p.spec.node_name for p in objs if p.spec.node_name}


def test_codec_roundtrip_pod_and_node():
    pod = (make_pod("p0").req({"cpu": "1500m", "memory": "2Gi"})
           .label("app", "web").priority(100)
           .node_affinity_in("disk", ["ssd"])
           .spread_constraint(1, "zone", selector=LabelSelector(match_labels={"app": "web"}))
           .pod_affinity("zone", LabelSelector(match_labels={"app": "web"}), anti=True)
           .toleration("dedicated", "gpu", "NoSchedule")
           .obj())
    p2 = from_wire(Pod, to_wire(pod))
    assert p2.meta.name == "p0" and p2.meta.labels == {"app": "web"}
    assert p2.spec.priority == 100
    assert p2.resource_request() == pod.resource_request()
    assert len(p2.spec.topology_spread_constraints) == 1
    assert p2.spec.topology_spread_constraints[0].label_selector.matches({"app": "web"})
    assert p2.spec.tolerations == pod.spec.tolerations
    assert to_wire(p2) == to_wire(pod)

    node = (make_node("n0").capacity({"cpu": "8", "memory": "16Gi", "pods": 100})
            .label("zone", "z1").taint("dedicated", "gpu", "NoSchedule").obj())
    n2 = from_wire(Node, to_wire(node))
    assert n2.meta.name == "n0"
    assert n2.spec.taints == node.spec.taints
    assert to_wire(n2) == to_wire(node)


def test_wire_scheduler_end_to_end():
    service = DeviceService(batch_size=32)
    server, port = serve(service)
    try:
        store = ClusterStore()
        sched = WireScheduler(store, endpoint=f"http://127.0.0.1:{port}", batch_size=8)
        for i in range(4):
            store.create_node(
                make_node(f"n{i}").capacity({"cpu": "4", "memory": "8Gi", "pods": 10})
                .label("zone", f"z{i % 2}").obj())
        for i in range(12):
            store.create_pod(make_pod(f"p{i}").req({"cpu": "1", "memory": "1Gi"}).obj())
        sched.run_until_settled()
        assert sched.metrics["scheduled"] == 12
        bound = _bound(store)
        per_node = {}
        for n in bound.values():
            per_node[n] = per_node.get(n, 0) + 1
        assert all(v <= 4 for v in per_node.values()), per_node  # 4cpu / 1cpu
    finally:
        server.shutdown()


def test_wire_unschedulable_and_recovery():
    """Pods that do not fit fail with plugin attribution over the wire, park
    unschedulable, and get scheduled after a node appears."""
    service = DeviceService(batch_size=32)
    server, port = serve(service)
    try:
        store = ClusterStore()
        sched = WireScheduler(store, endpoint=f"http://127.0.0.1:{port}", batch_size=8)
        store.create_node(
            make_node("small").capacity({"cpu": "1", "memory": "2Gi", "pods": 10}).obj())
        store.create_pod(make_pod("big").req({"cpu": "4", "memory": "4Gi"}).obj())
        sched.run_until_settled()
        assert sched.metrics["scheduled"] == 0
        assert sched.queue.pending_pods()["unschedulable"] == 1
        store.create_node(
            make_node("large").capacity({"cpu": "8", "memory": "16Gi", "pods": 10}).obj())
        # the reactivated pod sits out its backoff (1s) first
        import time
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and _bound(store).get("big") != "large":
            time.sleep(0.2)
            sched.run_until_settled()
        assert _bound(store).get("big") == "large"
    finally:
        server.shutdown()


def test_wire_matches_in_process_placements():
    """Same workload over the socket and in-process: identical placements
    (same program, same batch numbering, same tie-break seeds)."""
    def build(store):
        for i in range(6):
            store.create_node(
                make_node(f"n{i}").capacity({"cpu": "8", "memory": "16Gi", "pods": 20})
                .label("zone", f"z{i % 3}").obj())
        for i in range(15):
            pw = make_pod(f"p{i}").req({"cpu": "1", "memory": "1Gi"})
            if i % 3 == 0:
                pw.label("app", "web").spread_constraint(
                    1, "zone", selector=LabelSelector(match_labels={"app": "web"}))
            store.create_pod(pw.obj())

    service = DeviceService(batch_size=32)
    server, port = serve(service)
    try:
        store_w = ClusterStore()
        sched_w = WireScheduler(store_w, endpoint=f"http://127.0.0.1:{port}", batch_size=8)
        build(store_w)
        sched_w.run_until_settled()

        import os
        os.environ["KTPU_PIPELINE"] = "0"
        try:
            store_l = ClusterStore()
            sched_l = TPUScheduler(store_l, batch_size=8)
            build(store_l)
            sched_l.run_until_settled()
        finally:
            os.environ.pop("KTPU_PIPELINE", None)

        assert sched_w.metrics["scheduled"] == sched_l.metrics["scheduled"] == 15
        assert _bound(store_w) == _bound(store_l)
    finally:
        server.shutdown()
