"""The batched device service over a real localhost socket (SURVEY §5.8
hop 6): codec round-trips, e2e scheduling through HTTP, and parity with the
in-process batch path."""

import numpy as np

from kubernetes_tpu.api.codec import from_wire, to_wire
from kubernetes_tpu.api.types import LabelSelector, Node, Pod
from kubernetes_tpu.api.wrappers import make_node, make_pod
from kubernetes_tpu.apiserver import ClusterStore
from kubernetes_tpu.backend import TPUScheduler
from kubernetes_tpu.backend.service import DeviceService, WireScheduler, serve


def _bound(store):
    objs, _rv = store.list_objects("Pod")
    return {p.meta.name: p.spec.node_name for p in objs if p.spec.node_name}


def test_codec_roundtrip_pod_and_node():
    pod = (make_pod("p0").req({"cpu": "1500m", "memory": "2Gi"})
           .label("app", "web").priority(100)
           .node_affinity_in("disk", ["ssd"])
           .spread_constraint(1, "zone", selector=LabelSelector(match_labels={"app": "web"}))
           .pod_affinity("zone", LabelSelector(match_labels={"app": "web"}), anti=True)
           .toleration("dedicated", "gpu", "NoSchedule")
           .obj())
    p2 = from_wire(Pod, to_wire(pod))
    assert p2.meta.name == "p0" and p2.meta.labels == {"app": "web"}
    assert p2.spec.priority == 100
    assert p2.resource_request() == pod.resource_request()
    assert len(p2.spec.topology_spread_constraints) == 1
    assert p2.spec.topology_spread_constraints[0].label_selector.matches({"app": "web"})
    assert p2.spec.tolerations == pod.spec.tolerations
    assert to_wire(p2) == to_wire(pod)

    node = (make_node("n0").capacity({"cpu": "8", "memory": "16Gi", "pods": 100})
            .label("zone", "z1").taint("dedicated", "gpu", "NoSchedule").obj())
    n2 = from_wire(Node, to_wire(node))
    assert n2.meta.name == "n0"
    assert n2.spec.taints == node.spec.taints
    assert to_wire(n2) == to_wire(node)


def test_wire_scheduler_end_to_end():
    service = DeviceService(batch_size=32)
    server, port = serve(service)
    try:
        store = ClusterStore()
        sched = WireScheduler(store, endpoint=f"http://127.0.0.1:{port}", batch_size=8)
        for i in range(4):
            store.create_node(
                make_node(f"n{i}").capacity({"cpu": "4", "memory": "8Gi", "pods": 10})
                .label("zone", f"z{i % 2}").obj())
        for i in range(12):
            store.create_pod(make_pod(f"p{i}").req({"cpu": "1", "memory": "1Gi"}).obj())
        sched.run_until_settled()
        assert sched.metrics["scheduled"] == 12
        bound = _bound(store)
        per_node = {}
        for n in bound.values():
            per_node[n] = per_node.get(n, 0) + 1
        assert all(v <= 4 for v in per_node.values()), per_node  # 4cpu / 1cpu
    finally:
        server.shutdown()


def test_wire_unschedulable_and_recovery():
    """Pods that do not fit fail with plugin attribution over the wire, park
    unschedulable, and get scheduled after a node appears."""
    service = DeviceService(batch_size=32)
    server, port = serve(service)
    try:
        store = ClusterStore()
        sched = WireScheduler(store, endpoint=f"http://127.0.0.1:{port}", batch_size=8)
        store.create_node(
            make_node("small").capacity({"cpu": "1", "memory": "2Gi", "pods": 10}).obj())
        store.create_pod(make_pod("big").req({"cpu": "4", "memory": "4Gi"}).obj())
        sched.run_until_settled()
        assert sched.metrics["scheduled"] == 0
        assert sched.queue.pending_pods()["unschedulable"] == 1
        store.create_node(
            make_node("large").capacity({"cpu": "8", "memory": "16Gi", "pods": 10}).obj())
        # the reactivated pod sits out its backoff (1s) first
        import time
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and _bound(store).get("big") != "large":
            time.sleep(0.2)
            sched.run_until_settled()
        assert _bound(store).get("big") == "large"
    finally:
        server.shutdown()


def test_wire_matches_in_process_placements():
    """Same workload over the socket and in-process: identical placements
    (same program, same batch numbering, same tie-break seeds)."""
    def build(store):
        for i in range(6):
            store.create_node(
                make_node(f"n{i}").capacity({"cpu": "8", "memory": "16Gi", "pods": 20})
                .label("zone", f"z{i % 3}").obj())
        for i in range(15):
            pw = make_pod(f"p{i}").req({"cpu": "1", "memory": "1Gi"})
            if i % 3 == 0:
                pw.label("app", "web").spread_constraint(
                    1, "zone", selector=LabelSelector(match_labels={"app": "web"}))
            store.create_pod(pw.obj())

    service = DeviceService(batch_size=32)
    server, port = serve(service)
    try:
        store_w = ClusterStore()
        sched_w = WireScheduler(store_w, endpoint=f"http://127.0.0.1:{port}", batch_size=8)
        build(store_w)
        sched_w.run_until_settled()

        import os
        os.environ["KTPU_PIPELINE"] = "0"
        try:
            store_l = ClusterStore()
            sched_l = TPUScheduler(store_l, batch_size=8)
            build(store_l)
            sched_l.run_until_settled()
        finally:
            os.environ.pop("KTPU_PIPELINE", None)

        assert sched_w.metrics["scheduled"] == sched_l.metrics["scheduled"] == 15
        assert _bound(store_w) == _bound(store_l)
    finally:
        server.shutdown()


def test_wire_dra_mask_claim_pods_stay_on_wire():
    """ROADMAP PR 1 follow-up closed: claim-bearing pods ride the wire
    backend (the request ships resolved selector rows; the server builds
    the dra_mask against its own attribute table) — zero oracle fallback,
    allocations identical to the sequential path."""
    from kubernetes_tpu.api.types import ObjectMeta, ResourceClaim, ResourceClass
    from kubernetes_tpu.scheduler.scheduler import Scheduler

    def build(store):
        for i in range(6):
            store.create_node(
                make_node(f"n{i}").capacity({"cpu": "8", "memory": "16Gi", "pods": 20})
                .device_attrs({"tpu.dev/cores": 8 if i % 2 else 2,
                               "tpu.dev/gen": "v5" if i % 2 else "v4"}).obj())
        store.create_object("ResourceClass", ResourceClass(
            meta=ObjectMeta(name="tpu.example.com", namespace=""),
            driver_name="tpu.example.com", selectors={"tpu.dev/gen": "v5"}))
        for i in range(4):
            store.create_object("ResourceClaim", ResourceClaim(
                meta=ObjectMeta(name=f"c{i}"),
                resource_class_name="tpu.example.com",
                selectors={"tpu.dev/cores": ">=4"}))
            store.create_pod(make_pod(f"claim-{i}").req({"cpu": "300m"})
                             .resource_claim("dev", claim_name=f"c{i}").obj())
            store.create_pod(make_pod(f"plain-{i}").req({"cpu": "300m"}).obj())

    service = DeviceService(batch_size=32)
    server, port = serve(service)
    try:
        store_w = ClusterStore()
        sched_w = WireScheduler(store_w, endpoint=f"http://127.0.0.1:{port}",
                                batch_size=16)
        build(store_w)
        sched_w.run_until_settled()
        assert sched_w.metrics["scheduled"] == 8
        assert sched_w.degraded_pods == 0

        store_o = ClusterStore()
        sched_o = Scheduler(store_o)
        build(store_o)
        sched_o.run_until_settled()
        assert _bound(store_w) == _bound(store_o)
        claims_w = {k: (c.allocated_node, c.reserved_for)
                    for k, c in store_w.resource_claims.items()}
        claims_o = {k: (c.allocated_node, c.reserved_for)
                    for k, c in store_o.resource_claims.items()}
        assert claims_w == claims_o
        # only v5 nodes (odd indices) hold claim pods
        for k, node in _bound(store_w).items():
            if k.startswith("claim"):
                assert int(node[1:]) % 2 == 1, (k, node)
    finally:
        server.shutdown()


def test_conflict_vs_stale_epoch_409_disambiguation():
    """Two DIFFERENT 409s ride the same status code: ``staleEpoch`` (resync
    and carry on) vs ``conflict`` (another client owns it — requeue). The
    client must map them to distinct typed errors."""
    import pytest

    from kubernetes_tpu.backend.errors import ConflictError, StaleEpochError
    from kubernetes_tpu.backend.service import WireClient

    service = DeviceService(batch_size=8)
    server, port = serve(service)
    try:
        client = WireClient(f"http://127.0.0.1:{port}")
        # 409 + staleEpoch: wrong process epoch
        with pytest.raises(StaleEpochError):
            client.apply_deltas({"expectEpoch": "not-this-process",
                                 "nodes": []})
        # 409 + conflict: a fenced/raced session commit
        service.apply_deltas({"clientId": "A", "nodes": []})
        gen_a = service.sessions["A"].gen
        service._fence(service.sessions["A"])
        with pytest.raises(ConflictError):
            client.schedule_batch({"clientId": "A", "sessionGen": gen_a,
                                   "pods": []})
    finally:
        server.shutdown()


def test_wire_conflict_requeues_via_backoff_not_breaker():
    """A conflict verdict maps to a rate-limited backoffQ requeue and a
    session rejoin — never a breaker count (the service is healthy) and
    never oracle degradation."""
    from kubernetes_tpu.backend import circuit
    from kubernetes_tpu.testing.faults import FaultPlan
    from kubernetes_tpu.utils.clock import FakeClock

    service = DeviceService(batch_size=16)
    plan = FaultPlan()
    server, port = serve(service, fault_plan=plan)
    try:
        store = ClusterStore()
        clock = FakeClock()
        sched = WireScheduler(
            store, endpoint=f"http://127.0.0.1:{port}", batch_size=8,
            client_id="confl", now_fn=clock,
            sleep_fn=lambda s: clock.advance(s), fault_plan=plan,
            breaker_threshold=2)
        store.create_node(make_node("n0").capacity(
            {"cpu": "4", "memory": "8Gi", "pods": 10}).obj())
        plan.conflict("schedule_batch")
        store.create_pod(make_pod("p0").req({"cpu": "500m"}).obj())
        sched.run_until_settled()
        # the conflicted pod sat out a backoff window, the session rejoined,
        # and the retry landed the pod on the wire path
        assert sched.metrics["scheduled"] == 0
        assert sched.queue.pending_pods()["backoff"] == 1
        assert sched.breaker.state == circuit.CLOSED
        assert sched.degraded_pods == 0
        assert sched.session_rejoins == 1
        assert sched.smetrics.commit_conflicts.labels("confl") == 1
        clock.advance(1.1)
        sched.run_until_settled()
        assert sched.metrics["scheduled"] == 1
        assert sched.breaker.state == circuit.CLOSED
        assert service.batch_counter > 0
    finally:
        server.shutdown()


def test_per_pod_conflict_verdict_requeues_one_pod():
    """A per-result conflict (ownership check lost for ONE pod of a batch)
    requeues just that pod; the rest of the batch binds normally."""
    from kubernetes_tpu.api.codec import to_wire
    from kubernetes_tpu.utils.clock import FakeClock

    service = DeviceService(batch_size=16)
    server, port = serve(service)
    try:
        store = ClusterStore()
        clock = FakeClock()
        sched = WireScheduler(
            store, endpoint=f"http://127.0.0.1:{port}", batch_size=8,
            client_id="mine", now_fn=clock,
            sleep_fn=lambda s: clock.advance(s))
        store.create_node(make_node("n0").capacity(
            {"cpu": "8", "memory": "8Gi", "pods": 10}).obj())
        # a rival session commits (holds) the pod "stolen" before our
        # scheduler's batch reaches the service
        store.create_pod(make_pod("stolen").req({"cpu": "1"}).obj())
        store.create_pod(make_pod("okay").req({"cpu": "1"}).obj())
        rival_entry = {"gen": 1,
                       "node": to_wire(store.nodes["n0"]), "pods": []}
        service.apply_deltas({"clientId": "rival", "nodes": [rival_entry]})
        service.schedule_batch({
            "clientId": "rival", "batchId": "rival-1",
            "pods": [to_wire(store.get_pod("default/stolen"))]})
        sched.run_until_settled()
        assert _bound(store).get("okay") == "n0"
        assert "stolen" not in _bound(store)  # conflicted, parked in backoff
        assert sched.smetrics.commit_conflicts.labels("mine") >= 1
        assert sched.queue.pending_pods()["backoff"] == 1
    finally:
        server.shutdown()


def test_full_resync_after_restart_with_rejoined_session():
    """Device restart recovery must not depend on session-generation
    coincidence: a client whose session had already been re-minted (gen > 1)
    full-resyncs a RESTARTED service cleanly — the resync joins fresh
    instead of stamping the dead incarnation's gen (which the new instance
    would refuse as a zombie)."""
    from kubernetes_tpu.backend import circuit
    from kubernetes_tpu.testing.faults import FaultPlan
    from kubernetes_tpu.utils.clock import FakeClock

    service = DeviceService(batch_size=16)
    plan = FaultPlan()
    server, port = serve(service, fault_plan=plan)
    try:
        store = ClusterStore()
        clock = FakeClock()
        sched = WireScheduler(
            store, endpoint=f"http://127.0.0.1:{port}", batch_size=8,
            client_id="rs", now_fn=clock,
            sleep_fn=lambda s: clock.advance(s), fault_plan=plan)
        store.create_node(make_node("n0").capacity(
            {"cpu": "8", "memory": "8Gi", "pods": 10}).obj())
        store.create_pod(make_pod("p0").req({"cpu": "1"}).obj())
        sched.run_until_settled()
        assert sched._session_gen is not None
        # server-side fence forces a rejoin: the client's NEXT flush gets a
        # conflict, rejoins, and lands under a fresh (non-1) generation
        server.binding.service._fence(
            server.binding.service.sessions["rs"])
        store.create_pod(make_pod("p1").req({"cpu": "1"}).obj())
        sched.run_until_settled()
        clock.advance(1.1)
        sched.run_until_settled()
        assert sched.session_rejoins == 1
        assert sched._session_gen is not None and sched._session_gen > 1
        assert _bound(store).get("p1") == "n0"
        conflicts_after_rejoin = sched.smetrics.commit_conflicts.labels("rs")

        # the sidecar crashes (fresh instance, session gens restart at 1):
        # stale-epoch recovery must be ONE clean full resync, not a second
        # conflict round-trip
        plan.crash("apply_deltas")
        store.create_pod(make_pod("p2").req({"cpu": "1"}).obj())
        sched.run_until_settled()
        clock.advance(1.1)
        sched.run_until_settled()
        assert _bound(store).get("p2") == "n0"
        assert sched.resyncs == 1
        assert sched.breaker.state == circuit.CLOSED
        assert (sched.smetrics.commit_conflicts.labels("rs")
                == conflicts_after_rejoin)  # no conflict on the restart path
    finally:
        server.shutdown()


def test_heartbeat_skipped_while_breaker_open():
    """Degraded-mode liveness: with the breaker OPEN the scheduler must not
    burn retry backoffs on heartbeats against a dead device — the breaker
    probe owns re-discovery."""
    from kubernetes_tpu.backend import circuit
    from kubernetes_tpu.testing.faults import FaultPlan
    from kubernetes_tpu.utils.clock import FakeClock

    service = DeviceService(batch_size=16)
    plan = FaultPlan()
    server, port = serve(service, fault_plan=plan)
    try:
        store = ClusterStore()
        clock = FakeClock()
        sched = WireScheduler(
            store, endpoint=f"http://127.0.0.1:{port}", batch_size=8,
            client_id="hb", now_fn=clock,
            sleep_fn=lambda s: clock.advance(s), fault_plan=plan,
            wire_max_retries=0, breaker_threshold=1, breaker_reset_s=60.0,
            heartbeat_interval_s=1.0)
        store.create_node(make_node("n0").capacity(
            {"cpu": "4", "memory": "8Gi", "pods": 10}).obj())
        beats = []
        real_heartbeat = sched.client.heartbeat
        sched.client.heartbeat = lambda p: (beats.append(1),
                                            real_heartbeat(p))[1]
        plan.drop(count=1)
        store.create_pod(make_pod("p0").req({"cpu": "500m"}).obj())
        sched.run_until_settled()
        assert sched.breaker.state == circuit.OPEN
        assert sched.metrics["scheduled"] == 1  # degraded oracle path
        # heartbeat intervals elapse while open: no wire beats fired
        for _ in range(5):
            clock.advance(2.0)
            sched.run_until_settled()
        assert beats == []
    finally:
        server.shutdown()


def test_heartbeat_verb_and_debug_sessions():
    """The heartbeat verb renews the lease and reports live sessions; the
    /debug/sessions body carries per-client lease age, deltaSeq, and hold
    counts from the service's session table."""
    service = DeviceService(batch_size=16)
    server, port = serve(service)
    try:
        store = ClusterStore()
        sched = WireScheduler(store, endpoint=f"http://127.0.0.1:{port}",
                              batch_size=8, client_id="dbg")
        store.create_node(make_node("n0").capacity(
            {"cpu": "4", "memory": "8Gi", "pods": 10}).obj())
        store.create_pod(make_pod("p0").req({"cpu": "500m"}).obj())
        sched.run_until_settled()
        assert sched.metrics["scheduled"] == 1
        sched._heartbeat()
        assert sched._session_gen == service.sessions["dbg"].gen
        assert sched.smetrics.client_sessions.labels() == 1

        doc = sched.debug_sessions()
        assert doc["enabled"] and doc["clientId"] == "dbg"
        table = {s["clientId"]: s for s in doc["service"]["sessions"]}
        assert "dbg" in table
        row = table["dbg"]
        assert row["deltaSeq"] >= 1
        assert row["leaseAgeS"] >= 0.0
        assert row["batches"] >= 1
        assert "inflightHolds" in row and row["fenced"] is False
    finally:
        server.shutdown()


def test_wire_health_verb_and_half_open_probe():
    """The Health RPC answers cheaply with the process identity, and a
    half-open breaker probes through it instead of pushing a full batch."""
    from kubernetes_tpu.backend import circuit
    from kubernetes_tpu.testing.faults import FaultPlan
    from kubernetes_tpu.utils.clock import FakeClock

    service = DeviceService(batch_size=16)
    plan = FaultPlan()
    server, port = serve(service, fault_plan=plan)
    try:
        store = ClusterStore()
        clock = FakeClock()
        sched = WireScheduler(
            store, endpoint=f"http://127.0.0.1:{port}", batch_size=8,
            now_fn=clock, sleep_fn=lambda s: clock.advance(s),
            fault_plan=plan, wire_max_retries=0, breaker_threshold=1,
            breaker_reset_s=5.0)
        out = sched.client.health()
        assert out["status"] == "serving"
        assert out["epoch"] == service.epoch

        for i in range(2):
            store.create_node(make_node(f"n{i}").capacity(
                {"cpu": "4", "memory": "8Gi", "pods": 10}).obj())
        # open the breaker: one dropped push
        plan.drop(count=1)
        store.create_pod(make_pod("p0").req({"cpu": "500m"}).obj())
        sched.run_until_settled()
        assert sched.breaker.state == circuit.OPEN
        assert sched.metrics["scheduled"] == 1  # degraded via oracle

        # half-open probe: health is the FIRST wire op attempted, and a
        # dead service fails it without burning a batch push
        plan.drop(op="health", count=1)
        clock.advance(5.5)
        store.create_pod(make_pod("p1").req({"cpu": "500m"}).obj())
        sched.run_until_settled()
        assert ("client", "health", "drop") in plan.log
        assert sched.breaker.state == circuit.OPEN  # probe failed, re-opened
        assert sched.metrics["scheduled"] == 2      # batch still landed

        # next probe succeeds -> breaker closes, wire path resumes
        clock.advance(5.5)
        store.create_pod(make_pod("p2").req({"cpu": "500m"}).obj())
        sched.run_until_settled()
        assert sched.breaker.state == circuit.CLOSED
        assert sched.metrics["scheduled"] == 3
        assert service.batch_counter > 0
    finally:
        server.shutdown()


class TestWirePipeline:
    """Pipelined wire transport (ROADMAP item 2, wire half): K batches in
    flight over concurrent connection lanes, replies matched by the
    server-echoed batchId, epoch/session/conflict semantics identical to
    the synchronous path, and commit holds protected across the pipelined
    delta/reply interleaving."""

    def _rig(self, depth, plan=None, nodes=4, pods=12, batch_size=4):
        from kubernetes_tpu.testing.faults import FaultPlan
        from kubernetes_tpu.utils.clock import FakeClock

        plan = plan if plan is not None else FaultPlan()
        service = DeviceService(batch_size=32)
        server, port = serve(service, fault_plan=plan)
        clock = FakeClock()
        store = ClusterStore()
        sched = WireScheduler(
            store, endpoint=f"http://127.0.0.1:{port}", batch_size=batch_size,
            wire_pipeline_depth=depth, fault_plan=plan,
            now_fn=clock, sleep_fn=lambda s: clock.advance(s),
            heartbeat_interval_s=0.0, wire_max_retries=1,
            pod_initial_backoff=0.01, pod_max_backoff=0.05)
        for i in range(nodes):
            store.create_node(make_node(f"n{i}").capacity(
                {"cpu": "8", "memory": "16Gi", "pods": 20}).obj())
        for i in range(pods):
            store.create_pod(make_pod(f"p{i}").req({"cpu": "500m"}).obj())
        return service, server, store, sched, clock, plan

    def test_pipelined_placements_match_synchronous(self):
        """Depth K>1 changes WHEN replies are processed, never WHAT is
        decided: placements are byte-identical to the synchronous path."""
        results = {}
        for depth in (0, 3):
            service, server, store, sched, _, _ = self._rig(depth)
            try:
                sched.run_until_settled()
                results[depth] = _bound(store)
                assert len(results[depth]) == 12
                assert service.batch_replays == 0
            finally:
                server.shutdown()
        assert results[0] == results[3]

    def test_keeps_k_batches_in_flight(self):
        """Three cycles submit three batches without blocking on replies —
        the ring only drains past its depth (or at the empty-pop flush)."""
        service, server, store, sched, _, _ = self._rig(3)
        try:
            for _ in range(3):
                sched.schedule_batch_cycle()
            assert len(sched._wire_inflight) == 3
            assert sched.smetrics.wire_inflight.labels() == 3
            sched.run_until_settled()
            assert len(sched._wire_inflight) == 0
            assert sched.smetrics.wire_inflight.labels() == 0
            assert len(_bound(store)) == 12
            assert sched.pipelined_wire_batches >= 2
            # the stall-aware sizer (shared with the in-process ring) was
            # fed real pop->processed observations
            assert sched.wire_sizer.updates >= 3
        finally:
            server.shutdown()

    def test_out_of_order_replies_matched_by_batch_id(self):
        """The reorder fault swaps the next two replies across lanes: each
        lane receives the OTHER call's reply, and the completion router
        must pair every reply with its batch by the echoed batchId."""
        from kubernetes_tpu.testing.faults import FaultPlan

        plan = FaultPlan().reorder("schedule_batch")
        service, server, store, sched, _, _ = self._rig(3, plan=plan)
        try:
            sched.run_until_settled()
            assert len(_bound(store)) == 12
            assert service.batch_replays == 0
            assert sched._wire_pipeline.duplicate_replies == 0
            # the swap really fired: both consumptions of the two-shot fault
            assert [e for e in plan.log if e == ("reply", "schedule_batch",
                                                 "reorder")] != []
        finally:
            server.shutdown()

    def test_duplicate_reply_dropped_by_router(self):
        from kubernetes_tpu.testing.faults import FaultPlan

        plan = FaultPlan().dup_reply("schedule_batch")
        service, server, store, sched, _, _ = self._rig(3, plan=plan)
        try:
            sched.run_until_settled()
            assert len(_bound(store)) == 12
            assert sched._wire_pipeline.duplicate_replies == 1
            assert service.batch_replays == 0
        finally:
            server.shutdown()

    def test_torn_reply_replays_idempotently_under_pipeline(self):
        """Torn mid-stream disconnect with batches in flight: the server
        committed, the reply died — the transport retry replays by batchId
        and nothing is double-committed."""
        from kubernetes_tpu.testing.faults import FaultPlan

        plan = FaultPlan().torn("schedule_batch")
        service, server, store, sched, _, _ = self._rig(3, plan=plan)
        try:
            sched.run_until_settled()
            bound = _bound(store)
            assert len(bound) == 12
            assert service.batch_replays == 1
            per_node = {}
            for n in bound.values():
                per_node[n] = per_node.get(n, 0) + 1
            assert all(v <= 16 for v in per_node.values())
        finally:
            server.shutdown()

    def test_inflight_batch_holds_survive_owner_delta_push(self):
        """The pipelined hole in hold reconciliation, closed: the owner's
        delta push omits placements from batches whose replies it has not
        processed — naming them in inflightBatchIds keeps their holds (and
        the capacity they occupy) alive; omitting the name releases."""
        node = make_node("n0").capacity(
            {"cpu": "4", "memory": "8Gi", "pods": 10}).obj()
        service = DeviceService(batch_size=8)
        entry = {"gen": 1, "node": to_wire(node), "pods": []}
        service.apply_deltas({"clientId": "A", "nodes": [entry]})
        pod = to_wire(make_pod("p").req({"cpu": "2"}).obj())
        out = service.schedule_batch({"clientId": "A", "pods": [pod],
                                      "batchId": "b-1"})
        assert out["results"][0]["nodeName"] == "n0"
        assert out["batchId"] == "b-1"
        assert service.infos["n0"].requested.milli_cpu == 2000
        # the owner pushes the node WITHOUT the pod, but names b-1 in
        # flight: the hold must survive (the owner cannot know yet)
        service.apply_deltas({"clientId": "A",
                              "nodes": [dict(entry, gen=2)],
                              "inflightBatchIds": ["b-1"]})
        assert "p" in {h.pod.meta.name for h in service.holds.values()}
        assert service.infos["n0"].requested.milli_cpu == 2000
        # same push with b-1 no longer in flight: owner content is truth
        # again - the omission means surrendered, the hold releases
        service.apply_deltas({"clientId": "A",
                              "nodes": [dict(entry, gen=3)]})
        assert service.holds == {}
        assert service.infos["n0"].requested.milli_cpu == 0


def test_replicator_entries_never_regress_direct_client_rows():
    """A warm-standby replicator mirrors a client's PAST pushes; if one of
    its pushes lands late (e.g. a push hung across a promote), it must
    never overwrite a direct session's newer truth — entries at a
    generation <= the direct client's are skipped, stale removals too."""
    service = DeviceService(batch_size=8)

    def node_v(v):
        return to_wire(make_node("n0").capacity(
            {"cpu": "4", "memory": "8Gi", "pods": 10}).label("v", v).obj())

    service.apply_deltas({"clientId": "A",
                          "nodes": [{"gen": 5, "node": node_v("2"),
                                     "pods": []}]})
    # a lagging replicator entry (older gen) is skipped...
    service.apply_deltas({"clientId": "R", "replicator": True,
                          "nodes": [{"gen": 3, "node": node_v("1"),
                                     "pods": []}]})
    assert service.infos["n0"].node.meta.labels["v"] == "2"
    assert "n0" not in service.sessions["R"].sent_gens
    # ...and a stale replicated removal is skipped when the direct client
    # pushed the node SINCE the replicator's previous contact
    service.apply_deltas({"clientId": "A",
                          "nodes": [{"gen": 6, "node": node_v("2"),
                                     "pods": []}]})
    service.apply_deltas({"clientId": "R", "replicator": True,
                          "nodes": [], "removed": ["n0"]})
    assert "n0" in service.infos
    # a replicated entry NEWER than the direct client's applies normally
    service.apply_deltas({"clientId": "R", "replicator": True,
                          "nodes": [{"gen": 7, "node": node_v("3"),
                                     "pods": []}]})
    assert service.infos["n0"].node.meta.labels["v"] == "3"
    # healed-ex-active case: the direct session goes idle (its lease kept
    # warm but no pushes) — the replication stream is the freshest truth
    # and its removal must land, not strand a ghost behind stale claims
    service.apply_deltas({"clientId": "R", "replicator": True,
                          "nodes": [], "removed": ["n0"]})
    assert "n0" not in service.infos
