"""Device-side over-quota screen (ISSUE 19 tentpole): oracle<->tpu<->wire
parity of the verdict column, the in-batch sequential-charge semantics, the
namespace-quota tensor sync, and the relay guard — a screened batch still
costs exactly one blocking read and zero extra dispatches (the screen is
traced into the batch program; its words ride the packed result block)."""

import random

import numpy as np
import pytest

from kubernetes_tpu.api.types import QUOTA_DIM_ORDER, QUOTA_PODS
from kubernetes_tpu.api.wrappers import make_node, make_pod
from kubernetes_tpu.apiserver import ClusterStore
from kubernetes_tpu.backend import TPUScheduler
from kubernetes_tpu.backend.device_state import DeviceState, caps_for_cluster
from kubernetes_tpu.ops.quota import (
    QUOTA_DIMS,
    QUOTA_NO_LIMIT,
    QUOTA_OK_BIT,
    QUOTA_SCREEN_BIT,
    build_quota_batch_args,
    quota_screen,
    quota_screen_host,
)
from kubernetes_tpu.utils import relay


# ---------------------------------------------------------------------------
# kernel <-> host-oracle parity


def _random_case(seed):
    rng = random.Random(seed)
    p = rng.choice([4, 8, 16])
    ns_n = rng.randint(1, 4)
    node_idx = np.array([rng.randint(-1, 7) for _ in range(p)], np.int32)
    ns_idx = np.array([rng.randint(-1, ns_n - 1) for _ in range(p)], np.int32)
    req = np.array([[rng.randint(0, 5) for _ in range(QUOTA_DIMS)]
                    for _ in range(p)], np.int32)
    used = np.array([[rng.randint(0, 6) for _ in range(QUOTA_DIMS)]
                     for _ in range(ns_n)], np.int32)
    limit = np.array([[rng.choice([rng.randint(0, 10), int(QUOTA_NO_LIMIT)])
                       for _ in range(QUOTA_DIMS)]
                      for _ in range(ns_n)], np.int32)
    return node_idx, ns_idx, req, used, limit


class TestKernelHostParity:
    @pytest.mark.parametrize("seed", range(8))
    def test_randomized_parity(self, seed):
        """The parity contract: the lax.scan kernel and its numpy twin
        judge every randomized batch identically, bit for bit."""
        import jax.numpy as jnp

        node_idx, ns_idx, req, used, limit = _random_case(seed)
        dev = np.asarray(quota_screen(
            jnp.asarray(node_idx), jnp.asarray(ns_idx), jnp.asarray(req),
            jnp.asarray(used), jnp.asarray(limit)))
        host = quota_screen_host(node_idx, ns_idx, req, used, limit)
        assert np.array_equal(dev, host), (seed, dev, host)

    def test_sequential_same_namespace_charging(self):
        """Two same-namespace winners in one batch see each other's
        charges (the scan carries evolving usage): with headroom for one,
        the FIRST in batch order passes and the second flags."""
        import jax.numpy as jnp

        node_idx = np.array([0, 1], np.int32)
        ns_idx = np.array([0, 0], np.int32)
        req = np.zeros((2, QUOTA_DIMS), np.int32)
        pods_col = QUOTA_DIM_ORDER.index(QUOTA_PODS)
        req[:, pods_col] = 1
        used = np.zeros((1, QUOTA_DIMS), np.int32)
        limit = np.full((1, QUOTA_DIMS), QUOTA_NO_LIMIT, np.int32)
        limit[0, pods_col] = 1
        words = np.asarray(quota_screen(
            jnp.asarray(node_idx), jnp.asarray(ns_idx), jnp.asarray(req),
            jnp.asarray(used), jnp.asarray(limit)))
        assert int(words[0]) == QUOTA_SCREEN_BIT | QUOTA_OK_BIT
        assert int(words[1]) == QUOTA_SCREEN_BIT
        host = quota_screen_host(node_idx, ns_idx, req, used, limit)
        assert np.array_equal(words, host)

    def test_losers_read_ok_and_never_charge(self):
        """An unplaced pod (node_idx < 0) reads as ok — there is nothing
        to reject — and must not consume the namespace's headroom from a
        later winner in the same batch."""
        import jax.numpy as jnp

        node_idx = np.array([-1, 3], np.int32)
        ns_idx = np.array([0, 0], np.int32)
        req = np.zeros((2, QUOTA_DIMS), np.int32)
        pods_col = QUOTA_DIM_ORDER.index(QUOTA_PODS)
        req[:, pods_col] = 1
        used = np.zeros((1, QUOTA_DIMS), np.int32)
        limit = np.full((1, QUOTA_DIMS), QUOTA_NO_LIMIT, np.int32)
        limit[0, pods_col] = 1
        words = np.asarray(quota_screen(
            jnp.asarray(node_idx), jnp.asarray(ns_idx), jnp.asarray(req),
            jnp.asarray(used), jnp.asarray(limit)))
        # the loser is screened-and-ok; the winner takes the last slot
        assert int(words[0]) == QUOTA_SCREEN_BIT | QUOTA_OK_BIT
        assert int(words[1]) == QUOTA_SCREEN_BIT | QUOTA_OK_BIT
        host = quota_screen_host(node_idx, ns_idx, req, used, limit)
        assert np.array_equal(words, host)

    def test_unscreened_namespace_word_zero(self):
        import jax.numpy as jnp

        node_idx = np.array([0], np.int32)
        ns_idx = np.array([-1], np.int32)
        req = np.ones((1, QUOTA_DIMS), np.int32)
        used = np.zeros((1, QUOTA_DIMS), np.int32)
        limit = np.zeros((1, QUOTA_DIMS), np.int32)
        words = np.asarray(quota_screen(
            jnp.asarray(node_idx), jnp.asarray(ns_idx), jnp.asarray(req),
            jnp.asarray(used), jnp.asarray(limit)))
        assert int(words[0]) == 0


# ---------------------------------------------------------------------------
# batch-arg builder + device tensor sync


def _pods(n, ns="default"):
    return [make_pod(f"p{i}", namespace=ns).req({"cpu": "1"}).obj()
            for i in range(n)]


def _row(pods_cap):
    limit = [int(QUOTA_NO_LIMIT)] * QUOTA_DIMS
    limit[QUOTA_DIM_ORDER.index(QUOTA_PODS)] = pods_cap
    return [0] * QUOTA_DIMS, limit


class TestBuildArgsAndSync:
    def test_no_screened_namespace_is_none(self):
        """The common case — no pod in a quota'd namespace — adds NO args:
        the batch program is byte-identical to the pre-screen one."""
        device = DeviceState(caps_for_cluster(4))
        ns_idx, req = build_quota_batch_args(_pods(3), device, table={})
        assert ns_idx is None and req is None

    def test_padding_rows_are_exempt(self):
        device = DeviceState(caps_for_cluster(4))
        used, limit = _row(5)
        ns_idx, req = build_quota_batch_args(
            _pods(2, ns="team-a"), device,
            table={"team-a": (used, limit)}, pad_to=8)
        assert ns_idx is not None and len(ns_idx) == 8
        assert (ns_idx[2:] == -1).all()
        assert (ns_idx[:2] >= 0).all()
        assert req.shape == (8, QUOTA_DIMS)

    def test_table_sync_is_content_diffed(self):
        """A steady-state table uploads nothing (the screen must not add
        per-batch transfer traffic); only content changes re-upload."""
        device = DeviceState(caps_for_cluster(4))
        table = {"team-a": _row(5)}
        assert device.set_ns_quota(table) is True
        n = device.nsq_uploads
        assert device.set_ns_quota({"team-a": _row(5)}) is False
        assert device.nsq_uploads == n
        assert device.set_ns_quota({"team-a": _row(6)}) is True
        assert device.nsq_uploads == n + 1

    def test_deleted_namespace_resets_to_never_flags(self):
        """The table is the COMPLETE desired state: a registered namespace
        absent from it (quota deleted) resets to never-flags rows — a
        stale row would reject-and-requeue what the host gate re-admits,
        forever."""
        device = DeviceState(caps_for_cluster(4))
        used = [3] * QUOTA_DIMS
        _z, limit = _row(1)
        device.set_ns_quota({"team-a": (used, limit)})
        slot = device.nsq_slots["team-a"]
        device.set_ns_quota({})  # quota deleted
        assert not device._nsq_used_m[slot].any()
        assert (device._nsq_limit_m[slot] == int(QUOTA_NO_LIMIT)).all()
        # the slot survives (slot indices are sticky for in-flight batches)
        assert device.nsq_slots["team-a"] == slot


# ---------------------------------------------------------------------------
# the batched path end-to-end: screen fires in-jit, one read, no extras


def _spy_materialize(monkeypatch):
    """Record each batch's materialized quota column without adding reads:
    wraps commit_plane.materialize_profiled (imported at call time)."""
    from kubernetes_tpu.backend import commit_plane

    seen = []
    real = commit_plane.materialize_profiled

    def spy(*a, **kw):
        out, disp = real(*a, **kw)
        seen.append(out[3])  # quota_words column (or None)
        return out, disp

    monkeypatch.setattr(commit_plane, "materialize_profiled", spy)
    return seen


class TestBatchedScreenEndToEnd:
    def test_in_batch_over_admission_is_screened(self, monkeypatch):
        """Six same-namespace pods in ONE batch against a pods=2 cap: the
        host gate passes all six (the ledger charges at commit), so the
        in-jit screen is the thing that stops the four over-quota winners
        — its verdict column must carry exactly four screened-not-ok
        words, and the commit must bind exactly two pods."""
        words_per_batch = _spy_materialize(monkeypatch)
        store = ClusterStore()
        from tests.test_quota import nodes, pod, quota

        nodes(store)
        quota(store, "team-a", {QUOTA_PODS: 2})
        sched = TPUScheduler(store, batch_size=8)
        with relay.track() as counts:
            for i in range(6):
                pod(store, f"p{i}", ns="team-a")
            sched.run_batched_until_settled()
        assert sum(1 for p in store.pods.values() if p.spec.node_name) == 2
        plugin = next(iter(sched.profiles.values())).plugin("QuotaAdmission")
        assert plugin.usage("team-a")[QUOTA_PODS] == 2
        # the first batch carried the screen column and flagged the four
        # over-quota winners IN-JIT (not at host revalidation)
        first = words_per_batch[0]
        assert first is not None
        flagged = sum(1 for w in np.asarray(first)[:6]
                      if (int(w) & QUOTA_SCREEN_BIT)
                      and not (int(w) & QUOTA_OK_BIT))
        assert flagged == 4, np.asarray(first)[:6]
        # THE relay guard: screened batches still cost exactly one
        # blocking read each, and nothing else
        assert counts["commit-read"] == sched.batch_counter
        assert sum(counts.values()) == counts["commit-read"], dict(counts)

    def test_unquotad_namespaces_skip_the_screen(self, monkeypatch):
        """No quota anywhere: every batch dispatches without the quota
        column — the screen costs nothing when unused."""
        words_per_batch = _spy_materialize(monkeypatch)
        store = ClusterStore()
        from tests.test_quota import nodes

        nodes(store)
        sched = TPUScheduler(store, batch_size=8)
        with relay.track() as counts:
            for i in range(6):
                store.create_pod(
                    make_pod(f"p{i}").req({"cpu": "1", "memory": "1Gi"}).obj())
            sched.run_batched_until_settled()
        assert sum(1 for p in store.pods.values() if p.spec.node_name) == 6
        assert all(w is None for w in words_per_batch)
        assert counts["commit-read"] == sched.batch_counter
        assert sum(counts.values()) == counts["commit-read"], dict(counts)

    def test_screen_covers_borrowed_headroom(self, monkeypatch):
        """The synced limit rows are the ledger's EFFECTIVE caps (own hard
        + borrowable cohort headroom): a borrower's in-batch winners pass
        the screen up to the pool, not its own cap."""
        words_per_batch = _spy_materialize(monkeypatch)
        store = ClusterStore()
        from tests.test_quota import nodes, pod, quota

        nodes(store)
        quota(store, "lend", {QUOTA_PODS: 3}, cohort="pool")
        quota(store, "hungry", {QUOTA_PODS: 2}, cohort="pool")
        sched = TPUScheduler(store, batch_size=8)
        for i in range(7):  # pool = 5: five admit (3 borrowed), two flag
            pod(store, f"b{i}", ns="hungry")
        sched.run_batched_until_settled()
        assert sum(1 for p in store.pods.values() if p.spec.node_name) == 5
        plugin = next(iter(sched.profiles.values())).plugin("QuotaAdmission")
        assert plugin.borrowed("hungry")[QUOTA_PODS] == 3
        assert any(w is not None for w in words_per_batch)


# ---------------------------------------------------------------------------
# wire parity: the verdict word rides the result rows; the server screens
# with the same shared builder, so both transports place identically


class TestWireScreenParity:
    def test_wire_matches_in_process_with_quota(self):
        import os

        from kubernetes_tpu.backend.service import (
            DeviceService, WireScheduler, serve)
        from tests.test_quota import nodes, pod, quota

        def build(store):
            nodes(store)
            quota(store, "team-a", {QUOTA_PODS: 3})
            for i in range(8):
                pod(store, f"p{i}", ns="team-a")

        service = DeviceService(batch_size=32)
        server, port = serve(service)
        try:
            store_w = ClusterStore()
            sched_w = WireScheduler(
                store_w, endpoint=f"http://127.0.0.1:{port}", batch_size=8)
            build(store_w)
            sched_w.run_until_settled()

            os.environ["KTPU_PIPELINE"] = "0"
            try:
                store_l = ClusterStore()
                sched_l = TPUScheduler(store_l, batch_size=8)
                build(store_l)
                sched_l.run_batched_until_settled()
            finally:
                os.environ.pop("KTPU_PIPELINE", None)

            def bound(store):
                return {p.meta.name: p.spec.node_name
                        for p in store.pods.values() if p.spec.node_name}

            assert len(bound(store_w)) == len(bound(store_l)) == 3
            assert bound(store_w) == bound(store_l)
            # zero oversubscription on both transports, judged by the ledger
            for sched in (sched_w, sched_l):
                plugin = next(iter(sched.profiles.values())).plugin(
                    "QuotaAdmission")
                assert plugin.usage("team-a")[QUOTA_PODS] == 3
        finally:
            server.shutdown()
