"""tools/check_metrics.py + tools/check_markers.py as tier-1 gates: every
metric registered in SchedulerMetrics must be observed/set somewhere outside
its definition (defined-but-dead metrics can't reappear), and every
perf-scale test (>= 1k nodes / TEST_CASES defaults) must carry the ``slow``
marker so tier-1's ``-m 'not slow'`` budget holds."""

import importlib.util
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOL = os.path.join(REPO, "tools", "check_metrics.py")
MARKER_TOOL = os.path.join(REPO, "tools", "check_markers.py")


def _load(path, name):
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _load_tool():
    return _load(TOOL, "check_metrics")


def test_no_dead_metrics():
    p = subprocess.run([sys.executable, TOOL], cwd=REPO,
                       capture_output=True, text=True, timeout=120)
    assert p.returncode == 0, p.stdout + p.stderr
    assert "ok:" in p.stdout


def test_finds_all_registered_metrics():
    mod = _load_tool()
    attrs, dead = mod.find_dead_metrics()
    # the full SchedulerMetrics roster is visible to the AST pass
    for expected in ("schedule_attempts", "framework_extension_point_duration",
                     "plugin_execution_duration", "pending_pods",
                     "queue_incoming_pods", "unschedulable_pods"):
        assert expected in attrs
    assert dead == []


def test_detects_a_dead_metric(tmp_path, monkeypatch):
    """Negative control: a registered-but-unobserved metric is reported."""
    mod = _load_tool()
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    metrics_file = pkg / "sm.py"
    metrics_file.write_text(
        "class SchedulerMetrics:\n"
        "    def __init__(self, r):\n"
        "        self.live_metric = r.register(Counter('a', 'h'))\n"
        "        self.helper_metric = r.register(Counter('b', 'h'))\n"
        "        self.dead_metric = r.register(Counter('c', 'h'))\n"
        "    def sync_helper(self):\n"
        "        self.helper_metric.set('x', value=1)\n"
    )
    (pkg / "user.py").write_text(
        "def f(m):\n"
        "    m.live_metric.inc('x')\n"
        "    m.sync_helper()\n"
    )
    monkeypatch.setattr(mod, "PKG", str(pkg))
    monkeypatch.setattr(mod, "METRICS_FILE", str(metrics_file))
    attrs, dead = mod.find_dead_metrics()
    assert set(attrs) == {"live_metric", "helper_metric", "dead_metric"}
    assert dead == ["dead_metric"]


def test_gang_metrics_registered_and_live():
    """The gang metrics are in the checked roster AND fed (the check's
    coverage extends to them: a future refactor that orphans either fails
    tier-1 like any other dead metric)."""
    mod = _load_tool()
    attrs, dead = mod.find_dead_metrics()
    assert "gangs_rejected" in attrs
    assert "gang_wait_duration" in attrs
    assert dead == []


def test_marker_lint_clean():
    p = subprocess.run([sys.executable, MARKER_TOOL], cwd=REPO,
                       capture_output=True, text=True, timeout=120)
    assert p.returncode == 0, p.stdout + p.stderr
    assert "ok:" in p.stdout


def test_marker_lint_detects_unmarked_perf_test(tmp_path):
    """Negative control: an unmarked >=1k-node test (and a TEST_CASES
    default-size call) are both flagged; the slow-marked twin is not."""
    mod = _load(MARKER_TOOL, "check_markers")
    bad = tmp_path / "test_scale.py"
    bad.write_text(
        "import pytest\n"
        "def test_big_cluster(run):\n"
        "    run(nodes=5000)\n"
        "def test_defaults():\n"
        "    tc = TEST_CASES['SchedulingBasic']()\n"
        "@pytest.mark.slow\n"
        "def test_big_marked(run):\n"
        "    run(nodes=5000)\n"
        "def test_small(run):\n"
        "    run(nodes=16)\n"
        "class TestScale:\n"
        "    def test_in_class(self, run):\n"
        "        run(nodes=2000)\n"
        "@pytest.mark.slow\n"
        "class TestMarkedScale:\n"
        "    def test_covered(self, run):\n"
        "        run(nodes=2000)\n"
    )
    out = mod.find_unmarked([str(bad)])
    names = {v.split()[-1] for v in out}
    assert names == {"test_big_cluster", "test_defaults", "test_in_class"}
