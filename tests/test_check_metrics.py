"""tools/check_metrics.py as a tier-1 gate: every metric registered in
SchedulerMetrics must be observed/set somewhere outside its definition, so
defined-but-dead metrics (the family this PR wired: extension-point/plugin
durations, queue_incoming_pods, pending_pods, ...) can't reappear."""

import importlib.util
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOL = os.path.join(REPO, "tools", "check_metrics.py")


def _load_tool():
    spec = importlib.util.spec_from_file_location("check_metrics", TOOL)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_no_dead_metrics():
    p = subprocess.run([sys.executable, TOOL], cwd=REPO,
                       capture_output=True, text=True, timeout=120)
    assert p.returncode == 0, p.stdout + p.stderr
    assert "ok:" in p.stdout


def test_finds_all_registered_metrics():
    mod = _load_tool()
    attrs, dead = mod.find_dead_metrics()
    # the full SchedulerMetrics roster is visible to the AST pass
    for expected in ("schedule_attempts", "framework_extension_point_duration",
                     "plugin_execution_duration", "pending_pods",
                     "queue_incoming_pods", "unschedulable_pods"):
        assert expected in attrs
    assert dead == []


def test_detects_a_dead_metric(tmp_path, monkeypatch):
    """Negative control: a registered-but-unobserved metric is reported."""
    mod = _load_tool()
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    metrics_file = pkg / "sm.py"
    metrics_file.write_text(
        "class SchedulerMetrics:\n"
        "    def __init__(self, r):\n"
        "        self.live_metric = r.register(Counter('a', 'h'))\n"
        "        self.helper_metric = r.register(Counter('b', 'h'))\n"
        "        self.dead_metric = r.register(Counter('c', 'h'))\n"
        "    def sync_helper(self):\n"
        "        self.helper_metric.set('x', value=1)\n"
    )
    (pkg / "user.py").write_text(
        "def f(m):\n"
        "    m.live_metric.inc('x')\n"
        "    m.sync_helper()\n"
    )
    monkeypatch.setattr(mod, "PKG", str(pkg))
    monkeypatch.setattr(mod, "METRICS_FILE", str(metrics_file))
    attrs, dead = mod.find_dead_metrics()
    assert set(attrs) == {"live_metric", "helper_metric", "dead_metric"}
    assert dead == ["dead_metric"]
