"""tools/check_metrics.py + tools/check_markers.py as tier-1 gates: every
metric registered in SchedulerMetrics must be observed/set somewhere outside
its definition (defined-but-dead metrics can't reappear), and every
perf-scale test (>= 1k nodes / TEST_CASES defaults) must carry the ``slow``
marker so tier-1's ``-m 'not slow'`` budget holds."""

import importlib.util
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOL = os.path.join(REPO, "tools", "check_metrics.py")
MARKER_TOOL = os.path.join(REPO, "tools", "check_markers.py")


def _load(path, name):
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _load_tool():
    return _load(TOOL, "check_metrics")


def test_no_dead_metrics():
    p = subprocess.run([sys.executable, TOOL], cwd=REPO,
                       capture_output=True, text=True, timeout=120)
    assert p.returncode == 0, p.stdout + p.stderr
    assert "ok:" in p.stdout


def test_finds_all_registered_metrics():
    mod = _load_tool()
    attrs, dead = mod.find_dead_metrics()
    # the full SchedulerMetrics roster is visible to the AST pass
    for expected in ("schedule_attempts", "framework_extension_point_duration",
                     "plugin_execution_duration", "pending_pods",
                     "queue_incoming_pods", "unschedulable_pods"):
        assert expected in attrs
    assert dead == []


def test_detects_a_dead_metric(tmp_path, monkeypatch):
    """Negative control: a registered-but-unobserved metric is reported."""
    mod = _load_tool()
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    metrics_file = pkg / "sm.py"
    metrics_file.write_text(
        "class SchedulerMetrics:\n"
        "    def __init__(self, r):\n"
        "        self.live_metric = r.register(Counter('a', 'h'))\n"
        "        self.helper_metric = r.register(Counter('b', 'h'))\n"
        "        self.dead_metric = r.register(Counter('c', 'h'))\n"
        "    def sync_helper(self):\n"
        "        self.helper_metric.set('x', value=1)\n"
    )
    (pkg / "user.py").write_text(
        "def f(m):\n"
        "    m.live_metric.inc('x')\n"
        "    m.sync_helper()\n"
    )
    monkeypatch.setattr(mod, "PKG", str(pkg))
    monkeypatch.setattr(mod, "METRICS_FILE", str(metrics_file))
    attrs, dead = mod.find_dead_metrics()
    assert set(attrs) == {"live_metric", "helper_metric", "dead_metric"}
    assert dead == ["dead_metric"]


def test_gang_metrics_registered_and_live():
    """The gang metrics are in the checked roster AND fed (the check's
    coverage extends to them: a future refactor that orphans either fails
    tier-1 like any other dead metric)."""
    mod = _load_tool()
    attrs, dead = mod.find_dead_metrics()
    assert "gangs_rejected" in attrs
    assert "gang_wait_duration" in attrs
    assert dead == []


def test_marker_lint_clean():
    p = subprocess.run([sys.executable, MARKER_TOOL], cwd=REPO,
                       capture_output=True, text=True, timeout=120)
    assert p.returncode == 0, p.stdout + p.stderr
    assert "ok:" in p.stdout


def test_marker_lint_detects_unmarked_perf_test(tmp_path):
    """Negative control: an unmarked >=1k-node test (and a TEST_CASES
    default-size call) are both flagged; the slow-marked twin is not."""
    mod = _load(MARKER_TOOL, "check_markers")
    bad = tmp_path / "test_scale.py"
    bad.write_text(
        "import pytest\n"
        "def test_big_cluster(run):\n"
        "    run(nodes=5000)\n"
        "def test_defaults():\n"
        "    tc = TEST_CASES['SchedulingBasic']()\n"
        "@pytest.mark.slow\n"
        "def test_big_marked(run):\n"
        "    run(nodes=5000)\n"
        "def test_small(run):\n"
        "    run(nodes=16)\n"
        "class TestScale:\n"
        "    def test_in_class(self, run):\n"
        "        run(nodes=2000)\n"
        "@pytest.mark.slow\n"
        "class TestMarkedScale:\n"
        "    def test_covered(self, run):\n"
        "        run(nodes=2000)\n"
    )
    out = mod.find_unmarked([str(bad)])
    names = {v.split()[-1] for v in out}
    assert names == {"test_big_cluster", "test_defaults", "test_in_class"}


def test_telemetry_metrics_registered_and_live():
    """The device-runtime metric families (ISSUE 7) are in the checked
    roster AND fed — orphaning any of them fails tier-1."""
    mod = _load_tool()
    attrs, dead = mod.find_dead_metrics()
    for expected in ("xla_compilations", "xla_compile_duration",
                     "xla_retraces", "hbm_bytes", "device_transfer_bytes",
                     "flight_events"):
        assert expected in attrs
    assert dead == []


def test_quota_and_fairness_metrics_registered_and_live():
    """The multi-tenant admission families (ISSUE 8): per-namespace quota
    usage/decisions/releases and the fair-share turn counter are in the
    checked roster AND fed — orphaning any of them fails tier-1."""
    mod = _load_tool()
    attrs, dead = mod.find_dead_metrics()
    for expected in ("quota_usage", "quota_decisions", "quota_released_pods",
                     "fair_share_turns"):
        assert expected in attrs
    assert dead == []


def test_marker_lint_requires_slow_on_large_soak(tmp_path):
    """The large SchedulingSoak variant must be slow-marked even with a
    small ``nodes`` override: soak cost scales with rounds x scale, so the
    lint flags default/reference-size soak knobs; the small tier-1 shape
    and the slow-marked large twin pass."""
    mod = _load(MARKER_TOOL, "check_markers")
    f = tmp_path / "test_soak_scale.py"
    f.write_text(
        "import pytest\n"
        "def test_soak_default_knobs():\n"
        "    TEST_CASES['SchedulingSoak'](nodes=32)\n"
        "def test_soak_big_scale():\n"
        "    TEST_CASES['SchedulingSoak'](nodes=32, scale=64, rounds=4)\n"
        "def test_soak_big_rounds():\n"
        "    TEST_CASES['SchedulingSoak'](nodes=32, scale=6, rounds=50)\n"
        "def test_soak_small():\n"
        "    TEST_CASES['SchedulingSoak'](nodes=32, scale=6, rounds=4)\n"
        "@pytest.mark.slow\n"
        "def test_soak_large_marked():\n"
        "    TEST_CASES['SchedulingSoak']()\n"
    )
    out = mod.find_unmarked([str(f)])
    names = {v.split()[-1] for v in out}
    assert names == {"test_soak_default_knobs", "test_soak_big_scale",
                     "test_soak_big_rounds"}


def test_span_lint_clean():
    """Every span name the package emits is in bench.py's critical-path
    attribution table or the explicit ignore list."""
    mod = _load_tool()
    emitted, unattributed = mod.find_unattributed_spans()
    assert unattributed == [], unattributed
    # the lint actually sees the core cycle spans
    for must in ("scheduling.cycle", "device.sync", "device.commit.wait",
                 "host.commit"):
        assert must in emitted


def test_span_lint_detects_unattributed_span(tmp_path):
    """Negative control: a span emitted in code but absent from the bench
    table (and not ignored) is flagged; table entries, ignored prefixes,
    and dynamic f-string spans with attributed prefixes are not."""
    mod = _load_tool()
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "mod.py").write_text(
        "from x import tracing\n"
        "def f(point):\n"
        "    with tracing.span('device.sync'):\n"
        "        pass\n"
        "    with tracing.span('mystery.phase'):\n"
        "        pass\n"
        "    with tracing.span('framework.' + point):\n"
        "        pass\n"
        "    with tracing.span_from_remote(None, 'device.apply_deltas'):\n"
        "        pass\n"
        "    with tracing.span_from_remote(None, 'rogue.remote'):\n"
        "        pass\n"
    )
    bench = tmp_path / "bench.py"
    bench.write_text(
        "CRITICAL_PATH_SPANS = frozenset({\n"
        "    'device.sync', 'device.apply_deltas',\n"
        "})\n"
    )
    emitted, unattributed = mod.find_unattributed_spans(
        pkg=str(pkg), bench_path=str(bench))
    assert unattributed == ["mystery.phase", "rogue.remote"]
    assert "device.sync" in emitted


def test_fence_zero_throughput_is_judged_not_skipped():
    """A collapse to 0.0 pods/s is the worst regression, not a missing
    metric — the fence must flag it."""
    spec = importlib.util.spec_from_file_location(
        "trend", os.path.join(REPO, "tools", "trend.py"))
    trend = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(trend)
    out = trend.fence({"value": 0.0, "platform": "cpu-fallback"},
                      [{"value": 500.0, "platform": "cpu-fallback",
                        "_round": 7}])
    assert any("headline pods/s" in v for v in out["violations"])


def test_bench_span_table_parses_without_importing_bench():
    mod = _load_tool()
    table = mod.bench_span_table()
    assert "scheduling.cycle" in table
    assert "device.commit.wait" in table
