"""Durable store stand-in (VERDICT r3 item 8): WAL + snapshot restore for
ClusterStore — the crash-only recovery story must survive a real process
restart, not just an informer relist against a store that never died."""

import os

from kubernetes_tpu.api.types import ObjectMeta, Secret
from kubernetes_tpu.api.wrappers import make_node, make_pod
from kubernetes_tpu.apiserver.store import ClusterStore
from kubernetes_tpu.apiserver.wal import attach_wal, restore


def _cluster(store, nodes=4):
    for i in range(nodes):
        store.create_node(
            make_node(f"n{i}").capacity({"cpu": "8", "memory": "16Gi", "pods": 20})
            .label("zone", f"z{i % 2}").obj())


class TestWAL:
    def test_roundtrip_objects_and_deletes(self, tmp_path):
        path = str(tmp_path / "store.wal")
        store = ClusterStore()
        attach_wal(store, path)
        _cluster(store)
        store.create_pod(make_pod("keep").req({"cpu": "1"}).obj())
        store.create_pod(make_pod("gone").req({"cpu": "1"}).obj())
        store.delete_pod("default/gone")
        store.create_object("Secret", Secret(meta=ObjectMeta(name="s1")))

        restored = restore(path)
        assert set(restored.nodes) == {"n0", "n1", "n2", "n3"}
        assert set(restored.pods) == {"default/keep"}
        assert "default/s1" in restored.secrets
        # resourceVersions monotonic across the restart: a new write must
        # not reuse a pre-crash rv (watch resume correctness)
        rv_before = restored._rv
        restored.create_pod(make_pod("after").req({"cpu": "1"}).obj())
        assert restored.get_pod("default/after").meta.resource_version > rv_before

    def test_snapshot_compaction(self, tmp_path):
        path = str(tmp_path / "store.wal")
        store = ClusterStore()
        wal = attach_wal(store, path)
        _cluster(store, nodes=2)
        for i in range(20):
            store.create_pod(make_pod(f"p{i}").req({"cpu": "100m"}).obj())
        wal.snapshot(store)
        assert os.path.getsize(path) == 0  # log truncated into the snapshot
        # post-snapshot writes land in the fresh log
        store.create_pod(make_pod("tail").req({"cpu": "100m"}).obj())
        restored = restore(path)
        assert len(restored.pods) == 21
        assert "default/tail" in restored.pods

    def test_crash_mid_workload_scheduling_resumes(self, tmp_path):
        """The chaos criterion: kill the store mid-workload, restore from
        WAL, informers relist, scheduling resumes, no lost bindings."""
        from kubernetes_tpu.backend import TPUScheduler

        path = str(tmp_path / "store.wal")
        store = ClusterStore()
        attach_wal(store, path)
        _cluster(store, nodes=6)
        sched = TPUScheduler(store, batch_size=16)
        for i in range(12):
            store.create_pod(make_pod(f"pre{i}").req({"cpu": "1", "memory": "1Gi"}).obj())
        sched.run_until_settled()
        bound_before = {k: p.spec.node_name for k, p in store.pods.items()
                        if p.spec.node_name}
        assert len(bound_before) == 12
        # a batch of pods lands in the store but is NOT yet scheduled when
        # the process dies
        for i in range(8):
            store.create_pod(make_pod(f"mid{i}").req({"cpu": "1", "memory": "1Gi"}).obj())
        del store, sched  # the crash: nothing from the old process survives

        restored = restore(path)
        # no lost bindings
        for key, node in bound_before.items():
            assert restored.get_pod(key).spec.node_name == node, key
        # a fresh scheduler (informers relist against the restored store)
        # picks up the unfinished work
        sched2 = TPUScheduler(restored, batch_size=16)
        sched2.run_until_settled()
        assert all(p.spec.node_name for p in restored.pods.values())
        # and keeps scheduling new arrivals
        restored.create_pod(make_pod("post").req({"cpu": "1", "memory": "1Gi"}).obj())
        sched2.run_until_settled()
        assert restored.get_pod("default/post").spec.node_name


class TestTornTail:
    """Per-record checksum/length guard: a crash mid-append leaves a torn
    or corrupt final record; replay stops cleanly at it instead of raising
    (etcd walpb CRC semantics — availability over the torn tail)."""

    def test_truncated_final_record(self, tmp_path):
        path = str(tmp_path / "store.wal")
        store = ClusterStore()
        attach_wal(store, path)
        _cluster(store, nodes=2)
        store.create_pod(make_pod("keep").req({"cpu": "1"}).obj())
        store.create_pod(make_pod("torn").req({"cpu": "1"}).obj())
        with open(path, "rb+") as f:  # the crash: half the last line is gone
            f.seek(-20, 2)
            f.truncate()
        restored = restore(path)
        assert set(restored.nodes) == {"n0", "n1"}
        assert set(restored.pods) == {"default/keep"}
        # the restored store appends safely (restore compacted the torn
        # garbage away) and survives ANOTHER restore round-trip
        restored.create_pod(make_pod("after").req({"cpu": "1"}).obj())
        again = restore(path)
        assert set(again.pods) == {"default/keep", "default/after"}

    def test_corrupt_final_record_checksum(self, tmp_path):
        path = str(tmp_path / "store.wal")
        store = ClusterStore()
        attach_wal(store, path)
        _cluster(store, nodes=1)
        store.create_pod(make_pod("good").req({"cpu": "1"}).obj())
        store.create_pod(make_pod("flipped").req({"cpu": "1"}).obj())
        with open(path, encoding="utf-8") as f:
            lines = f.readlines()
        # bit-flip inside the final record's body: length intact, crc not
        lines[-1] = lines[-1].replace("flipped", "flipqed")
        with open(path, "w", encoding="utf-8") as f:
            f.writelines(lines)
        restored = restore(path)
        assert set(restored.pods) == {"default/good"}

    def test_replay_yields_clean_prefix_only(self, tmp_path):
        from kubernetes_tpu.apiserver.wal import replay

        path = str(tmp_path / "store.wal")
        store = ClusterStore()
        attach_wal(store, path)
        _cluster(store, nodes=1)
        store.create_pod(make_pod("p").req({"cpu": "1"}).obj())
        recs = list(replay(path))
        assert [r["event"] for r in recs] == ["ADDED", "ADDED"]
        with open(path, "a", encoding="utf-8") as f:
            f.write('deadbeef {"not": "valid for that crc"}\n')
        assert len(list(replay(path))) == 2  # guard trips, no raise

    def test_torn_batch_record_tail_drops_whole_batch(self, tmp_path):
        """Group-commit torn tail: the crc frames the WHOLE batch line, so
        a crash mid-write drops the batch atomically — none of its binds
        replay, everything before the line is the durable prefix."""
        from kubernetes_tpu.api.types import Binding

        path = str(tmp_path / "store.wal")
        store = ClusterStore()
        attach_wal(store, path)
        _cluster(store, nodes=2)
        for name in ("a", "b", "c"):
            store.create_pod(make_pod(name).req({"cpu": "1"}).obj())
        outcomes = store.bind_batch([
            Binding(pod_key=f"default/{n}", node_name="n0")
            for n in ("a", "b", "c")])
        assert outcomes == [None, None, None]
        with open(path, "rb+") as f:  # the crash: the batch line is torn
            f.seek(-10, 2)
            f.truncate()
        restored = restore(path)
        # every pre-batch record intact; NO bind from the torn batch
        assert set(restored.pods) == {"default/a", "default/b", "default/c"}
        assert all(not p.spec.node_name for p in restored.pods.values())

    def test_corrupt_batch_record_checksum_drops_whole_batch(self, tmp_path):
        from kubernetes_tpu.api.types import Binding

        path = str(tmp_path / "store.wal")
        store = ClusterStore()
        attach_wal(store, path)
        _cluster(store, nodes=1)
        for name in ("x", "y"):
            store.create_pod(make_pod(name).req({"cpu": "1"}).obj())
        store.bind_batch([Binding(pod_key=f"default/{n}", node_name="n0")
                          for n in ("x", "y")])
        with open(path, encoding="utf-8") as f:
            lines = f.readlines()
        lines[-1] = lines[-1].replace("Running", "Runnjng")
        with open(path, "w", encoding="utf-8") as f:
            f.writelines(lines)
        restored = restore(path)
        assert all(not p.spec.node_name for p in restored.pods.values())


class TestGroupCommit:
    """The commit data plane's WAL half: one crc-framed line per batch,
    per-record replay semantics, and byte-parity with the per-pod log."""

    def test_one_line_per_batch_and_per_record_replay(self, tmp_path):
        from kubernetes_tpu.api.types import Binding
        from kubernetes_tpu.apiserver.wal import replay

        path = str(tmp_path / "store.wal")
        store = ClusterStore()
        wal = attach_wal(store, path)
        _cluster(store, nodes=2)
        for i in range(5):
            store.create_pod(make_pod(f"p{i}").req({"cpu": "100m"}).obj())
        lines_before = wal.lines_written
        recs_before = wal.records_appended
        outcomes = store.bind_batch([
            Binding(pod_key=f"default/p{i}", node_name=f"n{i % 2}")
            for i in range(5)])
        assert outcomes == [None] * 5
        assert wal.lines_written == lines_before + 1  # ONE group append
        assert wal.records_appended == recs_before + 5
        # replay unpacks the envelope: five MODIFIED records in order
        tail = list(replay(path))[-5:]
        assert [r["event"] for r in tail] == ["MODIFIED"] * 5
        assert [r["key"] for r in tail] == [f"default/p{i}" for i in range(5)]
        restored = restore(path)
        assert {k: p.spec.node_name for k, p in restored.pods.items()} == {
            f"default/p{i}": f"n{i % 2}" for i in range(5)}

    def test_mixed_legacy_and_batch_replay_byte_identical(self, tmp_path):
        """A log mixing per-pod appends and group-commit batches restores a
        store byte-identical (wire form) to one written per-pod only."""
        from kubernetes_tpu.api.codec import to_wire
        from kubernetes_tpu.api.types import Binding

        def build(batched: bool, path: str) -> ClusterStore:
            store = ClusterStore()
            attach_wal(store, path)
            _cluster(store, nodes=2)
            for i in range(6):
                store.create_pod(
                    make_pod(f"p{i}").req({"cpu": "100m"}).obj())
            # first two bind per-pod (legacy records) in BOTH stores
            store.bind(Binding(pod_key="default/p0", node_name="n0"))
            store.bind(Binding(pod_key="default/p1", node_name="n1"))
            rest = [Binding(pod_key=f"default/p{i}", node_name=f"n{i % 2}")
                    for i in range(2, 6)]
            if batched:
                assert store.bind_batch(rest) == [None] * 4
            else:
                for b in rest:
                    store.bind(b)
            return store

        path_a = str(tmp_path / "legacy.wal")
        path_b = str(tmp_path / "batched.wal")
        build(False, path_a)
        build(True, path_b)
        ra, rb = restore(path_a), restore(path_b)

        def dump(store):
            out = {}
            for k, p in store.pods.items():
                wire = to_wire(p)
                # the only legitimate difference between the two builds is
                # the wall clock each create ran at
                wire["meta"]["creation_timestamp"] = 0
                out[k] = wire
            return out

        assert dump(ra) == dump(rb)
        assert ra._rv == rb._rv and ra._event_seq == rb._event_seq

    def test_single_record_batch_degenerates_to_legacy_form(self, tmp_path):
        from kubernetes_tpu.api.types import Binding

        path = str(tmp_path / "store.wal")
        store = ClusterStore()
        attach_wal(store, path)
        _cluster(store, nodes=1)
        store.create_pod(make_pod("solo").req({"cpu": "1"}).obj())
        store.bind_batch([Binding(pod_key="default/solo", node_name="n0")])
        with open(path, encoding="utf-8") as f:
            last = f.readlines()[-1]
        assert '"batch"' not in last  # legacy per-record form on the wire

    def test_per_pod_failures_do_not_block_batch_siblings(self, tmp_path):
        from kubernetes_tpu.api.types import Binding
        from kubernetes_tpu.apiserver.store import Conflict, NotFound

        path = str(tmp_path / "store.wal")
        store = ClusterStore()
        attach_wal(store, path)
        _cluster(store, nodes=1)
        store.create_pod(make_pod("ok").req({"cpu": "1"}).obj())
        store.create_pod(make_pod("dup").req({"cpu": "1"}).obj())
        store.bind(Binding(pod_key="default/dup", node_name="n0"))
        outcomes = store.bind_batch([
            Binding(pod_key="default/ghost", node_name="n0"),
            Binding(pod_key="default/dup", node_name="n0"),
            Binding(pod_key="default/ok", node_name="n0"),
        ])
        assert isinstance(outcomes[0], NotFound)
        assert isinstance(outcomes[1], Conflict)
        assert outcomes[2] is None
        restored = restore(path)
        assert restored.get_pod("default/ok").spec.node_name == "n0"


class TestAutoCompaction:
    """Periodic WAL auto-compaction (ISSUE 18 satellite): housekeeping
    snapshots-and-truncates once the log grows KTPU_WAL_COMPACT_LINES past
    the last compaction — default off, crash-safe at every point."""

    def test_default_off(self, tmp_path, monkeypatch):
        monkeypatch.delenv("KTPU_WAL_COMPACT_LINES", raising=False)
        store = ClusterStore()
        wal = attach_wal(store, str(tmp_path / "store.wal"))
        _cluster(store)
        for i in range(50):
            store.create_pod(make_pod(f"p{i}").req({"cpu": "100m"}).obj())
        assert wal.compact_lines == 0
        assert wal.maybe_compact(store) is False
        assert not os.path.exists(str(tmp_path / "store.wal") + ".snap")

    def test_threshold_triggers_compaction_and_restore(self, tmp_path,
                                                       monkeypatch):
        monkeypatch.setenv("KTPU_WAL_COMPACT_LINES", "10")
        path = str(tmp_path / "store.wal")
        store = ClusterStore()
        wal = attach_wal(store, path)
        _cluster(store, nodes=2)  # 2 lines: under the threshold
        assert wal.maybe_compact(store) is False
        for i in range(10):
            store.create_pod(make_pod(f"p{i}").req({"cpu": "100m"}).obj())
        assert wal.maybe_compact(store) is True
        assert os.path.getsize(path) == 0  # log truncated into the snapshot
        assert os.path.exists(path + ".snap")
        # the counter re-bases: no compaction until ANOTHER N lines land
        assert wal.maybe_compact(store) is False
        store.create_pod(make_pod("tail").req({"cpu": "100m"}).obj())
        assert wal.maybe_compact(store) is False  # 1 < 10 since compaction
        # crash here: snapshot + tail replay equals the pre-crash store
        restored = restore(path)
        assert set(restored.pods) == set(store.pods)
        assert set(restored.nodes) == set(store.nodes)

    def test_housekeeping_drives_compaction(self, tmp_path, monkeypatch):
        """The wiring: the scheduler's 1s housekeeping block calls
        ``maybe_compact`` on the store's attached WAL — a live workload
        crosses the threshold and compacts with zero lost writes."""
        from kubernetes_tpu.scheduler.scheduler import Scheduler

        monkeypatch.setenv("KTPU_WAL_COMPACT_LINES", "16")
        path = str(tmp_path / "store.wal")
        store = ClusterStore()
        wal = attach_wal(store, path)
        _cluster(store)
        sched = Scheduler(store)
        for i in range(24):
            store.create_pod(make_pod(f"w{i}").req({"cpu": "100m"}).obj())
        for _ in range(40):
            if not sched.schedule_one():
                break
        lines_before = wal.lines_written
        assert lines_before - wal._lines_at_compact >= 16
        # past the 1s sweep gate (scheduling already ticked it this second)
        sched._periodic_housekeeping(sched.now_fn() + 1.5)
        assert wal._lines_at_compact == wal.lines_written
        assert os.path.exists(path + ".snap")
        # restart recovery: restore sees every node, pod, and binding
        restored = restore(path)
        assert set(restored.nodes) == set(store.nodes)
        assert set(restored.pods) == set(store.pods)
        bound = {k: p.spec.node_name for k, p in store.pods.items()
                 if p.spec.node_name}
        assert bound  # the workload actually scheduled
        for k, node in bound.items():
            assert restored.pods[k].spec.node_name == node
