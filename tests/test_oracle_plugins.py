"""Table-driven unit tests for the scalar oracle plugins.

Cases are transcribed behaviors from the reference's plugin unit tests
(fit_test.go, taint_toleration_test.go, node_affinity_test.go, ...) — same
semantics, newly written.
"""

import pytest

from kubernetes_tpu.api.wrappers import make_node, make_pod
from kubernetes_tpu.api.types import LabelSelector, Requirement
from kubernetes_tpu.framework.interface import (
    CycleState,
    NodeScore,
    SUCCESS,
    UNSCHEDULABLE,
    UNSCHEDULABLE_AND_UNRESOLVABLE,
)
from kubernetes_tpu.framework.types import NodeInfo
from kubernetes_tpu.framework.plugins.basic import (
    NodeName,
    NodePorts,
    NodeUnschedulable,
    PrioritySort,
    TaintToleration,
)
from kubernetes_tpu.framework.plugins.nodeaffinity import NodeAffinity
from kubernetes_tpu.framework.plugins.noderesources import BalancedAllocation, Fit
from kubernetes_tpu.framework.plugins.interpodaffinity import InterPodAffinity
from kubernetes_tpu.framework.plugins.podtopologyspread import PodTopologySpread
from kubernetes_tpu.framework.types import QueuedPodInfo


def ni(node, *pods):
    info = NodeInfo(node)
    for p in pods:
        info.add_pod(p)
    return info


def run_filter(plugin, pod, node_info):
    state = CycleState()
    if hasattr(plugin, "pre_filter"):
        plugin.pre_filter(state, pod)
    return plugin.filter(state, pod, node_info)


# ---------------------------------------------------------------- NodeResourcesFit


class TestFit:
    def mknode(self, cpu="4", mem="8Gi", pods=10):
        return make_node("n1").capacity({"cpu": cpu, "memory": mem, "pods": pods}).obj()

    def test_fits_empty_node(self):
        pod = make_pod().req({"cpu": "1", "memory": "1Gi"}).obj()
        assert run_filter(Fit(), pod, ni(self.mknode())).code == SUCCESS

    def test_insufficient_cpu(self):
        existing = make_pod("e").req({"cpu": "3500m"}).obj()
        pod = make_pod().req({"cpu": "600m"}).obj()
        st = run_filter(Fit(), pod, ni(self.mknode(), existing))
        assert st.code == UNSCHEDULABLE
        assert "Insufficient cpu" in st.reasons

    def test_exact_fit_boundary(self):
        existing = make_pod("e").req({"cpu": "3500m"}).obj()
        pod = make_pod().req({"cpu": "500m"}).obj()
        assert run_filter(Fit(), pod, ni(self.mknode(), existing)).code == SUCCESS

    def test_too_many_pods(self):
        node = self.mknode(pods=1)
        existing = make_pod("e").obj()
        pod = make_pod().obj()
        st = run_filter(Fit(), pod, ni(node, existing))
        assert st.code == UNSCHEDULABLE
        assert "Too many pods" in st.reasons

    def test_zero_request_always_fits_resources(self):
        node = self.mknode(cpu="1")
        existing = make_pod("e").req({"cpu": "1"}).obj()
        pod = make_pod().obj()  # no requests
        assert run_filter(Fit(), pod, ni(node, existing)).code == SUCCESS

    def test_init_container_max(self):
        # request = max(sum(containers), max(init)) per resource
        pod = make_pod().req({"cpu": "1"}).init_req({"cpu": "3"}).obj()
        assert pod.resource_request()["cpu"] == 3000
        node = self.mknode(cpu="2")
        st = run_filter(Fit(), pod, ni(node))
        assert st.code == UNSCHEDULABLE

    def test_extended_resource(self):
        node = make_node("n").capacity({"cpu": "4", "memory": "8Gi", "pods": 10, "example.com/foo": 2}).obj()
        ok = make_pod().req({"example.com/foo": 2}).obj()
        bad = make_pod().req({"example.com/foo": 3}).obj()
        assert run_filter(Fit(), ok, ni(node)).code == SUCCESS
        st = run_filter(Fit(), bad, ni(node))
        assert "Insufficient example.com/foo" in st.reasons

    def test_least_allocated_score(self):
        # least_allocated.go: ((cap-req)*100/cap per resource, averaged
        node = self.mknode(cpu="4", mem="4Gi")
        pod = make_pod().req({"cpu": "1", "memory": "1Gi"}).obj()
        state = CycleState()
        score, st = Fit().score_node(state, pod, ni(node))
        assert st.code == SUCCESS
        assert score == 75  # (75 + 75) / 2

    def test_most_allocated_score(self):
        node = self.mknode(cpu="4", mem="4Gi")
        pod = make_pod().req({"cpu": "1", "memory": "1Gi"}).obj()
        score, _ = Fit(strategy="MostAllocated").score_node(CycleState(), pod, ni(node))
        assert score == 25

    def test_balanced_allocation_score(self):
        node = self.mknode(cpu="4", mem="4Gi")
        pod = make_pod().req({"cpu": "1", "memory": "2Gi"}).obj()
        score, _ = BalancedAllocation().score_node(CycleState(), pod, ni(node))
        # fractions 0.25, 0.5 -> std=(0.125) -> score 87
        assert score == 87


# ---------------------------------------------------------------- basic plugins


class TestBasic:
    def test_node_name(self):
        pod = make_pod().node("other").obj()
        st = run_filter(NodeName(), pod, ni(make_node("n1").obj()))
        assert st.code == UNSCHEDULABLE_AND_UNRESOLVABLE
        pod2 = make_pod().node("n1").obj()
        assert run_filter(NodeName(), pod2, ni(make_node("n1").obj())).code == SUCCESS

    def test_node_unschedulable(self):
        node = make_node("n1").unschedulable().obj()
        assert run_filter(NodeUnschedulable(), make_pod().obj(), ni(node)).code == UNSCHEDULABLE_AND_UNRESOLVABLE
        tolerant = make_pod().toleration(key="node.kubernetes.io/unschedulable", operator="Exists", effect="NoSchedule").obj()
        assert run_filter(NodeUnschedulable(), tolerant, ni(node)).code == SUCCESS

    def test_taint_filter(self):
        node = make_node("n1").taint("k1", "v1", "NoSchedule").obj()
        st = run_filter(TaintToleration(), make_pod().obj(), ni(node))
        assert st.code == UNSCHEDULABLE_AND_UNRESOLVABLE
        assert st.reasons == ("node(s) had untolerated taint {k1: v1}",)
        ok = make_pod().toleration(key="k1", operator="Equal", value="v1", effect="NoSchedule").obj()
        assert run_filter(TaintToleration(), ok, ni(node)).code == SUCCESS

    def test_prefer_no_schedule_ignored_by_filter(self):
        node = make_node("n1").taint("k1", "v1", "PreferNoSchedule").obj()
        assert run_filter(TaintToleration(), make_pod().obj(), ni(node)).code == SUCCESS

    def test_taint_score_normalized_reversed(self):
        tt = TaintToleration()
        pod = make_pod().obj()
        state = CycleState()
        tt.pre_score(state, pod, [])
        n_clean = ni(make_node("clean").obj())
        n_tainted = ni(make_node("tainted").taint("k", "v", "PreferNoSchedule").obj())
        s_clean, _ = tt.score_node(state, pod, n_clean)
        s_tainted, _ = tt.score_node(state, pod, n_tainted)
        scores = [NodeScore("clean", s_clean), NodeScore("tainted", s_tainted)]
        tt.normalize_score(state, pod, scores)
        assert scores[0].score == 100 and scores[1].score == 0

    def test_node_ports_conflict(self):
        existing = make_pod("e").host_port(8080).obj()
        node_info = ni(make_node("n1").capacity({"pods": 10}).obj(), existing)
        st = run_filter(NodePorts(), make_pod().host_port(8080).obj(), node_info)
        assert st.code == UNSCHEDULABLE
        assert run_filter(NodePorts(), make_pod().host_port(8081).obj(), node_info).code == SUCCESS
        # different protocol is no conflict
        assert run_filter(NodePorts(), make_pod().host_port(8080, protocol="UDP").obj(), node_info).code == SUCCESS

    def test_priority_sort(self):
        ps = PrioritySort()
        hi = QueuedPodInfo(pod=make_pod("hi").priority(10).obj(), timestamp=2.0)
        lo = QueuedPodInfo(pod=make_pod("lo").priority(1).obj(), timestamp=1.0)
        assert ps.less(hi, lo) and not ps.less(lo, hi)
        first = QueuedPodInfo(pod=make_pod("first").priority(1).obj(), timestamp=0.5)
        assert ps.less(first, lo)


# ---------------------------------------------------------------- NodeAffinity


class TestNodeAffinity:
    def test_node_selector_map(self):
        pod = make_pod().node_selector({"zone": "us-1"}).obj()
        hit = ni(make_node("a").label("zone", "us-1").obj())
        miss = ni(make_node("b").label("zone", "us-2").obj())
        assert run_filter(NodeAffinity(), pod, hit).code == SUCCESS
        st = run_filter(NodeAffinity(), pod, miss)
        assert st.code == UNSCHEDULABLE_AND_UNRESOLVABLE

    def test_required_terms_or(self):
        pod = (
            make_pod()
            .node_affinity_in("zone", ["a"])
            .obj()
        )
        # add a second OR term via wrapper
        pod2 = make_pod().node_affinity_in("zone", ["a", "b"]).obj()
        assert run_filter(NodeAffinity(), pod2, ni(make_node("n").label("zone", "b").obj())).code == SUCCESS
        assert run_filter(NodeAffinity(), pod, ni(make_node("n").label("zone", "b").obj())).code == UNSCHEDULABLE_AND_UNRESOLVABLE

    def test_not_in_missing_key_matches(self):
        pod = make_pod().node_affinity_not_in("zone", ["bad"]).obj()
        assert run_filter(NodeAffinity(), pod, ni(make_node("n").obj())).code == SUCCESS

    def test_preferred_scoring(self):
        na = NodeAffinity()
        pod = make_pod().preferred_node_affinity(5, "zone", ["a"]).preferred_node_affinity(3, "disk", ["ssd"]).obj()
        state = CycleState()
        na.pre_score(state, pod, [])
        both = ni(make_node("both").label("zone", "a").label("disk", "ssd").obj())
        one = ni(make_node("one").label("zone", "a").obj())
        none = ni(make_node("none").obj())
        s_both, _ = na.score_node(state, pod, both)
        s_one, _ = na.score_node(state, pod, one)
        s_none, _ = na.score_node(state, pod, none)
        assert (s_both, s_one, s_none) == (8, 5, 0)
        scores = [NodeScore("both", s_both), NodeScore("one", s_one), NodeScore("none", s_none)]
        na.normalize_score(state, pod, scores)
        assert [s.score for s in scores] == [100, 62, 0]


# ---------------------------------------------------------------- PodTopologySpread


class TestPodTopologySpread:
    def make_cluster(self):
        nodes = [
            make_node(f"n{i}").label("zone", f"z{i % 2}").obj() for i in range(4)
        ]
        infos = {n.meta.name: NodeInfo(n) for n in nodes}
        return nodes, infos

    def test_filter_max_skew(self):
        nodes, infos = self.make_cluster()
        sel = LabelSelector(match_labels={"app": "x"})
        # 2 matching pods in z0, 0 in z1
        infos["n0"].add_pod(make_pod("p1").label("app", "x").obj())
        infos["n2"].add_pod(make_pod("p2").label("app", "x").obj())
        plugin = PodTopologySpread(snapshot_fn=lambda: list(infos.values()))
        pod = make_pod("new").label("app", "x").spread_constraint(1, "zone", selector=sel).obj()
        state = CycleState()
        plugin.pre_filter(state, pod)
        # z0 has 2, z1 has 0 -> min=0; placing in z0: 2+1-0=3 > 1 -> reject
        assert plugin.filter(state, pod, infos["n0"]).code == UNSCHEDULABLE
        # z1: 0+1-0 = 1 <= 1 -> ok
        assert plugin.filter(state, pod, infos["n1"]).code == SUCCESS

    def test_filter_missing_label_unresolvable(self):
        nodes, infos = self.make_cluster()
        bare = NodeInfo(make_node("bare").obj())
        infos["bare"] = bare
        sel = LabelSelector(match_labels={"app": "x"})
        plugin = PodTopologySpread(snapshot_fn=lambda: list(infos.values()))
        pod = make_pod("new").label("app", "x").spread_constraint(1, "zone", selector=sel).obj()
        state = CycleState()
        plugin.pre_filter(state, pod)
        assert plugin.filter(state, pod, bare).code == UNSCHEDULABLE_AND_UNRESOLVABLE

    def test_score_prefers_less_loaded_domain(self):
        nodes, infos = self.make_cluster()
        sel = LabelSelector(match_labels={"app": "x"})
        infos["n0"].add_pod(make_pod("p1").label("app", "x").obj())
        infos["n0"].add_pod(make_pod("p2").label("app", "x").obj())
        plugin = PodTopologySpread(snapshot_fn=lambda: list(infos.values()))
        pod = make_pod("new").label("app", "x").spread_constraint(1, "zone", "ScheduleAnyway", selector=sel).obj()
        state = CycleState()
        plugin.pre_score(state, pod, nodes)
        raw = {}
        for name, info in infos.items():
            raw[name], _ = plugin.score_node(state, pod, info)
        scores = [NodeScore(n, raw[n]) for n in raw]
        plugin.normalize_score(state, pod, scores)
        by_name = {s.name: s.score for s in scores}
        # z1 nodes (n1, n3) strictly preferred over z0 nodes
        assert by_name["n1"] > by_name["n0"]
        assert by_name["n1"] == by_name["n3"] == 100


# ---------------------------------------------------------------- InterPodAffinity


class TestInterPodAffinity:
    def setup_cluster(self):
        n0 = make_node("n0").label("zone", "z0").obj()
        n1 = make_node("n1").label("zone", "z1").obj()
        infos = {"n0": NodeInfo(n0), "n1": NodeInfo(n1)}
        return infos

    def test_required_affinity(self):
        infos = self.setup_cluster()
        infos["n0"].add_pod(make_pod("svc").label("app", "db").obj())
        plugin = InterPodAffinity(snapshot_fn=lambda: list(infos.values()))
        pod = make_pod("new").pod_affinity("zone", LabelSelector(match_labels={"app": "db"})).obj()
        state = CycleState()
        plugin.pre_filter(state, pod)
        assert plugin.filter(state, pod, infos["n0"]).code == SUCCESS
        # unsatisfied required affinity is Unresolvable (filtering.go:379):
        # evicting pods cannot make it schedulable
        assert plugin.filter(state, pod, infos["n1"]).code == UNSCHEDULABLE_AND_UNRESOLVABLE

    def test_first_pod_self_match(self):
        infos = self.setup_cluster()
        plugin = InterPodAffinity(snapshot_fn=lambda: list(infos.values()))
        pod = make_pod("new").label("app", "db").pod_affinity("zone", LabelSelector(match_labels={"app": "db"})).obj()
        state = CycleState()
        plugin.pre_filter(state, pod)
        assert plugin.filter(state, pod, infos["n0"]).code == SUCCESS

    def test_anti_affinity(self):
        infos = self.setup_cluster()
        infos["n0"].add_pod(make_pod("svc").label("app", "db").obj())
        plugin = InterPodAffinity(snapshot_fn=lambda: list(infos.values()))
        pod = make_pod("new").pod_affinity("zone", LabelSelector(match_labels={"app": "db"}), anti=True).obj()
        state = CycleState()
        plugin.pre_filter(state, pod)
        assert plugin.filter(state, pod, infos["n0"]).code == UNSCHEDULABLE
        assert plugin.filter(state, pod, infos["n1"]).code == SUCCESS

    def test_existing_pods_anti_affinity(self):
        infos = self.setup_cluster()
        guard = make_pod("guard").label("app", "guard").pod_affinity(
            "zone", LabelSelector(match_labels={"app": "web"}), anti=True
        ).obj()
        infos["n0"].add_pod(guard)
        plugin = InterPodAffinity(snapshot_fn=lambda: list(infos.values()))
        pod = make_pod("new").label("app", "web").obj()
        state = CycleState()
        plugin.pre_filter(state, pod)
        assert plugin.filter(state, pod, infos["n0"]).code == UNSCHEDULABLE
        assert plugin.filter(state, pod, infos["n1"]).code == SUCCESS

    def test_preferred_scoring(self):
        infos = self.setup_cluster()
        infos["n0"].add_pod(make_pod("svc").label("app", "db").obj())
        plugin = InterPodAffinity(snapshot_fn=lambda: list(infos.values()))
        pod = make_pod("new").preferred_pod_affinity(10, "zone", LabelSelector(match_labels={"app": "db"})).obj()
        state = CycleState()
        plugin.pre_score(state, pod, [])
        s0, _ = plugin.score_node(state, pod, infos["n0"])
        s1, _ = plugin.score_node(state, pod, infos["n1"])
        assert s0 == 10 and s1 == 0
        scores = [NodeScore("n0", s0), NodeScore("n1", s1)]
        plugin.normalize_score(state, pod, scores)
        assert scores[0].score == 100 and scores[1].score == 0
