"""Kubelet node internals (VERDICT r3 item 7): PLEG event stream over the
CRI journal, and the eviction manager's pressure-signal loop — evict lowest
value first, report node conditions, scheduler reroutes replacements."""

import pytest

from kubernetes_tpu.api.types import ObjectMeta, Pod, PodSpec, Container
from kubernetes_tpu.api.wrappers import make_node, make_pod
from kubernetes_tpu.apiserver.store import ClusterStore
from kubernetes_tpu.kubelet.cri import FakeRuntimeService
from kubernetes_tpu.kubelet.eviction import (
    SIGNAL_MEMORY_AVAILABLE,
    EvictionManager,
    PodStats,
)
from kubernetes_tpu.kubelet.hollow import HollowKubelet
from kubernetes_tpu.kubelet.pleg import (
    CONTAINER_DIED,
    CONTAINER_REMOVED,
    CONTAINER_STARTED,
    GenericPLEG,
)


def _node(name, mem="8Gi"):
    return make_node(name).capacity({"cpu": "8", "memory": mem, "pods": 20}).obj()


class TestPLEG:
    def test_event_stream_started_died_removed(self):
        rt = FakeRuntimeService()
        pleg = GenericPLEG(rt)
        assert pleg.relist() == []  # empty runtime: no events

        sid = rt.run_pod_sandbox({"name": "web", "namespace": "default", "uid": "u1"})
        cid = rt.create_container(sid, {"name": "c", "image": "pause"})
        rt.start_container(cid)
        events = pleg.relist()
        assert [e.type for e in events] == [CONTAINER_STARTED]
        assert events[0].pod_key == "default/web"
        assert events[0].data == cid

        rt.stop_container(cid)
        events = pleg.relist()
        assert [e.type for e in events] == [CONTAINER_DIED]

        rt.remove_container(cid)
        events = pleg.relist()
        assert [e.type for e in events] == [CONTAINER_REMOVED]

        # steady state: no spurious events
        assert pleg.relist() == []

    def test_healthy_tracks_relist_age(self):
        clock = [0.0]
        pleg = GenericPLEG(FakeRuntimeService(), now_fn=lambda: clock[0])
        pleg.relist()
        assert pleg.healthy()
        clock[0] += 1000.0  # beyond the 3-minute relist threshold
        assert not pleg.healthy()

    def test_kubelet_restarts_crashed_container(self):
        """kubelet.go:2061 plegCh arm: a container that dies underneath the
        kubelet (crash) is restarted per restartPolicy Always."""
        store = ClusterStore()
        rt = FakeRuntimeService()
        kubelet = HollowKubelet(store, _node("n1"), runtime=rt)
        kubelet.register()
        pod = make_pod("web").req({"cpu": "1"}).obj()
        pod.spec.node_name = "n1"
        store.create_pod(pod)
        kubelet.run_once()  # pod Running, container up
        kubelet.run_once()  # PLEG observes the started container
        [c] = [c for c in rt.containers.values()
               if c["state"] == "CONTAINER_RUNNING"]
        rt.stop_container(c["id"])  # crash, not kubelet-initiated
        kubelet.run_once()
        assert kubelet.pleg_restarts == 1
        running = [c for c in rt.containers.values()
                   if c["state"] == "CONTAINER_RUNNING"]
        assert len(running) == 1  # replacement container is up
        assert running[0]["id"] != c["id"]


class TestEvictionManager:
    def _pressured_setup(self):
        store = ClusterStore()
        store.create_node(_node("n1"))
        signals = {SIGNAL_MEMORY_AVAILABLE: 1 << 30}  # 1Gi free: healthy
        usage = {}

        mgr = EvictionManager(
            store, "n1",
            stats_fn=lambda: dict(signals),
            pod_stats_fn=lambda key: usage.get(key, PodStats()),
            pressure_transition_period=30.0,
            now_fn=lambda: clock[0])
        clock = [0.0]
        return store, signals, usage, mgr, clock

    def test_no_pressure_no_eviction(self):
        store, signals, usage, mgr, clock = self._pressured_setup()
        p = make_pod("a").req({"cpu": "1"}).obj()
        p.spec.node_name = "n1"
        store.create_pod(p)
        assert mgr.synchronize() is None
        assert not store.nodes["n1"].status.memory_pressure

    def test_evicts_lowest_priority_first_and_sets_condition(self):
        store, signals, usage, mgr, clock = self._pressured_setup()
        for name, prio, mem in (("low", 0, 100 << 20),
                                ("high", 100, 200 << 20)):
            p = make_pod(name).req({"cpu": "1", "memory": "64Mi"}).priority(prio).obj()
            p.spec.node_name = "n1"
            p.status.phase = "Running"
            store.create_pod(p)
            usage[f"default/{name}"] = PodStats(memory_bytes=mem)
        signals[SIGNAL_MEMORY_AVAILABLE] = 50 << 20  # below the 100Mi threshold
        evicted = mgr.synchronize()
        # both exceed request; lower priority goes first despite lower usage
        assert evicted == "default/low"
        pod = store.get_pod("default/low")
        assert pod.status.phase == "Failed"
        assert pod.status.reason == "Evicted"
        assert store.nodes["n1"].status.memory_pressure
        # one eviction per pass (the next observation must see the relief)
        assert store.get_pod("default/high").status.phase == "Running"

    def test_exceeds_request_outranks_priority(self):
        store, signals, usage, mgr, clock = self._pressured_setup()
        # high-priority pod EXCEEDS its request; low-priority pod within
        for name, prio, req, mem in (("greedy", 100, "64Mi", 500 << 20),
                                     ("frugal", 0, "1Gi", 10 << 20)):
            p = make_pod(name).req({"cpu": "1", "memory": req}).priority(prio).obj()
            p.spec.node_name = "n1"
            p.status.phase = "Running"
            store.create_pod(p)
            usage[f"default/{name}"] = PodStats(memory_bytes=mem)
        signals[SIGNAL_MEMORY_AVAILABLE] = 50 << 20
        assert mgr.synchronize() == "default/greedy"

    def test_condition_clears_after_transition_period(self):
        store, signals, usage, mgr, clock = self._pressured_setup()
        signals[SIGNAL_MEMORY_AVAILABLE] = 50 << 20
        mgr.synchronize()
        assert store.nodes["n1"].status.memory_pressure
        signals[SIGNAL_MEMORY_AVAILABLE] = 4 << 30  # pressure relieved
        mgr.synchronize()
        # anti-flap: condition holds through the transition period
        assert store.nodes["n1"].status.memory_pressure
        clock[0] += 31.0
        mgr.synchronize()
        assert not store.nodes["n1"].status.memory_pressure


class TestEvictionEndToEnd:
    def test_pressured_node_evicts_and_scheduler_reroutes(self):
        """VERDICT r3 item 7 'done' criterion: a pressured node evicts its
        lowest-priority pod, the nodelifecycle controller mirrors the
        pressure condition as a NoSchedule taint, the ReplicaSet controller
        replaces the Failed pod, and the scheduler lands the replacement on
        the healthy node."""
        from kubernetes_tpu.api.types import ReplicaSet, LabelSelector
        from kubernetes_tpu.client.informer import SharedInformerFactory
        from kubernetes_tpu.controllers.manager import ControllerManager
        from kubernetes_tpu.controllers.nodelifecycle import TAINT_MEMORY_PRESSURE
        from kubernetes_tpu.scheduler import Scheduler

        store = ClusterStore()
        store.create_node(_node("pressured"))
        store.create_node(_node("healthy"))
        sched = Scheduler(store)
        mgr_ctl = ControllerManager(
            store, factory=SharedInformerFactory(store),
            controllers=["replicaset", "nodelifecycle"])

        template = Pod(
            meta=ObjectMeta(name="web", labels={"app": "web"}),
            spec=PodSpec(containers=[
                Container(name="c", requests={"cpu": "1", "memory": "64Mi"})]),
        )
        store.create_replica_set(ReplicaSet(
            meta=ObjectMeta(name="web"), replicas=2,
            selector=LabelSelector(match_labels={"app": "web"}),
            template=template))
        mgr_ctl.settle()
        sched.run_until_settled()
        pods = [p for p in store.pods.values() if p.status.phase != "Failed"]
        assert len(pods) == 2 and all(p.spec.node_name for p in pods)
        before_keys = {p.meta.key() for p in pods}

        # pressure the node one of them landed on
        victim_node = pods[0].spec.node_name
        signals = {SIGNAL_MEMORY_AVAILABLE: 10 << 20}
        ev_mgr = EvictionManager(store, victim_node, stats_fn=lambda: dict(signals))
        evicted_key = ev_mgr.synchronize()
        assert evicted_key is not None
        assert store.get_pod(evicted_key).status.reason == "Evicted"
        assert store.nodes[victim_node].status.memory_pressure

        # nodelifecycle mirrors the condition as a NoSchedule taint; the
        # ReplicaSet controller replaces the Failed pod
        mgr_ctl.settle()
        taints = {t.key for t in store.nodes[victim_node].spec.taints}
        assert TAINT_MEMORY_PRESSURE in taints
        sched.run_until_settled()
        fresh = [p for p in store.pods.values()
                 if p.status.phase != "Failed" and p.spec.node_name]
        assert len(fresh) == 2
        for p in fresh:
            if p.meta.key() in before_keys:
                continue  # the survivor, bound before the pressure
            assert p.spec.node_name != victim_node, \
                f"replacement landed on the pressured node {victim_node}"
