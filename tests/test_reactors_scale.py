"""Reaction hooks (fake-clientset analog) + error-path scheduling, and a
larger-scale smoke (20k nodes) for the capacity-growth path."""

from kubernetes_tpu.api.types import Binding
from kubernetes_tpu.api.wrappers import make_node, make_pod
from kubernetes_tpu.apiserver.store import ClusterStore, Conflict
from kubernetes_tpu.scheduler.scheduler import Scheduler
from kubernetes_tpu.testing import with_reactors
from kubernetes_tpu.testing.reactors import raise_
from kubernetes_tpu.utils.clock import FakeClock


class TestReactors:
    def test_observe_calls(self):
        store = ClusterStore()
        tracker = with_reactors(store)
        store.create_node(make_node("n1").obj())
        store.create_pod(make_pod("p").obj())
        verbs = [v for v, _ in tracker.calls]
        assert verbs == ["create_node", "create_pod"]

    def test_injected_bind_conflict_requeues(self):
        """A bind that 409s must roll back the assume and retry — the
        MakeDefaultErrorFunc path (scheduler.go:352) exercised via reactor."""
        store = ClusterStore()
        clock = FakeClock()
        sched = Scheduler(store, now_fn=clock)
        tracker = with_reactors(store)
        store.create_node(make_node("n1").capacity(
            {"cpu": "4", "memory": "8Gi", "pods": 10}).obj())
        fail_once = {"left": 1}

        def bind_conflict(verb, args):
            if fail_once["left"]:
                fail_once["left"] -= 1
                raise_(Conflict("simulated bind 409"))
            return False

        tracker.prepend("bind", bind_conflict)
        store.create_pod(make_pod("p").req({"cpu": "100m"}).obj())
        sched.run_until_settled()
        assert store.get_pod("default/p").spec.node_name == ""  # first try failed
        # error-path pods sit in unschedulableQ until the leftover flush
        # (5min, scheduling_queue.go:463) or a cluster event
        clock.advance(301.0)
        sched.run_until_settled()
        assert store.get_pod("default/p").spec.node_name == "n1"  # retried
        # cache didn't leak the failed assume
        assert sched.cache.nodes["n1"].requested.milli_cpu == 100

    def test_swallowed_call(self):
        store = ClusterStore()
        tracker = with_reactors(store)
        tracker.prepend("create_pod", lambda v, a: True)  # drop silently
        store.create_pod(make_pod("ghost").obj())
        assert store.get_pod("default/ghost") is None


class TestScale:
    def test_20k_nodes_capacity_growth(self):
        """The TPU mirror grows node capacity by doubling; 20k nodes force
        several growth resyncs and scheduling still works."""
        from kubernetes_tpu.backend.tpu_scheduler import TPUScheduler

        store = ClusterStore()
        sched = TPUScheduler(store, batch_size=64)
        for i in range(20000):
            store.create_node(make_node(f"n{i}").capacity(
                {"cpu": "8", "memory": "16Gi", "pods": 32}).obj())
        for i in range(100):
            store.create_pod(make_pod(f"p{i}").req({"cpu": "500m"}).obj())
        sched.run_until_settled()
        assert sched.metrics["scheduled"] == 100
        assert sched.device.caps.nodes >= 20000
        nodes_used = {p.spec.node_name for p in store.pods.values()}
        assert len(nodes_used) == 100  # least-allocated spreads on empty fleet
