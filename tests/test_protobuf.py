"""Protobuf serialization + content negotiation (VERDICT r3 missing #7:
the apiserver front was JSON-only; protobuf existed only on the device
seam). Round-trips real binary protobuf (magic-prefixed KObject envelope)
through the codec and the HTTP front."""

import urllib.request

import pytest

# the apiserver protobuf codec compiles native/ktpu_api.proto on demand
# (no vendored pb2 yet, unlike the device service): without protoc or a
# cached build every test here would error at the first pb2() call — skip
# the module with a reason instead (the PR-3 test_grpc_service treatment)
from kubernetes_tpu.api import protobuf as _protobuf

if not _protobuf.pb2_available():
    pytest.skip("no cached ktpu_api_pb2 build and no protoc on PATH "
                "(apiserver protobuf codec is not vendored yet)",
                allow_module_level=True)

from kubernetes_tpu.api.protobuf import (
    CONTENT_TYPE,
    MAGIC,
    decode_list,
    decode_object,
    encode_list,
    encode_object,
)
from kubernetes_tpu.api.wrappers import make_node, make_pod
from kubernetes_tpu.apiserver.http import serve_api, shutdown_api
from kubernetes_tpu.apiserver.store import ClusterStore


class TestCodec:
    def test_pod_roundtrip(self):
        pod = (make_pod("web").req({"cpu": "500m", "memory": "1Gi"})
               .label("app", "web").priority(7)
               .node_affinity_in("zone", ["z1", "z2"]).obj())
        data = encode_object("Pod", pod)
        assert data.startswith(MAGIC)
        assert b"web" in data  # real field bytes, not JSON text
        assert b'{"' not in data[:40]
        kind, back = decode_object(data)
        assert kind == "Pod"
        assert back.meta.name == "web"
        assert back.meta.labels == {"app": "web"}
        assert back.spec.priority == 7
        assert back.resource_request() == pod.resource_request()

    def test_list_roundtrip(self):
        nodes = [make_node(f"n{i}").capacity({"cpu": "4"}).obj() for i in range(3)]
        kind, back, rv = decode_list(encode_list("Node", nodes, resource_version=42))
        assert kind == "Node" and rv == 42
        assert [n.meta.name for n in back] == ["n0", "n1", "n2"]


class TestHTTPNegotiation:
    def test_get_and_list_protobuf(self):
        store = ClusterStore()
        store.create_node(make_node("n1").capacity({"cpu": "8"}).obj())
        server, port = serve_api(store)
        try:
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/api/v1/nodes/n1",
                headers={"Accept": CONTENT_TYPE})
            with urllib.request.urlopen(req, timeout=5) as resp:
                assert resp.headers["Content-Type"] == CONTENT_TYPE
                kind, node = decode_object(resp.read())
            assert kind == "Node" and node.meta.name == "n1"

            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/api/v1/nodes",
                headers={"Accept": CONTENT_TYPE})
            with urllib.request.urlopen(req, timeout=5) as resp:
                kind, items, rv = decode_list(resp.read())
            assert kind == "Node" and len(items) == 1 and rv > 0
        finally:
            shutdown_api(server)

    def test_post_protobuf_body(self):
        store = ClusterStore()
        server, port = serve_api(store)
        try:
            pod = make_pod("from-proto").req({"cpu": "250m"}).obj()
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/api/v1/namespaces/default/pods",
                data=encode_object("Pod", pod),
                headers={"Content-Type": CONTENT_TYPE}, method="POST")
            with urllib.request.urlopen(req, timeout=5) as resp:
                assert resp.status == 201
            assert store.get_pod("default/from-proto") is not None
        finally:
            shutdown_api(server)

    def test_json_clients_unaffected(self):
        store = ClusterStore()
        store.create_node(make_node("n1").capacity({"cpu": "8"}).obj())
        server, port = serve_api(store)
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/api/v1/nodes/n1", timeout=5) as resp:
                assert "application/json" in resp.headers["Content-Type"]
        finally:
            shutdown_api(server)
