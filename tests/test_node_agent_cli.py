"""Hollow kubelet / kubemark, checkpoint manager, kube-proxy rules compiler,
kubectl CLI."""

import pytest

from kubernetes_tpu.api.types import Deployment, ObjectMeta, Service
from kubernetes_tpu.api.wrappers import make_node, make_pod
from kubernetes_tpu.apiserver.store import ClusterStore
from kubernetes_tpu.client.informer import SharedInformerFactory
from kubernetes_tpu.controllers import ControllerManager
from kubernetes_tpu.controllers.nodelifecycle import NODE_LEASE_NAMESPACE, TAINT_UNREACHABLE
from kubernetes_tpu.kubectl import kubectl
from kubernetes_tpu.kubelet import CheckpointManager, HollowCluster, HollowKubelet
from kubernetes_tpu.kubelet.checkpoint import CorruptCheckpointError
from kubernetes_tpu.kubelet.hollow import TERMINATES_AFTER_ANNOTATION
from kubernetes_tpu.proxy import Proxier
from kubernetes_tpu.scheduler.scheduler import Scheduler
from kubernetes_tpu.utils.clock import FakeClock


class TestHollowKubelet:
    def test_register_heartbeat_and_run_pods(self):
        store = ClusterStore()
        clock = FakeClock()
        k = HollowKubelet(store, make_node("n1").capacity(
            {"cpu": "4", "memory": "8Gi", "pods": 10}).obj(), now_fn=clock)
        k.run_once()
        assert "n1" in store.nodes
        lease = store.get_lease(f"{NODE_LEASE_NAMESPACE}/n1")
        assert lease is not None and lease.holder_identity == "n1"
        # a bound pod goes Running
        store.create_pod(make_pod("p1").obj())
        from kubernetes_tpu.api.types import Binding
        store.bind(Binding(pod_key="default/p1", node_name="n1"))
        # store.bind sets Running already (binding shortcut); reset to Pending
        p = store.get_pod("default/p1").clone()
        p.status.phase = "Pending"
        store.update_pod(p)
        k.run_once()
        assert store.get_pod("default/p1").status.phase == "Running"

    def test_terminates_after_annotation(self):
        store = ClusterStore()
        clock = FakeClock()
        k = HollowKubelet(store, make_node("n1").obj(), now_fn=clock)
        k.run_once()
        pod = make_pod("job-pod").obj()
        pod.meta.annotations[TERMINATES_AFTER_ANNOTATION] = "5"
        pod.spec.node_name = "n1"
        store.create_pod(pod)
        k.run_once()
        assert store.get_pod("default/job-pod").status.phase == "Running"
        clock.advance(6.0)
        k.run_once()
        assert store.get_pod("default/job-pod").status.phase == "Succeeded"

    def test_heartbeat_keeps_node_ready(self):
        """kubelet heartbeats vs nodelifecycle: alive node stays Ready,
        a stopped kubelet's node goes NotReady + tainted."""
        store = ClusterStore()
        clock = FakeClock()
        alive = HollowKubelet(store, make_node("alive").obj(), now_fn=clock)
        dead = HollowKubelet(store, make_node("dead").obj(), now_fn=clock)
        alive.run_once()
        dead.run_once()
        m = ControllerManager(store, factory=SharedInformerFactory(store),
                              controllers=["nodelifecycle"], now_fn=clock)
        for _ in range(10):
            clock.advance(10.0)
            alive.run_once()  # dead stops heartbeating
            m.sync_round(monitor_nodes=True)
        assert store.nodes["alive"].status.ready
        assert not store.nodes["dead"].status.ready
        assert any(t.key == TAINT_UNREACHABLE for t in store.nodes["dead"].spec.taints)


class TestKubemark:
    def test_hollow_cluster_end_to_end(self):
        """kubemark-style: scheduler + KCM + 50 hollow nodes running a
        deployment to completion."""
        store = ClusterStore()
        clock = FakeClock()
        cluster = HollowCluster(store, n_nodes=50, now_fn=clock)
        cluster.register_all()
        sched = Scheduler(store, now_fn=clock)
        m = ControllerManager(store, factory=SharedInformerFactory(store),
                              controllers=["deployment", "replicaset", "endpoints"],
                              now_fn=clock)
        store.create_service(Service(meta=ObjectMeta(name="web"), selector={"app": "web"}))
        tmpl = make_pod("t").req({"cpu": "500m"}).label("app", "web").obj()
        store.create_object("Deployment", Deployment(
            meta=ObjectMeta(name="web"), replicas=200, template=tmpl))
        for _ in range(10):
            m.settle()
            sched.run_until_settled()
            cluster.tick()
        running = [p for p in store.pods.values() if p.status.phase == "Running"]
        assert len(running) == 200
        nodes_used = {p.spec.node_name for p in running}
        assert len(nodes_used) == 50  # spread over the fleet
        m.settle()
        eps = store.get_object("Endpoints", "default/web")
        assert len(eps.addresses) == 200


class TestCheckpointManager:
    def test_roundtrip(self, tmp_path):
        cm = CheckpointManager(str(tmp_path))
        cm.create_checkpoint("devices", {"gpu": [0, 1]})
        assert cm.get_checkpoint("devices") == {"gpu": [0, 1]}
        assert cm.list_checkpoints() == ["devices"]
        cm.remove_checkpoint("devices")
        assert cm.get_checkpoint("devices") is None

    def test_corruption_detected(self, tmp_path):
        cm = CheckpointManager(str(tmp_path))
        cm.create_checkpoint("state", {"a": 1})
        path = tmp_path / "state"
        doc = path.read_text().replace('\\"a\\": 1', '\\"a\\": 2')
        path.write_text(doc.replace('"a\\": 1', '"a\\": 2'))
        # direct tamper: swap payload digit
        raw = path.read_text()
        path.write_text(raw.replace("1", "7", 1))
        with pytest.raises(CorruptCheckpointError):
            cm.get_checkpoint("state")

    def test_survives_restart(self, tmp_path):
        CheckpointManager(str(tmp_path)).create_checkpoint("x", {"k": "v"})
        assert CheckpointManager(str(tmp_path)).get_checkpoint("x") == {"k": "v"}


class TestProxier:
    def _cluster(self):
        store = ClusterStore()
        factory = SharedInformerFactory(store)
        proxier = Proxier(store, factory=factory)
        m = ControllerManager(store, factory=SharedInformerFactory(store),
                              controllers=["endpoints"])
        return store, factory, proxier, m

    def test_rules_follow_endpoints(self):
        store, factory, proxier, m = self._cluster()
        store.create_service(Service(meta=ObjectMeta(name="svc"), selector={"app": "a"}))
        for i in range(3):
            p = make_pod(f"p{i}").label("app", "a").obj()
            p.status.phase = "Running"
            p.spec.node_name = "n1"
            store.create_pod(p)
        m.settle()
        factory.pump()
        proxier.sync_proxy_rules()
        assert sorted(proxier.backends("default/svc")) == [
            "default/p0", "default/p1", "default/p2"]
        # round robin covers all backends
        picks = {proxier.route("default/svc") for _ in range(3)}
        assert picks == {"default/p0", "default/p1", "default/p2"}

    def test_service_delete_clears_rules(self):
        store, factory, proxier, m = self._cluster()
        store.create_service(Service(meta=ObjectMeta(name="svc"), selector={"app": "a"}))
        m.settle()
        factory.pump()
        proxier.sync_proxy_rules()
        assert proxier.backends("default/svc") == []
        store.delete_object("Service", "default/svc")
        factory.pump()
        proxier.sync_proxy_rules()
        assert proxier.route("default/svc") is None


class TestKubectl:
    def _store(self):
        store = ClusterStore()
        store.create_node(make_node("n1").capacity({"cpu": "4", "memory": "8Gi", "pods": 10}).obj())
        store.create_pod(make_pod("web-1").label("app", "web").obj())
        return store

    def test_get_pods(self):
        out = kubectl(self._store(), "get pods")
        assert "NAME" in out and "web-1" in out and "Pending" in out

    def test_get_single_not_found(self):
        out = kubectl(self._store(), "get pods nope")
        assert "NotFound" in out

    def test_describe_node(self):
        out = kubectl(self._store(), "describe node n1")
        assert "Name:         n1" in out and "Ready:        True" in out

    def test_create_apply_delete_roundtrip(self, tmp_path):
        manifest = tmp_path / "deploy.yaml"
        manifest.write_text("""
apiVersion: apps/v1
kind: Deployment
metadata:
  name: api
spec:
  replicas: 2
  selector:
    matchLabels: {app: api}
  template:
    metadata:
      labels: {app: api}
    spec:
      containers:
      - image: api:v1
        resources:
          requests: {cpu: 100m}
---
apiVersion: v1
kind: Service
metadata:
  name: api
spec:
  selector: {app: api}
""")
        store = self._store()
        out = kubectl(store, f"create -f {manifest}")
        assert "deployment/api created" in out and "service/api created" in out
        assert store.get_object("Deployment", "default/api").replicas == 2
        out = kubectl(store, f"create -f {manifest}")
        assert "AlreadyExists" in out
        out = kubectl(store, f"apply -f {manifest}")
        assert "configured" in out
        out = kubectl(store, "delete deployment api")
        assert 'deleted' in out
        assert store.get_object("Deployment", "default/api") is None

    def test_scale_and_cordon(self, tmp_path):
        store = self._store()
        manifest = tmp_path / "rs.yaml"
        manifest.write_text("""
kind: ReplicaSet
metadata: {name: web}
spec:
  replicas: 1
  selector: {app: web}
  template:
    spec: {containers: [{image: web}]}
""")
        kubectl(store, f"create -f {manifest}")
        out = kubectl(store, "scale rs web --replicas=5")
        assert "scaled" in out
        assert store.get_replica_set("default/web").replicas == 5
        out = kubectl(store, "cordon n1")
        assert "cordoned" in out
        assert store.nodes["n1"].spec.unschedulable
        out = kubectl(store, "get nodes")
        assert "SchedulingDisabled" in out
        kubectl(store, "uncordon n1")
        assert not store.nodes["n1"].spec.unschedulable

    def test_kubectl_drives_scheduler(self, tmp_path):
        """create -f pod manifest → scheduler binds → get shows the node."""
        store = self._store()
        sched = Scheduler(store)
        manifest = tmp_path / "pod.yaml"
        manifest.write_text("""
kind: Pod
metadata: {name: cli-pod}
spec:
  containers:
  - name: app
    image: app:v1
    resources:
      requests: {cpu: 200m}
""")
        kubectl(store, f"create -f {manifest}")
        sched.run_until_settled()
        out = kubectl(store, "get pods cli-pod")
        assert "n1" in out


class TestReviewRegressions:
    def test_pv_quantities_parsed_with_suffixes(self, tmp_path):
        store = ClusterStore()
        m = tmp_path / "pv.yaml"
        m.write_text("""
kind: PersistentVolume
metadata: {name: data}
spec:
  storageClassName: fast
  capacity: {storage: 10Gi}
---
kind: PersistentVolumeClaim
metadata: {name: claim}
spec:
  storageClassName: fast
  resources:
    requests: {storage: 5Gi}
""")
        kubectl(store, f"create -f {m}")
        assert store.get_pv("data").capacity_bytes == 10 * 1024**3
        assert store.get_pvc("default/claim").requested_bytes == 5 * 1024**3

    def test_selector_match_expressions_preserved(self, tmp_path):
        store = ClusterStore()
        m = tmp_path / "rs.yaml"
        m.write_text("""
kind: ReplicaSet
metadata: {name: web}
spec:
  replicas: 1
  selector:
    matchExpressions:
    - {key: app, operator: In, values: [web]}
  template:
    spec: {containers: [{image: web}]}
""")
        kubectl(store, f"create -f {m}")
        sel = store.get_replica_set("default/web").selector
        assert sel.matches({"app": "web"}) and not sel.matches({"app": "db"})

    def test_apply_preserves_binding(self, tmp_path):
        store = ClusterStore()
        store.create_node(make_node("n1").capacity({"cpu": "4", "memory": "8Gi", "pods": 10}).obj())
        sched = Scheduler(store)
        m = tmp_path / "pod.yaml"
        m.write_text("""
kind: Pod
metadata: {name: p}
spec: {containers: [{name: a, image: a, resources: {requests: {cpu: 100m}}}]}
""")
        kubectl(store, f"create -f {m}")
        sched.run_until_settled()
        assert store.get_pod("default/p").spec.node_name == "n1"
        kubectl(store, f"apply -f {m}")
        pod = store.get_pod("default/p")
        assert pod.spec.node_name == "n1" and pod.status.phase == "Running"

    def test_proxier_full_sync_sweeps_deleted_service(self):
        store = ClusterStore()
        proxier = Proxier(store)  # no informers: full-sync path only
        store.create_service(Service(meta=ObjectMeta(name="svc"), selector={}))
        proxier.sync_proxy_rules(full=True)
        assert "default/svc" in proxier.rules
        store.delete_object("Service", "default/svc")
        proxier.sync_proxy_rules(full=True)
        assert "default/svc" not in proxier.rules

    def test_kubelet_restart_does_not_clobber_node(self):
        store = ClusterStore()
        k = HollowKubelet(store, make_node("n1").obj())
        k.run_once()
        from kubernetes_tpu.kubectl import kubectl as kc
        kc(store, "cordon n1")
        k2 = HollowKubelet(store, make_node("n1").obj())  # restart
        k2.run_once()
        assert store.nodes["n1"].spec.unschedulable  # cordon survived

    def test_hollow_admission_rejects_overcommit(self):
        store = ClusterStore()
        k = HollowKubelet(store, make_node("n1").capacity(
            {"cpu": "64", "memory": "64Gi", "pods": 2}).obj())
        k.run_once()
        for i in range(4):
            p = make_pod(f"p{i}").obj()
            p.spec.node_name = "n1"
            store.create_pod(p)
        k.run_once()
        phases = sorted(p.status.phase for p in store.pods.values())
        assert phases.count("Failed") == 2 and phases.count("Running") == 2


def test_hollow_cluster_with_runtime_and_volumes():
    """kubemark modes: per-kubelet fake CRI + PLEG, and instant-attach
    volume manager gating PVC pods."""
    from kubernetes_tpu.api.types import ObjectMeta, PersistentVolume, PersistentVolumeClaim
    from kubernetes_tpu.kubelet.kubemark import HollowCluster

    store = ClusterStore()
    cluster = HollowCluster(store, n_nodes=4, with_runtime=True,
                            with_volume_manager=True)
    cluster.register_all()
    store.create_pv(PersistentVolume(meta=ObjectMeta(name="pv1"),
                                     capacity_bytes=1 << 30,
                                     bound_pvc="default/c1"))
    store.create_pvc(PersistentVolumeClaim(meta=ObjectMeta(name="c1"),
                                           bound_pv="pv1"))
    plain = make_pod("plain").req({"cpu": "1"}).obj()
    plain.spec.node_name = "hollow-node-0"
    store.create_pod(plain)
    claimed = make_pod("claimed").req({"cpu": "1"}).pvc("c1").obj()
    claimed.spec.node_name = "hollow-node-1"
    store.create_pod(claimed)
    cluster.settle()
    assert store.get_pod("default/plain").status.phase == "Running"
    assert store.get_pod("default/claimed").status.phase == "Running"
    # the runtime really materialized sandboxes + containers
    k0 = cluster.kubelet_for("hollow-node-0")
    assert k0.runtime is not None
    assert any(c["state"] == "CONTAINER_RUNNING"
               for c in k0.runtime.containers.values())
    assert k0.pleg is not None and k0.pleg.healthy()
