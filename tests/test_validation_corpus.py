"""Reject corpus (VERDICT r4 item 9): every case here is one the reference's
pkg/apis/core/validation/validation.go rejects; each must be rejected with a
field-path-bearing message. Anchors cite the reference rule.
"""

import pytest

from kubernetes_tpu.api.types import (
    Affinity, Container, ContainerPort, LabelSelector, NodeAffinity,
    NodeSelector, NodeSelectorTerm, ObjectMeta, Pod, PodAffinity,
    PodAffinityTerm, PodAntiAffinity, PodSpec, PreferredSchedulingTerm,
    Requirement, Taint, TopologySpreadConstraint, WeightedPodAffinityTerm,
)
from kubernetes_tpu.api.validation import validate_node, validate_pod
from kubernetes_tpu.api.wrappers import make_node, make_pod


def _pod(**spec_kw):
    return Pod(meta=ObjectMeta(name="p", namespace="default"),
               spec=PodSpec(containers=(Container(name="c"),), **spec_kw))


def _expect(errs, fragment):
    assert any(fragment in e for e in errs), (fragment, errs)


class TestAffinityTermShape:
    def test_in_operator_requires_values(self):
        # ValidateNodeSelectorRequirement: In needs >=1 value
        pod = _pod(affinity=Affinity(node_affinity=NodeAffinity(
            required=NodeSelector(terms=(NodeSelectorTerm(
                match_expressions=(Requirement(key="zone", operator="In"),)),)))))
        _expect(validate_pod(pod), "values: must be specified")

    def test_exists_operator_forbids_values(self):
        pod = _pod(affinity=Affinity(node_affinity=NodeAffinity(
            required=NodeSelector(terms=(NodeSelectorTerm(
                match_expressions=(Requirement(key="zone", operator="Exists",
                                               values=("a",)),)),)))))
        _expect(validate_pod(pod), "values: may not be specified")

    def test_gt_requires_single_integer(self):
        pod = _pod(affinity=Affinity(node_affinity=NodeAffinity(
            required=NodeSelector(terms=(NodeSelectorTerm(
                match_expressions=(Requirement(key="cores", operator="Gt",
                                               values=("ten",)),)),)))))
        _expect(validate_pod(pod), "must be an integer")

    def test_unknown_operator_rejected(self):
        pod = _pod(affinity=Affinity(node_affinity=NodeAffinity(
            required=NodeSelector(terms=(NodeSelectorTerm(
                match_expressions=(Requirement(key="k", operator="Near"),)),)))))
        _expect(validate_pod(pod), "not a valid operator")

    def test_pod_affinity_term_requires_topology_key(self):
        # validatePodAffinityTerm: topologyKey can not be empty
        pod = _pod(affinity=Affinity(pod_affinity=PodAffinity(
            required=(PodAffinityTerm(label_selector=LabelSelector()),))))
        _expect(validate_pod(pod), "topologyKey: can not be empty")

    def test_preferred_weight_range(self):
        # weight must be in the range 1-100
        pod = _pod(affinity=Affinity(pod_anti_affinity=PodAntiAffinity(
            preferred=(WeightedPodAffinityTerm(
                weight=500,
                term=PodAffinityTerm(topology_key="zone")),))))
        _expect(validate_pod(pod), "must be in the range 1-100")

    def test_preferred_node_weight_range(self):
        pod = _pod(affinity=Affinity(node_affinity=NodeAffinity(
            preferred=(PreferredSchedulingTerm(weight=0),))))
        _expect(validate_pod(pod), "must be in the range 1-100")

    def test_bad_selector_key_in_term(self):
        pod = _pod(affinity=Affinity(pod_affinity=PodAffinity(
            required=(PodAffinityTerm(
                topology_key="zone",
                label_selector=LabelSelector(match_expressions=(
                    Requirement(key="-bad-", operator="Exists"),))),))))
        _expect(validate_pod(pod), "matchExpressions[0].key")


class TestSpreadConstraints:
    def test_min_domains_requires_do_not_schedule(self):
        # validateMinDomains: only with DoNotSchedule
        pod = _pod(topology_spread_constraints=(TopologySpreadConstraint(
            max_skew=1, topology_key="zone", when_unsatisfiable="ScheduleAnyway",
            min_domains=2),))
        _expect(validate_pod(pod), "minDomains: can only be specified")

    def test_min_domains_positive(self):
        pod = _pod(topology_spread_constraints=(TopologySpreadConstraint(
            max_skew=1, topology_key="zone", when_unsatisfiable="DoNotSchedule",
            min_domains=0),))
        _expect(validate_pod(pod), "minDomains: 0 must be greater than 0")

    def test_max_skew_positive(self):
        pod = _pod(topology_spread_constraints=(TopologySpreadConstraint(
            max_skew=0, topology_key="zone",
            when_unsatisfiable="DoNotSchedule"),))
        _expect(validate_pod(pod), "maxSkew")

    def test_selector_shape_checked(self):
        pod = _pod(topology_spread_constraints=(TopologySpreadConstraint(
            max_skew=1, topology_key="zone", when_unsatisfiable="DoNotSchedule",
            label_selector=LabelSelector(match_expressions=(
                Requirement(key="app", operator="In"),))),))
        _expect(validate_pod(pod), "labelSelector.matchExpressions[0].values")


class TestHostPorts:
    def test_duplicate_host_port_rejected(self):
        # AccumulateUniqueHostPorts
        pod = Pod(meta=ObjectMeta(name="p", namespace="default"),
                  spec=PodSpec(containers=(
                      Container(name="a", ports=(ContainerPort(
                          container_port=80, host_port=8080),)),
                      Container(name="b", ports=(ContainerPort(
                          container_port=81, host_port=8080),)),
                  )))
        _expect(validate_pod(pod), "duplicate host port")

    def test_out_of_range_host_port(self):
        pod = Pod(meta=ObjectMeta(name="p", namespace="default"),
                  spec=PodSpec(containers=(Container(name="a", ports=(
                      ContainerPort(container_port=80, host_port=70000),)),)))
        _expect(validate_pod(pod), "must be in 1-65535")


class TestResources:
    def test_request_exceeding_limit(self):
        pod = Pod(meta=ObjectMeta(name="p", namespace="default"),
                  spec=PodSpec(containers=(Container(
                      name="a", requests={"cpu": "2"}, limits={"cpu": "1"}),)))
        _expect(validate_pod(pod), "must be ≤ the cpu limit")

    def test_unparseable_quantity(self):
        pod = Pod(meta=ObjectMeta(name="p", namespace="default"),
                  spec=PodSpec(containers=(Container(
                      name="a", requests={"cpu": "two"}),)))
        _expect(validate_pod(pod), "quantity 'two' is invalid")


class TestTaintsTolerations:
    def test_duplicate_taint_rejected(self):
        # validateNodeTaints: duplicate (key, effect)
        node = make_node("n").taint("k", "v").taint("k", "w").obj()
        _expect(validate_node(node), "duplicate taint")

    def test_bad_taint_value(self):
        node = make_node("n").obj()
        node.spec.taints = (Taint(key="k", value="bad value!", effect="NoSchedule"),)
        _expect(validate_node(node), "not a valid taint value")

    def test_exists_toleration_with_value(self):
        pod = make_pod("p").toleration(key="k", operator="Exists", value="v").obj()
        _expect(validate_pod(pod), "must be empty when operator is Exists")


class TestStoreRejects:
    """The write path must actually refuse these (422 position)."""

    def test_store_rejects_invalid_pod(self):
        from kubernetes_tpu.apiserver import ClusterStore
        from kubernetes_tpu.api.validation import ValidationError

        store = ClusterStore()
        bad = _pod(affinity=Affinity(pod_affinity=PodAffinity(
            required=(PodAffinityTerm(),))))
        with pytest.raises(ValidationError) as e:
            store.create_pod(bad)
        assert "topologyKey" in str(e.value)
