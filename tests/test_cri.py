"""CRI seam (cri-api v1 api.proto, reduced): the fake runtime's state
machines, the real gRPC binding, the kubelet driving pod lifecycle through
it, and the kube-proxy iptables-save rendering."""

import pytest

from kubernetes_tpu.api.types import Binding, Endpoints, EndpointAddress, ObjectMeta, Service
from kubernetes_tpu.api.wrappers import make_node, make_pod
from kubernetes_tpu.apiserver.store import ClusterStore
from kubernetes_tpu.kubelet import cri as _cri
from kubernetes_tpu.kubelet.cri import CRIClient, FakeRuntimeService, serve_cri

# the CRI gRPC binding compiles native/ktpu_cri.proto on demand (not
# vendored): only the over-the-wire tests need it — the fake runtime,
# in-process kubelet, and proxier tests below run regardless
needs_cri_grpc = pytest.mark.skipif(
    not _cri.pb2_available(),
    reason="no cached ktpu_cri_pb2 build and no protoc on PATH "
           "(CRI protos are not vendored yet)")
from kubernetes_tpu.kubelet.hollow import HollowKubelet
from kubernetes_tpu.proxy.proxier import Proxier
from kubernetes_tpu.utils.clock import FakeClock


class TestFakeRuntime:
    def test_sandbox_container_lifecycle(self):
        rt = FakeRuntimeService()
        sid = rt.run_pod_sandbox({"name": "web", "namespace": "prod"})
        assert rt.pod_sandbox_status(sid)["state"] == "SANDBOX_READY"
        cid = rt.create_container(sid, {"name": "app", "image": "nginx:1.25"})
        assert rt.container_status(cid)["state"] == "CONTAINER_CREATED"
        rt.start_container(cid)
        assert rt.container_status(cid)["state"] == "CONTAINER_RUNNING"
        assert any(i["repo_tags"] == ["nginx:1.25"] for i in rt.list_images())
        rt.stop_pod_sandbox(sid)
        assert rt.container_status(cid)["state"] == "CONTAINER_EXITED"
        assert rt.container_status(cid)["exit_code"] == 137
        rt.remove_pod_sandbox(sid)
        assert rt.list_pod_sandbox() == [] and rt.list_containers() == []

    def test_graceful_stop_exit_zero(self):
        rt = FakeRuntimeService()
        sid = rt.run_pod_sandbox({"name": "p", "namespace": "default"})
        cid = rt.create_container(sid, {"name": "c", "image": "x"})
        rt.start_container(cid)
        rt.stop_container(cid)
        c = rt.container_status(cid)
        assert c["state"] == "CONTAINER_EXITED" and c["exit_code"] == 0


@needs_cri_grpc
class TestCRIOverGrpc:
    def test_full_lifecycle_over_the_wire(self):
        rt = FakeRuntimeService()
        server, port = serve_cri(rt)
        try:
            client = CRIClient(f"127.0.0.1:{port}")
            v = client.version()
            assert v["runtime_name"] == "ktpu-hollow"
            sid = client.run_pod_sandbox({"name": "web", "namespace": "prod"})
            cid = client.create_container(sid, {"name": "app", "image": "nginx"})
            client.start_container(cid)
            assert client.list_containers(sid)[0]["state"] == "CONTAINER_RUNNING"
            assert client.list_pod_sandbox()[0]["config"]["name"] == "web"
            client.stop_pod_sandbox(sid)
            client.remove_pod_sandbox(sid)
            assert client.list_pod_sandbox() == []
            client.close()
        finally:
            server.stop(0)


class TestKubeletOverCRI:
    def test_pod_lifecycle_materializes_in_runtime(self):
        clock = FakeClock()
        store = ClusterStore()
        rt = FakeRuntimeService(now_fn=clock)
        node = make_node("n1").capacity({"cpu": "4", "memory": "8Gi", "pods": 10}).obj()
        kubelet = HollowKubelet(store, node, now_fn=clock, runtime=rt)
        kubelet.run_once()
        pod = make_pod("web").req({"cpu": "100m"}).obj()
        pod.meta.annotations["kubelet/terminates-after"] = "5"
        store.create_pod(pod)
        store.bind(Binding(pod_key="default/web", node_name="n1"))
        kubelet.run_once()
        assert store.get_pod("default/web").status.phase == "Running"
        assert rt.list_pod_sandbox()[0]["config"]["name"] == "web"
        assert rt.list_containers()[0]["state"] == "CONTAINER_RUNNING"
        clock.advance(6)
        kubelet.run_once()
        assert store.get_pod("default/web").status.phase == "Succeeded"
        assert rt.list_containers()[0]["state"] == "CONTAINER_EXITED"
        # pod deleted -> sandbox garbage-collected
        store.delete_pod("default/web")
        kubelet.run_once()
        assert rt.list_pod_sandbox() == []

    def test_ttl_completion_exits_zero(self):
        # Succeeded pods' containers must read exit 0 (graceful), not 137
        clock = FakeClock()
        store = ClusterStore()
        rt = FakeRuntimeService(now_fn=clock)
        node = make_node("n1").capacity({"cpu": "4", "memory": "8Gi", "pods": 10}).obj()
        kubelet = HollowKubelet(store, node, now_fn=clock, runtime=rt)
        kubelet.run_once()
        pod = make_pod("job").req({"cpu": "100m"}).obj()
        pod.meta.annotations["kubelet/terminates-after"] = "3"
        store.create_pod(pod)
        store.bind(Binding(pod_key="default/job", node_name="n1"))
        kubelet.run_once()
        clock.advance(4)
        kubelet.run_once()
        assert store.get_pod("default/job").status.phase == "Succeeded"
        c = rt.list_containers()[0]
        assert c["state"] == "CONTAINER_EXITED" and c["exit_code"] == 0

    def test_evicted_pod_sandbox_torn_down(self):
        clock = FakeClock()
        store = ClusterStore()
        rt = FakeRuntimeService(now_fn=clock)
        node = make_node("n1").capacity({"cpu": "8", "memory": "8Gi", "pods": 1}).obj()
        kubelet = HollowKubelet(store, node, now_fn=clock, runtime=rt)
        kubelet.run_once()
        for name in ("a", "b"):
            store.create_pod(make_pod(name).req({"cpu": "100m"}).obj())
            store.bind(Binding(pod_key=f"default/{name}", node_name="n1"))
        kubelet.run_once()
        kubelet.run_once()
        phases = {p.meta.name: p.status.phase for p in store.pods.values()}
        assert "Failed" in phases.values()
        # exactly one sandbox remains (the surviving pod's)
        assert len(rt.list_pod_sandbox()) == 1

    @needs_cri_grpc
    def test_kubelet_over_grpc_runtime(self):
        clock = FakeClock()
        store = ClusterStore()
        rt = FakeRuntimeService(now_fn=clock)
        server, port = serve_cri(rt)
        try:
            client = CRIClient(f"127.0.0.1:{port}")
            node = make_node("n1").capacity(
                {"cpu": "4", "memory": "8Gi", "pods": 10}).obj()
            kubelet = HollowKubelet(store, node, now_fn=clock, runtime=client)
            kubelet.run_once()
            store.create_pod(make_pod("w").req({"cpu": "100m"}).obj())
            store.bind(Binding(pod_key="default/w", node_name="n1"))
            kubelet.run_once()
            assert store.get_pod("default/w").status.phase == "Running"
            # the state landed in the REMOTE runtime, over real gRPC
            assert rt.list_containers()[0]["state"] == "CONTAINER_RUNNING"
            assert "RunPodSandbox" in rt.calls and "StartContainer" in rt.calls
            client.close()
        finally:
            server.stop(0)


class TestProtocAvailabilityGate:
    """utils/protoc.build_available — the ONE rule behind the three
    pb2_available() gates (api/protobuf, kubelet/cri, backend/grpc_service)."""

    def test_missing_proto_source_is_never_buildable(self, tmp_path):
        from kubernetes_tpu.utils.protoc import build_available

        missing = str(tmp_path / "nope.proto")
        pb2 = str(tmp_path / "nope_pb2.py")
        # protoc on PATH changes nothing: pb2() compares mtimes against
        # the .proto even with a cached build, so a missing source means
        # every path through pb2() raises
        assert build_available(None, pb2, missing) is False
        # an already-imported module short-circuits everything
        assert build_available(object(), pb2, missing) is True

    def test_fresh_cached_build_is_available_without_protoc(self, tmp_path):
        from kubernetes_tpu.utils.protoc import build_available

        proto = tmp_path / "x.proto"
        proto.write_text('syntax = "proto3";')
        pb2 = tmp_path / "x_pb2.py"
        pb2.write_text("# cached build")
        assert build_available(None, str(pb2), str(proto)) is True


class TestIptablesRendering:
    def test_chains_and_probabilities(self):
        store = ClusterStore()
        store.create_service(Service(meta=ObjectMeta(name="web"),
                                     selector={"app": "web"}))
        store.create_object("Endpoints", Endpoints(
            meta=ObjectMeta(name="web"),
            addresses=(EndpointAddress(pod_key="default/p1", node_name="n1"),
                       EndpointAddress(pod_key="default/p2", node_name="n2"),
                       EndpointAddress(pod_key="default/p3", node_name="n3"))))
        proxier = Proxier(store)
        proxier.mark_dirty("default/web")
        proxier.sync_proxy_rules()
        text = proxier.render_iptables()
        assert text.startswith("*nat")
        assert text.rstrip().endswith("COMMIT")
        assert "-j KUBE-SVC-" in text
        # 3 backends: first jump at p=1/3, second at 1/2, last unconditional
        assert "--probability 0.3333333333" in text
        assert "--probability 0.5000000000" in text
        assert text.count("KUBE-SEP-") >= 6  # 3 chains declared + 3 jumps
        assert '--comment "default/p1"' in text


def test_render_ipvs_and_conntrack_cleanup():
    """ipvs proxier mode (pkg/proxy/ipvs): ipvsadm-save text with one rr
    virtual server per service + conntrack stale-flow targets when a
    backend disappears."""
    from kubernetes_tpu.api.types import EndpointAddress, Endpoints, ObjectMeta, Service

    store = ClusterStore()
    store.create_service(Service(meta=ObjectMeta(name="web"),
                                 selector={"app": "web"}))
    store.create_object("Endpoints", Endpoints(
        meta=ObjectMeta(name="web"),
        addresses=(EndpointAddress(pod_key="default/p1", node_name="n1"),
                   EndpointAddress(pod_key="default/p2", node_name="n2"))))
    proxier = Proxier(store)
    proxier.mark_dirty("default/web")
    proxier.sync_proxy_rules()
    text = proxier.render_ipvs()
    assert "-A -t default/web -s rr" in text
    assert "-a -t default/web -r default/p1 -m -w 1" in text
    assert "-a -t default/web -r default/p2 -m -w 1" in text

    before = {"default/web": tuple(proxier.backends("default/web"))}
    store.update_object("Endpoints", Endpoints(
        meta=ObjectMeta(name="web"),
        addresses=(EndpointAddress(pod_key="default/p1", node_name="n1"),)))
    proxier.mark_dirty("default/web")
    proxier.sync_proxy_rules()
    assert proxier.stale_conntrack_entries(before) == ["default/p2"]
