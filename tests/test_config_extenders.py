"""Component-config (KubeSchedulerConfiguration) + extender protocol tests.

Covers: decode/default/validate (apis/config/v1beta3), profile plugin-set
merging incl. disable-'*' and MultiPoint, per-plugin args plumbing, multi-
profile scheduling, and extender filter/prioritize/bind verbs
(extender.go:247,:317,:359).
"""

import pytest

from kubernetes_tpu.api.wrappers import make_node, make_pod
from kubernetes_tpu.apiserver.store import ClusterStore
from kubernetes_tpu.config import (
    ConfigError,
    load_config,
    expand_profile,
    scheduler_from_config,
)
from kubernetes_tpu.config.types import Extender as ExtenderConfig
from kubernetes_tpu.scheduler.extender import CallableExtender


def test_defaults():
    cfg = load_config(None)
    assert cfg.parallelism == 16
    assert cfg.percentage_of_nodes_to_score == 0
    assert cfg.pod_initial_backoff_seconds == 1.0
    assert cfg.pod_max_backoff_seconds == 10.0
    assert len(cfg.profiles) == 1
    assert cfg.profiles[0].scheduler_name == "default-scheduler"


def test_validation_errors():
    with pytest.raises(ConfigError):
        load_config({"parallelism": 0})
    with pytest.raises(ConfigError):
        load_config({"percentageOfNodesToScore": 101})
    with pytest.raises(ConfigError):
        load_config({"podMaxBackoffSeconds": 0.5})  # < initial 1.0
    with pytest.raises(ConfigError):
        load_config({"profiles": [{"schedulerName": "a"}, {"schedulerName": "a"}]})
    with pytest.raises(ConfigError):
        load_config({"apiVersion": "kubescheduler.config.k8s.io/v1beta1"})


def test_profile_disable_and_enable():
    cfg = load_config(
        {
            "profiles": [
                {
                    "schedulerName": "custom",
                    "plugins": {
                        "score": {
                            "disabled": [{"name": "ImageLocality"}],
                            "enabled": [{"name": "TaintToleration", "weight": 7}],
                        },
                        "filter": {"disabled": [{"name": "*"}]},
                    },
                }
            ]
        }
    )
    pc = expand_profile(cfg.profiles[0])
    score = dict(pc["score"])
    assert "ImageLocality" not in score
    assert score["TaintToleration"] == 7  # re-enable overrides default weight 3
    assert pc["filter"] == []
    # untouched point keeps defaults
    assert ("NodeResourcesFit", 0) in pc["pre_filter"]


def test_plugin_args_reach_plugin():
    cfg = load_config(
        {
            "profiles": [
                {
                    "schedulerName": "default-scheduler",
                    "pluginConfig": [
                        {"name": "NodeResourcesFit", "args": {"strategy": "MostAllocated"}}
                    ],
                }
            ]
        }
    )
    store = ClusterStore()
    s = scheduler_from_config(store, cfg)
    fit = s.profiles["default-scheduler"].plugin("NodeResourcesFit")
    assert fit.strategy == "MostAllocated"


def test_multi_profile_scheduling():
    raw = {
        "profiles": [
            {"schedulerName": "default-scheduler"},
            {
                "schedulerName": "no-scoring",
                "plugins": {"score": {"disabled": [{"name": "*"}]}},
            },
        ]
    }
    store = ClusterStore()
    store.create_node(make_node("n1").capacity({"cpu": "4", "memory": "8Gi", "pods": 10}).obj())
    s = scheduler_from_config(store, raw=raw)
    store.create_pod(make_pod("a").req({"cpu": "100m"}).obj())
    p = make_pod("b").req({"cpu": "100m"}).obj()
    p.spec.scheduler_name = "no-scoring"
    store.create_pod(p)
    q = make_pod("c").req({"cpu": "100m"}).obj()
    q.spec.scheduler_name = "unknown-scheduler"  # not ours: must be ignored
    store.create_pod(q)
    s.run_until_settled()
    assert store.get_pod("default/a").spec.node_name == "n1"
    assert store.get_pod("default/b").spec.node_name == "n1"
    assert store.get_pod("default/c").spec.node_name == ""


def test_extender_filter_and_prioritize():
    """Extender trims feasible set and its scores (×weight) shift the win."""
    store = ClusterStore()
    for i in range(3):
        store.create_node(make_node(f"n{i}").capacity({"cpu": "4", "memory": "8Gi", "pods": 10}).obj())

    def filt(pod, nodes):
        keep = [n for n in nodes if n.meta.name != "n0"]
        return keep, {"n0": "extender says no"}

    def prio(pod, nodes):
        return {n.meta.name: (10 if n.meta.name == "n2" else 0) for n in nodes}

    ext = ExtenderConfig(instance=CallableExtender(filter_fn=filt, prioritize_fn=prio, weight=100))
    s = scheduler_from_config(store, load_config(None))
    s.extenders.extend(__import__("kubernetes_tpu.scheduler.extender", fromlist=["build_extenders"]).build_extenders([ext]))
    store.create_pod(make_pod("p").req({"cpu": "100m"}).obj())
    s.run_until_settled()
    assert store.get_pod("default/p").spec.node_name == "n2"


def test_extender_binder():
    store = ClusterStore()
    store.create_node(make_node("n1").capacity({"cpu": "4", "memory": "8Gi", "pods": 10}).obj())
    bound = {}

    def bind(pod, node_name):
        bound[pod.key()] = node_name
        from kubernetes_tpu.api.types import Binding

        store.bind(Binding(pod_key=pod.key(), node_name=node_name))

    cfg = load_config(None)
    cfg.extenders.append(ExtenderConfig(instance=CallableExtender(bind_fn=bind)))
    s = scheduler_from_config(store, cfg)
    store.create_pod(make_pod("p").req({"cpu": "100m"}).obj())
    s.run_until_settled()
    assert bound == {"default/p": "n1"}
    assert store.get_pod("default/p").spec.node_name == "n1"


def test_ignorable_extender_failure_is_tolerated():
    store = ClusterStore()
    store.create_node(make_node("n1").capacity({"cpu": "4", "memory": "8Gi", "pods": 10}).obj())

    def bad_filter(pod, nodes):
        from kubernetes_tpu.scheduler.extender import ExtenderError

        raise ExtenderError("down")

    cfg = load_config(None)
    cfg.extenders.append(
        ExtenderConfig(instance=CallableExtender(filter_fn=bad_filter, ignorable=True))
    )
    s = scheduler_from_config(store, cfg)
    store.create_pod(make_pod("p").req({"cpu": "100m"}).obj())
    s.run_until_settled()
    assert store.get_pod("default/p").spec.node_name == "n1"


def test_out_of_tree_plugin_registration():
    """app.WithPlugin (server.go:293): out-of-tree factory merged into the
    registry and enabled via profile config."""
    calls = []

    class VetoN1:
        def name(self):
            return "VetoN1"

        def filter(self, state, pod, node_info):
            calls.append(node_info.node.meta.name)
            from kubernetes_tpu.framework.interface import OK, Status

            if node_info.node.meta.name == "n1":
                return Status.unschedulable("vetoed")
            return OK

    raw = {
        "profiles": [
            {
                "schedulerName": "default-scheduler",
                "plugins": {"filter": {"enabled": [{"name": "VetoN1"}]}},
            }
        ]
    }
    store = ClusterStore()
    for i in range(1, 3):
        store.create_node(make_node(f"n{i}").capacity({"cpu": "4", "memory": "8Gi", "pods": 10}).obj())
    s = scheduler_from_config(
        store, raw=raw, out_of_tree_registry={"VetoN1": lambda h, a: VetoN1()}
    )
    store.create_pod(make_pod("p").req({"cpu": "100m"}).obj())
    s.run_until_settled()
    assert store.get_pod("default/p").spec.node_name == "n2"
    assert calls  # plugin actually ran
