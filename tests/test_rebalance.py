"""Continuous rebalancing — the SLO-guarded descheduler (ISSUE 18).

Unit tier: the packing-entropy scorer's bounds, the hysteresis trigger
band, per-wave migration budget + cooldown, migrate-then-reopen
(``uncordon_after``) completion, gang-atomic disruption gating, the SLO
guardrail breaker's trip/probe/heal ladder, and the ``/debug/rebalance``
dump shape.

Acceptance tier (ISSUE 18): the SchedulingReplay trace (diurnal curve,
burst storms, tenant-mix shift, churn) run A/B with rebalancing on vs
off on a FakeClock — post-churn packing efficiency must be measurably
better with the Rebalancer on while every tenant's e2e p99 stays within
the trend.py fence tolerance of the off run.
"""

import json

import numpy as np
import pytest

import jax.numpy as jnp

from kubernetes_tpu.controllers.rebalance import (
    Rebalancer, packing_entropy, score_from_snapshot)
from kubernetes_tpu.perf import TEST_CASES, run_workload
from kubernetes_tpu.perf.harness import Runner
from kubernetes_tpu.utils.clock import FakeClock


def _runner(nodes=6, clock=None):
    clock = clock or FakeClock()
    r = Runner(backend="oracle", now_fn=clock)
    r.create_nodes(count=nodes, zones=2,
                   capacity={"cpu": "4", "memory": "16Gi", "pods": 16})
    return r, clock


def _spawn(r, n, ns="default", prefix="rb", gang_size=0):
    """Create n pods (optionally gang members) and return their keys."""
    base = {"namespace": ns, "req": {"cpu": "200m", "memory": "512Mi"}}
    keys = []
    for j in range(n):
        params = (dict(base, gang_size=gang_size, _gang_ordinal=j)
                  if gang_size else dict(base))
        p = r._make_pod(prefix, params)
        r.store.create_pod(p)
        r._pod_counter += 1
        keys.append(p.key())
    return keys


def _settle(r, budget=600):
    sched = r.scheduler
    for _ in range(budget):
        if not sched.schedule_one():
            sched.queue.flush_backoff_completed()
            if len(sched.queue) == 0:
                break
    sched.cache.update_snapshot(sched.snapshot)


def _smear(r, keep_every=3):
    """Delete all but every ``keep_every``-th bound pod — the post-churn
    thin smear a week of elastic arrivals leaves behind."""
    bound = [p for p in r.store.pods.values() if p.spec.node_name]
    for i, p in enumerate(bound):
        if i % keep_every:
            r.store.delete_pod(p.key())
    r.scheduler.cache.update_snapshot(r.scheduler.snapshot)


class TestPackingEntropy:
    def test_even_spread_scores_one(self):
        req = jnp.full((8, 4), 10.0, jnp.float32)
        valid = jnp.ones(8, bool)
        mean, per_axis = packing_entropy(req, valid)
        assert float(mean) == pytest.approx(1.0, abs=1e-5)
        assert np.allclose(np.asarray(per_axis), 1.0, atol=1e-5)

    def test_consolidated_scores_zero(self):
        req = np.zeros((8, 4), np.float32)
        req[3] = 10.0  # everything on one node
        mean, _ = packing_entropy(jnp.asarray(req), jnp.ones(8, bool))
        assert float(mean) == pytest.approx(0.0, abs=1e-5)

    def test_dead_axes_excluded_from_mean(self):
        req = np.full((8, 4), 10.0, np.float32)
        req[:, 2] = 0.0  # nobody requests ephemeral: dead axis
        mean, per_axis = packing_entropy(jnp.asarray(req), jnp.ones(8, bool))
        assert float(mean) == pytest.approx(1.0, abs=1e-5)
        assert float(np.asarray(per_axis)[2]) == 0.0

    def test_invalid_rows_ignored(self):
        req = np.full((8, 4), 10.0, np.float32)
        valid = np.ones(8, bool)
        valid[4:] = False
        req[4:] = 77.0  # garbage on invalid rows must not matter
        mean, _ = packing_entropy(jnp.asarray(req), jnp.asarray(valid))
        assert float(mean) == pytest.approx(1.0, abs=1e-5)


class TestTriggerBand:
    def test_hysteresis_arm_and_disarm(self):
        r, _ = _runner(nodes=2)
        try:
            rb = Rebalancer(r.scheduler, entropy_high=0.9, entropy_low=0.7,
                            frag_high=0.6, frag_low=0.4)
            s = {"entropy": 0.85, "frag_max": 0.0}
            rb._update_trigger(s)
            assert not rb.armed  # below high water: never arms
            rb._update_trigger({"entropy": 0.95, "frag_max": 0.0})
            assert rb.armed  # crossed high water
            rb._update_trigger({"entropy": 0.75, "frag_max": 0.0})
            assert rb.armed  # inside the band: hysteresis holds the arm
            rb._update_trigger({"entropy": 0.65, "frag_max": 0.0})
            assert not rb.armed  # below low water on every axis: disarm
        finally:
            r.close()

    def test_frag_axis_arms_independently(self):
        r, _ = _runner(nodes=2)
        try:
            rb = Rebalancer(r.scheduler)
            rb._update_trigger({"entropy": 0.1, "frag_max": 0.9})
            assert rb.armed
            # frag recovered but entropy band not crossed low: stays armed
            rb._update_trigger({"entropy": 0.81, "frag_max": 0.0})
            assert rb.armed
        finally:
            r.close()


class TestMigrationWaves:
    def _armed_rb(self, r, clock, **kw):
        kw.setdefault("entropy_high", 0.05)  # any real spread arms
        kw.setdefault("entropy_low", 0.01)
        kw.setdefault("score_interval_s", 0.0)
        kw.setdefault("cooldown_s", 5.0)
        return Rebalancer(r.scheduler, now_fn=clock, **kw)

    def test_wave_respects_migration_budget_and_cooldown(self):
        r, clock = _runner(nodes=6)
        try:
            _spawn(r, 24)
            _settle(r)
            _smear(r)
            rb = self._armed_rb(r, clock, max_migrations_per_wave=3)
            out = rb.maybe_run(clock())
            assert out["ran"], out
            assert 0 < out["wave"]["evicted"] <= 3
            assert rb.waves_executed == 1
            assert rb.migrations == out["wave"]["evicted"]
            # victims are cordoned until their pods re-bind elsewhere
            assert rb.drain.pending_uncordons
            for name in rb.last_waves[-1]["nodes"]:
                assert r.store.nodes[name].spec.unschedulable
            # second tick inside the cooldown: no second wave
            out2 = rb.maybe_run(clock())
            assert not out2["ran"] and out2["reason"] == "cooldown"
            m = r.scheduler.smetrics
            assert m.rebalance_waves.labels("executed") == 1.0
            assert m.rebalance_migrations.labels() == float(rb.migrations)
            assert m.packing_entropy.labels() > 0.0
        finally:
            r.close()

    def test_densest_node_never_a_victim(self):
        r, clock = _runner(nodes=6)
        try:
            _spawn(r, 24)
            _settle(r)
            _smear(r)
            sched = r.scheduler
            by_occ = sorted(
                (ni for ni in sched.snapshot.list() if ni.pods),
                key=lambda ni: len(ni.pods))
            densest = by_occ[-1].node.meta.name
            rb = self._armed_rb(r, clock, max_migrations_per_wave=100)
            victims = rb._pick_victims()
            assert victims and densest not in victims
        finally:
            r.close()

    def test_uncordon_after_waits_for_rebind(self):
        r, clock = _runner(nodes=6)
        try:
            spawned = _spawn(r, 24)
            _settle(r)
            _smear(r)
            alive = [k for k in spawned if r.store.get_pod(k) is not None]
            rb = self._armed_rb(r, clock, max_migrations_per_wave=4)
            out = rb.maybe_run(clock())
            assert out["ran"]
            wave_nodes = list(rb.last_waves[-1]["nodes"])
            # evicted pods are back in the queue; nodes stay cordoned while
            # any of them is still unbound
            assert rb.drain.poll_pending_uncordons() == []
            _settle(r)  # re-binds land elsewhere: victims are cordoned
            reopened = rb.drain.poll_pending_uncordons()
            assert sorted(reopened) == sorted(wave_nodes)
            assert not rb.drain.pending_uncordons
            for name in wave_nodes:
                assert not r.store.nodes[name].spec.unschedulable
            # zero lost, zero double-bound: every pre-wave pod is bound
            # exactly once, and never onto a wave node it was evicted from
            for k in alive:
                pod = r.store.get_pod(k)
                assert pod is not None and pod.spec.node_name
                assert pod.spec.node_name not in wave_nodes
        finally:
            r.close()

    def test_gang_atomic_disruption_gate(self):
        r, clock = _runner(nodes=4)
        try:
            keys = _spawn(r, 4, prefix="gangrb", gang_size=4)
            _settle(r)
            pods = [r.store.get_pod(k) for k in keys]
            assert all(p is not None and p.spec.node_name for p in pods)
            rb = self._armed_rb(r, clock)
            # a gate that rejects ONE member must withhold the whole gang
            victim = pods[0].meta.name
            gated = rb.drain._gate_whole_gangs(
                pods, lambda p: p.meta.name != victim)
            assert gated == []
            assert rb.drain._gate_whole_gangs(pods, lambda p: True) == pods
        finally:
            r.close()


class TestSLOGuardrail:
    def _tripped(self, r, clock):
        """Arm a watch on tenant t1, then regress its p99 hard."""
        rb = Rebalancer(r.scheduler, now_fn=clock, breaker_threshold=1,
                        probe_interval_s=60.0, slo_min_samples=5)
        hist = r.scheduler.smetrics.tenant_e2e_duration
        for _ in range(10):
            hist.observe(0.01, "t1")
        rb._arm_slo_watch()
        assert "t1" in rb._slo_watch
        rb.waves_executed = 1  # guardrail only judges after a real wave
        for _ in range(10):
            hist.observe(5.0, "t1")
        rb._judge_slo()
        return rb, hist

    def test_regression_trips_breaker_open(self):
        r, clock = _runner(nodes=2)
        try:
            rb, _ = self._tripped(r, clock)
            assert rb.suspended
            assert rb.breaker.dump()["state"] == "open"
            assert r.scheduler.smetrics.rebalance_suspended.labels() == 1.0
            # an armed Rebalancer refuses waves while suspended
            _spawn(r, 6)
            _settle(r)
            rb.armed = True
            rb.score_interval_s = 0.0
            rb.cooldown_s = 0.0
            out = rb.maybe_run(clock())
            assert not out["ran"] and out["reason"] == "slo-suspended"
            assert r.scheduler.smetrics.rebalance_waves.labels(
                "suspended") == 1.0
        finally:
            r.close()

    def test_half_open_probe_heals_on_clean_window(self):
        r, clock = _runner(nodes=2)
        try:
            rb, hist = self._tripped(r, clock)
            # clean windows do NOT close an OPEN breaker before the probe
            for _ in range(10):
                hist.observe(0.01, "t1")
            rb._judge_slo()
            assert rb.breaker.dump()["state"] == "open"
            # past the probe interval the breaker half-opens one wave …
            clock.advance(61.0)
            assert rb.breaker.allow()
            assert rb.breaker.dump()["state"] == "half_open"
            # … and only a clean judged window then closes it
            for _ in range(10):
                hist.observe(0.01, "t1")
            rb._judge_slo()
            assert rb.breaker.dump()["state"] == "closed"
            assert not rb.suspended
            assert r.scheduler.smetrics.rebalance_suspended.labels() == 0.0
        finally:
            r.close()

    def test_short_window_not_judged(self):
        r, clock = _runner(nodes=2)
        try:
            rb = Rebalancer(r.scheduler, now_fn=clock, breaker_threshold=1,
                            slo_min_samples=50)
            hist = r.scheduler.smetrics.tenant_e2e_duration
            for _ in range(60):
                hist.observe(0.01, "t1")
            rb._arm_slo_watch()
            rb.waves_executed = 1
            for _ in range(5):  # 5 < slo_min_samples: too little evidence
                hist.observe(5.0, "t1")
            rb._judge_slo()
            assert rb.breaker.dump()["state"] == "closed"
        finally:
            r.close()


class TestDebugDump:
    def test_dump_shape_and_limit(self):
        r, clock = _runner(nodes=6)
        try:
            _spawn(r, 24)
            _settle(r)
            _smear(r)
            rb = Rebalancer(r.scheduler, now_fn=clock, entropy_high=0.05,
                            entropy_low=0.01, score_interval_s=0.0,
                            cooldown_s=0.0, max_migrations_per_wave=2)
            for _ in range(3):
                rb.maybe_run(clock())
                _settle(r)
                clock.advance(1.0)
            assert rb.waves_executed >= 2
            dump = rb.debug_dump(limit=1)
            assert dump["enabled"] and dump["waves_executed"] >= 2
            assert len(dump["last_waves"]) == 1
            assert dump["truncated"]["last_waves"] == rb.waves_executed
            assert set(dump["breaker"]) >= {"state", "opens"}
            assert {"entropy_high", "entropy_low",
                    "frag_high", "frag_low"} <= set(dump["bands"])
            json.dumps(dump)  # the /debug/rebalance contract: JSON-clean
        finally:
            r.close()


class TestReplayAcceptance:
    """The ISSUE 18 acceptance: trace-replay A/B on a FakeClock."""

    REBALANCE_KNOBS = {"cooldown_s": 1.0, "score_interval_s": 0.25,
                       "entropy_high": 0.80, "entropy_low": 0.60,
                       "max_migrations_per_wave": 8}

    def _run(self, rebalance):
        tc = TEST_CASES["SchedulingReplay"](
            nodes=24, rounds=6, scale=4, cycles_per_round=120,
            tick_s=0.05, rebalance=rebalance)
        return run_workload(tc, backend="oracle", now_fn=FakeClock())

    @pytest.fixture(scope="class")
    def ab(self):
        def pick(items, name):
            return [it for it in items if it.labels.get("Name") == name]

        on_items = self._run(self.REBALANCE_KNOBS)
        off_items = self._run(False)
        (on_inv,) = pick(on_items, "ReplayInvariants")
        (off_inv,) = pick(off_items, "ReplayInvariants")
        on_t = {it.labels["namespace"]: it.data
                for it in pick(on_items, "ReplayTenant")}
        off_t = {it.labels["namespace"]: it.data
                 for it in pick(off_items, "ReplayTenant")}
        return on_inv.data, off_inv.data, on_t, off_t

    def test_rebalancer_ran_and_converged(self, ab):
        on, off, _, _ = ab
        assert on["Waves"] > 0 and on["Migrations"] > 0
        assert off["Waves"] == 0 and off["Migrations"] == 0
        # every migrate-then-reopen wave completed: nothing left cordoned,
        # nothing parked in the queue at end of trace — zero lost pods
        assert on["PendingUncordons"] == 0
        assert on["PendingAtEnd"] == 0 and off["PendingAtEnd"] == 0
        assert not on["Suspended"]

    def test_packing_measurably_better_with_rebalancing(self, ab):
        on, off, _, _ = ab
        # steady-state packing efficiency (1 - mean second-half entropy):
        # the rebalanced trace must beat churn-decayed one-shot placement
        # by a real margin, not noise
        assert on["PackingEff"] > off["PackingEff"] + 0.005, (
            f"rebalancing on: {on['PackingEff']:.4f} "
            f"vs off: {off['PackingEff']:.4f}")
        assert on["FinalEntropy"] < off["FinalEntropy"]

    def test_no_tenant_p99_moved(self, ab):
        on, off, on_t, off_t = ab
        # the fence discipline (tools/trend.py workload_replay_tenant_p99_s,
        # 200% tolerance) plus a floor for FakeClock bucket granularity
        tol, floor = 2.0, 0.5
        assert set(on_t) == set(off_t)
        for ns, t_off in off_t.items():
            t_on = on_t[ns]
            if not t_on["E2eCount"] or not t_off["E2eCount"]:
                continue
            assert t_on["E2eP99"] <= t_off["E2eP99"] * (1 + tol) + floor, (
                f"tenant {ns} p99 moved: {t_on['E2eP99']:.3f}s on vs "
                f"{t_off['E2eP99']:.3f}s off")
        assert on["TenantP99Max"] <= off["TenantP99Max"] * (1 + tol) + floor
