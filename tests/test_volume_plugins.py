"""Volume plugin tests: VolumeZone, VolumeRestrictions (RWOP), NodeVolumeLimits
(CSINode limits), VolumeBinding (immediate-unbound, WaitForFirstConsumer
match+reserve+prebind, PV node affinity)."""

from kubernetes_tpu.api.types import (
    BINDING_WAIT_FOR_FIRST_CONSUMER,
    CSINode,
    ObjectMeta,
    PersistentVolume,
    PersistentVolumeClaim,
    RWOP,
    StorageClass,
)
from kubernetes_tpu.api.wrappers import make_node, make_pod
from kubernetes_tpu.apiserver.store import ClusterStore
from kubernetes_tpu.scheduler.scheduler import Scheduler


def mk_store(n_nodes=2, zone=None):
    store = ClusterStore()
    for i in range(n_nodes):
        nw = make_node(f"node-{i}").capacity({"cpu": "4", "memory": "8Gi", "pods": 10})
        if zone:
            nw.label("topology.kubernetes.io/zone", f"z{i}")
        store.create_node(nw.obj())
    return store


def pvc(name, sc="", pv="", modes=(), ns="default"):
    return PersistentVolumeClaim(
        meta=ObjectMeta(name=name, namespace=ns),
        storage_class=sc,
        bound_pv=pv,
        access_modes=tuple(modes),
    )


def test_volume_zone_filter():
    store = mk_store(zone=True)
    store.create_pv(PersistentVolume(
        meta=ObjectMeta(name="pv-a", labels={"topology.kubernetes.io/zone": "z1"}),
    ))
    store.create_pvc(pvc("claim-a", pv="pv-a"))
    s = Scheduler(store)
    store.create_pod(make_pod("p").req({"cpu": "100m"}).pvc("claim-a").obj())
    s.run_until_settled()
    assert store.get_pod("default/p").spec.node_name == "node-1"


def test_rwop_exclusivity():
    store = mk_store(n_nodes=1)
    store.create_pv(PersistentVolume(meta=ObjectMeta(name="pv-excl"), bound_pvc="default/excl"))
    store.create_pvc(pvc("excl", pv="pv-excl", modes=(RWOP,)))
    s = Scheduler(store)
    store.create_pod(make_pod("first").req({"cpu": "100m"}).pvc("excl").obj())
    s.run_until_settled()
    assert store.get_pod("default/first").spec.node_name == "node-0"
    store.create_pod(make_pod("second").req({"cpu": "100m"}).pvc("excl").obj())
    s.run_until_settled()
    assert store.get_pod("default/second").spec.node_name == ""


def test_node_volume_limits():
    store = mk_store(n_nodes=1)
    store.create_storage_class(StorageClass(meta=ObjectMeta(name="fast"), provisioner="csi.x"))
    store.create_csinode(CSINode(meta=ObjectMeta(name="node-0"), drivers={"csi.x": 2}))
    for i in range(3):
        store.create_pv(PersistentVolume(meta=ObjectMeta(name=f"pv-{i}"), storage_class="fast", bound_pvc=f"default/c{i}"))
        store.create_pvc(pvc(f"c{i}", sc="fast", pv=f"pv-{i}"))
    s = Scheduler(store)
    store.create_pod(make_pod("a").req({"cpu": "100m"}).pvc("c0").pvc("c1").obj())
    s.run_until_settled()
    assert store.get_pod("default/a").spec.node_name == "node-0"
    store.create_pod(make_pod("b").req({"cpu": "100m"}).pvc("c2").obj())
    s.run_until_settled()
    assert store.get_pod("default/b").spec.node_name == ""


def test_unbound_immediate_claim_blocks():
    store = mk_store(n_nodes=1)
    store.create_pvc(pvc("slow-claim"))  # no storage class => immediate, unbound
    s = Scheduler(store)
    store.create_pod(make_pod("p").req({"cpu": "100m"}).pvc("slow-claim").obj())
    s.run_until_settled()
    assert store.get_pod("default/p").spec.node_name == ""


def test_wait_for_first_consumer_binds_on_prebind():
    store = mk_store(n_nodes=2, zone=True)
    store.create_storage_class(StorageClass(
        meta=ObjectMeta(name="wffc"), provisioner="csi.x",
        volume_binding_mode=BINDING_WAIT_FOR_FIRST_CONSUMER,
    ))
    # one PV, only on node-1's zone
    store.create_pv(PersistentVolume(
        meta=ObjectMeta(name="pv-z1"),
        storage_class="wffc",
        capacity_bytes=10 << 30,
        node_affinity={"topology.kubernetes.io/zone": ("z1",)},
    ))
    store.create_pvc(pvc("data", sc="wffc"))
    s = Scheduler(store)
    store.create_pod(make_pod("p").req({"cpu": "100m"}).pvc("data").obj())
    s.run_until_settled()
    p = store.get_pod("default/p")
    assert p.spec.node_name == "node-1"  # only node whose zone has a PV
    assert store.get_pvc("default/data").bound_pv == "pv-z1"
    assert store.get_pv("pv-z1").bound_pvc == "default/data"


def test_bound_pv_node_affinity_conflict():
    store = mk_store(n_nodes=2, zone=True)
    store.create_pv(PersistentVolume(
        meta=ObjectMeta(name="pv-pinned"),
        node_affinity={"topology.kubernetes.io/zone": ("z0",)},
        bound_pvc="default/pinned",
    ))
    store.create_pvc(pvc("pinned", pv="pv-pinned"))
    s = Scheduler(store)
    store.create_pod(make_pod("p").req({"cpu": "100m"}).pvc("pinned").obj())
    s.run_until_settled()
    assert store.get_pod("default/p").spec.node_name == "node-0"


def test_rwop_cluster_wide_at_prefilter():
    """RWOP conflict rejects at PreFilter (UnschedulableAndUnresolvable) even
    on nodes not hosting the conflicting pod (volume_restrictions.go:149)."""
    store = mk_store(n_nodes=3)
    store.create_pv(PersistentVolume(meta=ObjectMeta(name="pv-x"), bound_pvc="default/excl"))
    store.create_pvc(pvc("excl", pv="pv-x", modes=(RWOP,)))
    s = Scheduler(store)
    store.create_pod(make_pod("first").req({"cpu": "100m"}).pvc("excl").obj())
    s.run_until_settled()
    store.create_pod(make_pod("second").req({"cpu": "100m"}).pvc("excl").obj())
    s.run_until_settled()
    second = store.get_pod("default/second")
    assert second.spec.node_name == ""
    # unresolvable ⇒ no preemption nomination either
    assert second.status.nominated_node_name == ""


def test_tpu_backend_batches_volume_pods_with_mask():
    """PVC pods ride the batched path (ops/volume_mask.py pre-pass + exact
    host verify of the chosen node) — VolumeZone semantics hold WITHOUT the
    sequential fallback (VERDICT r4 item 4)."""
    from kubernetes_tpu.backend.tpu_scheduler import TPUScheduler

    store = mk_store(n_nodes=2, zone=True)
    store.create_pv(PersistentVolume(
        meta=ObjectMeta(name="pv-a", labels={"topology.kubernetes.io/zone": "z1"}),
    ))
    store.create_pvc(pvc("claim-a", pv="pv-a"))
    s = TPUScheduler(store, batch_size=8)
    store.create_pod(make_pod("vp").req({"cpu": "100m"}).pvc("claim-a").obj())
    store.create_pod(make_pod("plain").req({"cpu": "100m"}).obj())
    s.run_until_settled()
    assert store.get_pod("default/vp").spec.node_name == "node-1"  # zone matched
    assert store.get_pod("default/plain").spec.node_name != ""
    assert s.fallback_scheduled == 0  # the mask kept it on the batch path


def test_tpu_backend_unscreenable_volume_pod_falls_back():
    """A pod whose claim the mask can't screen (missing PVC) keeps the
    sequential fallback path."""
    from kubernetes_tpu.backend.tpu_scheduler import TPUScheduler

    store = mk_store(n_nodes=2, zone=True)
    s = TPUScheduler(store, batch_size=8)
    store.create_pod(make_pod("ghost").req({"cpu": "100m"}).pvc("nope").obj())
    s.run_until_settled(max_cycles=20, flush=True)
    ghost = store.get_pod("default/ghost")
    assert ghost.spec.node_name == ""  # unresolvable claim never binds


def test_smallest_fitting_pv_chosen():
    store = mk_store(n_nodes=1)
    store.create_storage_class(StorageClass(
        meta=ObjectMeta(name="wffc"), provisioner="csi.x",
        volume_binding_mode=BINDING_WAIT_FOR_FIRST_CONSUMER,
    ))
    store.create_pv(PersistentVolume(meta=ObjectMeta(name="big"), storage_class="wffc", capacity_bytes=100 << 30))
    store.create_pv(PersistentVolume(meta=ObjectMeta(name="small"), storage_class="wffc", capacity_bytes=5 << 30))
    c = pvc("data", sc="wffc")
    c.requested_bytes = 1 << 30
    store.create_pvc(c)
    s = Scheduler(store)
    store.create_pod(make_pod("p").req({"cpu": "100m"}).pvc("data").obj())
    s.run_until_settled()
    assert store.get_pvc("default/data").bound_pv == "small"


class TestVolumeCapacityPriority:
    def test_score_prefers_tighter_fit(self):
        from kubernetes_tpu.api.types import (
            BINDING_WAIT_FOR_FIRST_CONSUMER, ObjectMeta, PersistentVolume,
            PersistentVolumeClaim, StorageClass,
        )
        from kubernetes_tpu.api.wrappers import make_node, make_pod
        from kubernetes_tpu.apiserver.store import ClusterStore
        from kubernetes_tpu.framework.interface import CycleState
        from kubernetes_tpu.framework.plugins.volume import VolumeBinding
        from kubernetes_tpu.framework.types import NodeInfo

        store = ClusterStore()
        store.create_storage_class(StorageClass(
            meta=ObjectMeta(name="wffc"),
            volume_binding_mode=BINDING_WAIT_FOR_FIRST_CONSUMER))
        # n1 has a tight 10GiB PV, n2 a loose 100GiB PV
        store.create_pv(PersistentVolume(
            meta=ObjectMeta(name="pv-tight"), storage_class="wffc",
            capacity_bytes=10 * 2**30, node_affinity={"host": ("n1",)}))
        store.create_pv(PersistentVolume(
            meta=ObjectMeta(name="pv-loose"), storage_class="wffc",
            capacity_bytes=100 * 2**30, node_affinity={"host": ("n2",)}))
        store.create_pvc(PersistentVolumeClaim(
            meta=ObjectMeta(name="claim"), storage_class="wffc",
            requested_bytes=9 * 2**30))
        pl = VolumeBinding(client=store, volume_capacity_priority=True)
        pod = make_pod("p").pvc("claim").obj()
        state = CycleState()
        _, st = pl.pre_filter(state, pod)
        assert st.is_success()
        n1 = NodeInfo(make_node("n1").label("host", "n1").obj())
        n2 = NodeInfo(make_node("n2").label("host", "n2").obj())
        assert pl.filter(state, pod, n1).is_success()
        assert pl.filter(state, pod, n2).is_success()
        s1, _ = pl.score_node(state, pod, n1)
        s2, _ = pl.score_node(state, pod, n2)
        assert s1 == 90 and s2 == 9  # tight fit wins

    def test_score_zero_when_gated_off(self):
        from kubernetes_tpu.apiserver.store import ClusterStore
        from kubernetes_tpu.framework.interface import CycleState
        from kubernetes_tpu.framework.plugins.volume import VolumeBinding
        from kubernetes_tpu.framework.types import NodeInfo
        from kubernetes_tpu.api.wrappers import make_node, make_pod

        pl = VolumeBinding(client=ClusterStore(), volume_capacity_priority=False)
        score, st = pl.score_node(CycleState(), make_pod("p").obj(),
                                  NodeInfo(make_node("n").obj()))
        assert score == 0 and st.is_success()
