"""Oracle ↔ batched-kernel parity on randomized clusters.

The scalar oracle plugins (tests/test_oracle_plugins.py pins them to reference
semantics) are the ground truth; every batched filter mask must match exactly
and every score within ±1 (float32 vs int64 arithmetic).
"""

import random

import numpy as np
import pytest

from kubernetes_tpu.api.types import LabelSelector, Requirement
from kubernetes_tpu.api.wrappers import make_node, make_pod
from kubernetes_tpu.framework.interface import CycleState, NodeScore
from kubernetes_tpu.framework.types import NodeInfo
from kubernetes_tpu.framework.plugins.basic import (
    NodeName,
    NodePorts,
    NodeUnschedulable,
    TaintToleration,
)
from kubernetes_tpu.framework.plugins.imagelocality import ImageLocality
from kubernetes_tpu.framework.plugins.nodeaffinity import NodeAffinity
from kubernetes_tpu.framework.plugins.noderesources import BalancedAllocation, Fit
from kubernetes_tpu.ops import filters, scores, select
from kubernetes_tpu.ops.encode import ClusterEncoder
from kubernetes_tpu.ops.schema import Capacities

ZONES = ["z0", "z1", "z2", "z3"]
DISKS = ["ssd", "hdd"]
IMAGES = [f"registry/app{i}:v1" for i in range(6)]


def random_cluster(rng: random.Random, n_nodes: int):
    infos = []
    for i in range(n_nodes):
        nw = (
            make_node(f"node-{i}")
            .capacity({
                "cpu": rng.choice(["2", "4", "8", "16"]),
                "memory": rng.choice(["4Gi", "8Gi", "32Gi"]),
                "pods": rng.choice([3, 10, 110]),
            })
            .label("zone", rng.choice(ZONES))
            .label("disk", rng.choice(DISKS))
            .label("idx", str(i))
        )
        if rng.random() < 0.15:
            nw.unschedulable()
        for _ in range(rng.randint(0, 2)):
            nw.taint(
                rng.choice(["dedicated", "team"]),
                rng.choice(["a", "b", ""]),
                rng.choice(["NoSchedule", "PreferNoSchedule", "NoExecute"]),
            )
        for img in rng.sample(IMAGES, rng.randint(0, 3)):
            nw.image(img, rng.randint(20, 900) * 1024 * 1024)
        ni = NodeInfo(nw.obj())
        for j in range(rng.randint(0, 3)):
            pw = make_pod(f"existing-{i}-{j}").req(
                {"cpu": rng.choice(["100m", "500m", "1"]), "memory": rng.choice(["64Mi", "1Gi"])}
            )
            if rng.random() < 0.3:
                pw.host_port(rng.choice([80, 443, 8080]), rng.choice(["TCP", "UDP"]))
            ni.add_pod(pw.obj())
        infos.append(ni)
    return infos


def random_pods(rng: random.Random, n_pods: int, n_nodes: int):
    pods = []
    for i in range(n_pods):
        pw = make_pod(f"pending-{i}").req(
            {"cpu": rng.choice(["100m", "1", "2", "6"]), "memory": rng.choice(["128Mi", "1Gi", "16Gi"])}
        ).priority(rng.randint(0, 10))
        r = rng.random()
        if r < 0.15:
            pw.node_selector({"disk": rng.choice(DISKS)})
        elif r < 0.3:
            pw.node_affinity_in("zone", rng.sample(ZONES, rng.randint(1, 2)))
        elif r < 0.4:
            pw.node_affinity_not_in("zone", rng.sample(ZONES, rng.randint(1, 2)))
        elif r < 0.45:
            pw.pod.spec.affinity = None
            from kubernetes_tpu.api.types import NodeSelectorTerm
            pw._add_required_node_term(
                NodeSelectorTerm(match_expressions=(Requirement("idx", "Gt", (str(rng.randint(0, n_nodes)),)),))
            )
        elif r < 0.5:
            pw.node(f"node-{rng.randint(0, n_nodes + 2)}")  # sometimes nonexistent
        if rng.random() < 0.3:
            pw.preferred_node_affinity(rng.randint(1, 50), "zone", [rng.choice(ZONES)])
            pw.preferred_node_affinity(rng.randint(1, 50), "disk", [rng.choice(DISKS)])
        if rng.random() < 0.3:
            pw.toleration(
                key=rng.choice(["dedicated", "team"]),
                operator=rng.choice(["Equal", "Exists"]),
                value=rng.choice(["a", "b", ""]),
                effect=rng.choice(["NoSchedule", "NoExecute", "PreferNoSchedule", ""]),
            )
        if rng.random() < 0.1:
            pw.toleration(operator="Exists")  # tolerate everything
        if rng.random() < 0.25:
            pw.host_port(rng.choice([80, 443, 8080]), rng.choice(["TCP", "UDP"]))
        if rng.random() < 0.4:
            pw.pod.spec.containers[0].image = rng.choice(IMAGES)
        pods.append(pw.obj())
    return pods


ORACLES = {
    "NodeUnschedulable": NodeUnschedulable(),
    "NodeName": NodeName(),
    "TaintToleration": TaintToleration(),
    "NodeAffinity": NodeAffinity(),
    "NodePorts": NodePorts(),
    "NodeResourcesFit": Fit(),
}


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_filter_parity(seed):
    rng = random.Random(seed)
    infos = random_cluster(rng, 24)
    pods = random_pods(rng, 16, 24)
    enc = ClusterEncoder(Capacities(nodes=32, pods=16, value_words=32))
    nt = enc.encode_snapshot(infos)
    pb, et = enc.encode_pods(pods)
    out = filters.run_all_filters(pb, et, nt)

    for name, plugin in ORACLES.items():
        kernel_mask = np.asarray(out["masks"][name])
        for p, pod in enumerate(pods):
            state = CycleState()
            if hasattr(plugin, "pre_filter"):
                plugin.pre_filter(state, pod)
            for ni in infos:
                slot = enc.node_slots[ni.node.meta.name]
                want = plugin.filter(state, pod, ni).is_success()
                got = bool(kernel_mask[p, slot])
                assert got == want, (
                    f"seed={seed} plugin={name} pod={pod.meta.name} node={ni.node.meta.name}: "
                    f"kernel={got} oracle={want}"
                )


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_score_parity(seed):
    rng = random.Random(seed + 100)
    infos = random_cluster(rng, 16)
    pods = random_pods(rng, 12, 16)
    enc = ClusterEncoder(Capacities(nodes=32, pods=16, value_words=32))
    nt = enc.encode_snapshot(infos)
    pb, et = enc.encode_pods(pods)
    out = filters.run_all_filters(pb, et, nt)
    feasible = out["feasible"]

    kernels = {
        "NodeResourcesFit": scores.score_least_allocated(pb, nt),
        "NodeResourcesBalancedAllocation": scores.score_balanced_allocation(pb, nt),
        "TaintToleration": scores.normalize_default(scores.score_taint_toleration(pb, nt), feasible, reverse=True),
        "NodeAffinity": scores.normalize_default(
            scores.score_node_affinity(pb, et, nt, out["expr_match"]), feasible, reverse=False
        ),
        "ImageLocality": scores.score_image_locality(pb, nt),
    }
    kernels = {k: np.asarray(v) for k, v in kernels.items()}
    feasible_np = np.asarray(feasible)

    snapshot_fn = lambda: infos  # noqa: E731
    oracle_plugins = {
        "NodeResourcesFit": Fit(),
        "NodeResourcesBalancedAllocation": BalancedAllocation(),
        "TaintToleration": TaintToleration(),
        "NodeAffinity": NodeAffinity(),
        "ImageLocality": ImageLocality(snapshot_fn=snapshot_fn),
    }

    for p, pod in enumerate(pods):
        feas_nodes = [ni for ni in infos if feasible_np[p, enc.node_slots[ni.node.meta.name]]]
        if not feas_nodes:
            continue
        for name, plugin in oracle_plugins.items():
            state = CycleState()
            if hasattr(plugin, "pre_score"):
                plugin.pre_score(state, pod, [ni.node for ni in feas_nodes])
            node_scores = []
            for ni in feas_nodes:
                s, _ = plugin.score_node(state, pod, ni)
                node_scores.append(NodeScore(ni.node.meta.name, s))
            ext = plugin.score_extensions()
            if ext is not None:
                ext.normalize_score(state, pod, node_scores)
            for ns in node_scores:
                slot = enc.node_slots[ns.name]
                got = float(kernels[name][p, slot])
                assert abs(got - ns.score) <= 1.001, (
                    f"seed={seed} plugin={name} pod={pod.meta.name} node={ns.name}: "
                    f"kernel={got} oracle={ns.score}"
                )


def test_select_host_tie_break_uniform():
    import jax

    total = np.zeros((1, 8), np.float32)
    total[0, [2, 5, 7]] = 100.0
    feasible = np.ones((1, 8), bool)
    picks = set()
    for i in range(64):
        idx, best, ok = select.select_host(total, feasible, jax.random.PRNGKey(i))
        assert bool(ok[0]) and float(best[0]) == 100.0
        picks.add(int(idx[0]))
    assert picks == {2, 5, 7}  # all maxima reachable, only maxima picked


def test_select_host_infeasible():
    import jax

    total = np.zeros((2, 4), np.float32)
    feasible = np.zeros((2, 4), bool)
    feasible[1, 3] = True
    idx, _, ok = select.select_host(total, feasible, jax.random.PRNGKey(0))
    assert int(idx[0]) == -1 and not bool(ok[0])
    assert int(idx[1]) == 3 and bool(ok[1])


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_encode_template_cache_parity(seed):
    """Cold-cache and warm-cache encodes of the same pods must be
    byte-identical (the template cache only skips recomputation)."""
    import dataclasses

    from kubernetes_tpu.ops.encode import ClusterEncoder
    from kubernetes_tpu.ops.schema import Capacities

    rng = random.Random(seed)
    infos = random_cluster(rng, 24)
    pods = random_pods(rng, 32, 24)

    enc = ClusterEncoder(Capacities(nodes=32, pods=32, value_words=32))
    enc.encode_snapshot(infos)
    cold_b, cold_t = enc.encode_pods(pods)
    assert enc._pod_templates  # shapes were cached
    warm_b, warm_t = enc.encode_pods(pods)

    for f in dataclasses.fields(cold_b):
        a, b = getattr(cold_b, f.name), getattr(warm_b, f.name)
        assert np.array_equal(np.asarray(a), np.asarray(b)), f.name
    for f in dataclasses.fields(cold_t):
        a, b = getattr(cold_t, f.name), getattr(warm_t, f.name)
        assert np.array_equal(np.asarray(a), np.asarray(b)), f.name


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_packed_result_block_parity(seed):
    """The packed result block (ISSUE 5) must reproduce node_idx and
    first_fail EXACTLY after the host-side unpack — this pins the
    lax.bitcast_convert_type ↔ numpy-view byte order the single-transfer
    commit depends on, end to end through a real schedule_batch call."""
    import jax

    from kubernetes_tpu.backend.batch import schedule_batch, unpack_result_block
    from kubernetes_tpu.backend.sig_table import SigTable

    rng = random.Random(seed)
    infos = random_cluster(rng, 20)
    pods = random_pods(rng, 16, 20)
    enc = ClusterEncoder(Capacities(nodes=32, pods=16, value_words=32))
    sig = SigTable(enc)
    nt = enc.encode_snapshot(infos)
    pb, et = enc.encode_pods(pods)
    tb = sig.encode_topo(pods)
    tc = sig.topo_counts()
    res = schedule_batch(pb, et, nt, tc, tb, jax.random.PRNGKey(seed),
                         topo_enabled=False)
    assert res.packed is not None
    node_idx, ff, slice_words, quota_words = unpack_result_block(
        res.packed, nt.capacity)
    assert np.array_equal(node_idx, np.asarray(res.node_idx))
    assert np.array_equal(ff, np.asarray(res.first_fail))
    assert slice_words is None  # no slice gangs -> no verdict column
    assert quota_words is None  # no screened namespaces -> no quota column
