"""Informers over REST (VERDICT r3 §2.5 partial): the same Reflector/
DeltaFIFO/SharedInformer stack running against the HTTP apiserver through
client/rest.py APIClient — the reference's client-go topology, including
watch streaming, resourceVersion resume, and relist-on-expiry."""

import time

import pytest

from kubernetes_tpu.api.wrappers import make_node, make_pod
from kubernetes_tpu.apiserver.http import serve_api, shutdown_api
from kubernetes_tpu.apiserver.store import ClusterStore
from kubernetes_tpu.client.informer import SharedInformerFactory
from kubernetes_tpu.client.rest import APIClient


def _wait(cond, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.02)
    return False


class TestRESTInformers:
    def test_list_watch_and_handlers_over_http(self):
        store = ClusterStore()
        store.create_node(make_node("n1").capacity({"cpu": "4"}).obj())
        server, port = serve_api(store)
        try:
            client = APIClient(f"http://127.0.0.1:{port}")
            factory = SharedInformerFactory(client)
            inf = factory.informer_for("Node")
            events = []
            inf.add_event_handler(lambda ev, old, new: events.append(
                (ev, (new or old).meta.name)))
            inf.start()
            assert _wait(lambda: inf.pump() or ("add", "n1") in events)
            # live watch: a node created AFTER the informer synced arrives
            store.create_node(make_node("n2").capacity({"cpu": "4"}).obj())
            assert _wait(lambda: inf.pump() or ("add", "n2") in events)
            assert {"n1", "n2"} <= {inf.get(k).meta.name
                                    for k in ("n1", "n2")
                                    if inf.get(k) is not None}
        finally:
            shutdown_api(server)

    def test_pod_informer_sees_updates_and_deletes(self):
        store = ClusterStore()
        server, port = serve_api(store)
        try:
            client = APIClient(f"http://127.0.0.1:{port}")
            factory = SharedInformerFactory(client)
            inf = factory.informer_for("Pod")
            seen = []
            inf.add_event_handler(lambda ev, old, new: seen.append(ev))
            inf.start()
            inf.pump()
            store.create_pod(make_pod("w").req({"cpu": "1"}).obj())
            assert _wait(lambda: inf.pump() or "add" in seen)
            pod = store.get_pod("default/w").clone()
            pod.status.phase = "Running"
            store.update_pod(pod)
            assert _wait(lambda: inf.pump() or "update" in seen)
            store.delete_pod("default/w")
            assert _wait(lambda: inf.pump() or "delete" in seen)
            assert inf.get("default/w") is None
        finally:
            shutdown_api(server)

    def test_scheduler_over_rest_informers(self):
        """The reference topology end-to-end: a scheduler whose informers
        list/watch the apiserver over HTTP while it WRITES through the store
        it was given (here the same store object — the read path is what
        crosses the wire)."""
        store = ClusterStore()
        for i in range(4):
            store.create_node(
                make_node(f"n{i}").capacity({"cpu": "8", "memory": "16Gi",
                                             "pods": 20}).obj())
        server, port = serve_api(store)
        try:
            client = APIClient(f"http://127.0.0.1:{port}")
            factory = SharedInformerFactory(client)
            node_inf = factory.informer_for("Node")
            node_inf.start()
            assert _wait(lambda: node_inf.pump() or
                         node_inf.get("n3") is not None)
            # informer cache state matches the server truth
            for i in range(4):
                assert node_inf.get(f"n{i}") is not None
        finally:
            shutdown_api(server)
