"""Certificate/security control loops (controllermanager.go:412 tail —
the last missing initializers): CSR approve→sign→clean lifecycle,
clusterrole aggregation, bootstrap token cleaner/signer, PV expander."""

import dataclasses

from kubernetes_tpu.api.types import (
    SECRET_TYPE_BOOTSTRAP_TOKEN,
    CertificateSigningRequest,
    ConfigMap,
    ObjectMeta,
    PersistentVolume,
    PersistentVolumeClaim,
    Secret,
    StorageClass,
)
from kubernetes_tpu.apiserver.auth import ClusterRole, PolicyRule
from kubernetes_tpu.apiserver.store import ClusterStore
from kubernetes_tpu.client.informer import SharedInformerFactory
from kubernetes_tpu.controllers.certificates import KUBELET_CLIENT_SIGNER
from kubernetes_tpu.controllers.manager import ControllerManager


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


def make_manager(store, controllers, now_fn=None):
    return ControllerManager(store, factory=SharedInformerFactory(store),
                             controllers=controllers,
                             now_fn=now_fn or FakeClock())


def _csr(name="node-csr", signer=KUBELET_CLIENT_SIGNER,
         username="system:node:n1", usages=("client auth",), **kw):
    return CertificateSigningRequest(
        meta=ObjectMeta(name=name), signer_name=signer, username=username,
        usages=tuple(usages), request="blob", **kw)


class TestCSRChain:
    def test_kubelet_client_csr_approved_and_signed(self):
        store = ClusterStore()
        m = make_manager(store, ["csrapproving", "csrsigning"])
        store.create_object("CertificateSigningRequest", _csr())
        m.settle()
        csr = store.csrs["node-csr"]
        assert csr.approved and "AutoApproved" in csr.approval_reason
        assert csr.certificate.startswith("-----BEGIN CERTIFICATE-----")

    def test_non_node_csr_not_auto_approved(self):
        store = ClusterStore()
        m = make_manager(store, ["csrapproving", "csrsigning"])
        store.create_object("CertificateSigningRequest",
                            _csr(name="user-csr", username="alice", groups=()))
        m.settle()
        csr = store.csrs["user-csr"]
        assert not csr.approved and not csr.certificate

    def test_denied_csr_never_signed(self):
        store = ClusterStore()
        m = make_manager(store, ["csrsigning"])
        store.create_object("CertificateSigningRequest",
                            _csr(name="bad", approved=True, denied=True))
        m.settle()
        assert not store.csrs["bad"].certificate

    def test_cleaner_drops_stale_pending_and_old_issued(self):
        store = ClusterStore()
        clock = FakeClock(10_000.0)
        m = make_manager(store, ["csrcleaner"], now_fn=clock)
        pending = _csr(name="stale-pending")
        store.create_object("CertificateSigningRequest", pending)
        store.csrs["stale-pending"].meta.creation_timestamp = 100.0  # old
        issued = _csr(name="old-issued", approved=True,
                      certificate="cert", issued_at=100.0)
        store.create_object("CertificateSigningRequest", issued)
        fresh = _csr(name="fresh")
        store.create_object("CertificateSigningRequest", fresh)
        store.csrs["fresh"].meta.creation_timestamp = clock()  # just created
        clock.t = 10_000.0 + 90_000.0  # beyond the 24h issued TTL
        store.csrs["fresh"].meta.creation_timestamp = clock() - 10.0
        m.settle()
        assert "stale-pending" not in store.csrs
        assert "old-issued" not in store.csrs
        assert "fresh" in store.csrs


class TestClusterRoleAggregation:
    def test_rules_union_from_matching_roles(self):
        store = ClusterStore()
        m = make_manager(store, ["clusterrole-aggregation"])
        store.create_object("ClusterRole", ClusterRole(
            meta=ObjectMeta(name="view-pods",
                            labels={"rbac.example.com/aggregate-to-view": "true"}),
            rules=(PolicyRule(verbs=("get", "list"), resources=("Pod",)),)))
        store.create_object("ClusterRole", ClusterRole(
            meta=ObjectMeta(name="view-services",
                            labels={"rbac.example.com/aggregate-to-view": "true"}),
            rules=(PolicyRule(verbs=("get",), resources=("Service",)),)))
        store.create_object("ClusterRole", ClusterRole(
            meta=ObjectMeta(name="view"),
            aggregation_selectors=({"rbac.example.com/aggregate-to-view": "true"},)))
        m.settle()
        view = store.cluster_roles["view"]
        resources = {r for rule in view.rules for r in rule.resources}
        assert resources == {"Pod", "Service"}

    def test_new_matching_role_feeds_aggregate(self):
        store = ClusterStore()
        m = make_manager(store, ["clusterrole-aggregation"])
        store.create_object("ClusterRole", ClusterRole(
            meta=ObjectMeta(name="edit"),
            aggregation_selectors=({"aggregate-to-edit": "true"},)))
        m.settle()
        assert store.cluster_roles["edit"].rules == ()
        store.create_object("ClusterRole", ClusterRole(
            meta=ObjectMeta(name="edit-jobs", labels={"aggregate-to-edit": "true"}),
            rules=(PolicyRule(verbs=("*",), resources=("Job",)),)))
        m.settle()
        assert any("Job" in r.resources for r in store.cluster_roles["edit"].rules)


class TestBootstrapTokens:
    def test_token_cleaner_deletes_expired(self):
        store = ClusterStore()
        clock = FakeClock(5000.0)
        m = make_manager(store, ["tokencleaner"], now_fn=clock)
        store.create_object("Secret", Secret(
            meta=ObjectMeta(name="bootstrap-token-old", namespace="kube-system"),
            type=SECRET_TYPE_BOOTSTRAP_TOKEN,
            data={"token-id": "old", "expiration": "4000"}))
        store.create_object("Secret", Secret(
            meta=ObjectMeta(name="bootstrap-token-live", namespace="kube-system"),
            type=SECRET_TYPE_BOOTSTRAP_TOKEN,
            data={"token-id": "live", "expiration": "9000"}))
        m.settle()
        assert "kube-system/bootstrap-token-old" not in store.secrets
        assert "kube-system/bootstrap-token-live" in store.secrets

    def test_bootstrapsigner_signs_cluster_info(self):
        store = ClusterStore()
        m = make_manager(store, ["bootstrapsigner"])
        store.create_object("ConfigMap", ConfigMap(
            meta=ObjectMeta(name="cluster-info", namespace="kube-system"),
            data={"kubeconfig": "apiVersion: v1\nclusters: []\n"}))
        store.create_object("Secret", Secret(
            meta=ObjectMeta(name="bootstrap-token-ab12", namespace="kube-system"),
            type=SECRET_TYPE_BOOTSTRAP_TOKEN,
            data={"token-id": "ab12", "token-secret": "s3cr3t"}))
        m.settle()
        cm = store.config_maps["kube-system/cluster-info"]
        assert "jws-kubeconfig-ab12" in cm.data
        # token deleted → signature removed
        store.delete_object("Secret", "kube-system/bootstrap-token-ab12")
        m.settle()
        cm = store.config_maps["kube-system/cluster-info"]
        assert "jws-kubeconfig-ab12" not in cm.data


class TestPVExpander:
    def test_pv_grows_when_class_allows(self):
        store = ClusterStore()
        m = make_manager(store, ["persistentvolume-expander"])
        store.create_storage_class(StorageClass(
            meta=ObjectMeta(name="fast"), allow_volume_expansion=True))
        store.create_pv(PersistentVolume(
            meta=ObjectMeta(name="pv1"), capacity_bytes=1 << 30,
            storage_class="fast", bound_pvc="default/c1"))
        store.create_pvc(PersistentVolumeClaim(
            meta=ObjectMeta(name="c1"), storage_class="fast",
            bound_pv="pv1", requested_bytes=2 << 30))
        m.settle()
        assert store.pvs["pv1"].capacity_bytes == 2 << 30

    def test_no_growth_without_expansion_flag(self):
        store = ClusterStore()
        m = make_manager(store, ["persistentvolume-expander"])
        store.create_storage_class(StorageClass(
            meta=ObjectMeta(name="rigid"), allow_volume_expansion=False))
        store.create_pv(PersistentVolume(
            meta=ObjectMeta(name="pv1"), capacity_bytes=1 << 30,
            storage_class="rigid", bound_pvc="default/c1"))
        store.create_pvc(PersistentVolumeClaim(
            meta=ObjectMeta(name="c1"), storage_class="rigid",
            bound_pv="pv1", requested_bytes=2 << 30))
        m.settle()
        assert store.pvs["pv1"].capacity_bytes == 1 << 30
