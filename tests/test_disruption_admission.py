"""Disruption controller (live PDB status) and the round-3 admission
plugins: LimitRanger, DefaultTolerationSeconds, PodNodeSelector — plus
preemption consuming controller-maintained disruption budgets."""

import pytest

from kubernetes_tpu.api.types import (
    LabelSelector,
    LimitRange,
    LimitRangeItem,
    Namespace,
    ObjectMeta,
    PodDisruptionBudget,
    Taint,
)
from kubernetes_tpu.api.wrappers import make_node, make_pod
from kubernetes_tpu.apiserver.admission import AdmissionError
from kubernetes_tpu.apiserver.store import ClusterStore
from kubernetes_tpu.client.informer import SharedInformerFactory
from kubernetes_tpu.controllers import ControllerManager
from kubernetes_tpu.utils.clock import FakeClock


def make_manager(store, controllers=None):
    return ControllerManager(store, factory=SharedInformerFactory(store),
                             controllers=controllers, now_fn=FakeClock())


def _pdb(name="pdb", min_available=None, max_unavailable=None, labels=None):
    return PodDisruptionBudget(
        meta=ObjectMeta(name=name),
        selector=LabelSelector(match_labels=labels or {"app": "web"}),
        min_available=min_available, max_unavailable=max_unavailable)


class TestDisruptionController:
    def test_status_from_min_available(self):
        store = ClusterStore()
        m = make_manager(store, ["disruption"])
        store.create_object("PodDisruptionBudget", _pdb(min_available=3))
        for i in range(5):
            store.create_pod(
                make_pod(f"w{i}").req({"cpu": "100m"}).label("app", "web")
                .node(f"n{i}").obj())
        m.settle()
        pdb = next(iter(store.pdbs.values()))
        assert pdb.expected_pods == 5
        assert pdb.current_healthy == 5
        assert pdb.desired_healthy == 3
        assert pdb.disruptions_allowed == 2

    def test_status_tracks_pod_deletes_and_percentages(self):
        store = ClusterStore()
        m = make_manager(store, ["disruption"])
        store.create_object("PodDisruptionBudget", _pdb(max_unavailable="50%"))
        for i in range(4):
            store.create_pod(
                make_pod(f"w{i}").req({"cpu": "100m"}).label("app", "web")
                .node(f"n{i}").obj())
        m.settle()
        pdb = next(iter(store.pdbs.values()))
        assert pdb.expected_pods == 4
        assert pdb.desired_healthy == 2  # 4 - ceil(50% of 4)
        assert pdb.disruptions_allowed == 2
        store.delete_pod("default/w0")
        m.settle()
        pdb = next(iter(store.pdbs.values()))  # status writes clone the PDB
        assert pdb.expected_pods == 3
        assert pdb.desired_healthy == 1  # 3 - ceil(1.5)
        assert pdb.disruptions_allowed == 2

    def test_unbound_pods_not_healthy(self):
        store = ClusterStore()
        m = make_manager(store, ["disruption"])
        store.create_object("PodDisruptionBudget", _pdb(min_available=1))
        store.create_pod(make_pod("pending").req({"cpu": "100m"}).label("app", "web").obj())
        m.settle()
        pdb = next(iter(store.pdbs.values()))
        assert pdb.expected_pods == 1
        assert pdb.current_healthy == 0
        assert pdb.disruptions_allowed == 0


class TestAdmissionPlugins:
    def test_limit_ranger_defaults_then_quota_sees_them(self):
        store = ClusterStore()
        store.create_object("LimitRange", LimitRange(
            meta=ObjectMeta(name="lr"),
            limits=(LimitRangeItem(
                default_request={"cpu": "200m", "memory": "256Mi"},
                max={"cpu": "1"}),)))
        store.create_pod(make_pod("defaulted").obj())
        p = store.get_pod("default/defaulted")
        assert p.spec.containers[0].requests["cpu"] == "200m"
        assert p.resource_request()["cpu"] == 200

    def test_limit_ranger_rejects_over_max(self):
        store = ClusterStore()
        store.create_object("LimitRange", LimitRange(
            meta=ObjectMeta(name="lr"),
            limits=(LimitRangeItem(max={"cpu": "1"}),)))
        with pytest.raises(AdmissionError, match="exceeds max"):
            store.create_pod(make_pod("big").req({"cpu": "2"}).obj())

    def test_default_toleration_seconds(self):
        store = ClusterStore()
        store.create_pod(make_pod("p").req({"cpu": "100m"}).obj())
        p = store.get_pod("default/p")
        assert any(
            t.tolerates(Taint(key="node.kubernetes.io/not-ready", effect="NoExecute"))
            for t in p.spec.tolerations)
        assert any(
            t.tolerates(Taint(key="node.kubernetes.io/unreachable", effect="NoExecute"))
            for t in p.spec.tolerations)

    def test_pod_node_selector_merge_and_conflict(self):
        store = ClusterStore()
        store.create_namespace(Namespace(meta=ObjectMeta(
            name="team-a",
            annotations={"scheduler.alpha.kubernetes.io/node-selector": "tier=gold"})))
        pw = make_pod("p").req({"cpu": "100m"})
        pod = pw.obj()
        pod.meta.namespace = "team-a"
        store.create_pod(pod)
        assert store.get_pod("team-a/p").spec.node_selector["tier"] == "gold"

        bad = make_pod("q").req({"cpu": "100m"}).obj()
        bad.meta.namespace = "team-a"
        bad.spec.node_selector["tier"] = "bronze"
        with pytest.raises(AdmissionError, match="conflicts"):
            store.create_pod(bad)

    def test_quota_charged_via_create_object(self):
        """ADVICE r2 low #3: create_object('Pod', ...) must charge quota like
        create_pod."""
        from kubernetes_tpu.api.types import ResourceQuota

        store = ClusterStore()
        store.create_object("ResourceQuota", ResourceQuota(
            meta=ObjectMeta(name="rq"), hard={"pods": 1}))
        store.create_object("Pod", make_pod("one").req({"cpu": "100m"}).obj())
        with pytest.raises(AdmissionError, match="exceeded quota"):
            store.create_object("Pod", make_pod("two").req({"cpu": "100m"}).obj())


class TestPreemptionWithLiveBudgets:
    def test_preemption_prefers_node_with_disruption_budget(self):
        """Two preemption candidates; victims on one are PDB-protected with
        zero remaining budget, the other's PDB still has headroom — the
        5-criteria selection must prefer the budgeted node (criterion 1)."""
        from kubernetes_tpu.scheduler import Scheduler

        store = ClusterStore()
        m = make_manager(store, ["disruption"])
        sched = Scheduler(store)
        for name in ("tight", "roomy"):
            store.create_node(
                make_node(name).capacity({"cpu": "2", "memory": "4Gi", "pods": 10}).obj())
        # tight: victim protected by a zero-budget PDB (minAvailable = count)
        store.create_object("PodDisruptionBudget", _pdb(
            "pdb-tight", min_available=1, labels={"group": "tight"}))
        # roomy: PDB with slack
        store.create_object("PodDisruptionBudget", _pdb(
            "pdb-roomy", min_available=0, labels={"group": "roomy"}))
        v1 = make_pod("v-tight").req({"cpu": "1500m"}).label("group", "tight").priority(0).obj()
        v1.spec.node_name = "tight"
        store.create_pod(v1)
        v2 = make_pod("v-roomy").req({"cpu": "1500m"}).label("group", "roomy").priority(0).obj()
        v2.spec.node_name = "roomy"
        store.create_pod(v2)
        m.settle()
        assert store.pdbs["default/pdb-tight"].disruptions_allowed == 0
        assert store.pdbs["default/pdb-roomy"].disruptions_allowed == 1

        store.create_pod(
            make_pod("preemptor").req({"cpu": "1500m"}).priority(100).obj())
        sched.run_until_settled()
        objs, _ = store.list_objects("Pod")
        names = {p.meta.name for p in objs}
        # the roomy victim was evicted; the protected one survived
        assert "v-tight" in names
        assert "v-roomy" not in names
