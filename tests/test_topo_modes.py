"""Topology program modes: the hostname fast path and the compact-domain
general path must decide identically to the full-domain general program."""

import jax
import numpy as np

from kubernetes_tpu.api.types import LabelSelector
from kubernetes_tpu.api.wrappers import make_node, make_pod
from kubernetes_tpu.apiserver import ClusterStore
from kubernetes_tpu.backend import TPUScheduler
from kubernetes_tpu.backend.batch import schedule_batch
from kubernetes_tpu.backend.sig_table import SigTable
from kubernetes_tpu.framework.types import NodeInfo
from kubernetes_tpu.ops.encode import ClusterEncoder
from kubernetes_tpu.ops.schema import Capacities


def _hostname_inputs(n_nodes=16, n_pods=6):
    """Mutually anti-affine + self-spread pods on the hostname topology."""
    infos = []
    for i in range(n_nodes):
        nw = make_node(f"n{i}").capacity({"cpu": "8", "memory": "16Gi", "pods": 10})
        infos.append(NodeInfo(nw.obj()))
    enc = ClusterEncoder(Capacities(nodes=n_nodes, pods=n_pods, value_words=32))
    sig = SigTable(enc)
    nt = enc.encode_snapshot(infos)
    sel = LabelSelector(match_labels={"app": "x"})
    pods = []
    for i in range(n_pods):
        pw = (make_pod(f"p{i}").req({"cpu": "1", "memory": "1Gi"}).label("app", "x")
              .spread_constraint(2, "kubernetes.io/hostname", selector=sel))
        if i % 2 == 0:
            pw.pod_affinity("kubernetes.io/hostname", sel, anti=True)
        pods.append(pw.obj())
    pb, et = enc.encode_pods(pods)
    tb = sig.encode_topo(pods)
    tc = sig.topo_counts()
    host_slot = enc.key_slot("kubernetes.io/hostname")
    return pb, et, nt, tc, tb, host_slot


def test_host_mode_matches_general_mode():
    pb, et, nt, tc, tb, host_slot = _hostname_inputs()
    key = jax.random.PRNGKey(5)
    gen = schedule_batch(pb, et, nt, tc, tb, key, topo_enabled=True,
                         topo_mode="general")
    host = schedule_batch(pb, et, nt, tc, tb, key, topo_enabled=True,
                          topo_mode="host", host_key=host_slot)
    assert np.array_equal(np.asarray(gen.node_idx), np.asarray(host.node_idx))
    assert np.array_equal(np.asarray(gen.any_feasible), np.asarray(host.any_feasible))
    for name in ("spread_ok", "ipa_ok", "first_fail"):
        assert np.array_equal(np.asarray(getattr(gen, name)),
                              np.asarray(getattr(host, name))), name
    np.testing.assert_allclose(np.asarray(gen.best_score),
                               np.asarray(host.best_score), atol=1e-4)
    assert np.array_equal(np.asarray(gen.final_sel_counts),
                          np.asarray(host.final_sel_counts))


def test_vd_override_matches_full_domain():
    """Zone-key spread with a compact 64-domain axis must equal the full
    per-key-vocab axis."""
    n_nodes, n_pods = 16, 6
    infos = [NodeInfo(make_node(f"n{i}")
                      .capacity({"cpu": "8", "memory": "16Gi", "pods": 10})
                      .label("zone", f"z{i % 4}").obj())
             for i in range(n_nodes)]
    enc = ClusterEncoder(Capacities(nodes=n_nodes, pods=n_pods, value_words=32))
    sig = SigTable(enc)
    nt = enc.encode_snapshot(infos)
    sel = LabelSelector(match_labels={"app": "s"})
    pods = [make_pod(f"p{i}").req({"cpu": "1", "memory": "1Gi"}).label("app", "s")
            .spread_constraint(1, "zone", selector=sel).obj() for i in range(n_pods)]
    pb, et = enc.encode_pods(pods)
    tb = sig.encode_topo(pods)
    tc = sig.topo_counts()
    key = jax.random.PRNGKey(9)
    full = schedule_batch(pb, et, nt, tc, tb, key, topo_enabled=True)
    compact = schedule_batch(pb, et, nt, tc, tb, key, topo_enabled=True,
                             vd_override=64)
    assert np.array_equal(np.asarray(full.node_idx), np.asarray(compact.node_idx))
    for name in ("spread_ok", "ipa_ok", "any_feasible"):
        assert np.array_equal(np.asarray(getattr(full, name)),
                              np.asarray(getattr(compact, name))), name


def test_duplicate_hostname_falls_back_to_general():
    """Two nodes sharing a hostname label: the scheduler must refuse the
    fast path, and required anti-affinity must block BOTH nodes."""
    store = ClusterStore()
    sched = TPUScheduler(store, batch_size=4)
    twin_a = make_node("twin-a").capacity({"cpu": "8", "memory": "16Gi", "pods": 10}).obj()
    twin_b = make_node("twin-b").capacity({"cpu": "8", "memory": "16Gi", "pods": 10}).obj()
    # both claim the same hostname (hostname-override collision)
    twin_a.meta.labels["kubernetes.io/hostname"] = "shared"
    twin_b.meta.labels["kubernetes.io/hostname"] = "shared"
    store.create_node(twin_a)
    store.create_node(twin_b)
    sel = LabelSelector(match_labels={"app": "x"})
    for i in range(3):
        store.create_pod(
            make_pod(f"p{i}").req({"cpu": "1"}).label("app", "x")
            .pod_affinity("kubernetes.io/hostname", sel, anti=True).obj())
    sched.run_until_settled()
    assert sched._topo_mode_info()[0] == "general"
    objs, _ = store.list_objects("Pod")
    bound = [p for p in objs if p.spec.node_name]
    # one shared hostname domain ⇒ exactly ONE of the anti-affine pods places
    assert len(bound) == 1, [(p.meta.name, p.spec.node_name) for p in objs]


def test_scheduler_selects_host_mode_for_unique_hostnames():
    store = ClusterStore()
    sched = TPUScheduler(store, batch_size=4)
    for i in range(4):
        store.create_node(
            make_node(f"n{i}").capacity({"cpu": "8", "memory": "16Gi", "pods": 10}).obj())
    sel = LabelSelector(match_labels={"app": "x"})
    for i in range(6):
        store.create_pod(
            make_pod(f"p{i}").req({"cpu": "1"}).label("app", "x")
            .pod_affinity("kubernetes.io/hostname", sel, anti=True).obj())
    sched.run_until_settled()
    assert sched._topo_mode_info()[0] == "host"
    objs, _ = store.list_objects("Pod")
    bound = {p.spec.node_name for p in objs if p.spec.node_name}
    assert len(bound) == 4  # one per node, 2 pods unschedulable
