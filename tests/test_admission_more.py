"""Admission breadth toward AllOrderedPlugins (plugins.go:64): the plugins
added in round 4 — RuntimeClass defaulting, certificate gating, external-IP
denial, in-use protection finalizers, plus the default-off family."""

import pytest

from kubernetes_tpu.api.types import (
    CertificateSigningRequest,
    LabelSelector,
    ObjectMeta,
    PersistentVolume,
    PersistentVolumeClaim,
    RuntimeClass,
    Service,
)
from kubernetes_tpu.api.wrappers import make_node, make_pod
from kubernetes_tpu.apiserver.admission import (
    AdmissionChain,
    AdmissionError,
    AlwaysDeny,
    ExtendedResourceToleration,
    LimitPodHardAntiAffinityTopology,
    NamespaceAutoProvision,
    all_ordered_plugins,
    default_chain,
)
from kubernetes_tpu.apiserver.auth import RBACAuthorizer
from kubernetes_tpu.apiserver.store import ClusterStore


class TestRuntimeClassAdmission:
    def test_overhead_defaulted_from_runtime_class(self):
        store = ClusterStore()
        store.create_object("RuntimeClass", RuntimeClass(
            meta=ObjectMeta(name="gvisor"), handler="runsc",
            overhead={"cpu": "250m", "memory": "64Mi"},
            node_selector={"sandbox": "gvisor"}))
        pod = make_pod("sandboxed").req({"cpu": "1"}).obj()
        pod.spec.runtime_class_name = "gvisor"
        store.create_pod(pod)
        stored = store.get_pod("default/sandboxed")
        assert stored.spec.overhead == {"cpu": "250m", "memory": "64Mi"}
        assert stored.spec.node_selector["sandbox"] == "gvisor"
        # overhead feeds the scheduler's resource request
        assert stored.resource_request()["cpu"] == 1250

    def test_unknown_runtime_class_rejected(self):
        store = ClusterStore()
        pod = make_pod("p").req({"cpu": "1"}).obj()
        pod.spec.runtime_class_name = "missing"
        with pytest.raises(AdmissionError, match="not found"):
            store.create_pod(pod)


class TestCertificateAdmission:
    def _csr(self, **kw):
        defaults = dict(meta=ObjectMeta(name="c1"),
                        signer_name="kubernetes.io/kube-apiserver-client",
                        username="alice", usages=("client auth",))
        defaults.update(kw)
        return CertificateSigningRequest(**defaults)

    def test_subject_restriction_blocks_masters(self):
        store = ClusterStore()
        with pytest.raises(AdmissionError, match="system:masters"):
            store.create_object("CertificateSigningRequest",
                                self._csr(groups=("system:masters",)))

    def test_approval_requires_authorization(self):
        store = ClusterStore()
        store.authorizer = RBACAuthorizer(store)  # no bindings: deny-all
        store.create_object("CertificateSigningRequest", self._csr())
        import dataclasses

        csr = store.csrs["c1"]
        new = dataclasses.replace(csr, approved=True)
        new.meta = dataclasses.replace(csr.meta)
        with store.as_user("mallory"):
            with pytest.raises(AdmissionError, match="may not approve"):
                store.update_object("CertificateSigningRequest", new)
        # system:masters passes via RBAC bypass
        with store.as_user("root", ("system:masters",)):
            store.update_object("CertificateSigningRequest", new)
        assert store.csrs["c1"].approved


class TestServiceExternalIPs:
    def test_external_ips_rejected_when_enabled(self):
        # default-OFF upstream (DefaultOffAdmissionPlugins): enable explicitly
        from kubernetes_tpu.apiserver.admission import DenyServiceExternalIPs

        store = ClusterStore()
        store.admission = AdmissionChain(
            plugins=default_chain() + [DenyServiceExternalIPs()])
        with pytest.raises(AdmissionError, match="externalIPs"):
            store.create_service(Service(meta=ObjectMeta(name="svc"),
                                         external_ips=("10.0.0.1",)))

    def test_default_chain_allows_external_ips(self):
        # reference default behavior: the plugin is off
        store = ClusterStore()
        store.create_service(Service(meta=ObjectMeta(name="svc"),
                                     external_ips=("10.0.0.1",)))

    def test_plain_service_fine(self):
        store = ClusterStore()
        store.create_service(Service(meta=ObjectMeta(name="svc")))


class TestStorageProtectionFinalizers:
    def test_pvc_and_pv_get_finalizers(self):
        store = ClusterStore()
        store.create_pvc(PersistentVolumeClaim(meta=ObjectMeta(name="c")))
        store.create_pv(PersistentVolume(meta=ObjectMeta(name="v")))
        assert "kubernetes.io/pvc-protection" in store.pvcs["default/c"].meta.finalizers
        assert "kubernetes.io/pv-protection" in store.pvs["v"].meta.finalizers


class TestDefaultOffFamily:
    def test_hard_anti_affinity_topology_limited(self):
        chain = AdmissionChain(plugins=[LimitPodHardAntiAffinityTopology()])
        store = ClusterStore()
        pod = make_pod("p").req({"cpu": "1"}).pod_affinity(
            "topology.kubernetes.io/zone",
            LabelSelector(match_labels={"a": "b"}), anti=True).obj()
        with pytest.raises(AdmissionError, match="must be kubernetes.io/hostname"):
            chain.run(store, "Pod", pod)

    def test_namespace_autoprovision_creates(self):
        store = ClusterStore()
        store.admission = AdmissionChain(
            plugins=[NamespaceAutoProvision()] + default_chain())
        pod = make_pod("p", namespace="brand-new").req({"cpu": "1"}).obj()
        store.create_pod(pod)
        assert any(n.meta.name == "brand-new" for n in store.namespaces.values())

    def test_extended_resource_toleration(self):
        chain = AdmissionChain(plugins=[ExtendedResourceToleration()])
        store = ClusterStore()
        pod = make_pod("gpu").req({"cpu": "1", "example.com/gpu": "2"}).obj()
        chain.run(store, "Pod", pod)
        assert any(t.key == "example.com/gpu" and t.operator == "Exists"
                   for t in pod.spec.tolerations)

    def test_always_deny(self):
        chain = AdmissionChain(plugins=[AlwaysDeny()])
        with pytest.raises(AdmissionError):
            chain.run(ClusterStore(), "Pod", make_pod("p").obj())

    def test_all_ordered_roster_instantiates(self):
        names = [p.name for p in all_ordered_plugins()]
        assert len(names) == len(set(names)) == 31
        assert names[0] == "AlwaysAdmit" and names[-1] == "AlwaysDeny"

    def test_security_context_deny_catches_root_uid_zero(self):
        from kubernetes_tpu.api.types import SecurityContext
        from kubernetes_tpu.apiserver.admission import SecurityContextDeny

        chain = AdmissionChain(plugins=[SecurityContextDeny()])
        pod = make_pod("root").req({"cpu": "1"}).obj()
        pod.spec.security_context = SecurityContext(run_as_user=0)
        with pytest.raises(AdmissionError):
            chain.run(ClusterStore(), "Pod", pod)

    def test_runtime_class_overhead_mismatch_rejected(self):
        from kubernetes_tpu.api.types import RuntimeClass as RC

        store = ClusterStore()
        store.create_object("RuntimeClass", RC(
            meta=ObjectMeta(name="gvisor"), overhead={"cpu": "100m"}))
        pod = make_pod("lie").req({"cpu": "1"}).obj()
        pod.spec.runtime_class_name = "gvisor"
        pod.spec.overhead = {"cpu": "999"}  # asserts its own overhead
        with pytest.raises(AdmissionError, match="overhead must match"):
            store.create_pod(pod)


class TestDefaultIngressClass:
    def test_defaulted_from_marked_class(self):
        from kubernetes_tpu.api.types import (
            ANNOTATION_DEFAULT_INGRESS_CLASS,
            Ingress,
            IngressClass,
        )

        store = ClusterStore()
        store.create_object("IngressClass", IngressClass(
            meta=ObjectMeta(name="nginx",
                            annotations={ANNOTATION_DEFAULT_INGRESS_CLASS: "true"})))
        store.create_object("Ingress", Ingress(meta=ObjectMeta(name="web")))
        assert store.ingresses["default/web"].ingress_class_name == "nginx"

    def test_explicit_class_kept(self):
        from kubernetes_tpu.api.types import (
            ANNOTATION_DEFAULT_INGRESS_CLASS,
            Ingress,
            IngressClass,
        )

        store = ClusterStore()
        store.create_object("IngressClass", IngressClass(
            meta=ObjectMeta(name="nginx",
                            annotations={ANNOTATION_DEFAULT_INGRESS_CLASS: "true"})))
        store.create_object("Ingress", Ingress(
            meta=ObjectMeta(name="web"), ingress_class_name="haproxy"))
        assert store.ingresses["default/web"].ingress_class_name == "haproxy"

    def test_two_defaults_rejected(self):
        from kubernetes_tpu.api.types import (
            ANNOTATION_DEFAULT_INGRESS_CLASS,
            Ingress,
            IngressClass,
        )

        store = ClusterStore()
        for n in ("a", "b"):
            store.create_object("IngressClass", IngressClass(
                meta=ObjectMeta(name=n,
                                annotations={ANNOTATION_DEFAULT_INGRESS_CLASS: "true"})))
        with pytest.raises(AdmissionError, match="multiple IngressClasses"):
            store.create_object("Ingress", Ingress(meta=ObjectMeta(name="web")))


class TestEventsThroughStore:
    def test_recorder_persists_and_dedups(self):
        from kubernetes_tpu.utils.events import EventRecorder

        store = ClusterStore()
        rec = EventRecorder(store=store, reporting_controller="default-scheduler")
        rec.eventf("default/web", "Warning", "FailedScheduling", "Scheduling",
                   "no feasible node")
        rec.eventf("default/web", "Warning", "FailedScheduling", "Scheduling",
                   "no feasible node")  # series bump, not a second object
        events = list(store.events.values())
        assert len(events) == 1
        assert events[0].count == 2
        assert events[0].involved_object == "default/web"
        from kubernetes_tpu.kubectl.cli import kubectl

        out = kubectl(store, "get events")
        assert "FailedScheduling" in out and "(x2)" in out

    def test_event_rate_limit(self):
        from kubernetes_tpu.api.types import Event as APIEvent
        from kubernetes_tpu.apiserver.admission import EventRateLimit

        clock = [0.0]
        plugin = EventRateLimit(qps=1.0, burst=2, now_fn=lambda: clock[0])
        chain = AdmissionChain(plugins=[plugin])
        store = ClusterStore()
        for i in range(2):
            chain.run(store, "Event", APIEvent(meta=ObjectMeta(name=f"e{i}")))
        with pytest.raises(AdmissionError, match="rate limit"):
            chain.run(store, "Event", APIEvent(meta=ObjectMeta(name="e3")))
        clock[0] += 2.0  # refill
        chain.run(store, "Event", APIEvent(meta=ObjectMeta(name="e4")))
