"""Controller manager loops: workloads, node lifecycle, GC, namespace,
endpoints, PV binder — and the full control plane (KCM + scheduler) together."""

import dataclasses

from kubernetes_tpu.api.types import (
    BINDING_IMMEDIATE,
    DaemonSet,
    Deployment,
    Job,
    LabelSelector,
    Lease,
    Namespace,
    ObjectMeta,
    PersistentVolume,
    PersistentVolumeClaim,
    ReplicaSet,
    Service,
    StatefulSet,
    StorageClass,
)
from kubernetes_tpu.api.wrappers import make_node, make_pod
from kubernetes_tpu.apiserver.store import ClusterStore
from kubernetes_tpu.client.informer import SharedInformerFactory
from kubernetes_tpu.controllers import ControllerManager
from kubernetes_tpu.controllers.nodelifecycle import (
    NODE_LEASE_NAMESPACE,
    TAINT_UNREACHABLE,
)
from kubernetes_tpu.scheduler.scheduler import Scheduler
from kubernetes_tpu.utils.clock import FakeClock


def make_manager(store, controllers=None, now_fn=None):
    return ControllerManager(
        store,
        factory=SharedInformerFactory(store),
        controllers=controllers,
        now_fn=now_fn or FakeClock(),
    )


def pod_template(labels=None):
    pw = make_pod("template").req({"cpu": "100m"})
    for k, v in (labels or {}).items():
        pw.label(k, v)
    return pw.obj()


class TestReplicaSet:
    def test_scale_up_creates_owned_pods(self):
        store = ClusterStore()
        m = make_manager(store, ["replicaset"])
        store.create_replica_set(ReplicaSet(
            meta=ObjectMeta(name="web"),
            selector=LabelSelector(match_labels={"app": "web"}),
            replicas=3,
            template=pod_template({"app": "web"}),
        ))
        m.settle()
        pods = [p for p in store.pods.values()]
        assert len(pods) == 3
        assert all(p.meta.controller_of().name == "web" for p in pods)

    def test_scale_down_prefers_unscheduled(self):
        store = ClusterStore()
        m = make_manager(store, ["replicaset"])
        store.create_replica_set(ReplicaSet(
            meta=ObjectMeta(name="web"), replicas=3, template=pod_template()))
        m.settle()
        # bind two of the three
        keys = sorted(store.pods)
        from kubernetes_tpu.api.types import Binding
        store.bind(Binding(pod_key=keys[0], node_name="n1"))
        store.bind(Binding(pod_key=keys[1], node_name="n1"))
        rs = store.get_replica_set("default/web")
        new_rs = dataclasses.replace(rs, replicas=2)
        new_rs.meta = dataclasses.replace(rs.meta)
        store.update_object("ReplicaSet", new_rs)
        m.settle()
        remaining = list(store.pods.values())
        assert len(remaining) == 2
        assert all(p.spec.node_name for p in remaining)  # unscheduled one went

    def test_pod_deletion_restored(self):
        store = ClusterStore()
        m = make_manager(store, ["replicaset"])
        store.create_replica_set(ReplicaSet(
            meta=ObjectMeta(name="web"), replicas=2, template=pod_template()))
        m.settle()
        victim = next(iter(store.pods))
        store.delete_pod(victim)
        m.settle()
        assert len(store.pods) == 2


class TestDeploymentAndFriends:
    def test_deployment_creates_replicaset(self):
        store = ClusterStore()
        m = make_manager(store, ["deployment", "replicaset"])
        store.create_object("Deployment", Deployment(
            meta=ObjectMeta(name="api"), replicas=2, template=pod_template()))
        m.settle()
        # per-revision RS named <deploy>-<templatehash>
        rss = [rs for rs in store.replica_sets.values()
               if rs.meta.name.startswith("api-")]
        assert len(rss) == 1
        assert len(store.pods) == 2

    def test_deployment_scale_propagates(self):
        store = ClusterStore()
        m = make_manager(store, ["deployment", "replicaset"])
        dep = Deployment(meta=ObjectMeta(name="api"), replicas=1, template=pod_template())
        store.create_object("Deployment", dep)
        m.settle()
        new = dataclasses.replace(dep, replicas=4)
        new.meta = dataclasses.replace(dep.meta)
        store.update_object("Deployment", new)
        m.settle()
        assert len(store.pods) == 4

    def _mark_running(self, store):
        for p in list(store.pods.values()):
            if p.status.phase == "Pending":
                new = dataclasses.replace(p)
                new.meta = dataclasses.replace(p.meta)
                new.status = dataclasses.replace(p.status, phase="Running")
                new.spec = p.spec
                store.update_pod(new)

    def test_rolling_update_respects_windows(self):
        store = ClusterStore()
        m = make_manager(store, ["deployment", "replicaset"])
        dep = Deployment(meta=ObjectMeta(name="api"), replicas=4,
                         template=pod_template({"v": "1"}),
                         max_surge=1, max_unavailable=1)
        store.create_object("Deployment", dep)
        m.settle()
        self._mark_running(store)
        m.settle()
        assert len(store.pods) == 4

        new = dataclasses.replace(dep, template=pod_template({"v": "2"}))
        new.meta = dataclasses.replace(dep.meta)
        store.update_object("Deployment", new)
        # drive the rollout stepwise, checking the windows at every step
        for _ in range(30):
            m.settle()
            pods = list(store.pods.values())
            alive = [p for p in pods if p.status.phase in ("Pending", "Running")]
            running = [p for p in pods if p.status.phase == "Running"]
            assert len(alive) <= 4 + 1, len(alive)       # maxSurge window
            assert len(running) >= 4 - 1, len(running)   # maxUnavailable window
            self._mark_running(store)
            rss = [rs for rs in store.replica_sets.values()
                   if rs.meta.name.startswith("api-")]
            if (len(rss) == 1
                    and all(p.meta.labels.get("v") == "2" for p in store.pods.values())
                    and len(store.pods) == 4):
                break
        assert len(store.pods) == 4
        assert all(p.meta.labels.get("v") == "2" for p in store.pods.values())
        assert len([rs for rs in store.replica_sets.values()
                    if rs.meta.name.startswith("api-")]) == 1  # old revision GC'd

    def test_rolling_update_zero_surge_progresses(self):
        """maxSurge=0: the new revision can only grow as the old shrinks —
        the rollout must still complete (regression: early-return after
        creating the 0-replica new RS stalled forever)."""
        store = ClusterStore()
        m = make_manager(store, ["deployment", "replicaset"])
        dep = Deployment(meta=ObjectMeta(name="api"), replicas=3,
                         template=pod_template({"v": "1"}),
                         max_surge=0, max_unavailable=1)
        store.create_object("Deployment", dep)
        m.settle()
        self._mark_running(store)
        m.settle()
        new = dataclasses.replace(dep, template=pod_template({"v": "2"}))
        new.meta = dataclasses.replace(dep.meta)
        store.update_object("Deployment", new)
        for _ in range(30):
            m.settle()
            alive = [p for p in store.pods.values()
                     if p.status.phase in ("Pending", "Running")]
            assert len(alive) <= 3  # surge window: never above replicas
            self._mark_running(store)
            if (len(store.pods) == 3
                    and all(p.meta.labels.get("v") == "2" for p in store.pods.values())):
                break
        assert all(p.meta.labels.get("v") == "2" for p in store.pods.values())

    def test_recreate_strategy_tears_down_first(self):
        store = ClusterStore()
        m = make_manager(store, ["deployment", "replicaset"])
        dep = Deployment(meta=ObjectMeta(name="api"), replicas=2,
                         template=pod_template({"v": "1"}), strategy="Recreate")
        store.create_object("Deployment", dep)
        m.settle()
        self._mark_running(store)
        new = dataclasses.replace(dep, template=pod_template({"v": "2"}))
        new.meta = dataclasses.replace(dep.meta)
        store.update_object("Deployment", new)
        for _ in range(20):
            m.settle()
            pods = list(store.pods.values())
            # never both revisions alive at once under Recreate
            versions = {p.meta.labels.get("v") for p in pods
                        if p.status.phase in ("Pending", "Running")}
            assert versions in (set(), {"1"}, {"2"}), versions
            self._mark_running(store)
            if (len(store.pods) == 2
                    and all(p.meta.labels.get("v") == "2" for p in store.pods.values())):
                break
        assert all(p.meta.labels.get("v") == "2" for p in store.pods.values())

    def test_statefulset_ordered_creation(self):
        store = ClusterStore()
        m = make_manager(store, ["statefulset"])
        store.create_stateful_set(StatefulSet(
            meta=ObjectMeta(name="db"), replicas=3, template=pod_template()))
        m.settle()
        # only db-0 until it runs
        assert sorted(p.meta.name for p in store.pods.values()) == ["db-0"]
        p0 = store.get_pod("default/db-0").clone()
        p0.status.phase = "Running"
        store.update_pod(p0)
        m.settle()
        assert "db-1" in {p.meta.name for p in store.pods.values()}

    def test_daemonset_one_pod_per_node(self):
        store = ClusterStore()
        for i in range(3):
            store.create_node(make_node(f"n{i}").obj())
        m = make_manager(store, ["daemonset"])
        store.create_object("DaemonSet", DaemonSet(
            meta=ObjectMeta(name="agent"), template=pod_template()))
        m.settle()
        assert len(store.pods) == 3
        store.create_node(make_node("n3").obj())
        m.settle()
        assert len(store.pods) == 4
        store.delete_node("n0")
        m.settle()
        assert len(store.pods) == 3

    def test_job_runs_to_completion(self):
        store = ClusterStore()
        m = make_manager(store, ["job"])
        store.create_object("Job", Job(
            meta=ObjectMeta(name="batch"), completions=3, parallelism=2,
            template=pod_template()))
        m.settle()
        assert len(store.pods) == 2  # parallelism cap
        for key in list(store.pods):
            p = store.get_pod(key).clone()
            p.status.phase = "Succeeded"
            store.update_pod(p)
        m.settle()
        job = store.get_object("Job", "default/batch")
        assert job.succeeded == 2
        # third pod created; finish it
        active = [p for p in store.pods.values() if p.status.phase == "Pending"]
        assert len(active) == 1
        p = active[0].clone()
        p.status.phase = "Succeeded"
        store.update_pod(p)
        m.settle()
        assert store.get_object("Job", "default/batch").succeeded == 3


class TestNodeLifecycle:
    def test_missed_heartbeats_taint_and_evict(self):
        store = ClusterStore()
        clock = FakeClock()
        m = make_manager(store, ["nodelifecycle"], now_fn=clock)
        store.create_node(make_node("n1").obj())
        store.create_lease(Lease(
            meta=ObjectMeta(name="n1", namespace=NODE_LEASE_NAMESPACE),
            renew_time=clock(),
        ))
        store.create_pod(make_pod("victim").node("n1").obj())
        store.pods["default/victim"].spec.node_name = "n1"
        m.sync_round(monitor_nodes=True)
        assert store.nodes["n1"].status.ready
        clock.advance(60.0)  # past 40s grace
        m.sync_round(monitor_nodes=True)
        node = store.nodes["n1"]
        assert not node.status.ready
        assert any(t.key == TAINT_UNREACHABLE for t in node.spec.taints)
        # the DefaultTolerationSeconds admission default (300s) keeps the pod
        # through the bounded window, then the taint manager evicts
        assert store.get_pod("default/victim") is not None
        clock.advance(301.0)
        m.sync_round(monitor_nodes=True)
        assert store.get_pod("default/victim") is None  # evicted

    def test_recovery_clears_taint(self):
        store = ClusterStore()
        clock = FakeClock()
        m = make_manager(store, ["nodelifecycle"], now_fn=clock)
        store.create_node(make_node("n1").obj())
        lease = Lease(meta=ObjectMeta(name="n1", namespace=NODE_LEASE_NAMESPACE),
                      renew_time=clock())
        store.create_lease(lease)
        clock.advance(60.0)
        m.sync_round(monitor_nodes=True)
        assert not store.nodes["n1"].status.ready
        stored = store.get_lease(f"{NODE_LEASE_NAMESPACE}/n1")
        renewed = dataclasses.replace(stored, renew_time=clock())
        renewed.meta = dataclasses.replace(stored.meta)
        store.update_lease(renewed, expect_rv=stored.meta.resource_version)
        m.sync_round(monitor_nodes=True)
        node = store.nodes["n1"]
        assert node.status.ready and not node.spec.taints


class TestHousekeeping:
    def test_podgc_orphaned(self):
        store = ClusterStore()
        m = make_manager(store, ["podgc"])
        store.create_node(make_node("n1").obj())
        store.create_pod(make_pod("p").obj())
        store.pods["default/p"].spec.node_name = "ghost-node"
        m.settle()
        assert store.get_pod("default/p") is None

    def test_gc_cascade_on_owner_delete(self):
        store = ClusterStore()
        m = make_manager(store, ["replicaset", "garbagecollector"])
        store.create_replica_set(ReplicaSet(
            meta=ObjectMeta(name="web"), replicas=2, template=pod_template()))
        m.settle()
        assert len(store.pods) == 2
        store.delete_object("ReplicaSet", "default/web")
        m.settle()
        assert len(store.pods) == 0

    def test_namespace_deletion_cascades(self):
        store = ClusterStore()
        m = make_manager(store, ["namespace"])
        store.create_namespace(Namespace(meta=ObjectMeta(name="doomed")))
        store.create_pod(make_pod("p", namespace="doomed").obj())
        store.create_service(Service(meta=ObjectMeta(name="s", namespace="doomed")))
        ns = store.namespaces["doomed"]
        ns.meta.deletion_timestamp = 1.0
        store._notify("Namespace", "MODIFIED", ns, ns)
        m.settle()
        assert store.get_pod("doomed/p") is None
        assert "doomed/s" not in store.services
        assert "doomed" not in store.namespaces

    def test_endpoints_track_running_pods(self):
        store = ClusterStore()
        m = make_manager(store, ["endpoints"])
        store.create_service(Service(meta=ObjectMeta(name="svc"), selector={"app": "web"}))
        p = make_pod("p1").label("app", "web").obj()
        store.create_pod(p)
        m.settle()
        eps = store.get_object("Endpoints", "default/svc")
        assert eps is not None and eps.addresses == ()  # pod not Running
        bound = store.get_pod("default/p1").clone()
        bound.status.phase = "Running"
        bound.spec.node_name = "n1"
        store.update_pod(bound)
        m.settle()
        eps = store.get_object("Endpoints", "default/svc")
        assert [a.pod_key for a in eps.addresses] == ["default/p1"]

    def test_pv_binder_immediate(self):
        store = ClusterStore()
        m = make_manager(store, ["pvbinder"])
        store.create_storage_class(StorageClass(
            meta=ObjectMeta(name="fast"), volume_binding_mode=BINDING_IMMEDIATE))
        store.create_pv(PersistentVolume(
            meta=ObjectMeta(name="pv-big"), storage_class="fast", capacity_bytes=100))
        store.create_pv(PersistentVolume(
            meta=ObjectMeta(name="pv-small"), storage_class="fast", capacity_bytes=10))
        store.create_pvc(PersistentVolumeClaim(
            meta=ObjectMeta(name="claim"), storage_class="fast", requested_bytes=5))
        m.settle()
        pvc = store.get_pvc("default/claim")
        assert pvc.bound_pv == "pv-small"  # smallest fit


class TestControlPlaneTogether:
    def test_deployment_to_bound_pods(self):
        """Deployment → RS → pods → scheduler binds them: the full loop."""
        store = ClusterStore()
        clock = FakeClock()
        m = make_manager(store, ["deployment", "replicaset"], now_fn=clock)
        sched = Scheduler(store, now_fn=clock)
        for i in range(4):
            store.create_node(make_node(f"n{i}").capacity(
                {"cpu": "4", "memory": "8Gi", "pods": 10}).obj())
        store.create_object("Deployment", Deployment(
            meta=ObjectMeta(name="api"), replicas=6, template=pod_template()))
        m.settle()
        sched.run_until_settled()
        bound = [p for p in store.pods.values() if p.spec.node_name]
        assert len(bound) == 6


class TestReviewRegressions:
    def test_endpoints_drop_pod_that_stops_matching(self):
        """A pod whose labels stop matching must leave the Endpoints."""
        store = ClusterStore()
        m = make_manager(store, ["endpoints"])
        store.create_service(Service(meta=ObjectMeta(name="svc"), selector={"app": "web"}))
        p = make_pod("p1").label("app", "web").obj()
        p.status.phase = "Running"
        store.create_pod(p)
        m.settle()
        eps = store.get_object("Endpoints", "default/svc")
        assert [a.pod_key for a in eps.addresses] == ["default/p1"]
        relabeled = store.get_pod("default/p1").clone()
        relabeled.meta.labels = {"app": "other"}
        store.update_pod(relabeled)
        m.settle()
        eps = store.get_object("Endpoints", "default/svc")
        assert eps.addresses == ()

    def test_daemonset_recreates_deleted_pod(self):
        store = ClusterStore()
        store.create_node(make_node("n0").obj())
        m = make_manager(store, ["daemonset"])
        store.create_object("DaemonSet", DaemonSet(
            meta=ObjectMeta(name="agent"), template=pod_template()))
        m.settle()
        assert len(store.pods) == 1
        store.delete_pod(next(iter(store.pods)))
        m.settle()  # pod event alone must re-level the daemonset
        assert len(store.pods) == 1

    def test_journal_order_matches_store_state_under_concurrency(self):
        """ADDED/DELETED for one key must appear in mutation order even with
        racing writers (journal append is inside the mutator's critical
        section)."""
        import threading

        store = ClusterStore()
        errors = []

        def churn(idx):
            try:
                for i in range(200):
                    key = f"p-{idx}-{i % 5}"
                    store.create_pod(make_pod(key).obj())
                    store.delete_pod(f"default/{key}")
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=churn, args=(t,)) for t in range(4)]
        w = store.watch("Pod", since=0)
        store._journal_capacity = 100000
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors
        # replay: per key the stream must strictly alternate ADDED/DELETED
        state = {}
        for ev in w.drain():
            key = ev.object.meta.key()
            if ev.type == "ADDED":
                assert state.get(key) != "present", f"double-add {key}"
                state[key] = "present"
            elif ev.type == "DELETED":
                assert state.get(key) == "present", f"delete-before-add {key}"
                state[key] = "absent"
        assert all(v == "absent" for v in state.values())


class TestAdmissionAndQuota:
    def test_quota_rejects_over_limit(self):
        from kubernetes_tpu.api.types import ResourceQuota
        from kubernetes_tpu.apiserver.admission import AdmissionError

        store = ClusterStore()
        store.create_object("ResourceQuota", ResourceQuota(
            meta=ObjectMeta(name="q"), hard={"pods": 2, "requests.cpu": 1000}))
        store.create_pod(make_pod("a").req({"cpu": "400m"}).obj())
        store.create_pod(make_pod("b").req({"cpu": "400m"}).obj())
        import pytest as _pytest
        with _pytest.raises(AdmissionError):  # pod count at 2/2
            store.create_pod(make_pod("c").req({"cpu": "100m"}).obj())
        rq = store.get_object("ResourceQuota", "default/q")
        assert rq.used["pods"] == 2 and rq.used["requests.cpu"] == 800

    def test_quota_controller_reconciles_after_delete(self):
        from kubernetes_tpu.api.types import ResourceQuota

        store = ClusterStore()
        store.create_object("ResourceQuota", ResourceQuota(
            meta=ObjectMeta(name="q"), hard={"pods": 5}))
        store.create_pod(make_pod("a").obj())
        store.create_pod(make_pod("b").obj())
        store.delete_pod("default/a")
        m = make_manager(store, ["resourcequota"])
        m.settle()
        rq = store.get_object("ResourceQuota", "default/q")
        assert rq.used == {"pods": 1}
        # headroom restored: a new pod admits
        store.create_pod(make_pod("c").obj())

    def test_failed_duplicate_create_does_not_charge_quota(self):
        # ADVICE r1 (medium): charge must be atomic with the insert — a
        # Conflict on duplicate key must leave usage untouched.
        from kubernetes_tpu.api.types import ResourceQuota
        from kubernetes_tpu.apiserver.store import Conflict

        store = ClusterStore()
        store.create_object("ResourceQuota", ResourceQuota(
            meta=ObjectMeta(name="q"), hard={"pods": 10}))
        store.create_pod(make_pod("a").obj())
        import pytest as _pytest
        for _ in range(2):
            with _pytest.raises(Conflict):
                store.create_pod(make_pod("a").obj())
        rq = store.get_object("ResourceQuota", "default/q")
        assert rq.used["pods"] == 1

    def test_later_quota_rejection_rolls_back_earlier_quota(self):
        # Drive charge() directly: the advisory validate() would reject first
        # on the create path, leaving the rollback branch uncovered.
        from kubernetes_tpu.api.types import ResourceQuota
        from kubernetes_tpu.apiserver.admission import (
            AdmissionChain, AdmissionError, ResourceQuotaAdmission)

        store = ClusterStore()
        store.admission = None  # quotas below are checked via charge() only
        store.create_object("ResourceQuota", ResourceQuota(
            meta=ObjectMeta(name="roomy"), hard={"pods": 10}))
        store.create_object("ResourceQuota", ResourceQuota(
            meta=ObjectMeta(name="tight"), hard={"requests.cpu": 100}))
        chain = AdmissionChain([ResourceQuotaAdmission()])
        import pytest as _pytest
        with _pytest.raises(AdmissionError):
            chain.charge(store, "Pod", make_pod("big").req({"cpu": "2"}).obj())
        assert store.get_object("ResourceQuota", "default/roomy").used.get("pods", 0) == 0
        # a fitting pod charges both quotas, and undo removes both charges
        undo = chain.charge(store, "Pod", make_pod("ok").req({"cpu": "50m"}).obj())
        assert store.get_object("ResourceQuota", "default/roomy").used["pods"] == 1
        assert store.get_object("ResourceQuota", "default/tight").used["requests.cpu"] == 50
        undo()
        assert store.get_object("ResourceQuota", "default/roomy").used["pods"] == 0
        assert store.get_object("ResourceQuota", "default/tight").used["requests.cpu"] == 0

    def test_absent_namespace_rejects_creates_except_default(self):
        # ADVICE r1 (low): the reference rejects creates into nonexistent
        # namespaces; only the bootstrap 'default' namespace is lazy here.
        from kubernetes_tpu.apiserver.admission import AdmissionError

        store = ClusterStore()
        import pytest as _pytest
        with _pytest.raises(AdmissionError):
            store.create_pod(make_pod("p", namespace="typo-ns").obj())
        store.create_pod(make_pod("p").obj())  # default: tolerated
        store.create_namespace(Namespace(meta=ObjectMeta(name="real")))
        store.create_pod(make_pod("p2", namespace="real").obj())

    def test_priority_class_resolved_at_admission(self):
        from kubernetes_tpu.api.types import PriorityClass

        store = ClusterStore()
        store.create_priority_class(PriorityClass(meta=ObjectMeta(name="high"), value=1000))
        pod = make_pod("p").obj()
        pod.spec.priority_class_name = "high"
        store.create_pod(pod)
        assert store.get_pod("default/p").spec.priority == 1000

    def test_terminating_namespace_rejects_creates(self):
        from kubernetes_tpu.apiserver.admission import AdmissionError

        store = ClusterStore()
        store.create_namespace(Namespace(meta=ObjectMeta(name="dying")))
        store.namespaces["dying"].meta.deletion_timestamp = 1.0
        import pytest as _pytest
        with _pytest.raises(AdmissionError):
            store.create_pod(make_pod("p", namespace="dying").obj())

    def test_rc_controller(self):
        from kubernetes_tpu.api.types import ReplicationController

        store = ClusterStore()
        m = make_manager(store, ["replicationcontroller"])
        store.create_replication_controller(ReplicationController(
            meta=ObjectMeta(name="old-school"), selector={"app": "x"},
            replicas=3, template=pod_template({"app": "x"})))
        m.settle()
        assert len(store.pods) == 3
        store.delete_pod(next(iter(store.pods)))
        m.settle()
        assert len(store.pods) == 3
