"""Client runtime layer: watch journal, reflector, DeltaFIFO, informers,
workqueue, leader election — and the scheduler driven through informers."""

import pytest

from kubernetes_tpu.api.wrappers import make_node, make_pod
from kubernetes_tpu.apiserver.store import ADDED, ClusterStore, DELETED, Expired, MODIFIED
from kubernetes_tpu.client import (
    DeltaFIFO,
    LeaderElector,
    RateLimitingQueue,
    Reflector,
    SharedInformerFactory,
    parallelize_until,
)
from kubernetes_tpu.client.delta_fifo import ADDED as D_ADDED, DELETED as D_DELETED, REPLACED
from kubernetes_tpu.client.leaderelection import LeaderElectionConfig
from kubernetes_tpu.client.workqueue import chunk_size_for
from kubernetes_tpu.scheduler.scheduler import Scheduler
from kubernetes_tpu.utils.clock import FakeClock


class TestWatch:
    def test_watch_streams_events(self):
        store = ClusterStore()
        _, rv = store.list_objects("Pod")
        w = store.watch("Pod", since=rv)
        store.create_pod(make_pod("a").obj())
        store.delete_pod("default/a")
        evs = w.drain()
        assert [e.type for e in evs] == [ADDED, DELETED]
        w.stop()

    def test_watch_backlog_from_journal(self):
        store = ClusterStore()
        store.create_pod(make_pod("a").obj())
        w = store.watch("Pod", since=0)  # journal replay
        evs = w.drain()
        assert [e.type for e in evs] == [ADDED]
        w.stop()

    def test_watch_expired(self):
        store = ClusterStore()
        store._journal_capacity = 2
        for i in range(5):
            store.create_pod(make_pod(f"p{i}").obj())
        with pytest.raises(Expired):
            store.watch("Pod", since=1)

    def test_watch_filters_kind(self):
        store = ClusterStore()
        _, rv = store.list_objects("Pod")
        w = store.watch("Pod", since=rv)
        store.create_node(make_node("n").obj())
        store.create_pod(make_pod("a").obj())
        evs = w.drain()
        assert len(evs) == 1 and evs[0].object.meta.name == "a"
        w.stop()


class TestDeltaFIFO:
    def _fifo(self, known=None):
        return DeltaFIFO(lambda o: o.meta.key(), known_objects=known)

    def test_accumulates_deltas_per_key(self):
        f = self._fifo()
        p = make_pod("a").obj()
        f.add(p)
        f.update(p)
        deltas = f.pop()
        assert [d.type for d in deltas] == [D_ADDED, "Updated"]
        assert f.pop() is None

    def test_replace_synthesizes_deletes(self):
        known_keys = ["default/gone"]
        f = self._fifo(known=lambda: known_keys)
        f.replace([make_pod("kept").obj()])
        types = {}
        while (ds := f.pop()) is not None:
            for d in ds:
                key = d.object if isinstance(d.object, str) else d.object.meta.key()
                types.setdefault(key, []).append(d.type)
        assert types["default/kept"] == [REPLACED]
        assert types["default/gone"] == [D_DELETED]

    def test_replace_tombstones_queued_unknown_keys(self):
        # ADVICE r1 (low): a key with a queued, un-popped Added that is absent
        # from the relist must still get a Deleted tombstone even though the
        # consumer's store (known_objects) has never seen it.
        f = self._fifo(known=lambda: [])
        f.add(make_pod("flash").obj())  # never popped
        f.replace([])                   # relist: object already gone
        deltas = f.pop()
        assert [d.type for d in deltas] == [D_ADDED, D_DELETED]
        assert f.pop() is None

    def test_has_synced_after_initial_pop(self):
        f = self._fifo(known=lambda: [])
        f.replace([make_pod("a").obj(), make_pod("b").obj()])
        assert not f.has_synced()
        f.pop(); f.pop()
        assert f.has_synced()


class TestReflectorInformer:
    def test_reflector_list_then_watch(self):
        store = ClusterStore()
        store.create_pod(make_pod("pre").obj())
        f = DeltaFIFO(lambda o: o.meta.key())
        r = Reflector(store, "Pod", f)
        r.list_and_establish_watch()
        assert f.pop()[0].type == REPLACED  # pre-existing via LIST
        store.create_pod(make_pod("post").obj())
        assert r.step() == 1
        assert f.pop()[0].type == D_ADDED

    def test_informer_indexer_and_handlers(self):
        store = ClusterStore()
        store.create_node(make_node("n1").obj())
        factory = SharedInformerFactory(store)
        inf = factory.informer_for("Node")
        events = []
        inf.add_event_handler(lambda e, old, new: events.append((e, (new or old).meta.name)))
        factory.start()
        assert inf.get("n1") is not None
        assert ("add", "n1") in events
        store.create_node(make_node("n2").obj())
        store.delete_node("n1")
        factory.pump()
        assert inf.get("n2") is not None and inf.get("n1") is None
        assert ("delete", "n1") in events

    def test_late_handler_gets_replay(self):
        store = ClusterStore()
        store.create_pod(make_pod("a").obj())
        factory = SharedInformerFactory(store)
        inf = factory.informer_for("Pod")
        factory.start()
        seen = []
        inf.add_event_handler(lambda e, old, new: seen.append((e, new.meta.name)))
        assert seen == [("add", "a")]

    def test_informer_survives_journal_expiry(self):
        store = ClusterStore()
        factory = SharedInformerFactory(store)
        inf = factory.informer_for("Pod")
        factory.start()
        store._journal_capacity = 4
        # force the watch to lag: stop it, churn past capacity, then relist
        inf.reflector._watch.stop()
        inf.reflector._watch = None
        for i in range(10):
            store.create_pod(make_pod(f"p{i}").obj())
        store.delete_pod("default/p0")
        inf.reflector.relist()
        inf.pump()
        assert inf.get("default/p0") is None
        assert inf.get("default/p9") is not None


class TestWorkqueue:
    def test_dedup(self):
        q = RateLimitingQueue()
        q.add("x"); q.add("x")
        assert len(q) == 1
        assert q.get() == "x"
        assert q.get() is None

    def test_readd_while_processing_requeues_on_done(self):
        q = RateLimitingQueue()
        q.add("x")
        item = q.get()
        q.add("x")  # arrives while processing
        assert len(q) == 0
        q.done(item)
        assert q.get() == "x"

    def test_rate_limited_backoff(self):
        t = [0.0]
        q = RateLimitingQueue(base_delay=1.0, now_fn=lambda: t[0])
        q.add_rate_limited("x")
        assert q.get() is None  # not ready yet
        t[0] = 1.1
        assert q.get() == "x"
        q.done("x")
        q.add_rate_limited("x")  # second failure: 2s
        t[0] = 2.0
        assert q.get() is None
        t[0] = 3.2
        assert q.get() == "x"
        q.forget("x")
        assert q.num_requeues("x") == 0

    def test_parallelize_until_covers_all(self):
        seen = []
        parallelize_until(4, 100, lambda i: seen.append(i))
        assert sorted(seen) == list(range(100))

    def test_chunk_size(self):
        assert chunk_size_for(100, 16) == 7  # min(10, 100/16+1=7)
        assert chunk_size_for(1, 16) == 1


class TestLeaderElection:
    def test_acquire_renew_steal(self):
        store = ClusterStore()
        t = [0.0]
        cfg_a = LeaderElectionConfig(identity="a", lease_duration=15.0)
        cfg_b = LeaderElectionConfig(identity="b", lease_duration=15.0)
        a = LeaderElector(store, cfg_a, now_fn=lambda: t[0])
        b = LeaderElector(store, cfg_b, now_fn=lambda: t[0])
        assert a.run_once() is True
        assert b.run_once() is False  # lease held and fresh
        t[0] = 10.0
        assert a.run_once() is True  # renew
        assert b.run_once() is False
        t[0] = 30.0  # a's renew (t=10) + 15s expired
        assert b.run_once() is True  # steal
        assert store.get_lease("kube-system/kube-scheduler").lease_transitions == 1
        assert a.run_once() is False  # a lost it

    def test_callbacks(self):
        store = ClusterStore()
        t = [0.0]
        calls = []
        a = LeaderElector(store, LeaderElectionConfig(identity="a"),
                          on_started_leading=lambda: calls.append("start"),
                          on_stopped_leading=lambda: calls.append("stop"),
                          now_fn=lambda: t[0])
        b = LeaderElector(store, LeaderElectionConfig(identity="b"), now_fn=lambda: t[0])
        a.run_once()
        t[0] = 100.0
        b.run_once()  # steals
        a.run_once()  # notices
        assert calls == ["start", "stop"]


class TestSchedulerThroughInformers:
    def test_e2e_with_informer_bus(self):
        store = ClusterStore()
        for i in range(5):
            store.create_node(make_node(f"n{i}").capacity({"cpu": "4", "memory": "8Gi", "pods": 10}).obj())
        factory = SharedInformerFactory(store)
        sched = Scheduler(store, informer_factory=factory)
        for i in range(8):
            store.create_pod(make_pod(f"p{i}").req({"cpu": "100m"}).obj())
        sched.run_until_settled()
        bound = [p for p in store.pods.values() if p.spec.node_name]
        assert len(bound) == 8

    def test_informer_scheduler_sees_node_added_later(self):
        store = ClusterStore()
        factory = SharedInformerFactory(store)
        clock = FakeClock()
        sched = Scheduler(store, informer_factory=factory, now_fn=clock)
        store.create_pod(make_pod("p").req({"cpu": "100m"}).obj())
        sched.run_until_settled()
        assert store.get_pod("default/p").spec.node_name == ""
        store.create_node(make_node("n1").capacity({"cpu": "4", "memory": "8Gi", "pods": 10}).obj())
        clock.advance(10.1)  # past pod backoff
        sched.run_until_settled()
        assert store.get_pod("default/p").spec.node_name == "n1"
