"""Chaos & recovery (test/e2e/chaosmonkey + SURVEY §5.3 build mapping):
disruption injected concurrently with scheduling; crash-only recovery —
a restarted scheduler/device rebuilds from the store and continues. The
device-failure suite (TestDeviceServiceFaults) scripts sidecar crashes,
drops, and restarts through testing/faults.py — deterministic, no sleeps
against the wall clock.
"""

import numpy as np
import pytest

from kubernetes_tpu.api.wrappers import make_node, make_pod
from kubernetes_tpu.apiserver.store import ClusterStore
from kubernetes_tpu.backend import circuit
from kubernetes_tpu.backend.service import DeviceService, WireScheduler, serve
from kubernetes_tpu.backend.tpu_scheduler import TPUScheduler
from kubernetes_tpu.client.informer import SharedInformerFactory
from kubernetes_tpu.controllers import ControllerManager
from kubernetes_tpu.scheduler.scheduler import Scheduler
from kubernetes_tpu.testing import locktrace
from kubernetes_tpu.testing.faults import FaultPlan
from kubernetes_tpu.utils.clock import FakeClock


@pytest.fixture
def locktraced(monkeypatch):
    """Run the test with the instrumented-lock harness on: every lock the
    factory hands out (DeviceService, SchedulingQueue, Cache, ClusterStore)
    records acquisitions into the global lock-order graph, and the known
    blocking seams (device dispatch, HTTP, retry sleeps, WAL appends) report
    when fired under a held lock. Teardown asserts the run produced ZERO
    order-inversion cycles and ZERO non-allowed blocking-under-lock events —
    a new nested acquire or a sleep under a component lock fails the suite
    here before it ever wedges production."""
    monkeypatch.setenv("KTPU_LOCKTRACE", "1")
    locktrace.reset()
    yield locktrace.tracer()
    try:
        locktrace.assert_clean()
        # the suites this fixture guards construct traced locks and drive
        # them from multiple threads; a zero-acquisition run means the
        # factory swap silently stopped covering them
        assert locktrace.tracer().acquisitions, \
            "locktrace saw no acquisitions — factory locks not traced?"
    finally:
        locktrace.reset()


def _cluster(store, n=20, cap="8"):
    for i in range(n):
        store.create_node(make_node(f"n{i}").capacity(
            {"cpu": cap, "memory": "16Gi", "pods": 30}).obj())


class TestChurnDuringScheduling:
    def test_node_churn_mid_workload(self):
        """Nodes deleted and added while pods schedule: everything still
        lands, nothing lands on a deleted node (chaosmonkey-style interleave)."""
        store = ClusterStore()
        clock = FakeClock()
        _cluster(store, 20)
        sched = Scheduler(store, now_fn=clock)
        for wave in range(5):
            for i in range(10):
                store.create_pod(make_pod(f"w{wave}-p{i}").req({"cpu": "100m"}).obj())
            # disrupt: drop one node, add a replacement
            store.delete_node(f"n{wave}")
            store.create_node(make_node(f"replacement-{wave}").capacity(
                {"cpu": "8", "memory": "16Gi", "pods": 30}).obj())
            clock.advance(11.0)
            sched.run_until_settled()
        live = set(store.nodes)
        bound = [p for p in store.pods.values() if p.spec.node_name]
        assert len(bound) == 50
        orphans = [p for p in bound if p.spec.node_name not in live]
        # pods bound to since-deleted nodes are PodGC's job, not the
        # scheduler's: they must be from the deleted set only
        assert all(p.spec.node_name.startswith("n") for p in orphans)

    def test_podgc_cleans_after_node_loss(self):
        store = ClusterStore()
        clock = FakeClock()
        _cluster(store, 4)
        sched = Scheduler(store, now_fn=clock)
        for i in range(8):
            store.create_pod(make_pod(f"p{i}").req({"cpu": "100m"}).obj())
        sched.run_until_settled()
        victims = {p.meta.key() for p in store.pods.values() if p.spec.node_name == "n0"}
        store.delete_node("n0")
        m = ControllerManager(store, factory=SharedInformerFactory(store),
                              controllers=["podgc"], now_fn=clock)
        m.settle()
        for key in victims:
            assert store.get_pod(key) is None


class TestCrashOnlyRecovery:
    def test_scheduler_restart_rebuilds_from_store(self):
        """Crash-only: a brand-new Scheduler over the same store resumes
        exactly where the old one stopped (informers relist, §5.3)."""
        store = ClusterStore()
        _cluster(store, 10)
        s1 = Scheduler(store)
        for i in range(10):
            store.create_pod(make_pod(f"a{i}").req({"cpu": "100m"}).obj())
        s1.run_until_settled()
        del s1  # crash
        for i in range(10):
            store.create_pod(make_pod(f"b{i}").req({"cpu": "100m"}).obj())
        s2 = Scheduler(store)
        s2.run_until_settled()
        bound = [p for p in store.pods.values() if p.spec.node_name]
        assert len(bound) == 20

    def test_device_restart_resyncs(self):
        """The device mirror is a cache: dropping it mid-stream (sidecar
        crash analog) forces a full-generation resync and scheduling
        continues (§5.3: restartable mid-stream)."""
        store = ClusterStore()
        _cluster(store, 12)
        sched = TPUScheduler(store, batch_size=8)
        for i in range(10):
            store.create_pod(make_pod(f"a{i}").req({"cpu": "100m"}).obj())
        sched.run_until_settled()
        assert sched.metrics["scheduled"] == 10
        sched.device = None  # device process crash
        for i in range(10):
            store.create_pod(make_pod(f"b{i}").req({"cpu": "100m"}).obj())
        sched.run_until_settled()
        assert sched.metrics["scheduled"] == 20
        # placements respect capacity after resync
        per_node = {}
        for p in store.pods.values():
            per_node[p.spec.node_name] = per_node.get(p.spec.node_name, 0) + 1
        assert all(v <= 30 for v in per_node.values())

    def test_wal_torn_tail_recovery_is_chaos_safe(self, tmp_path):
        """Process dies mid-append: the WAL's last record is torn. Restore
        must recover the clean prefix and scheduling must resume (the
        crash-only contract extended to the log itself)."""
        from kubernetes_tpu.apiserver.wal import attach_wal, restore

        path = str(tmp_path / "store.wal")
        store = ClusterStore()
        attach_wal(store, path)
        _cluster(store, 4)
        for i in range(6):
            store.create_pod(make_pod(f"p{i}").req({"cpu": "100m"}).obj())
        # tear the tail: the crash truncated the final record mid-line
        with open(path, "rb+") as f:
            f.seek(-17, 2)
            f.truncate()
        restored = restore(path)
        assert set(restored.nodes) == {"n0", "n1", "n2", "n3"}
        assert len(restored.pods) == 5  # the torn record's pod is lost, rest live
        sched = Scheduler(restored)
        sched.run_until_settled()
        assert all(p.spec.node_name for p in restored.pods.values())

    def test_assumed_pods_expire_after_ttl(self):
        """Assume-TTL sweep (cache.go:731): an assume never confirmed by a
        bind event expires and the node's resources free up."""
        store = ClusterStore()
        clock = FakeClock()
        sched = Scheduler(store, now_fn=clock, assume_ttl=30.0)
        store.create_node(make_node("n1").capacity(
            {"cpu": "1", "memory": "2Gi", "pods": 5}).obj())
        pod = make_pod("ghost").req({"cpu": "900m"}).obj()
        sched.cache.assume_pod(pod, "n1")
        sched.cache.finish_binding(pod)  # expiry clock starts at finishBinding
        clock.advance(31.0)
        expired = sched.cache.cleanup()
        assert [p.meta.name for p in expired] == ["ghost"]
        ni = sched.cache.nodes["n1"]
        assert ni.requested.milli_cpu == 0


def _bound(store):
    return {p.meta.name: p.spec.node_name
            for p in store.pods.values() if p.spec.node_name}


class TestPipelineRingChaos:
    """Device death with K>1 batches in the in-flight ring (ISSUE 5): every
    poisoned batch — the one being committed AND everything dispatched after
    it — must fail back to the queue with zero lost / double-bound pods, and
    the rebuilt device mirror must be byte-identical to a fresh sync from
    host truth.

    Runs under KTPU_LOCKTRACE=1 (the ``locktraced`` fixture): the ring's
    dispatch/poison/requeue interleavings must produce an acyclic lock-order
    graph and no blocking-under-lock events."""

    @pytest.fixture(autouse=True)
    def _traced(self, locktraced):
        yield

    def _fill_ring(self, monkeypatch):
        monkeypatch.setenv("KTPU_PIPELINE_DEPTH", "2")
        store = ClusterStore()
        _cluster(store, 6)
        # short error backoff: the recovery half of the test must not spin
        # the settle loop's no-progress bound against the real-time backoff
        sched = TPUScheduler(store, batch_size=4, comparer_every_n=1,
                             pod_initial_backoff=0.01, pod_max_backoff=0.05)
        # two waves, one cycle each: both batches sit dispatched in the ring
        for i in range(4):
            store.create_pod(make_pod(f"a{i}").req({"cpu": "100m"}).obj())
        sched.schedule_batch_cycle()
        for i in range(4):
            store.create_pod(make_pod(f"b{i}").req({"cpu": "100m"}).obj())
        sched.schedule_batch_cycle()
        assert len(sched._inflight) == 2, "ring must hold K=2 batches"
        return store, sched

    def test_device_kill_poisons_all_inflight_batches(self, monkeypatch):
        store, sched = self._fill_ring(monkeypatch)
        from kubernetes_tpu.backend import batch as batch_mod

        real_unpack = batch_mod.unpack_result_block

        def dead(*a, **kw):
            raise RuntimeError("relay dropped mid-flight")

        monkeypatch.setattr(batch_mod, "unpack_result_block", dead)
        sched._drain_inflight()
        # ALL in-flight batches poisoned: nothing bound, nothing lost, the
        # ring is empty and the device is marked for rebuild
        assert sched.metrics["scheduled"] == 0
        assert _bound(store) == {}
        assert len(sched._inflight) == 0
        assert sched.device is None
        pending = sched.queue.pending_pods()
        assert sum(pending.values()) == 8, pending

        # device heals: every pod schedules exactly once, capacity respected
        monkeypatch.setattr(batch_mod, "unpack_result_block", real_unpack)
        import time as _time

        _time.sleep(0.06)  # let the (shortened) error backoff expire
        sched.run_until_settled()
        assert sched.metrics["scheduled"] == 8
        bound = _bound(store)
        assert len(bound) == 8  # zero lost
        assert len(store.pods) == 8  # zero duplicated
        assert sched.comparer_mismatches == 0
        per_node = {}
        for n in bound.values():
            per_node[n] = per_node.get(n, 0) + 1
        assert all(v <= 30 for v in per_node.values())

        # byte-identical resync: the rebuilt mirror equals a fresh device
        # synced from the same host snapshot, field for field
        from kubernetes_tpu.backend.device_state import DeviceState

        sched.cache.update_snapshot(sched.snapshot)
        fresh = DeviceState(sched.device.caps,
                            ns_labels_fn=sched.store.ns_labels)
        fresh.sync(sched.snapshot)
        for field, arr in sched.device._mirror.items():
            import numpy as _np

            assert _np.array_equal(arr, fresh._mirror[field]), field

    def test_mid_drain_death_requeues_newer_batches_too(self, monkeypatch):
        """The failure hits while the OLDEST batch commits: the newer
        in-flight batch must be poisoned alongside it, not committed from
        dead futures (the single-slot code handled exactly one stale
        batch; the ring handles them all)."""
        store, sched = self._fill_ring(monkeypatch)
        from kubernetes_tpu.backend import batch as batch_mod

        real_unpack = batch_mod.unpack_result_block
        calls = []

        def die_once(*a, **kw):
            calls.append(1)
            raise RuntimeError("relay dropped")

        monkeypatch.setattr(batch_mod, "unpack_result_block", die_once)
        sched._drain_inflight()
        # the first materialization failed; the SECOND batch must never
        # have been materialized at all (its futures are poison)
        assert len(calls) == 1
        assert sched.metrics["scheduled"] == 0
        monkeypatch.setattr(batch_mod, "unpack_result_block", real_unpack)
        import time as _time

        _time.sleep(0.06)  # let the (shortened) error backoff expire
        sched.run_until_settled()
        assert sched.metrics["scheduled"] == 8
        assert sched.comparer_mismatches == 0


class TestCommitWorkerChaos:
    """Commit data plane, async half: the commit WORKER lands batch K's
    host commit on its own thread while K+1 dispatches. Killing the device
    mid-batch with the worker mid-commit must preserve the ring-poison
    contract exactly — zero lost, zero double-bound, every in-flight batch
    requeued (worker backlog stolen in one sweep; ring stragglers fail the
    device-instance check) — under KTPU_LOCKTRACE (acyclic lock graph, no
    blocking-under-lock across the worker/scheduler interleavings)."""

    @pytest.fixture(autouse=True)
    def _traced(self, locktraced):
        yield

    def _rig(self, monkeypatch):
        monkeypatch.setenv("KTPU_PIPELINE_DEPTH", "2")
        monkeypatch.setenv("KTPU_COMMIT_WORKER", "1")  # force on (CPU box)
        store = ClusterStore()
        _cluster(store, 6)
        sched = TPUScheduler(store, batch_size=4, comparer_every_n=1,
                             pod_initial_backoff=0.01, pod_max_backoff=0.05)
        assert sched.commit_worker is not None
        return store, sched

    def test_steady_state_worker_commits_all(self, monkeypatch):
        store, sched = self._rig(monkeypatch)
        for i in range(24):
            store.create_pod(make_pod(f"p{i}").req({"cpu": "100m"}).obj())
        sched.run_until_settled()
        assert sched.metrics["scheduled"] == 24
        bound = _bound(store)
        assert len(bound) == 24 and len(store.pods) == 24
        assert sched.comparer_mismatches == 0
        assert sched.commit_worker.committed > 0  # commits ran off-thread
        assert sched.commit_plane.pods_bound == sched.batch_scheduled

    def test_worker_kill_mid_batch_poisons_ring(self, monkeypatch):
        from kubernetes_tpu.backend import batch as batch_mod
        from kubernetes_tpu.backend import telemetry

        store, sched = self._rig(monkeypatch)
        tele = telemetry.enable(sched.smetrics)
        # two waves, one cycle each: both batches sit dispatched
        for i in range(4):
            store.create_pod(make_pod(f"a{i}").req({"cpu": "100m"}).obj())
        sched.schedule_batch_cycle()
        for i in range(4):
            store.create_pod(make_pod(f"b{i}").req({"cpu": "100m"}).obj())
        sched.schedule_batch_cycle()

        real_unpack = batch_mod.unpack_result_block
        calls = []

        def dead(*a, **kw):
            calls.append(1)
            raise RuntimeError("relay dropped mid-commit (worker)")

        monkeypatch.setattr(batch_mod, "unpack_result_block", dead)
        sched._drain_inflight()  # submits the ring; flush joins the worker
        # ring-poison semantics preserved across the thread boundary:
        # nothing bound, nothing lost, at most one materialization, device
        # marked for rebuild, every pod back in a queue
        assert len(calls) == 1, "newer batches must never materialize"
        assert sched.metrics["scheduled"] == 0
        assert _bound(store) == {}
        assert len(sched._inflight) == 0
        assert sched.device is None
        pending = sched.queue.pending_pods()
        assert sum(pending.values()) == 8, pending
        # flight events: each poisoned batch logged poison AND requeue
        events = [e for e in tele.flight.dump()
                  if e.get("type") in ("poison", "requeue")]
        poisoned = {e["batchId"] for e in events if e["type"] == "poison"}
        requeued = {e["batchId"] for e in events if e["type"] == "requeue"}
        assert poisoned == requeued and len(poisoned) == 2
        telemetry.disable()

        # heal: the rebuilt device schedules everything exactly once
        monkeypatch.setattr(batch_mod, "unpack_result_block", real_unpack)
        import time as _time

        _time.sleep(0.06)  # let the (shortened) error backoff expire
        sched.run_until_settled()
        assert sched.metrics["scheduled"] == 8
        bound = _bound(store)
        assert len(bound) == 8 and len(store.pods) == 8
        assert sched.comparer_mismatches == 0

        # byte-identical resync: the rebuilt mirror equals a fresh device
        # synced from the same host snapshot
        from kubernetes_tpu.backend.device_state import DeviceState

        sched.cache.update_snapshot(sched.snapshot)
        fresh = DeviceState(sched.device.caps,
                            ns_labels_fn=sched.store.ns_labels)
        fresh.sync(sched.snapshot)
        for field, arr in sched.device._mirror.items():
            assert np.array_equal(arr, fresh._mirror[field]), field

    def test_worker_gang_atomicity_under_churn(self, monkeypatch):
        """Gangs committed THROUGH the worker stay all-or-nothing while
        plain pods interleave — the Permit-park interleaving the batched
        engine must reproduce runs on the worker thread here."""
        from kubernetes_tpu.api.types import ObjectMeta, PodGroup


        store, sched = self._rig(monkeypatch)
        store.create_object("PodGroup", PodGroup(
            meta=ObjectMeta(name="g1", namespace="default"), min_member=3))
        for i in range(3):
            store.create_pod(
                make_pod(f"g1-{i}").req({"cpu": "100m"})
                .pod_group("g1").obj())
        for i in range(5):
            store.create_pod(make_pod(f"solo{i}").req({"cpu": "100m"}).obj())
        sched.run_until_settled()
        bound = _bound(store)
        gang_bound = [k for k in bound if k.startswith("g1-")]
        assert len(gang_bound) in (0, 3), "partial gang must never land"
        assert len(gang_bound) == 3
        assert len(bound) == 8


class _WireRig:
    """A WireScheduler + restartable served DeviceService on an injected
    clock: retry sleeps advance the FakeClock, never the wall clock."""

    def __init__(self, fault_plan=None, nodes=4, **sched_kw):
        self.plan = fault_plan
        self.service = DeviceService(batch_size=32)
        self.server, port = serve(self.service, fault_plan=fault_plan)
        self.store = ClusterStore()
        self.clock = FakeClock()
        self.sleeps = []

        def sleep(s):
            self.sleeps.append(s)
            self.clock.advance(s)

        sched_kw.setdefault("batch_size", 8)
        sched_kw.setdefault("wire_max_retries", 1)
        # the device-fault suites script EXACT fault counts against the
        # delta/batch ops; lease heartbeats would consume wildcard faults
        # and skew the accounting (the HA suite opts back in)
        sched_kw.setdefault("heartbeat_interval_s", 0.0)
        # synchronous transport by default: these scripts assert per-cycle
        # visibility and exact op ordering; the pipelined-wire chaos suite
        # (TestWirePipelineChaos) opts in with an explicit depth
        sched_kw.setdefault("wire_pipeline_depth", 0)
        self.sched = WireScheduler(
            self.store, endpoint=f"http://127.0.0.1:{port}",
            now_fn=self.clock, sleep_fn=sleep, fault_plan=fault_plan,
            **sched_kw)
        for i in range(nodes):
            self.store.create_node(
                make_node(f"n{i}").capacity(
                    {"cpu": "4", "memory": "8Gi", "pods": 10})
                .label("zone", f"z{i % 2}").obj())

    def close(self):
        self.server.shutdown()


class TestGangChaos:
    """Gang all-or-nothing under device failure: a sidecar killed and
    restarted mid-gang must never leave a partially-bound gang, and the
    epoch resync must re-place the gang byte-identically to an uncrashed
    run (ISSUE 4 acceptance, chaos half)."""

    GROUP = "train"

    def _gang_workload(self, store, n=4):
        from kubernetes_tpu.api.types import ObjectMeta, PodGroup


        store.create_object("PodGroup", PodGroup(
            meta=ObjectMeta(name=self.GROUP), min_member=n,
            schedule_timeout_seconds=30))
        for i in range(n):
            store.create_pod(
                make_pod(f"{self.GROUP}-{i}").req({"cpu": "1", "memory": "1Gi"})
                .pod_group(self.GROUP).obj())

    def _gang_bound_count(self, store):
        from kubernetes_tpu.api.types import POD_GROUP_LABEL

        return sum(1 for p in store.pods.values()
                   if p.meta.labels.get(POD_GROUP_LABEL) == self.GROUP
                   and p.spec.node_name)

    def test_device_kill_mid_gang_no_partial_bind(self):
        """The service crashes while the gang's batch is on the wire: after
        the stale-epoch resync the WHOLE gang lands — at no settle point is
        the gang partially bound, and placements match an uncrashed run
        byte for byte."""
        # run A: healthy baseline
        rig_a = _WireRig()
        try:
            self._gang_workload(rig_a.store)
            rig_a.sched.run_until_settled()
            bound_a = _bound(rig_a.store)
        finally:
            rig_a.close()
        assert len(bound_a) == 4

        # run B: the sidecar dies mid-batch (crash + fresh empty epoch)
        plan = FaultPlan().crash("schedule_batch")
        rig_b = _WireRig(fault_plan=plan)
        try:
            self._gang_workload(rig_b.store)
            rig_b.sched.run_until_settled()
            assert self._gang_bound_count(rig_b.store) in (0, 4)  # atomic
            bound_b = _bound(rig_b.store)
            assert rig_b.server.binding.restarts == 1
            assert rig_b.sched.resyncs == 1
            assert len(rig_b.sched.waiting_pods) == 0
            assert rig_b.sched.breaker.state == circuit.CLOSED
        finally:
            rig_b.close()
        assert bound_b == bound_a  # byte-identical across the crash

    def test_crash_between_gang_waves_resyncs_atomically(self):
        """First gang lands, the device restarts, a second gang lands on
        the resynced mirror: both gangs complete, neither ever partial,
        zero degraded fallback."""
        from kubernetes_tpu.api.types import ObjectMeta, PodGroup


        plan = FaultPlan()
        rig = _WireRig(fault_plan=plan)
        try:
            self._gang_workload(rig.store)
            rig.sched.run_until_settled()
            assert self._gang_bound_count(rig.store) == 4
            plan.crash("apply_deltas")  # dies between the waves
            rig.store.create_object("PodGroup", PodGroup(
                meta=ObjectMeta(name="second"), min_member=2,
                schedule_timeout_seconds=30))
            for i in range(2):
                rig.store.create_pod(
                    make_pod(f"second-{i}").req({"cpu": "500m"})
                    .pod_group("second").obj())
            rig.sched.run_until_settled()
            bound = _bound(rig.store)
            assert len(bound) == 6
            assert rig.sched.resyncs == 1
            assert rig.sched.degraded_pods == 0
            # capacity respected on the resynced base: no double-commit
            per_node = {}
            for n in bound.values():
                per_node[n] = per_node.get(n, 0) + 1
            assert all(v <= 4 for v in per_node.values()), per_node
        finally:
            rig.close()


class _Die(RuntimeError):
    """Injected scheduler death: raised from inside a replica's result
    processing, after the service committed the batch — the exact window
    where a real process kill strands adopted-but-unbound capacity."""


class _ReplicaScheduler(WireScheduler):
    """WireScheduler with a partitionable pod keyspace: each active-active
    replica owns a slice of the unbound pods (partition=None owns all);
    observing a peer's fence widens this replica's slice to everything
    before the normal orphan adoption runs."""

    def __init__(self, *args, partition=None, **kwargs):
        self._partition = partition  # before super(): event replay uses it
        super().__init__(*args, **kwargs)

    def _responsible_for(self, pod):
        if not super()._responsible_for(pod):
            return False
        return self._partition is None or self._partition(pod)

    def _adopt_after_takeover(self, dead_client):
        self._partition = None  # adopt the whole keyspace
        super()._adopt_after_takeover(dead_client)


def _assert_oracle_replay_valid(store):
    """Single-scheduler oracle replay validation: every bound placement,
    re-judged by the sequential oracle's filters against the final cluster
    state (the pod itself removed from its node), must pass — and no node
    may exceed its allocatable on any axis."""
    from kubernetes_tpu.framework.interface import CycleState
    from kubernetes_tpu.framework.types import NodeInfo

    infos = {}
    for name, node in store.nodes.items():
        infos[name] = NodeInfo(node)
    bound = []
    for p in store.pods.values():
        if p.spec.node_name:
            assert p.spec.node_name in infos, (p.meta.name, p.spec.node_name)
            infos[p.spec.node_name].add_pod(p)
            bound.append(p)
    for ni in infos.values():
        assert ni.requested.milli_cpu <= ni.allocatable.milli_cpu, ni.node.meta.name
        assert ni.requested.memory <= ni.allocatable.memory, ni.node.meta.name
        assert len(ni.pods) <= ni.allocatable.allowed_pod_number, ni.node.meta.name
    oracle = Scheduler(store)
    oracle.cache.update_snapshot(oracle.snapshot)
    for p in bound:
        fwk = oracle.framework_for_pod(p)
        ni = infos[p.spec.node_name].clone()
        ni.remove_pod(p)
        state = CycleState()
        fwk.run_pre_filter_plugins(state, p)
        st = fwk.run_filter_plugins(state, p, ni)
        assert st.is_success(), (p.meta.name, p.spec.node_name, st.message)


class _HaRig:
    """Two active-active scheduler replicas on ONE device service and ONE
    apiserver store, partitioned pod queues, every clock (lease, backoff,
    heartbeat, breaker) on a single FakeClock — no wall-clock sleeps."""

    LEASE_TTL = 6.0

    def __init__(self, nodes=4, cap="8", partition=True):
        self.clock = FakeClock()
        self.service = DeviceService(batch_size=64, now_fn=self.clock,
                                     lease_ttl_s=self.LEASE_TTL)
        self.server, self.port = serve(self.service)
        self.store = ClusterStore()
        for i in range(nodes):
            self.store.create_node(make_node(f"n{i}").capacity(
                {"cpu": cap, "memory": "16Gi", "pods": 20}).obj())
        part_a = (lambda p: p.meta.name.startswith("a-")) if partition else None
        part_b = (lambda p: p.meta.name.startswith("b-")) if partition else None
        self.a = self._replica("A", part_a)
        self.b = self._replica("B", part_b)

    def _replica(self, cid, partition):
        return _ReplicaScheduler(
            self.store, endpoint=f"http://127.0.0.1:{self.port}",
            batch_size=8, client_id=cid, partition=partition,
            now_fn=self.clock, sleep_fn=lambda s: self.clock.advance(s),
            heartbeat_interval_s=1.0, wire_max_retries=1,
            # synchronous: the kill scripts fire _Die at exact per-cycle
            # commit points (pipelined drains would shift them)
            wire_pipeline_depth=0,
            pod_initial_backoff=0.01, pod_max_backoff=0.05)

    def survive(self, replica, rounds=4, step=2.0):
        """Advance time past the lease TTL in sub-TTL steps, driving
        ``replica`` each step so ITS heartbeats keep its own lease fresh
        while the dead peer's lease runs out — the real deployment shape
        (a jumped shared clock would expire both leases at once)."""
        for _ in range(rounds):
            self.clock.advance(step)
            replica.run_until_settled()

    def close(self):
        self.server.shutdown()


class TestActiveActiveChaos:
    """ISSUE 6 acceptance: two replicas, one DeviceService; killing one
    mid-gang and mid-drain yields zero lost pods and zero double-binds;
    the survivor adopts the fenced capacity within the lease TTL; final
    placements pass single-scheduler oracle replay validation.

    Runs under KTPU_LOCKTRACE=1 (the ``locktraced`` fixture): two replicas
    hammering one DeviceService across serving threads is exactly the
    topology where a lock-order inversion or a blocking call under the
    service lock would deadlock or fence healthy peers — the teardown
    asserts the whole suite observed neither."""

    @pytest.fixture(autouse=True)
    def _traced(self, locktraced):
        yield

    def _gang(self, store, prefix, n=4):
        from kubernetes_tpu.api.types import ObjectMeta, PodGroup


        store.create_object("PodGroup", PodGroup(
            meta=ObjectMeta(name=prefix), min_member=n,
            schedule_timeout_seconds=30))
        for i in range(n):
            store.create_pod(
                make_pod(f"{prefix}-{i}").req({"cpu": "1", "memory": "1Gi"})
                .pod_group(prefix).obj())

    def test_kill_replica_mid_gang_survivor_adopts(self, monkeypatch):
        """Replica A dies after the service committed its gang batch but
        before any member bound (the mid-gang window): the gang's capacity
        sits in server-side holds, the lease fence releases it, and the
        survivor re-places the WHOLE gang — never a partial bind."""
        rig = _HaRig()
        try:
            self._gang(rig.store, "a-train")
            for i in range(2):
                rig.store.create_pod(
                    make_pod(f"b-solo-{i}").req({"cpu": "500m"}).obj())
            rig.b.run_until_settled()
            assert sum(1 for p in rig.store.pods.values()
                       if p.spec.node_name) == 2  # B's slice landed

            def boom(*a, **kw):
                raise _Die("replica A killed mid-gang")

            monkeypatch.setattr(rig.a, "_process_wire_results", boom)
            import pytest

            with pytest.raises(_Die):
                rig.a.schedule_batch_cycle()
            # the service committed the gang: 4 adopted-but-unbound holds
            # occupy real capacity; the store shows the gang unbound (a
            # partial bind never exists at ANY point). Bound pods may hold
            # too until every replica's truth confirms them — count A's
            # UNBOUND holds, the fenced-capacity set.
            unbound_held = [
                k for k, h in rig.service.holds.items()
                if h.owner == "A"
                and not rig.store.get_pod(k).spec.node_name]
            assert len(unbound_held) == 4
            assert all(not p.spec.node_name for p in rig.store.pods.values()
                       if p.meta.name.startswith("a-train"))

            # lease runs out under B's heartbeats: A is fenced, its holds
            # release, B adopts the orphaned slice and lands the gang
            rig.survive(rig.b)
            assert rig.service.sessions["A"].fenced
            assert rig.service.takeovers == 1
            assert rig.b.ha_takeovers == 1
            assert rig.b.smetrics.ha_takeovers.labels() == 1
            bound = _bound(rig.store)
            assert len(bound) == 6  # zero lost
            gang_nodes = {bound[f"a-train-{i}"] for i in range(4)}
            assert len(gang_nodes) == 4  # distinct-node gang, fully placed
            _assert_oracle_replay_valid(rig.store)
        finally:
            rig.close()

    def test_kill_replica_mid_drain_zero_lost_zero_double_bind(self, monkeypatch):
        """Replica A dies mid-way through draining a multi-batch queue:
        batch 1's pods are already bound (they stay), batch 2 was committed
        server-side but never processed (fenced + released), the unpopped
        tail was never sent. The survivor adopts everything unbound; no pod
        is lost, none binds twice, and a zombie commit from the fenced
        session is refused with the typed conflict."""
        import pytest

        from kubernetes_tpu.backend.errors import ConflictError

        rig = _HaRig()
        try:
            for i in range(12):
                rig.store.create_pod(
                    make_pod(f"a-p{i}").req({"cpu": "1", "memory": "1Gi"}).obj())
            for i in range(2):
                rig.store.create_pod(
                    make_pod(f"b-p{i}").req({"cpu": "500m"}).obj())
            rig.b.run_until_settled()
            # batch 1 (8 pods) lands normally on A
            rig.a.schedule_batch_cycle()
            bound_before = _bound(rig.store)
            assert sum(1 for k in bound_before if k.startswith("a-")) == 8
            zombie_gen = rig.a._session_gen
            assert zombie_gen is not None

            def boom(*a, **kw):
                raise _Die("replica A killed mid-drain")

            monkeypatch.setattr(rig.a, "_process_wire_results", boom)
            with pytest.raises(_Die):
                rig.a.schedule_batch_cycle()  # batch 2 committed, A dead
            unbound_held = [
                k for k, h in rig.service.holds.items()
                if h.owner == "A"
                and not rig.store.get_pod(k).spec.node_name]
            assert len(unbound_held) == 4

            rig.survive(rig.b)
            assert rig.service.sessions["A"].fenced
            # the fenced incarnation can never commit again (fencing token)
            with pytest.raises(ConflictError):
                rig.a.client.schedule_batch({
                    "apiVersion": "ktpu/v1", "clientId": "A",
                    "sessionGen": zombie_gen, "pods": [],
                    "batchId": "zombie-late-retry"})

            bound = _bound(rig.store)
            assert len(bound) == 14                      # zero lost
            assert len(rig.store.pods) == 14             # zero duplicated
            for name, node in bound_before.items():
                assert bound[name] == node               # batch 1 undisturbed
            _assert_oracle_replay_valid(rig.store)
        finally:
            rig.close()

    def test_deliberate_race_same_pod_two_clients_single_winner(self):
        """The ownership check, proven by a deliberate race: two sessions
        submit the SAME pod; exactly one gets a placement, the other gets
        the typed conflict verdict, and the capacity is counted once."""
        from kubernetes_tpu.api.codec import to_wire
        from kubernetes_tpu.utils.clock import FakeClock as _FC

        service = DeviceService(batch_size=8, now_fn=_FC())
        node = make_node("n0").capacity(
            {"cpu": "4", "memory": "8Gi", "pods": 10}).obj()
        entry = {"gen": 1, "node": to_wire(node), "pods": []}
        service.apply_deltas({"clientId": "A", "nodes": [entry]})
        service.apply_deltas({"clientId": "B", "nodes": [entry]})
        pod = to_wire(make_pod("raced").req({"cpu": "1"}).obj())
        first = service.schedule_batch(
            {"clientId": "A", "pods": [pod], "batchId": "a-1"})
        assert first["results"][0]["nodeName"] == "n0"
        second = service.schedule_batch(
            {"clientId": "B", "pods": [pod], "batchId": "b-1"})
        assert second["results"][0]["nodeName"] is None
        assert second["results"][0]["conflict"] is True
        assert service.commit_conflicts == 1
        # capacity counted exactly once
        assert service.infos["n0"].requested.milli_cpu == 1000

    def test_lagging_replica_delta_cannot_erase_peer_commit(self):
        """The hold overlay: B pushes a node's content that predates A's
        commit on that node — the service re-overlays A's held pod so the
        capacity stays taken, and a B batch cannot double-allocate it."""
        from kubernetes_tpu.api.codec import to_wire
        from kubernetes_tpu.utils.clock import FakeClock as _FC

        service = DeviceService(batch_size=8, now_fn=_FC())
        node = make_node("n0").capacity(
            {"cpu": "2", "memory": "8Gi", "pods": 10}).obj()
        entry = {"gen": 1, "node": to_wire(node), "pods": []}
        service.apply_deltas({"clientId": "A", "nodes": [entry]})
        service.apply_deltas({"clientId": "B", "nodes": [entry]})
        # A commits a 2-cpu pod: node n0 is now full (held)
        big = to_wire(make_pod("a-big").req({"cpu": "2"}).obj())
        out = service.schedule_batch({"clientId": "A", "pods": [big],
                                      "batchId": "a-1"})
        assert out["results"][0]["nodeName"] == "n0"
        # B's lagging push re-sends n0 WITHOUT a-big: the hold re-overlays
        service.apply_deltas({"clientId": "B",
                              "nodes": [{"gen": 2, "node": to_wire(node),
                                         "pods": []}]})
        assert service.infos["n0"].requested.milli_cpu == 2000
        # B's batch finds no room on n0 (no double-allocation)
        small = to_wire(make_pod("b-small").req({"cpu": "1"}).obj())
        out_b = service.schedule_batch({"clientId": "B", "pods": [small],
                                        "batchId": "b-1"})
        assert out_b["results"][0]["nodeName"] is None

    def test_two_replicas_shared_keyspace_never_oversubscribe(self):
        """Both replicas responsible for EVERY pod (no partition), driven
        interleaved against one service on an exactly-filling workload: all
        pods land exactly once, no node oversubscribes, and the run passes
        oracle replay — the two-replica concurrent acceptance check."""
        rig = _HaRig(nodes=4, cap="4", partition=False)
        try:
            for i in range(16):  # 16 × 1cpu == 4 nodes × 4cpu: exact fill
                rig.store.create_pod(
                    make_pod(f"p{i}").req({"cpu": "1", "memory": "1Gi"}).obj())
            for _ in range(300):
                rig.a.schedule_batch_cycle()
                rig.b.schedule_batch_cycle()
                rig.clock.advance(0.1)
                rig.a.queue.flush_backoff_completed()
                rig.b.queue.flush_backoff_completed()
                if len(_bound(rig.store)) == 16:
                    break
            bound = _bound(rig.store)
            assert len(bound) == 16
            per_node = {}
            for n in bound.values():
                per_node[n] = per_node.get(n, 0) + 1
            assert all(v <= 4 for v in per_node.values()), per_node
            _assert_oracle_replay_valid(rig.store)
            assert rig.service.takeovers == 0  # both leases stayed fresh
        finally:
            rig.close()


class TestDeviceServiceFaults:
    """The device-failure acceptance suite: sidecar killed mid-batch,
    restart + epoch resync, breaker-open oracle degradation and heal."""

    def test_crash_mid_batch_no_pod_lost_or_double_bound(self):
        """The service dies while a batch is on the wire: the retry hits
        the restarted (empty, new-epoch) service, the stale-epoch error
        forces a full resync, and the batch lands — every pod bound exactly
        once, none lost, capacity respected."""
        plan = FaultPlan().crash("schedule_batch")
        rig = _WireRig(fault_plan=plan)
        try:
            for i in range(12):
                rig.store.create_pod(
                    make_pod(f"p{i}").req({"cpu": "1", "memory": "1Gi"}).obj())
            rig.sched.run_until_settled()
            bound = _bound(rig.store)
            assert len(bound) == 12                      # zero lost
            assert rig.server.binding.restarts == 1      # the crash fired
            assert rig.sched.resyncs == 1                # epoch-detected
            assert rig.sched.breaker.state == circuit.CLOSED
            per_node = {}
            for n in bound.values():
                per_node[n] = per_node.get(n, 0) + 1
            # zero double-bound: occupancy within capacity on the resynced
            # base (a double-commit would overshoot 4 cpu / 1 cpu each)
            assert all(v <= 4 for v in per_node.values()), per_node
            assert ("server", "schedule_batch", "crash") in plan.log
        finally:
            rig.close()

    def test_restart_resyncs_to_identical_placements(self):
        """A restarted device service is detected via epoch mismatch and
        fully resynced: placements are byte-identical to an uncrashed run
        AND to the sequential oracle, with zero permanent fallback (the
        batched wire path resumes)."""
        def workload(store):
            for i in range(6):
                store.create_pod(
                    make_pod(f"a{i}").req({"cpu": "500m", "memory": "1Gi"}).obj())

        def workload2(store):
            for i in range(6):
                store.create_pod(
                    make_pod(f"b{i}").req({"cpu": "700m", "memory": "1Gi"}).obj())

        # run A: healthy service end to end
        rig_a = _WireRig()
        try:
            workload(rig_a.store)
            rig_a.sched.run_until_settled()
            workload2(rig_a.store)
            rig_a.sched.run_until_settled()
            bound_a = _bound(rig_a.store)
        finally:
            rig_a.close()

        # run B: the service crashes (and restarts empty) between the waves
        plan = FaultPlan()
        rig_b = _WireRig(fault_plan=plan)
        try:
            workload(rig_b.store)
            rig_b.sched.run_until_settled()
            epoch_before = rig_b.sched._device_epoch
            plan.crash("apply_deltas")  # the sidecar dies between the waves
            workload2(rig_b.store)
            rig_b.sched.run_until_settled()
            bound_b = _bound(rig_b.store)
            assert rig_b.server.binding.restarts == 1
            assert rig_b.sched.resyncs == 1
            assert rig_b.sched._device_epoch != epoch_before
            assert rig_b.sched._device_epoch == rig_b.server.binding.service.epoch
            # zero permanent fallback: nothing went through the degraded
            # oracle path and the breaker never opened
            assert rig_b.sched.degraded_pods == 0
            assert rig_b.sched.breaker.state == circuit.CLOSED
            assert rig_b.server.binding.service.batch_counter > 0
        finally:
            rig_b.close()
        assert bound_b == bound_a  # byte-identical across the crash

        # oracle-identical: the same workload through the sequential path
        store_o = ClusterStore()
        for i in range(4):
            store_o.create_node(
                make_node(f"n{i}").capacity(
                    {"cpu": "4", "memory": "8Gi", "pods": 10})
                .label("zone", f"z{i % 2}").obj())
        sched_o = Scheduler(store_o)
        workload(store_o)
        sched_o.run_until_settled()
        workload2(store_o)
        sched_o.run_until_settled()
        assert _bound(store_o) == bound_a

    def test_breaker_opens_degrades_to_oracle_and_heals(self):
        """A flapping/dead service: transient failures re-enter pods via
        the backoff queue, the breaker opens after the threshold and every
        pod schedules through the sequential oracle (throughput never
        zero), scheduler_degraded_seconds_total grows, and once the
        service behaves a half-open probe closes the breaker and the
        batched wire path resumes."""
        # 6 drops: 2 per wire flush (initial + 1 retry) — flush 1 counts
        # breaker failure #1, flush 2 opens it, the first probe re-opens it
        plan = FaultPlan().drop(count=6)
        rig = _WireRig(fault_plan=plan, breaker_threshold=2, breaker_reset_s=5.0)
        m = rig.sched.smetrics
        try:
            for i in range(6):
                rig.store.create_pod(
                    make_pod(f"p{i}").req({"cpu": "500m", "memory": "1Gi"}).obj())
            # flush 1: transport fails after retry → rate-limited requeue
            rig.sched.run_until_settled()
            assert rig.sched.metrics["scheduled"] == 0
            assert rig.sched.queue.pending_pods()["backoff"] == 6
            assert m.wire_retries.labels("apply_deltas") > 0
            assert rig.sched.breaker.state == circuit.CLOSED

            # flush 2 (after backoff): fails again → breaker OPENS → the
            # batch degrades to the oracle path in the same cycle
            rig.clock.advance(1.1)
            rig.sched.run_until_settled()
            assert rig.sched.breaker.state == circuit.OPEN
            assert m.backend_circuit_state.labels() == 2
            assert rig.sched.metrics["scheduled"] == 6   # throughput nonzero
            assert rig.sched.degraded_pods == 6

            # still open (reset timeout not reached): new pods keep landing
            # through the oracle; degraded seconds accrue on the fake clock
            rig.clock.advance(2.0)
            for i in range(2):
                rig.store.create_pod(
                    make_pod(f"q{i}").req({"cpu": "200m"}).obj())
            rig.sched.run_until_settled()
            assert rig.sched.metrics["scheduled"] == 8
            assert rig.sched.degraded_pods == 8
            assert m.degraded_seconds.labels() > 0

            # first half-open probe: the remaining 2 drops kill it → the
            # breaker re-opens, the probe batch still lands via the oracle
            rig.clock.advance(5.5)
            for i in range(2):
                rig.store.create_pod(
                    make_pod(f"r{i}").req({"cpu": "200m"}).obj())
            rig.sched.run_until_settled()
            assert plan.pending() == 0
            assert rig.sched.breaker.state == circuit.OPEN
            assert rig.sched.metrics["scheduled"] == 10

            # faults exhausted: the next probe succeeds, the breaker closes,
            # and the batched wire path resumes (device sees real batches)
            rig.clock.advance(5.5)
            for i in range(2):
                rig.store.create_pod(
                    make_pod(f"s{i}").req({"cpu": "200m"}).obj())
            rig.sched.run_until_settled()
            assert rig.sched.breaker.state == circuit.CLOSED
            assert m.backend_circuit_state.labels() == 0
            assert rig.sched.metrics["scheduled"] == 12
            assert rig.server.binding.service.batch_counter > 0
            assert rig.sched._device_epoch == rig.server.binding.service.epoch
            # degraded window closed: total seconds strictly positive and
            # the open→close span is accounted exactly once
            assert m.degraded_seconds.labels() > 0
        finally:
            rig.close()


class TestFlightRecorderChaos:
    """ISSUE 7 acceptance: after a kill-mid-drain run the flight recorder
    (read over the REAL /debug/flightrecorder endpoint, not the in-process
    object) carries the poison/requeue event sequence for every affected
    batchId; the HA suite's lease fence lands a fence event naming the dead
    client and its last committed batchId. Postmortems read the ring, not
    print-debugging."""

    def _debug_get(self, sched, path):
        import json
        import urllib.request

        from kubernetes_tpu.cmd.server import (
            ComponentServer, build_debug_handlers)

        server = ComponentServer(configz={}, debug=build_debug_handlers(sched))
        port = server.start()
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}{path}", timeout=10) as r:
                return json.loads(r.read())
        finally:
            server.stop()

    def test_kill_mid_drain_poison_requeue_sequence_per_batch_id(
            self, monkeypatch):
        from kubernetes_tpu.backend import batch as batch_mod
        from kubernetes_tpu.backend import telemetry

        telemetry.enable()
        try:
            monkeypatch.setenv("KTPU_PIPELINE_DEPTH", "2")
            store = ClusterStore()
            _cluster(store, 6)
            sched = TPUScheduler(store, batch_size=4, comparer_every_n=1,
                                 pod_initial_backoff=0.01,
                                 pod_max_backoff=0.05)
            for i in range(4):
                store.create_pod(make_pod(f"a{i}").req({"cpu": "100m"}).obj())
            sched.schedule_batch_cycle()
            for i in range(4):
                store.create_pod(make_pod(f"b{i}").req({"cpu": "100m"}).obj())
            sched.schedule_batch_cycle()
            assert len(sched._inflight) == 2
            affected = [fl.batch_id for fl in sched._inflight]

            def dead(*a, **kw):
                raise RuntimeError("relay dropped mid-drain")

            monkeypatch.setattr(batch_mod, "unpack_result_block", dead)
            sched._drain_inflight()
            assert sched.metrics["scheduled"] == 0

            body = self._debug_get(sched, "/debug/flightrecorder")
            assert body["enabled"] is True
            events = body["events"]
            assert body["ring"]["held"] == len(events)
            for bid in affected:
                seq = [e["type"] for e in events if e.get("batchId") == bid]
                # the full lifecycle per poisoned batch, in ring order:
                # dispatched, then poisoned by the device death, then every
                # pod requeued via backoffQ
                assert seq.index("dispatch") < seq.index("poison") \
                    < seq.index("requeue"), (bid, seq)
                poison = next(e for e in events
                              if e.get("batchId") == bid
                              and e["type"] == "poison")
                assert poison["pods"] == 4
                assert "relay dropped" in poison["error"]
            # nothing outside the two affected batches was poisoned
            assert sum(1 for e in events if e["type"] == "poison") == 2
        finally:
            telemetry.disable()

    def test_ha_lease_fence_event_names_client_and_batch_id(self, monkeypatch):
        from kubernetes_tpu.backend import telemetry

        tele = telemetry.enable()
        rig = _HaRig()
        try:
            for i in range(8):
                rig.store.create_pod(
                    make_pod(f"a-p{i}").req({"cpu": "1", "memory": "1Gi"}).obj())
            for i in range(2):
                rig.store.create_pod(
                    make_pod(f"b-p{i}").req({"cpu": "500m"}).obj())
            rig.b.run_until_settled()

            def boom(*a, **kw):
                raise _Die("replica A killed mid-drain")

            monkeypatch.setattr(rig.a, "_process_wire_results", boom)
            import pytest as _pytest

            with _pytest.raises(_Die):
                rig.a.schedule_batch_cycle()
            # the service committed A's batch: its id is in the commit event
            commits_a = [e for e in tele.flight.events("commit")
                         if e.get("client") == "A"]
            assert commits_a, "server-side commit event missing"
            a_batch_id = commits_a[-1]["batchId"]
            assert a_batch_id

            rig.survive(rig.b)
            assert rig.service.sessions["A"].fenced
            fences = [e for e in tele.flight.events("fence")
                      if e.get("client") == "A"]
            assert len(fences) == 1
            # the fence names the dead client's last committed batch — the
            # postmortem link from "capacity released" back to the batch
            # whose holds were fenced
            assert fences[0]["batchId"] == a_batch_id
            assert fences[0]["releasedHolds"] > 0
            # the survivor recorded its takeover of the fenced peer
            takeovers = [e for e in tele.flight.events("takeover")
                         if e.get("fencedPeer") == "A"]
            assert len(takeovers) == 1
            assert takeovers[0]["client"] == "B"
            # and the fence ordered strictly after A's commit in the ring
            assert fences[0]["seq"] > commits_a[-1]["seq"]
        finally:
            rig.close()
            telemetry.disable()


class _FabricRig:
    """A WireScheduler over N served DeviceServices through the
    DeviceFabric (backend/fabric.py), one FaultPlan per endpoint so chaos
    scripts scope to a single replica. Every clock (retry backoff, breaker,
    probe interval) rides one FakeClock — no wall-clock sleeps."""

    def __init__(self, nodes=4, cap="4", replicas=2, **sched_kw):
        self.clock = FakeClock()
        self.plans = [FaultPlan() for _ in range(replicas)]
        self.services = [DeviceService(batch_size=32, now_fn=self.clock)
                         for _ in range(replicas)]
        self.servers = []
        self.endpoints = []
        for svc, plan in zip(self.services, self.plans):
            server, port = serve(svc, fault_plan=plan)
            self.servers.append(server)
            self.endpoints.append(f"http://127.0.0.1:{port}")
        self.store = ClusterStore()
        for i in range(nodes):
            self.store.create_node(make_node(f"n{i}").capacity(
                {"cpu": cap, "memory": "16Gi", "pods": 10}).obj())
        sched_kw.setdefault("batch_size", 8)
        sched_kw.setdefault("wire_max_retries", 1)
        # fault scripts count exact ops per endpoint; heartbeats off
        sched_kw.setdefault("heartbeat_interval_s", 0.0)
        # synchronous transport: the per-endpoint scripts assert per-cycle
        # visibility (the pipelined fabric suite opts in with K>=3)
        sched_kw.setdefault("wire_pipeline_depth", 0)
        sched_kw.setdefault("pod_initial_backoff", 0.01)
        sched_kw.setdefault("pod_max_backoff", 0.05)
        self.sched = WireScheduler(
            self.store, endpoint=self.endpoints, fault_plan=self.plans,
            now_fn=self.clock, sleep_fn=lambda s: self.clock.advance(s),
            **sched_kw)

    def settle(self, rounds=2, step=1.1):
        """Drive the scheduler with clock advances between rounds so
        error-requeued pods clear their backoff windows."""
        self.sched.run_until_settled()
        for _ in range(rounds):
            self.clock.advance(step)
            self.sched.run_until_settled()

    def active_service(self):
        return self.services[self.sched.client.active_replica().index]

    def close(self):
        for s in self.servers:
            s.shutdown()


def _assert_resync_mirror_identical(rig):
    """Byte-identical post-resync mirror: force a FULL resync into the
    surviving replica and assert its rebuilt device mirror equals, array
    for array, the state it already held — i.e. the post-failover state
    is exactly what a from-scratch sync of host truth produces (the wire
    twin of TestPipelineRingChaos's fresh-device comparison)."""
    svc = rig.active_service()
    before = {k: v.copy() for k, v in svc.device._mirror.items()}
    rig.sched._full_resync(svc.epoch)
    after = svc.device._mirror
    assert set(before) == set(after)
    for field, arr in before.items():
        assert np.array_equal(arr, after[field]), field


class TestDeviceFabricChaos:
    """ISSUE 10 acceptance: N DeviceService replicas behind one
    DeviceFabric. Killing the primary mid-gang and mid-drain, an
    asymmetric partition, a slow standby, a flapping primary, and
    all-replicas-down each complete with zero lost pods, zero
    double-binds, a byte-identical post-resync mirror on the surviving
    replica, and placements that pass single-scheduler oracle replay.

    Runs under KTPU_LOCKTRACE=1 (the ``locktraced`` fixture): failover
    probes and transport calls must never fire under the fabric lock, and
    the whole suite must produce an acyclic lock-order graph."""

    GROUP = "train"

    @pytest.fixture(autouse=True)
    def _traced(self, locktraced):
        yield

    def _gang(self, store, n=4):
        from kubernetes_tpu.api.types import ObjectMeta, PodGroup


        store.create_object("PodGroup", PodGroup(
            meta=ObjectMeta(name=self.GROUP), min_member=n,
            schedule_timeout_seconds=30))
        for i in range(n):
            store.create_pod(
                make_pod(f"{self.GROUP}-{i}").req({"cpu": "1", "memory": "1Gi"})
                .pod_group(self.GROUP).obj())

    def test_primary_kill_mid_gang_fails_over_whole_gang(self):
        """The primary dies while the gang's batch is on the wire (deltas
        landed, ScheduleBatch never answers): the fabric poisons the
        in-flight batch, promotes the standby, the epoch resync seeds it
        under a fresh session, and the WHOLE gang lands there — never a
        partial bind, nothing replayed."""
        from kubernetes_tpu.testing.faults import SCHEDULE_BATCH

        rig = _FabricRig(cap="8")
        try:
            self._gang(rig.store)
            # deltas reach the primary; the gang batch dies with it
            rig.plans[0].partition(SCHEDULE_BATCH)
            rig.settle()
            bound = _bound(rig.store)
            assert len(bound) == 4                        # zero lost
            assert len(rig.store.pods) == 4               # zero duplicated
            gang_nodes = {bound[f"{self.GROUP}-{i}"] for i in range(4)}
            assert len(gang_nodes) == 4                   # distinct, whole
            assert len(rig.sched.waiting_pods) == 0       # never parked partial
            fab = rig.sched.client
            assert fab.failovers == 1
            assert fab.active_endpoint() == rig.endpoints[1]
            assert rig.sched.smetrics.fabric_failovers.labels("transient") == 1
            # the primary never computed the gang; the standby computed it
            # exactly once — idempotent batch ids, nothing replayed
            assert rig.services[0].batch_counter == 0
            assert rig.services[1].batch_counter >= 1
            assert rig.services[1].batch_replays == 0
            # failover is a replica hop, not a degrade: the oracle path
            # never fired and the scheduler breaker stayed closed
            assert rig.sched.degraded_pods == 0
            assert rig.sched.breaker.state == circuit.CLOSED
            _assert_oracle_replay_valid(rig.store)
            _assert_resync_mirror_identical(rig)
        finally:
            rig.close()

    def test_primary_kill_mid_drain_batch1_undisturbed(self):
        """The primary dies mid-way through draining a multi-batch queue:
        batch 1's binds stay exactly where they are, the in-flight work
        requeues, and the remainder lands on the re-seeded standby within
        capacity — zero lost, zero double-bound."""
        rig = _FabricRig(cap="8")
        try:
            for i in range(12):
                rig.store.create_pod(
                    make_pod(f"p{i}").req({"cpu": "1", "memory": "1Gi"}).obj())
            rig.sched.schedule_batch_cycle()               # batch 1 on A
            bound_before = _bound(rig.store)
            assert len(bound_before) == 8
            assert rig.services[0].batch_counter == 1
            rig.plans[0].kill()                            # primary dies
            rig.settle()
            bound = _bound(rig.store)
            assert len(bound) == 12                        # zero lost
            assert len(rig.store.pods) == 12               # zero duplicated
            for name, node in bound_before.items():
                assert bound[name] == node                 # batch 1 untouched
            per_node = {}
            for n in bound.values():
                per_node[n] = per_node.get(n, 0) + 1
            assert all(v <= 8 for v in per_node.values()), per_node
            assert rig.sched.client.failovers == 1
            assert rig.sched.degraded_pods == 0
            _assert_oracle_replay_valid(rig.store)
            _assert_resync_mirror_identical(rig)
        finally:
            rig.close()

    def test_asymmetric_partition_fails_over_despite_healthy_probe(self):
        """Batch traffic to the primary is dropped while its Health verb
        still answers — the failure a health-only detector never catches.
        Failure detection is call-driven, so the fabric still fails over;
        the partitioned primary later rejoins as a STANDBY (sticky
        selection: never re-adopted mid-flight)."""
        rig = _FabricRig()
        try:
            rig.plans[0].partition()
            for i in range(6):
                rig.store.create_pod(
                    make_pod(f"p{i}").req({"cpu": "500m"}).obj())
            rig.settle()
            bound = _bound(rig.store)
            assert len(bound) == 6
            fab = rig.sched.client
            assert fab.failovers == 1
            assert fab.active_endpoint() == rig.endpoints[1]
            # A's health answers: the rate-limited standby probe marks it
            # healthy again — but the active NEVER flips back mid-flight
            rig.clock.advance(6.0)
            rig.store.create_pod(make_pod("late").req({"cpu": "500m"}).obj())
            rig.settle(rounds=1)
            assert fab.replicas[0].healthy is True
            assert fab.active_endpoint() == rig.endpoints[1]  # sticky
            assert rig.sched.smetrics.fabric_replica_health.labels(
                rig.endpoints[0]) == 1
            assert len(_bound(rig.store)) == 7
            _assert_oracle_replay_valid(rig.store)
            _assert_resync_mirror_identical(rig)
        finally:
            rig.close()

    def test_slow_standby_absorbed_then_adopted_on_failover(self):
        """A laggy-but-live standby (persistent delay under the read
        deadline) must not destabilize the healthy primary; when the
        primary dies the slow standby is still adopted and serves."""
        rig = _FabricRig(cap="8")
        try:
            rig.plans[1].slow(0.05)
            for i in range(4):
                rig.store.create_pod(
                    make_pod(f"a{i}").req({"cpu": "1"}).obj())
            rig.settle(rounds=1)
            assert len(_bound(rig.store)) == 4
            assert rig.sched.client.failovers == 0        # slowness != death
            rig.plans[0].kill()
            for i in range(4):
                rig.store.create_pod(
                    make_pod(f"b{i}").req({"cpu": "1"}).obj())
            rig.settle()
            assert len(_bound(rig.store)) == 8
            assert rig.sched.client.failovers == 1
            # the slow script really fired (delays absorbed, not raised)
            assert any(k == "delay" for _, _, k in rig.plans[1].log)
            _assert_oracle_replay_valid(rig.store)
        finally:
            rig.close()

    def test_flapping_primary_reseeded_on_failback_never_adopted_stale(self):
        """Partition A → fail over to B → heal A (same epoch, STALE
        mirror) → kill B → fail back to A. The rejoined ex-primary must be
        re-seeded by a full resync (the client's known epoch is B's, so
        A's first answer is the stale-epoch verdict) — its stale mirror is
        never trusted mid-flight, and every wave lands exactly once."""
        rig = _FabricRig(cap="8")
        try:
            for i in range(4):
                rig.store.create_pod(make_pod(f"w1-{i}").req({"cpu": "1"}).obj())
            rig.settle(rounds=1)                           # wave 1 on A
            assert rig.sched.client.failovers == 0
            resyncs_before = rig.sched.resyncs
            rig.plans[0].partition()
            for i in range(4):
                rig.store.create_pod(make_pod(f"w2-{i}").req({"cpu": "1"}).obj())
            rig.settle()                                   # wave 2 → B
            assert rig.sched.client.failovers == 1
            assert rig.sched.resyncs > resyncs_before      # B was seeded
            rig.plans[0].heal()
            rig.clock.advance(6.0)
            for i in range(2):
                rig.store.create_pod(make_pod(f"w3-{i}").req({"cpu": "1"}).obj())
            rig.settle(rounds=1)                           # wave 3 on B; A rejoins
            assert rig.sched.client.replicas[0].healthy
            assert rig.sched.client.active_endpoint() == rig.endpoints[1]
            resyncs_mid = rig.sched.resyncs
            rig.plans[1].kill()
            for i in range(2):
                rig.store.create_pod(make_pod(f"w4-{i}").req({"cpu": "1"}).obj())
            rig.settle()                                   # wave 4 → back to A
            fab = rig.sched.client
            assert fab.failovers == 2
            assert fab.active_endpoint() == rig.endpoints[0]
            # the failback re-seeded A: a full resync fired against its
            # unchanged epoch (stale-mirror detection, not blind adoption)
            assert rig.sched.resyncs > resyncs_mid
            bound = _bound(rig.store)
            assert len(bound) == 12 and len(rig.store.pods) == 12
            _assert_oracle_replay_valid(rig.store)
            _assert_resync_mirror_identical(rig)
        finally:
            rig.close()

    def test_all_replicas_down_degrades_to_oracle_then_heals(self):
        """The last rung of the ladder: with EVERY replica dead the
        original transport error reaches the scheduler breaker, which
        opens and routes pods through the sequential oracle — throughput
        never zero. When a replica heals, the half-open probe rides the
        fabric's health() and the batched path resumes on it."""
        rig = _FabricRig(breaker_threshold=2, cap="8")
        try:
            rig.plans[0].kill()
            rig.plans[1].kill()
            for i in range(6):
                rig.store.create_pod(
                    make_pod(f"p{i}").req({"cpu": "1"}).obj())
            rig.settle()
            bound = _bound(rig.store)
            assert len(bound) == 6                         # oracle landed them
            assert rig.sched.degraded_pods >= 6
            assert rig.sched.breaker.state == circuit.OPEN
            assert rig.sched.client.failovers == 0         # nowhere to go
            assert rig.services[0].batch_counter == 0
            assert rig.services[1].batch_counter == 0

            rig.plans[0].heal()                            # A comes back
            rig.clock.advance(5.5)                         # past breaker reset
            for i in range(2):
                rig.store.create_pod(
                    make_pod(f"q{i}").req({"cpu": "1"}).obj())
            rig.settle()
            assert rig.sched.breaker.state == circuit.CLOSED
            assert len(_bound(rig.store)) == 8             # zero lost
            assert rig.services[0].batch_counter > 0       # batched path back
            # the open→close degraded window is accounted on the fake clock
            assert rig.sched.smetrics.degraded_seconds.labels() > 0
            _assert_oracle_replay_valid(rig.store)
            _assert_resync_mirror_identical(rig)
        finally:
            rig.close()

    def _debug_get(self, sched, path):
        import json
        import urllib.request

        from kubernetes_tpu.cmd.server import (
            ComponentServer, build_debug_handlers)

        server = ComponentServer(configz={}, debug=build_debug_handlers(sched))
        port = server.start()
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}{path}", timeout=10) as r:
                return json.loads(r.read())
        finally:
            server.stop()

    def test_failover_flight_event_ordered_after_last_poison(self):
        """ISSUE 10 acceptance, observability half: read over the REAL
        /debug/flightrecorder endpoint, the failover event is ordered
        strictly after the last poisoned batch's poison event and names
        both endpoints + the batch; /debug/fabric serves the replica
        table with the uniform ?limit= capping."""
        from kubernetes_tpu.backend import telemetry
        from kubernetes_tpu.testing.faults import SCHEDULE_BATCH

        telemetry.enable()
        rig = _FabricRig()
        try:
            rig.plans[0].partition(SCHEDULE_BATCH)  # batch dies in flight
            for i in range(4):
                rig.store.create_pod(
                    make_pod(f"p{i}").req({"cpu": "500m"}).obj())
            rig.settle()
            assert len(_bound(rig.store)) == 4
            assert rig.sched.smetrics.fabric_failovers.labels("transient") == 1

            body = self._debug_get(rig.sched, "/debug/flightrecorder")
            assert body["enabled"] is True
            events = body["events"]
            poisons = [e for e in events if e["type"] == "poison"]
            failovers = [e for e in events if e["type"] == "failover"]
            downs = [e for e in events if e["type"] == "replica_down"]
            assert poisons and failovers and downs
            last_poison = max(e["seq"] for e in poisons)
            assert failovers[0]["seq"] > last_poison
            assert failovers[0]["batchId"] == poisons[-1]["batchId"]
            assert failovers[0]["fromEndpoint"] == rig.endpoints[0]
            assert failovers[0]["endpoint"] == rig.endpoints[1]
            assert downs[0]["endpoint"] == rig.endpoints[0]
            # the poisoned batch's pods were requeued (ring lifecycle)
            requeues = [e for e in events if e["type"] == "requeue"]
            assert requeues and requeues[0]["seq"] > last_poison

            fab = self._debug_get(rig.sched, "/debug/fabric")
            assert fab["enabled"] is True and fab["activeIndex"] == 1
            assert [r["endpoint"] for r in fab["replicas"]] == rig.endpoints
            assert fab["log"][0]["from"] == rig.endpoints[0]
            capped = self._debug_get(rig.sched, "/debug/fabric?limit=0")
            assert capped["log"] == [] and capped["replicas"] == []
            assert capped["truncated"]["replicas"] == 2
        finally:
            rig.close()
            telemetry.disable()


class TestElasticChaos:
    """ISSUE 12 acceptance: cluster elasticity as a chaos-proven capability.
    A 30%-of-cluster add/remove storm, a rolling gang-aware drain wave, and
    a mass spot reclamation each overlap in-flight batches (ring depth 2);
    a fourth scenario overlaps node churn with a fabric failover. Standing
    invariants throughout: zero lost pods, zero double-binds, byte-identical
    post-resync mirrors, oracle-replay-valid placements — plus the new
    shrink-direction guarantees: a commit naming a reclaimed slot is a
    TYPED rejection (backoffQ requeue), never a ghost placement, and
    tombstoned slots are reused instead of growing the node axis.

    Runs under KTPU_LOCKTRACE=1 (the ``locktraced`` fixture): the removal
    sweep and slot free-list ride the same device path the lock passes
    cover; the teardown asserts an acyclic lock graph and zero non-allowed
    blocking events."""

    @pytest.fixture(autouse=True)
    def _traced(self, locktraced):
        yield

    @pytest.fixture(autouse=True)
    def _flight(self):
        from kubernetes_tpu.backend import telemetry

        self.tele = telemetry.enable()
        yield
        telemetry.disable()

    def _ring_sched(self, monkeypatch, store, batch=4, **kw):
        monkeypatch.setenv("KTPU_PIPELINE_DEPTH", "2")
        kw.setdefault("pod_initial_backoff", 0.01)
        kw.setdefault("pod_max_backoff", 0.05)
        return TPUScheduler(store, batch_size=batch, **kw)

    def _assert_mirror_byte_identical(self, sched):
        """Post-resync byte-identity, free-list aware: slot AND vocab-id
        reuse make the churned encoder's mapping legitimately differ from a
        fresh encoder's, so identity is judged the way the fabric suite
        does — a forced FULL re-encode of host truth through the SAME
        device leaves every mirror array byte-identical (any drift between
        mirror and host truth would rewrite rows), the slot map covers
        exactly the live nodes, and every tombstoned slot still holds the
        empty-row encoding."""
        from kubernetes_tpu.backend.device_state import DeviceState
        from kubernetes_tpu.framework.types import NodeInfo

        sched._drain_inflight()
        sched._ensure_device()
        sched.cache.update_snapshot(sched.snapshot)
        dev = sched.device
        dev.sync(sched.snapshot)
        before = {f: arr.copy() for f, arr in dev._mirror.items()}
        dev._uploaded_gen.clear()  # force a full re-encode of every row
        dev._mirror_node.clear()
        dev.sync(sched.snapshot)
        for field, arr in dev._mirror.items():
            assert np.array_equal(arr, before[field]), field
        assert set(dev.encoder.node_slots) == set(
            sched.snapshot.node_info_map)
        empty_row = dev.encoder.encode_node_row(NodeInfo())
        assigned = set(dev.encoder.node_slots.values())
        from kubernetes_tpu.backend.device_state import _ROW_FIELDS

        for slot in range(dev.caps.nodes):
            if slot in assigned:
                continue
            for field, dtype in _ROW_FIELDS:
                assert np.array_equal(
                    dev._mirror[field][slot],
                    np.asarray(empty_row[field], dtype)), (field, slot)

    def test_node_delete_midflight_typed_rejection_no_ghost(self, monkeypatch):
        """Regression (ISSUE 12 satellite): a node deleted while a
        ring-depth-2 in-flight batch holds a placement on it — the commit
        rejects with a typed verdict, the pods requeue via backoffQ, and no
        ghost placement survives on the device or in the cache."""
        store = ClusterStore()
        store.create_node(make_node("doomed").capacity(
            {"cpu": "8", "memory": "16Gi", "pods": 30}).obj())
        sched = self._ring_sched(monkeypatch, store)
        for i in range(4):
            store.create_pod(make_pod(f"a{i}").req({"cpu": "100m"}).obj())
        sched.schedule_batch_cycle()
        for i in range(4):
            store.create_pod(make_pod(f"b{i}").req({"cpu": "100m"}).obj())
        sched.schedule_batch_cycle()
        assert len(sched._inflight) == 2, "ring must hold K=2 batches"
        # the only node leaves while both batches are in flight
        store.delete_node("doomed")
        sched._drain_inflight()
        # typed rejection, never a ghost: nothing bound, nothing lost
        assert sched.metrics["scheduled"] == 0
        assert _bound(store) == {}
        assert sched.metrics["errors"] == 8
        pending = sched.queue.pending_pods()
        assert pending["backoff"] == 8, pending  # error → backoffQ requeue
        # no ghost NodeInfo materialized in the cache for the dead node
        assert not sched.cache.has_real_node("doomed")
        reclaims = self.tele.flight.events("slot_reclaim")
        assert len([e for e in reclaims if e.get("reason")]) == 8
        assert all("removed while batch in flight" in e["reason"]
                   or "reclaimed since dispatch" in e["reason"]
                   for e in reclaims if e.get("reason"))
        # capacity arrives: the NODE_ADD move + expired backoff rebind all 8
        store.create_node(make_node("fresh").capacity(
            {"cpu": "8", "memory": "16Gi", "pods": 30}).obj())
        import time as _time

        _time.sleep(0.06)
        sched.run_until_settled()
        assert sched.metrics["scheduled"] == 8
        assert set(_bound(store).values()) == {"fresh"}
        # the next sync's removal sweep tombstoned the dead node's row
        removes = self.tele.flight.events("node_remove")
        assert any(e["node"] == "doomed" for e in removes)
        _assert_oracle_replay_valid(store)
        self._assert_mirror_byte_identical(sched)

    def test_reclaimed_slot_reused_by_new_node_rejected_not_misplaced(
            self, monkeypatch):
        """The sharper half of the guard: the dead node's SLOT is already
        reused by a replacement node when the in-flight commit lands. The
        slot now resolves to a live node the kernel never judged — the
        release-generation check must reject it (requeue), not bind."""
        store = ClusterStore()
        store.create_node(make_node("doomed").capacity(
            {"cpu": "8", "memory": "16Gi", "pods": 30}).obj())
        sched = self._ring_sched(monkeypatch, store)
        for i in range(4):
            store.create_pod(make_pod(f"a{i}").req({"cpu": "100m"}).obj())
        sched.schedule_batch_cycle()
        assert len(sched._inflight) == 1
        # churn while in flight: the tombstoned slot goes to the newcomer
        store.delete_node("doomed")
        store.create_node(make_node("newcomer").capacity(
            {"cpu": "8", "memory": "16Gi", "pods": 30}).obj())
        sched.cache.update_snapshot(sched.snapshot)
        sched.device.sync(sched.snapshot)  # release + free-list reuse
        assert sched.device.encoder.node_slots.get("newcomer") == 0
        assert sched.device.encoder.slot_reuses == 1
        sched._drain_inflight()
        # the commit named slot 0, which now means "newcomer": typed
        # rejection — newcomer was never judged by that batch's kernel
        assert sched.metrics["scheduled"] == 0
        assert _bound(store) == {}
        assert sched.queue.pending_pods()["backoff"] == 4
        reclaims = [e for e in self.tele.flight.events("slot_reclaim")
                    if e.get("reason")]
        assert reclaims and all("reclaimed since dispatch" in e["reason"]
                                for e in reclaims)
        import time as _time

        _time.sleep(0.06)
        sched.run_until_settled()
        assert sched.metrics["scheduled"] == 4
        assert set(_bound(store).values()) == {"newcomer"}
        _assert_oracle_replay_valid(store)
        self._assert_mirror_byte_identical(sched)

    def test_storm_drain_spot_overlapping_inflight_invariants(
            self, monkeypatch):
        """The full elastic ladder against a ring-depth-2 pipeline: an
        add/remove storm over 30% of the cluster, a rolling drain wave, and
        a mass spot reclamation, each launched while batches are in flight.
        Zero lost pods, zero double-binds, bounded row capacity (slot
        reuse), byte-identical post-resync mirror, oracle-replay-valid."""
        from kubernetes_tpu.api.types import ObjectMeta, PodGroup

        from kubernetes_tpu.controllers.drain import DrainOrchestrator

        store = ClusterStore()
        for i in range(10):
            store.create_node(make_node(f"node-{i}").capacity(
                {"cpu": "8", "memory": "16Gi", "pods": 30}).obj())
        sched = self._ring_sched(monkeypatch, store, batch=8)
        drainer = DrainOrchestrator(store, metrics=sched.smetrics,
                                    queue=sched.queue)
        store.create_object("PodGroup", PodGroup(
            meta=ObjectMeta(name="band"), min_member=3,
            schedule_timeout_seconds=30))
        created = []
        for i in range(8):
            p = make_pod(f"p{i}").req({"cpu": "200m"}).obj()
            store.create_pod(p)
            created.append(p.key())
        for i in range(3):
            p = (make_pod(f"band-{i}").req({"cpu": "200m"})
                 .pod_group("band").obj())
            store.create_pod(p)
            created.append(p.key())
        sched.run_until_settled()
        caps_nodes0 = sched.device.caps.nodes
        next_node = 10

        def churn_pods(wave):
            for i in range(4):
                p = make_pod(f"w{wave}-{i}").req({"cpu": "200m"}).obj()
                store.create_pod(p)
                created.append(p.key())

        import time as _time

        def settle():
            for _ in range(6):
                _time.sleep(0.06)  # clear the (shortened) error backoff
                sched.run_until_settled()
                if sum(sched.queue.pending_pods().values()) == 0:
                    break

        # --- 1. add/remove storm (30%) over in-flight batches ------------
        churn_pods(0)
        sched.schedule_batch_cycle()  # leave a batch in flight
        live = sorted(store.nodes)
        storm = live[:3]
        drainer.drain_wave(storm)
        for name in storm:
            store.delete_node(name)
        for _ in range(3):
            store.create_node(make_node(f"node-{next_node}").capacity(
                {"cpu": "8", "memory": "16Gi", "pods": 30}).obj())
            next_node += 1
        settle()
        # --- 2. rolling drain wave over in-flight batches ----------------
        churn_pods(1)
        sched.schedule_batch_cycle()
        wave = sorted(store.nodes)[:2]
        drainer.drain_wave(wave)
        settle()
        for name in wave:
            drainer.uncordon(name)
        # --- 3. mass spot reclamation over in-flight batches -------------
        churn_pods(2)
        sched.schedule_batch_cycle()
        spots = sorted(store.nodes)[-3:]
        drainer.spot_reclaim(spots, delete_nodes=True)
        for _ in range(3):
            store.create_node(make_node(f"node-{next_node}").capacity(
                {"cpu": "8", "memory": "16Gi", "pods": 30}).obj())
            next_node += 1
        settle()

        # standing invariants
        bound = _bound(store)
        assert len(bound) == len(created), "zero lost pods"
        assert sorted(p.key() for p in store.pods.values()) == sorted(created)
        live = set(store.nodes)
        assert all(n in live for n in bound.values())
        # whole-gang atomicity: all 3 members bound (never partial)
        assert sum(1 for k in bound if k.startswith("band")) == 3
        _assert_oracle_replay_valid(store)
        # bounded shrink/grow: churned well past the free-list, capacity
        # never grew and tombstoned slots were REUSED
        assert sched.device.caps.nodes == caps_nodes0
        assert sched.smetrics.device_slot_reuse.labels() > 0
        assert self.tele.flight.events("node_remove")
        assert self.tele.flight.events("evict_wave")
        self._assert_mirror_byte_identical(sched)

    def test_churn_with_fabric_failover_no_ghost_on_standby(self):
        """Elasticity × HA: nodes churn while the fabric primary dies
        mid-batch. The poisoned work requeues, the standby is seeded by the
        full resync — WITHOUT the removed node (no ghost row on any
        replica) — and every pod lands with oracle-valid placements and a
        byte-identical post-resync mirror."""
        from kubernetes_tpu.backend import telemetry

        rig = _FabricRig(nodes=4, cap="8", replicas=2)
        try:
            for i in range(6):
                rig.store.create_pod(
                    make_pod(f"p{i}").req({"cpu": "1", "memory": "1Gi"}).obj())
            rig.settle()
            assert len(_bound(rig.store)) == 6
            # churn: one node out (its pods evicted+recreated), one in —
            # then kill the primary while the rebind batch is on the wire
            from kubernetes_tpu.controllers.drain import DrainOrchestrator

            drainer = DrainOrchestrator(rig.store, queue=rig.sched.queue)
            drainer.drain_wave(["n0"])
            rig.store.delete_node("n0")
            rig.store.create_node(make_node("n9").capacity(
                {"cpu": "8", "memory": "16Gi", "pods": 10}).obj())
            self_kill = rig.plans[0]
            self_kill.kill()
            rig.settle(rounds=4)
            bound = _bound(rig.store)
            assert len(bound) == 6, "zero lost pods across churn + failover"
            assert "n0" not in set(bound.values())
            _assert_oracle_replay_valid(rig.store)
            # the surviving replica's mirror carries no ghost of n0
            svc = rig.active_service()
            assert svc is rig.services[1]
            assert "n0" not in svc.infos
            assert "n0" not in svc.device.encoder.node_slots
            _assert_resync_mirror_identical(rig)
        finally:
            rig.close()


class TestWirePipelineChaos:
    """Pipelined wire transport under fire (ROADMAP item 2, wire half):
    K>=3 batches in flight across the wire while the device service
    crashes, the transport drops everything, or the stream reorders/tears —
    zero pods lost, zero double-binds, zero replays beyond the idempotent
    ones, and the flight recorder carries pipeline_poison -> requeue per
    poisoned batch.

    Runs under KTPU_LOCKTRACE=1 (the ``locktraced`` fixture): the reply
    lanes and the completion router are new threads against the
    WirePipeline condition — the suite must produce an acyclic lock graph
    and zero non-allowed blocking-under-lock events."""

    @pytest.fixture(autouse=True)
    def _traced(self, locktraced):
        yield

    @pytest.fixture(autouse=True)
    def _flight(self):
        from kubernetes_tpu.backend import telemetry

        self.tele = telemetry.enable()
        yield
        telemetry.disable()

    def _pods(self, rig, n, cpu="500m", prefix="p"):
        for i in range(n):
            rig.store.create_pod(
                make_pod(f"{prefix}{i}").req({"cpu": cpu}).obj())

    def _settle(self, rig, rounds=3, step=1.1):
        rig.sched.run_until_settled()
        for _ in range(rounds):
            rig.clock.advance(step)
            rig.sched.run_until_settled()

    def test_crash_with_k_batches_in_flight_recovers_in_place(self):
        """The sidecar dies while three batches ride the wire: the torn
        call retries into the restarted (fresh-epoch) service, the stale
        verdicts trigger ONE full resync, and every batch re-sends under
        its original idempotent batchId — nothing lost, nothing double,
        nothing replayed from a cache (the new instance computed fresh)."""
        plan = FaultPlan().crash("schedule_batch")
        rig = _WireRig(fault_plan=plan, nodes=6,
                       wire_pipeline_depth=3, batch_size=4)
        try:
            self._pods(rig, 12)
            self._settle(rig)
            bound = _bound(rig.store)
            assert len(bound) == 12                    # zero lost
            assert len(rig.store.pods) == 12           # zero duplicated
            assert rig.server.binding.restarts == 1
            assert rig.sched.resyncs >= 1
            assert rig.server.binding.service.batch_replays == 0
            assert rig.sched.breaker.state == circuit.CLOSED
            per_node = {}
            for n in bound.values():
                per_node[n] = per_node.get(n, 0) + 1
            assert all(v <= 10 for v in per_node.values()), per_node
        finally:
            rig.close()

    def test_kill_with_k_batches_in_flight_poisons_all(self):
        """Transport death with K=3 in flight: every in-flight batch is
        poisoned exactly like ring poison — pipeline_poison then requeue
        per batchId in the flight recorder, pods re-enter via backoffQ (or
        the oracle once the breaker opens), zero lost, zero double."""
        from kubernetes_tpu.testing.faults import SCHEDULE_BATCH

        plan = FaultPlan()
        rig = _WireRig(fault_plan=plan, nodes=6,
                       wire_pipeline_depth=3, batch_size=4,
                       wire_max_retries=0)
        try:
            self._pods(rig, 12)
            # deltas land; every batch call dies (so three submitted
            # batches are genuinely in flight when the poison fires)
            plan.partition(SCHEDULE_BATCH)
            for _ in range(3):                         # 3 batches in flight
                rig.sched.schedule_batch_cycle()
            assert len(rig.sched._wire_inflight) == 3
            rig.sched._drain_wire_inflight()
            poisons = self.tele.flight.events("pipeline_poison")
            assert len(poisons) == 3
            requeues = [e for e in self.tele.flight.events("requeue")
                        if e.get("batchId")]
            # poison strictly before its own requeue, per batchId (the
            # third batch degrades to the oracle instead: breaker opened)
            by_batch = {e["batchId"]: e["seq"] for e in poisons}
            for e in requeues:
                assert by_batch[e["batchId"]] < e["seq"]
            plan.heal()
            rig.clock.advance(6.0)                     # breaker reset window
            self._settle(rig)
            bound = _bound(rig.store)
            assert len(bound) == 12                    # zero lost
            assert len(rig.store.pods) == 12           # zero duplicated
            assert rig.server.binding.service.batch_replays == 0
        finally:
            rig.close()

    def test_reordered_and_torn_stream_under_load(self):
        """Reordered replies + a torn response while pipelined: the router
        matches by batchId, the torn call replays idempotently — all pods
        land once."""
        plan = FaultPlan().reorder("schedule_batch").torn("schedule_batch")
        rig = _WireRig(fault_plan=plan, nodes=6,
                       wire_pipeline_depth=3, batch_size=4)
        try:
            self._pods(rig, 12)
            self._settle(rig)
            bound = _bound(rig.store)
            assert len(bound) == 12
            assert rig.server.binding.service.batch_replays == 1  # the tear
            assert rig.sched._wire_pipeline.duplicate_replies == 0
        finally:
            rig.close()


class TestWarmStandbyChaos:
    """Warm-standby failover (ROADMAP item 2, device half): the fabric
    fans the delta stream out to standbys in the background, so a promoted
    standby resyncs O(dirty) — asserted in upload BYTES via the PR-7
    telemetry, not wall time — the device survives lease windows (kept
    warm by the replication worker's heartbeats), and a kill with K=3 wire
    batches in flight loses zero pods with poison ordered before failover
    in the flight recorder."""

    @pytest.fixture(autouse=True)
    def _traced(self, locktraced):
        yield

    @pytest.fixture(autouse=True)
    def _flight(self):
        from kubernetes_tpu.backend import telemetry

        self.tele = telemetry.enable()
        yield
        telemetry.disable()

    def _rig(self, nodes=32, **kw):
        kw.setdefault("wire_pipeline_depth", 3)
        kw.setdefault("heartbeat_interval_s", 1.0)
        kw.setdefault("batch_size", 16)
        return _FabricRig(nodes=nodes, cap="8", replicas=2, **kw)

    def _steady_state(self, rig, pods=32):
        """Settle a workload AND push the settled truth: the trailing pod
        forces one more delta flush so the replication state matches the
        bound cluster (continuous traffic does this for free)."""
        for i in range(pods):
            rig.store.create_pod(
                make_pod(f"p{i}").req({"cpu": "500m"}).obj())
        rig.settle()
        rig.store.create_pod(make_pod("trail").req({"cpu": "100m"}).obj())
        rig.settle(rounds=1)
        rig.sched.client.replication_flush()

    def test_promote_resyncs_dirty_suffix_only(self):
        """The headline assertion: a warm standby's promote-time resync
        uploads a small fraction of the cold full=True seed — O(dirty),
        judged by DeviceState upload bytes (PR-7 telemetry)."""
        rig = self._rig()
        try:
            self._steady_state(rig)
            standby = rig.services[1]
            assert standby.device is not None          # warmed by replication
            cold_seed = standby.device.upload_bytes
            assert cold_seed > 0
            dev_id = id(standby.device)
            up_before = standby.device.upload_bytes
            assert self.tele.flight.events("replication")
            # primary dies; a small live wave rides the failover
            rig.plans[0].kill()
            for i in range(4):
                rig.store.create_pod(
                    make_pod(f"x{i}").req({"cpu": "250m"}).obj())
            rig.settle(rounds=4)
            bound = _bound(rig.store)
            assert len(bound) == 37                    # 32 + trail + 4: zero lost
            assert len(rig.store.pods) == 37           # zero duplicated
            fab = rig.sched.client
            assert fab.failovers == 1
            assert fab.active_endpoint() == rig.endpoints[1]
            assert standby.batch_replays == 0          # nothing replayed
            # the warm win: the SAME DeviceState survived the promote (no
            # rebuild) and the resync uploaded only the dirty suffix
            assert id(standby.device) == dev_id
            promote_bytes = standby.device.upload_bytes - up_before
            assert promote_bytes * 4 < cold_seed, (promote_bytes, cold_seed)
            _assert_oracle_replay_valid(rig.store)
        finally:
            rig.close()

    def test_lagging_standby_at_failover_loses_nothing(self):
        """The standby lags (its delta path is partitioned) when the
        primary dies with K=3 batches in flight: the fabric poisons the
        in-flight work BEFORE the failover event (flight-recorder order),
        the full resync repairs the stale mirror, and every pod lands
        exactly once — the lag costs upload bytes, never correctness."""
        from kubernetes_tpu.testing.faults import APPLY_DELTAS, SCHEDULE_BATCH

        rig = self._rig(nodes=8)
        try:
            self._steady_state(rig, pods=8)
            fab = rig.sched.client
            # the standby stops receiving deltas: lag grows
            rig.plans[1].partition(APPLY_DELTAS)
            for i in range(6):
                rig.store.create_pod(
                    make_pod(f"lag{i}").req({"cpu": "250m"}).obj())
            rig.settle(rounds=1)
            fab.replication_flush()
            assert fab.replication_lag(fab.replicas[1]) > 0
            # primary's batch path dies while batches are in flight; the
            # standby heals just as it is promoted
            rig.plans[1].heal()
            rig.plans[0].partition(SCHEDULE_BATCH)
            for i in range(4):
                rig.store.create_pod(
                    make_pod(f"x{i}").req({"cpu": "250m"}).obj())
            rig.settle(rounds=4)
            bound = _bound(rig.store)
            assert len(bound) == len(rig.store.pods)   # zero lost
            assert fab.failovers == 1
            # ordering: the first poison precedes the failover event
            poisons = self.tele.flight.events("poison")
            failovers = self.tele.flight.events("failover")
            assert poisons and failovers
            assert min(e["seq"] for e in poisons) < failovers[0]["seq"]
            assert rig.services[1].batch_replays == 0
            _assert_oracle_replay_valid(rig.store)
        finally:
            rig.close()

    def test_standby_sessions_survive_lease_windows(self):
        """The standby blind spot, closed: nothing but replication talks
        to a standby, so its sessions would silently expire (fencing the
        replicator releases its node claims — the promote-time ghost sweep
        would then drop the warm DeviceState). The replication worker's
        keep-warm heartbeats carry both sessions across several lease
        TTLs; the promote still finds the warm device. (32 nodes: row
        uploads are bucket-padded to 8-row blocks, so the dirty-suffix /
        cold-seed byte ratio needs a cluster several buckets wide.)"""
        rig = self._rig()
        try:
            self._steady_state(rig, pods=8)
            standby = rig.services[1]
            dev_id = id(standby.device)
            cold_seed = standby.device.upload_bytes
            repl_cid = rig.sched.client._repl_client_id
            # several lease TTLs pass; the scheduler only heartbeats the
            # primary — the worker's keep-warm beats carry the standby
            for _ in range(6):
                rig.clock.advance(6.0)                 # > probe interval
                rig.sched.run_until_settled()          # primary heartbeats
                rig.sched.client.replication_flush()   # keep-warm beats
            assert repl_cid in standby.sessions
            assert standby.sessions[repl_cid].fenced is False
            assert standby.sessions[repl_cid].replicator is True
            # the scheduler client's session was fanned out and kept warm
            assert rig.sched.client_id in standby.sessions
            assert standby.sessions[rig.sched.client_id].fenced is False
            up_before = standby.device.upload_bytes
            rig.plans[0].kill()
            rig.store.create_pod(make_pod("late").req({"cpu": "250m"}).obj())
            rig.settle(rounds=4)
            assert len(_bound(rig.store)) == len(rig.store.pods)
            assert rig.sched.client.failovers == 1
            # the warm device SURVIVED the lease window + promote: no
            # ghost-sweep teardown, dirty-suffix upload only
            assert id(standby.device) == dev_id
            promote_bytes = standby.device.upload_bytes - up_before
            assert promote_bytes * 4 < cold_seed, (promote_bytes, cold_seed)
            _assert_oracle_replay_valid(rig.store)
        finally:
            rig.close()


class TestRebalanceChaos:
    """Continuous-rebalancing chaos (ISSUE 18): a migration wave is the
    worst possible moment for the device to die — pods it just evicted
    are mid-rebind when every in-flight batch poisons. The wave must
    degrade to plain requeues (zero lost, zero double-bound, gangs never
    partial, mirror byte-identical after the resync), and a hostile
    flood landing during rebalancing must trip the SLO guardrail breaker
    while the cluster still converges.

    Runs under KTPU_LOCKTRACE=1: the Rebalancer's scoring path takes the
    commit plane's DeviceMutex around the mirror read — the interleaving
    with drain/evict/requeue must stay acyclic with no blocking under a
    held lock."""

    GROUP = "rbgang"

    @pytest.fixture(autouse=True)
    def _traced(self, locktraced):
        yield

    @pytest.fixture(autouse=True)
    def _flight(self):
        from kubernetes_tpu.backend import telemetry

        self.tele = telemetry.enable()
        yield
        telemetry.disable()

    def _rig(self, gang=False, now_fn=None):
        """8 nodes, a settled population, then a churn smear that leaves
        low-occupancy victims — the state a Rebalancer wave fires on."""
        from kubernetes_tpu.api.types import POD_GROUP_LABEL

        store = ClusterStore()
        _cluster(store, 8)
        kw = {"now_fn": now_fn} if now_fn is not None else {}
        sched = TPUScheduler(store, batch_size=4, comparer_every_n=1,
                             pod_initial_backoff=0.01,
                             pod_max_backoff=0.05, **kw)
        for i in range(12):
            store.create_pod(make_pod(f"rb{i}").req({"cpu": "100m"}).obj())
        if gang:
            from kubernetes_tpu.api.types import ObjectMeta, PodGroup

            store.create_object("PodGroup", PodGroup(
                meta=ObjectMeta(name=self.GROUP), min_member=4,
                schedule_timeout_seconds=30))
            for i in range(4):
                store.create_pod(
                    make_pod(f"{self.GROUP}-{i}").req({"cpu": "100m"})
                    .pod_group(self.GROUP).obj())
        sched.run_until_settled()
        assert sched.metrics["scheduled"] == (16 if gang else 12)
        solo = [p for p in store.pods.values() if p.spec.node_name
                and not p.meta.labels.get(POD_GROUP_LABEL)]
        for i, p in enumerate(solo):
            if i % 3:
                store.delete_pod(p.key())
        sched.cache.update_snapshot(sched.snapshot)
        rb = sched.enable_rebalancer(
            entropy_high=0.05, entropy_low=0.01, score_interval_s=0.0,
            cooldown_s=3600.0, max_migrations_per_wave=6,
            slo_min_samples=10, breaker_threshold=1, probe_interval_s=60.0)
        return store, sched, rb

    def _gang_bound(self, store):
        from kubernetes_tpu.api.types import POD_GROUP_LABEL

        return [p for p in store.pods.values()
                if p.meta.labels.get(POD_GROUP_LABEL) == self.GROUP
                and p.spec.node_name]

    def test_device_kill_mid_wave_exactly_once(self, monkeypatch):
        store, sched, rb = self._rig()
        out = rb.maybe_run(sched.now_fn())
        assert out["ran"] and out["wave"]["evicted"] > 0, out
        wave_nodes = list(rb.last_waves[-1]["nodes"])
        evicted = list(rb.drain.pending_uncordons[-1]["pods"])
        population = len(store.pods)

        from kubernetes_tpu.backend import batch as batch_mod

        real_unpack = batch_mod.unpack_result_block

        def dead(*a, **kw):
            raise RuntimeError("relay dropped mid-wave")

        monkeypatch.setattr(batch_mod, "unpack_result_block", dead)
        sched.schedule_batch_cycle()
        sched._drain_inflight()
        # the wave degraded to plain requeues: the device is down, every
        # evicted pod is back in the store UNBOUND (not lost, not ghosted),
        # and the victims stay cordoned — operator-visible, no data loss
        assert sched.device is None
        for key in evicted:
            pod = store.get_pod(key)
            assert pod is not None and not pod.spec.node_name, key
        assert rb.drain.poll_pending_uncordons() == []
        for name in wave_nodes:
            assert store.nodes[name].spec.unschedulable

        monkeypatch.setattr(batch_mod, "unpack_result_block", real_unpack)
        import time as _time

        _time.sleep(0.06)  # let the (shortened) error backoff expire
        sched.run_until_settled()
        # exactly-once rebind: every evicted pod bound, OFF the wave nodes,
        # population unchanged (no duplicate clones), capacity respected
        for key in evicted:
            pod = store.get_pod(key)
            assert pod is not None and pod.spec.node_name, key
            assert pod.spec.node_name not in wave_nodes, key
        assert len(store.pods) == population
        assert len(_bound(store)) == population
        rb.drain.poll_pending_uncordons()
        assert not rb.drain.pending_uncordons
        for name in wave_nodes:
            assert not store.nodes[name].spec.unschedulable
        assert sched.comparer_mismatches == 0
        _assert_oracle_replay_valid(store)

        # byte-identical resync: the healed mirror equals a fresh device
        # synced from the same host snapshot, field for field. A probe pod
        # first: the uncordons just changed host truth, and only a real
        # scheduling cycle syncs that into the mirror
        from kubernetes_tpu.backend.device_state import DeviceState

        store.create_pod(make_pod("probe").req({"cpu": "50m"}).obj())
        sched.run_until_settled()
        assert sched.device is not None
        sched.cache.update_snapshot(sched.snapshot)
        fresh = DeviceState(sched.device.caps,
                            ns_labels_fn=sched.store.ns_labels)
        fresh.sync(sched.snapshot)
        for field, arr in sched.device._mirror.items():
            assert np.array_equal(arr, fresh._mirror[field]), field

    def test_gang_wave_atomic_under_device_kill(self, monkeypatch):
        """A wave that evicts a placed gang, killed mid-rebind: the gang is
        never partially bound at ANY observation point — all-out while the
        device is dead, all-in (off the cordoned victims) after it heals."""
        store, sched, rb = self._rig(gang=True)
        gang_nodes = sorted({p.spec.node_name for p in self._gang_bound(store)})
        # the exact drain_wave call _run_wave makes, aimed at the gang's
        # hosts: the gang closure evicts every member, whole or not at all
        result = rb.drain.drain_wave(
            gang_nodes, uncordon_after=True,
            allow_fn=rb.drain._pdb_disruption_gate())
        assert result["gangs"] == 1
        assert self._gang_bound(store) == []  # evicted whole

        from kubernetes_tpu.backend import batch as batch_mod

        real_unpack = batch_mod.unpack_result_block

        def dead(*a, **kw):
            raise RuntimeError("relay dropped mid-wave")

        monkeypatch.setattr(batch_mod, "unpack_result_block", dead)
        sched.schedule_batch_cycle()
        sched._drain_inflight()
        assert self._gang_bound(store) == []  # still atomic: none bound
        assert rb.drain.poll_pending_uncordons() == []

        monkeypatch.setattr(batch_mod, "unpack_result_block", real_unpack)
        import time as _time

        _time.sleep(0.06)
        sched.run_until_settled()
        rebound = self._gang_bound(store)
        assert len(rebound) == 4  # all-in, never partial
        assert all(p.spec.node_name not in gang_nodes for p in rebound)
        rb.drain.poll_pending_uncordons()
        assert not rb.drain.pending_uncordons
        assert sched.comparer_mismatches == 0
        _assert_oracle_replay_valid(store)

    def test_hostile_flood_trips_slo_breaker_and_converges(self):
        """A flood storm lands while the Rebalancer is active: queue waits
        blow up every tenant's e2e p99, the guardrail breaker trips OPEN
        (waves suspended, flight event), yet the cluster converges — and
        the breaker heals only through the half-open probe discipline."""
        from kubernetes_tpu.metrics import latency_ledger

        clock = FakeClock()
        store, sched, rb = self._rig(now_fn=clock)
        # drive the control loop manually: housekeeping firing waves on its
        # own cadence would race the trip/probe points this test scripts
        sched.rebalancer = None
        # every namespace is a labeled tenant here (the harness wires the
        # quota plugin's weight lookup instead)
        ledger = latency_ledger.enable(sched.smetrics, now_fn=clock,
                                       tenant_fn=lambda ns: 1)
        assert ledger is not None
        try:
            # pre-storm baseline: pods bind instantly, e2e p99 ~ 0
            for i in range(16):
                store.create_pod(
                    make_pod(f"calm{i}").req({"cpu": "100m"}).obj())
            sched.run_until_settled()
            out = rb.maybe_run(clock())  # the wave arms the SLO watch
            assert out["ran"], out
            assert "default" in rb._slo_watch
            assert self.tele.flight.events("rebalance_wave")

            # hostile flood: arrivals outpace the device, the clock ticks
            # between cycles, so every later bind carries seconds of queue
            # wait — a real p99 regression, not a synthetic observation
            for i in range(48):
                store.create_pod(
                    make_pod(f"storm{i}").req({"cpu": "50m"}).obj())
            for _ in range(14):
                sched.schedule_batch_cycle()
                clock.advance(1.0)
            sched.run_until_settled()
            rb.cooldown_s = 0.0  # a wave would be admissible — if allowed
            out = rb.maybe_run(clock())
            assert rb.suspended and rb.breaker.dump()["state"] == "open"
            assert not out["ran"] and out["reason"] == "slo-suspended"
            assert self.tele.flight.events("rebalance_suspended")
            assert sched.smetrics.rebalance_suspended.labels() == 1.0
            # the storm itself converged: every pod bound, nothing lost
            assert len(_bound(store)) == len(store.pods)
            rb.drain.poll_pending_uncordons()
            assert not rb.drain.pending_uncordons

            # heal: a clean window alone may NOT close an OPEN breaker …
            for i in range(12):
                store.create_pod(
                    make_pod(f"calm2-{i}").req({"cpu": "50m"}).obj())
            sched.run_until_settled()
            rb.maybe_run(clock())
            assert rb.breaker.dump()["state"] == "open"
            # … only the half-open probe after the reset window does
            clock.advance(61.0)
            rb.maybe_run(clock())
            assert rb.breaker.dump()["state"] in ("half_open", "closed")
            for i in range(12):
                store.create_pod(
                    make_pod(f"calm3-{i}").req({"cpu": "50m"}).obj())
            sched.run_until_settled()
            rb.maybe_run(clock())
            assert rb.breaker.dump()["state"] == "closed"
            assert not rb.suspended
            assert self.tele.flight.events("rebalance_resume")
            assert sched.smetrics.rebalance_suspended.labels() == 0.0
            _assert_oracle_replay_valid(store)
        finally:
            latency_ledger.disable()


class TestBorrowChaos:
    """Device death mid-reclaim (ISSUE 19): the reclaim pass has evicted
    the borrower's loans (delete + recreate through the drain machinery)
    and the lender's woken pods plus the recreated borrowers are in flight
    when the relay dies. Required outcome: zero lost / double-bound pods,
    the loan ledger reconciled to the post-reclaim truth, and the rebuilt
    device mirror byte-identical to a fresh sync.

    Runs under KTPU_LOCKTRACE=1 (the ``locktraced`` fixture): the
    reclaim's queue-lock/ledger-lock/drain interleavings must keep the
    lock-order graph acyclic with no blocking-under-lock events."""

    @pytest.fixture(autouse=True)
    def _traced(self, locktraced):
        yield

    def _quota(self, store, ns, pods_cap, cohort):
        from kubernetes_tpu.api.types import ObjectMeta, SchedulingQuota

        if ns not in store.namespaces:
            from kubernetes_tpu.api.types import Namespace

            store.create_namespace(Namespace(meta=ObjectMeta(name=ns)))
        store.create_object("SchedulingQuota", SchedulingQuota(
            meta=ObjectMeta(name="quota", namespace=ns),
            hard={"pods": pods_cap}, cohort=cohort))

    def test_device_kill_mid_reclaim_no_lost_no_double_bind(self, monkeypatch):
        store = ClusterStore()
        _cluster(store, 6)
        self._quota(store, "lend", 4, "pool")
        self._quota(store, "hungry", 2, "pool")
        sched = TPUScheduler(store, batch_size=8, comparer_every_n=1,
                             pod_initial_backoff=0.01, pod_max_backoff=0.05)
        for i in range(6):
            store.create_pod(make_pod(f"b{i}", namespace="hungry")
                             .req({"cpu": "100m"}).obj())
        sched.run_batched_until_settled()
        plugin = next(iter(sched.profiles.values())).plugin("QuotaAdmission")
        assert plugin.borrowed("hungry")["pods"] == 4
        # the lender wakes: four own-fit pods, pool exhausted by loans —
        # the gate parks them and records reclaim demand
        for i in range(4):
            store.create_pod(make_pod(f"l{i}", namespace="lend")
                             .req({"cpu": "100m"}).obj())
        sched.schedule_batch_cycle()
        sched._drain_inflight()
        assert plugin._reclaim_demand.get("pool")
        # the reclaim pass fires (housekeeping-driven in steady state;
        # invoked directly here to pin the chaos window): loans evicted,
        # borrower pods recreated unbound, lender pods reactivated
        evicted = plugin.run_reclaim(now=sched.now_fn())
        assert evicted == 4
        assert plugin.borrowed("hungry").get("pods", 0) == 0
        assert not plugin._loans

        # the device dies exactly as the post-reclaim wave materializes
        from kubernetes_tpu.backend import batch as batch_mod

        real_unpack = batch_mod.unpack_result_block

        def dead(*a, **kw):
            raise RuntimeError("relay dropped mid-reclaim")

        monkeypatch.setattr(batch_mod, "unpack_result_block", dead)
        sched.schedule_batch_cycle()
        sched._drain_inflight()
        assert sched.device is None  # poisoned: marked for rebuild
        monkeypatch.setattr(batch_mod, "unpack_result_block", real_unpack)
        import time as _time

        _time.sleep(0.06)  # let the (shortened) error backoff expire
        sched.run_until_settled()

        # zero lost / double-bound: ten pods exist exactly once; the
        # lender's four own-cap pods all bound, the borrower holds its own
        # cap and the four recreated ex-loan pods park behind the gate
        assert len(store.pods) == 10
        bound = _bound(store)
        assert len(bound) == 6
        lend_bound = [n for n in bound if n.startswith("l")]
        assert len(lend_bound) == 4
        assert sched.comparer_mismatches == 0
        # loan ledger reconciled to post-reclaim truth: the pool is full
        # of guaranteed usage, zero loans outstanding
        assert plugin.usage("lend")["pods"] == 4
        assert plugin.usage("hungry")["pods"] == 2
        assert plugin.borrowed("hungry").get("pods", 0) == 0
        assert not plugin._loans
        caps, used = plugin.cohort_state("pool")
        assert used["pods"] == caps["pods"] == 6
        pending = sched.queue.pending_pods()
        assert pending["gated"] == 4, pending

        # byte-identical resync: the rebuilt mirror equals a fresh device
        # synced from the same host snapshot, field for field
        from kubernetes_tpu.backend.device_state import DeviceState

        sched.cache.update_snapshot(sched.snapshot)
        fresh = DeviceState(sched.device.caps,
                            ns_labels_fn=sched.store.ns_labels)
        fresh.sync(sched.snapshot)
        for field, arr in sched.device._mirror.items():
            assert np.array_equal(arr, fresh._mirror[field]), field
        # including the namespace-quota tensor pair the screen reads: the
        # rows are synced per DISPATCH (so the device may lag the final
        # commits), but one sync from the live ledger converges both
        # devices to identical content
        assert sched.device.nsq_slots
        table = plugin.device_quota_table()
        fresh.set_ns_quota(table)
        sched.device.set_ns_quota(table)
        assert sched.device.set_ns_quota(table) is False  # now steady-state
        for ns, slot in sched.device.nsq_slots.items():
            fslot = fresh.nsq_slots[ns]
            assert np.array_equal(sched.device._nsq_used_m[slot],
                                  fresh._nsq_used_m[fslot]), ns
            assert np.array_equal(sched.device._nsq_limit_m[slot],
                                  fresh._nsq_limit_m[fslot]), ns
