"""Chaos & recovery (test/e2e/chaosmonkey + SURVEY §5.3 build mapping):
disruption injected concurrently with scheduling; crash-only recovery —
a restarted scheduler/device rebuilds from the store and continues.
"""

import numpy as np

from kubernetes_tpu.api.wrappers import make_node, make_pod
from kubernetes_tpu.apiserver.store import ClusterStore
from kubernetes_tpu.backend.tpu_scheduler import TPUScheduler
from kubernetes_tpu.client.informer import SharedInformerFactory
from kubernetes_tpu.controllers import ControllerManager
from kubernetes_tpu.scheduler.scheduler import Scheduler
from kubernetes_tpu.utils.clock import FakeClock


def _cluster(store, n=20, cap="8"):
    for i in range(n):
        store.create_node(make_node(f"n{i}").capacity(
            {"cpu": cap, "memory": "16Gi", "pods": 30}).obj())


class TestChurnDuringScheduling:
    def test_node_churn_mid_workload(self):
        """Nodes deleted and added while pods schedule: everything still
        lands, nothing lands on a deleted node (chaosmonkey-style interleave)."""
        store = ClusterStore()
        clock = FakeClock()
        _cluster(store, 20)
        sched = Scheduler(store, now_fn=clock)
        for wave in range(5):
            for i in range(10):
                store.create_pod(make_pod(f"w{wave}-p{i}").req({"cpu": "100m"}).obj())
            # disrupt: drop one node, add a replacement
            store.delete_node(f"n{wave}")
            store.create_node(make_node(f"replacement-{wave}").capacity(
                {"cpu": "8", "memory": "16Gi", "pods": 30}).obj())
            clock.advance(11.0)
            sched.run_until_settled()
        live = set(store.nodes)
        bound = [p for p in store.pods.values() if p.spec.node_name]
        assert len(bound) == 50
        orphans = [p for p in bound if p.spec.node_name not in live]
        # pods bound to since-deleted nodes are PodGC's job, not the
        # scheduler's: they must be from the deleted set only
        assert all(p.spec.node_name.startswith("n") for p in orphans)

    def test_podgc_cleans_after_node_loss(self):
        store = ClusterStore()
        clock = FakeClock()
        _cluster(store, 4)
        sched = Scheduler(store, now_fn=clock)
        for i in range(8):
            store.create_pod(make_pod(f"p{i}").req({"cpu": "100m"}).obj())
        sched.run_until_settled()
        victims = {p.meta.key() for p in store.pods.values() if p.spec.node_name == "n0"}
        store.delete_node("n0")
        m = ControllerManager(store, factory=SharedInformerFactory(store),
                              controllers=["podgc"], now_fn=clock)
        m.settle()
        for key in victims:
            assert store.get_pod(key) is None


class TestCrashOnlyRecovery:
    def test_scheduler_restart_rebuilds_from_store(self):
        """Crash-only: a brand-new Scheduler over the same store resumes
        exactly where the old one stopped (informers relist, §5.3)."""
        store = ClusterStore()
        _cluster(store, 10)
        s1 = Scheduler(store)
        for i in range(10):
            store.create_pod(make_pod(f"a{i}").req({"cpu": "100m"}).obj())
        s1.run_until_settled()
        del s1  # crash
        for i in range(10):
            store.create_pod(make_pod(f"b{i}").req({"cpu": "100m"}).obj())
        s2 = Scheduler(store)
        s2.run_until_settled()
        bound = [p for p in store.pods.values() if p.spec.node_name]
        assert len(bound) == 20

    def test_device_restart_resyncs(self):
        """The device mirror is a cache: dropping it mid-stream (sidecar
        crash analog) forces a full-generation resync and scheduling
        continues (§5.3: restartable mid-stream)."""
        store = ClusterStore()
        _cluster(store, 12)
        sched = TPUScheduler(store, batch_size=8)
        for i in range(10):
            store.create_pod(make_pod(f"a{i}").req({"cpu": "100m"}).obj())
        sched.run_until_settled()
        assert sched.metrics["scheduled"] == 10
        sched.device = None  # device process crash
        for i in range(10):
            store.create_pod(make_pod(f"b{i}").req({"cpu": "100m"}).obj())
        sched.run_until_settled()
        assert sched.metrics["scheduled"] == 20
        # placements respect capacity after resync
        per_node = {}
        for p in store.pods.values():
            per_node[p.spec.node_name] = per_node.get(p.spec.node_name, 0) + 1
        assert all(v <= 30 for v in per_node.values())

    def test_assumed_pods_expire_after_ttl(self):
        """Assume-TTL sweep (cache.go:731): an assume never confirmed by a
        bind event expires and the node's resources free up."""
        store = ClusterStore()
        clock = FakeClock()
        sched = Scheduler(store, now_fn=clock, assume_ttl=30.0)
        store.create_node(make_node("n1").capacity(
            {"cpu": "1", "memory": "2Gi", "pods": 5}).obj())
        pod = make_pod("ghost").req({"cpu": "900m"}).obj()
        sched.cache.assume_pod(pod, "n1")
        sched.cache.finish_binding(pod)  # expiry clock starts at finishBinding
        clock.advance(31.0)
        expired = sched.cache.cleanup()
        assert [p.meta.name for p in expired] == ["ghost"]
        ni = sched.cache.nodes["n1"]
        assert ni.requested.milli_cpu == 0
