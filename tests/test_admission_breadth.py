"""Round-3 admission breadth: ServiceAccount, PodSecurity, NodeRestriction,
TaintNodesByCondition, DefaultStorageClass, PersistentVolumeClaimResize,
OwnerReferencesPermissionEnforcement, and webhook admission — the modeled
subset of AllOrderedPlugins (pkg/kubeapiserver/options/plugins.go:64)."""

import pytest

from kubernetes_tpu.api.types import (
    ANNOTATION_DEFAULT_STORAGE_CLASS,
    Lease,
    Namespace,
    ObjectMeta,
    OwnerReference,
    PersistentVolumeClaim,
    SecurityContext,
    ServiceAccount,
    StorageClass,
)
from kubernetes_tpu.api.wrappers import make_node, make_pod
from kubernetes_tpu.apiserver.admission import (
    PS_ENFORCE_LABEL,
    AdmissionError,
    WebhookConfiguration,
)
from kubernetes_tpu.apiserver.store import ClusterStore


def _ns(name, labels=None):
    return Namespace(meta=ObjectMeta(name=name, labels=labels or {}))


class TestServiceAccountAdmission:
    def test_defaults_to_default_sa(self):
        store = ClusterStore()
        pod = make_pod("p").req({"cpu": "100m"}).obj()
        store.create_pod(pod)
        assert pod.spec.service_account_name == "default"

    def test_missing_named_sa_rejected(self):
        store = ClusterStore()
        pod = make_pod("p").req({"cpu": "100m"}).obj()
        pod.spec.service_account_name = "builder"
        with pytest.raises(AdmissionError, match="service account"):
            store.create_pod(pod)

    def test_pod_updates_survive_sa_deletion(self):
        # SA existence is a CREATE check: deleting the SA must not brick
        # status updates of the running pods that reference it
        store = ClusterStore()
        store.create_object(
            "ServiceAccount", ServiceAccount(meta=ObjectMeta(name="builder")))
        pod = make_pod("p").req({"cpu": "100m"}).obj()
        pod.spec.service_account_name = "builder"
        store.create_pod(pod)
        store.delete_object("ServiceAccount", "default/builder")
        up = store.get_pod(pod.key()).clone()
        up.status.phase = "Succeeded"
        store.update_pod(up)  # must not raise
        assert store.get_pod(pod.key()).status.phase == "Succeeded"

    def test_existing_named_sa_accepted(self):
        store = ClusterStore()
        store.create_object(
            "ServiceAccount", ServiceAccount(meta=ObjectMeta(name="builder")))
        pod = make_pod("p").req({"cpu": "100m"}).obj()
        pod.spec.service_account_name = "builder"
        store.create_pod(pod)
        assert store.get_pod(pod.key()) is not None


class TestPodSecurity:
    def _store(self, level):
        store = ClusterStore()
        store.create_namespace(_ns("secure", {PS_ENFORCE_LABEL: level}))
        return store

    def test_privileged_level_allows_hostnetwork(self):
        store = ClusterStore()
        store.create_namespace(_ns("open"))
        pod = make_pod("p", namespace="open").req({"cpu": "1"}).obj()
        pod.spec.host_network = True
        store.create_pod(pod)  # no enforcement label → privileged

    def test_baseline_rejects_host_namespaces_and_privileged(self):
        store = self._store("baseline")
        pod = make_pod("p", namespace="secure").req({"cpu": "1"}).obj()
        pod.spec.host_pid = True
        with pytest.raises(AdmissionError, match="host namespaces"):
            store.create_pod(pod)
        pod2 = make_pod("p2", namespace="secure").req({"cpu": "1"}).obj()
        pod2.spec.containers[0].security_context = SecurityContext(privileged=True)
        with pytest.raises(AdmissionError, match="privileged"):
            store.create_pod(pod2)

    def test_restricted_requires_non_root_and_no_escalation(self):
        store = self._store("restricted")
        pod = make_pod("p", namespace="secure").req({"cpu": "1"}).obj()
        with pytest.raises(AdmissionError, match="runAsNonRoot"):
            store.create_pod(pod)
        ok = make_pod("ok", namespace="secure").req({"cpu": "1"}).obj()
        ok.spec.containers[0].security_context = SecurityContext(
            run_as_non_root=True, allow_privilege_escalation=False,
            capabilities_drop=("ALL",))
        store.create_pod(ok)
        assert store.get_pod(ok.key()) is not None

    def test_status_update_survives_level_tightening(self):
        # upstream exempts the status subresource: a pod admitted before the
        # namespace's enforce level tightened must keep updating (kubelet
        # phase writes) as long as its spec is unchanged
        store = ClusterStore()
        store.create_namespace(_ns("late"))
        pod = make_pod("p", namespace="late").req({"cpu": "1"}).obj()
        pod.spec.host_network = True
        store.create_pod(pod)
        ns = store.namespaces["late"]
        ns.meta.labels[PS_ENFORCE_LABEL] = "restricted"
        phase_up = store.get_pod(pod.key()).clone()
        phase_up.status.phase = "Succeeded"
        store.update_pod(phase_up)  # must not raise
        assert store.get_pod(pod.key()).status.phase == "Succeeded"

    def test_restricted_enforced_on_update_too(self):
        store = self._store("restricted")
        ok = make_pod("ok", namespace="secure").req({"cpu": "1"}).obj()
        ok.spec.containers[0].security_context = SecurityContext(
            run_as_non_root=True, allow_privilege_escalation=False,
            capabilities_drop=("ALL",))
        store.create_pod(ok)
        evil = ok.clone()
        evil.spec.host_network = True
        with pytest.raises(AdmissionError, match="host namespaces"):
            store.update_pod(evil)


class TestNodeRestriction:
    def test_kubelet_may_update_own_node_only(self):
        store = ClusterStore()
        store.create_node(make_node("n1").capacity({"cpu": "4"}).obj())
        store.create_node(make_node("n2").capacity({"cpu": "4"}).obj())
        with store.as_user("system:node:n1"):
            n1 = store.nodes["n1"]
            store.update_node(n1)  # own node: allowed
            with pytest.raises(AdmissionError, match="may not modify"):
                store.update_node(store.nodes["n2"])

    def test_kubelet_pod_writes_scoped_to_itself(self):
        store = ClusterStore()
        with store.as_user("system:node:n1"):
            mirror = make_pod("mirror").req({"cpu": "1"}).node("n1").obj()
            store.create_pod(mirror)
            other = make_pod("other").req({"cpu": "1"}).node("n2").obj()
            with pytest.raises(AdmissionError, match="bound to itself"):
                store.create_pod(other)

    def test_kubelet_lease_scoped(self):
        store = ClusterStore()
        with store.as_user("system:node:n1"):
            store.create_lease(Lease(meta=ObjectMeta(
                name="n1", namespace="kube-node-lease")))
            with pytest.raises(AdmissionError, match="lease"):
                store.create_lease(Lease(meta=ObjectMeta(
                    name="n2", namespace="kube-node-lease")))

    def test_ordinary_user_unrestricted(self):
        store = ClusterStore()
        store.create_node(make_node("n1").capacity({"cpu": "4"}).obj())
        store.update_node(store.nodes["n1"])  # system:admin


class TestTaintNodesByCondition:
    def test_not_ready_node_tainted_on_create(self):
        store = ClusterStore()
        node = make_node("cold").capacity({"cpu": "4"}).obj()
        node.status.ready = False
        store.create_node(node)
        assert any(t.key == "node.kubernetes.io/not-ready"
                   and t.effect == "NoSchedule" for t in node.spec.taints)

    def test_ready_node_untouched(self):
        store = ClusterStore()
        node = make_node("warm").capacity({"cpu": "4"}).obj()
        store.create_node(node)
        assert not node.spec.taints


class TestStorageAdmission:
    def test_default_storage_class_applied(self):
        store = ClusterStore()
        store.create_storage_class(StorageClass(
            meta=ObjectMeta(name="standard",
                            annotations={ANNOTATION_DEFAULT_STORAGE_CLASS: "true"})))
        store.create_storage_class(StorageClass(meta=ObjectMeta(name="other")))
        pvc = PersistentVolumeClaim(meta=ObjectMeta(name="data"))
        store.create_pvc(pvc)
        assert pvc.storage_class == "standard"

    def test_explicit_class_kept(self):
        store = ClusterStore()
        store.create_storage_class(StorageClass(
            meta=ObjectMeta(name="standard",
                            annotations={ANNOTATION_DEFAULT_STORAGE_CLASS: "true"})))
        pvc = PersistentVolumeClaim(meta=ObjectMeta(name="data"),
                                    storage_class="fast")
        store.create_pvc(pvc)
        assert pvc.storage_class == "fast"

    def test_pvc_resize_requires_expandable_class(self):
        store = ClusterStore()
        store.create_storage_class(StorageClass(meta=ObjectMeta(name="rigid")))
        store.create_storage_class(StorageClass(
            meta=ObjectMeta(name="elastic"), allow_volume_expansion=True))
        pvc = PersistentVolumeClaim(meta=ObjectMeta(name="a"),
                                    storage_class="rigid", requested_bytes=100)
        store.create_pvc(pvc)
        grown = PersistentVolumeClaim(meta=ObjectMeta(name="a"),
                                      storage_class="rigid", requested_bytes=200)
        with pytest.raises(AdmissionError, match="expansion"):
            store.update_object("PersistentVolumeClaim", grown)
        pvc2 = PersistentVolumeClaim(meta=ObjectMeta(name="b"),
                                     storage_class="elastic", requested_bytes=100)
        store.create_pvc(pvc2)
        store.update_object("PersistentVolumeClaim", PersistentVolumeClaim(
            meta=ObjectMeta(name="b"), storage_class="elastic", requested_bytes=200))
        shrunk = PersistentVolumeClaim(meta=ObjectMeta(name="b"),
                                       storage_class="elastic", requested_bytes=50)
        with pytest.raises(AdmissionError, match="shrink"):
            store.update_object("PersistentVolumeClaim", shrunk)


class TestOwnerReferencesPermissionEnforcement:
    class _DenyAll:
        def allowed(self, user, verb, kind, name, subresource=""):
            return False

    class _AllowAll:
        def allowed(self, user, verb, kind, name, subresource=""):
            return True

    def test_block_owner_deletion_needs_finalizer_permission(self):
        store = ClusterStore()
        store.authorizer = self._DenyAll()
        pod = make_pod("p").req({"cpu": "1"}).obj()
        pod.meta.owner_references = (OwnerReference(
            kind="ReplicaSet", name="rs", controller=True,
            block_owner_deletion=True),)
        with pytest.raises(AdmissionError, match="blockOwnerDeletion"):
            store.create_pod(pod)
        store.authorizer = self._AllowAll()
        store.create_pod(pod)

    def test_no_authorizer_no_enforcement(self):
        store = ClusterStore()
        pod = make_pod("p").req({"cpu": "1"}).obj()
        pod.meta.owner_references = (OwnerReference(
            kind="ReplicaSet", name="rs", block_owner_deletion=True),)
        store.create_pod(pod)


class TestWebhookAdmission:
    def test_mutating_webhook_patches_priority(self):
        store = ClusterStore()

        def bump_priority(review):
            assert review["kind"] == "Pod"
            return {"allowed": True,
                    "patch": [{"op": "replace", "path": "/spec/priority",
                               "value": 7}]}

        store.create_object("MutatingWebhookConfiguration", WebhookConfiguration(
            meta=ObjectMeta(name="bumper"), kinds=("Pod",),
            handler=bump_priority))
        pod = make_pod("p").req({"cpu": "1"}).obj()
        store.create_pod(pod)
        assert store.get_pod(pod.key()).spec.priority == 7

    def test_validating_webhook_denies(self):
        store = ClusterStore()
        store.create_object("ValidatingWebhookConfiguration", WebhookConfiguration(
            meta=ObjectMeta(name="gate"), kinds=("Pod",),
            handler=lambda review: {"allowed": False, "message": "not today"}))
        with pytest.raises(AdmissionError, match="not today"):
            store.create_pod(make_pod("p").req({"cpu": "1"}).obj())

    def test_failure_policy_ignore_tolerates_broken_webhook(self):
        store = ClusterStore()

        def broken(review):
            raise RuntimeError("down")

        store.create_object("ValidatingWebhookConfiguration", WebhookConfiguration(
            meta=ObjectMeta(name="flaky"), kinds=("Pod",), handler=broken,
            failure_policy="Ignore"))
        store.create_pod(make_pod("p").req({"cpu": "1"}).obj())

    def test_failure_policy_fail_rejects(self):
        store = ClusterStore()

        def broken(review):
            raise RuntimeError("down")

        store.create_object("ValidatingWebhookConfiguration", WebhookConfiguration(
            meta=ObjectMeta(name="strict"), kinds=("Pod",), handler=broken))
        with pytest.raises(AdmissionError, match="webhook call failed"):
            store.create_pod(make_pod("p").req({"cpu": "1"}).obj())

    def test_webhook_over_http(self):
        import json
        from http.server import BaseHTTPRequestHandler, HTTPServer
        import threading

        class H(BaseHTTPRequestHandler):
            def do_POST(self):
                n = int(self.headers["Content-Length"])
                review = json.loads(self.rfile.read(n))
                body = json.dumps({
                    "allowed": review["name"] != "bad"}).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        srv = HTTPServer(("127.0.0.1", 0), H)
        t = threading.Thread(target=srv.serve_forever, daemon=True)
        t.start()
        try:
            store = ClusterStore()
            store.create_object(
                "ValidatingWebhookConfiguration", WebhookConfiguration(
                    meta=ObjectMeta(name="remote"), kinds=("Pod",),
                    url=f"http://127.0.0.1:{srv.server_address[1]}/validate"))
            store.create_pod(make_pod("good").req({"cpu": "1"}).obj())
            with pytest.raises(AdmissionError):
                store.create_pod(make_pod("bad").req({"cpu": "1"}).obj())
        finally:
            srv.shutdown()
