"""BASELINE config-5 stretch smoke: 50k nodes through the capacity-growth
path, and the sharded program at 50k slots on the 8-device virtual mesh
(SURVEY §5.7: the node axis is this framework's long-context dimension)."""

import numpy as np
import jax
import pytest

from kubernetes_tpu.api.wrappers import make_node, make_pod
from kubernetes_tpu.apiserver import ClusterStore
from kubernetes_tpu.backend import TPUScheduler
from kubernetes_tpu.backend.sig_table import SigTable
from kubernetes_tpu.framework.types import NodeInfo
from kubernetes_tpu.ops.encode import ClusterEncoder
from kubernetes_tpu.ops.schema import Capacities
from kubernetes_tpu.parallel import (
    make_node_mesh,
    make_sharded_schedule_fn,
    shard_node_tensors,
    shard_topo_counts,
)


@pytest.mark.slow
def test_50k_nodes_schedule_and_spread():
    """50k nodes force several capacity doublings; a pod wave must place
    validly (comparer-checked) and spread across many nodes."""
    store = ClusterStore()
    sched = TPUScheduler(store, batch_size=128, comparer_every_n=16)
    for i in range(50000):
        store.create_node(
            make_node(f"n{i}").capacity({"cpu": "8", "memory": "16Gi", "pods": 32})
            .label("zone", f"z{i % 20}").obj())
    for i in range(256):
        store.create_pod(make_pod(f"p{i}").req({"cpu": "1", "memory": "1Gi"}).obj())
    sched.run_until_settled()
    assert sched.metrics["scheduled"] == 256
    assert sched.device.caps.nodes >= 50000
    assert sched.comparer_mismatches == 0
    objs, _ = store.list_objects("Pod")
    nodes_used = {p.spec.node_name for p in objs if p.spec.node_name}
    # adaptive sampling (K=100 window rotating) still spreads the wave
    assert len(nodes_used) > 50


@pytest.mark.slow
def test_50k_slots_sharded_program():
    """The SPMD program at 65536 slots over the 8-device mesh: 8192-slot
    shards, winners valid and capacity-respecting."""
    assert len(jax.devices()) == 8
    n_nodes, cap = 50000, 65536
    infos = [
        NodeInfo(make_node(f"n{i}").capacity({"cpu": "8", "memory": "16Gi", "pods": 32}).obj())
        for i in range(n_nodes)
    ]
    enc = ClusterEncoder(Capacities(
        nodes=cap, pods=64, value_words=(cap + 34) // 32))
    sig = SigTable(enc)
    nt = enc.encode_snapshot(infos)
    pods = [make_pod(f"p{i}").req({"cpu": "2", "memory": "2Gi"}).obj() for i in range(64)]
    pb, et = enc.encode_pods(pods)
    tb = sig.encode_topo(pods)
    tc = sig.topo_counts()

    mesh = make_node_mesh()
    fn = make_sharded_schedule_fn(mesh, topo_enabled=False)
    res = fn(pb, et, shard_node_tensors(nt, mesh), shard_topo_counts(tc, mesh),
             tb, jax.random.PRNGKey(3))
    idx = np.asarray(res.node_idx)
    assert (idx >= 0).all()
    assert (idx < n_nodes).all()
    # distinct winners: 64 pods over 50k empty nodes never need to share
    assert len(set(int(i) for i in idx)) == 64


@pytest.mark.slow
def test_50k_slots_sharded_speculative_decode():
    """The flagship SPECULATIVE program at 65536 slots over the 8-device
    mesh: the decide/repair rounds must match the sharded scan at stretch
    scale (BASELINE config 5's node-axis long-context analog)."""
    assert len(jax.devices()) == 8
    n_nodes, cap = 50000, 65536
    infos = [
        NodeInfo(make_node(f"n{i}").capacity({"cpu": "8", "memory": "16Gi", "pods": 32}).obj())
        for i in range(n_nodes)
    ]
    enc = ClusterEncoder(Capacities(
        nodes=cap, pods=64, value_words=(cap + 34) // 32))
    sig = SigTable(enc)
    nt = enc.encode_snapshot(infos)
    pods = [make_pod(f"p{i}").req({"cpu": "2", "memory": "2Gi"}).obj() for i in range(64)]
    pb, et = enc.encode_pods(pods)
    tb = sig.encode_topo(pods)
    tc = sig.topo_counts()

    mesh = make_node_mesh()
    nts = shard_node_tensors(nt, mesh)
    tcs = shard_topo_counts(tc, mesh)
    key = jax.random.PRNGKey(7)
    scan = make_sharded_schedule_fn(mesh, topo_enabled=False)(
        pb, et, nts, tcs, tb, key)
    spec = make_sharded_schedule_fn(mesh, topo_enabled=False, spec_decode=True)(
        pb, et, nts, tcs, tb, key)
    assert np.array_equal(np.asarray(scan.node_idx), np.asarray(spec.node_idx))
