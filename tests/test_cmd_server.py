"""Scheduler command server: config decode (v1beta2/v1beta3, durations,
leader election), feature gates, healthz/readyz/configz/metrics mux, and the
leader-gated loop."""

import json
import urllib.request

import pytest

from kubernetes_tpu.api.wrappers import make_node, make_pod
from kubernetes_tpu.apiserver.store import ClusterStore
from kubernetes_tpu.cmd.server import ComponentServer, SchedulerApp, setup
from kubernetes_tpu.config.types import ConfigError, _parse_duration, load_config
from kubernetes_tpu.utils.featuregate import FeatureGate, FeatureSpec


class TestConfigVersions:
    def test_v1beta3_accepted(self):
        cfg = load_config({"apiVersion": "kubescheduler.config.k8s.io/v1beta3"})
        assert cfg.api_version.endswith("v1beta3")

    def test_v1beta2_accepted(self):
        cfg = load_config({
            "apiVersion": "kubescheduler.config.k8s.io/v1beta2",
            "profiles": [{"schedulerName": "default-scheduler"}],
        })
        assert cfg.api_version.endswith("v1beta2")

    def test_unknown_version_rejected(self):
        with pytest.raises(ConfigError):
            load_config({"apiVersion": "kubescheduler.config.k8s.io/v1alpha1"})

    def test_leader_election_decoded(self):
        cfg = load_config({
            "leaderElection": {
                "leaderElect": False,
                "leaseDuration": "30s",
                "renewDeadline": "20s",
                "retryPeriod": "5s",
            }
        })
        assert cfg.leader_elect is False
        assert cfg.leader_elect_lease_duration == 30.0
        assert cfg.leader_elect_renew_deadline == 20.0

    def test_durations(self):
        assert _parse_duration("15s") == 15.0
        assert _parse_duration("2m30s") == 150.0
        assert _parse_duration("100ms") == 0.1
        assert _parse_duration("1h") == 3600.0
        assert _parse_duration(7) == 7.0
        with pytest.raises(ConfigError):
            _parse_duration("3x")

    def test_client_connection(self):
        cfg = load_config({"clientConnection": {"qps": 5000, "burst": 5000}})
        assert cfg.client_qps == 5000 and cfg.client_burst == 5000


class TestFeatureGates:
    def test_defaults_and_overrides(self):
        fg = FeatureGate()
        assert fg.enabled("TPUBatchedScheduling") is True
        fg.set_from_string("TPUBatchedScheduling=false,ReadWriteOncePod=true")
        assert fg.enabled("TPUBatchedScheduling") is False
        assert fg.enabled("ReadWriteOncePod") is True

    def test_locked_ga_feature(self):
        fg = FeatureGate()
        with pytest.raises(ValueError):
            fg.set_from_map({"DefaultPodTopologySpread": False})

    def test_unknown_feature(self):
        fg = FeatureGate()
        with pytest.raises(ValueError):
            fg.set_from_string("NoSuchFeature=true")
        with pytest.raises(KeyError):
            fg.enabled("NoSuchFeature")

    def test_add_custom(self):
        fg = FeatureGate()
        fg.add({"MyGate": FeatureSpec(False)})
        assert fg.enabled("MyGate") is False


def _get(port, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}", timeout=5) as r:
        return r.status, r.read().decode()


class TestComponentServer:
    def test_mux_endpoints(self):
        from kubernetes_tpu.metrics.registry import Registry, Counter

        reg = Registry()
        c = Counter("test_requests_total", "help")
        reg.register(c)
        c.inc()
        srv = ComponentServer(configz={"a": {"b": 1}}, registry=reg)
        port = srv.start()
        try:
            assert _get(port, "/healthz") == (200, "ok")
            assert _get(port, "/readyz")[0] == 200
            status, body = _get(port, "/configz")
            assert status == 200 and json.loads(body) == {"a": {"b": 1}}
            status, body = _get(port, "/metrics")
            assert status == 200 and "test_requests_total 1" in body
            try:
                _get(port, "/nope")
                assert False, "expected 404"
            except urllib.error.HTTPError as e:
                assert e.code == 404
        finally:
            srv.stop()


class TestDebugEndpoints:
    def test_debug_mux_over_http(self):
        from kubernetes_tpu.utils import tracing

        store = ClusterStore()
        for i in range(3):
            store.create_node(make_node(f"n{i}").capacity(
                {"cpu": "4", "memory": "8Gi", "pods": 10}).obj())
        app = SchedulerApp(store, raw_config=None)
        port = app.server.start()
        tracing.enable()  # in-memory exporter feeds /debug/spans
        try:
            store.create_pod(make_pod("ok").req({"cpu": "100m"}).obj())
            store.create_pod(make_pod("huge").req({"cpu": "64"}).obj())
            app.tick()

            # GET /debug is the self-describing index: one JSON listing of
            # every mounted endpoint, so docs can't silently drift — the
            # set below IS the documented surface (README Observability)
            status, body = _get(port, "/debug")
            assert status == 200
            assert set(json.loads(body)["endpoints"]) == {
                "/debug/queue", "/debug/cache", "/debug/devicestate",
                "/debug/slices", "/debug/spans", "/debug/circuit",
                "/debug/sessions", "/debug/fabric", "/debug/flightrecorder",
                "/debug/quota", "/debug/locktrace", "/debug/ledger",
                "/debug/timeline", "/debug/dispatch", "/debug/rebalance"}
            # every listed endpoint answers 200 with a JSON body (the
            # index can't name a route the mux doesn't actually serve)
            for ep in json.loads(body)["endpoints"]:
                st, b = _get(port, ep)
                assert st == 200, ep
                json.loads(b)

            # latency ledger off by default: the disabled report
            status, body = _get(port, "/debug/ledger")
            assert status == 200
            assert json.loads(body) == {"enabled": False}

            # no Rebalancer attached on a plain oracle app: disabled report
            status, body = _get(port, "/debug/rebalance")
            assert status == 200
            assert json.loads(body) == {"enabled": False}

            # the unified timeline renders even with the ledger off
            # (spans + flight events only) and is valid Chrome trace JSON
            status, body = _get(port, "/debug/timeline")
            assert status == 200
            doc = json.loads(body)
            assert isinstance(doc["traceEvents"], list)
            assert all("ph" in ev and "name" in ev
                       for ev in doc["traceEvents"])

            # non-wire scheduler: the fabric endpoint reports disabled
            status, body = _get(port, "/debug/fabric")
            assert status == 200
            assert json.loads(body)["enabled"] is False

            # locktrace endpoint: disabled report by default, full graph
            # dump when the suite runs under KTPU_LOCKTRACE=1
            status, body = _get(port, "/debug/locktrace")
            assert status == 200
            doc = json.loads(body)
            if doc["enabled"]:
                assert "cycles" in doc and "acquisitions" in doc
            else:
                assert doc == {"enabled": False}

            status, body = _get(port, "/debug/queue")
            doc = json.loads(body)
            assert status == 200
            assert doc["counts"]["unschedulable"] == 1
            assert doc["unschedulable"][0]["pod"] == "default/huge"
            assert "NodeResourcesFit" in doc["unschedulable"][0]["unschedulablePlugins"]

            status, body = _get(port, "/debug/cache")
            doc = json.loads(body)
            assert status == 200
            assert doc["nodes"] == 3 and doc["pods"] >= 1
            assert doc["inSync"] is True

            status, body = _get(port, "/debug/devicestate")
            assert status == 200
            assert json.loads(body) == {"enabled": False}  # oracle scheduler

            status, body = _get(port, "/debug/circuit")
            assert status == 200
            assert json.loads(body) == {"enabled": False}  # no wire backend

            with tracing.span("probe"):
                pass
            status, body = _get(port, "/debug/spans")
            doc = json.loads(body)
            assert status == 200
            assert any(s["name"] == "probe" for s in doc)

            try:
                _get(port, "/debug/nope")
                assert False, "expected 404"
            except urllib.error.HTTPError as e:
                assert e.code == 404
        finally:
            tracing.disable()
            app.server.stop()

    def test_debug_limit_caps_unbounded_dumps(self):
        """ISSUE 7 satellite: ?limit=N bounds every list-shaped /debug dump
        (a 5k-node queue dump serialized whole is megabytes of JSON from
        the serving thread); the default cap applies without the query."""
        from kubernetes_tpu.cmd import server as srv_mod

        store = ClusterStore()
        for i in range(3):
            store.create_node(make_node(f"n{i}").capacity(
                {"cpu": "32", "memory": "64Gi", "pods": 110}).obj())
        app = SchedulerApp(store, raw_config=None)
        port = app.server.start()
        try:
            # park more pods than the limit in the unschedulable queue
            for i in range(8):
                store.create_pod(make_pod(f"huge{i}").req({"cpu": "640"}).obj())
            app.tick()

            status, body = _get(port, "/debug/queue?limit=3")
            doc = json.loads(body)
            assert status == 200
            assert doc["counts"]["unschedulable"] == 8  # counts stay exact
            assert len(doc["unschedulable"]) == 3       # entries capped
            assert doc["truncated"]["unschedulable"] == 8

            # default cap (no query) leaves small dumps whole
            status, body = _get(port, "/debug/queue")
            doc = json.loads(body)
            assert len(doc["unschedulable"]) == 8
            assert "truncated" not in doc
            assert srv_mod.DEFAULT_DEBUG_LIMIT >= 8

            status, body = _get(port, "/debug/spans?limit=2")
            assert status == 200
            assert len(json.loads(body)) <= 2

            # limit=0 means ZERO entries, never "all" (the spans[-0:] trap)
            from kubernetes_tpu.utils import tracing
            tracing.enable()
            try:
                with tracing.span("probe"):
                    pass
                status, body = _get(port, "/debug/spans?limit=0")
                assert status == 200 and json.loads(body) == []
            finally:
                tracing.disable()
            status, body = _get(port, "/debug/queue?limit=0")
            doc = json.loads(body)
            assert doc["unschedulable"] == []
            assert doc["counts"]["unschedulable"] == 8

            # cache dump truncation is visible, never silent
            status, body = _get(port, "/debug/cache?limit=1000")
            doc = json.loads(body)
            assert status == 200 and "truncated" not in doc

            # a garbage limit falls back to the default instead of erroring
            status, _body = _get(port, "/debug/queue?limit=bogus")
            assert status == 200
        finally:
            app.server.stop()

    def test_debug_quota_cohort_view(self):
        """ISSUE 19 satellite: /debug/quota grows the per-cohort borrowing
        pool view (guaranteed/lent/headroom, outstanding loans newest-first,
        reclaim breaker state) and the loans list honours ?limit=."""
        from kubernetes_tpu.api.types import (
            Namespace, ObjectMeta, SchedulingQuota)

        store = ClusterStore()
        store.create_node(make_node("n0").capacity(
            {"cpu": "32", "memory": "64Gi", "pods": 64}).obj())
        for ns in ("team-a", "team-b"):
            store.create_namespace(Namespace(meta=ObjectMeta(name=ns)))
            store.create_object("SchedulingQuota", SchedulingQuota(
                meta=ObjectMeta(name="q", namespace=ns),
                hard={"pods": 3}, cohort="ml"))
        app = SchedulerApp(store, raw_config=None)
        port = app.server.start()
        try:
            # team-b runs past its own cap into team-a's idle headroom
            for i in range(5):
                store.create_pod(make_pod(
                    f"b{i}", namespace="team-b").req({"cpu": "100m"}).obj())
            app.tick()

            status, body = _get(port, "/debug/quota")
            doc = json.loads(body)
            assert status == 200 and doc["enabled"] is True
            assert "_cohorts" not in doc["namespaces"]
            ml = doc["cohorts"]["ml"]
            assert sorted(ml["members"]) == ["team-a", "team-b"]
            assert ml["lent"]["pods"] == 2
            assert len(ml["loans"]) == 2
            assert ml["reclaim_breaker"]["state"] == "closed"
            assert doc["namespaces"]["team-b"]["borrowed"]["pods"] == 2
            assert doc["namespaces"]["team-b"]["cohort"] == "ml"

            # loans honour the uniform entry cap, truncation visible
            status, body = _get(port, "/debug/quota?limit=1")
            doc = json.loads(body)
            assert len(doc["cohorts"]["ml"]["loans"]) == 1
            assert doc["cohorts"]["ml"]["loansTruncated"] == 2
        finally:
            app.server.stop()

    def test_debug_flightrecorder_endpoint(self):
        from kubernetes_tpu.backend import telemetry

        store = ClusterStore()
        app = SchedulerApp(store, raw_config=None)
        port = app.server.start()
        try:
            # off by default: the endpoint reports disabled, not an error
            status, body = _get(port, "/debug/flightrecorder")
            assert status == 200
            assert json.loads(body) == {"enabled": False}

            t = telemetry.enable()
            for i in range(5):
                t.event("dispatch", batchId=f"b{i}")
            status, body = _get(port, "/debug/flightrecorder?limit=2")
            doc = json.loads(body)
            assert status == 200
            assert doc["enabled"] is True
            assert doc["ring"]["held"] == 5
            assert [e["batchId"] for e in doc["events"]] == ["b3", "b4"]
            assert doc["truncated"] == {"events": 5}  # capped ≠ short
            assert "compile" in doc and "transfer" in doc
        finally:
            telemetry.disable()
            app.server.stop()

    def test_debug_sessions_on_wire_scheduler(self):
        """/debug/sessions smoke: per-client lease age, deltaSeq, and
        in-flight hold counts ride the cmd mux for a WireScheduler; plain
        schedulers answer enabled=false."""
        from kubernetes_tpu.backend.service import DeviceService, WireScheduler, serve
        from kubernetes_tpu.cmd.server import ComponentServer, build_debug_handlers
        from kubernetes_tpu.scheduler.scheduler import Scheduler

        service = DeviceService(batch_size=16)
        dev_server, dev_port = serve(service)
        try:
            store = ClusterStore()
            sched = WireScheduler(store,
                                  endpoint=f"http://127.0.0.1:{dev_port}",
                                  batch_size=8, client_id="muxed")
            store.create_node(make_node("n0").capacity(
                {"cpu": "4", "memory": "8Gi", "pods": 10}).obj())
            store.create_pod(make_pod("p0").req({"cpu": "500m"}).obj())
            sched.run_until_settled()
            srv = ComponentServer(configz={},
                                  debug=build_debug_handlers(sched))
            port = srv.start()
            try:
                status, body = _get(port, "/debug/sessions")
                assert status == 200
                doc = json.loads(body)
                assert doc["enabled"] is True and doc["clientId"] == "muxed"
                table = {s["clientId"]: s
                         for s in doc["service"]["sessions"]}
                row = table["muxed"]
                assert row["deltaSeq"] >= 1
                assert row["leaseAgeS"] >= 0.0
                assert row["batches"] >= 1
                assert row["fenced"] is False
                assert "inflightHolds" in row
            finally:
                srv.stop()
        finally:
            dev_server.shutdown()
        # a non-wire scheduler has no session surface
        plain = build_debug_handlers(Scheduler(ClusterStore()))
        assert plain["sessions"]() == {"enabled": False}

    def test_devicestate_dump_on_batched_scheduler(self):
        from kubernetes_tpu.backend import TPUScheduler
        from kubernetes_tpu.cmd.server import build_debug_handlers

        store = ClusterStore()
        for i in range(4):
            store.create_node(make_node(f"n{i}").capacity(
                {"cpu": "4", "memory": "8Gi", "pods": 10}).obj())
        sched = TPUScheduler(store, batch_size=8)
        for i in range(5):
            store.create_pod(make_pod(f"p{i}").req({"cpu": "100m"}).obj())
        sched.run_until_settled()
        doc = json.loads(json.dumps(
            build_debug_handlers(sched)["devicestate"](), default=str))
        assert doc["enabled"] is True
        assert doc["caps"]["nodes"] >= 4
        assert doc["nodesMirrored"] == 4
        assert doc["batchCounter"] >= 1
        assert doc["sigTable"]["nSigs"] >= 1
        assert doc["batchSizer"]["target"] >= 1
        # unlabeled nodes get synthetic torus coords from the encoder, so
        # the topology block is populated even without well-known labels
        topo = doc["topology"]
        assert topo["chipsPerNode"] >= 1
        assert len(topo["nodes"]) == 4
        assert {n["node"] for n in topo["nodes"]} == {f"n{i}" for i in range(4)}

    def test_slices_dump_topology_and_limit(self):
        """ISSUE 16 satellite: /debug/slices renders the torus occupancy
        map off the host mirror, /debug/devicestate carries the topology
        block, and both honor the uniform ?limit= capping."""
        from kubernetes_tpu.backend import TPUScheduler
        from kubernetes_tpu.cmd.server import build_debug_handlers
        from kubernetes_tpu.ops.encode import (TOPO_SLOT_LABEL,
                                               TOPO_SUPERPOD_LABEL)

        store = ClusterStore()
        for i in range(8):
            store.create_node(
                make_node(f"n{i}").capacity(
                    {"cpu": "4", "memory": "8Gi", "pods": 10})
                .label(TOPO_SUPERPOD_LABEL, str(i // 4))
                .label(TOPO_SLOT_LABEL, str(i % 4)).obj())
        sched = TPUScheduler(store, batch_size=8)
        store.create_pod(make_pod("p0").req({"cpu": "100m"}).obj())
        sched.run_until_settled()
        handlers = build_debug_handlers(sched)
        doc = json.loads(json.dumps(handlers["slices"](), default=str))
        assert doc["enabled"] is True
        assert len(doc["superpods"]) == 2
        for row in doc["superpods"]:
            assert set(row) >= {"sp", "free", "used", "largest_run",
                                "frag", "map"}
            # 4 mapped hosts per superpod; one host is used somewhere
            assert len(row["map"]) == doc["grid"]["slots"]
            assert row["map"].count("-") == doc["grid"]["slots"] - 4
        assert sum(r["used"] for r in doc["superpods"]) == 1
        # ?limit= caps the superpod rows, truncation stays visible
        capped = handlers["slices"](limit=1)
        assert len(capped["superpods"]) == 1
        assert capped["superpodsTruncated"] == 2
        # the devicestate topology block honors the same cap on nodes
        dev = handlers["devicestate"](limit=3)
        assert len(dev["topology"]["nodes"]) == 3
        assert dev["topology"]["nodesTruncated"] == 8
        assert dev["topology"]["grid"]["slots"] >= 4


class TestSchedulerApp:
    def test_app_schedules_and_serves(self):
        store = ClusterStore()
        for i in range(5):
            store.create_node(make_node(f"n{i}").capacity(
                {"cpu": "4", "memory": "8Gi", "pods": 10}).obj())
        app = SchedulerApp(store, raw_config={
            "apiVersion": "kubescheduler.config.k8s.io/v1beta3",
            "leaderElection": {"leaderElect": True},
        })
        app.server.start()
        try:
            for i in range(10):
                store.create_pod(make_pod(f"p{i}").req({"cpu": "100m"}).obj())
            app.tick()
            bound = [p for p in store.pods.values() if p.spec.node_name]
            assert len(bound) == 10
            # leader lease exists
            assert store.get_lease("kube-system/kube-scheduler") is not None
            status, body = _get(app.server.port, "/configz")
            assert "kubescheduler.config.k8s.io" in body
            status, body = _get(app.server.port, "/metrics")
            assert status == 200
        finally:
            app.server.stop()

    def test_standby_does_not_schedule(self):
        store = ClusterStore()
        store.create_node(make_node("n1").capacity(
            {"cpu": "4", "memory": "8Gi", "pods": 10}).obj())
        leader = SchedulerApp(store, raw_config=None, identity="a")
        standby = SchedulerApp(store, raw_config=None, identity="b")
        store.create_pod(make_pod("p").req({"cpu": "100m"}).obj())
        assert leader.tick() > 0
        assert standby.tick() == 0  # not the leader: loop gated

    def test_setup_with_feature_gates(self):
        store = ClusterStore()
        sched = setup(store, raw=None, feature_gates="PodOverhead=false")
        assert sched is not None


class TestStandaloneAPIServer:
    def test_binary_serves_and_restores_wal(self, tmp_path):
        """cmd/kube-apiserver: launch as a subprocess with a WAL + token
        auth, drive it over HTTP, restart, state survives."""
        import json
        import os
        import threading
        import signal
        import subprocess
        import sys
        import time
        import urllib.error
        import urllib.request

        wal = str(tmp_path / "store.wal")
        tokens = tmp_path / "tokens.csv"
        tokens.write_text('tok-admin,admin,uid1,"system:masters"\n')

        env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH="/root/repo")

        def launch():
            return subprocess.Popen(
                [sys.executable, "-m", "kubernetes_tpu.cmd.apiserver",
                 "--port", "0", "--wal", wal,
                 "--token-auth-file", str(tokens),
                 "--authorization-mode", "RBAC"],
                env=env, stderr=subprocess.PIPE, text=True)

        def read_port(proc, timeout=30.0):
            # scan stderr until the listen line (warnings/restore lines may
            # precede it); a deadline thread guards against a hung child
            killer = threading.Timer(timeout, proc.kill)
            killer.start()
            try:
                for line in proc.stderr:
                    if "listening on" in line:
                        return int(line.split("127.0.0.1:")[1].split()[0])
                raise AssertionError("apiserver exited before listening")
            finally:
                killer.cancel()

        proc = launch()
        try:
            port = read_port(proc)
            body = json.dumps({"meta": {"name": "n1"},
                               "status": {"capacity": {"cpu": "4"}}}).encode()
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/api/v1/nodes", data=body,
                headers={"Content-Type": "application/json",
                         "Authorization": "Bearer tok-admin"}, method="POST")
            with urllib.request.urlopen(req, timeout=5) as r:
                assert r.status == 201
            # RBAC denies an unauthenticated write
            req2 = urllib.request.Request(
                f"http://127.0.0.1:{port}/api/v1/nodes",
                data=json.dumps({"meta": {"name": "n2"}}).encode(),
                headers={"Content-Type": "application/json"}, method="POST")
            try:
                urllib.request.urlopen(req2, timeout=5)
                raise AssertionError("anonymous write passed RBAC")
            except urllib.error.HTTPError as e:
                assert e.code == 403
        finally:
            proc.send_signal(signal.SIGTERM)
            proc.wait(timeout=15)

        # restart: the node survives via the WAL
        proc = launch()
        try:
            port = read_port(proc)
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/api/v1/nodes/n1",
                headers={"Authorization": "Bearer tok-admin"})
            with urllib.request.urlopen(req, timeout=5) as r:
                doc = json.loads(r.read())
            assert doc["meta"]["name"] == "n1"
        finally:
            proc.send_signal(signal.SIGTERM)
            proc.wait(timeout=15)
