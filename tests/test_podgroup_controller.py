"""PodGroup controller (controllers/podgroup.py): out-of-band group status
reconciliation — orphaned-group GC, status drift repair after a scheduler
restart, and controller/plugin non-interference (ISSUE 8 satellite)."""

import dataclasses

from kubernetes_tpu.api.types import (
    ObjectMeta,
    POD_GROUP_LABEL,
    POD_GROUP_PENDING,
    POD_GROUP_RUNNING,
    POD_GROUP_SCHEDULING,
    PodGroup,
)
from kubernetes_tpu.api.wrappers import make_node, make_pod
from kubernetes_tpu.apiserver.store import ClusterStore
from kubernetes_tpu.client.informer import SharedInformerFactory
from kubernetes_tpu.controllers.podgroup import PodGroupController
from kubernetes_tpu.scheduler.scheduler import Scheduler
from kubernetes_tpu.utils.clock import FakeClock


def make_controller(store, clock=None, ttl=60.0):
    factory = SharedInformerFactory(store)
    ctrl = PodGroupController(store, factory, now_fn=clock or FakeClock(),
                              orphan_ttl_s=ttl)
    factory.wait_for_cache_sync()
    return ctrl, factory


def pump(ctrl, factory):
    factory.pump()
    ctrl.sync_once()


def make_group(store, name="g", ns="default", min_member=2, **kw):
    pg = PodGroup(meta=ObjectMeta(name=name, namespace=ns),
                  min_member=min_member, **kw)
    store.create_object("PodGroup", pg)
    return pg


def member(store, name, group="g", node=""):
    pw = make_pod(name).req({"cpu": "100m"}).pod_group(group)
    pod = pw.obj()
    if node:
        pod.spec.node_name = node
    store.create_pod(pod)
    return pod


class TestStatusDriftRepair:
    def test_restart_drift_repaired_from_store_truth(self):
        """A scheduler restart loses the plugin's bound-count cache: a group
        whose members are all bound but whose status still reads Pending/0
        (or stale-Scheduling) is repaired to Running/N from store truth."""
        store = ClusterStore()
        make_group(store, min_member=2)
        member(store, "m0", node="n0")
        member(store, "m1", node="n1")
        ctrl, factory = make_controller(store)
        pump(ctrl, factory)
        pg = store.get_object("PodGroup", "default/g")
        assert pg.phase == POD_GROUP_RUNNING
        assert pg.scheduled == 2

    def test_running_with_lost_quorum_demoted(self):
        """Running recorded in the store but quorum gone (members deleted
        while the scheduler was down) is impossible-by-truth — demote."""
        store = ClusterStore()
        make_group(store, min_member=2,
                   phase=POD_GROUP_RUNNING, scheduled=2)
        member(store, "m0", node="n0")  # only one bound member remains
        ctrl, factory = make_controller(store)
        pump(ctrl, factory)
        pg = store.get_object("PodGroup", "default/g")
        assert pg.phase == POD_GROUP_SCHEDULING
        assert pg.scheduled == 1

    def test_scheduling_below_quorum_not_flipped(self):
        """Pending↔Scheduling below quorum is transient Permit-park state
        only the plugin can witness: the controller corrects the COUNT but
        never flips the phase (the non-interference contract)."""
        store = ClusterStore()
        make_group(store, min_member=3,
                   phase=POD_GROUP_SCHEDULING, scheduled=0)
        member(store, "m0")
        member(store, "m1")
        ctrl, factory = make_controller(store)
        pump(ctrl, factory)
        pg = store.get_object("PodGroup", "default/g")
        assert pg.phase == POD_GROUP_SCHEDULING  # untouched
        assert pg.scheduled == 0


class TestOrphanGC:
    def test_memberless_group_reset_then_reaped(self):
        store = ClusterStore()
        clock = FakeClock()
        make_group(store, min_member=2,
                   phase=POD_GROUP_RUNNING, scheduled=2)  # stale leftovers
        ctrl, factory = make_controller(store, clock=clock, ttl=60.0)
        pump(ctrl, factory)
        # first observation: status reset to Pending/0, object kept
        pg = store.get_object("PodGroup", "default/g")
        assert pg is not None
        assert (pg.phase, pg.scheduled) == (POD_GROUP_PENDING, 0)
        # ...and once memberless past the TTL, deleted outright
        clock.advance(61.0)
        ctrl.tick()
        ctrl.sync_once()
        assert store.get_object("PodGroup", "default/g") is None

    def test_member_blip_resets_gc_clock(self):
        store = ClusterStore()
        clock = FakeClock()
        make_group(store, min_member=1)
        ctrl, factory = make_controller(store, clock=clock, ttl=60.0)
        pump(ctrl, factory)
        clock.advance(45.0)
        member(store, "m0")  # members appear before the TTL
        pump(ctrl, factory)
        clock.advance(45.0)  # 90s total, but only 0s memberless since blip
        store.delete_pod("default/m0")
        pump(ctrl, factory)
        clock.advance(45.0)
        ctrl.tick()
        ctrl.sync_once()
        assert store.get_object("PodGroup", "default/g") is not None
        clock.advance(30.0)  # 75s memberless: past the TTL
        ctrl.tick()
        ctrl.sync_once()
        assert store.get_object("PodGroup", "default/g") is None


class TestNonInterference:
    def _scheduled_gang(self):
        """A live scheduler with a bound 2-gang plus the controller over the
        same store — both reconciling the same group."""
        store = ClusterStore()
        for i in range(4):
            store.create_node(make_node(f"n{i}").capacity(
                {"cpu": "4", "memory": "8Gi", "pods": 10}).obj())
        sched = Scheduler(store)
        make_group(store, min_member=2)
        member(store, "m0")
        member(store, "m1")
        sched.run_until_settled()
        pg = store.get_object("PodGroup", "default/g")
        assert pg.phase == POD_GROUP_RUNNING and pg.scheduled == 2
        return store, sched

    def test_controller_plugin_non_interference(self):
        """Both the plugin and the controller reconciling the same group
        converge instead of livelocking: after one controller pass over a
        plugin-maintained group, further alternating passes write NOTHING
        (resource_version stays put)."""
        store, sched = self._scheduled_gang()
        ctrl, factory = make_controller(store)
        pump(ctrl, factory)
        rv = store.get_object("PodGroup", "default/g").meta.resource_version
        for _ in range(5):
            # controller pass + plugin pass (a member PostBind-equivalent
            # status refresh via pod_deleted bookkeeping on a no-op event)
            ctrl.tick()
            ctrl.sync_once()
            factory.pump()
        assert store.get_object(
            "PodGroup", "default/g").meta.resource_version == rv

    def test_controller_repairs_while_plugin_restarts(self):
        """Scheduler restart: a FRESH scheduler (empty plugin caches) plus
        the controller both see the half-deleted gang; they settle on the
        same store-derived status and stop writing."""
        store, sched = self._scheduled_gang()
        store.delete_pod("default/m1")  # quorum lost while "restarting"
        sched2 = Scheduler(store)  # fresh plugin caches  # noqa: F841
        ctrl, factory = make_controller(store)
        pump(ctrl, factory)
        pg = store.get_object("PodGroup", "default/g")
        assert pg.phase == POD_GROUP_SCHEDULING
        assert pg.scheduled == 1
        rv = pg.meta.resource_version
        for _ in range(3):
            ctrl.tick()
            ctrl.sync_once()
        assert store.get_object(
            "PodGroup", "default/g").meta.resource_version == rv
