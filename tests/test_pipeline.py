"""Async batch pipeline (SURVEY §2.7 P3): batch k+1 dispatches on batch k's
adopted device carry while the host commits batch k. These tests prove the
overlap actually happens and that it never changes placements."""

import os

import pytest

from kubernetes_tpu.api.types import LabelSelector
from kubernetes_tpu.api.wrappers import make_node, make_pod
from kubernetes_tpu.apiserver import ClusterStore
from kubernetes_tpu.backend import TPUScheduler
from kubernetes_tpu.scheduler import Scheduler


def _bound(store):
    objs, _rv = store.list_objects("Pod")
    return {p.meta.name: p.spec.node_name for p in objs if p.spec.node_name}


def _run(pipeline: bool, build):
    os.environ["KTPU_PIPELINE"] = "1" if pipeline else "0"
    try:
        store = ClusterStore()
        sched = TPUScheduler(store, batch_size=4, comparer_every_n=1)
        build(store)
        sched.run_until_settled()
        return store, sched
    finally:
        os.environ.pop("KTPU_PIPELINE", None)


def _basic_cluster(store):
    for i in range(8):
        store.create_node(
            make_node(f"n{i}").capacity({"cpu": "4", "memory": "8Gi", "pods": 10})
            .label("zone", f"z{i % 2}").obj())
    for i in range(20):
        store.create_pod(make_pod(f"p{i}").req({"cpu": "1", "memory": "1Gi"}).obj())


def test_pipeline_overlaps_and_matches_synchronous():
    store_p, sched_p = _run(True, _basic_cluster)
    store_s, sched_s = _run(False, _basic_cluster)
    assert sched_p.metrics["scheduled"] == 20
    # overlap evidence: at least one batch was dispatched on the carry
    assert sched_p.pipelined_batches > 0
    assert sched_s.pipelined_batches == 0
    # same decisions (deterministic batch numbering keys the tie-break PRNG)
    assert _bound(store_p) == _bound(store_s)
    assert sched_p.comparer_mismatches == 0


@pytest.mark.parametrize("depth", [2, 3])
def test_ring_depth_k_matches_synchronous(depth, monkeypatch):
    """Placement parity at ring depth K≥2 (ISSUE 5 acceptance): with
    multiple batches in flight on the carry chain, placements must equal
    the synchronous run's exactly — including under anti-affinity, where a
    stale carry would immediately show as a same-zone double-place."""
    monkeypatch.setenv("KTPU_PIPELINE_DEPTH", str(depth))

    def build(store):
        for i in range(8):
            store.create_node(
                make_node(f"n{i}").capacity({"cpu": "8", "memory": "16Gi", "pods": 10})
                .label("zone", f"z{i % 2}").obj())
        sel = LabelSelector(match_labels={"app": "x"})
        for i in range(6):
            store.create_pod(
                make_pod(f"aa{i}").req({"cpu": "1"}).label("app", "x")
                .pod_affinity("zone", sel, anti=True).obj())
        for i in range(18):
            store.create_pod(make_pod(f"p{i}").req({"cpu": "1", "memory": "1Gi"}).obj())

    store_p, sched_p = _run(True, build)
    monkeypatch.delenv("KTPU_PIPELINE_DEPTH")
    store_s, sched_s = _run(False, build)
    assert sched_p.pipelined_batches > 0
    assert _bound(store_p) == _bound(store_s)
    assert sched_p.comparer_mismatches == 0


def test_pipeline_capacity_respected_across_batches():
    """The r2 stale-device failure mode, now across PIPELINED batches: a
    1-slot cluster must admit exactly one pod even when later batches are
    dispatched before the first batch's host commit."""
    def build(store):
        store.create_node(
            make_node("only").capacity({"cpu": "2", "memory": "4Gi", "pods": 1}).obj())
        for i in range(9):
            store.create_pod(make_pod(f"p{i}").req({"cpu": "1", "memory": "1Gi"}).obj())

    store, sched = _run(True, build)
    assert sched.metrics["scheduled"] == 1
    assert len(_bound(store)) == 1
    assert sched.comparer_mismatches == 0


def test_pipeline_topo_carry_across_batches():
    """Anti-affinity committed in batch k must be visible to batch k+1 even
    though k+1 is dispatched BEFORE k's host commit (the sel_counts/seg_exist
    carry chain — without it, k+1 would read the stale pre-k host tables)."""
    def build(store):
        for i in range(8):
            store.create_node(
                make_node(f"n{i}").capacity({"cpu": "8", "memory": "16Gi", "pods": 10})
                .label("zone", f"z{i % 2}").obj())
        sel = LabelSelector(match_labels={"app": "x"})
        for i in range(8):
            store.create_pod(
                make_pod(f"p{i}").req({"cpu": "1"}).label("app", "x")
                .pod_affinity("zone", sel, anti=True).obj())

    store, sched = _run(True, build)
    bound = _bound(store)
    # 2 zones ⇒ exactly 2 of the 8 mutually-anti-affine pods can place
    assert len(bound) == 2, bound
    zones = {int(n[1:]) % 2 for n in bound.values()}
    assert zones == {0, 1}
    assert sched.comparer_mismatches == 0


def test_pipeline_chain_breaks_on_external_change():
    """A node created between cycles makes has_dirty trip: the chain must
    break (drain + resync) and the new node must become schedulable."""
    os.environ["KTPU_PIPELINE"] = "1"
    try:
        store = ClusterStore()
        sched = TPUScheduler(store, batch_size=4, comparer_every_n=1)
        store.create_node(
            make_node("small").capacity({"cpu": "4", "memory": "8Gi", "pods": 4}).obj())
        for i in range(4):
            store.create_pod(make_pod(f"a{i}").req({"cpu": "1", "memory": "1Gi"}).obj())
        sched.run_until_settled()
        assert sched.metrics["scheduled"] == 4

        # external change: a big node appears; the next pods must see it
        store.create_node(
            make_node("big").capacity({"cpu": "64", "memory": "128Gi", "pods": 100}).obj())
        for i in range(8):
            store.create_pod(make_pod(f"b{i}").req({"cpu": "2", "memory": "2Gi"}).obj())
        sched.run_until_settled()
        assert sched.metrics["scheduled"] == 12
        bound = _bound(store)
        assert sum(1 for n in bound.values() if n == "big") == 8
        assert sched.comparer_mismatches == 0
    finally:
        os.environ.pop("KTPU_PIPELINE", None)


def test_reconcile_elides_matching_rows_and_leaves_divergent_dirty():
    """DeviceState.reconcile must refresh generations ONLY for rows whose
    content matches the mirror (adopted commits); divergent rows must stay
    dirty so the pipelined chain breaks instead of scattering host rows
    into an adopted-ahead carry (code-review r3 finding)."""
    from kubernetes_tpu.backend.device_state import DeviceState, caps_for_cluster
    from kubernetes_tpu.cache.cache import Cache
    from kubernetes_tpu.cache.snapshot import Snapshot

    cache = Cache()
    snap = Snapshot()
    nodes = {}
    for i in range(3):
        n = make_node(f"n{i}").capacity({"cpu": "4", "memory": "8Gi", "pods": 10}).obj()
        nodes[n.meta.name] = n
        cache.add_node(n)
    cache.update_snapshot(snap)
    dev = DeviceState(caps_for_cluster(3))
    dev.sync(snap)
    assert not dev.has_dirty(snap)

    # bump n0's generation WITHOUT changing content: reconcile elides it
    cache.update_node(nodes["n0"])
    cache.update_snapshot(snap)
    assert dev.has_dirty(snap)
    left = dev.reconcile(snap)
    assert left == 0
    assert not dev.has_dirty(snap)

    # change n1's content (labels): reconcile must leave it dirty
    n1 = make_node("n1").capacity({"cpu": "4", "memory": "8Gi", "pods": 10}).label("zone", "z9").obj()
    cache.update_node(n1)
    cache.update_snapshot(snap)
    left = dev.reconcile(snap)
    assert left == 1
    assert dev.has_dirty(snap)
    # the full sync then repairs it
    dev.sync(snap)
    assert not dev.has_dirty(snap)


def test_batch_sizer_deadline_controller():
    """BatchSizer: a + b·B ≤ deadline over the POP→COMMIT attempt latency,
    clamped to [min, max] and floored to a compile bucket, from EMA
    estimates of fixed and per-pod latency cost."""
    from kubernetes_tpu.backend.tpu_scheduler import BatchSizer

    s = BatchSizer(max_batch=512, deadline_s=0.0)
    assert s.target() == 512  # disabled: always max

    s = BatchSizer(max_batch=512, deadline_s=0.3)
    # feed consistent observations: a=40ms fixed, b=1ms/pod — the decayed
    # least-squares fit must recover them exactly
    for _ in range(30):
        s.update(128, 0.040 + 0.001 * 128)
        s.update(256, 0.040 + 0.001 * 256)
    assert abs(s._a - 0.040) < 0.005 and abs(s._b - 0.001) < 0.0001
    t = s.target()
    # budget = 300ms·headroom(0.6) − a(40ms) = ~140ms; /1ms ≈ 140 → bucket
    # 128 (the headroom keeps the observed p99 — ~1.6-2x the mean span —
    # inside the declared deadline, not just the average)
    assert 64 <= t <= 256, t
    # sustained latency spike → smaller batches (the first few spikes are
    # outlier-rejected as suspected compile blips, then accepted)
    for _ in range(30):
        s.update(t, 0.100 + 0.004 * t)
    assert s.target() < t
    # tiny deadline → clamps to min
    s2 = BatchSizer(max_batch=512, deadline_s=0.01)
    for _ in range(10):
        s2.update(64, 0.05 + 0.001 * 64)
    assert s2.target() == s2.min_batch


def test_deadline_bounds_pop_size_end_to_end():
    """With a deadline set, the scheduler pops bounded batches but still
    schedules everything correctly."""
    os.environ["KTPU_BATCH_DEADLINE_MS"] = "120"
    try:
        store = ClusterStore()
        sched = TPUScheduler(store, batch_size=256)
        for i in range(16):
            store.create_node(
                make_node(f"n{i}").capacity({"cpu": "16", "memory": "32Gi", "pods": 40}).obj())
        for i in range(300):
            store.create_pod(make_pod(f"p{i}").req({"cpu": "500m", "memory": "512Mi"}).obj())
        sched.run_until_settled()
        assert sched.metrics["scheduled"] == 300
        # env wiring + real observations reached the controller
        assert sched.sizer.deadline_s == 0.12
        assert sched.sizer.updates > 0
        # and the POP SITE consults the sizer: force a tiny target and check
        # every subsequent pop is cut to it (machine-speed independent)
        class _Stub:
            def target(self):
                return 9

            def update(self, *a):
                pass

            def update_wait(self, *a):
                pass

            def bucket_for(self, n):
                return 16  # the encode bucket the program pads to

        sched.sizer = _Stub()
        pops = []
        orig_pop = sched.queue.pop_batch
        sched.queue.pop_batch = lambda k: (pops.append(k), orig_pop(k))[1]
        for i in range(40):
            store.create_pod(make_pod(f"q{i}").req({"cpu": "100m", "memory": "64Mi"}).obj())
        sched.run_until_settled()
        assert sched.metrics["scheduled"] == 340
        assert pops and all(k == 9 for k in pops), pops
    finally:
        os.environ.pop("KTPU_BATCH_DEADLINE_MS", None)


def test_adaptive_sampling_on_batch_path():
    """percentageOfNodesToScore emulation (schedule_one.go:525): with the
    knob restricting, each pod's winner must come from the first K feasible
    slots in rotated order, and the rotation must advance across pods."""
    import jax
    import numpy as np

    from kubernetes_tpu.api.wrappers import make_node, make_pod
    from kubernetes_tpu.backend.batch import schedule_batch
    from kubernetes_tpu.backend.sig_table import SigTable
    from kubernetes_tpu.framework.types import NodeInfo
    from kubernetes_tpu.ops.encode import ClusterEncoder
    from kubernetes_tpu.ops.schema import Capacities

    n_nodes = 64
    # identical nodes: every node feasible and score-tied, so the winner is
    # the jitter tie-break WITHIN the eligible window — the assertions below
    # verify window membership and rotation, not score ordering
    infos = []
    for i in range(n_nodes):
        nw = make_node(f"n{i}").capacity({"cpu": "64", "memory": "128Gi", "pods": 200})
        infos.append(NodeInfo(nw.obj()))
    enc = ClusterEncoder(Capacities(nodes=n_nodes, pods=8, value_words=32))
    sig = SigTable(enc)
    nt = enc.encode_snapshot(infos)
    pods = [make_pod(f"p{i}").req({"cpu": "1", "memory": "1Gi"}).obj() for i in range(8)]
    pb, et = enc.encode_pods(pods)
    tb = sig.encode_topo(pods)
    tc = sig.topo_counts()
    key = jax.random.PRNGKey(0)

    k = 16
    res = schedule_batch(pb, et, nt, tc, tb, key, topo_enabled=False,
                         sample_k=np.int32(k), sample_start=np.int32(0))
    idx = np.asarray(res.node_idx)
    start = 0
    for i in range(8):
        # all nodes feasible → window = slots [start, start+k) mod N
        window = {(start + j) % n_nodes for j in range(k)}
        assert int(idx[i]) in window, (i, idx[i], start)
        start = (start + k) % n_nodes  # K-th feasible found at position k-1
    assert int(np.asarray(res.final_sample_start)) == start


def test_adaptive_sampling_scheduler_equivalence_small_cluster():
    """Below the 100-node threshold K == N: the sampling knob must not
    change placements vs the full-evaluation program."""
    store_a = ClusterStore()
    sched_a = TPUScheduler(store_a, batch_size=8)
    store_b = ClusterStore()
    sched_b = TPUScheduler(store_b, batch_size=8)
    for store in (store_a, store_b):
        for i in range(12):
            store.create_node(
                make_node(f"n{i}").capacity({"cpu": "8", "memory": "16Gi", "pods": 20}).obj())
        for i in range(20):
            store.create_pod(make_pod(f"p{i}").req({"cpu": "1", "memory": "1Gi"}).obj())
    sched_a.run_until_settled()
    sched_b.run_until_settled()
    assert _bound(store_a) == _bound(store_b)
    assert sched_a.metrics["scheduled"] == 20


def test_explicit_sampling_spreads_on_large_cluster():
    """An EXPLICIT percentageOfNodesToScore gets the exact rotating-window
    emulation (the adaptive default now runs full-batch evaluation — the
    SURVEY §2.7 P2 divergence): at 150 nodes / 66% the window restricts to
    K=100 and the batch path must still place everything, with the comparer
    confirming validity."""
    store = ClusterStore()
    sched = TPUScheduler(store, batch_size=16, comparer_every_n=4,
                         percentage_of_nodes_to_score=66)
    for i in range(150):
        store.create_node(
            make_node(f"n{i}").capacity({"cpu": "8", "memory": "16Gi", "pods": 20}).obj())
    for i in range(60):
        store.create_pod(make_pod(f"p{i}").req({"cpu": "1", "memory": "1Gi"}).obj())
    sched.run_until_settled()
    assert sched.metrics["scheduled"] == 60
    assert sched.comparer_mismatches == 0
    assert sched._start_carry is not None  # the sampling path actually ran


def test_pipeline_equivalence_with_heterogeneous_batches():
    """Mixed spread + affinity + plain pods across several batches: pipelined
    and synchronous runs must produce identical placements."""
    def build(store):
        for i in range(12):
            store.create_node(
                make_node(f"n{i}").capacity({"cpu": "8", "memory": "16Gi", "pods": 20})
                .label("zone", f"z{i % 3}").label("disk", "ssd" if i % 2 else "hdd").obj())
        sel = LabelSelector(match_labels={"app": "web"})
        for i in range(18):
            pw = make_pod(f"p{i}").req({"cpu": "1", "memory": "1Gi"})
            if i % 3 == 0:
                pw.label("app", "web").spread_constraint(1, "zone", selector=sel)
            if i % 4 == 0:
                pw.node_affinity_in("disk", ["ssd"])
            store.create_pod(pw.obj())

    store_p, sched_p = _run(True, build)
    store_s, sched_s = _run(False, build)
    assert sched_p.pipelined_batches > 0
    assert _bound(store_p) == _bound(store_s)
    assert sched_p.metrics["scheduled"] == sched_s.metrics["scheduled"]
    assert sched_p.comparer_mismatches == 0


def test_adaptive_default_samples_on_cpu(monkeypatch):
    """Platform-aware adaptive default (VERDICT r4): on CPU the default
    config (percentageOfNodesToScore=0) keeps the reference's adaptive
    sampling — at 150 nodes the window is 48% ≈ 72→100 floor — while
    KTPU_FULL_BATCH=1 restores the accelerator full-batch behavior."""
    def run(full_batch_flag):
        monkeypatch.setenv("KTPU_FULL_BATCH", full_batch_flag)
        store = ClusterStore()
        sched = TPUScheduler(store, batch_size=16)
        for i in range(150):
            store.create_node(
                make_node(f"n{i}").capacity({"cpu": "8", "memory": "16Gi", "pods": 20}).obj())
        for i in range(30):
            store.create_pod(make_pod(f"p{i}").req({"cpu": "1", "memory": "1Gi"}).obj())
        sched.run_until_settled()
        assert sched.metrics["scheduled"] == 30
        return sched

    sampled = run("0")   # reference adaptive sampling path
    assert sampled._start_carry is not None, "sampling path did not run"
    full = run("1")      # accelerator-style full batch
    assert full._start_carry is None, "full-batch path unexpectedly sampled"


def test_batch_sizer_deadline_bounds_batches():
    """The deadline-based sizer (ON by default, KTPU_BATCH_DEADLINE_MS=500)
    shrinks the target batch when observed cycles are slow, and never below
    min_batch."""
    from kubernetes_tpu.backend.tpu_scheduler import BatchSizer

    sizer = BatchSizer(max_batch=512, deadline_s=0.5)
    for _ in range(20):
        sizer.update(512, 2.0)  # 2s cycles: way over deadline
    assert sizer.min_batch <= sizer.target() < 512
    fast = BatchSizer(max_batch=512, deadline_s=0.5)
    for _ in range(20):
        fast.update(512, 0.02)  # fast cycles: deadline never binds
    assert fast.target() == 512
