"""Device-side HA fabric, unit layer (backend/fabric.py): selection
policy, failover verdicts, health passthrough, rejoin probing, metrics,
and the WireScheduler construction seam. Transport is stubbed — the
over-the-socket story lives in tests/test_chaos.py::TestDeviceFabricChaos."""

import pytest

from kubernetes_tpu.backend import telemetry
from kubernetes_tpu.backend.errors import (
    ConflictError,
    FailoverError,
    PermanentDeviceError,
    StaleEpochError,
    TransientDeviceError,
)
from kubernetes_tpu.backend.fabric import DeviceFabric
from kubernetes_tpu.metrics.scheduler_metrics import SchedulerMetrics
from kubernetes_tpu.utils.clock import FakeClock


class _StubClient:
    """Scripted transport client: raises ``fail`` on batch-path verbs and
    ``fail_health`` on Health — per-endpoint, mutable mid-test."""

    supports_dra = True
    supports_health = True
    supports_sessions = True

    def __init__(self, endpoint):
        self.endpoint = endpoint
        self.epoch = f"epoch-{endpoint}"
        self.calls = []
        self.fail = None         # exception for apply_deltas/schedule_batch
        self.fail_health = None  # exception for health

    def _out(self, **extra):
        out = {"apiVersion": "ktpu/v1", "epoch": self.epoch, "deltaSeq": 1}
        out.update(extra)
        return out

    def apply_deltas(self, payload):
        self.calls.append("apply_deltas")
        if self.fail is not None:
            raise self.fail
        return self._out(nodes=len(payload.get("nodes", ())))

    def schedule_batch(self, payload):
        self.calls.append("schedule_batch")
        if self.fail is not None:
            raise self.fail
        return self._out(results=[])

    def heartbeat(self, payload):
        self.calls.append("heartbeat")
        if self.fail is not None:
            raise self.fail
        return self._out(fenced=[])

    def health(self):
        self.calls.append("health")
        if self.fail_health is not None:
            raise self.fail_health
        return self._out(status="serving")

    def sessions_dump(self):
        self.calls.append("sessions")
        if self.fail is not None:
            raise self.fail
        return self._out(sessions=[])


def _fabric(n=3, metrics=None, clock=None, probe_interval_s=5.0):
    clock = clock or FakeClock()
    clients = {}

    def factory(ep, i):
        clients[ep] = _StubClient(ep)
        return clients[ep]

    fab = DeviceFabric([f"ep{i}" for i in range(n)], factory,
                       metrics=metrics, now_fn=clock,
                       probe_interval_s=probe_interval_s)
    return fab, clients, clock


class TestSelection:
    def test_routes_to_first_endpoint_and_mirrors_capabilities(self):
        fab, clients, _ = _fabric()
        out = fab.schedule_batch({"pods": [], "batchId": "b-1"})
        assert out["epoch"] == "epoch-ep0"
        assert clients["ep0"].calls == ["schedule_batch"]
        assert clients["ep1"].calls == []
        assert fab.supports_dra and fab.supports_health
        assert fab.supports_sessions
        assert fab.active_endpoint() == "ep0"

    def test_needs_at_least_one_endpoint(self):
        with pytest.raises(ValueError):
            DeviceFabric([], lambda ep, i: _StubClient(ep))

    def test_protocol_verdicts_pass_through_without_failover(self):
        """StaleEpoch/Conflict come from a HEALTHY service — the client's
        own recovery paths handle them; the fabric must not demote."""
        fab, clients, _ = _fabric()
        clients["ep0"].fail = StaleEpochError("fresh-epoch")
        with pytest.raises(StaleEpochError):
            fab.apply_deltas({"nodes": []})
        clients["ep0"].fail = ConflictError("raced")
        with pytest.raises(ConflictError):
            fab.schedule_batch({"pods": [], "batchId": "b-2"})
        assert fab.active_endpoint() == "ep0"
        assert fab.failovers == 0
        assert fab.replicas[0].healthy


class TestFailover:
    def test_primary_loss_promotes_first_live_standby(self):
        m = SchedulerMetrics()
        fab, clients, _ = _fabric(metrics=m)
        clients["ep0"].fail = TransientDeviceError("connection reset")
        with pytest.raises(FailoverError) as ei:
            fab.schedule_batch({"pods": [{}], "batchId": "b-7"})
        assert ei.value.from_endpoint == "ep0"
        assert ei.value.to_endpoint == "ep1"
        assert fab.active_endpoint() == "ep1"
        assert fab.failovers == 1
        assert not fab.replicas[0].healthy and fab.replicas[1].healthy
        # the standby was verified live with the cheap Health verb, not a
        # blind adoption
        assert clients["ep1"].calls == ["health"]
        assert m.fabric_active_replica.labels() == 1
        assert m.fabric_failovers.labels("transient") == 1
        assert m.fabric_replica_health.labels("ep0") == 0
        assert m.fabric_replica_health.labels("ep1") == 1
        # FailoverError is transient by taxonomy: the scheduler requeues
        # the batch and counts it against ITS breaker, never retries it
        assert isinstance(ei.value, TransientDeviceError)

    def test_dead_standby_skipped_for_the_next_one(self):
        fab, clients, _ = _fabric(n=3)
        clients["ep0"].fail = TransientDeviceError("down")
        clients["ep1"].fail_health = TransientDeviceError("also down")
        with pytest.raises(FailoverError) as ei:
            fab.apply_deltas({"nodes": []})
        assert ei.value.to_endpoint == "ep2"
        assert fab.active_endpoint() == "ep2"
        assert not fab.replicas[1].healthy

    def test_all_replicas_down_propagates_original_error(self):
        """No standby answers: the ORIGINAL transport error reaches the
        scheduler so its breaker walks the last rung of the ladder
        (oracle degrade) with the true failure visible."""
        fab, clients, _ = _fabric(n=2)
        exc = TransientDeviceError("primary gone")
        clients["ep0"].fail = exc
        clients["ep1"].fail_health = TransientDeviceError("standby gone")
        with pytest.raises(TransientDeviceError) as ei:
            fab.schedule_batch({"pods": [], "batchId": "b-1"})
        assert ei.value is exc
        assert fab.failovers == 0
        assert fab.active_endpoint() == "ep0"  # nowhere better to point

    def test_permanent_error_fails_over_with_reason_label(self):
        m = SchedulerMetrics()
        fab, clients, _ = _fabric(metrics=m)
        clients["ep0"].fail = PermanentDeviceError("version skew: 400")
        with pytest.raises(FailoverError):
            fab.apply_deltas({"nodes": []})
        assert m.fabric_failovers.labels("permanent") == 1

    def test_health_fails_over_transparently(self):
        """The scheduler's half-open probe calls health(): with the
        primary dead but a standby live, the probe must SUCCEED (answer
        from the standby) — the batch proceeds and the epoch protocol
        re-seeds on the next push."""
        fab, clients, _ = _fabric()
        clients["ep0"].fail = TransientDeviceError("dead")
        clients["ep0"].fail_health = TransientDeviceError("dead")
        out = fab.health()
        assert out["epoch"] == "epoch-ep1"
        assert fab.active_endpoint() == "ep1"
        assert fab.failovers == 1

    def test_poison_then_failover_event_order(self):
        """The in-flight batch's poison event lands strictly before the
        failover event — the postmortem reads 'batch died, THEN the
        fabric moved on' (ISSUE 10 acceptance, unit half)."""
        tele = telemetry.enable()
        try:
            fab, clients, _ = _fabric()
            clients["ep0"].fail = TransientDeviceError("mid-batch death")
            with pytest.raises(FailoverError):
                fab.schedule_batch({"pods": [{}, {}], "batchId": "b-9"})
            poisons = tele.flight.events("poison", batch_id="b-9")
            failovers = tele.flight.events("failover")
            downs = tele.flight.events("replica_down")
            assert len(poisons) == 1 and poisons[0]["pods"] == 2
            assert len(failovers) == 1
            assert failovers[0]["batchId"] == "b-9"
            assert failovers[0]["fromEndpoint"] == "ep0"
            assert failovers[0]["endpoint"] == "ep1"
            assert downs[0]["seq"] < poisons[0]["seq"] < failovers[0]["seq"]
        finally:
            telemetry.disable()

    def test_delta_failure_poisons_nothing(self):
        tele = telemetry.enable()
        try:
            fab, clients, _ = _fabric()
            clients["ep0"].fail = TransientDeviceError("down")
            with pytest.raises(FailoverError):
                fab.apply_deltas({"nodes": []})
            assert tele.flight.events("poison") == []
            assert len(tele.flight.events("failover")) == 1
        finally:
            telemetry.disable()


class TestRejoin:
    def _failed_over(self, m=None):
        clock = FakeClock()
        fab, clients, _ = _fabric(n=2, metrics=m, clock=clock)
        fab.apply_deltas({"nodes": []})  # learn ep0's epoch while healthy
        clients["ep0"].fail = TransientDeviceError("down")
        clients["ep0"].fail_health = TransientDeviceError("down")
        with pytest.raises(FailoverError):
            fab.apply_deltas({"nodes": []})
        return fab, clients, clock

    def test_rejoined_primary_becomes_standby_never_active(self):
        """Sticky selection: the probed-up ex-primary is healthy again
        but the fabric keeps routing to the promoted standby — adoption
        only ever happens through a failover (whose resync re-seeds the
        stale mirror via the epoch check)."""
        m = SchedulerMetrics()
        fab, clients, clock = self._failed_over(m)
        clients["ep0"].fail = None
        clients["ep0"].fail_health = None
        clock.advance(6.0)  # past probe_interval AND the replica breaker
        tele = telemetry.enable()
        try:
            fab.schedule_batch({"pods": [], "batchId": "b-2"})
            rejoins = tele.flight.events("replica_rejoin")
            assert [e["endpoint"] for e in rejoins] == ["ep0"]
            assert rejoins[0]["restarted"] is False  # same epoch answered
        finally:
            telemetry.disable()
        assert fab.replicas[0].healthy
        assert fab.active_endpoint() == "ep1"  # sticky
        assert m.fabric_replica_health.labels("ep0") == 1

    def test_restarted_primary_flagged_on_rejoin(self):
        fab, clients, clock = self._failed_over()
        clients["ep0"].fail = None
        clients["ep0"].fail_health = None
        clients["ep0"].epoch = "epoch-ep0-RESTARTED"
        clock.advance(6.0)
        tele = telemetry.enable()
        try:
            fab.schedule_batch({"pods": [], "batchId": "b-3"})
            rejoins = tele.flight.events("replica_rejoin")
            assert rejoins and rejoins[0]["restarted"] is True
        finally:
            telemetry.disable()

    def test_probe_is_rate_limited(self):
        fab, clients, clock = self._failed_over()
        clients["ep0"].fail_health = None
        probes_before = clients["ep0"].calls.count("health")
        fab.schedule_batch({"pods": [], "batchId": "b-4"})  # interval not up
        assert clients["ep0"].calls.count("health") == probes_before
        clock.advance(6.0)
        fab.schedule_batch({"pods": [], "batchId": "b-5"})
        assert clients["ep0"].calls.count("health") == probes_before + 1
        # and not again until the next window
        fab.schedule_batch({"pods": [], "batchId": "b-6"})
        assert clients["ep0"].calls.count("health") == probes_before + 1

    def test_failback_probes_the_rejoined_primary(self):
        """Standby dies after the ex-primary rejoined: the fabric fails
        BACK — verifying with Health first — so the scheduler's next push
        hits the old epoch mismatch and re-seeds it."""
        fab, clients, clock = self._failed_over()
        clients["ep0"].fail = None
        clients["ep0"].fail_health = None
        clock.advance(6.0)
        fab.schedule_batch({"pods": [], "batchId": "b-7"})  # rejoin probe
        clients["ep1"].fail = TransientDeviceError("standby dies")
        with pytest.raises(FailoverError) as ei:
            fab.schedule_batch({"pods": [], "batchId": "b-8"})
        assert ei.value.to_endpoint == "ep0"
        assert fab.active_endpoint() == "ep0"
        assert fab.failovers == 2


class TestProbeClient:
    def test_probes_ride_the_dedicated_probe_client(self):
        """Promotion and rejoin probes use the single-attempt probe
        client, never the main (retry-budgeted) transport client — a
        blackholed standby costs one connect timeout per window on the
        scheduling thread, not retries × timeout + backoff sleeps."""
        clock = FakeClock()
        mains, probes = {}, {}

        def factory(ep, i):
            mains[ep] = _StubClient(ep)
            return mains[ep]

        def pfactory(ep, i):
            probes[ep] = _StubClient(ep)
            return probes[ep]

        fab = DeviceFabric(["ep0", "ep1"], factory,
                           probe_client_factory=pfactory, now_fn=clock)
        mains["ep0"].fail = TransientDeviceError("down")
        with pytest.raises(FailoverError):
            fab.apply_deltas({"nodes": []})
        assert probes["ep1"].calls == ["health"]   # promotion probe
        assert mains["ep1"].calls == []
        clock.advance(6.0)
        fab.schedule_batch({"pods": [], "batchId": "b-1"})
        assert probes["ep0"].calls == ["health"]   # rejoin probe
        assert "health" not in mains["ep0"].calls

    def test_wire_scheduler_probe_clients_have_no_retry_budget(self):
        from kubernetes_tpu.api.wrappers import make_node
        from kubernetes_tpu.apiserver.store import ClusterStore
        from kubernetes_tpu.backend.service import WireScheduler

        store = ClusterStore()
        store.create_node(make_node("n0").capacity(
            {"cpu": "4", "memory": "8Gi", "pods": 10}).obj())
        sched = WireScheduler(
            store, endpoint=["http://127.0.0.1:9", "http://127.0.0.1:10"])
        for rep in sched.client.replicas:
            assert rep.probe is not rep.client
            assert rep.probe.retry.max_retries == 0
            assert rep.client.retry.max_retries == 3


class TestSessionsDumpIntrospection:
    def test_sessions_dump_never_runs_failover_machinery(self):
        """sessions_dump is reachable from the /debug SERVING thread
        (WireScheduler.debug_sessions): it must be a pure read of the
        active replica — a transport error surfaces to the caller, never
        a demotion, promotion probe, or failover counter bump from a
        nominally read-only endpoint."""
        fab, clients, _ = _fabric()
        clients["ep0"].fail = TransientDeviceError("down")
        with pytest.raises(TransientDeviceError):
            fab.sessions_dump()
        assert fab.failovers == 0
        assert fab.active_endpoint() == "ep0"
        assert fab.replicas[0].healthy            # no demotion
        assert clients["ep1"].calls == []         # no probes fired


class TestDump:
    def test_dump_shape(self):
        fab, clients, _ = _fabric(n=2)
        clients["ep0"].fail = TransientDeviceError("down")
        with pytest.raises(FailoverError):
            fab.apply_deltas({"nodes": []})
        out = fab.dump()
        assert out["enabled"] is True
        assert out["active"] == "ep1" and out["activeIndex"] == 1
        assert out["failovers"] == 1 and out["replicaCount"] == 2
        assert [r["endpoint"] for r in out["replicas"]] == ["ep0", "ep1"]
        assert out["replicas"][0]["healthy"] is False
        assert out["replicas"][1]["active"] is True
        assert "TransientDeviceError" in out["replicas"][0]["lastError"]
        assert out["log"] and out["log"][0]["from"] == "ep0"
        assert out["replicas"][0]["breaker"]["state"] == "open"


class TestWireSchedulerSeam:
    def _store(self):
        from kubernetes_tpu.api.wrappers import make_node
        from kubernetes_tpu.apiserver.store import ClusterStore

        store = ClusterStore()
        store.create_node(make_node("n0").capacity(
            {"cpu": "4", "memory": "8Gi", "pods": 10}).obj())
        return store

    def test_single_endpoint_keeps_the_plain_client(self):
        from kubernetes_tpu.backend.service import WireClient, WireScheduler

        sched = WireScheduler(self._store(), endpoint="http://127.0.0.1:9")
        assert isinstance(sched.client, WireClient)
        assert sched.debug_fabric() == {"enabled": False,
                                       "endpoint": "http://127.0.0.1:9"}

    def test_endpoint_list_and_comma_string_build_the_fabric(self):
        from kubernetes_tpu.backend.service import WireScheduler

        sched = WireScheduler(
            self._store(),
            endpoint="http://127.0.0.1:9, http://127.0.0.1:10")
        assert isinstance(sched.client, DeviceFabric)
        assert [r.endpoint for r in sched.client.replicas] == [
            "http://127.0.0.1:9", "http://127.0.0.1:10"]
        assert sched.debug_fabric()["enabled"] is True
        sched2 = WireScheduler(
            self._store(),
            endpoint=["http://127.0.0.1:9", "http://127.0.0.1:10"])
        assert isinstance(sched2.client, DeviceFabric)

    def test_fault_plan_list_must_match_endpoints(self):
        from kubernetes_tpu.backend.service import WireScheduler
        from kubernetes_tpu.testing.faults import FaultPlan

        with pytest.raises(ValueError, match="fault_plan"):
            WireScheduler(
                self._store(),
                endpoint=["http://127.0.0.1:9", "http://127.0.0.1:10"],
                fault_plan=[FaultPlan()])

    def test_empty_endpoint_rejected(self):
        from kubernetes_tpu.backend.service import WireScheduler

        with pytest.raises(ValueError, match="endpoint"):
            WireScheduler(self._store(), endpoint=" , ")


class _RecordingStub(_StubClient):
    """_StubClient that also keeps the payloads (the replication tests
    assert WHAT was pushed, not just that something was)."""

    def __init__(self, endpoint):
        super().__init__(endpoint)
        self.payloads = []

    def apply_deltas(self, payload):
        self.payloads.append(("apply_deltas", payload))
        return super().apply_deltas(payload)

    def heartbeat(self, payload):
        self.payloads.append(("heartbeat", payload))
        return super().heartbeat(payload)


def _entry(name, gen=1):
    return {"gen": gen, "node": {"meta": {"name": name}}, "pods": []}


def _repl_fabric(n=2, metrics=None, probe_interval_s=5.0):
    clock = FakeClock()
    clients = {}

    def factory(ep, i):
        clients.setdefault(ep, _RecordingStub(ep))
        return clients[ep]

    # replication_worker=False: these tests drive replication_flush()
    # themselves — a background worker consuming the dirty set would make
    # the asserted push counts/payloads racy
    fab = DeviceFabric([f"ep{i}" for i in range(n)], factory,
                       metrics=metrics, now_fn=clock,
                       probe_interval_s=probe_interval_s,
                       replication=True, replication_worker=False)
    return fab, clients, clock


class TestStandbyReplication:
    """Warm-standby delta fan-out, unit layer: fold/coalesce semantics,
    full seeds vs dirty suffixes, the replicator session flag, keep-warm
    heartbeats, failure backoff, and lag accounting — all driven through
    replication_flush() (no background-thread timing in assertions)."""

    def _deltas(self, fab, entries, removed=(), full=False, client="sched-A"):
        payload = {"nodes": entries, "removed": list(removed),
                   "clientId": client}
        if full:
            payload["full"] = True
        return fab.apply_deltas(payload)

    def test_first_flush_seeds_standby_with_full_push(self):
        fab, clients, _ = _repl_fabric()
        self._deltas(fab, [_entry("n0"), _entry("n1")])
        assert fab.replication_flush() == 1
        op, payload = clients["ep1"].payloads[0]
        assert op == "apply_deltas"
        assert payload["full"] is True
        assert payload["replicator"] is True
        assert payload["clientId"].startswith("fabric-repl-")
        assert {e["node"]["meta"]["name"] for e in payload["nodes"]} == \
            {"n0", "n1"}
        assert fab.replicas[1].repl_needs_full is False
        assert fab.replicas[1].repl_synced_seq == fab._repl_seq

    def test_dirty_suffix_coalesces_per_node(self):
        """A node that changed N times while the standby lagged ships
        ONCE, with its newest content — replication cost is O(dirty
        nodes), not O(delta stream)."""
        fab, clients, _ = _repl_fabric()
        self._deltas(fab, [_entry("n0"), _entry("n1")])
        fab.replication_flush()                       # seed
        for gen in (2, 3, 4):
            self._deltas(fab, [_entry("n0", gen=gen)])
        assert fab.replication_flush() == 1
        _, payload = clients["ep1"].payloads[-1]
        assert "full" not in payload
        assert [e["node"]["meta"]["name"] for e in payload["nodes"]] == ["n0"]
        assert payload["nodes"][0]["gen"] == 4        # newest content only
        assert fab.replication_flush() == 0           # nothing left pending

    def test_removals_propagate_incrementally_and_from_full_folds(self):
        fab, clients, _ = _repl_fabric()
        self._deltas(fab, [_entry("n0"), _entry("n1"), _entry("n2")])
        fab.replication_flush()
        # incremental removal
        self._deltas(fab, [], removed=["n2"])
        fab.replication_flush()
        _, payload = clients["ep1"].payloads[-1]
        assert payload["removed"] == ["n2"]
        # a full client push omitting n1 IS its removal (ghost-sweep twin)
        self._deltas(fab, [_entry("n0", gen=5)], full=True)
        fab.replication_flush()
        _, payload = clients["ep1"].payloads[-1]
        assert payload["removed"] == ["n1"]
        assert "n1" not in fab._repl_nodes and "n2" not in fab._repl_nodes

    def test_replication_skips_the_active_and_backs_off_failures(self):
        fab, clients, clock = _repl_fabric(n=3)
        self._deltas(fab, [_entry("n0")])
        clients["ep2"].fail = TransientDeviceError("standby down")
        assert fab.replication_flush() == 1           # ep1 only
        # the active receives the CLIENT's pushes, never the replicator's
        assert all(p["clientId"] == "sched-A"
                   for _op, p in clients["ep0"].payloads)
        assert fab.replicas[2].repl_needs_full is True
        assert fab.replicas[2].repl_last_error.startswith("TransientDeviceError")
        # backoff: no retry inside the probe window, retry after it
        clients["ep2"].fail = None
        assert fab.replication_flush() == 0
        clock.advance(6.0)
        assert fab.replication_flush() == 1
        assert fab.replicas[2].repl_needs_full is False

    def test_stale_epoch_reseeds_conflict_rejoins(self):
        fab, clients, clock = _repl_fabric()
        self._deltas(fab, [_entry("n0")])
        fab.replication_flush()
        assert fab.replicas[1].repl_needs_full is False
        # the standby restarted: next push must be a fresh full seed
        clients["ep1"].fail = StaleEpochError("fresh-epoch")
        self._deltas(fab, [_entry("n0", gen=2)])
        fab.replication_flush()
        assert fab.replicas[1].repl_needs_full is True
        assert fab.replicas[1].repl_session_gen is None
        clients["ep1"].fail = None
        fab.replication_flush()
        _, payload = clients["ep1"].payloads[-1]
        assert payload["full"] is True
        # a fenced replicator session rejoins without a gen
        clients["ep1"].fail = ConflictError("lease fenced")
        self._deltas(fab, [_entry("n0", gen=3)])
        fab.replication_flush()
        assert fab.replicas[1].repl_session_gen is None
        clients["ep1"].fail = None
        fab.replication_flush()
        _, payload = clients["ep1"].payloads[-1]
        assert "sessionGen" not in payload

    def test_keep_warm_heartbeats_cover_replicator_and_client_sessions(self):
        fab, clients, clock = _repl_fabric()
        self._deltas(fab, [_entry("n0")])
        fab.heartbeat({"clientId": "sched-A"})       # records the client id
        clock.advance(6.0)
        fab.replication_flush()
        beats = [p for op, p in clients["ep1"].payloads if op == "heartbeat"]
        cids = {p["clientId"] for p in beats}
        assert "sched-A" in cids                      # client session warmed
        assert any(c.startswith("fabric-repl-") for c in cids)
        # the client fan-out never stamps a sessionGen (the standby owns
        # its generation) and never claims to be the replicator
        sched_beat = [p for p in beats if p["clientId"] == "sched-A"][0]
        assert "sessionGen" not in sched_beat
        assert "replicator" not in sched_beat

    def test_lag_accounting_and_metrics(self):
        m = SchedulerMetrics()
        fab, clients, clock = _repl_fabric(metrics=m)
        clients["ep1"].fail = TransientDeviceError("lagging")
        for gen in (1, 2, 3):
            self._deltas(fab, [_entry("n0", gen=gen)])
        fab.replication_flush()
        assert fab.replication_lag(fab.replicas[1]) == 3
        assert m.standby_replication_lag.labels("ep1") == 3
        clients["ep1"].fail = None
        clock.advance(6.0)
        fab.replication_flush()
        assert fab.replication_lag(fab.replicas[1]) == 0
        assert m.standby_replication_lag.labels("ep1") == 0
        assert m.standby_resync_bytes.labels("full") > 0
        dump = fab.dump()
        assert dump["replication"]["enabled"] is True
        assert dump["replicas"][1]["replication"]["lag"] == 0

    def test_rejoining_replica_is_reseeded_wholesale(self):
        """down -> up marks needs_full: the mirror went arbitrarily stale
        while the replica was away."""
        fab, clients, clock = _repl_fabric()
        self._deltas(fab, [_entry("n0")])
        fab.replication_flush()
        assert fab.replicas[1].repl_needs_full is False
        # the standby drops off (call-driven detection marks it down),
        # then answers the rate-limited rejoin probe
        fab._mark_health(fab.replicas[1], False)
        self._deltas(fab, [_entry("n0", gen=2)])
        assert fab.replication_flush() == 0           # down: not a target
        clock.advance(6.0)
        self._deltas(fab, [_entry("n0", gen=3)])      # probe window passes
        assert fab.replicas[1].healthy
        assert fab.replicas[1].repl_needs_full is True
        fab.replication_flush()
        payload = [p for op, p in clients["ep1"].payloads
                   if op == "apply_deltas"][-1]
        assert payload["full"] is True
