"""Preemption engine tests (framework/preemption.py + DefaultPreemption).

Scenarios transcribed from the reference's defaultpreemption/default_preemption_test.go
and preemption.go semantics: victim selection + reprieve, PDB-violation
minimization, 5-criteria node pick, Never-policy, unresolvable-node skip, and
the end-to-end preempt → delete victims → reschedule flow.
"""

import pytest

from kubernetes_tpu.api.types import LabelSelector, PodDisruptionBudget
from kubernetes_tpu.api.wrappers import make_node, make_pod
from kubernetes_tpu.apiserver.store import ClusterStore
from kubernetes_tpu.framework import interface as fw
from kubernetes_tpu.framework.preemption import Evaluator, more_important
from kubernetes_tpu.framework.runtime import Framework
from kubernetes_tpu.framework.interface import CycleState, Status
from kubernetes_tpu.scheduler.scheduler import Scheduler


def mk_cluster(store, n_nodes=2, cpu="2"):
    for i in range(n_nodes):
        store.create_node(make_node(f"node-{i}").capacity({"cpu": cpu, "memory": "8Gi", "pods": 10}).obj())


def sched(store, **kw):
    return Scheduler(store, **kw)


def test_basic_preemption_end_to_end():
    store = ClusterStore()
    mk_cluster(store, n_nodes=2, cpu="2")
    s = sched(store)
    # fill both nodes with low-priority pods
    for i in range(2):
        store.create_pod(make_pod(f"low-{i}").req({"cpu": "1800m"}).priority(1).obj())
    s.run_until_settled()
    assert sum(1 for p in store.pods.values() if p.spec.node_name) == 2

    # high-priority pod needs a full node worth of cpu
    store.create_pod(make_pod("high").req({"cpu": "1500m"}).priority(100).obj())
    s.schedule_one()  # fails, triggers preemption
    high = store.get_pod("default/high")
    assert high.status.nominated_node_name != ""
    # exactly one victim deleted
    lows = [p for p in store.pods.values() if p.meta.name.startswith("low-")]
    assert len(lows) == 1
    # victim deletion reactivated the preemptor; it now schedules
    s.run_until_settled()
    high = store.get_pod("default/high")
    assert high.spec.node_name == high.status.nominated_node_name


def test_preemption_never_policy():
    store = ClusterStore()
    mk_cluster(store, n_nodes=1, cpu="2")
    s = sched(store)
    store.create_pod(make_pod("low").req({"cpu": "1800m"}).priority(1).obj())
    s.run_until_settled()
    p = make_pod("high").req({"cpu": "1500m"}).priority(100).obj()
    p.spec.preemption_policy = "Never"
    store.create_pod(p)
    s.schedule_one()
    assert store.get_pod("default/high").status.nominated_node_name == ""
    assert "default/low" in store.pods


def test_no_preemption_of_equal_or_higher_priority():
    store = ClusterStore()
    mk_cluster(store, n_nodes=1, cpu="2")
    s = sched(store)
    store.create_pod(make_pod("peer").req({"cpu": "1800m"}).priority(100).obj())
    s.run_until_settled()
    store.create_pod(make_pod("high").req({"cpu": "1500m"}).priority(100).obj())
    s.schedule_one()
    assert store.get_pod("default/high").status.nominated_node_name == ""
    assert "default/peer" in store.pods


def test_victim_reprieve_minimizes_victims():
    """Node has 3 low pods of 600m each; preemptor needs 700m of 2000m.
    Removing ALL low pods then re-adding highest-priority-first should
    reprieve two of them — exactly one victim."""
    store = ClusterStore()
    mk_cluster(store, n_nodes=1, cpu="2")
    s = sched(store)
    for i, prio in enumerate([3, 2, 1]):
        store.create_pod(make_pod(f"low-{i}").req({"cpu": "600m"}).priority(prio).obj())
    s.run_until_settled()
    store.create_pod(make_pod("high").req({"cpu": "700m"}).priority(100).obj())
    s.schedule_one()
    lows = sorted(p.meta.name for p in store.pods.values() if p.meta.name.startswith("low-"))
    # lowest-priority pod (low-2, prio 1) is the victim
    assert lows == ["low-0", "low-1"]


def test_pdb_violation_minimized():
    """Two identical nodes; victims on node-0 are PDB-protected. The picker
    must choose node-1 (fewer PDB violations, preemption.go:397 criterion 1)."""
    store = ClusterStore()
    mk_cluster(store, n_nodes=2, cpu="2")
    s = sched(store)
    p0 = make_pod("a").req({"cpu": "1800m"}).priority(1).label("app", "guarded").obj()
    p0.spec.node_name = ""
    store.create_pod(p0)
    s.run_until_settled()
    p1 = make_pod("b").req({"cpu": "1800m"}).priority(1).obj()
    store.create_pod(p1)
    s.run_until_settled()
    store.create_pdb(
        PodDisruptionBudget(
            selector=LabelSelector(match_labels={"app": "guarded"}),
            disruptions_allowed=0,
        )
    )
    store.create_pod(make_pod("high").req({"cpu": "1500m"}).priority(50).obj())
    s.schedule_one()
    # the non-guarded pod is the victim
    assert "default/a" in store.pods
    assert "default/b" not in store.pods


def test_pick_lowest_max_victim_priority():
    """Criterion 2: prefer the node whose highest victim priority is lowest."""
    store = ClusterStore()
    mk_cluster(store, n_nodes=2, cpu="2")
    s = sched(store)
    store.create_pod(make_pod("v-high").req({"cpu": "1800m"}).priority(10).obj())
    s.run_until_settled()
    store.create_pod(make_pod("v-low").req({"cpu": "1800m"}).priority(2).obj())
    s.run_until_settled()
    store.create_pod(make_pod("high").req({"cpu": "1500m"}).priority(50).obj())
    s.schedule_one()
    assert "default/v-high" in store.pods
    assert "default/v-low" not in store.pods


def test_unresolvable_nodes_skipped():
    evaluated = {}

    class SpyEvaluator(Evaluator):
        def select_victims_on_node(self, pod, ni, pdbs):
            evaluated[ni.node.meta.name] = True
            return super().select_victims_on_node(pod, ni, pdbs)

    store = ClusterStore()
    mk_cluster(store, n_nodes=2, cpu="2")
    s = sched(store)
    store.create_pod(make_pod("low").req({"cpu": "1800m"}).priority(1).obj())
    s.run_until_settled()
    s.cache.update_snapshot(s.snapshot)
    fwk = s.profiles["default-scheduler"]
    node_infos = s.snapshot.list()
    assigned_node = store.get_pod("default/low").spec.node_name
    other = next(n for n in ("node-0", "node-1") if n != assigned_node)
    status_map = {
        assigned_node: Status.unschedulable("too much cpu"),
        other: Status.unresolvable("node had untolerated taint"),
    }
    pod = make_pod("high").req({"cpu": "1500m"}).priority(100).obj()
    state = CycleState()
    fwk.run_pre_filter_plugins(state, pod)  # dry-run filters read this state
    ev = SpyEvaluator("DefaultPreemption", fwk, store.list_pdbs, state)
    name, status = ev.preempt(pod, status_map, node_infos)
    assert other not in evaluated
    assert name == assigned_node


def test_more_important_ordering():
    a = make_pod("a").priority(5).obj()
    b = make_pod("b").priority(3).obj()
    assert more_important(a, b)
    c = make_pod("c").priority(5).obj()
    a.status.start_time = 1.0
    c.status.start_time = 2.0
    assert more_important(a, c)


def test_nominated_node_cleared_for_lower_priority():
    """prepareCandidate (:331): lower-priority pods nominated on the chosen
    node lose their nomination."""
    store = ClusterStore()
    mk_cluster(store, n_nodes=1, cpu="4")
    s = sched(store)
    store.create_pod(make_pod("low").req({"cpu": "3500m"}).priority(1).obj())
    s.run_until_settled()
    # mid fails + preempts nothing helpful but gets nominated via its own preemption
    store.create_pod(make_pod("mid").req({"cpu": "3000m"}).priority(10).obj())
    s.schedule_one()
    mid = store.get_pod("default/mid")
    assert mid.status.nominated_node_name == "node-0"
    # now an even higher pod preempts on the same node: mid's nomination clears
    store.create_pod(make_pod("top").req({"cpu": "3000m"}).priority(100).obj())
    # drain queue: mid is in backoff; schedule attempts happen for both
    s.run_until_settled()
    top = store.get_pod("default/top")
    mid = store.get_pod("default/mid")
    assert top is not None
    # top either scheduled or nominated on node-0; mid must not hold both a
    # nomination and an assignment
    if top.spec.node_name != "node-0":
        assert top.status.nominated_node_name == "node-0"
        assert mid is None or mid.status.nominated_node_name == "" or mid.spec.node_name


class TestPrescreen:
    """The max-free candidate pre-screen must never change outcomes, only
    skip provably hopeless nodes."""

    def test_hopeless_nodes_skipped_same_result(self):
        from kubernetes_tpu.api.wrappers import make_node, make_pod
        from kubernetes_tpu.apiserver.store import ClusterStore
        from kubernetes_tpu.scheduler.scheduler import Scheduler

        store = ClusterStore()
        sched = Scheduler(store)
        # n-full: high-priority pods fill it (nothing reclaimable);
        # n-soft: low-priority pods fill it (preemptable)
        store.create_node(make_node("n-full").capacity({"cpu": "2", "memory": "4Gi", "pods": 5}).obj())
        store.create_node(make_node("n-soft").capacity({"cpu": "2", "memory": "4Gi", "pods": 5}).obj())
        for i in range(2):
            hi = make_pod(f"hi-{i}").req({"cpu": "900m"}).priority(1000).node("n-full").obj()
            store.create_pod(hi)
            store.pods[hi.key()].spec.node_name = "n-full"
            lo = make_pod(f"lo-{i}").req({"cpu": "900m"}).priority(1).obj()
            store.create_pod(lo)
            store.pods[lo.key()].spec.node_name = "n-soft"
        sched = Scheduler(store)  # rebuild: sees the fixed placements
        # preemptor at priority 500: can evict lo-* on n-soft but not hi-*
        store.create_pod(make_pod("preemptor").req({"cpu": "1800m"}).priority(500).obj())
        sched.run_until_settled()
        p = store.get_pod("default/preemptor")
        assert (p.status.nominated_node_name or p.spec.node_name) == "n-soft"
        # the evaluator provably skipped n-full (hi-priority only)
        # (prescreen counter lives on the per-attempt evaluator; assert via
        # outcome: victims were the lo pods)
        assert store.get_pod("default/lo-0") is None
        assert store.get_pod("default/hi-0") is not None

    def test_prescreen_counts_skips(self):
        from kubernetes_tpu.api.wrappers import make_node, make_pod
        from kubernetes_tpu.framework.preemption import Evaluator
        from kubernetes_tpu.framework.types import NodeInfo

        # node with tiny capacity entirely used by HIGHER-priority pods:
        # provably hopeless for the preemptor
        full = NodeInfo(make_node("full").capacity({"cpu": "1", "memory": "1Gi", "pods": 2}).obj())
        p_high = make_pod("hp").req({"cpu": "900m"}).priority(100).obj()
        p_high.spec.node_name = "full"
        full.add_pod(p_high)
        preemptor = make_pod("pre").req({"cpu": "800m"}).priority(50).obj()
        mask = Evaluator._max_free_prescreen(preemptor, [full])
        assert mask == [False]
        # same node but victim at LOWER priority: reclaimable
        soft = NodeInfo(make_node("soft").capacity({"cpu": "1", "memory": "1Gi", "pods": 2}).obj())
        p_low = make_pod("lp").req({"cpu": "900m"}).priority(1).obj()
        p_low.spec.node_name = "soft"
        soft.add_pod(p_low)
        mask = Evaluator._max_free_prescreen(preemptor, [soft])
        assert mask == [True]
