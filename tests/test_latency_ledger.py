"""Pod-lifetime latency ledger (metrics/latency_ledger.py): per-segment
e2e attribution across adversarial flows (backoff requeue, ring poison,
gang Permit park + whole-gang reject, wire conflict), the e2e == sum(
segments) invariant, churn-cannot-leak + cap eviction bounds, the
disabled-cost/placement-parity contract (the PR-2/PR-7 rule), the bounded
tenant SLO label set, and the unified /debug/timeline Chrome-trace export
— including the acceptance proof: a pod scheduled through the pipelined
wire path after one injected poison requeue."""

import json
import time
import urllib.request

import pytest

from kubernetes_tpu.api.wrappers import make_node, make_pod
from kubernetes_tpu.apiserver import ClusterStore
from kubernetes_tpu.metrics import latency_ledger
from kubernetes_tpu.metrics.latency_ledger import PodLatencyLedger, SEGMENTS
from kubernetes_tpu.metrics.scheduler_metrics import SchedulerMetrics
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.utils.clock import FakeClock


@pytest.fixture(autouse=True)
def _clean_ledger():
    latency_ledger.disable()
    yield
    latency_ledger.disable()


def _entry_invariant(entry, eps=1e-9):
    """The gap-free state machine's contract: e2e == sum(segments)."""
    assert entry is not None and entry["closed"] is not None
    e2e = entry["closed"] - entry["opened"]
    total = sum(entry["segments"].values())
    assert abs(e2e - total) <= eps, (e2e, total, entry["segments"])
    assert set(entry["segments"]) <= SEGMENTS
    return e2e


# ------------------------------------------------------------ unit mechanics


class TestLedgerMechanics:
    def test_transitions_accumulate_and_close_observes(self):
        clock = FakeClock()
        m = SchedulerMetrics()
        led = PodLatencyLedger(m, now_fn=clock,
                               tenant_fn=lambda ns: 2 if ns == "t" else None)
        led.transition("t/p", "queue.active", namespace="t")
        clock.advance(1.0)
        led.transition("t/p", "cycle.host")
        clock.advance(0.5)
        led.transition("t/p", "queue.backoff")  # requeue
        clock.advance(2.0)
        led.transition("t/p", "cycle.host")     # second attempt
        clock.advance(0.25)
        led.transition("t/p", "bind")
        clock.advance(0.125)
        led.close("t/p", "scheduled")
        e = led.entry("t/p")
        assert e["segments"] == {
            "queue.active": 1.0, "cycle.host": 0.75,
            "queue.backoff": 2.0, "bind": 0.125}
        assert _entry_invariant(e) == pytest.approx(3.875)
        assert m.pod_e2e_duration.count("scheduled") == 1
        assert m.pod_latency_segment.count("queue.backoff") == 1
        assert m.pod_latency_segment.sum("cycle.host") == 0.75
        # tenant namespace: the SLO histogram observed it
        assert m.tenant_e2e_duration.count("t") == 1
        assert len(led) == 0

    def test_tenant_label_set_is_bounded_to_quota_tenants(self):
        m = SchedulerMetrics()
        led = PodLatencyLedger(m, now_fn=FakeClock(),
                               tenant_fn=lambda ns: 1 if ns == "quota" else None)
        for ns in ("quota", "default", "anon-1", "anon-2", "anon-3"):
            led.transition(f"{ns}/p", "queue.active", namespace=ns)
            led.close(f"{ns}/p", "scheduled")
        # only the quota tenant appears — an unbounded namespace population
        # cannot explode the registry
        assert m.tenant_e2e_duration.label_sets() == [("quota",)]
        assert m.pod_e2e_duration.count("scheduled") == 5

    def test_deleted_close_skips_tenant_slo(self):
        m = SchedulerMetrics()
        led = PodLatencyLedger(m, now_fn=FakeClock(),
                               tenant_fn=lambda ns: 1)
        led.transition("t/p", "queue.active", namespace="t")
        led.drop("t/p")
        assert m.pod_e2e_duration.count("deleted") == 1
        assert m.tenant_e2e_duration.label_sets() == []

    def test_cap_evicts_oldest_with_counter(self):
        m = SchedulerMetrics()
        led = PodLatencyLedger(m, cap=4, now_fn=FakeClock())
        for i in range(10):
            led.transition(f"ns/p{i}", "queue.active", namespace="ns")
        assert len(led) == 4
        assert led.evicted == 6
        assert m.ledger_evicted.labels() == 6
        # the oldest are gone, the newest survive
        assert led.entry("ns/p0") is None
        assert led.entry("ns/p9") is not None

    def test_batch_transitions_share_one_clock_read(self):
        clock = FakeClock()
        led = PodLatencyLedger(now_fn=clock)
        led.transition_many(["a/1", "a/2", "a/3"], "queue.active",
                            create=True)
        clock.advance(1.0)
        led.transition_many(["a/1", "a/2", "a/3"], "device.inflight",
                            batch_id="b7")
        clock.advance(0.5)
        led.close_many(["a/1", "a/2", "a/3"], "scheduled")
        for k in ("a/1", "a/2", "a/3"):
            e = led.entry(k)
            assert e["batchId"] == "b7"
            assert e["segments"] == {"queue.active": 1.0,
                                     "device.inflight": 0.5}
            _entry_invariant(e)

    def test_post_queue_transitions_never_resurrect_dropped_entries(self):
        """A pod deleted mid-flight has its entry dropped; the batch's
        later claim/bind hooks (create=False) must NOT re-create it as a
        ghost with a bogus near-zero e2e — one pod, one close."""
        m = SchedulerMetrics()
        led = PodLatencyLedger(m, now_fn=FakeClock())
        led.transition("ns/p", "queue.active", namespace="ns")
        led.transition_many(["ns/p"], "device.inflight", batch_id="b1")
        led.drop("ns/p")  # user deletes the pod while the batch flies
        # the claim and bind-tail hooks arrive after the drop
        led.transition_many(["ns/p"], "commit.host")
        led.transition("ns/p", "bind", create=False)
        assert len(led) == 0
        led.close_many(["ns/p"], "scheduled")  # no-op on the absent key
        assert m.pod_e2e_duration.count("deleted") == 1
        assert m.pod_e2e_duration.count("scheduled") == 0

    def test_chrome_trace_structure(self):
        clock = FakeClock(1000.0)
        led = PodLatencyLedger(now_fn=clock)
        led.transition("ns/p", "queue.active", namespace="ns")
        clock.advance(1.0)
        led.transition("ns/p", "device.inflight", batch_id="b1")
        clock.advance(1.0)
        led.close("ns/p", "scheduled")
        doc = latency_ledger.chrome_trace(
            flight=[{"seq": 1, "t": 1001.0, "type": "dispatch",
                     "batchId": "b1"}],
            ledger=led)
        body = json.dumps(doc)  # must be JSON-serializable as-is
        doc = json.loads(body)
        evs = doc["traceEvents"]
        slices = [e for e in evs if e.get("cat") == "ledger"]
        assert {e["name"] for e in slices} == {"queue.active",
                                               "device.inflight"}
        for e in slices:
            assert e["ph"] == "X" and e["args"]["pod"] == "ns/p"
            assert e["ts"] >= 1000.0 * 1e6 and e["dur"] > 0
        (inst,) = [e for e in evs if e.get("cat") == "flight"]
        assert inst["ph"] == "i" and inst["args"]["batchId"] == "b1"
        # pod track named after the pod UID
        assert any(e["ph"] == "M" and e["name"] == "thread_name"
                   and e["args"]["name"] == "ns/p" for e in evs)


# -------------------------------------------------------- disabled contract


class TestDisabledContract:
    """The PR-2/PR-7 rule: one module-global read per hook when off."""

    def test_disabled_hooks_are_noops(self):
        assert latency_ledger.get() is None
        assert latency_ledger.transition("a/b", "queue.active") is None
        assert latency_ledger.transition_many(["a/b"], "bind") is None
        assert latency_ledger.close("a/b") is None
        assert latency_ledger.close_many(["a/b"]) is None
        assert latency_ledger.drop("a/b") is None

    def test_enable_disable_roundtrip(self):
        led = latency_ledger.enable()
        assert latency_ledger.get() is led
        latency_ledger.transition("a/b", "queue.active")
        assert len(led) == 1
        latency_ledger.disable()
        assert latency_ledger.get() is None
        latency_ledger.transition("a/c", "queue.active")  # no-op, no error
        assert len(led) == 1

    def test_maybe_enable_from_env_gate(self, monkeypatch):
        monkeypatch.delenv("KTPU_LEDGER", raising=False)
        latency_ledger.maybe_enable_from_env()
        assert latency_ledger.get() is None
        monkeypatch.setenv("KTPU_LEDGER", "1")
        latency_ledger.maybe_enable_from_env()
        assert latency_ledger.get() is not None

    def test_placement_parity_ledger_on_equals_off(self):
        """Enabling the ledger changes counters, never decisions."""

        def run(with_ledger):
            store = ClusterStore()
            sched = Scheduler(store, seed=3)
            if with_ledger:
                latency_ledger.enable(sched.smetrics)
            for i in range(6):
                store.create_node(make_node(f"n{i}").capacity(
                    {"cpu": str(4 + i), "memory": "16Gi", "pods": 20}).obj())
            for i in range(12):
                store.create_pod(make_pod(f"p{i}").req(
                    {"cpu": "500m", "memory": "1Gi"}).obj())
            sched.run_until_settled()
            latency_ledger.disable()
            return {k: p.spec.node_name for k, p in store.pods.items()}

        assert run(False) == run(True)


# -------------------------------------------------------- adversarial flows


class TestAdversarialFlows:
    def test_backoff_and_unschedulable_accumulate_across_attempts(self):
        """No-capacity park -> NODE_ADD wake -> bind: the entry carries
        queue.unschedulable dwell plus both attempts' cycle work, and the
        invariant holds on the FakeClock exactly."""
        clock = FakeClock()
        store = ClusterStore()
        sched = Scheduler(store, now_fn=clock)
        led = latency_ledger.enable(sched.smetrics, now_fn=clock)
        store.create_pod(make_pod("p0").req({"cpu": "1"}).obj())
        sched.run_until_settled()  # no nodes: parks unschedulable
        assert sched.queue.pending_pods()["unschedulable"] == 1
        clock.advance(3.0)
        store.create_node(make_node("n0").capacity(
            {"cpu": "8", "memory": "16Gi", "pods": 10}).obj())
        sched.run_until_settled()
        assert sched.metrics["scheduled"] == 1
        e = led.entry("default/p0")
        assert e["result"] == "scheduled"
        assert e["segments"]["queue.unschedulable"] >= 3.0
        # host segments exist even though the FakeClock reads 0 for them
        # (nothing advances it during host work)
        assert {"cycle.host", "bind"} <= set(e["segments"])
        _entry_invariant(e)
        assert len(led) == 0

    def test_ring_poison_requeue_accumulates_device_and_backoff(self):
        """In-process pipelined path: one scripted relay death poisons the
        ring; the pods' entries carry device.inflight + queue.backoff from
        the poisoned attempt AND the successful retry's segments."""
        from kubernetes_tpu.backend import TPUScheduler

        store = ClusterStore()
        sched = TPUScheduler(store, batch_size=8,
                             pod_initial_backoff=0.05, pod_max_backoff=0.1)
        led = latency_ledger.enable(sched.smetrics)
        for i in range(4):
            store.create_node(make_node(f"n{i}").capacity(
                {"cpu": "8", "memory": "16Gi", "pods": 20}).obj())
        for i in range(6):
            store.create_pod(make_pod(f"p{i}").req({"cpu": "500m"}).obj())
        fired = []

        def fault(_op):
            if not fired:
                fired.append(1)
                return RuntimeError("scripted poison")
            return None

        sched.relay_fault_fn = fault
        for _ in range(40):
            sched.run_until_settled()
            if sched.metrics["scheduled"] == 6:
                break
            time.sleep(0.06)
        assert sched.metrics["scheduled"] == 6
        assert fired  # the poison actually fired
        e = led.entry("default/p0")
        assert e["result"] == "scheduled"
        assert e["segments"]["device.inflight"] > 0
        assert e["segments"]["queue.backoff"] > 0
        assert e["segments"]["commit.host"] > 0
        _entry_invariant(e)
        assert len(led) == 0

    def test_gang_permit_park_and_whole_gang_reject(self):
        """A lone gang member parks at Permit (gang.permit_park), the
        timeout sweep rejects the WHOLE gang, and when the missing sibling
        arrives both members bind — their entries carrying the park."""
        from kubernetes_tpu.api.types import ObjectMeta, PodGroup

        clock = FakeClock()
        store = ClusterStore()
        sched = Scheduler(store, now_fn=clock)
        led = latency_ledger.enable(sched.smetrics, now_fn=clock)
        for i in range(4):
            store.create_node(make_node(f"n{i}").capacity(
                {"cpu": "8", "memory": "16Gi", "pods": 20}).obj())
        store.create_object("PodGroup", PodGroup(
            meta=ObjectMeta(name="g", namespace="default"),
            min_member=2, schedule_timeout_seconds=5))
        store.create_pod(
            make_pod("g-0").req({"cpu": "500m"}).pod_group("g").obj())
        store.create_pod(
            make_pod("g-1").req({"cpu": "500m"}).pod_group("g").obj())
        # one cycle: the FIRST member parks at Permit waiting on quorum
        assert sched.schedule_one()
        assert "default/g-0" in sched.waiting_pods
        assert led.entry("default/g-0")["segment"] == "gang.permit_park"
        # past the gang timeout BEFORE the sibling's cycle runs: the sweep
        # tears down the WHOLE gang (reject cascades through Coscheduling)
        clock.advance(6.0)
        sched.schedule_one()
        assert "default/g-0" not in sched.waiting_pods
        e = led.entry("default/g-0")
        assert e["segments"]["gang.permit_park"] >= 5.0
        # both members park unschedulable (no ClusterEvent wakes a gang
        # denial); the unschedulable-timeout flush retries them — by then
        # the denial backoff has lapsed, quorum holds, the gang binds whole
        for _ in range(20):
            sched.run_until_settled()
            if sched.metrics["scheduled"] == 2:
                break
            clock.advance(60.0)
        assert sched.metrics["scheduled"] == 2
        e = led.entry("default/g-0")
        assert e["result"] == "scheduled"
        assert e["segments"]["gang.permit_park"] >= 5.0
        # the post-reject park shows up as queue dwell (map or backoff)
        assert (e["segments"].get("queue.unschedulable", 0)
                + e["segments"].get("queue.backoff", 0)) > 0
        _entry_invariant(e)
        assert len(led) == 0

    def test_delete_while_unbound_drops_entry_under_churn(self):
        """2x-cluster churn of never-schedulable pods: every deleted pod's
        entry drops (result=deleted) — the ledger cannot leak."""
        store = ClusterStore()
        sched = Scheduler(store)
        led = latency_ledger.enable(sched.smetrics)
        store.create_node(make_node("n0").capacity(
            {"cpu": "1", "memory": "1Gi", "pods": 10}).obj())
        for round_ in range(4):
            for i in range(5):
                store.create_pod(make_pod(f"c{round_}-{i}").req(
                    {"cpu": "64"}).obj())  # never fits
            sched.run_until_settled()
            for i in range(5):
                store.delete_pod(f"default/c{round_}-{i}")
        assert len(led) == 0
        assert led.evicted == 0
        assert sched.smetrics.pod_e2e_duration.count("deleted") == 20


# ------------------------------------------- wire acceptance + /debug/timeline


def _get(port, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=5) as r:
        return r.status, r.read().decode()


class TestWirePipelineAcceptance:
    """The ISSUE acceptance proof: a pod scheduled through the PIPELINED
    wire path after one injected poison requeue shows e2e == sum(segments)
    with nonzero device.inflight and queue.backoff, and /debug/timeline
    renders its segments next to its batch's dispatch/commit flight events
    as valid Chrome trace-event JSON."""

    def test_pipelined_wire_poison_then_timeline(self):
        from kubernetes_tpu.backend import telemetry
        from kubernetes_tpu.backend.service import (DeviceService,
                                                    WireScheduler, serve)
        from kubernetes_tpu.cmd.server import ComponentServer, \
            build_debug_handlers
        from kubernetes_tpu.testing.faults import FaultPlan

        # one transport error burst that outlives the retry budget: the
        # in-flight batch dies with its transport -> pipeline_poison ->
        # backoffQ requeue, exactly like ring poison
        plan = FaultPlan().error_n(2, "schedule_batch")
        service = DeviceService(batch_size=32)
        server, port = serve(service, fault_plan=plan)
        clock = FakeClock()
        store = ClusterStore()
        sched = WireScheduler(
            store, endpoint=f"http://127.0.0.1:{port}", batch_size=4,
            wire_pipeline_depth=3, fault_plan=plan,
            now_fn=clock, sleep_fn=lambda s: clock.advance(s),
            heartbeat_interval_s=0.0, wire_max_retries=1,
            pod_initial_backoff=0.01, pod_max_backoff=0.05)
        # ledger on its own wall clock: transport dwell is real time even
        # though the scheduler runs on the FakeClock
        led = latency_ledger.enable(sched.smetrics)
        tele = telemetry.enable(sched.smetrics)
        try:
            for i in range(4):
                store.create_node(make_node(f"n{i}").capacity(
                    {"cpu": "8", "memory": "16Gi", "pods": 20}).obj())
            for i in range(8):
                store.create_pod(
                    make_pod(f"p{i}").req({"cpu": "500m"}).obj())
            for _ in range(40):
                sched.run_until_settled()
                if sched.metrics["scheduled"] == 8:
                    break
                clock.advance(0.1)
                time.sleep(0.002)  # real dwell for the backoff segment
            assert sched.metrics["scheduled"] == 8
            # the poison really happened
            assert tele.flight.events("pipeline_poison")
            poisoned = {e["batchId"]
                        for e in tele.flight.events("pipeline_poison")}
            # find a pod whose batch was poisoned and later rebound
            victim = None
            for view in led.timeline_entries():
                if (view["result"] == "scheduled"
                        and view["segments"].get("queue.backoff", 0) > 0):
                    victim = view
                    break
            assert victim is not None, "no poisoned-then-bound pod found"
            assert victim["segments"]["device.inflight"] > 0
            assert victim["segments"]["queue.backoff"] > 0
            _entry_invariant(victim)
            assert victim["batchId"] not in poisoned  # rebound on a NEW batch

            # ---- /debug/timeline over real HTTP
            mux = ComponentServer(configz={},
                                  registry=sched.smetrics.registry,
                                  debug=build_debug_handlers(sched))
            mux_port = mux.start()
            try:
                status, body = _get(mux_port, "/debug/timeline?limit=2000")
                assert status == 200
                doc = json.loads(body)  # valid Chrome trace-event JSON
                evs = doc["traceEvents"]
                assert all("ph" in e and "name" in e and "pid" in e
                           for e in evs)
                pod_slices = [e for e in evs if e.get("cat") == "ledger"
                              and e["args"].get("pod") == victim["pod"]]
                names = {e["name"] for e in pod_slices}
                assert {"device.inflight", "queue.backoff"} <= names
                # the pod's FINAL batch's dispatch + commit flight events
                # share the timeline, correlated by batchId
                flight_names = {
                    e["name"] for e in evs if e.get("cat") == "flight"
                    and e["args"].get("batchId") == victim["batchId"]}
                assert {"dispatch", "commit"} <= flight_names
            finally:
                mux.stop()
        finally:
            telemetry.disable()
            server.shutdown()

    def test_wire_conflict_requeue_accumulates(self):
        """A scripted cross-client conflict verdict: the pod bounces off
        backoffQ (conflict -> error requeue) and the retry binds it — the
        entry spans both attempts."""
        from kubernetes_tpu.backend.service import (DeviceService,
                                                    WireScheduler, serve)
        from kubernetes_tpu.testing.faults import FaultPlan

        plan = FaultPlan().conflict("schedule_batch")
        service = DeviceService(batch_size=32)
        server, port = serve(service, fault_plan=plan)
        clock = FakeClock()
        store = ClusterStore()
        sched = WireScheduler(
            store, endpoint=f"http://127.0.0.1:{port}", batch_size=8,
            fault_plan=plan, now_fn=clock,
            sleep_fn=lambda s: clock.advance(s),
            heartbeat_interval_s=0.0, wire_max_retries=1,
            pod_initial_backoff=0.01, pod_max_backoff=0.05)
        led = latency_ledger.enable(sched.smetrics)
        try:
            for i in range(4):
                store.create_node(make_node(f"n{i}").capacity(
                    {"cpu": "8", "memory": "16Gi", "pods": 20}).obj())
            for i in range(4):
                store.create_pod(
                    make_pod(f"p{i}").req({"cpu": "500m"}).obj())
            for _ in range(40):
                sched.run_until_settled()
                if sched.metrics["scheduled"] == 4:
                    break
                clock.advance(0.1)
                time.sleep(0.002)
            assert sched.metrics["scheduled"] == 4
            assert sched.session_rejoins >= 1  # the conflict really fired
            e = led.entry("default/p0")
            assert e["result"] == "scheduled"
            assert e["segments"]["queue.backoff"] > 0
            assert e["segments"]["device.inflight"] > 0
            _entry_invariant(e)
            assert len(led) == 0
        finally:
            server.shutdown()
