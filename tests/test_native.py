"""Native C++ quantity parser: build, parity vs the Fraction oracle, fallback."""

import math

import pytest

from kubernetes_tpu.api import resource as res
from kubernetes_tpu.native import canonical_native, native_available
from kubernetes_tpu.native.loader import CLS_COUNT, CLS_KIB, CLS_MIB, CLS_MILLI

pytestmark = pytest.mark.skipif(
    not native_available(), reason="no native toolchain in this environment"
)

# (string, resource) corpus spanning every suffix class and rounding edge
CORPUS = [
    ("0", "cpu"), ("1", "cpu"), ("2", "cpu"), ("100m", "cpu"), ("1500m", "cpu"),
    ("0.1", "cpu"), ("0.5", "cpu"), ("2.5", "cpu"), ("0.001", "cpu"),
    ("1n", "cpu"), ("999999999n", "cpu"), ("250u", "cpu"), ("3.14159", "cpu"),
    ("1k", "cpu"), ("+2", "cpu"),
    ("0", "memory"), ("128", "memory"), ("1Ki", "memory"), ("1500", "memory"),
    ("1Mi", "memory"), ("32Gi", "memory"), ("1Ti", "memory"), ("2Pi", "memory"),
    ("1.5Gi", "memory"), ("100M", "memory"), ("1G", "memory"), ("1023", "memory"),
    ("1025", "memory"), ("0.5Ki", "memory"), ("123456789", "memory"),
    ("10Gi", "ephemeral-storage"), ("1048577", "ephemeral-storage"),
    ("2Mi", "hugepages-2Mi"), ("1Gi", "hugepages-1Gi"),
    ("3", "pods"), ("110", "pods"), ("4", "example.com/gpu"),
]


def _python_canonical(resource, value):
    """The Fraction oracle, bypassing the native fast path."""
    if resource == res.CPU:
        return res.milli_value(value)
    if resource == res.MEMORY:
        return math.ceil(res.parse_quantity(value) / 2**10)
    if resource == res.EPHEMERAL_STORAGE or resource.startswith(res.HUGEPAGES_PREFIX):
        return math.ceil(res.parse_quantity(value) / 2**20)
    return res.int_value(value)


class TestNativeParity:
    @pytest.mark.parametrize("value,resource", CORPUS)
    def test_matches_fraction_oracle(self, value, resource):
        native = canonical_native(value, res._native_cls(resource))
        assert native is not None, f"native rejected {value!r}"
        assert native == _python_canonical(resource, value), (value, resource)

    def test_canonical_uses_native(self):
        # the public canonical() must agree with the oracle on strings
        for value, resource in CORPUS:
            assert res.canonical(resource, value) == _python_canonical(resource, value)

    def test_invalid_strings_fall_through(self):
        assert canonical_native("abc", CLS_COUNT) is None
        assert canonical_native("1..2", CLS_MILLI) is None
        assert canonical_native("", CLS_KIB) is None
        assert canonical_native("1Xi", CLS_MIB) is None
        with pytest.raises(ValueError):
            res.canonical("cpu", "not-a-quantity")

    def test_deep_fractional_tail_falls_back_to_exact_path(self):
        # ADVICE r1 (low): nonzero fractional digits beyond 18 significant
        # digits must NOT be silently truncated (the ceil would undershoot);
        # the native parser signals failure and canonical() goes exact.
        deep = "1.0000000000000000001"  # 19 sig digits, nonzero tail
        assert canonical_native(deep, CLS_COUNT) is None
        assert res.canonical("pods", deep) == 2       # exact ceil
        assert res.canonical("cpu", deep) == 1001     # 1000.0...1m -> ceil
        # trailing ZERO tail is exactly representable: native may keep it
        zeros = "1.0000000000000000000"
        assert res.canonical("pods", zeros) == 1

    def test_negative_and_whitespace(self):
        assert canonical_native(" 100m ", CLS_MILLI) == 100
        assert canonical_native("-1", CLS_MILLI) == -1000

    def test_huge_values_rejected_not_wrapped(self):
        # 19-digit integer part: error, not silent wrap
        assert canonical_native("12345678901234567890", CLS_COUNT) is None

    def test_speed_sanity(self):
        import time

        t0 = time.perf_counter()
        for _ in range(2000):
            canonical_native("1500m", CLS_MILLI)
        native_dt = time.perf_counter() - t0
        t0 = time.perf_counter()
        for _ in range(2000):
            res.milli_value("1500m")
        python_dt = time.perf_counter() - t0
        assert native_dt < python_dt  # the point of the exercise
