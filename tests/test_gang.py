"""Gang scheduling: the PodGroup kind, the Coscheduling plugin set
(QueueSort/PreFilter/Permit/Unreserve/PostBind), gang-aware queue
activation, the batched gang kernel, and the oracle↔TPU acceptance
(identical placements, zero fallback, never a partial gang)."""

import random

import numpy as np
import pytest

from kubernetes_tpu.api.types import (
    POD_GROUP_LABEL,
    LabelSelector,
    ObjectMeta,
    PodGroup,
)
from kubernetes_tpu.api.wrappers import make_node, make_pod
from kubernetes_tpu.apiserver.store import ClusterStore
from kubernetes_tpu.framework.plugins.coscheduling import pod_group_key
from kubernetes_tpu.scheduler.scheduler import Scheduler
from kubernetes_tpu.utils.clock import FakeClock


def mk_store(n_nodes=8, cpu="8"):
    store = ClusterStore()
    for i in range(n_nodes):
        store.create_node(
            make_node(f"node-{i}")
            .capacity({"cpu": cpu, "memory": "16Gi", "pods": 32})
            .label("kubernetes.io/hostname", f"node-{i}").obj())
    return store


def add_group(store, name="g", min_member=3, timeout_s=0, ns="default"):
    store.create_object("PodGroup", PodGroup(
        meta=ObjectMeta(name=name, namespace=ns),
        min_member=min_member, schedule_timeout_seconds=timeout_s))


def gang_pod(name, group, cpu="500m", anti=True):
    pw = make_pod(name).req({"cpu": cpu, "memory": "256Mi"}).pod_group(group)
    if anti:
        # one member per host: the multi-host TPU shape
        pw.pod_affinity("kubernetes.io/hostname",
                        LabelSelector(match_labels={POD_GROUP_LABEL: group}),
                        anti=True)
    return pw.obj()


def bound_map(store):
    return {k: p.spec.node_name for k, p in store.pods.items()
            if p.spec.node_name}


def assert_gang_atomic(store, group, size):
    """All-or-nothing: a gang is bound in full or not at all."""
    n = sum(1 for p in store.pods.values()
            if p.meta.labels.get(POD_GROUP_LABEL) == group
            and p.spec.node_name)
    assert n in (0, size), f"partial gang {group}: {n}/{size} bound"


# ---------------------------------------------------------------------------
# PodGroup API kind


class TestPodGroupAPI:
    def test_scheme_wire_roundtrip(self):
        from kubernetes_tpu.api.scheme import default_scheme

        scheme = default_scheme()
        pg = PodGroup(meta=ObjectMeta(name="train", namespace="ml"),
                      min_member=32, schedule_timeout_seconds=120,
                      phase="Scheduling", scheduled=7)
        doc = scheme.encode(pg)
        assert doc["apiVersion"] == "scheduling.x-k8s.io/v1alpha1"
        assert doc["spec"]["minMember"] == 32
        back = scheme.decode(doc)
        assert back.min_member == 32
        assert back.schedule_timeout_seconds == 120
        assert back.phase == "Scheduling" and back.scheduled == 7

    def test_wal_roundtrip(self, tmp_path):
        from kubernetes_tpu.apiserver.wal import attach_wal, restore

        path = str(tmp_path / "store.wal")
        store = ClusterStore()
        attach_wal(store, path)
        add_group(store, "train", min_member=8, timeout_s=60)
        restored = restore(path)
        pg = restored.get_object("PodGroup", "default/train")
        assert pg is not None and pg.min_member == 8
        assert pg.schedule_timeout_seconds == 60

    def test_http_route_serves_podgroups(self):
        from kubernetes_tpu.apiserver.http import _route

        group, kind, ns, name, _sub = _route(
            "/apis/scheduling.x-k8s.io/v1alpha1/namespaces/ml/podgroups/train")
        assert kind == "PodGroup" and ns == "ml" and name == "train"

    def test_validation_rejects_bad_min_member(self):
        from kubernetes_tpu.api.validation import ValidationError

        store = ClusterStore()
        with pytest.raises(ValidationError):
            store.create_object("PodGroup", PodGroup(
                meta=ObjectMeta(name="bad"), min_member=0))


# ---------------------------------------------------------------------------
# queue sort: gang members adjacent, groupless order preserved


class TestGangQueueSort:
    def test_members_sort_adjacently(self):
        store = mk_store(4)
        clock = FakeClock()
        s = Scheduler(store, now_fn=clock)
        add_group(store, "a", min_member=2)
        add_group(store, "b", min_member=2)
        # interleave group adds with singletons at one timestamp
        s.queue.add(gang_pod("a-0", "a", anti=False))
        s.queue.add(make_pod("solo-0").req({"cpu": "1m"}).obj())
        s.queue.add(gang_pod("b-0", "b", anti=False))
        s.queue.add(gang_pod("a-1", "a", anti=False))
        s.queue.add(gang_pod("b-1", "b", anti=False))
        order = []
        while True:
            qp = s.queue.pop()
            if qp is None:
                break
            order.append(qp.pod.meta.name)
        groups = [pod_group_key(store.get_pod(f"default/{n}") or
                                gang_pod(n, n.split("-")[0], anti=False))
                  for n in order]
        # each gang's members are contiguous in pop order
        for g in ("default/a", "default/b"):
            idxs = [i for i, gg in enumerate(groups) if gg == g]
            assert idxs == list(range(idxs[0], idxs[0] + len(idxs))), order

    def test_priority_still_dominates(self):
        store = mk_store(4)
        s = Scheduler(store)
        add_group(store, "g", min_member=1)
        s.queue.add(gang_pod("g-0", "g", anti=False))
        hi = make_pod("hi").req({"cpu": "1m"}).priority(100).obj()
        s.queue.add(hi)
        assert s.queue.pop().pod.meta.name == "hi"


# ---------------------------------------------------------------------------
# Coscheduling on the sequential oracle path


class TestCoschedulingOracle:
    def test_all_or_nothing_release_at_quorum(self):
        store = mk_store(8)
        s = Scheduler(store)
        add_group(store, "train", min_member=4)
        for i in range(4):
            store.create_pod(gang_pod(f"train-{i}", "train"))
        s.run_until_settled()
        assert len(bound_map(store)) == 4
        # distinct nodes (the anti-affinity contract held)
        assert len(set(bound_map(store).values())) == 4
        pg = store.get_object("PodGroup", "default/train")
        assert pg.phase == "Running" and pg.scheduled == 4
        m = s.smetrics
        assert m.gang_wait_duration.count("scheduled") == 1
        assert m.gangs_rejected.labels("timeout") == 0

    def test_prefilter_fast_fails_below_min_member(self):
        store = mk_store(8)
        s = Scheduler(store)
        add_group(store, "train", min_member=4)
        for i in range(2):
            store.create_pod(gang_pod(f"train-{i}", "train"))
        s.run_until_settled()
        assert bound_map(store) == {}
        assert len(s.waiting_pods) == 0  # fast-fail parks NOTHING at Permit

    def test_late_sibling_arrival_coactivates_gang(self):
        store = mk_store(8)
        clock = FakeClock()
        s = Scheduler(store, now_fn=clock)
        add_group(store, "train", min_member=4)
        for i in range(3):
            store.create_pod(gang_pod(f"train-{i}", "train"))
        s.run_until_settled()
        assert bound_map(store) == {}
        store.create_pod(gang_pod("train-3", "train"))
        clock.advance(2.0)
        s.run_until_settled()
        assert len(bound_map(store)) == 4

    def test_missing_group_parks_until_created(self):
        store = mk_store(4)
        clock = FakeClock()
        s = Scheduler(store, now_fn=clock)
        store.create_pod(gang_pod("g-0", "g", anti=False))
        s.run_until_settled()
        assert bound_map(store) == {}
        add_group(store, "g", min_member=1)  # PodGroup event reactivates
        clock.advance(2.0)
        s.run_until_settled()
        assert len(bound_map(store)) == 1

    def test_permit_timeout_tears_down_whole_gang(self):
        store = mk_store(2, cpu="2")
        clock = FakeClock()
        s = Scheduler(store, now_fn=clock)
        add_group(store, "h", min_member=3, timeout_s=2)
        for i in range(3):  # only 2 can hold a node at once (2 cpu each)
            store.create_pod(gang_pod(f"h-{i}", "h", cpu="2", anti=False))
        s.run_until_settled()
        assert len(s.waiting_pods) == 2  # two parked, one unschedulable
        clock.advance(2.5)
        s.run_until_settled()
        assert len(s.waiting_pods) == 0
        assert bound_map(store) == {}  # never a partial gang
        m = s.smetrics
        assert m.gangs_rejected.labels("timeout") == 1
        assert m.gang_wait_duration.count("rejected") == 1

    def test_rejected_gang_backs_off_then_retries(self):
        store = mk_store(2, cpu="2")
        clock = FakeClock()
        s = Scheduler(store, now_fn=clock)
        add_group(store, "h", min_member=3, timeout_s=1)
        for i in range(3):
            store.create_pod(gang_pod(f"h-{i}", "h", cpu="2", anti=False))
        s.run_until_settled()
        clock.advance(1.5)
        s.run_until_settled()  # timeout -> rejection + denial backoff
        # capacity appears: a third node
        store.create_node(make_node("node-extra").capacity(
            {"cpu": "2", "memory": "16Gi", "pods": 32}).obj())
        clock.advance(6.0)  # past the denial window
        s.run_until_settled()
        clock.advance(2.0)
        s.run_until_settled()
        assert len(bound_map(store)) == 3
        pg = store.get_object("PodGroup", "default/h")
        assert pg.phase == "Running"

    def test_member_delete_decrements_bound_and_updates_status(self):
        """ROADMAP PR4 follow-up: deleting a bound gang member must
        decrement the plugin's bound-count cache and refresh the PodGroup
        status instead of leaving both frozen at quorum."""
        store = mk_store(8)
        s = Scheduler(store)
        add_group(store, "train", min_member=4)
        for i in range(4):
            store.create_pod(gang_pod(f"train-{i}", "train"))
        s.run_until_settled()
        assert len(bound_map(store)) == 4
        plugin = s.profiles["default-scheduler"].plugin("Coscheduling")
        assert plugin._bound["default/train"] == 4
        store.delete_pod("default/train-0")
        assert plugin._bound["default/train"] == 3
        pg = store.get_object("PodGroup", "default/train")
        assert pg.scheduled == 3 and pg.phase == "Scheduling"

    def test_stale_quorum_cannot_release_partial_recreated_gang(self):
        """THE stale-quorum bug: after members of a Running gang die, a
        replacement member must NOT be released at Permit on the strength
        of the old bound count — that binds a partial gang that can never
        complete. 4 one-per-node members fill 4 nodes; 2 die; of the 2
        replacements one is unschedulable, so the other must park (real
        quorum 2+1 < 4) and then tear down — bound stays exactly 2."""
        store = mk_store(4, cpu="2")
        clock = FakeClock()
        s = Scheduler(store, now_fn=clock)
        add_group(store, "train", min_member=4, timeout_s=1)
        for i in range(4):
            store.create_pod(gang_pod(f"train-{i}", "train", cpu="2",
                                      anti=False))
        s.run_until_settled()
        assert len(bound_map(store)) == 4
        store.delete_pod("default/train-0")
        store.delete_pod("default/train-1")
        # two replacements: one fits a freed node, one can never fit
        store.create_pod(gang_pod("train-4", "train", cpu="2", anti=False))
        store.create_pod(gang_pod("train-5", "train", cpu="16", anti=False))
        clock.advance(2.0)
        s.run_until_settled()
        clock.advance(2.0)  # permit-timeout sweep for any parked member
        s.run_until_settled()
        # with the stale count (4) the fitting replacement would have been
        # released solo → 3 bound members of a gang that can never reach 4
        assert len(bound_map(store)) == 2, bound_map(store)

    def test_emptied_gang_gc_resets_state_for_recreation(self):
        """When the last member disappears the per-gang plugin state is
        GC'd and the PodGroup status resets, so a re-created gang with the
        same group key is judged entirely afresh."""
        store = mk_store(8)
        clock = FakeClock()
        s = Scheduler(store, now_fn=clock)
        add_group(store, "train", min_member=4)
        for i in range(4):
            store.create_pod(gang_pod(f"train-{i}", "train"))
        s.run_until_settled()
        assert len(bound_map(store)) == 4
        plugin = s.profiles["default-scheduler"].plugin("Coscheduling")
        for i in range(4):
            store.delete_pod(f"default/train-{i}")
        assert "default/train" not in plugin._bound
        assert "default/train" not in plugin._denied
        pg = store.get_object("PodGroup", "default/train")
        assert pg.phase == "Pending" and pg.scheduled == 0
        # the re-created gang schedules from scratch and reaches Running
        for i in range(4):
            store.create_pod(gang_pod(f"redo-{i}", "train"))
        clock.advance(2.0)
        s.run_until_settled()
        assert len(bound_map(store)) == 4
        pg = store.get_object("PodGroup", "default/train")
        assert pg.phase == "Running" and pg.scheduled == 4


# ---------------------------------------------------------------------------
# the gang kernel (ops/gang.py) — device vs host-oracle parity


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_gang_kernel_parity(seed):
    from kubernetes_tpu.ops.gang import assign_gangs, gang_assign_host

    rng = random.Random(seed)
    G, M, N = 4, 6, 12
    feasible = np.zeros((G, M, N), bool)
    prefer = np.full((G, M), -1, np.int32)
    active = np.zeros((G, M), bool)
    for g in range(G):
        size = rng.randint(1, M)
        for m in range(size):
            active[g, m] = True
            for n in range(N):
                feasible[g, m, n] = rng.random() < 0.4
            if rng.random() < 0.7:
                prefer[g, m] = rng.randrange(N)
    idx_d, ok_d = assign_gangs(feasible, prefer, active)
    idx_d, ok_d = np.asarray(idx_d), np.asarray(ok_d)
    for g in range(G):
        want_idx, want_ok = gang_assign_host(feasible[g], prefer[g], active[g])
        assert bool(ok_d[g]) == want_ok, f"seed={seed} gang={g}"
        assert list(idx_d[g]) == want_idx, f"seed={seed} gang={g}"
        if want_ok:
            # distinct nodes among active members, feasibility respected
            chosen = [idx_d[g][m] for m in range(M) if active[g][m]]
            assert len(set(chosen)) == len(chosen)
            assert all(feasible[g][m][idx_d[g][m]]
                       for m in range(M) if active[g][m])


def test_gang_kernel_prefers_program_choices():
    """When the program's choices are distinct and feasible, the kernel
    reproduces them exactly (the parity-by-construction property)."""
    from kubernetes_tpu.ops.gang import assign_gangs

    feasible = np.ones((1, 3, 8), bool)
    prefer = np.array([[5, 2, 7]], np.int32)
    active = np.ones((1, 3), bool)
    idx, ok = assign_gangs(feasible, prefer, active)
    assert bool(np.asarray(ok)[0])
    assert list(np.asarray(idx)[0]) == [5, 2, 7]


# ---------------------------------------------------------------------------
# acceptance: batched path parity + atomicity + no fallback


class TestBatchedGangs:
    def _workload(self, store):
        add_group(store, "train", min_member=4)
        for i in range(4):
            store.create_pod(gang_pod(f"train-{i}", "train"))
        add_group(store, "infer", min_member=2)
        for i in range(2):
            store.create_pod(gang_pod(f"infer-{i}", "infer"))
        for i in range(3):
            store.create_pod(
                make_pod(f"solo-{i}").req({"cpu": "200m"}).obj())

    def test_tpu_matches_oracle_and_stays_batched(self):
        """Acceptance: identical pod→node assignments between the
        sequential oracle Coscheduling path and the TPU batched gang path,
        with zero sequential fallback and both gangs released atomically."""
        from kubernetes_tpu.backend.tpu_scheduler import TPUScheduler

        store_o, store_t = mk_store(12), mk_store(12)
        oracle = Scheduler(store_o)
        tpu = TPUScheduler(store_t, batch_size=16)
        self._workload(store_o)
        self._workload(store_t)
        oracle.run_until_settled()
        tpu.run_until_settled()
        po, pt = bound_map(store_o), bound_map(store_t)
        assert po == pt
        assert len(pt) == 9  # everything landed
        assert tpu.fallback_scheduled == 0
        assert tpu.batch_scheduled == len(pt)
        for g, size in (("train", 4), ("infer", 2)):
            assert_gang_atomic(store_t, g, size)
            pg = store_t.get_object("PodGroup", f"default/{g}")
            assert pg.phase == "Running" and pg.scheduled == size

    def test_infeasible_gang_rejected_whole_batch(self):
        """A gang that cannot fully place (anti-affinity over fewer nodes
        than members) is rejected WHOLE by the batch commit — no member
        binds, no member stays parked, singletons still land."""
        from kubernetes_tpu.backend.tpu_scheduler import TPUScheduler

        store = mk_store(3)
        tpu = TPUScheduler(store, batch_size=16)
        add_group(store, "big", min_member=5, timeout_s=2)
        for i in range(5):
            store.create_pod(gang_pod(f"big-{i}", "big"))
        store.create_pod(make_pod("solo").req({"cpu": "200m"}).obj())
        tpu.run_until_settled(max_cycles=60)
        assert set(bound_map(store)) == {"default/solo"}
        assert len(tpu.waiting_pods) == 0
        assert_gang_atomic(store, "big", 5)
        m = tpu.smetrics
        assert (m.gangs_rejected.labels("infeasible")
                + m.gangs_rejected.labels("incomplete")) >= 1

    def test_gang_split_across_batches_still_atomic(self):
        """A gang larger than the micro-batch spans batches: earlier
        members park at Permit and the final batch's quorum releases the
        whole gang."""
        from kubernetes_tpu.backend.tpu_scheduler import TPUScheduler

        store = mk_store(10)
        tpu = TPUScheduler(store, batch_size=4)
        tpu.sizer.max_batch = 4  # pin the pop size below the gang size
        add_group(store, "wide", min_member=6)
        for i in range(6):
            store.create_pod(gang_pod(f"wide-{i}", "wide"))
        tpu.run_until_settled()
        assert len(bound_map(store)) == 6
        assert len(set(bound_map(store).values())) == 6
        assert_gang_atomic(store, "wide", 6)

    def test_wire_gang_surrender_releases_device_capacity(self):
        """Regression: a gang the device placed but the host rejected whole
        must not leave phantom capacity in the device service's mirror — a
        later solo pod that fits on host truth must bind on the wire path
        exactly as it does in-process."""
        from kubernetes_tpu.backend.service import (
            DeviceService, WireScheduler, serve)
        from kubernetes_tpu.backend.tpu_scheduler import TPUScheduler

        def build(store):
            for i in range(2):
                store.create_node(make_node(f"n{i}").capacity(
                    {"cpu": "1", "memory": "8Gi", "pods": 10}).obj())
            add_group(store, "g3", min_member=3, timeout_s=2)
            for i in range(3):  # device places 2, gang rejected whole
                store.create_pod(make_pod(f"g3-{i}").req({"cpu": "900m"})
                                 .pod_group("g3").obj())

        service = DeviceService(batch_size=16)
        server, port = serve(service)
        try:
            store_w = ClusterStore()
            wire = WireScheduler(store_w, endpoint=f"http://127.0.0.1:{port}",
                                 batch_size=8)
            build(store_w)
            wire.run_until_settled(max_cycles=40)
            assert bound_map(store_w) == {}  # atomic reject, nothing bound
            store_w.create_pod(make_pod("solo").req({"cpu": "900m"}).obj())
            wire.run_until_settled(max_cycles=40)
            assert "default/solo" in bound_map(store_w), (
                "phantom gang capacity stranded the solo pod on the device")

            store_t = ClusterStore()
            tpu = TPUScheduler(store_t, batch_size=8)
            build(store_t)
            tpu.run_until_settled(max_cycles=40)
            store_t.create_pod(make_pod("solo").req({"cpu": "900m"}).obj())
            tpu.run_until_settled(max_cycles=40)
            assert bound_map(store_w) == bound_map(store_t)
        finally:
            server.shutdown()

    def test_wire_backend_gang_parity(self):
        """The wire transport path: gangs ride the device service and match
        the oracle exactly (Permit parks/releases on the client)."""
        from kubernetes_tpu.backend.service import (
            DeviceService, WireScheduler, serve)

        store_o, store_w = mk_store(12), mk_store(12)
        oracle = Scheduler(store_o)
        service = DeviceService(batch_size=32)
        server, port = serve(service)
        try:
            wire = WireScheduler(store_w, endpoint=f"http://127.0.0.1:{port}",
                                 batch_size=16)
            self._workload(store_o)
            self._workload(store_w)
            oracle.run_until_settled()
            wire.run_until_settled()
            assert bound_map(store_o) == bound_map(store_w)
            assert wire.degraded_pods == 0
            for g, size in (("train", 4), ("infer", 2)):
                assert_gang_atomic(store_w, g, size)
        finally:
            server.shutdown()


# ---------------------------------------------------------------------------
# queue regression: a big stuck gang must not starve singletons


class TestGangStarvationGuard:
    def test_stuck_32_gang_does_not_starve_singletons(self):
        """A 32-pod gang behind insufficient capacity parks whole-node
        holds at Permit; after the timeout the gang is torn down, the freed
        capacity reactivates the singletons, and the gang's denial backoff
        keeps it from re-parking under them."""
        store = mk_store(4, cpu="2")
        clock = FakeClock()
        s = Scheduler(store, now_fn=clock)
        add_group(store, "huge", min_member=32, timeout_s=1)
        for i in range(32):  # full-node members: they hold ALL capacity
            store.create_pod(gang_pod(f"huge-{i}", "huge", cpu="2",
                                      anti=False))
        s.run_until_settled()
        # members hold every node at Permit; the rest parked unschedulable
        assert len(s.waiting_pods) == 4
        for i in range(6):
            store.create_pod(
                make_pod(f"solo-{i}").req({"cpu": "200m"}).obj())
        s.run_until_settled()
        assert not any("solo" in k for k in bound_map(store))
        for _ in range(4):
            clock.advance(1.6)
            s.run_until_settled()
        solos = [k for k in bound_map(store) if "solo" in k]
        assert len(solos) == 6, bound_map(store)
        assert_gang_atomic(store, "huge", 32)
        assert s.smetrics.gangs_rejected.labels("timeout") >= 1

    def test_gang_coactivation_is_rate_limited(self):
        from kubernetes_tpu.queue.scheduling_queue import SchedulingQueue

        clock = FakeClock()
        q = SchedulingQueue(now_fn=clock,
                            gang_key_fn=pod_group_key,
                            gang_coactivation_interval=1.0)
        from kubernetes_tpu.framework.types import QueuedPodInfo

        for i in range(3):
            qp = QueuedPodInfo(pod=gang_pod(f"m-{i}", "g", anti=False),
                               timestamp=clock())
            q._unschedulable[qp.pod.key()] = qp
        assert q.activate_gang("default/g") == 3
        # re-park and try again inside the interval: guarded
        for i in range(3):
            q._in_queue.clear()
            q._active.clear()
            qp = QueuedPodInfo(pod=gang_pod(f"m-{i}", "g", anti=False),
                               timestamp=clock())
            q._unschedulable[qp.pod.key()] = qp
        assert q.activate_gang("default/g") == 0
        clock.advance(1.5)
        assert q.activate_gang("default/g") == 3


# ---------------------------------------------------------------------------
# perf harness workload


class TestSchedulingGangsWorkload:
    @pytest.mark.parametrize("backend", ["oracle", "tpu"])
    def test_small_variant_runs(self, backend):
        from kubernetes_tpu.perf import TEST_CASES, run_workload

        tc = TEST_CASES["SchedulingGangs"](nodes=48, init_gangs=1,
                                           measured_gangs=1)
        items = run_workload(tc, backend=backend)
        tputs = [it for it in items
                 if it.labels.get("Name") == "SchedulingThroughput"]
        assert len(tputs) == 2  # the 8-gang and the 32-gang measure phases
        assert all(t.data["Average"] > 0 for t in tputs)

    @pytest.mark.slow
    def test_large_variant(self):
        """The reference-size row (kept out of tier-1: slow)."""
        from kubernetes_tpu.perf import TEST_CASES, run_workload

        tc = TEST_CASES["SchedulingGangs"]()  # 5000 nodes, gangs of 8/32
        items = run_workload(tc, backend="tpu")
        tputs = [it for it in items
                 if it.labels.get("Name") == "SchedulingThroughput"]
        assert len(tputs) == 2 and all(t.data["Average"] > 0 for t in tputs)
