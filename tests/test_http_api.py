"""The HTTP REST + watch apiserver front (L2): reference-shaped paths, JSON
round-trips through the codec, resourceVersion watch semantics, the binding
subresource, and a scheduler driving a store that is also served over HTTP."""

import json
import threading
import urllib.error
import urllib.request

import pytest

from kubernetes_tpu.api.wrappers import make_node, make_pod
from kubernetes_tpu.apiserver.http import serve_api, shutdown_api
from kubernetes_tpu.apiserver.store import ClusterStore
from kubernetes_tpu.backend import TPUScheduler


@pytest.fixture()
def api():
    store = ClusterStore()
    server, port = serve_api(store)
    yield store, f"http://127.0.0.1:{port}"
    shutdown_api(server)


def _req(url, method="GET", body=None):
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=method,
                                 headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=30) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_crud_and_list(api):
    store, base = api
    # create a node cluster-scoped
    code, out = _req(f"{base}/api/v1/nodes", "POST", {
        "meta": {"name": "n1"},
        "status": {"allocatable": {"cpu": "4", "memory": "8Gi", "pods": "10"},
                   "capacity": {"cpu": "4", "memory": "8Gi", "pods": "10"},
                   "ready": True},
    })
    assert code == 201, out
    assert store.nodes["n1"].status.ready

    # create a pod namespaced
    code, out = _req(f"{base}/api/v1/namespaces/default/pods", "POST", {
        "meta": {"name": "p1"},
        "spec": {"containers": [{"name": "c", "requests": {"cpu": "1"}}]},
    })
    assert code == 201, out
    assert store.get_pod("default/p1") is not None

    # GET named + LIST
    code, pod = _req(f"{base}/api/v1/namespaces/default/pods/p1")
    assert code == 200 and pod["meta"]["name"] == "p1"
    code, lst = _req(f"{base}/api/v1/namespaces/default/pods")
    assert code == 200 and lst["kind"] == "PodList" and len(lst["items"]) == 1

    # namespace filtering
    code, lst = _req(f"{base}/api/v1/namespaces/other/pods")
    assert code == 200 and lst["items"] == []

    # DELETE
    code, _ = _req(f"{base}/api/v1/namespaces/default/pods/p1", "DELETE")
    assert code == 200
    assert store.get_pod("default/p1") is None

    # 404s and 409s
    code, st = _req(f"{base}/api/v1/namespaces/default/pods/nope")
    assert code == 404 and st["reason"] == "NotFound"
    _req(f"{base}/api/v1/nodes", "POST", {"meta": {"name": "n1"}})
    code, st = _req(f"{base}/api/v1/nodes", "POST", {"meta": {"name": "n1"}})
    assert code == 409


def test_binding_subresource_and_admission(api):
    store, base = api
    _req(f"{base}/api/v1/nodes", "POST", {
        "meta": {"name": "n1"},
        "status": {"allocatable": {"cpu": "4", "memory": "8Gi", "pods": "10"},
                   "capacity": {"cpu": "4", "memory": "8Gi", "pods": "10"},
                   "ready": True}})
    _req(f"{base}/api/v1/namespaces/default/pods", "POST", {
        "meta": {"name": "p1"},
        "spec": {"containers": [{"name": "c", "requests": {"cpu": "1"}}]}})
    code, _ = _req(f"{base}/api/v1/namespaces/default/pods/p1/binding", "POST",
                   {"target": {"name": "n1"}})
    assert code == 201
    assert store.get_pod("default/p1").spec.node_name == "n1"
    # double bind conflicts (BindingREST semantics)
    code, st = _req(f"{base}/api/v1/namespaces/default/pods/p1/binding", "POST",
                    {"target": {"name": "n1"}})
    assert code == 409

    # admission runs over HTTP: creating into an absent namespace is denied
    code, st = _req(f"{base}/api/v1/namespaces/ghost/pods", "POST", {
        "meta": {"name": "px"},
        "spec": {"containers": [{"name": "c", "requests": {"cpu": "1"}}]}})
    assert code == 403, st


def test_watch_streams_events(api):
    store, base = api
    code, lst = _req(f"{base}/api/v1/namespaces/default/pods")
    rv = lst["metadata"]["resourceVersion"]
    events = []
    done = threading.Event()

    def watcher():
        req = urllib.request.Request(
            f"{base}/api/v1/namespaces/default/pods?watch=1&resourceVersion={rv}")
        with urllib.request.urlopen(req, timeout=30) as resp:
            for line in resp:
                ev = json.loads(line)
                events.append((ev["type"], ev["object"]["meta"]["name"]))
                if len(events) >= 2:
                    break
        done.set()

    t = threading.Thread(target=watcher, daemon=True)
    t.start()
    store.create_pod(make_pod("w1").req({"cpu": "1"}).obj())
    store.delete_pod("default/w1")
    assert done.wait(10), events
    assert events[0] == ("ADDED", "w1")
    assert events[1][0] == "DELETED"


def test_watch_410_on_expired_rv(api):
    store, base = api
    for i in range(20):
        store.create_pod(make_pod(f"x{i}").req({"cpu": "1m"}).obj())
    code, st = _req(f"{base}/api/v1/namespaces/default/pods?watch=1&resourceVersion=-5000")
    # -5000 predates the journal → reference 410 Gone semantics
    assert code == 410 or st.get("reason") == "Expired"


def test_apps_group_and_scheduler_coexistence(api):
    store, base = api
    code, _ = _req(f"{base}/apis/apps/v1/namespaces/default/deployments", "POST", {
        "meta": {"name": "web"}, "replicas": 2})
    assert code == 201
    code, lst = _req(f"{base}/apis/apps/v1/namespaces/default/deployments")
    assert len(lst["items"]) == 1

    # a scheduler on the same store schedules pods created over HTTP
    sched = TPUScheduler(store, batch_size=8)
    _req(f"{base}/api/v1/nodes", "POST", {
        "meta": {"name": "n1"},
        "status": {"allocatable": {"cpu": "8", "memory": "16Gi", "pods": "20"},
                   "capacity": {"cpu": "8", "memory": "16Gi", "pods": "20"},
                   "ready": True}})
    for i in range(4):
        _req(f"{base}/api/v1/namespaces/default/pods", "POST", {
            "meta": {"name": f"job-{i}"},
            "spec": {"containers": [{"name": "c", "requests": {"cpu": "1", "memory": "1Gi"}}]}})
    sched.run_until_settled()
    code, lst = _req(f"{base}/api/v1/namespaces/default/pods")
    bound = [p for p in lst["items"] if p["spec"].get("node_name")]
    assert len(bound) == 4
