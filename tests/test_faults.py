"""Fault-tolerant device path, unit layer: error taxonomy + retry policy,
circuit breaker transitions, fault-plan scripting, split deadlines, and the
epoch/resync protocol over a real localhost socket. No test sleeps against
the wall clock — sleeps and clocks are injected."""

import threading

import pytest

from kubernetes_tpu.api.wrappers import make_node, make_pod
from kubernetes_tpu.backend import circuit
from kubernetes_tpu.backend.circuit import CircuitBreaker
from kubernetes_tpu.backend.errors import (
    ConflictError,
    DeviceServiceError,
    PermanentDeviceError,
    RetryPolicy,
    StaleEpochError,
    TransientDeviceError,
)
from kubernetes_tpu.backend.service import DeviceService, WireClient, serve
from kubernetes_tpu.framework.types import QueuedPodInfo
from kubernetes_tpu.queue.scheduling_queue import SchedulingQueue
from kubernetes_tpu.testing.faults import FaultPlan
from kubernetes_tpu.utils.clock import FakeClock


class _FakeSleeper:
    """sleep_fn that advances a FakeClock instead of blocking."""

    def __init__(self, clock):
        self.clock = clock
        self.sleeps = []

    def __call__(self, seconds):
        self.sleeps.append(seconds)
        self.clock.advance(seconds)


class TestRetryPolicy:
    def _policy(self, **kw):
        clock = FakeClock()
        sleeper = _FakeSleeper(clock)
        kw.setdefault("sleep_fn", sleeper)
        kw.setdefault("now_fn", clock)
        return RetryPolicy(**kw), sleeper

    def test_transient_retries_then_succeeds(self):
        policy, sleeper = self._policy(max_retries=3, backoff_base=0.1)
        calls = []

        def fn():
            calls.append(1)
            if len(calls) < 3:
                raise TransientDeviceError("flake")
            return "ok"

        assert policy.run("op", fn) == "ok"
        assert len(calls) == 3 and len(sleeper.sleeps) == 2

    def test_exponential_backoff_with_jitter_bounds(self):
        policy, sleeper = self._policy(max_retries=4, backoff_base=0.1,
                                       backoff_max=10.0, jitter=0.5)
        calls = []

        def fn():
            calls.append(1)
            raise TransientDeviceError("down")

        with pytest.raises(TransientDeviceError):
            policy.run("op", fn)
        assert len(calls) == 5  # initial + 4 retries
        # jittered backoff stays in [0.5, 1.0]·(base·2^k)
        for k, s in enumerate(sleeper.sleeps):
            nominal = 0.1 * (2 ** k)
            assert 0.5 * nominal <= s <= nominal

    def test_deadline_budget_bounds_retries(self):
        policy, sleeper = self._policy(max_retries=100, backoff_base=1.0,
                                       backoff_max=1.0, deadline_s=3.0,
                                       jitter=0.0)
        calls = []

        def fn():
            calls.append(1)
            raise TransientDeviceError("down")

        with pytest.raises(TransientDeviceError):
            policy.run("op", fn)
        # 1s sleeps against a 3s budget: the loop must stop near the budget,
        # nowhere near the 100-retry ceiling
        assert len(calls) <= 5

    def test_permanent_and_stale_never_retry(self):
        for exc in (PermanentDeviceError("bad"), StaleEpochError("e2"),
                    ConflictError("raced")):
            policy, sleeper = self._policy(max_retries=5)
            calls = []

            def fn():
                calls.append(1)
                raise exc

            with pytest.raises(DeviceServiceError):
                policy.run("op", fn)
            assert len(calls) == 1 and not sleeper.sleeps

    def test_on_retry_hook_fires_per_retry(self):
        seen = []
        policy, _ = self._policy(max_retries=2, on_retry=seen.append)
        with pytest.raises(TransientDeviceError):
            policy.run("sync", lambda: (_ for _ in ()).throw(
                TransientDeviceError("x")))
        assert seen == ["sync", "sync"]


class TestCircuitBreaker:
    def test_opens_after_threshold_and_probes_after_timeout(self):
        clock = FakeClock()
        transitions = []
        br = CircuitBreaker(failure_threshold=3, reset_timeout_s=5.0,
                            now_fn=clock,
                            on_state_change=lambda o, n: transitions.append(n))
        assert br.allow() and br.state == circuit.CLOSED
        br.record_failure(TransientDeviceError("a"))
        br.record_failure(TransientDeviceError("b"))
        assert br.state == circuit.CLOSED and br.allow()
        br.record_failure(TransientDeviceError("c"))
        assert br.state == circuit.OPEN
        assert not br.allow()  # timer not expired
        clock.advance(5.1)
        assert br.allow() and br.state == circuit.HALF_OPEN  # the probe
        br.record_failure(TransientDeviceError("probe failed"))
        assert br.state == circuit.OPEN  # one half-open failure re-opens
        clock.advance(5.1)
        assert br.allow()
        br.record_success()
        assert br.state == circuit.CLOSED and br.consecutive_failures == 0
        assert transitions == [circuit.OPEN, circuit.HALF_OPEN, circuit.OPEN,
                               circuit.HALF_OPEN, circuit.CLOSED]

    def test_success_resets_failure_count(self):
        br = CircuitBreaker(failure_threshold=2, now_fn=FakeClock())
        br.record_failure()
        br.record_success()
        br.record_failure()
        assert br.state == circuit.CLOSED  # never two CONSECUTIVE failures


class TestFaultPlan:
    def test_error_n_consumes_in_order(self):
        plan = FaultPlan().error_n(2, "schedule_batch").drop("apply_deltas")
        assert plan.next_client("schedule_batch").kind == "error"
        assert plan.next_client("schedule_batch").kind == "error"
        assert plan.next_client("schedule_batch") is None
        assert plan.next_client("apply_deltas").kind == "drop"
        assert plan.pending() == 0
        assert [k for _, _, k in plan.log] == ["error", "error", "drop"]

    def test_wildcard_matches_either_op(self):
        plan = FaultPlan().error_once("*")
        assert plan.next_client("apply_deltas") is not None
        assert plan.next_client("schedule_batch") is None

    def test_server_and_client_sides_independent(self):
        plan = FaultPlan().crash("schedule_batch").error_once("schedule_batch")
        assert plan.next_client("schedule_batch").kind == "error"
        assert plan.next_server("schedule_batch").kind == "crash"


class TestPartitionPrimitive:
    """ISSUE 10 satellite: per-verb persistent drop — batch traffic fails
    while Health still answers (the asymmetric-partition failure mode)."""

    def test_batch_verbs_drop_health_answers(self):
        plan = FaultPlan().partition()
        for _ in range(5):  # persistent: never drains
            assert plan.next_client("schedule_batch").kind == "drop"
            assert plan.next_client("apply_deltas").kind == "drop"
        assert plan.next_client("health") is None
        assert plan.next_client("heartbeat") is None
        assert plan.pending() == 0  # persistent faults don't count down

    def test_partition_narrowed_to_one_verb(self):
        from kubernetes_tpu.testing.faults import SCHEDULE_BATCH

        plan = FaultPlan().partition(SCHEDULE_BATCH)
        assert plan.next_client("schedule_batch").kind == "drop"
        assert plan.next_client("apply_deltas") is None

    def test_heal_lifts_the_partition(self):
        plan = FaultPlan().partition()
        assert plan.next_client("schedule_batch") is not None
        plan.heal()
        assert plan.next_client("schedule_batch") is None
        assert plan.next_client("apply_deltas") is None

    def test_heal_is_selective_by_op(self):
        plan = FaultPlan().partition()
        plan.heal(op="apply_deltas")
        assert plan.next_client("apply_deltas") is None
        assert plan.next_client("schedule_batch").kind == "drop"

    def test_per_op_heal_under_a_wildcard_fault_raises(self):
        """heal(op=X) against a kill() (wildcard drop) would otherwise
        silently no-op — every X call still matches the '*' queue while
        the script believes X recovered. The plan rejects it loudly."""
        plan = FaultPlan().kill()
        with pytest.raises(ValueError, match="wildcard"):
            plan.heal(op="schedule_batch")
        assert plan.next_client("schedule_batch").kind == "drop"  # still dead
        plan.heal()  # the sanctioned full heal
        assert plan.next_client("schedule_batch") is None
        # idempotent no-op heal with no wildcard present stays silent
        plan.heal(op="schedule_batch")

    def test_kill_drops_everything_including_health(self):
        plan = FaultPlan().kill()
        for op in ("apply_deltas", "schedule_batch", "health", "heartbeat"):
            assert plan.next_client(op).kind == "drop"
        assert plan.next_client("health").kind == "drop"  # persistent
        plan.heal()
        assert plan.next_client("health") is None

    def test_injecting_behind_a_persistent_fault_is_rejected(self):
        """A persistent fault never leaves its queue head, so a finite
        fault injected behind it on the same key would silently never
        fire — the plan rejects the script instead of losing its intent
        (heal() first, or target a different op: exact-op queues are
        consulted before the ANY queue, so kill() + a per-op fault still
        composes)."""
        from kubernetes_tpu.testing.faults import ANY, Fault

        plan = FaultPlan().kill()
        with pytest.raises(ValueError, match="persistent"):
            plan.inject(ANY, Fault("error"))
        # exact-op injection behind a wildcard kill is fine (and fires
        # first: _take prefers the (side, op) queue over (side, ANY))
        plan.inject("schedule_batch", Fault("error"))
        assert plan.next_client("schedule_batch").kind == "error"
        assert plan.next_client("schedule_batch").kind == "drop"
        # heal() then re-inject is the sanctioned sequence
        plan.heal()
        plan.inject(ANY, Fault("error"))
        assert plan.next_client("health").kind == "error"

    def test_partition_raises_the_transient_family(self):
        from kubernetes_tpu.backend.errors import raise_injected_fault

        plan = FaultPlan().partition()
        with pytest.raises(TransientDeviceError):
            raise_injected_fault(plan, "schedule_batch", read_timeout=60.0)
        raise_injected_fault(plan, "health", read_timeout=60.0)  # no-op


class TestSlowPrimitive:
    """ISSUE 10 satellite: persistent per-endpoint latency — below the
    read deadline the calls succeed slow (laggy-but-live must NOT read as
    dead), at/above it every call times out."""

    def test_slow_below_deadline_is_absorbed_forever(self):
        from kubernetes_tpu.backend.errors import raise_injected_fault

        plan = FaultPlan().slow(0.05)
        for _ in range(4):
            raise_injected_fault(plan, "schedule_batch", read_timeout=1.0)
        # consumed (and logged) every time, but never raised
        assert [k for _, _, k in plan.log] == ["delay"] * 4
        assert plan.pending() == 0

    def test_slow_past_deadline_times_out_every_call(self):
        from kubernetes_tpu.backend.errors import raise_injected_fault

        plan = FaultPlan().slow(10.0)
        for _ in range(3):
            with pytest.raises(TransientDeviceError, match="timeout"):
                raise_injected_fault(plan, "apply_deltas", read_timeout=1.0)
        plan.heal()
        raise_injected_fault(plan, "apply_deltas", read_timeout=1.0)  # healed

    def test_slow_endpoint_still_serves_over_the_socket(self):
        service = DeviceService(batch_size=8)
        server, port = serve(service)
        try:
            plan = FaultPlan().slow(0.01)
            client = WireClient(f"http://127.0.0.1:{port}", read_timeout=5.0,
                                fault_plan=plan)
            for _ in range(3):
                out = client.apply_deltas({"nodes": []})
            assert out["deltaSeq"] == 3
        finally:
            server.shutdown()


class TestWireClientTaxonomy:
    def test_connection_refused_is_transient(self):
        # nothing listens on this port: refusal must classify transient and
        # burn exactly max_retries+1 attempts
        clock = FakeClock()
        sleeper = _FakeSleeper(clock)
        retries = []
        client = WireClient(
            "http://127.0.0.1:9",  # discard port, never bound
            connect_timeout=0.2,
            retry=RetryPolicy(max_retries=2, sleep_fn=sleeper, now_fn=clock,
                              on_retry=retries.append))
        with pytest.raises(TransientDeviceError):
            client.apply_deltas({"nodes": []})
        assert retries == ["apply_deltas", "apply_deltas"]

    def test_injected_delay_beyond_read_deadline_is_timeout(self):
        plan = FaultPlan().delay(10.0, "schedule_batch")
        client = WireClient("http://127.0.0.1:9", read_timeout=1.0,
                            retry=RetryPolicy(max_retries=0),
                            fault_plan=plan)
        with pytest.raises(TransientDeviceError, match="timeout"):
            client.schedule_batch({"pods": []})

    def test_injected_delay_under_deadline_is_absorbed(self):
        service = DeviceService(batch_size=8)
        server, port = serve(service)
        try:
            plan = FaultPlan().delay(0.01, "apply_deltas")
            client = WireClient(f"http://127.0.0.1:{port}", read_timeout=5.0,
                                fault_plan=plan)
            out = client.apply_deltas({"nodes": []})
            assert out["epoch"] == service.epoch
        finally:
            server.shutdown()

    def test_server_exception_is_permanent(self):
        service = DeviceService(batch_size=8)
        server, port = serve(service)
        try:
            client = WireClient(f"http://127.0.0.1:{port}",
                                retry=RetryPolicy(max_retries=3))
            # 500 (service-side exception) and 4xx are PERMANENT — only
            # infrastructure 502/503/504 are transient; exercise the
            # permanent arm via an unknown route (404)
            with pytest.raises(DeviceServiceError):
                client._post("/v1/doesNotExist", {}, "apply_deltas")
        finally:
            server.shutdown()


class TestEpochProtocol:
    def test_stale_epoch_detected_over_socket(self):
        service = DeviceService(batch_size=8)
        server, port = serve(service)
        try:
            client = WireClient(f"http://127.0.0.1:{port}")
            out = client.apply_deltas({"nodes": []})
            e1 = out["epoch"]
            assert out["deltaSeq"] == 1
            # sidecar restart: fresh service behind the same socket
            fresh = server.binding.restart()
            assert fresh.epoch != e1
            with pytest.raises(StaleEpochError) as ei:
                client.apply_deltas({"nodes": [], "expectEpoch": e1})
            assert ei.value.epoch == fresh.epoch
            with pytest.raises(StaleEpochError):
                client.schedule_batch({"pods": [], "expectEpoch": e1})
            # the recovery move — a FULL resync — is exempt from the check
            node = make_node("n0").capacity(
                {"cpu": "4", "memory": "8Gi", "pods": 10}).obj()
            from kubernetes_tpu.api.codec import to_wire

            out = client.apply_deltas({
                "full": True, "expectEpoch": e1,
                "nodes": [{"gen": 1, "node": to_wire(node), "pods": []}]})
            assert out["epoch"] == fresh.epoch and out["nodes"] == 1
        finally:
            server.shutdown()

    def test_batch_replay_is_idempotent(self):
        """A transport retry after a LOST RESPONSE (the server committed,
        then the connection died) must replay the committed result, not
        double-commit the pods against capacity their first copies took."""
        from kubernetes_tpu.api.codec import to_wire

        service = DeviceService(batch_size=8)
        node = make_node("n0").capacity(
            {"cpu": "4", "memory": "8Gi", "pods": 10}).obj()
        service.apply_deltas({"nodes": [{"gen": 1, "node": to_wire(node),
                                         "pods": []}]})
        pods = [to_wire(make_pod(f"p{i}").req({"cpu": "1"}).obj())
                for i in range(4)]
        req = {"pods": pods, "batchId": "client-1-7"}
        first = service.schedule_batch(req)
        assert all(r["nodeName"] == "n0" for r in first["results"])
        counter = service.batch_counter
        # the retry: identical request, same batchId
        second = service.schedule_batch(req)
        assert second == first                      # byte-identical replay
        assert service.batch_counter == counter     # nothing recomputed
        assert service.batch_replays == 1
        # a NEW batch id computes normally (and 4 more 1-cpu pods no longer
        # fit the 4-cpu node the first batch filled — no phantom capacity)
        third = service.schedule_batch({"pods": pods, "batchId": "client-1-8"})
        assert all(r["nodeName"] is None for r in third["results"])

    def test_fresh_client_first_push_is_full_sync(self):
        """A restarted CLIENT against a surviving device: the first push
        (epoch unknown) must be a full sync so ghost nodes from the
        previous client's lifetime cannot linger in the device mirror."""
        from kubernetes_tpu.apiserver import ClusterStore
        from kubernetes_tpu.api.codec import to_wire
        from kubernetes_tpu.backend.service import WireScheduler

        service = DeviceService(batch_size=8)
        server, port = serve(service)
        try:
            # the PREVIOUS client's world: a node that no longer exists
            ghost = make_node("ghost").capacity(
                {"cpu": "4", "memory": "8Gi", "pods": 10}).obj()
            service.apply_deltas({"nodes": [{"gen": 1, "node": to_wire(ghost),
                                             "pods": []}]})
            assert "ghost" in service.infos
            store = ClusterStore()
            store.create_node(make_node("real").capacity(
                {"cpu": "4", "memory": "8Gi", "pods": 10}).obj())
            sched = WireScheduler(store, endpoint=f"http://127.0.0.1:{port}",
                                  batch_size=4)
            store.create_pod(make_pod("p").req({"cpu": "1"}).obj())
            sched.run_until_settled()
            assert set(service.infos) == {"real"}   # ghost swept by full sync
            assert store.get_pod("default/p").spec.node_name == "real"
        finally:
            server.shutdown()

    def test_full_resync_clears_stale_mirror(self):
        service = DeviceService(batch_size=8)
        from kubernetes_tpu.api.codec import to_wire

        nodes = [make_node(f"n{i}").capacity(
            {"cpu": "4", "memory": "8Gi", "pods": 10}).obj() for i in range(3)]
        service.apply_deltas({"nodes": [
            {"gen": 1, "node": to_wire(n), "pods": []} for n in nodes]})
        assert len(service.infos) == 3
        out = service.apply_deltas({"full": True, "nodes": [
            {"gen": 1, "node": to_wire(nodes[0]), "pods": []}]})
        assert out["nodes"] == 1 and set(service.infos) == {"n0"}


class TestConflictTaxonomy:
    """ConflictError is its own family: a 409 whose body says ``conflict``
    (NOT staleEpoch) — never retried at the transport, never a resync."""

    def test_injected_conflict_maps_to_typed_error(self):
        service = DeviceService(batch_size=8)
        plan = FaultPlan().conflict("schedule_batch")
        server, port = serve(service, fault_plan=plan)
        try:
            client = WireClient(f"http://127.0.0.1:{port}",
                                retry=RetryPolicy(max_retries=3))
            with pytest.raises(ConflictError):
                client.schedule_batch({"pods": []})
            assert ("server", "schedule_batch", "conflict") in plan.log
        finally:
            server.shutdown()


class TestSessionLease:
    """Per-client sessions + lease fencing at the DeviceService level."""

    def _service(self, ttl=5.0):
        clock = FakeClock()
        return DeviceService(batch_size=8, lease_ttl_s=ttl, now_fn=clock), clock

    def test_lease_expiry_fences_and_releases_holds(self):
        from kubernetes_tpu.api.codec import to_wire

        service, clock = self._service()
        node = make_node("n0").capacity(
            {"cpu": "4", "memory": "8Gi", "pods": 10}).obj()
        entry = {"gen": 1, "node": to_wire(node), "pods": []}
        out_a = service.apply_deltas({"clientId": "A", "nodes": [entry]})
        gen_a = out_a["sessionGen"]
        service.apply_deltas({"clientId": "B", "nodes": [entry]})
        pod = to_wire(make_pod("p").req({"cpu": "2"}).obj())
        out = service.schedule_batch({"clientId": "A", "sessionGen": gen_a,
                                      "pods": [pod], "batchId": "a-1"})
        assert out["results"][0]["nodeName"] == "n0"
        assert service.infos["n0"].requested.milli_cpu == 2000  # held

        # A goes silent past the TTL while B keeps beating; B's next
        # heartbeat sweeps A's lease
        clock.advance(3.0)
        service.heartbeat({"clientId": "B"})
        clock.advance(3.0)
        hb = service.heartbeat({"clientId": "B"})
        assert hb["fenced"] == ["A"]
        assert service.sessions["A"].fenced
        assert service.takeovers == 1
        # the held (assumed-but-unbound) capacity is released
        assert service.infos["n0"].requested.milli_cpu == 0
        assert service.holds == {}

        # fencing token: the dead incarnation can never commit again
        with pytest.raises(ConflictError):
            service.schedule_batch({"clientId": "A", "sessionGen": gen_a,
                                    "pods": [pod], "batchId": "a-2"})
        # ...and its poisoned idempotency cache never replays a-1
        assert service.sessions["A"].last_batch is None

        # rejoin (no sessionGen): a fresh incarnation under a new gen
        out = service.heartbeat({"clientId": "A"})
        assert out["sessionGen"] != gen_a
        assert not service.sessions["A"].fenced

    def test_fence_keeps_confirmed_bound_capacity(self):
        """Fencing releases only NEVER-CONFIRMED holds: a hold whose pod
        already appeared in the owner's pushed content is really bound —
        freeing it would hand a live pod's capacity out twice."""
        from kubernetes_tpu.api.codec import to_wire

        service, clock = self._service()
        node = make_node("n0").capacity(
            {"cpu": "4", "memory": "8Gi", "pods": 10}).obj()
        entry = {"gen": 1, "node": to_wire(node), "pods": []}
        service.apply_deltas({"clientId": "A", "nodes": [entry]})
        service.apply_deltas({"clientId": "B", "nodes": [entry]})
        bound_pod = make_pod("bound").req({"cpu": "2"}).obj()
        service.schedule_batch({"clientId": "A",
                                "pods": [to_wire(bound_pod)],
                                "batchId": "a-1"})
        # A binds the pod and pushes content INCLUDING it (host truth);
        # B (lagging) has not confirmed, so the hold still exists
        bound_pod.spec.node_name = "n0"
        service.apply_deltas({"clientId": "A", "nodes": [{
            "gen": 2, "node": to_wire(node),
            "pods": [to_wire(bound_pod)]}]})
        assert "default/bound" in service.holds  # B hasn't seen it yet
        # ...and an unconfirmed second commit from A on top
        loose = make_pod("loose").req({"cpu": "1"}).obj()
        service.schedule_batch({"clientId": "A",
                                "pods": [to_wire(loose)], "batchId": "a-2"})
        assert service.infos["n0"].requested.milli_cpu == 3000

        clock.advance(3.0)
        service.heartbeat({"clientId": "B"})
        clock.advance(3.0)
        service.heartbeat({"clientId": "B"})  # sweeps A's lease
        assert service.sessions["A"].fenced
        # only the never-confirmed hold ("loose") was released; the bound
        # pod's capacity is untouched
        assert service.holds == {}
        assert service.infos["n0"].requested.milli_cpu == 2000
        assert service.sessions["A"].released_holds == 1

    def test_pod_index_survives_same_key_rebind(self):
        """A pod deleted and re-created under the same key on another node:
        the old node's stale key list must not erase the live index entry,
        or a rival's in-flight copy would pass the 'already bound' check."""
        from kubernetes_tpu.api.codec import to_wire

        service, _clock = self._service()
        n1 = make_node("n1").capacity(
            {"cpu": "4", "memory": "8Gi", "pods": 10}).obj()
        n2 = make_node("n2").capacity(
            {"cpu": "4", "memory": "8Gi", "pods": 10}).obj()
        p_on_n1 = make_pod("p").req({"cpu": "1"}).obj()
        p_on_n1.spec.node_name = "n1"
        service.apply_deltas({"clientId": "A", "nodes": [
            {"gen": 1, "node": to_wire(n1), "pods": [to_wire(p_on_n1)]},
            {"gen": 1, "node": to_wire(n2), "pods": []}]})
        assert service._pod_nodes["default/p"] == "n1"
        # rebind lands in one push with the NEW node's entry first and the
        # old node's (now empty) entry second — the adversarial order
        p_on_n2 = make_pod("p").req({"cpu": "1"}).obj()
        p_on_n2.spec.node_name = "n2"
        service.apply_deltas({"clientId": "A", "nodes": [
            {"gen": 2, "node": to_wire(n2), "pods": [to_wire(p_on_n2)]},
            {"gen": 2, "node": to_wire(n1), "pods": []}]})
        assert service._pod_nodes["default/p"] == "n2"
        # a rival's in-flight copy of the pod still hits the bound check
        out = service.schedule_batch({"clientId": "B", "batchId": "b-1",
                                      "pods": [to_wire(
                                          make_pod("p").req({"cpu": "1"})
                                          .obj())]})
        assert out["results"][0]["conflict"] is True

    def test_fence_bookkeeping_is_pruned(self):
        """Dead replicas must not accrete forever: once every live session's
        heartbeat cursor passed a fence and the grace window (10×TTL)
        elapsed, the fence-log entry and the dead session record drop."""
        service, clock = self._service(ttl=5.0)
        service.heartbeat({"clientId": "A"})
        service.heartbeat({"clientId": "B"})
        clock.advance(3.0)
        service.heartbeat({"clientId": "B"})
        clock.advance(3.0)
        hb = service.heartbeat({"clientId": "B"})  # fences A, reports it
        assert hb["fenced"] == ["A"]
        assert "A" in service.sessions  # grace window: still inspectable
        # B keeps beating past the grace window (10×TTL = 50s)
        for _ in range(14):
            clock.advance(4.0)
            service.heartbeat({"clientId": "B"})
        assert "A" not in service.sessions
        assert service._fences == []

    def test_anonymous_session_never_expires(self):
        service, clock = self._service()
        service.apply_deltas({"nodes": []})  # legacy clientId-less client
        clock.advance(3600.0)
        out = service.apply_deltas({"nodes": []})  # still served, no fence
        assert out["deltaSeq"] == 2
        assert service.takeovers == 0

    def test_heartbeat_keeps_lease_fresh(self):
        service, clock = self._service(ttl=5.0)
        service.heartbeat({"clientId": "A"})
        for _ in range(5):
            clock.advance(3.0)  # 15s total, but beats every 3s
            service.heartbeat({"clientId": "A"})
        assert not service.sessions["A"].fenced


class TestRelayBreakerProbeCadence:
    """PR 3 carryover: the in-process TPU relay path gets its OWN breaker
    with a cheap probe cadence — a dead relay degrades the batch path to
    the oracle, and a healed one is probed after 0.5s (relay default), not
    the wire breaker's 5s."""

    def _sched(self, monkeypatch, clock):
        from kubernetes_tpu.apiserver.store import ClusterStore
        from kubernetes_tpu.backend.tpu_scheduler import TPUScheduler

        monkeypatch.setenv("KTPU_PIPELINE", "0")  # commit inline per cycle
        store = ClusterStore()
        for i in range(4):
            store.create_node(make_node(f"n{i}").capacity(
                {"cpu": "4", "memory": "8Gi", "pods": 10}).obj())
        sched = TPUScheduler(store, batch_size=4, now_fn=clock,
                             relay_breaker_threshold=2,
                             relay_probe_interval_s=0.5,
                             pod_initial_backoff=0.01, pod_max_backoff=0.02)
        return store, sched

    def test_relay_death_degrades_and_cheap_probe_heals(self, monkeypatch):
        from kubernetes_tpu.backend import batch as batch_mod

        clock = FakeClock()
        store, sched = self._sched(monkeypatch, clock)
        real_unpack = batch_mod.unpack_result_block

        def dead(*a, **kw):
            raise RuntimeError("relay dropped mid-flight")

        monkeypatch.setattr(batch_mod, "unpack_result_block", dead)
        for i in range(4):
            store.create_pod(make_pod(f"p{i}").req({"cpu": "100m"}).obj())
        # failure 1: commit dies, pods requeue, breaker still counting
        sched.schedule_batch_cycle()
        assert sched.metrics["scheduled"] == 0
        assert sched.relay_breaker.state == circuit.CLOSED
        clock.advance(0.05)
        # failure 2: threshold crossed -> OPEN
        sched.schedule_batch_cycle()
        assert sched.relay_breaker.state == circuit.OPEN
        clock.advance(0.05)
        # open: every pod takes the oracle path in-cycle — scheduling never
        # stops, and the dead device is not rebuilt per cycle
        sched.schedule_batch_cycle()
        assert sched.metrics["scheduled"] == 4
        assert sched.relay_degraded_pods == 4
        assert sched.fallback_scheduled == 4
        assert sched.relay_breaker.state == circuit.OPEN

        # the relay heals, but the probe interval hasn't elapsed: still open
        monkeypatch.setattr(batch_mod, "unpack_result_block", real_unpack)
        for i in range(2):
            store.create_pod(make_pod(f"q{i}").req({"cpu": "100m"}).obj())
        clock.advance(0.3)
        sched.schedule_batch_cycle()
        assert sched.relay_breaker.state == circuit.OPEN
        assert sched.metrics["scheduled"] == 6  # oracle keeps landing pods

        # past the RELAY cadence (0.5s — a wire-tuned 5s breaker would still
        # be waiting): the next batch is the half-open probe; it commits and
        # the batch path resumes
        for i in range(2):
            store.create_pod(make_pod(f"r{i}").req({"cpu": "100m"}).obj())
        clock.advance(0.3)  # 0.6 total since the last failure
        sched.schedule_batch_cycle()
        assert sched.relay_breaker.state == circuit.CLOSED
        assert sched.metrics["scheduled"] == 8
        assert sched.batch_scheduled >= 2  # the probe batch went on-device

    def test_failed_probe_reopens(self, monkeypatch):
        from kubernetes_tpu.backend import batch as batch_mod

        clock = FakeClock()
        store, sched = self._sched(monkeypatch, clock)

        def dead(*a, **kw):
            raise RuntimeError("still dead")

        monkeypatch.setattr(batch_mod, "unpack_result_block", dead)
        for i in range(2):
            store.create_pod(make_pod(f"p{i}").req({"cpu": "100m"}).obj())
        sched.schedule_batch_cycle()
        clock.advance(0.05)
        sched.schedule_batch_cycle()
        assert sched.relay_breaker.state == circuit.OPEN
        # probe admitted after the cadence, fails, re-opens immediately
        clock.advance(0.6)
        sched.schedule_batch_cycle()
        assert sched.relay_breaker.state == circuit.OPEN


class TestErrorRequeue:
    def test_error_status_reenters_via_backoff_queue(self):
        """A cycle ERROR (device batch failure) must re-enter via the
        backoffQ — rate-limited — not park in the unschedulable map (no
        ClusterEvent would ever wake it) and not hot-loop activeQ."""
        clock = FakeClock()
        q = SchedulingQueue(now_fn=clock)
        q.add(make_pod("p1").obj())
        qp = q.pop()
        assert qp.attempts == 1
        q.add_unschedulable_if_not_present(qp, q.scheduling_cycle, error=True)
        pending = q.pending_pods()
        assert pending["backoff"] == 1 and pending["unschedulable"] == 0
        assert q.pop() is None          # backoff gates the retry
        clock.advance(1.1)              # initial backoff 1s
        qp2 = q.pop()
        assert qp2 is not None and qp2.attempts == 2
        # second error: attempts grew, so the backoff window doubles
        q.add_unschedulable_if_not_present(qp2, q.scheduling_cycle, error=True)
        clock.advance(1.1)
        assert q.pop() is None          # 2s window now — still rate-limited
        clock.advance(1.0)
        assert q.pop() is not None

    def test_unschedulable_status_still_parks(self):
        clock = FakeClock()
        q = SchedulingQueue(now_fn=clock)
        q.add(make_pod("p1").obj())
        qp = q.pop()
        qp.unschedulable_plugins = {"NodeResourcesFit"}
        q.add_unschedulable_if_not_present(qp, q.scheduling_cycle)
        assert q.pending_pods()["unschedulable"] == 1


class TestStreamFaultPrimitives:
    """Stream-level fault primitives (pipelined transport failure modes):
    torn mid-stream disconnect, duplicated reply delivery, reordered
    replies. Unit layer — the end-to-end behavior under load lives in
    tests/test_wire_service.py::TestWirePipeline and
    tests/test_chaos.py::TestWirePipelineChaos."""

    def test_reply_faults_live_on_their_own_queue(self):
        """dup/reorder are REPLY-side: raise_injected_fault (the request
        side) must never consume or fire them — a request script cannot
        accidentally swallow a stream fault."""
        from kubernetes_tpu.backend.errors import raise_injected_fault

        plan = FaultPlan().dup_reply("schedule_batch")
        raise_injected_fault(plan, "schedule_batch", 60.0)  # no raise, no consume
        assert plan.pending() == 1
        f = plan.next_reply("schedule_batch")
        assert f is not None and f.kind == "dup"
        assert plan.next_reply("schedule_batch") is None
        assert ("reply", "schedule_batch", "dup") in plan.log

    def test_reorder_injects_two_shot_fault_with_shared_rendezvous(self):
        plan = FaultPlan().reorder("schedule_batch")
        f1 = plan.next_reply("schedule_batch")
        f2 = plan.next_reply("schedule_batch")
        assert f1 is f2                      # one fault consumed twice
        assert f1.kind == "reorder" and f1.rendezvous is not None
        assert plan.next_reply("schedule_batch") is None

    def test_rendezvous_swaps_replies_across_threads(self):
        from kubernetes_tpu.testing.faults import _Rendezvous

        rv = _Rendezvous()
        out = {}

        def first():
            out["first"] = rv.swap({"batchId": "b-1"})

        t = threading.Thread(target=first)
        t.start()
        out["second"] = rv.swap({"batchId": "b-2"})
        t.join(5)
        # each party received the OTHER call's reply
        assert out["first"]["batchId"] == "b-2"
        assert out["second"]["batchId"] == "b-1"

    def test_rendezvous_partner_never_arrives_falls_back_to_own_reply(self):
        from kubernetes_tpu.testing.faults import _Rendezvous

        rv = _Rendezvous(timeout_s=0.01)
        assert rv.swap({"batchId": "b-1"})["batchId"] == "b-1"

    def test_torn_server_side_processes_then_severs(self):
        """torn: the service COMMITS the request but the reply never
        leaves — the client's transport retry re-sends the same batchId
        and the idempotency cache replays; one commit, ever."""
        from kubernetes_tpu.api.codec import to_wire
        from kubernetes_tpu.backend.service import WireClient

        plan = FaultPlan().torn("schedule_batch")
        service = DeviceService(batch_size=8)
        server, port = serve(service, fault_plan=plan)
        try:
            clock = FakeClock()
            sleeper = _FakeSleeper(clock)
            client = WireClient(
                f"http://127.0.0.1:{port}",
                retry=RetryPolicy(max_retries=2, sleep_fn=sleeper,
                                  now_fn=clock))
            node = make_node("n0").capacity(
                {"cpu": "4", "memory": "8Gi", "pods": 10}).obj()
            client.apply_deltas({"nodes": [
                {"gen": 1, "node": to_wire(node), "pods": []}]})
            pod = to_wire(make_pod("p").req({"cpu": "1"}).obj())
            out = client.schedule_batch({"pods": [pod], "batchId": "t-1"})
            # the retry's reply is the REPLAY of the torn call's commit
            assert out["results"][0]["nodeName"] == "n0"
            assert out["batchId"] == "t-1"
            assert service.batch_replays == 1
            assert service.batch_counter == 1       # computed exactly once
            assert ("server", "schedule_batch", "torn") in plan.log
        finally:
            server.shutdown()

    def test_idempotency_cache_covers_last_k_batches(self):
        """Pipelined clients retry any of their last K batches, not just
        the newest: the per-session idempotency cache is a bounded map."""
        from kubernetes_tpu.api.codec import to_wire

        service = DeviceService(batch_size=8)
        node = make_node("n0").capacity(
            {"cpu": "16", "memory": "8Gi", "pods": 20}).obj()
        service.apply_deltas({"clientId": "A", "nodes": [
            {"gen": 1, "node": to_wire(node), "pods": []}]})
        outs = {}
        for i in range(3):
            pod = to_wire(make_pod(f"p{i}").req({"cpu": "1"}).obj())
            outs[f"b-{i}"] = service.schedule_batch(
                {"clientId": "A", "pods": [pod], "batchId": f"b-{i}"})
        # a retry of the OLDEST of the three replays its stored response
        replay = service.schedule_batch(
            {"clientId": "A", "pods": [], "batchId": "b-0"})
        assert replay is outs["b-0"]
        assert service.batch_replays == 1
        # the cache is bounded: far-older ids fall off
        s = service.sessions["A"]
        for i in range(3, 3 + s.IDEMPOTENCY_DEPTH):
            pod = to_wire(make_pod(f"p{i}").req({"cpu": "1"}).obj())
            service.schedule_batch(
                {"clientId": "A", "pods": [pod], "batchId": f"b-{i}"})
        assert len(s.last_batches) == s.IDEMPOTENCY_DEPTH
        assert "b-0" not in s.last_batches
