"""Fault-tolerant device path, unit layer: error taxonomy + retry policy,
circuit breaker transitions, fault-plan scripting, split deadlines, and the
epoch/resync protocol over a real localhost socket. No test sleeps against
the wall clock — sleeps and clocks are injected."""

import threading

import pytest

from kubernetes_tpu.api.wrappers import make_node, make_pod
from kubernetes_tpu.backend import circuit
from kubernetes_tpu.backend.circuit import CircuitBreaker
from kubernetes_tpu.backend.errors import (
    DeviceServiceError,
    PermanentDeviceError,
    RetryPolicy,
    StaleEpochError,
    TransientDeviceError,
)
from kubernetes_tpu.backend.service import DeviceService, WireClient, serve
from kubernetes_tpu.framework.types import QueuedPodInfo
from kubernetes_tpu.queue.scheduling_queue import SchedulingQueue
from kubernetes_tpu.testing.faults import FaultPlan
from kubernetes_tpu.utils.clock import FakeClock


class _FakeSleeper:
    """sleep_fn that advances a FakeClock instead of blocking."""

    def __init__(self, clock):
        self.clock = clock
        self.sleeps = []

    def __call__(self, seconds):
        self.sleeps.append(seconds)
        self.clock.advance(seconds)


class TestRetryPolicy:
    def _policy(self, **kw):
        clock = FakeClock()
        sleeper = _FakeSleeper(clock)
        kw.setdefault("sleep_fn", sleeper)
        kw.setdefault("now_fn", clock)
        return RetryPolicy(**kw), sleeper

    def test_transient_retries_then_succeeds(self):
        policy, sleeper = self._policy(max_retries=3, backoff_base=0.1)
        calls = []

        def fn():
            calls.append(1)
            if len(calls) < 3:
                raise TransientDeviceError("flake")
            return "ok"

        assert policy.run("op", fn) == "ok"
        assert len(calls) == 3 and len(sleeper.sleeps) == 2

    def test_exponential_backoff_with_jitter_bounds(self):
        policy, sleeper = self._policy(max_retries=4, backoff_base=0.1,
                                       backoff_max=10.0, jitter=0.5)
        calls = []

        def fn():
            calls.append(1)
            raise TransientDeviceError("down")

        with pytest.raises(TransientDeviceError):
            policy.run("op", fn)
        assert len(calls) == 5  # initial + 4 retries
        # jittered backoff stays in [0.5, 1.0]·(base·2^k)
        for k, s in enumerate(sleeper.sleeps):
            nominal = 0.1 * (2 ** k)
            assert 0.5 * nominal <= s <= nominal

    def test_deadline_budget_bounds_retries(self):
        policy, sleeper = self._policy(max_retries=100, backoff_base=1.0,
                                       backoff_max=1.0, deadline_s=3.0,
                                       jitter=0.0)
        calls = []

        def fn():
            calls.append(1)
            raise TransientDeviceError("down")

        with pytest.raises(TransientDeviceError):
            policy.run("op", fn)
        # 1s sleeps against a 3s budget: the loop must stop near the budget,
        # nowhere near the 100-retry ceiling
        assert len(calls) <= 5

    def test_permanent_and_stale_never_retry(self):
        for exc in (PermanentDeviceError("bad"), StaleEpochError("e2")):
            policy, sleeper = self._policy(max_retries=5)
            calls = []

            def fn():
                calls.append(1)
                raise exc

            with pytest.raises(DeviceServiceError):
                policy.run("op", fn)
            assert len(calls) == 1 and not sleeper.sleeps

    def test_on_retry_hook_fires_per_retry(self):
        seen = []
        policy, _ = self._policy(max_retries=2, on_retry=seen.append)
        with pytest.raises(TransientDeviceError):
            policy.run("sync", lambda: (_ for _ in ()).throw(
                TransientDeviceError("x")))
        assert seen == ["sync", "sync"]


class TestCircuitBreaker:
    def test_opens_after_threshold_and_probes_after_timeout(self):
        clock = FakeClock()
        transitions = []
        br = CircuitBreaker(failure_threshold=3, reset_timeout_s=5.0,
                            now_fn=clock,
                            on_state_change=lambda o, n: transitions.append(n))
        assert br.allow() and br.state == circuit.CLOSED
        br.record_failure(TransientDeviceError("a"))
        br.record_failure(TransientDeviceError("b"))
        assert br.state == circuit.CLOSED and br.allow()
        br.record_failure(TransientDeviceError("c"))
        assert br.state == circuit.OPEN
        assert not br.allow()  # timer not expired
        clock.advance(5.1)
        assert br.allow() and br.state == circuit.HALF_OPEN  # the probe
        br.record_failure(TransientDeviceError("probe failed"))
        assert br.state == circuit.OPEN  # one half-open failure re-opens
        clock.advance(5.1)
        assert br.allow()
        br.record_success()
        assert br.state == circuit.CLOSED and br.consecutive_failures == 0
        assert transitions == [circuit.OPEN, circuit.HALF_OPEN, circuit.OPEN,
                               circuit.HALF_OPEN, circuit.CLOSED]

    def test_success_resets_failure_count(self):
        br = CircuitBreaker(failure_threshold=2, now_fn=FakeClock())
        br.record_failure()
        br.record_success()
        br.record_failure()
        assert br.state == circuit.CLOSED  # never two CONSECUTIVE failures


class TestFaultPlan:
    def test_error_n_consumes_in_order(self):
        plan = FaultPlan().error_n(2, "schedule_batch").drop("apply_deltas")
        assert plan.next_client("schedule_batch").kind == "error"
        assert plan.next_client("schedule_batch").kind == "error"
        assert plan.next_client("schedule_batch") is None
        assert plan.next_client("apply_deltas").kind == "drop"
        assert plan.pending() == 0
        assert [k for _, _, k in plan.log] == ["error", "error", "drop"]

    def test_wildcard_matches_either_op(self):
        plan = FaultPlan().error_once("*")
        assert plan.next_client("apply_deltas") is not None
        assert plan.next_client("schedule_batch") is None

    def test_server_and_client_sides_independent(self):
        plan = FaultPlan().crash("schedule_batch").error_once("schedule_batch")
        assert plan.next_client("schedule_batch").kind == "error"
        assert plan.next_server("schedule_batch").kind == "crash"


class TestWireClientTaxonomy:
    def test_connection_refused_is_transient(self):
        # nothing listens on this port: refusal must classify transient and
        # burn exactly max_retries+1 attempts
        clock = FakeClock()
        sleeper = _FakeSleeper(clock)
        retries = []
        client = WireClient(
            "http://127.0.0.1:9",  # discard port, never bound
            connect_timeout=0.2,
            retry=RetryPolicy(max_retries=2, sleep_fn=sleeper, now_fn=clock,
                              on_retry=retries.append))
        with pytest.raises(TransientDeviceError):
            client.apply_deltas({"nodes": []})
        assert retries == ["apply_deltas", "apply_deltas"]

    def test_injected_delay_beyond_read_deadline_is_timeout(self):
        plan = FaultPlan().delay(10.0, "schedule_batch")
        client = WireClient("http://127.0.0.1:9", read_timeout=1.0,
                            retry=RetryPolicy(max_retries=0),
                            fault_plan=plan)
        with pytest.raises(TransientDeviceError, match="timeout"):
            client.schedule_batch({"pods": []})

    def test_injected_delay_under_deadline_is_absorbed(self):
        service = DeviceService(batch_size=8)
        server, port = serve(service)
        try:
            plan = FaultPlan().delay(0.01, "apply_deltas")
            client = WireClient(f"http://127.0.0.1:{port}", read_timeout=5.0,
                                fault_plan=plan)
            out = client.apply_deltas({"nodes": []})
            assert out["epoch"] == service.epoch
        finally:
            server.shutdown()

    def test_server_exception_is_permanent(self):
        service = DeviceService(batch_size=8)
        server, port = serve(service)
        try:
            client = WireClient(f"http://127.0.0.1:{port}",
                                retry=RetryPolicy(max_retries=3))
            # 500 (service-side exception) and 4xx are PERMANENT — only
            # infrastructure 502/503/504 are transient; exercise the
            # permanent arm via an unknown route (404)
            with pytest.raises(DeviceServiceError):
                client._post("/v1/doesNotExist", {}, "apply_deltas")
        finally:
            server.shutdown()


class TestEpochProtocol:
    def test_stale_epoch_detected_over_socket(self):
        service = DeviceService(batch_size=8)
        server, port = serve(service)
        try:
            client = WireClient(f"http://127.0.0.1:{port}")
            out = client.apply_deltas({"nodes": []})
            e1 = out["epoch"]
            assert out["deltaSeq"] == 1
            # sidecar restart: fresh service behind the same socket
            fresh = server.binding.restart()
            assert fresh.epoch != e1
            with pytest.raises(StaleEpochError) as ei:
                client.apply_deltas({"nodes": [], "expectEpoch": e1})
            assert ei.value.epoch == fresh.epoch
            with pytest.raises(StaleEpochError):
                client.schedule_batch({"pods": [], "expectEpoch": e1})
            # the recovery move — a FULL resync — is exempt from the check
            node = make_node("n0").capacity(
                {"cpu": "4", "memory": "8Gi", "pods": 10}).obj()
            from kubernetes_tpu.api.codec import to_wire

            out = client.apply_deltas({
                "full": True, "expectEpoch": e1,
                "nodes": [{"gen": 1, "node": to_wire(node), "pods": []}]})
            assert out["epoch"] == fresh.epoch and out["nodes"] == 1
        finally:
            server.shutdown()

    def test_batch_replay_is_idempotent(self):
        """A transport retry after a LOST RESPONSE (the server committed,
        then the connection died) must replay the committed result, not
        double-commit the pods against capacity their first copies took."""
        from kubernetes_tpu.api.codec import to_wire

        service = DeviceService(batch_size=8)
        node = make_node("n0").capacity(
            {"cpu": "4", "memory": "8Gi", "pods": 10}).obj()
        service.apply_deltas({"nodes": [{"gen": 1, "node": to_wire(node),
                                         "pods": []}]})
        pods = [to_wire(make_pod(f"p{i}").req({"cpu": "1"}).obj())
                for i in range(4)]
        req = {"pods": pods, "batchId": "client-1-7"}
        first = service.schedule_batch(req)
        assert all(r["nodeName"] == "n0" for r in first["results"])
        counter = service.batch_counter
        # the retry: identical request, same batchId
        second = service.schedule_batch(req)
        assert second == first                      # byte-identical replay
        assert service.batch_counter == counter     # nothing recomputed
        assert service.batch_replays == 1
        # a NEW batch id computes normally (and 4 more 1-cpu pods no longer
        # fit the 4-cpu node the first batch filled — no phantom capacity)
        third = service.schedule_batch({"pods": pods, "batchId": "client-1-8"})
        assert all(r["nodeName"] is None for r in third["results"])

    def test_fresh_client_first_push_is_full_sync(self):
        """A restarted CLIENT against a surviving device: the first push
        (epoch unknown) must be a full sync so ghost nodes from the
        previous client's lifetime cannot linger in the device mirror."""
        from kubernetes_tpu.apiserver import ClusterStore
        from kubernetes_tpu.api.codec import to_wire
        from kubernetes_tpu.backend.service import WireScheduler

        service = DeviceService(batch_size=8)
        server, port = serve(service)
        try:
            # the PREVIOUS client's world: a node that no longer exists
            ghost = make_node("ghost").capacity(
                {"cpu": "4", "memory": "8Gi", "pods": 10}).obj()
            service.apply_deltas({"nodes": [{"gen": 1, "node": to_wire(ghost),
                                             "pods": []}]})
            assert "ghost" in service.infos
            store = ClusterStore()
            store.create_node(make_node("real").capacity(
                {"cpu": "4", "memory": "8Gi", "pods": 10}).obj())
            sched = WireScheduler(store, endpoint=f"http://127.0.0.1:{port}",
                                  batch_size=4)
            store.create_pod(make_pod("p").req({"cpu": "1"}).obj())
            sched.run_until_settled()
            assert set(service.infos) == {"real"}   # ghost swept by full sync
            assert store.get_pod("default/p").spec.node_name == "real"
        finally:
            server.shutdown()

    def test_full_resync_clears_stale_mirror(self):
        service = DeviceService(batch_size=8)
        from kubernetes_tpu.api.codec import to_wire

        nodes = [make_node(f"n{i}").capacity(
            {"cpu": "4", "memory": "8Gi", "pods": 10}).obj() for i in range(3)]
        service.apply_deltas({"nodes": [
            {"gen": 1, "node": to_wire(n), "pods": []} for n in nodes]})
        assert len(service.infos) == 3
        out = service.apply_deltas({"full": True, "nodes": [
            {"gen": 1, "node": to_wire(nodes[0]), "pods": []}]})
        assert out["nodes"] == 1 and set(service.infos) == {"n0"}


class TestErrorRequeue:
    def test_error_status_reenters_via_backoff_queue(self):
        """A cycle ERROR (device batch failure) must re-enter via the
        backoffQ — rate-limited — not park in the unschedulable map (no
        ClusterEvent would ever wake it) and not hot-loop activeQ."""
        clock = FakeClock()
        q = SchedulingQueue(now_fn=clock)
        q.add(make_pod("p1").obj())
        qp = q.pop()
        assert qp.attempts == 1
        q.add_unschedulable_if_not_present(qp, q.scheduling_cycle, error=True)
        pending = q.pending_pods()
        assert pending["backoff"] == 1 and pending["unschedulable"] == 0
        assert q.pop() is None          # backoff gates the retry
        clock.advance(1.1)              # initial backoff 1s
        qp2 = q.pop()
        assert qp2 is not None and qp2.attempts == 2
        # second error: attempts grew, so the backoff window doubles
        q.add_unschedulable_if_not_present(qp2, q.scheduling_cycle, error=True)
        clock.advance(1.1)
        assert q.pop() is None          # 2s window now — still rate-limited
        clock.advance(1.0)
        assert q.pop() is not None

    def test_unschedulable_status_still_parks(self):
        clock = FakeClock()
        q = SchedulingQueue(now_fn=clock)
        q.add(make_pod("p1").obj())
        qp = q.pop()
        qp.unschedulable_plugins = {"NodeResourcesFit"}
        q.add_unschedulable_if_not_present(qp, q.scheduling_cycle)
        assert q.pending_pods()["unschedulable"] == 1
