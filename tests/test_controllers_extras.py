"""Round-3 controllers: ttl, endpointslice, cronjob, attachdetach."""

from kubernetes_tpu.api.types import (
    CronJob,
    ObjectMeta,
    PersistentVolume,
    PersistentVolumeClaim,
    Service,
)
from kubernetes_tpu.api.wrappers import make_node, make_pod
from kubernetes_tpu.apiserver.store import ClusterStore
from kubernetes_tpu.client.informer import SharedInformerFactory
from kubernetes_tpu.controllers import ControllerManager
from kubernetes_tpu.controllers.extras import TTL_ANNOTATION, cron_matches
from kubernetes_tpu.utils.clock import FakeClock


def make_manager(store, controllers, now_fn=None):
    return ControllerManager(store, factory=SharedInformerFactory(store),
                             controllers=controllers, now_fn=now_fn or FakeClock())


class TestTTL:
    def test_annotation_tracks_cluster_size_tier(self):
        store = ClusterStore()
        m = make_manager(store, ["ttl"])
        for i in range(5):
            store.create_node(make_node(f"n{i}").obj())
        m.settle()
        assert store.nodes["n0"].meta.annotations[TTL_ANNOTATION] == "0"
        for i in range(5, 120):
            store.create_node(make_node(f"n{i}").obj())
        m.settle()
        # >100 nodes → 15s tier, applied to every node incl. early ones
        assert store.nodes["n0"].meta.annotations[TTL_ANNOTATION] == "15"
        assert store.nodes["n119"].meta.annotations[TTL_ANNOTATION] == "15"


class TestEndpointSlice:
    def test_slices_shard_and_track_pods(self):
        store = ClusterStore()
        m = make_manager(store, ["endpointslice"])
        store.create_object("Service", Service(
            meta=ObjectMeta(name="web"), selector={"app": "web"}))
        for i in range(150):
            p = make_pod(f"w{i}").req({"cpu": "1m"}).label("app", "web").node("n1").obj()
            p.status.phase = "Running"
            store.create_pod(p)
        m.settle()
        slices = [s for s in store.endpoint_slices.values()
                  if s.service == "default/web"]
        assert len(slices) == 2  # 150 / 100-per-slice
        total = sum(len(s.addresses) for s in slices)
        assert total == 150
        # pod removal re-shards
        store.delete_pod("default/w0")
        m.settle()
        total = sum(len(s.addresses) for s in store.endpoint_slices.values())
        assert total == 149
        # service deletion removes slices
        store.delete_object("Service", "default/web")
        m.settle()
        assert not store.endpoint_slices


class TestCronJob:
    def test_cron_matches(self):
        assert cron_matches("* * * * *", 0)
        assert cron_matches("*/5 * * * *", 300)       # minute 5
        assert not cron_matches("*/5 * * * *", 60)    # minute 1
        assert cron_matches("0 0 * * *", 0)           # midnight
        assert not cron_matches("0 1 * * *", 0)
        assert cron_matches("0-30 * * * *", 60 * 20)

    def test_spawns_jobs_on_schedule(self):
        store = ClusterStore()
        clock = FakeClock()
        m = make_manager(store, ["cronjob", "job"], now_fn=clock)
        # drive the clock to the next */5 minute boundary first
        now = clock()
        clock.advance((300 - now % 300) % 300)
        minute = int(clock() // 60)
        store.create_object("CronJob", CronJob(
            meta=ObjectMeta(name="tick"), schedule="*/5 * * * *",
            template=make_pod("t").req({"cpu": "1m"}).obj()))
        m.settle()
        jobs = list(store.jobs.values())
        assert len(jobs) == 1
        assert jobs[0].meta.name == f"tick-{minute}"
        # same minute: no duplicate
        m.settle()
        assert len(store.jobs) == 1
        # next */5 boundary: second firing (job controller spawns pods too)
        clock.advance(300)
        m.settle()
        assert len(store.jobs) == 2
        # non-matching minute: nothing
        clock.advance(60)
        m.settle()
        assert len(store.jobs) == 2
        # suspend stops firing
        cj = store.cron_jobs["default/tick"]
        cj.suspend = True
        clock.advance(240)
        m.settle()
        assert len(store.jobs) == 2


class TestJobFailurePolicy:
    def _fail_pending(self, store, n):
        import dataclasses

        failed = 0
        for p in list(store.pods.values()):
            if failed >= n:
                break
            if p.status.phase == "Pending":
                new = dataclasses.replace(p)
                new.meta = dataclasses.replace(p.meta)
                new.status = dataclasses.replace(p.status, phase="Failed")
                store.update_pod(new)
                failed += 1

    def test_backoff_limit_fails_job(self):
        from kubernetes_tpu.api.types import Job, ObjectMeta

        store = ClusterStore()
        m = make_manager(store, ["job"])
        store.create_object("Job", Job(
            meta=ObjectMeta(name="flaky"), completions=1, parallelism=1,
            backoff_limit=2, template=make_pod("t").req({"cpu": "1m"}).obj()))
        for _ in range(6):
            m.settle()
            self._fail_pending(store, 1)
        m.settle()
        job = store.get_object("Job", "default/flaky")
        assert job.condition == "Failed"
        assert job.failed_reason == "BackoffLimitExceeded"
        assert job.failed > 2
        # terminal: no new pods spawn
        alive = [p for p in store.pods.values()
                 if p.status.phase in ("Pending", "Running")]
        assert not alive

    def test_active_deadline(self):
        from kubernetes_tpu.api.types import Job, ObjectMeta

        store = ClusterStore()
        clock = FakeClock()
        m = make_manager(store, ["job"], now_fn=clock)
        store.create_object("Job", Job(
            meta=ObjectMeta(name="slow"), completions=1, parallelism=1,
            active_deadline_seconds=30,
            template=make_pod("t").req({"cpu": "1m"}).obj()))
        m.settle()
        assert store.get_object("Job", "default/slow").condition == ""
        clock.advance(31)
        m.settle()
        job = store.get_object("Job", "default/slow")
        assert job.condition == "Failed"
        assert job.failed_reason == "DeadlineExceeded"

    def test_completion_sets_condition(self):
        import dataclasses

        from kubernetes_tpu.api.types import Job, ObjectMeta

        store = ClusterStore()
        m = make_manager(store, ["job"])
        store.create_object("Job", Job(
            meta=ObjectMeta(name="ok"), completions=2, parallelism=2,
            template=make_pod("t").req({"cpu": "1m"}).obj()))
        m.settle()
        for p in list(store.pods.values()):
            new = dataclasses.replace(p)
            new.meta = dataclasses.replace(p.meta)
            new.status = dataclasses.replace(p.status, phase="Succeeded")
            store.update_pod(new)
        m.settle()
        job = store.get_object("Job", "default/ok")
        assert job.condition == "Complete"
        assert job.succeeded == 2


class TestAttachDetach:
    def test_attach_and_detach_follow_pod_lifecycle(self):
        store = ClusterStore()
        m = make_manager(store, ["attachdetach"])
        store.create_object("PersistentVolume", PersistentVolume(
            meta=ObjectMeta(name="pv1"), capacity_bytes=1 << 30, bound_pvc="default/claim1"))
        store.create_object("PersistentVolumeClaim", PersistentVolumeClaim(
            meta=ObjectMeta(name="claim1"), bound_pv="pv1"))
        pod = make_pod("user").req({"cpu": "1m"}).obj()
        pod.spec.volumes = ("claim1",)
        pod.spec.node_name = "n1"
        store.create_pod(pod)
        m.settle()
        assert "pv1^n1" in store.volume_attachments
        va = store.volume_attachments["pv1^n1"]
        assert va.pv_name == "pv1" and va.node_name == "n1" and va.attached

        store.delete_pod("default/user")
        m.settle()
        assert not store.volume_attachments
