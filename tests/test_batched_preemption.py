"""Batched preemption: the device screen+rank (ops/preempt.py) against the
host oracle (framework/preemption.py), and the end-to-end PostFilter flow
through the TPU batch path."""

import numpy as np

from kubernetes_tpu.api.types import LabelSelector, PodDisruptionBudget
from kubernetes_tpu.api.wrappers import make_node, make_pod
from kubernetes_tpu.apiserver import ClusterStore
from kubernetes_tpu.backend import TPUScheduler
from kubernetes_tpu.scheduler import Scheduler


def _bound(store):
    objs, _rv = store.list_objects("Pod")
    return {p.meta.name: p.spec.node_name for p in objs if p.spec.node_name}


def _fill_cluster(store, n_nodes=6, pods_per_node=3, prio=0):
    for i in range(n_nodes):
        store.create_node(
            make_node(f"n{i}").capacity({"cpu": "3", "memory": "6Gi", "pods": 10}).obj())
    for i in range(n_nodes * pods_per_node):
        store.create_pod(
            make_pod(f"low-{i}").req({"cpu": "1", "memory": "1Gi"})
            .priority(prio).obj())


def test_batched_preemption_evicts_and_schedules():
    """Cluster saturated with low-priority pods; high-priority pods must
    preempt via the device-proposed candidate and end up bound."""
    store = ClusterStore()
    sched = TPUScheduler(store, batch_size=8, comparer_every_n=1)
    _fill_cluster(store)
    sched.run_until_settled()
    assert sched.metrics["scheduled"] == 18

    for i in range(4):
        store.create_pod(
            make_pod(f"high-{i}").req({"cpu": "2", "memory": "2Gi"})
            .priority(1000).obj())
    # first pass: fails + preempts (victims deleted), later passes bind
    for _ in range(30):
        sched.run_until_settled()
        bound = _bound(store)
        if sum(1 for n in bound if n.startswith("high-")) == 4:
            break
    bound = _bound(store)
    assert sum(1 for n in bound if n.startswith("high-")) == 4, bound
    assert sched.comparer_mismatches == 0
    # victims actually evicted: some low pods are gone or unbound
    objs, _ = store.list_objects("Pod")
    low_alive = [p for p in objs if p.meta.name.startswith("low-")]
    assert len(low_alive) < 18


def test_preemption_matches_sequential_path():
    """Same scenario through the TPU batch path and the sequential oracle
    scheduler: both must schedule every high-priority pod (node choice may
    differ only within equally-ranked candidates)."""
    results = {}
    for name, cls in (("tpu", TPUScheduler), ("seq", Scheduler)):
        store = ClusterStore()
        sched = cls(store) if cls is Scheduler else cls(store, batch_size=8)
        _fill_cluster(store, n_nodes=4, pods_per_node=2)
        sched.run_until_settled()
        for i in range(2):
            store.create_pod(
                make_pod(f"high-{i}").req({"cpu": "2", "memory": "2Gi"})
                .priority(500).obj())
        for _ in range(30):
            sched.run_until_settled()
            if sum(1 for n in _bound(store) if n.startswith("high-")) == 2:
                break
        results[name] = sum(1 for n in _bound(store) if n.startswith("high-"))
    assert results["tpu"] == results["seq"] == 2, results


def test_preemption_with_pdbs_takes_host_path_and_respects_ranking():
    """With PDBs present the device best-candidate is ignored (criterion 1
    not modeled on device) but preemption still works via the host path with
    the device screen."""
    from kubernetes_tpu.api.types import ObjectMeta

    store = ClusterStore()
    sched = TPUScheduler(store, batch_size=8)
    _fill_cluster(store, n_nodes=4, pods_per_node=2)
    # a PDB matching every pod (1 disruption allowed): forces the host path
    store.create_object("PodDisruptionBudget", PodDisruptionBudget(
        meta=ObjectMeta(name="pdb-low"),
        selector=LabelSelector(match_labels={}),
        disruptions_allowed=1))
    sched.run_until_settled()
    store.create_pod(
        make_pod("high-0").req({"cpu": "2", "memory": "2Gi"}).priority(500).obj())
    for _ in range(30):
        sched.run_until_settled()
        if "high-0" in _bound(store):
            break
    assert "high-0" in _bound(store)


def test_screen_matches_host_prescreen():
    """Device screen == host _max_free_prescreen on a mixed cluster (exact
    for the resource dims both model)."""
    import jax

    from kubernetes_tpu.framework.preemption import Evaluator
    from kubernetes_tpu.framework.types import NodeInfo
    from kubernetes_tpu.ops.preempt import preempt_screen

    store = ClusterStore()
    sched = TPUScheduler(store, batch_size=4)
    # heterogeneous: some nodes full of evictable pods, some full of
    # high-priority pods, some empty-but-small
    for i in range(3):
        store.create_node(
            make_node(f"evict-{i}").capacity({"cpu": "2", "memory": "4Gi", "pods": 10}).obj())
    for i in range(3):
        store.create_node(
            make_node(f"hard-{i}").capacity({"cpu": "2", "memory": "4Gi", "pods": 10}).obj())
    store.create_node(make_node("tiny").capacity({"cpu": "500m", "memory": "1Gi", "pods": 10}).obj())
    for i in range(3):
        store.create_pod(
            make_pod(f"lo-{i}").req({"cpu": "1500m", "memory": "3Gi"})
            .priority(0).node(f"evict-{i}").obj())
        store.create_pod(
            make_pod(f"hi-{i}").req({"cpu": "1500m", "memory": "3Gi"})
            .priority(2000).node(f"hard-{i}").obj())
    sched.cache.update_snapshot(sched.snapshot)
    sched._ensure_device()
    sched.device.sync(sched.snapshot)

    pods = [make_pod("claim").req({"cpu": "1", "memory": "2Gi"}).priority(1000).obj()]
    pb, et = sched.device.encoder.encode_pods(pods)
    masks = {}  # no static obstacles in this scenario
    failed = np.zeros(pb.capacity, bool)
    failed[0] = True
    res = preempt_screen(pb, sched.device.nt, masks, failed)
    screen = np.asarray(res.screen)[0]
    slot_of = dict(sched.device.encoder.node_slots)

    infos = [ni for ni in sched.snapshot.list() if ni.node is not None]
    host = Evaluator._max_free_prescreen(pods[0], infos)
    for ni, ok in zip(infos, host):
        name = ni.node.meta.name
        assert bool(screen[slot_of[name]]) == ok, name
