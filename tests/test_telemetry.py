"""Device-runtime observability (backend/telemetry.py): compile/retrace
ledger attribution, flight-recorder ring bounds, HBM/transfer counters, the
near-zero-disabled-cost contract (the PR 2 disabled-tracer rule: one global
read per event), and the placement-parity guard — enabling the layer must
change no scheduling decision."""

import contextlib

import numpy as np
import pytest

from kubernetes_tpu.api.wrappers import make_node, make_pod
from kubernetes_tpu.apiserver import ClusterStore
from kubernetes_tpu.backend import TPUScheduler, telemetry
from kubernetes_tpu.backend.telemetry import (
    CompileLedger,
    DeviceTelemetry,
    FlightRecorder,
    STORM_RETRACES,
)
from kubernetes_tpu.metrics.scheduler_metrics import SchedulerMetrics


@pytest.fixture(autouse=True)
def _clean_recorder():
    telemetry.disable()
    yield
    telemetry.disable()


class TestDisabledContract:
    """Tier-1 guard: the whole layer is near-zero-cost when disabled —
    every module hook returns after ONE module-global read, allocating
    nothing."""

    def test_disabled_hooks_are_noops(self):
        assert telemetry.get() is None
        assert telemetry.event("dispatch", batchId="x") is None
        assert telemetry.transfer("upload", 1024) is None
        assert telemetry.sample_hbm() is None

    def test_disabled_dispatch_returns_shared_null_context(self):
        # identity, not just equality: the disabled path allocates NO
        # per-call context manager object
        cm1 = telemetry.dispatch("schedule_batch", bucket="128/off")
        cm2 = telemetry.dispatch("gang_verdicts")
        assert cm1 is cm2 is telemetry._NULL_CM
        with cm1:
            pass  # reusable

    def test_enable_disable_roundtrip(self):
        t = telemetry.enable()
        assert telemetry.get() is t
        telemetry.event("encode", batchId="b1")
        assert len(t.flight) == 1
        telemetry.disable()
        assert telemetry.get() is None
        telemetry.event("encode", batchId="b2")  # no-op, no error
        assert len(t.flight) == 1


class TestFlightRecorderRing:
    def test_ring_overflow_evicts_oldest_bounded_memory(self):
        fr = FlightRecorder(capacity=8)
        for i in range(20):
            fr.record("encode", batchId=f"b{i}")
        assert len(fr) == 8  # bounded
        events = fr.dump()
        # oldest evicted: the ring holds exactly the newest 8, in order
        assert [e["batchId"] for e in events] == [f"b{i}" for i in range(12, 20)]
        assert events[0]["seq"] == 13  # seqs keep counting across evictions
        assert fr.recorded == 20

    def test_dump_limit_caps_from_the_newest_end(self):
        fr = FlightRecorder(capacity=64)
        for i in range(10):
            fr.record("commit", batchId=f"b{i}")
        tail = fr.dump(limit=3)
        assert [e["batchId"] for e in tail] == ["b7", "b8", "b9"]
        assert fr.dump(limit=0) == []

    def test_filtered_events_view(self):
        fr = FlightRecorder()
        fr.record("dispatch", batchId="b1")
        fr.record("poison", batchId="b1")
        fr.record("dispatch", batchId="b2")
        assert [e["type"] for e in fr.events(batch_id="b1")] == [
            "dispatch", "poison"]
        assert len(fr.events("dispatch")) == 2


class TestCompileLedger:
    def test_attribution_retraces_and_storm(self):
        m = SchedulerMetrics()
        led = CompileLedger(m, FlightRecorder())
        # first compile of a program is not a retrace
        with led.dispatch("prog", bucket="16/off"):
            led.record_compile(0.5)
        assert led.compilations[("prog", "16/off")] == 1
        assert led.total_retraces() == 0
        # every further compile (new bucket = the sizer walking) retraces
        for i in range(STORM_RETRACES):
            with led.dispatch("prog", bucket=f"{32 * (i + 1)}/off"):
                led.record_compile(0.1)
        assert led.total_compilations() == 1 + STORM_RETRACES
        assert led.retraces["prog"] == STORM_RETRACES
        # three retraces within the window: exactly one storm flagged
        assert led.storms.get("prog") == 1
        assert led.flight.events("retrace_storm")
        # metrics fed: per-(program, bucket) counter + retrace counter
        assert m.xla_compilations.labels("prog", "16/off") == 1
        assert m.xla_retraces.labels("prog") == STORM_RETRACES
        assert m.xla_compile_duration.count("prog") == 1 + STORM_RETRACES

    def test_unattributed_compile_lands_in_other(self):
        led = CompileLedger()
        led.record_compile(0.2)
        assert led.compilations[(telemetry.OTHER_PROGRAM, "-")] == 1

    def test_real_jit_compile_is_counted(self):
        """End to end through jax.monitoring: a fresh jitted program
        compiled inside a dispatch context lands in the ledger; a cache
        hit does not."""
        import jax
        import jax.numpy as jnp

        t = telemetry.enable()

        @jax.jit
        def probe(x):
            return x * 3 + 1

        with telemetry.dispatch("probe_prog", bucket="4"):
            probe(jnp.ones(4)).block_until_ready()
        n = t.ledger.compilations.get(("probe_prog", "4"), 0)
        assert n >= 1
        with telemetry.dispatch("probe_prog", bucket="4"):
            probe(jnp.ones(4)).block_until_ready()  # cache hit
        assert t.ledger.compilations[("probe_prog", "4")] == n
        assert t.ledger.total_retraces() == 0
        # a new shape retraces
        with telemetry.dispatch("probe_prog", bucket="8"):
            probe(jnp.ones(8)).block_until_ready()
        assert t.ledger.compilations.get(("probe_prog", "8", ), 0) >= 1
        assert t.ledger.total_retraces() >= 1


class TestTransferAndHbm:
    def test_transfer_counters_and_metrics(self):
        m = SchedulerMetrics()
        t = telemetry.enable(m)
        telemetry.transfer("upload", 4096)
        telemetry.transfer("upload", 1024)
        telemetry.transfer("fetch", 256)
        assert t.transfer_bytes == {"upload": 5120, "fetch": 256}
        assert m.device_transfer_bytes.labels("upload") == 5120.0
        assert m.device_transfer_bytes.labels("fetch") == 256.0

    def test_transfer_annotates_active_span(self):
        from kubernetes_tpu.utils import tracing

        telemetry.enable()
        tracer = tracing.enable()
        with tracing.span("device.sync") as s:
            telemetry.transfer("upload", 7777)
        assert s.attributes["device.upload"] == 7777
        tracing.disable()
        assert tracer is not None

    def test_second_scheduler_registry_attaches(self, monkeypatch):
        """Two schedulers set up in one process (the HA topology): the
        second maybe_enable_from_env binds its SchedulerMetrics too —
        events land in BOTH registries, not silently only the first."""
        monkeypatch.setenv("KTPU_TELEMETRY", "1")
        m1, m2 = SchedulerMetrics(), SchedulerMetrics()
        telemetry.maybe_enable_from_env(m1)
        telemetry.maybe_enable_from_env(m2)
        telemetry.maybe_enable_from_env(m2)  # idempotent: no double-count
        telemetry.event("dispatch", batchId="b1")
        telemetry.transfer("upload", 128)
        for m in (m1, m2):
            assert m.flight_events.labels("dispatch") == 1.0
            assert m.device_transfer_bytes.labels("upload") == 128.0

    def test_sample_hbm_never_raises(self):
        t = telemetry.enable()
        # CPU backend: memory_stats() is None -> sample returns None and
        # the peak stays 0; on an accelerator it returns the stats dict
        out = t.sample_hbm()
        assert out is None or "bytes_in_use" in out

    def test_dump_shape(self):
        t = telemetry.enable()
        telemetry.event("dispatch", batchId="b1", bucket=16)
        telemetry.transfer("fetch", 64)
        body = t.dump(limit=10)
        assert body["enabled"] is True
        assert body["ring"]["held"] == 1
        assert body["transfer"]["fetchBytes"] == 64
        assert body["events"][0]["batchId"] == "b1"
        assert "compilations" in body["compile"]


def _run_small_cluster(n_nodes=12, n_pods=24):
    store = ClusterStore()
    sched = TPUScheduler(store, batch_size=8, comparer_every_n=1)
    for i in range(n_nodes):
        store.create_node(
            make_node(f"n{i}")
            .capacity({"cpu": str(4 + i % 5), "memory": "16Gi", "pods": 20})
            .label("zone", f"z{i % 3}").obj())
    for i in range(n_pods):
        store.create_pod(
            make_pod(f"p{i}").req({"cpu": "500m", "memory": "1Gi"}).obj())
    sched.run_until_settled()
    placements = {k: p.spec.node_name for k, p in store.pods.items()
                  if p.spec.node_name}
    return sched, placements


class TestPlacementParityGuard:
    """Enabling the layer changes counters, never placements: identical
    clusters scheduled with telemetry off and on bind identically, and the
    in-run oracle comparer stays clean (oracle<->tpu parity unchanged)."""

    def test_enabled_changes_no_placements(self):
        telemetry.disable()
        sched_off, placements_off = _run_small_cluster()
        assert sched_off.comparer_mismatches == 0

        t = telemetry.enable(SchedulerMetrics())
        sched_on, placements_on = _run_small_cluster()
        assert sched_on.comparer_mismatches == 0
        assert placements_on == placements_off
        # and the layer actually observed the run: lifecycle events with
        # the in-process batch ids, and fetch transfer per commit
        dispatches = t.flight.events("dispatch")
        commits = t.flight.events("commit")
        assert dispatches and commits
        assert all(e["batchId"].startswith("b") for e in dispatches)
        assert t.transfer_bytes["fetch"] > 0


class TestDeviceStateUploadBytes:
    def test_sync_counts_upload_bytes(self):
        from kubernetes_tpu.backend.device_state import (
            DeviceState, caps_for_cluster)

        t = telemetry.enable()
        store = ClusterStore()
        sched = TPUScheduler(store, batch_size=8)
        for i in range(4):
            store.create_node(make_node(f"n{i}").capacity(
                {"cpu": "4", "memory": "8Gi", "pods": 10}).obj())
        sched.cache.update_snapshot(sched.snapshot)
        dev = DeviceState(caps_for_cluster(4, batch=8),
                          ns_labels_fn=store.ns_labels)
        rows = dev.sync(sched.snapshot)
        assert rows == 4
        assert dev.last_upload_bytes > 0
        assert dev.upload_bytes == dev.last_upload_bytes
        assert t.transfer_bytes["upload"] == dev.upload_bytes
        # clean resync: nothing dirty -> no upload counted
        rows2 = dev.sync(sched.snapshot)
        assert rows2 == 0
        assert dev.last_upload_bytes == 0
