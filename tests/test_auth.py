"""Authn / RBAC authz / API priority-and-fairness on the HTTP front
(config.go:806 DefaultBuildHandlerChain stages; pkg/util/flowcontrol)."""

import json
import threading
import urllib.error
import urllib.request

import pytest

from kubernetes_tpu.api.types import ObjectMeta
from kubernetes_tpu.api.wrappers import make_node, make_pod
from kubernetes_tpu.apiserver.auth import (
    AuthConfig,
    GROUP_MASTERS,
    Authenticator,
    AuthenticationError,
    ClusterRole,
    ClusterRoleBinding,
    FlowController,
    FlowSchema,
    PolicyRule,
    PriorityLevel,
    RBACAuthorizer,
    UserInfo,
    default_flow_config,
)
from kubernetes_tpu.apiserver.http import serve_api, shutdown_api
from kubernetes_tpu.apiserver.store import ClusterStore


def _req(port, path, method="GET", body=None, headers=None):
    url = f"http://127.0.0.1:{port}{path}"
    data = json.dumps(body).encode() if body is not None else None
    r = urllib.request.Request(url, data=data, method=method,
                               headers=headers or {})
    if data is not None:
        r.add_header("Content-Type", "application/json")
    with urllib.request.urlopen(r, timeout=10) as resp:
        return resp.status, json.loads(resp.read().decode())


class TestAuthenticator:
    def test_bearer_token(self):
        a = Authenticator(tokens={"s3cret": UserInfo("alice", ("devs",))})
        u = a.authenticate({"Authorization": "Bearer s3cret"})
        assert u.name == "alice" and "system:authenticated" in u.groups

    def test_bad_token_rejected_not_anonymous(self):
        a = Authenticator(tokens={"s3cret": UserInfo("alice")})
        with pytest.raises(AuthenticationError):
            a.authenticate({"Authorization": "Bearer wrong"})

    def test_proxy_headers_and_anonymous(self):
        a = Authenticator()
        u = a.authenticate({"X-Remote-User": "kubelet-1",
                            "X-Remote-Group": "system:nodes"})
        assert u.name == "kubelet-1" and "system:nodes" in u.groups
        anon = a.authenticate({})
        assert anon.name == "system:anonymous"

    def test_anonymous_disabled(self):
        a = Authenticator(allow_anonymous=False)
        with pytest.raises(AuthenticationError):
            a.authenticate({})


class TestRBAC:
    def _store_with_policy(self):
        store = ClusterStore()
        store.create_object("ClusterRole", ClusterRole(
            meta=ObjectMeta(name="pod-reader"),
            rules=(PolicyRule(verbs=("get", "list", "watch"),
                              resources=("Pod",)),)))
        store.create_object("ClusterRoleBinding", ClusterRoleBinding(
            meta=ObjectMeta(name="readers"), role="pod-reader",
            subjects=("user:alice", "group:auditors")))
        return store

    def test_rule_match_and_deny(self):
        store = self._store_with_policy()
        authz = RBACAuthorizer(store)
        assert authz.allowed_for("alice", (), "get", "Pod")
        assert authz.allowed_for("bob", ("auditors",), "list", "Pod")
        assert not authz.allowed_for("alice", (), "create", "Pod")
        assert not authz.allowed_for("alice", (), "get", "Node")
        assert authz.allowed_for("root", ("system:masters",), "delete", "Node")

    def test_resource_names_and_subresources(self):
        store = ClusterStore()
        store.create_object("ClusterRole", ClusterRole(
            meta=ObjectMeta(name="binder"),
            rules=(PolicyRule(verbs=("create",), resources=("Pod",),
                              subresources=("binding",)),)))
        store.create_object("ClusterRoleBinding", ClusterRoleBinding(
            meta=ObjectMeta(name="b"), role="binder",
            subjects=("user:sched",)))
        authz = RBACAuthorizer(store)
        assert authz.allowed_for("sched", (), "create", "Pod", "p1", "binding")
        assert not authz.allowed_for("sched", (), "create", "Pod", "p1", "eviction")


class TestFlowController:
    def test_classify_and_exempt(self):
        fc = FlowController()
        assert fc.classify("root", ("system:masters",), "get") == "exempt"
        assert fc.classify("kubelet", ("system:nodes",), "update") == "system"
        assert fc.classify("anyone", ("system:authenticated",), "get") == "global-default"
        assert fc.classify("anon", (), "get") == "catch-all"
        assert fc.classify("anyone", ("system:authenticated",), "watch") == "exempt"

    def test_concurrency_limit_and_rejection(self):
        fc = FlowController(
            levels=[PriorityLevel("only", concurrency=2, queue_length=0)],
            schemas=[FlowSchema("all", "only")], wait_timeout=0.1)
        r1 = fc.dispatch("u", (), "get")
        r2 = fc.dispatch("u", (), "get")
        assert r1 is not None and r2 is not None
        assert fc.dispatch("u", (), "get") is None  # full, queue 0 → reject
        r1()
        r3 = fc.dispatch("u", (), "get")
        assert r3 is not None
        r2(); r3()

    def test_queued_request_gets_slot_on_release(self):
        fc = FlowController(
            levels=[PriorityLevel("only", concurrency=1, queue_length=4)],
            schemas=[FlowSchema("all", "only")], wait_timeout=5.0)
        r1 = fc.dispatch("u", (), "get")
        got = []

        def waiter():
            r = fc.dispatch("u", (), "get")
            got.append(r)
            if r:
                r()

        t = threading.Thread(target=waiter)
        t.start()
        import time

        time.sleep(0.1)
        r1()  # release → queued request proceeds
        t.join(timeout=5)
        assert got and got[0] is not None


class TestHandlerChainE2E:
    def _serve(self, store, auth):
        server, port = serve_api(store, auth=auth)
        return server, port

    def test_full_chain(self):
        store = ClusterStore()
        store.create_node(make_node("n1").capacity({"cpu": "4"}).obj())
        store.create_object("ClusterRole", ClusterRole(
            meta=ObjectMeta(name="pod-reader"),
            rules=(PolicyRule(verbs=("get", "list"), resources=("Pod",)),)))
        store.create_object("ClusterRoleBinding", ClusterRoleBinding(
            meta=ObjectMeta(name="rb"), role="pod-reader",
            subjects=("user:alice",)))
        auth = AuthConfig(
            authenticator=Authenticator(tokens={
                "alice-tok": UserInfo("alice"),
                "root-tok": UserInfo("root", ("system:masters",)),
            }, allow_anonymous=False),
            authorizer=RBACAuthorizer(store),
            flow=FlowController(),
        )
        server, port = self._serve(store, auth)
        try:
            # no credentials → 401
            with pytest.raises(urllib.error.HTTPError) as e:
                _req(port, "/api/v1/namespaces/default/pods")
            assert e.value.code == 401
            # alice can list pods
            code, body = _req(port, "/api/v1/namespaces/default/pods",
                              headers={"Authorization": "Bearer alice-tok"})
            assert code == 200 and body["kind"] == "PodList"
            # alice cannot list nodes → 403
            with pytest.raises(urllib.error.HTTPError) as e:
                _req(port, "/api/v1/nodes",
                     headers={"Authorization": "Bearer alice-tok"})
            assert e.value.code == 403
            # alice cannot create pods → 403
            with pytest.raises(urllib.error.HTTPError) as e:
                _req(port, "/api/v1/namespaces/default/pods", method="POST",
                     body={"meta": {"name": "p"}},
                     headers={"Authorization": "Bearer alice-tok"})
            assert e.value.code == 403
            # root (system:masters) can do anything
            code, _ = _req(port, "/api/v1/nodes",
                           headers={"Authorization": "Bearer root-tok"})
            assert code == 200
        finally:
            shutdown_api(server)

    def test_flow_rejection_is_429(self):
        store = ClusterStore()
        auth = AuthConfig(flow=FlowController(
            levels=[PriorityLevel("tiny", concurrency=1, queue_length=0)],
            schemas=[FlowSchema("all", "tiny")], wait_timeout=0.1))
        server, port = self._serve(store, auth)
        try:
            # saturate the single slot with a long watch... watches would be
            # exempt under the default config, but this custom config has no
            # exemption, so use two concurrent LISTs via threads
            results = []
            barrier = threading.Barrier(3)

            def lister():
                barrier.wait()
                try:
                    code, _ = _req(port, "/api/v1/namespaces/default/pods")
                    results.append(code)
                except urllib.error.HTTPError as e:
                    results.append(e.code)

            ts = [threading.Thread(target=lister) for _ in range(2)]
            for t in ts:
                t.start()
            barrier.wait()
            for t in ts:
                t.join(timeout=10)
            # both eventually succeed OR one hits 429 — but never hangs;
            # with queue_length=0 a true overlap yields a 429
            assert len(results) == 2 and all(r in (200, 429) for r in results)
        finally:
            shutdown_api(server)

    def test_node_restriction_via_proxy_header(self):
        store = ClusterStore()
        store.create_node(make_node("n1").capacity({"cpu": "4"}).obj())
        store.create_node(make_node("n2").capacity({"cpu": "4"}).obj())
        server, port = self._serve(store, None)  # no auth config: open server
        try:
            node_wire = json.loads(json.dumps({
                "meta": {"name": "n2"}, "spec": {}, "status": {"ready": True},
            }))
            with pytest.raises(urllib.error.HTTPError) as e:
                _req(port, "/api/v1/nodes/n2", method="PUT", body=node_wire,
                     headers={"X-Remote-User": "system:node:n1"})
            assert e.value.code == 403
        finally:
            shutdown_api(server)


class TestNodeAuthorizer:
    """Graph-based node authorizer (plugin/pkg/auth/authorizer/node):
    kubelet reads of Secret/ConfigMap gated on a pod bound to that node
    referencing the object."""

    def _store(self):
        from kubernetes_tpu.api.types import Secret

        store = ClusterStore()
        store.create_node(make_node("n1").capacity({"cpu": "8"}).obj())
        store.create_node(make_node("n2").capacity({"cpu": "8"}).obj())
        store.create_object("Secret", Secret(meta=ObjectMeta(name="db-creds")))
        pod = make_pod("web").obj()
        pod.spec.secret_volumes = ("db-creds",)
        pod.spec.node_name = "n1"
        store.create_pod(pod)
        return store

    def test_kubelet_reads_referenced_secret_only(self):
        from kubernetes_tpu.apiserver.auth import NodeAuthorizer

        store = self._store()
        authz = NodeAuthorizer(store)
        assert authz.allowed_for("system:node:n1", (), "get", "Secret",
                                 "default/db-creds")
        # n2 has no pod referencing it
        assert not authz.allowed_for("system:node:n2", (), "get", "Secret",
                                     "default/db-creds")
        # unreferenced secret denied even on the right node
        assert not authz.allowed_for("system:node:n1", (), "get", "Secret",
                                     "default/other")
        # writes never pass the graph rule
        assert not authz.allowed_for("system:node:n1", (), "update", "Secret",
                                     "default/db-creds")

    def test_node_writes_own_object_only(self):
        from kubernetes_tpu.apiserver.auth import NodeAuthorizer

        authz = NodeAuthorizer(self._store())
        assert authz.allowed_for("system:node:n1", (), "update", "Node", "n1")
        assert not authz.allowed_for("system:node:n1", (), "update", "Node", "n2")
        assert authz.allowed_for("system:node:n1", (), "get", "Node", "n2")

    def test_non_node_users_delegate(self):
        from kubernetes_tpu.apiserver.auth import NodeAuthorizer, RBACAuthorizer

        store = self._store()
        authz = NodeAuthorizer(store, delegate=RBACAuthorizer(store))
        # no bindings: denied via RBAC delegate, not via node rules
        assert not authz.allowed_for("alice", (), "get", "Secret", "default/db-creds")
        assert authz.allowed_for("root", (GROUP_MASTERS,), "get", "Secret",
                                 "default/db-creds")
