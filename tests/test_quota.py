"""Multi-tenant admission (ISSUE 8 tentpole): SchedulingQuota kind,
QuotaAdmission PreEnqueue/PreFilter/Reserve gate, targeted quota-release
reactivation (no thrash under sustained over-quota load), and the
scheduling queue's per-namespace fair-share (DRR) dequeueing."""

import dataclasses

import pytest

from kubernetes_tpu.api.types import (
    ObjectMeta,
    QUOTA_CLAIMS,
    QUOTA_CPU,
    QUOTA_MEMORY,
    QUOTA_PODS,
    SchedulingQuota,
)
from kubernetes_tpu.api.scheme import GroupVersionKind, default_scheme
from kubernetes_tpu.api.validation import ValidationError
from kubernetes_tpu.api.wrappers import make_node, make_pod
from kubernetes_tpu.apiserver.store import ClusterStore
from kubernetes_tpu.framework.plugins.quota import (
    ERR_REASON_QUOTA_EXCEEDED,
    QuotaAdmission,
    pod_quota_request,
)
from kubernetes_tpu.scheduler.scheduler import Scheduler
from kubernetes_tpu.utils.clock import FakeClock


def ensure_ns(store, ns):
    from kubernetes_tpu.api.types import Namespace

    if ns != "default" and ns not in store.namespaces:
        store.create_namespace(Namespace(meta=ObjectMeta(name=ns)))


def quota(store, ns, hard, weight=1, name="quota", cohort=""):
    ensure_ns(store, ns)
    sq = SchedulingQuota(meta=ObjectMeta(name=name, namespace=ns),
                         hard=dict(hard), weight=weight, cohort=cohort)
    store.create_object("SchedulingQuota", sq)
    return sq


def nodes(store, n=4, cpu="8", pods=32):
    for i in range(n):
        store.create_node(make_node(f"n{i}").capacity(
            {"cpu": cpu, "memory": "32Gi", "pods": pods}).obj())


def pod(store, name, ns="default", cpu="1", prio=0, group=None):
    ensure_ns(store, ns)
    pw = make_pod(name, namespace=ns).req({"cpu": cpu, "memory": "1Gi"})
    if prio:
        pw.priority(prio)
    if group:
        pw.pod_group(group)
    p = pw.obj()
    store.create_pod(p)
    return p


def sched_with_clock(store, **kw):
    clock = FakeClock()
    s = Scheduler(store, now_fn=clock, pod_initial_backoff=0.1,
                  pod_max_backoff=0.5, **kw)
    return s, clock


def churn(s, clock, rounds=60, step=0.2):
    """Like settle, but keeps sweeping after the active queue drains —
    the reclaim pass runs from housekeeping, which only ticks while the
    scheduler loop turns."""
    for _ in range(rounds):
        s.schedule_one()
        clock.advance(step)
        s.queue.flush_backoff_completed()


def settle(s, clock, rounds=60):
    for _ in range(rounds):
        progressed = s.schedule_one()
        clock.advance(0.2)
        if not progressed:
            s.queue.flush_backoff_completed()
            if s.queue.pending_pods()["active"] == 0:
                break


# ---------------------------------------------------------------------------
# the API kind


class TestSchedulingQuotaKind:
    def test_scheme_round_trip(self):
        scheme = default_scheme()
        sq = SchedulingQuota(
            meta=ObjectMeta(name="q", namespace="team-a"),
            hard={QUOTA_PODS: 10, QUOTA_CPU: 4000}, weight=3,
            used={QUOTA_PODS: 2})
        doc = scheme.encode(sq)
        assert doc["apiVersion"] == "scheduling.x-k8s.io/v1alpha1"
        assert doc["kind"] == "SchedulingQuota"
        back = scheme.decode(doc)
        assert back.hard == sq.hard
        assert back.weight == 3
        assert back.used == {QUOTA_PODS: 2}
        assert scheme.recognizes(GroupVersionKind(
            "scheduling.x-k8s.io", "v1alpha1", "SchedulingQuota"))

    def test_wal_round_trip(self, tmp_path):
        from kubernetes_tpu.apiserver.wal import attach_wal, restore

        store = ClusterStore()
        attach_wal(store, str(tmp_path / "wal.log"))
        quota(store, "team-a", {QUOTA_PODS: 5, QUOTA_CPU: 2000}, weight=2)

        store2 = restore(str(tmp_path / "wal.log"))
        sq = store2.get_object("SchedulingQuota", "team-a/quota")
        assert sq is not None
        assert sq.hard == {QUOTA_PODS: 5, QUOTA_CPU: 2000}
        assert sq.weight == 2

    def test_http_route(self):
        from kubernetes_tpu.apiserver.http import serve_api

        store = ClusterStore()
        ensure_ns(store, "team-a")
        server, port = serve_api(store)
        try:
            import json
            import urllib.request

            body = json.dumps({
                "apiVersion": "scheduling.x-k8s.io/v1alpha1",
                "kind": "SchedulingQuota",
                "metadata": {"name": "q", "namespace": "team-a"},
                "spec": {"hard": {QUOTA_PODS: 3}, "weight": 2},
            }).encode()
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/apis/scheduling.x-k8s.io/"
                "v1alpha1/namespaces/team-a/schedulingquotas",
                data=body, method="POST",
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req) as resp:
                assert resp.status in (200, 201)
            assert store.get_object("SchedulingQuota", "team-a/q").weight == 2
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/apis/scheduling.x-k8s.io/"
                    "v1alpha1/namespaces/team-a/schedulingquotas/q") as resp:
                doc = json.loads(resp.read())
            # GET serves the framework's reflection wire format (same
            # contract as every other kind, e.g. PodGroup)
            assert doc["hard"] == {QUOTA_PODS: 3}
            assert doc["weight"] == 2
        finally:
            server.shutdown()
            server.server_close()

    def test_validation(self):
        store = ClusterStore()
        with pytest.raises(ValidationError):
            quota(store, "a", {"bogus.dimension": 1})
        with pytest.raises(ValidationError):
            quota(store, "a", {QUOTA_PODS: -1})
        with pytest.raises(ValidationError):
            store.create_object("SchedulingQuota", SchedulingQuota(
                meta=ObjectMeta(name="q", namespace="a"),
                hard={QUOTA_PODS: 1}, weight=-1))
        quota(store, "a", {QUOTA_PODS: 1, QUOTA_CPU: 100,
                           QUOTA_MEMORY: 1024, QUOTA_CLAIMS: 2})

    def test_pod_quota_request_dimensions(self):
        p = make_pod("p").req({"cpu": "500m", "memory": "1Gi"}).obj()
        req = pod_quota_request(p)
        assert req[QUOTA_PODS] == 1
        assert req[QUOTA_CPU] == 500
        assert req[QUOTA_MEMORY] == 1 << 20  # KiB
        assert req[QUOTA_CLAIMS] == 0


# ---------------------------------------------------------------------------
# the admission gate


class TestQuotaGate:
    def test_over_quota_pods_park_gated(self):
        store = ClusterStore()
        nodes(store)
        quota(store, "team-a", {QUOTA_PODS: 2})
        s, clock = sched_with_clock(store)
        for i in range(5):
            pod(store, f"p{i}", ns="team-a")
        settle(s, clock)
        bound = [p for p in store.pods.values() if p.spec.node_name]
        assert len(bound) == 2  # exactly the quota
        pending = s.queue.pending_pods()
        assert pending["gated"] == 3
        assert pending["active"] == 0  # gated pods cost no cycles
        # typed attribution: the gate names its plugin
        gated = [qp for qp in s.queue.pending_pod_infos() if qp.gated]
        assert all("QuotaAdmission" in qp.unschedulable_plugins
                   for qp in gated)

    def test_cpu_dimension_gates(self):
        store = ClusterStore()
        nodes(store)
        quota(store, "team-a", {QUOTA_CPU: 2000})
        s, clock = sched_with_clock(store)
        for i in range(4):
            pod(store, f"p{i}", ns="team-a", cpu="1")  # 1000m each
        settle(s, clock)
        assert sum(1 for p in store.pods.values() if p.spec.node_name) == 2

    def test_usage_seeds_from_bound_pods(self):
        """A restarted scheduler resumes with true ledger usage: pods bound
        before it started still count."""
        store = ClusterStore()
        nodes(store)
        quota(store, "team-a", {QUOTA_PODS: 2})
        ensure_ns(store, "team-a")
        pre = make_pod("pre", namespace="team-a").req({"cpu": "1"}).obj()
        pre.spec.node_name = "n0"  # bound by a previous incarnation
        store.create_pod(pre)
        s, clock = sched_with_clock(store)
        for i in range(3):
            pod(store, f"p{i}", ns="team-a")
        settle(s, clock)
        newly = [p for p in store.pods.values()
                 if p.spec.node_name and p.meta.name != "pre"]
        assert len(newly) == 1  # 1 slot of headroom, not 2

    def test_delete_releases_and_reactivates(self):
        store = ClusterStore()
        nodes(store)
        quota(store, "team-a", {QUOTA_PODS: 1})
        s, clock = sched_with_clock(store)
        pod(store, "p0", ns="team-a")
        pod(store, "p1", ns="team-a")
        settle(s, clock)
        assert s.queue.pending_pods()["gated"] == 1
        bound = next(p for p in store.pods.values() if p.spec.node_name)
        store.delete_pod(bound.key())
        settle(s, clock)
        assert s.queue.pending_pods()["gated"] == 0
        assert sum(1 for p in store.pods.values() if p.spec.node_name) == 1

    def test_raising_quota_reactivates(self):
        store = ClusterStore()
        nodes(store)
        sq = quota(store, "team-a", {QUOTA_PODS: 1})
        s, clock = sched_with_clock(store)
        for i in range(3):
            pod(store, f"p{i}", ns="team-a")
        settle(s, clock)
        assert s.queue.pending_pods()["gated"] == 2
        store.update_object("SchedulingQuota", dataclasses.replace(
            sq, hard={QUOTA_PODS: 3}))
        settle(s, clock)
        assert s.queue.pending_pods()["gated"] == 0
        assert sum(1 for p in store.pods.values() if p.spec.node_name) == 3

    def test_raising_quota_reactivates_under_informers(self):
        """The production topology (cmd/server wires shared informers): the
        SchedulingQuota store handler must be registered there too — gated
        pods are exempt from the timeout flush, so the quota-change queue
        move is their ONLY wake-up when an admin raises the cap."""
        from kubernetes_tpu.client.informer import SharedInformerFactory

        store = ClusterStore()
        nodes(store)
        sq = quota(store, "team-a", {QUOTA_PODS: 1})
        clock = FakeClock()
        s = Scheduler(store, now_fn=clock, pod_initial_backoff=0.1,
                      pod_max_backoff=0.5,
                      informer_factory=SharedInformerFactory(store))
        for i in range(3):
            pod(store, f"p{i}", ns="team-a")
        settle(s, clock)
        assert s.queue.pending_pods()["gated"] == 2
        store.update_object("SchedulingQuota", dataclasses.replace(
            sq, hard={QUOTA_PODS: 3}))
        settle(s, clock)
        assert s.queue.pending_pods()["gated"] == 0
        assert sum(1 for p in store.pods.values() if p.spec.node_name) == 3

    def test_zero_oversubscription_under_settle(self):
        """The ledger never exceeds hard at any instant: Reserve is the
        charge, so admitted usage is checked before every assume."""
        store = ClusterStore()
        nodes(store)
        quota(store, "team-a", {QUOTA_PODS: 3, QUOTA_CPU: 2500})
        s, clock = sched_with_clock(store)
        plugin = next(iter(s.profiles.values())).plugin("QuotaAdmission")
        for i in range(8):
            pod(store, f"p{i}", ns="team-a", cpu="1")
        for _ in range(80):
            s.schedule_one()
            clock.advance(0.2)
            used = plugin.usage("team-a")
            assert used.get(QUOTA_PODS, 0) <= 3
            assert used.get(QUOTA_CPU, 0) <= 2500
        assert sum(1 for p in store.pods.values() if p.spec.node_name) == 2

    def test_rejected_counted_once_per_episode(self):
        """The decisions counter records pod-level outcomes: a parked pod
        re-checked by every wave/flush/probe still counts ONE rejection."""
        store = ClusterStore()
        nodes(store)
        quota(store, "team-a", {QUOTA_PODS: 1})
        s, clock = sched_with_clock(store)
        pod(store, "p0", ns="team-a")
        pod(store, "p1", ns="team-a")
        settle(s, clock)
        m = s.smetrics.quota_decisions
        assert m.labels("team-a", "rejected") == 1
        clock.advance(400.0)  # timeout flush re-runs the gate on p1
        s.queue.flush_unschedulable_left_over()
        settle(s, clock)
        assert m.labels("team-a", "rejected") == 1

    def test_multi_profile_shares_one_ledger(self):
        """Reserve charges land in the pod's own profile's QuotaAdmission;
        with two profiles both instances must read ONE cluster ledger or
        the release wave / fair-share weights undercount usage."""
        store = ClusterStore()
        nodes(store)
        quota(store, "team-a", {QUOTA_PODS: 2})
        s, clock = sched_with_clock(
            store, profiles={"default-scheduler": {}, "second": {}})
        ensure_ns(store, "team-a")
        for i in range(2):  # fill the quota through the SECOND profile
            store.create_pod(make_pod(f"p{i}", namespace="team-a")
                             .req({"cpu": "1", "memory": "1Gi"})
                             .scheduler_name("second").obj())
        settle(s, clock)
        assert sum(1 for p in store.pods.values() if p.spec.node_name) == 2
        first = s.profiles["default-scheduler"].plugin("QuotaAdmission")
        second = s.profiles["second"].plugin("QuotaAdmission")
        assert first.usage("team-a")[QUOTA_PODS] == 2
        assert first.usage("team-a") == second.usage("team-a")
        # the default profile gates its pod against the same ledger
        pod(store, "p2", ns="team-a")
        settle(s, clock)
        assert s.queue.pending_pods()["gated"] == 1

    def test_quota_metrics_live(self):
        store = ClusterStore()
        nodes(store)
        quota(store, "team-a", {QUOTA_PODS: 1})
        s, clock = sched_with_clock(store)
        pod(store, "p0", ns="team-a")
        pod(store, "p1", ns="team-a")
        settle(s, clock)
        m = s.smetrics
        assert m.quota_usage.labels("team-a", QUOTA_PODS) == 1
        assert m.quota_decisions.labels("team-a", "admitted") >= 1
        assert m.quota_decisions.labels("team-a", "rejected") >= 1
        store.delete_pod(next(
            p for p in store.pods.values() if p.spec.node_name).key())
        settle(s, clock)
        assert m.quota_released_pods.labels("team-a") >= 1


class TestReactivationThrash:
    """Satellite: reject_waiting_pod / quota-release reactivation must not
    fire a queue move for pods in namespaces still over quota."""

    def test_pod_delete_wave_skips_still_over_quota_namespace(self):
        store = ClusterStore()
        nodes(store)
        quota(store, "team-a", {QUOTA_PODS: 1})
        s, clock = sched_with_clock(store)
        for i in range(6):
            pod(store, f"p{i}", ns="team-a")
        # an unrelated tenant binds + deletes, firing AssignedPodDelete
        # reactivation waves — the classic thrash trigger
        settle(s, clock)
        assert s.queue.pending_pods()["gated"] == 5
        incoming = s.smetrics.queue_incoming_pods
        before = sum(incoming.labels(q, e) for q, e in incoming.label_sets()
                     if q in ("active", "backoff"))
        other = pod(store, "noise", ns="default")
        settle(s, clock)
        store.delete_pod(other.key())  # bound-pod delete → POD_DELETE wave
        after_del = s.queue.pending_pods()
        assert after_del["gated"] == 5  # nobody in team-a moved
        after = sum(incoming.labels(q, e) for q, e in incoming.label_sets()
                    if q in ("active", "backoff"))
        # the only active/backoff traffic was the noise pod itself
        assert after - before <= 2

    def test_release_admits_exactly_the_freed_headroom(self):
        """The shadow-ledger release gate: freeing ONE pod slot moves ONE
        gated pod toward activeQ, not the whole parked backlog."""
        store = ClusterStore()
        nodes(store)
        quota(store, "team-a", {QUOTA_PODS: 1})
        s, clock = sched_with_clock(store)
        for i in range(6):
            pod(store, f"p{i}", ns="team-a")
        settle(s, clock)
        assert s.queue.pending_pods()["gated"] == 5
        bound = next(p for p in store.pods.values() if p.spec.node_name)
        store.delete_pod(bound.key())
        pending = s.queue.pending_pods()  # before any new cycle runs
        assert pending["active"] + pending["backoff"] == 1
        assert pending["gated"] == 4

    def test_unschedulable_timeout_flush_exempts_gated(self):
        store = ClusterStore()
        nodes(store)
        quota(store, "team-a", {QUOTA_PODS: 1})
        s, clock = sched_with_clock(store)
        for i in range(3):
            pod(store, f"p{i}", ns="team-a")
        settle(s, clock)
        assert s.queue.pending_pods()["gated"] == 2
        clock.advance(400.0)  # past DEFAULT_UNSCHEDULABLE_TIMEOUT
        s.queue.flush_unschedulable_left_over()
        pending = s.queue.pending_pods()
        assert pending["gated"] == 2
        assert pending["active"] == 0


# ---------------------------------------------------------------------------
# fair-share dequeueing


class TestFairShare:
    def _flooded_queue(self, weights, per_tenant=30):
        store = ClusterStore()
        for ns, w in weights.items():
            quota(store, ns, {QUOTA_PODS: 10 ** 6}, weight=w)
        s, clock = sched_with_clock(store)
        for ns in weights:
            for i in range(per_tenant):
                pod(store, f"{ns}-p{i}", ns=ns)
        return store, s, clock

    def test_drr_shares_proportional_to_weight(self):
        weights = {"team-a": 1, "team-b": 2, "team-c": 4}
        _store, s, _clock = self._flooded_queue(weights)
        popped = {ns: 0 for ns in weights}
        for _ in range(56):  # a prefix window while all stay backlogged
            qp = s.queue.pop()
            popped[qp.pod.meta.namespace] += 1
        total = sum(popped.values())
        for ns, w in weights.items():
            fair = w / sum(weights.values())
            share = popped[ns] / total
            assert abs(share - fair) <= 0.2 * fair + 2 / total, \
                f"{ns}: share {share:.2f} vs fair {fair:.2f} ({popped})"

    def test_flooding_tenant_cannot_starve(self):
        """One tenant floods 10x the pods; the other's drain rate is still
        its weight share, not its backlog share."""
        store = ClusterStore()
        quota(store, "flood", {QUOTA_PODS: 10 ** 6}, weight=1)
        quota(store, "calm", {QUOTA_PODS: 10 ** 6}, weight=1)
        s, clock = sched_with_clock(store)
        for i in range(200):
            pod(store, f"f{i}", ns="flood")
        for i in range(20):
            pod(store, f"c{i}", ns="calm")
        calm_positions = []
        for pos in range(80):
            qp = s.queue.pop()
            if qp.pod.meta.namespace == "calm":
                calm_positions.append(pos)
        # all 20 calm pods drained inside the first ~half of the window
        assert len(calm_positions) == 20
        assert calm_positions[-1] < 60

    def test_gang_members_stay_adjacent_within_turn(self):
        """A gang bigger than the DRR quantum holds the tenant's turn (gang
        continuation): members never interleave with another tenant."""
        store = ClusterStore()
        from kubernetes_tpu.api.types import PodGroup as PG

        quota(store, "team-a", {QUOTA_PODS: 10 ** 6}, weight=1)
        quota(store, "team-b", {QUOTA_PODS: 10 ** 6}, weight=1)
        store.create_object("PodGroup", PG(
            meta=ObjectMeta(name="gang", namespace="team-a"), min_member=8))
        s, clock = sched_with_clock(store)
        for i in range(8):
            pod(store, f"g{i}", ns="team-a", group="gang")
        for i in range(16):
            pod(store, f"b{i}", ns="team-b")
        order = [s.queue.pop() for _ in range(24)]
        gang_positions = [i for i, qp in enumerate(order)
                          if qp.pod.meta.labels.get(
                              "scheduling.x-k8s.io/pod-group")]
        assert gang_positions == list(range(
            gang_positions[0], gang_positions[0] + 8))

    def test_solo_tenant_accrues_no_debt(self):
        """Uncontended pops (single-bucket fast path) charge no deficit:
        a tenant that drained 50 pods alone is NOT starved for 50 pops of
        payback when a second tenant appears — shares are proportional
        immediately."""
        store = ClusterStore()
        quota(store, "solo", {QUOTA_PODS: 10 ** 6}, weight=1)
        quota(store, "late", {QUOTA_PODS: 10 ** 6}, weight=1)
        s, clock = sched_with_clock(store)
        for i in range(100):
            pod(store, f"s{i}", ns="solo")
        for _ in range(50):  # solo drains alone
            assert s.queue.pop().pod.meta.namespace == "solo"
        assert s.queue._deficit.get("solo", 0.0) >= 0.0  # no banked debt
        for i in range(50):
            pod(store, f"l{i}", ns="late")
        popped = {"solo": 0, "late": 0}
        for _ in range(40):
            popped[s.queue.pop().pod.meta.namespace] += 1
        # equal weights: the former solo tenant gets ~half of the window
        assert popped["solo"] >= 14, popped

    def test_priority_order_preserved_within_tenant(self):
        store = ClusterStore()
        quota(store, "team-a", {QUOTA_PODS: 10 ** 6}, weight=1)
        s, clock = sched_with_clock(store)
        pod(store, "low", ns="team-a", prio=0)
        pod(store, "high", ns="team-a", prio=100)
        first = s.queue.pop()
        assert first.pod.meta.name == "high"

    def test_no_quota_namespaces_keep_legacy_order(self):
        """Without tenants the queue is byte-identical to the legacy single
        heap: strict (-priority, timestamp) order."""
        store = ClusterStore()
        s, clock = sched_with_clock(store)
        pod(store, "a", prio=1)
        clock.advance(0.01)
        pod(store, "b", prio=5)
        clock.advance(0.01)
        pod(store, "c", prio=1)
        names = [s.queue.pop().pod.meta.name for _ in range(3)]
        assert names == ["b", "a", "c"]
        assert s.queue._active_ns == {}  # the DRR layer never engaged

    def test_fair_share_turn_metric(self):
        weights = {"team-a": 1, "team-b": 1}
        _store, s, _clock = self._flooded_queue(weights, per_tenant=10)
        for _ in range(20):
            s.queue.pop()
        m = s.smetrics.fair_share_turns
        assert m.labels("team-a") >= 1
        assert m.labels("team-b") >= 1


# ---------------------------------------------------------------------------
# the batched path


class TestBatchedQuotaGate:
    def test_tpu_precheck_fails_over_quota_pod_without_device_slot(self):
        from kubernetes_tpu.backend.tpu_scheduler import TPUScheduler

        store = ClusterStore()
        nodes(store)
        quota(store, "team-a", {QUOTA_PODS: 2})
        sched = TPUScheduler(store, batch_size=16)
        for i in range(5):
            pod(store, f"p{i}", ns="team-a")
        sched.run_batched_until_settled()
        assert sum(1 for p in store.pods.values() if p.spec.node_name) == 2
        pending = sched.queue.pending_pods()
        assert pending["gated"] + pending["unschedulable"] == 3
        plugin = next(iter(sched.profiles.values())).plugin("QuotaAdmission")
        assert plugin.usage("team-a")[QUOTA_PODS] == 2

    def test_tpu_release_reactivation(self):
        from kubernetes_tpu.backend.tpu_scheduler import TPUScheduler

        store = ClusterStore()
        nodes(store)
        quota(store, "team-a", {QUOTA_PODS: 1})
        sched = TPUScheduler(store, batch_size=16)
        pod(store, "p0", ns="team-a")
        pod(store, "p1", ns="team-a")
        sched.run_batched_until_settled()
        bound = [p for p in store.pods.values() if p.spec.node_name]
        assert len(bound) == 1
        store.delete_pod(bound[0].key())
        sched.run_batched_until_settled()
        assert sum(1 for p in store.pods.values() if p.spec.node_name) == 1


class TestPreFilterStatus:
    def test_quota_exceeded_is_unresolvable_and_typed(self):
        store = ClusterStore()
        quota(store, "team-a", {QUOTA_PODS: 0})
        plugin = QuotaAdmission(client=store)
        p = make_pod("p", namespace="team-a").req({"cpu": "1"}).obj()
        _r, st = plugin.pre_filter(None, p)
        assert not st.is_success()
        assert st.code == 3  # UNSCHEDULABLE_AND_UNRESOLVABLE: no preemption
        assert any(ERR_REASON_QUOTA_EXCEEDED in r for r in st.reasons)


# ---------------------------------------------------------------------------
# cohort borrowing (ISSUE 19)


def cohort_pair(store, lender_cap=4, borrower_cap=2, cohort="pool"):
    quota(store, "lend", {QUOTA_PODS: lender_cap}, weight=2, cohort=cohort)
    quota(store, "hungry", {QUOTA_PODS: borrower_cap}, cohort=cohort)


class TestCohortBorrowing:
    def test_borrow_grants_idle_headroom(self):
        """A tenant over its own cap charges the cohort's idle guaranteed
        headroom; the loans are recorded per pod, newest-seq-last."""
        store = ClusterStore()
        nodes(store)
        cohort_pair(store)  # pool = 4 + 2 = 6
        s, clock = sched_with_clock(store)
        for i in range(7):
            pod(store, f"b{i}", ns="hungry")
        settle(s, clock)
        assert sum(1 for p in store.pods.values() if p.spec.node_name) == 6
        assert s.queue.pending_pods()["gated"] == 1
        plugin = next(iter(s.profiles.values())).plugin("QuotaAdmission")
        assert plugin.usage("hungry")[QUOTA_PODS] == 6
        assert plugin.borrowed("hungry")[QUOTA_PODS] == 4
        assert len(plugin._loans) == 4
        assert plugin.cohort_headroom("pool").get(QUOTA_PODS, 0) == 0
        assert s.smetrics.quota_borrowed.labels("hungry", QUOTA_PODS) == 4
        assert s.smetrics.quota_decisions.labels("hungry", "borrowed") == 4

    def test_no_borrowing_without_cohort(self):
        store = ClusterStore()
        nodes(store)
        cohort_pair(store, cohort="")
        s, clock = sched_with_clock(store)
        for i in range(7):
            pod(store, f"b{i}", ns="hungry")
        settle(s, clock)
        assert sum(1 for p in store.pods.values() if p.spec.node_name) == 2

    def test_release_of_loan_decrements_borrowed(self):
        store = ClusterStore()
        nodes(store)
        cohort_pair(store)
        s, clock = sched_with_clock(store)
        for i in range(4):
            pod(store, f"b{i}", ns="hungry")
        settle(s, clock)
        plugin = next(iter(s.profiles.values())).plugin("QuotaAdmission")
        assert plugin.borrowed("hungry")[QUOTA_PODS] == 2
        loan_key = next(iter(plugin._loans))
        store.delete_pod(loan_key)
        settle(s, clock)
        assert plugin.borrowed("hungry")[QUOTA_PODS] == 1
        assert plugin.usage("hungry")[QUOTA_PODS] == 3

    def test_lender_wakeup_reclaims_newest_loans_first(self):
        """The lender's own arrivals, blocked only by outstanding loans,
        trigger reclaim-by-preemption of the NEWEST loans — and only as
        many as the aggregate lender demand needs."""
        store = ClusterStore()
        nodes(store)
        cohort_pair(store)  # lend cap 4, hungry cap 2, pool 6
        s, clock = sched_with_clock(store)
        for i in range(6):
            pod(store, f"b{i}", ns="hungry")
            settle(s, clock, rounds=4)  # serialize: loan seq order == i
        settle(s, clock)
        plugin = next(iter(s.profiles.values())).plugin("QuotaAdmission")
        assert plugin.borrowed("hungry")[QUOTA_PODS] == 4
        oldest = sorted(plugin._loans.items(), key=lambda kv: kv[1][2])
        oldest_keys = [k for k, _v in oldest[:2]]
        # the lender wakes up with 2 pods: own-fit, pool exhausted
        pod(store, "l0", ns="lend")
        pod(store, "l1", ns="lend")
        churn(s, clock, rounds=120)
        assert plugin.reclaims_executed >= 1
        lender_bound = [p for p in store.pods.values()
                        if p.spec.node_name and p.meta.namespace == "lend"]
        assert len(lender_bound) == 2
        # exactly the aggregate demand was reclaimed, newest loans first:
        # the two OLDEST loans survive
        assert sorted(plugin._loans) == sorted(oldest_keys)
        assert plugin.borrowed("hungry")[QUOTA_PODS] == 2
        assert s.smetrics.quota_reclaims.labels("evicted") >= 1
        # pool invariant held: used never exceeds guaranteed
        caps, used = plugin.cohort_state("pool")
        assert used[QUOTA_PODS] <= caps[QUOTA_PODS]

    def test_borrowing_frozen_while_lender_demand_pending(self):
        """Outstanding lender demand blocks NEW loans: freed capacity is
        spoken for, and must not be re-stolen ahead of the lender."""
        store = ClusterStore()
        quota(store, "lend", {QUOTA_PODS: 2}, cohort="pool")
        quota(store, "hungry", {QUOTA_PODS: 1}, cohort="pool")
        plugin = QuotaAdmission(client=store)
        ensure_ns(store, "hungry")
        b0 = make_pod("b0", namespace="hungry").req({"cpu": "1"}).obj()
        b0.spec.node_name = "n0"
        store.create_pod(b0)
        b1 = make_pod("b1", namespace="hungry").req({"cpu": "1"}).obj()
        b1.spec.node_name = "n0"
        store.create_pod(b1)
        plugin.pod_observed_bound(b0)
        plugin.pod_observed_bound(b1)  # 1 own + 1 loan, pool 3 used... 
        # lender pod own-fits but one more would exceed the pool? no —
        # pool = 3, used 2: the lender pod fits; fill the pool first
        b2 = make_pod("b2", namespace="hungry").req({"cpu": "1"}).obj()
        b2.spec.node_name = "n0"
        store.create_pod(b2)
        plugin.pod_observed_bound(b2)
        assert plugin.borrowed("hungry")[QUOTA_PODS] == 2
        lp = make_pod("l0", namespace="lend").req({"cpu": "1"}).obj()
        store.create_pod(lp)
        st = plugin.pre_enqueue_status(lp)
        assert st is not None and "cohort exhausted" in str(st.reasons)
        assert plugin._reclaim_demand.get("pool")
        # a loan is released (borrower pod gone) — the freed slot must NOT
        # be borrowable while the lender's demand is pending
        loan_key = sorted(plugin._loans)[0]
        store.delete_pod(loan_key)
        plugin.pod_deleted(store.get_pod(loan_key) or b2
                           if loan_key != b2.key() else b2)
        nb = make_pod("b3", namespace="hungry").req({"cpu": "1"}).obj()
        store.create_pod(nb)
        st2 = plugin.pre_enqueue_status(nb)
        assert st2 is not None  # borrow frozen
        # the lender pod, by contrast, admits into the freed slot
        assert plugin.pre_enqueue_status(lp) is None

    def test_reclaim_cooldown_paces_same_demand(self):
        """A pass that cannot free enough (no loans left to evict) does
        not re-run at sweep cadence for the SAME demand — the cooldown
        paces it; fresh demand bypasses the cooldown."""
        store = ClusterStore()
        nodes(store)
        cohort_pair(store)
        s, clock = sched_with_clock(store)
        plugin = next(iter(s.profiles.values())).plugin("QuotaAdmission")
        evict_calls = []
        real_evict = plugin.on_evict
        plugin.on_evict = lambda pods, reason: (
            evict_calls.append([p.key() for p in pods]),
            real_evict(pods, reason))[1]
        for i in range(6):
            pod(store, f"b{i}", ns="hungry")
        settle(s, clock)
        pod(store, "l0", ns="lend")
        churn(s, clock, rounds=40)
        assert plugin.reclaims_executed == 1
        n_first = len(evict_calls)
        # the demand is satisfied; repeated sweeps with no new demand
        # must not evict again
        for _ in range(30):
            plugin.run_reclaim(now=clock())
            clock.advance(0.3)
        assert len(evict_calls) == n_first

    def test_reclaim_breaker_suspends_on_slo_regression(self):
        """PR-17 pattern: a guard_fn that judges the wave a lender-SLO
        regression feeds the breaker; at the threshold the breaker opens
        and reclaim suspends (event + metric) instead of storming."""
        store = ClusterStore()
        nodes(store)
        cohort_pair(store, lender_cap=6, borrower_cap=2)
        s, clock = sched_with_clock(store)
        plugin = next(iter(s.profiles.values())).plugin("QuotaAdmission")
        plugin.reclaim_guard_fn = lambda: False  # every wave "regresses"
        plugin.reclaim_cooldown_s = 0.0
        for i in range(8):
            pod(store, f"b{i}", ns="hungry")
        settle(s, clock)
        assert plugin.borrowed("hungry")[QUOTA_PODS] == 6
        # three lender wake-ups, one pod each: passes 1+2 execute and
        # record failures; the third finds the breaker open
        for i in range(3):
            pod(store, f"l{i}", ns="lend")
            churn(s, clock, rounds=40)
        assert plugin.reclaim_breaker.state == "open"
        assert plugin.reclaim_suspended is True
        assert s.smetrics.quota_reclaims.labels("suspended") >= 1
        assert plugin.reclaims_executed == 2

    def test_gang_never_half_admitted_past_quota(self):
        """Gang members price the remaining gang aggregate against quota
        AND cohort headroom: a gang that cannot fully fit the pool is
        admitted zero-members, never partially."""
        from kubernetes_tpu.api.types import PodGroup

        store = ClusterStore()
        nodes(store)
        cohort_pair(store, lender_cap=2, borrower_cap=1)  # pool = 3
        store.create_object("PodGroup", PodGroup(
            meta=ObjectMeta(name="g4", namespace="hungry"), min_member=4))
        s, clock = sched_with_clock(store)
        for i in range(4):
            pod(store, f"g{i}", ns="hungry", group="g4")
        settle(s, clock)
        assert sum(1 for p in store.pods.values() if p.spec.node_name) == 0
        plugin = next(iter(s.profiles.values())).plugin("QuotaAdmission")
        assert plugin.usage("hungry").get(QUOTA_PODS, 0) == 0
        # a gang that fits the pool whole admits whole
        store.create_object("PodGroup", PodGroup(
            meta=ObjectMeta(name="g3", namespace="hungry"), min_member=3))
        for i in range(3):
            pod(store, f"h{i}", ns="hungry", group="g3")
        settle(s, clock)
        bound = [p for p in store.pods.values()
                 if p.spec.node_name and p.meta.name.startswith("h")]
        assert len(bound) == 3

    def test_borrower_delete_wakes_gated_lender(self):
        """_fire_release fans out to every cohort member: the lender's
        gated pod lives in a DIFFERENT namespace than the freed loan."""
        store = ClusterStore()
        nodes(store)
        cohort_pair(store, lender_cap=2, borrower_cap=1)
        s, clock = sched_with_clock(store)
        for i in range(3):
            pod(store, f"b{i}", ns="hungry")
        settle(s, clock)
        plugin = next(iter(s.profiles.values())).plugin("QuotaAdmission")
        assert plugin.borrowed("hungry")[QUOTA_PODS] == 2
        # stop the reclaim sweep: this test isolates the release fan-out
        plugin.on_evict = None
        pod(store, "l0", ns="lend")
        settle(s, clock, rounds=10)
        assert s.queue.pending_pods()["gated"] == 1
        loan_key = max(plugin._loans.items(), key=lambda kv: kv[1][2])[0]
        store.delete_pod(loan_key)
        settle(s, clock)
        lender_bound = [p for p in store.pods.values()
                        if p.spec.node_name and p.meta.namespace == "lend"]
        assert len(lender_bound) == 1

    def test_dump_carries_cohort_view(self):
        store = ClusterStore()
        nodes(store)
        cohort_pair(store)
        s, clock = sched_with_clock(store)
        for i in range(4):
            pod(store, f"b{i}", ns="hungry")
        settle(s, clock)
        plugin = next(iter(s.profiles.values())).plugin("QuotaAdmission")
        out = plugin.dump()
        assert out["hungry"]["borrowed"][QUOTA_PODS] == 2
        assert out["hungry"]["cohort"] == "pool"
        pool = out["_cohorts"]["pool"]
        assert sorted(pool["members"]) == ["hungry", "lend"]
        assert pool["guaranteed"][QUOTA_PODS] == 6
        assert pool["lent"][QUOTA_PODS] == 2
        assert pool["headroom"][QUOTA_PODS] == 2
        assert len(pool["loans"]) == 2
        # newest first
        seqs = [plugin._loans[ln["pod"]][2] for ln in pool["loans"]]
        assert seqs == sorted(seqs, reverse=True)
        assert pool["reclaim_breaker"]["state"] == "closed"


class TestBorrowRestartReseed:
    def test_mid_borrow_restart_reconstructs_loan_split(self):
        """ISSUE 19 satellite: a scheduler taking over mid-borrow reseeds
        the ledger charge-order own-quota-first-then-cohort, so the
        outstanding-loan split survives restart — without it borrowed
        capacity double-counts as both used and lendable."""
        store = ClusterStore()
        nodes(store)
        cohort_pair(store, lender_cap=3, borrower_cap=2)  # pool = 5
        ensure_ns(store, "hungry")
        for i in range(4):  # bound by the previous incarnation: 2 own + 2 loans
            p = make_pod(f"pre{i}", namespace="hungry").req(
                {"cpu": "1", "memory": "1Gi"}).obj()
            p.spec.node_name = f"n{i % 4}"
            store.create_pod(p)
        s, clock = sched_with_clock(store)
        plugin = next(iter(s.profiles.values())).plugin("QuotaAdmission")
        assert plugin.usage("hungry")[QUOTA_PODS] == 4
        assert plugin.borrowed("hungry")[QUOTA_PODS] == 2
        assert len(plugin._loans) == 2
        # remaining pool headroom is exactly 1 — not 3: the loans are NOT
        # double-counted as lendable
        assert plugin.cohort_headroom("pool")[QUOTA_PODS] == 1
        pod(store, "b-new", ns="hungry")
        pod(store, "b-new2", ns="hungry")
        settle(s, clock)
        assert plugin.usage("hungry")[QUOTA_PODS] == 5
        assert s.queue.pending_pods()["gated"] == 1
        # and the lender's guarantee is still reclaimable after takeover:
        # its own pods preempt the reseeded loans
        for i in range(3):
            pod(store, f"l{i}", ns="lend")
        churn(s, clock, rounds=160)
        lender_bound = [p for p in store.pods.values()
                        if p.spec.node_name and p.meta.namespace == "lend"]
        assert len(lender_bound) == 3
        caps, used = plugin.cohort_state("pool")
        assert used[QUOTA_PODS] <= caps[QUOTA_PODS]
