"""kube-proxy depth (VERDICT r4 item 7): ClusterIP/NodePort dispatch,
ClientIP session affinity with timeout + stickiness under endpoint churn,
conntrack stale-flow cleanup, and the iptables-save / ipvsadm-save render
contracts diff-tested against recorded fixtures.

Reference: pkg/proxy/iptables/proxier.go:809 syncProxyRules,
pkg/proxy/ipvs/proxier.go, pkg/proxy/conntrack/cleanup.go.
"""

from kubernetes_tpu.api.types import (
    Endpoints, EndpointAddress, ObjectMeta, Service, ServicePort,
)
from kubernetes_tpu.api.corev1 import service_from, service_to
from kubernetes_tpu.apiserver import ClusterStore
from kubernetes_tpu.proxy import Proxier


def _svc(name="svc", **kw):
    kw.setdefault("selector", {"app": "a"})
    return Service(meta=ObjectMeta(name=name), **kw)


def _endpoints(name, *pods):
    return Endpoints(meta=ObjectMeta(name=name),
                     addresses=tuple(EndpointAddress(pod_key=p) for p in pods))


def _put_endpoints(store, eps):
    store._admit("Endpoints", eps)
    with store._lock:
        store._bump(eps)
        store.endpoints[eps.meta.key()] = eps


class TestDispatch:
    def _proxier(self, svc, *pods, t0=None):
        store = ClusterStore()
        store.create_service(svc)
        _put_endpoints(store, _endpoints(svc.meta.name, *pods))
        clock = {"t": 0.0}
        p = Proxier(store, now_fn=lambda: clock["t"])
        p.mark_dirty(svc.meta.key())
        p.sync_proxy_rules()
        return store, p, clock

    def test_cluster_ip_and_node_port_dispatch(self):
        svc = _svc(type="NodePort", cluster_ip="10.0.0.10",
                   ports=(ServicePort(name="http", port=80, target_port=8080,
                                      node_port=30080),))
        _, p, _ = self._proxier(svc, "default/a", "default/b")
        assert p.route_cluster_ip("10.0.0.10", 80) in ("default/a", "default/b")
        assert p.route_node_port(30080) in ("default/a", "default/b")
        assert p.route_cluster_ip("10.0.0.10", 81) is None
        assert p.route_node_port(31000) is None

    def test_round_robin_covers_backends(self):
        _, p, _ = self._proxier(_svc(), "default/a", "default/b", "default/c")
        assert {p.route("default/svc") for _ in range(3)} == {
            "default/a", "default/b", "default/c"}

    def test_client_ip_affinity_sticky_and_expiring(self):
        svc = _svc(session_affinity="ClientIP", session_affinity_timeout_s=100)
        store, p, clock = self._proxier(svc, "default/a", "default/b", "default/c")
        first = p.route("default/svc", client_ip="1.2.3.4")
        # sticky across many picks while other clients round-robin freely
        for _ in range(5):
            assert p.route("default/svc", client_ip="1.2.3.4") == first
        others = {p.route("default/svc", client_ip=f"9.9.9.{i}") for i in range(9)}
        assert len(others) > 1
        # timeout expiry: past the window the entry is re-drawn (and the
        # refreshed stamp keeps a hot client sticky indefinitely)
        clock["t"] = 101.0
        for _ in range(3):
            p.route("default/svc", client_ip="1.2.3.4")
        clock["t"] = 190.0  # < 90s since last touch: still inside the window
        assert p.route("default/svc", client_ip="1.2.3.4") in p.backends("default/svc")

    def test_affinity_survives_unrelated_churn_but_not_backend_removal(self):
        svc = _svc(session_affinity="ClientIP")
        store, p, clock = self._proxier(svc, "default/a", "default/b", "default/c")
        first = p.route("default/svc", client_ip="1.2.3.4")
        # unrelated churn: a NEW backend appears; the sticky entry survives
        survivors = [b for b in ("default/a", "default/b", "default/c")] + ["default/d"]
        _put_endpoints(store, _endpoints("svc", *survivors))
        p.mark_dirty("default/svc")
        p.sync_proxy_rules()
        assert p.route("default/svc", client_ip="1.2.3.4") == first
        # the sticky backend is removed: entry flushed, new pick lands on a
        # survivor and the conntrack flush records the dead backend
        remaining = [b for b in survivors if b != first]
        _put_endpoints(store, _endpoints("svc", *remaining))
        p.mark_dirty("default/svc")
        p.sync_proxy_rules()
        repick = p.route("default/svc", client_ip="1.2.3.4")
        assert repick in remaining
        assert first in p.conntrack_flushed

    def test_conntrack_flows_flushed_for_gone_backends(self):
        store, p, clock = self._proxier(_svc(), "default/a", "default/b")
        # establish flows for many clients (plain service: no affinity)
        hit = {p.route("default/svc", client_ip=f"10.0.0.{i}") for i in range(8)}
        assert hit == {"default/a", "default/b"}
        _put_endpoints(store, _endpoints("svc", "default/b"))
        p.mark_dirty("default/svc")
        p.sync_proxy_rules()
        assert "default/a" in p.conntrack_flushed
        # legacy API still reports the stale diff
        stale = p.stale_conntrack_entries({"default/svc": ("default/a", "default/b")})
        assert stale == ["default/a"]


IPTABLES_FIXTURE = """\
*nat
:KUBE-SERVICES - [0:0]
:KUBE-NODEPORTS - [0:0]
:KUBE-MARK-MASQ - [0:0]
:KUBE-SVC-82B3ADE9D00CD164 - [0:0]
:KUBE-SEP-FBCC4E78E6FABD22 - [0:0]
:KUBE-SEP-4FBE0F86686BCBDA - [0:0]
-A KUBE-MARK-MASQ -j MARK --set-xmark 0x4000/0x4000
-A KUBE-SERVICES -m addrtype --dst-type LOCAL -j KUBE-NODEPORTS
-A KUBE-SERVICES -d 10.0.0.10/32 -p tcp -m tcp --dport 80 -m comment --comment "default/web:http cluster IP" -j KUBE-SVC-82B3ADE9D00CD164
-A KUBE-NODEPORTS -p tcp -m tcp --dport 30080 -m comment --comment "default/web:http" -j KUBE-MARK-MASQ
-A KUBE-NODEPORTS -p tcp -m tcp --dport 30080 -j KUBE-SVC-82B3ADE9D00CD164
-A KUBE-SVC-82B3ADE9D00CD164 -m statistic --mode random --probability 0.5000000000 -j KUBE-SEP-FBCC4E78E6FABD22
-A KUBE-SEP-FBCC4E78E6FABD22 -m comment --comment "default/a" -j DNAT --to-destination default/a
-A KUBE-SVC-82B3ADE9D00CD164 -j KUBE-SEP-4FBE0F86686BCBDA
-A KUBE-SEP-4FBE0F86686BCBDA -m comment --comment "default/b" -j DNAT --to-destination default/b
COMMIT
"""


class TestRenderFixtures:
    def _build(self, **svc_kw):
        store = ClusterStore()
        svc = Service(meta=ObjectMeta(name="web"), selector={"app": "web"}, **svc_kw)
        store.create_service(svc)
        _put_endpoints(store, _endpoints("web", "default/a", "default/b"))
        p = Proxier(store)
        p.mark_dirty("default/web")
        p.sync_proxy_rules()
        return p

    def test_iptables_save_matches_recorded_fixture(self):
        p = self._build(type="NodePort", cluster_ip="10.0.0.10",
                        ports=(ServicePort(name="http", port=80,
                                           target_port=8080, node_port=30080),))
        assert p.render_iptables() == IPTABLES_FIXTURE

    def test_iptables_affinity_uses_recent_module(self):
        p = self._build(cluster_ip="10.0.0.10",
                        ports=(ServicePort(port=80),),
                        session_affinity="ClientIP",
                        session_affinity_timeout_s=600)
        text = p.render_iptables()
        assert "-m recent" in text and "--rcheck --seconds 600" in text
        assert text.count("--set") >= 2  # one recent-set per endpoint

    def test_ipvs_save_virtual_servers_and_persistence(self):
        p = self._build(type="NodePort", cluster_ip="10.0.0.10",
                        ports=(ServicePort(port=80, node_port=30080),),
                        session_affinity="ClientIP",
                        session_affinity_timeout_s=300)
        text = p.render_ipvs()
        assert "-A -t 10.0.0.10:80 -s rr -p 300" in text
        assert "-A -t nodeport:30080 -s rr -p 300" in text
        assert text.count("-r default/a") == 2  # one real server per vserver
        assert text.count("-r default/b") == 2

    def test_udp_ports_render_as_udp(self):
        p = self._build(cluster_ip="10.0.0.10",
                        ports=(ServicePort(port=53, protocol="UDP"),))
        assert "-A -u 10.0.0.10:53 -s rr" in p.render_ipvs()
        assert "-p udp -m udp --dport 53" in p.render_iptables()


class TestServiceWire:
    def test_service_round_trip(self):
        svc = Service(
            meta=ObjectMeta(name="web"), selector={"app": "web"},
            external_ips=("1.2.3.4",), type="NodePort", cluster_ip="10.0.0.9",
            ports=(ServicePort(name="http", protocol="TCP", port=80,
                               target_port=8080, node_port=30080),),
            session_affinity="ClientIP", session_affinity_timeout_s=900,
        )
        doc = service_to(svc)
        back = service_from(doc)
        assert back.type == "NodePort" and back.cluster_ip == "10.0.0.9"
        assert back.ports == svc.ports
        assert back.session_affinity == "ClientIP"
        assert back.session_affinity_timeout_s == 900
        assert back.external_ips == ("1.2.3.4",)

    def test_headless_cluster_ip_none(self):
        back = service_from({"metadata": {"name": "hl"},
                             "spec": {"clusterIP": "None"}})
        assert back.cluster_ip == ""
