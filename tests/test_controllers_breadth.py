"""Round-3 controller breadth: serviceaccount, root-ca-cert-publisher,
ttl-after-finished, pvc/pv-protection (finalizer-gated deletes), nodeipam,
endpointslicemirroring, ephemeral-volume, horizontalpodautoscaling
(controllermanager.go:412 NewControllerInitializers parity)."""

import dataclasses

import pytest

from kubernetes_tpu.api.types import (
    Deployment,
    EndpointAddress,
    Endpoints,
    HorizontalPodAutoscaler,
    Job,
    Namespace,
    ObjectMeta,
    PersistentVolume,
    PersistentVolumeClaim,
    Service,
)
from kubernetes_tpu.api.wrappers import make_node, make_pod
from kubernetes_tpu.apiserver.store import ClusterStore
from kubernetes_tpu.client.informer import SharedInformerFactory
from kubernetes_tpu.controllers import ControllerManager
from kubernetes_tpu.controllers.auxiliary import (
    PVC_PROTECTION_FINALIZER,
    ROOT_CA_CONFIGMAP,
)
from kubernetes_tpu.utils.clock import FakeClock


def make_manager(store, controllers, clock=None):
    return ControllerManager(store, factory=SharedInformerFactory(store),
                             controllers=controllers,
                             now_fn=clock or FakeClock())


class TestServiceAccountAndRootCA:
    def test_default_sa_and_ca_configmap_created_per_namespace(self):
        store = ClusterStore()
        m = make_manager(store, ["serviceaccount", "root-ca-cert-publisher"])
        store.create_namespace(Namespace(meta=ObjectMeta(name="team-a")))
        m.settle()
        assert "team-a/default" in store.service_accounts
        cm = store.get_object("ConfigMap", f"team-a/{ROOT_CA_CONFIGMAP}")
        assert cm is not None and "ca.crt" in cm.data

    def test_recreated_after_deletion(self):
        store = ClusterStore()
        m = make_manager(store, ["serviceaccount"])
        store.create_namespace(Namespace(meta=ObjectMeta(name="team-b")))
        m.settle()
        store.delete_object("ServiceAccount", "team-b/default")
        m.settle()
        assert "team-b/default" in store.service_accounts


class TestTTLAfterFinished:
    def test_finished_job_deleted_after_ttl(self):
        store = ClusterStore()
        clock = FakeClock()
        m = make_manager(store, ["ttlafterfinished"], clock=clock)
        job = Job(meta=ObjectMeta(name="burn"), condition="Complete",
                  completion_time=clock(), ttl_seconds_after_finished=60)
        store.create_object("Job", job)
        m.settle()
        assert store.get_object("Job", "default/burn") is not None
        clock.advance(61)
        m.settle()
        assert store.get_object("Job", "default/burn") is None

    def test_no_ttl_means_kept(self):
        store = ClusterStore()
        clock = FakeClock()
        m = make_manager(store, ["ttlafterfinished"], clock=clock)
        store.create_object("Job", Job(meta=ObjectMeta(name="keep"),
                                       condition="Complete", completion_time=clock()))
        clock.advance(10000)
        m.settle()
        assert store.get_object("Job", "default/keep") is not None


class TestPVCProtection:
    def test_delete_deferred_while_pod_uses_claim(self):
        store = ClusterStore()
        m = make_manager(store, ["pvcprotection"])
        store.create_pvc(PersistentVolumeClaim(meta=ObjectMeta(name="data")))
        m.settle()
        pvc = store.get_object("PersistentVolumeClaim", "default/data")
        assert PVC_PROTECTION_FINALIZER in pvc.meta.finalizers
        store.create_pod(make_pod("user").req({"cpu": "1"}).pvc("data").obj())
        m.settle()
        store.delete_object("PersistentVolumeClaim", "default/data")
        m.settle()
        # still present: terminating but protected
        pvc = store.get_object("PersistentVolumeClaim", "default/data")
        assert pvc is not None and pvc.meta.deletion_timestamp > 0
        store.delete_pod("default/user")
        m.settle()
        assert store.get_object("PersistentVolumeClaim", "default/data") is None

    def test_unused_claim_deletes_immediately(self):
        store = ClusterStore()
        m = make_manager(store, ["pvcprotection"])
        store.create_pvc(PersistentVolumeClaim(meta=ObjectMeta(name="free")))
        m.settle()
        store.delete_object("PersistentVolumeClaim", "default/free")
        m.settle()
        assert store.get_object("PersistentVolumeClaim", "default/free") is None


class TestPVProtection:
    def test_bound_pv_protected_until_released(self):
        store = ClusterStore()
        m = make_manager(store, ["pvprotection"])
        store.create_pv(PersistentVolume(meta=ObjectMeta(name="vol"),
                                         capacity_bytes=1 << 30,
                                         bound_pvc="default/data"))
        m.settle()
        store.delete_object("PersistentVolume", "vol")
        m.settle()
        pv = store.get_object("PersistentVolume", "vol")
        assert pv is not None and pv.meta.deletion_timestamp > 0
        released = dataclasses.replace(pv, bound_pvc="")
        released.meta = dataclasses.replace(pv.meta)
        store.update_object("PersistentVolume", released)
        m.settle()
        assert store.get_object("PersistentVolume", "vol") is None


class TestNodeIpam:
    def test_unique_cidrs_allocated(self):
        store = ClusterStore()
        m = make_manager(store, ["nodeipam"])
        for i in range(5):
            store.create_node(make_node(f"n{i}").capacity({"cpu": "4"}).obj())
        m.settle()
        cidrs = [store.nodes[f"n{i}"].spec.pod_cidr for i in range(5)]
        assert all(c.endswith("/24") for c in cidrs)
        assert len(set(cidrs)) == 5


class TestEndpointSliceMirroring:
    def test_selectorless_service_endpoints_mirrored(self):
        store = ClusterStore()
        m = make_manager(store, ["endpointslicemirroring"])
        store.create_service(Service(meta=ObjectMeta(name="ext")))  # no selector
        store.create_object("Endpoints", Endpoints(
            meta=ObjectMeta(name="ext"),
            addresses=(EndpointAddress(pod_key="x/y", node_name="n1"),)))
        m.settle()
        sl = store.get_object("EndpointSlice", "default/ext-mirror")
        assert sl is not None and sl.addresses[0].pod_key == "x/y"

    def test_selector_service_not_mirrored(self):
        store = ClusterStore()
        m = make_manager(store, ["endpointslicemirroring"])
        store.create_service(Service(meta=ObjectMeta(name="app"),
                                     selector={"app": "web"}))
        store.create_object("Endpoints", Endpoints(meta=ObjectMeta(name="app")))
        m.settle()
        assert store.get_object("EndpointSlice", "default/app-mirror") is None


class TestEphemeralVolume:
    def test_pod_owned_pvc_created(self):
        store = ClusterStore()
        m = make_manager(store, ["ephemeral-volume"])
        pod = make_pod("worker").req({"cpu": "1"}).obj()
        pod.spec.ephemeral_claims = ("scratch",)
        store.create_pod(pod)
        m.settle()
        pvc = store.get_object("PersistentVolumeClaim", "default/worker-scratch")
        assert pvc is not None
        ref = pvc.meta.controller_of()
        assert ref is not None and ref.kind == "Pod" and ref.name == "worker"


class TestCrossControllerIntegration:
    """The interactions a single-controller harness misses: the full manager
    must not fight the new loops."""

    def test_mirroring_survives_endpoint_controllers(self):
        store = ClusterStore()
        m = make_manager(store, None)  # FULL default controller set
        store.create_service(Service(meta=ObjectMeta(name="ext")))  # no selector
        store.create_object("Endpoints", Endpoints(
            meta=ObjectMeta(name="ext"),
            addresses=(EndpointAddress(pod_key="x/y", node_name="n1"),)))
        m.settle()
        ep = store.get_object("Endpoints", "default/ext")
        assert ep is not None and ep.addresses, "user Endpoints were blanked"
        sl = store.get_object("EndpointSlice", "default/ext-mirror")
        assert sl is not None and sl.addresses[0].pod_key == "x/y"

    def test_ephemeral_pvc_garbage_collected_with_pod(self):
        store = ClusterStore()
        m = make_manager(store, None)
        pod = make_pod("worker").req({"cpu": "1"}).obj()
        pod.spec.ephemeral_claims = ("scratch",)
        store.create_pod(pod)
        m.settle()
        assert store.get_object(
            "PersistentVolumeClaim", "default/worker-scratch") is not None
        store.delete_pod("default/worker")
        m.settle()
        assert store.get_object(
            "PersistentVolumeClaim", "default/worker-scratch") is None, \
            "ephemeral PVC leaked after pod deletion"

    def test_namespace_deletion_sweeps_new_kinds(self):
        store = ClusterStore()
        m = make_manager(store, None)
        store.create_namespace(Namespace(meta=ObjectMeta(name="doomed")))
        m.settle()
        assert "doomed/default" in store.service_accounts
        store.create_pvc(PersistentVolumeClaim(
            meta=ObjectMeta(name="data", namespace="doomed")))
        m.settle()
        ns = store.namespaces["doomed"]
        ns.meta.deletion_timestamp = 1.0
        store.create_namespace(ns)  # re-notify (store has no delete_namespace verb)
        m.settle()
        assert "doomed" not in store.namespaces
        assert "doomed/default" not in store.service_accounts
        assert store.get_object("ConfigMap", "doomed/kube-root-ca.crt") is None
        assert store.get_object("PersistentVolumeClaim", "doomed/data") is None

    def test_nodeipam_reuses_released_cidrs(self):
        store = ClusterStore()
        m = make_manager(store, ["nodeipam"])
        for i in range(3):
            store.create_node(make_node(f"n{i}").capacity({"cpu": "4"}).obj())
        m.settle()
        freed = store.nodes["n1"].spec.pod_cidr
        store.delete_node("n1")
        m.settle()
        store.create_node(make_node("n9").capacity({"cpu": "4"}).obj())
        m.settle()
        assert store.nodes["n9"].spec.pod_cidr == freed

    def test_hpa_missing_metrics_never_scales_down_overloaded(self):
        store = ClusterStore()
        m = make_manager(store, ["horizontalpodautoscaling"])
        TestHPA()._workload(store, replicas=5)
        store.create_object("HorizontalPodAutoscaler", HorizontalPodAutoscaler(
            meta=ObjectMeta(name="web"), target_name="web",
            min_replicas=1, max_replicas=10, target_cpu_utilization=50))
        # only 2 of 5 pods report metrics, both far over target
        store.pod_metrics["default/web-0"] = 1000
        store.pod_metrics["default/web-1"] = 1000
        m.settle()
        assert store.get_object("Deployment", "default/web").replicas >= 5


class TestHPA:
    def _workload(self, store, replicas=2):
        store.create_object("Deployment", Deployment(
            meta=ObjectMeta(name="web"), replicas=replicas))
        # pods as the deployment controller would run them (via an RS)
        from kubernetes_tpu.api.types import ReplicaSet

        store.create_object("ReplicaSet", ReplicaSet(
            meta=ObjectMeta(name="web-1", owner_references=(
                __import__("kubernetes_tpu.api.types", fromlist=["OwnerReference"])
                .OwnerReference(kind="Deployment", name="web", controller=True),)),
            replicas=replicas))
        for i in range(replicas):
            p = make_pod(f"web-{i}").req({"cpu": "1"}).obj()
            p.meta.owner_references = (
                __import__("kubernetes_tpu.api.types", fromlist=["OwnerReference"])
                .OwnerReference(kind="ReplicaSet", name="web-1", controller=True),)
            p.status.phase = "Running"
            store.create_pod(p)

    def test_scales_up_on_high_utilization(self):
        store = ClusterStore()
        m = make_manager(store, ["horizontalpodautoscaling"])
        self._workload(store, replicas=2)
        store.create_object("HorizontalPodAutoscaler", HorizontalPodAutoscaler(
            meta=ObjectMeta(name="web"), target_name="web",
            min_replicas=1, max_replicas=8, target_cpu_utilization=50))
        # both pods at 100% of their 1-cpu request → ratio 2 → desired 4
        store.pod_metrics["default/web-0"] = 1000
        store.pod_metrics["default/web-1"] = 1000
        m.settle()
        assert store.get_object("Deployment", "default/web").replicas == 4

    def test_holds_within_tolerance_and_clamps(self):
        store = ClusterStore()
        m = make_manager(store, ["horizontalpodautoscaling"])
        self._workload(store, replicas=2)
        store.create_object("HorizontalPodAutoscaler", HorizontalPodAutoscaler(
            meta=ObjectMeta(name="web"), target_name="web",
            min_replicas=1, max_replicas=3, target_cpu_utilization=50))
        store.pod_metrics["default/web-0"] = 520   # 52% vs 50% target: in band
        store.pod_metrics["default/web-1"] = 480
        m.settle()
        assert store.get_object("Deployment", "default/web").replicas == 2
        store.pod_metrics["default/web-0"] = 5000  # way over: clamp to max
        store.pod_metrics["default/web-1"] = 5000
        m.settle()
        assert store.get_object("Deployment", "default/web").replicas == 3

    def test_downscale_stabilization(self):
        store = ClusterStore()
        clock = FakeClock()
        m = make_manager(store, ["horizontalpodautoscaling"], clock=clock)
        self._workload(store, replicas=2)
        store.create_object("HorizontalPodAutoscaler", HorizontalPodAutoscaler(
            meta=ObjectMeta(name="web"), target_name="web",
            min_replicas=1, max_replicas=8, target_cpu_utilization=50))
        store.pod_metrics["default/web-0"] = 1000
        store.pod_metrics["default/web-1"] = 1000
        m.settle()
        assert store.get_object("Deployment", "default/web").replicas == 4
        # load drops: a shrink inside the stabilization window must hold
        store.pod_metrics["default/web-0"] = 10
        store.pod_metrics["default/web-1"] = 10
        m.settle()
        assert store.get_object("Deployment", "default/web").replicas == 4
        clock.advance(301)
        m.settle()
        assert store.get_object("Deployment", "default/web").replicas < 4
