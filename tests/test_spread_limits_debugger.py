"""SelectorSpread, non-CSI volume limits, node tree, cache debugger."""

import logging

from kubernetes_tpu.api.types import (
    LabelSelector,
    Namespace,
    ObjectMeta,
    PersistentVolume,
    PersistentVolumeClaim,
    ReplicaSet,
    Service,
    get_zone_key,
)
from kubernetes_tpu.api.wrappers import make_node, make_pod
from kubernetes_tpu.apiserver.store import ClusterStore
from kubernetes_tpu.cache.cache import Cache
from kubernetes_tpu.cache.debugger import CacheComparer, CacheDebugger
from kubernetes_tpu.cache.node_tree import NodeTree, zone_interleaved
from kubernetes_tpu.cache.snapshot import Snapshot
from kubernetes_tpu.framework.interface import CycleState, NodeScore
from kubernetes_tpu.framework.plugins.selectorspread import SelectorSpread, default_selector
from kubernetes_tpu.framework.plugins.volume import make_ebs_limits
from kubernetes_tpu.framework.types import NodeInfo
from kubernetes_tpu.queue.scheduling_queue import SchedulingQueue


def _store_with_service(selector):
    store = ClusterStore()
    store.create_namespace(Namespace(meta=ObjectMeta(name="default")))
    store.create_service(Service(meta=ObjectMeta(name="svc"), selector=selector))
    return store


class TestDefaultSelector:
    def test_service_selector_collected(self):
        store = _store_with_service({"app": "web"})
        pod = make_pod("p").label("app", "web").obj()
        sels = default_selector(pod, store)
        assert len(sels) == 1
        assert sels[0].matches({"app": "web", "x": "y"})

    def test_non_matching_service_ignored(self):
        store = _store_with_service({"app": "db"})
        pod = make_pod("p").label("app", "web").obj()
        assert default_selector(pod, store) == []

    def test_replicaset_owner_selector(self):
        store = _store_with_service({"app": "web"})
        store.create_replica_set(
            ReplicaSet(selector=LabelSelector(match_labels={"app": "web", "tier": "fe"}))
        )
        store.replica_sets["default/rs-1"] = ReplicaSet(
            selector=LabelSelector(match_labels={"tier": "fe"})
        )
        pod = make_pod("p").label("app", "web").owner("ReplicaSet", "rs-1").obj()
        sels = default_selector(pod, store)
        assert len(sels) == 2


class TestSelectorSpreadScoring:
    def _make(self, store, snapshot):
        return SelectorSpread(store=store, snapshot_fn=lambda: snapshot.list())

    def _node_info(self, name, zone=None, pods=()):
        nw = make_node(name)
        if zone:
            nw.label("topology.kubernetes.io/zone", zone)
        ni = NodeInfo(nw.obj())
        for p in pods:
            ni.add_pod(p)
        return ni

    def test_score_counts_matching_pods(self):
        store = _store_with_service({"app": "web"})
        match = make_pod("m1").label("app", "web").obj()
        other = make_pod("o1").label("app", "db").obj()
        ni = self._node_info("n1", pods=[match, other])
        snap = Snapshot()
        pl = self._make(store, snap)
        state = CycleState()
        pod = make_pod("p").label("app", "web").obj()
        pl.pre_score(state, pod, [])
        raw, status = pl.score_node(state, pod, ni)
        assert status.is_success() and raw == 1

    def test_skip_when_pod_has_spread_constraints(self):
        store = _store_with_service({"app": "web"})
        pod = (
            make_pod("p").label("app", "web")
            .spread_constraint(1, "zone", when_unsatisfiable="ScheduleAnyway",
                               selector=LabelSelector(match_labels={"app": "web"}))
            .obj()
        )
        pl = self._make(store, Snapshot())
        state = CycleState()
        pl.pre_score(state, pod, [])
        raw, status = pl.score_node(state, pod, self._node_info("n1"))
        assert raw == 0 and status.is_success()

    def test_normalize_inverts_and_blends_zones(self):
        store = _store_with_service({"app": "web"})
        pod = make_pod("p").label("app", "web").obj()
        mk = lambda i: make_pod(f"m{i}").label("app", "web").obj()
        # zone a: n1 has 3 matching pods; zone b: n2 has 1, n3 has 0
        n1 = self._node_info("n1", zone="a", pods=[mk(1), mk(2), mk(3)])
        n2 = self._node_info("n2", zone="b", pods=[mk(4)])
        n3 = self._node_info("n3", zone="b")
        snap = Snapshot()
        for ni in (n1, n2, n3):
            snap.node_info_map[ni.node.meta.name] = ni
        snap.refresh_lists()
        pl = self._make(store, snap)
        state = CycleState()
        pl.pre_score(state, pod, [])
        scores = []
        for ni in (n1, n2, n3):
            raw, _ = pl.score_node(state, pod, ni)
            scores.append(NodeScore(name=ni.node.meta.name, score=raw))
        pl.normalize_score(state, pod, scores)
        by = {s.name: s.score for s in scores}
        # node score: n1=0 raw3/3, zone a count 3 = max → zone score 0 → 0
        assert by["n1"] == 0
        # n3 best: node inverse 100, zone b count 1 → zone 66 → blended > n2
        assert by["n3"] > by["n2"] > by["n1"]

    def test_zoneless_cluster_pure_node_spread(self):
        store = _store_with_service({"app": "web"})
        pod = make_pod("p").label("app", "web").obj()
        n1 = self._node_info("n1", pods=[make_pod("m").label("app", "web").obj()])
        n2 = self._node_info("n2")
        snap = Snapshot()
        for ni in (n1, n2):
            snap.node_info_map[ni.node.meta.name] = ni
        snap.refresh_lists()
        pl = self._make(store, snap)
        state = CycleState()
        pl.pre_score(state, pod, [])
        scores = [NodeScore(name="n1", score=1), NodeScore(name="n2", score=0)]
        pl.normalize_score(state, pod, scores)
        assert scores[0].score == 0 and scores[1].score == 100


class TestNonCSILimits:
    def _store(self, n_pvs):
        store = ClusterStore()
        for i in range(n_pvs):
            store.create_pv(PersistentVolume(meta=ObjectMeta(name=f"pv-{i}"), volume_type="ebs"))
            store.create_pvc(
                PersistentVolumeClaim(meta=ObjectMeta(name=f"claim-{i}"), bound_pv=f"pv-{i}")
            )
        return store

    def _run(self, pl, pod, ni):
        state = CycleState()
        _, st = pl.pre_filter(state, pod)
        assert st.is_success()
        return pl.filter(state, pod, ni)

    def test_under_limit_ok(self):
        store = self._store(2)
        pl = make_ebs_limits(client=store)
        pod = make_pod("p").pvc("claim-0").obj()
        ni = NodeInfo(make_node("n1").obj())
        assert self._run(pl, pod, ni).is_success()

    def test_over_allocatable_limit_rejected(self):
        store = self._store(3)
        pl = make_ebs_limits(client=store)
        node = make_node("n1").obj()
        node.status.allocatable["attachable-volumes-ebs"] = 1
        ni = NodeInfo(node)
        existing = make_pod("e").pvc("claim-0").obj()
        ni.add_pod(existing)
        pod = make_pod("p").pvc("claim-1").obj()
        status = self._run(pl, pod, ni)
        assert not status.is_success()

    def test_same_volume_shared_not_double_counted(self):
        store = self._store(1)
        pl = make_ebs_limits(client=store)
        node = make_node("n1").obj()
        node.status.allocatable["attachable-volumes-ebs"] = 1
        ni = NodeInfo(node)
        ni.add_pod(make_pod("e").pvc("claim-0").obj())
        pod = make_pod("p").pvc("claim-0").obj()  # same PV: no extra attach
        assert self._run(pl, pod, ni).is_success()


class TestNodeTree:
    def test_round_robin_across_zones(self):
        tree = NodeTree()
        nodes = []
        for i in range(6):
            n = make_node(f"n{i}").label("topology.kubernetes.io/zone", f"z{i % 2}").obj()
            nodes.append(n)
            tree.add_node(n)
        order = tree.list()
        assert len(order) == 6
        zones = ["z0" if n in ("n0", "n2", "n4") else "z1" for n in order]
        # alternating zones
        assert zones[:4] == ["z0", "z1", "z0", "z1"]

    def test_remove_and_update(self):
        tree = NodeTree()
        n = make_node("a").label("topology.kubernetes.io/zone", "z1").obj()
        tree.add_node(n)
        n2 = make_node("a").label("topology.kubernetes.io/zone", "z2").obj()
        tree.update_node(n, n2)
        assert tree.num_nodes == 1
        tree.remove_node(n2)
        assert tree.list() == []

    def test_snapshot_zone_interleaved(self):
        infos = []
        for i in range(4):
            n = make_node(f"n{i}").label("topology.kubernetes.io/zone", f"z{i // 2}").obj()
            infos.append(NodeInfo(n))
        out = zone_interleaved(infos)
        zones = [get_zone_key(ni.node) for ni in out]
        assert zones[0] != zones[1]  # interleaved, not grouped


class TestCacheDebugger:
    def _setup(self):
        store = ClusterStore()
        cache = Cache()
        queue = SchedulingQueue()
        return store, cache, queue

    def test_in_sync(self):
        store, cache, queue = self._setup()
        node = make_node("n1").obj()
        store.create_node(node)
        cache.add_node(node)
        pod = make_pod("p1").node("n1").obj()
        store.pods[pod.meta.key()] = pod
        cache.add_pod(pod)
        assert CacheComparer(store, cache, queue).compare()

    def test_drift_detected(self):
        store, cache, queue = self._setup()
        store.create_node(make_node("n1").obj())  # store-only node
        comparer = CacheComparer(store, cache, queue)
        missed, redundant = comparer.compare_nodes()
        assert missed == ["n1"] and redundant == []
        assert not comparer.compare()

    def test_dumper_output(self, caplog):
        store, cache, queue = self._setup()
        node = make_node("n1").capacity({"cpu": "4", "memory": "8Gi", "pods": 10}).obj()
        cache.add_node(node)
        queue.add(make_pod("p1").obj())
        dbg = CacheDebugger(store, cache, queue)
        with caplog.at_level(logging.INFO):
            text = dbg.dumper.dump_all()
        assert "Node: n1" in text and "Pod: default/p1" in text
