"""API field validation (pkg/apis/core/validation/validation.go distilled —
VERDICT r3 missing #5: bad manifests must no longer decode silently)."""

import pytest

from kubernetes_tpu.api.types import (
    Container,
    ContainerPort,
    ObjectMeta,
    Pod,
    PodSpec,
    Taint,
    Toleration,
    TopologySpreadConstraint,
)
from kubernetes_tpu.api.validation import (
    ValidationError,
    validate,
    validate_pod,
    validate_update,
)
from kubernetes_tpu.api.wrappers import make_node, make_pod
from kubernetes_tpu.apiserver.store import ClusterStore


def _pod(name="p", ns="default", containers=None):
    return Pod(meta=ObjectMeta(name=name, namespace=ns),
               spec=PodSpec(containers=containers if containers is not None
                            else [Container(name="c", image="img")]))


class TestPodValidation:
    def test_valid_pod_passes(self):
        assert validate_pod(make_pod("web").req({"cpu": "1"}).obj()) == []

    def test_bad_name_rejected(self):
        assert any("metadata.name" in e for e in validate_pod(_pod(name="Bad_Name")))
        assert any("name is required" in e for e in validate_pod(_pod(name="")))

    def test_no_containers_rejected(self):
        errs = validate_pod(_pod(containers=[]))
        assert any("at least one container" in e for e in errs)

    def test_duplicate_container_names_rejected(self):
        errs = validate_pod(_pod(containers=[
            Container(name="c", image="a"), Container(name="c", image="b")]))
        assert any("duplicate container name" in e for e in errs)

    def test_request_above_limit_rejected(self):
        errs = validate_pod(_pod(containers=[
            Container(name="c", image="a",
                      requests={"cpu": "2"}, limits={"cpu": "1"})]))
        assert any("must be ≤ the cpu limit" in e for e in errs)

    def test_unparseable_quantity_rejected(self):
        errs = validate_pod(_pod(containers=[
            Container(name="c", image="a", requests={"cpu": "banana"})]))
        assert any("is invalid" in e for e in errs)

    def test_bad_host_port_rejected(self):
        errs = validate_pod(_pod(containers=[
            Container(name="c", image="a",
                      ports=[ContainerPort(container_port=80, host_port=99999)])]))
        assert any("1-65535" in e for e in errs)

    def test_bad_toleration_rejected(self):
        p = _pod()
        p.spec.tolerations = (Toleration(key="k", operator="Sometimes"),)
        assert any("Exists or Equal" in e for e in validate_pod(p))
        p.spec.tolerations = (Toleration(key="k", operator="Exists", value="v"),)
        assert any("must be empty when operator is Exists" in e
                   for e in validate_pod(p))

    def test_bad_spread_constraint_rejected(self):
        p = _pod()
        p.spec.topology_spread_constraints = (
            TopologySpreadConstraint(max_skew=0, topology_key="",
                                     when_unsatisfiable="Whenever"),)
        errs = validate_pod(p)
        assert any("maxSkew" in e for e in errs)
        assert any("topologyKey is required" in e for e in errs)
        assert any("DoNotSchedule or ScheduleAnyway" in e for e in errs)

    def test_bad_label_key_rejected(self):
        p = make_pod("ok").req({"cpu": "1"}).obj()
        p.meta.labels["-bad/key!"] = "v"
        assert any("labels" in e for e in validate_pod(p))


class TestUpdateValidation:
    def test_node_name_immutable_once_set(self):
        old = make_pod("w").req({"cpu": "1"}).obj()
        old.spec.node_name = "n1"
        new = old.clone()
        new.spec.node_name = "n2"
        with pytest.raises(ValidationError, match="nodeName"):
            validate_update("Pod", old, new)

    def test_image_update_allowed(self):
        old = make_pod("w").req({"cpu": "1"}).obj()
        new = old.clone()
        new.spec.containers[0].image = "other:latest"
        validate_update("Pod", old, new)  # no raise


class TestStoreIntegration:
    def test_store_rejects_invalid_pod(self):
        store = ClusterStore()
        with pytest.raises(ValidationError):
            store.create_pod(_pod(name="Not-Valid-Name!"))
        assert not store.pods  # nothing persisted

    def test_store_rejects_invalid_node_taint(self):
        store = ClusterStore()
        node = make_node("n1").capacity({"cpu": "4"}).obj()
        node.spec.taints = (Taint(key="k", effect="Eventually"),)
        with pytest.raises(ValidationError):
            store.create_node(node)

    def test_http_front_maps_to_422(self):
        import json
        import urllib.error
        import urllib.request

        from kubernetes_tpu.apiserver.http import serve_api, shutdown_api

        store = ClusterStore()
        server, port = serve_api(store)
        try:
            body = json.dumps({"meta": {"name": "Bad_Name"},
                               "spec": {"containers": [{"name": "c"}]}}).encode()
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/api/v1/namespaces/default/pods",
                data=body, headers={"Content-Type": "application/json"},
                method="POST")
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(req, timeout=5)
            assert e.value.code == 422
        finally:
            shutdown_api(server)

    def test_kind_dispatch(self):
        validate("Node", make_node("ok").capacity({"cpu": "1"}).obj())
        with pytest.raises(ValidationError):
            from kubernetes_tpu.api.types import Namespace

            validate("Namespace", Namespace(meta=ObjectMeta(name="Not.A.Label")))
