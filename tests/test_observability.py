"""Metrics registry + scheduler metric names + events + trace tests
(SURVEY.md §5.1/§5.5: identical metric names keep a scheduler_perf-style
metricsCollector working; dedup in the event recorder; LogIfLong)."""

from kubernetes_tpu.api.wrappers import make_node, make_pod
from kubernetes_tpu.apiserver.store import ClusterStore
from kubernetes_tpu.metrics import Histogram, Registry, SchedulerMetrics
from kubernetes_tpu.scheduler.scheduler import Scheduler
from kubernetes_tpu.utils.events import EventRecorder
from kubernetes_tpu.utils.trace import Trace


def test_metric_names_match_reference():
    m = SchedulerMetrics()
    exposition = m.registry.expose()
    for name in (
        "scheduler_schedule_attempts_total",
        "scheduler_scheduling_attempt_duration_seconds",
        "scheduler_scheduling_algorithm_duration_seconds",
        "scheduler_framework_extension_point_duration_seconds",
        "scheduler_plugin_execution_duration_seconds",
        "scheduler_pending_pods",
        "scheduler_queue_incoming_pods_total",
        "scheduler_preemption_attempts_total",
        "scheduler_preemption_victims",
        "scheduler_unschedulable_pods",
    ):
        assert name in exposition, name


def test_histogram_percentile_and_exposition():
    h = Histogram("test_hist", "t", buckets=[0.001, 0.01, 0.1, 1.0])
    for v in [0.005] * 90 + [0.5] * 10:
        h.observe(v)
    assert h.count() == 100
    assert 0.001 < h.percentile(0.5) <= 0.01
    assert 0.1 < h.percentile(0.99) <= 1.0
    text = h.collect()
    assert any("test_hist_bucket" in line for line in text)
    assert any("+Inf" in line for line in text)


def test_scheduler_emits_metrics_and_events():
    store = ClusterStore()
    store.create_node(make_node("n1").capacity({"cpu": "1", "memory": "4Gi", "pods": 10}).obj())
    s = Scheduler(store)
    store.create_pod(make_pod("ok").req({"cpu": "100m"}).obj())
    store.create_pod(make_pod("huge").req({"cpu": "64"}).obj())
    s.run_until_settled()

    assert s.smetrics.schedule_attempts.labels("scheduled", "default-scheduler") == 1
    assert s.smetrics.schedule_attempts.labels("unschedulable", "default-scheduler") >= 1
    ev = s.recorder.for_object("default/ok")
    assert any(e.reason == "Scheduled" for e in ev)
    ev = s.recorder.for_object("default/huge")
    assert any(e.reason == "FailedScheduling" for e in ev)


def test_event_dedup():
    clock = [0.0]
    r = EventRecorder(now_fn=lambda: clock[0])
    for _ in range(5):
        r.eventf("default/p", "Warning", "FailedScheduling", "Scheduling", "no cpu")
        clock[0] += 1
    assert len(r.events) == 1
    assert r.events[0].count == 5


def test_trace_log_if_long():
    clock = [0.0]

    def now():
        return clock[0]

    t = Trace("Scheduling", now_fn=now, pod="default/p")
    clock[0] = 0.05
    t.step("predicates done")
    clock[0] = 0.2
    t.step("scoring done")
    out = t.log_if_long(0.1)
    assert out is not None and "predicates done" in out and "total=200.0ms" in out
    t2 = Trace("Scheduling", now_fn=now)
    assert t2.log_if_long(0.1) is None
