"""Metrics registry + scheduler metric names + events + trace tests
(SURVEY.md §5.1/§5.5: identical metric names keep a scheduler_perf-style
metricsCollector working; dedup in the event recorder; LogIfLong)."""

import re

from kubernetes_tpu.api.wrappers import make_node, make_pod
from kubernetes_tpu.apiserver.store import ClusterStore
from kubernetes_tpu.metrics import Histogram, Registry, SchedulerMetrics
from kubernetes_tpu.scheduler.scheduler import Scheduler
from kubernetes_tpu.utils.events import EventRecorder
from kubernetes_tpu.utils.trace import Trace


def test_metric_names_match_reference():
    m = SchedulerMetrics()
    exposition = m.registry.expose()
    for name in (
        "scheduler_schedule_attempts_total",
        "scheduler_scheduling_attempt_duration_seconds",
        "scheduler_scheduling_algorithm_duration_seconds",
        "scheduler_framework_extension_point_duration_seconds",
        "scheduler_plugin_execution_duration_seconds",
        "scheduler_pending_pods",
        "scheduler_queue_incoming_pods_total",
        "scheduler_preemption_attempts_total",
        "scheduler_preemption_victims",
        "scheduler_unschedulable_pods",
    ):
        assert name in exposition, name


def test_histogram_percentile_and_exposition():
    h = Histogram("test_hist", "t", buckets=[0.001, 0.01, 0.1, 1.0])
    for v in [0.005] * 90 + [0.5] * 10:
        h.observe(v)
    assert h.count() == 100
    assert 0.001 < h.percentile(0.5) <= 0.01
    assert 0.1 < h.percentile(0.99) <= 1.0
    text = h.collect()
    assert any("test_hist_bucket" in line for line in text)
    assert any("+Inf" in line for line in text)


def test_scheduler_emits_metrics_and_events():
    store = ClusterStore()
    store.create_node(make_node("n1").capacity({"cpu": "1", "memory": "4Gi", "pods": 10}).obj())
    s = Scheduler(store)
    store.create_pod(make_pod("ok").req({"cpu": "100m"}).obj())
    store.create_pod(make_pod("huge").req({"cpu": "64"}).obj())
    s.run_until_settled()

    assert s.smetrics.schedule_attempts.labels("scheduled", "default-scheduler") == 1
    assert s.smetrics.schedule_attempts.labels("unschedulable", "default-scheduler") >= 1
    ev = s.recorder.for_object("default/ok")
    assert any(e.reason == "Scheduled" for e in ev)
    ev = s.recorder.for_object("default/huge")
    assert any(e.reason == "FailedScheduling" for e in ev)


def test_event_dedup():
    clock = [0.0]
    r = EventRecorder(now_fn=lambda: clock[0])
    for _ in range(5):
        r.eventf("default/p", "Warning", "FailedScheduling", "Scheduling", "no cpu")
        clock[0] += 1
    assert len(r.events) == 1
    assert r.events[0].count == 5


def test_framework_runtime_observes_extension_points():
    """Tentpole: the framework runtime itself feeds the two attribution
    histograms — per extension point always, per plugin on sampled cycles
    (attempt 1 always samples)."""
    store = ClusterStore()
    for i in range(3):
        store.create_node(make_node(f"n{i}").capacity(
            {"cpu": "4", "memory": "8Gi", "pods": 10}).obj())
    s = Scheduler(store)
    store.create_pod(make_pod("p").req({"cpu": "100m"}).obj())
    s.run_until_settled()

    h = s.smetrics.framework_extension_point_duration
    points = {lv[0] for lv in h.label_sets()}
    assert {"pre_filter", "filter", "pre_score", "score",
            "reserve", "permit", "pre_bind", "bind", "post_bind"} <= points
    # "filter" is observed once per attempt over the whole node walk (the
    # reference's findNodesThatFitPod-level observation, not per node)
    assert sum(h.count(*lv) for lv in h.label_sets() if lv[0] == "filter") == 1
    # profile label rides along
    assert all(lv[2] == "default-scheduler" for lv in h.label_sets())

    hp = s.smetrics.plugin_execution_duration
    plugin_points = {(lv[0], lv[1]) for lv in hp.label_sets()}
    assert ("NodeResourcesFit", "filter") in plugin_points
    assert ("DefaultBinder", "bind") in plugin_points
    assert all(lv[2] == "Success" for lv in hp.label_sets()
               if lv[1] == "bind")


def test_wire_backend_observes_every_bind_path_plugin():
    """Acceptance: after a wire-backend run /metrics shows nonzero
    extension-point and per-plugin duration samples for every enabled
    plugin that ran."""
    from kubernetes_tpu.backend.service import DeviceService, WireScheduler, serve

    store = ClusterStore()
    svc = DeviceService(batch_size=8)
    server, port = serve(svc)
    try:
        s = WireScheduler(store, endpoint=f"http://127.0.0.1:{port}",
                          batch_size=8)
        for i in range(4):
            store.create_node(make_node(f"n{i}").capacity(
                {"cpu": "4", "memory": "8Gi", "pods": 10}).obj())
        for i in range(6):
            store.create_pod(make_pod(f"p{i}").req({"cpu": "100m"}).obj())
        s.run_until_settled()
    finally:
        server.shutdown()
        server.server_close()
    assert s.metrics["scheduled"] == 6

    exposition = s.smetrics.registry.expose()
    assert "scheduler_framework_extension_point_duration_seconds_count" in exposition
    assert "scheduler_plugin_execution_duration_seconds_count" in exposition

    h = s.smetrics.framework_extension_point_duration
    ran_points = {lv[0] for lv in h.label_sets()}
    assert {"reserve", "permit", "pre_bind", "bind", "post_bind"} <= ran_points
    hp = s.smetrics.plugin_execution_duration
    fwk = s.profiles["default-scheduler"]
    for point in ("reserve", "permit", "pre_bind", "bind", "post_bind"):
        for plugin, _w in fwk.points.get(point, []):
            n = sum(hp.count(*lv) for lv in hp.label_sets()
                    if lv[0] == plugin.name() and lv[1] == point)
            assert n > 0, f"no samples for {plugin.name()}@{point}"


def test_unschedulable_pods_gauge_counts_and_clears():
    """Satellite: the gauge tracks real per-plugin counts (not a sticky 1)
    and drains when pods schedule or are deleted."""
    store = ClusterStore()
    store.create_node(make_node("n1").capacity(
        {"cpu": "1", "memory": "4Gi", "pods": 10}).obj())
    s = Scheduler(store, pod_initial_backoff=0.0, pod_max_backoff=0.0)
    g = s.smetrics.unschedulable_pods
    store.create_pod(make_pod("big-a").req({"cpu": "64"}).obj())
    store.create_pod(make_pod("big-b").req({"cpu": "64"}).obj())
    s.run_until_settled()
    assert g.labels("NodeResourcesFit", "default-scheduler") == 2

    store.delete_pod("default/big-a")
    assert g.labels("NodeResourcesFit", "default-scheduler") == 1

    # capacity arrives: the remaining pod schedules and the gauge drains
    store.create_node(make_node("n2").capacity(
        {"cpu": "128", "memory": "64Gi", "pods": 10}).obj())
    s.run_until_settled()
    assert store.get_pod("default/big-b").spec.node_name == "n2"
    assert g.labels("NodeResourcesFit", "default-scheduler") == 0


def test_queue_metrics_wired():
    """Satellite: queue_incoming_pods counters + pending_pods gauge sync on
    queue transitions (both were registered-but-dead)."""
    store = ClusterStore()
    store.create_node(make_node("n1").capacity(
        {"cpu": "1", "memory": "4Gi", "pods": 10}).obj())
    s = Scheduler(store, pod_initial_backoff=0.0, pod_max_backoff=0.0)
    m = s.smetrics
    store.create_pod(make_pod("ok").req({"cpu": "100m"}).obj())
    store.create_pod(make_pod("huge").req({"cpu": "64"}).obj())
    assert m.queue_incoming_pods.labels("active", "PodAdd") == 2
    assert m.pending_pods.labels("active") == 2
    s.run_until_settled()
    # the failed pod landed in the unschedulable map on attempt failure
    assert m.queue_incoming_pods.labels("unschedulable", "ScheduleAttemptFailure") >= 1
    assert m.pending_pods.labels("active") == 0
    assert m.pending_pods.labels("unschedulable") == 1
    # a relevant cluster event moves it back out
    store.create_node(make_node("n2").capacity(
        {"cpu": "128", "memory": "64Gi", "pods": 10}).obj())
    s.run_until_settled()
    assert m.pending_pods.labels("unschedulable") == 0
    incoming = m.queue_incoming_pods
    moved = sum(incoming.labels(q, e) for q, e in incoming.label_sets()
                if e not in ("PodAdd",))
    assert moved >= 1


def _parse_prom(text):
    """Tiny Prometheus text-format parser: returns (help, type, samples)
    keyed by metric family, samples as (name, {label: value}, float)."""
    import re

    helps, types, samples = {}, {}, []
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            _, _, name, h = line.split(" ", 3)
            helps[name] = h
        elif line.startswith("# TYPE "):
            _, _, name, t = line.split(" ", 3)
            types[name] = t
        else:
            mm = re.match(r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{(.*)\})? (\S+)$", line)
            assert mm, f"malformed sample line: {line!r}"
            labels = {}
            if mm.group(3):
                for lm in re.finditer(r'(\w+)="((?:[^"\\]|\\.)*)"', mm.group(3)):
                    # single left-to-right pass: sequential replace() would
                    # corrupt a literal backslash followed by 'n'
                    labels[lm.group(1)] = re.sub(
                        r"\\(.)", lambda e: {"n": "\n"}.get(e.group(1), e.group(1)),
                        lm.group(2))
            samples.append((mm.group(1), labels, float(mm.group(4))))
    return helps, types, samples


def test_plugin_duration_exemplars_link_to_traces():
    """Satellite (PR 2 carryover): sampled plugin_execution_duration
    observations carry the active trace/span id as an OpenMetrics exemplar
    — a slow p99 bucket links to a concrete trace. The 0.0.4 exposition is
    untouched (exemplars are illegal there); the OpenMetrics body carries
    `# {trace_id=...,span_id=...} value` on bucket lines and ends in # EOF."""
    import urllib.request

    from kubernetes_tpu.cmd.server import ComponentServer
    from kubernetes_tpu.utils import tracing

    m = SchedulerMetrics()
    store = ClusterStore()
    store.create_node(make_node("n1").capacity(
        {"cpu": "4", "memory": "8Gi", "pods": 10}).obj())
    sched = Scheduler(store, metrics=m)
    tracing.enable()
    try:
        store.create_pod(make_pod("traced").req({"cpu": "100m"}).obj())
        sched.run_until_settled()  # attempt 1 always samples plugin metrics
    finally:
        spans = tracing.tail(4096)
        tracing.disable()
    trace_ids = {s.trace_id for s in spans}
    assert trace_ids

    # 0.0.4 exposition: byte-compatible, no exemplar syntax anywhere
    plain = m.registry.expose()
    assert " # {" not in plain
    assert "# EOF" not in plain

    om = m.registry.expose(openmetrics=True)
    assert om.rstrip().endswith("# EOF")
    ex_re = re.compile(
        r'^scheduler_plugin_execution_duration_seconds_bucket\{[^}]*\} '
        r'\d+ # \{trace_id="([0-9a-f]+)",span_id="([0-9a-f]+)"\} '
        r'[0-9.e+-]+$')
    matches = [ex_re.match(line) for line in om.splitlines()]
    matches = [mm for mm in matches if mm]
    assert matches, "no exemplar on any plugin-duration bucket line"
    # every exemplar's trace id names a REAL exported span's trace
    for mm in matches:
        assert mm.group(1) in trace_ids

    # exemplar rides the bucket its observation landed in (accessor view)
    hist = m.plugin_execution_duration
    found = False
    for lv in hist.label_sets():
        for i in range(len(hist.buckets)):
            ex = hist.exemplar_for(i, *lv)
            if ex is not None:
                ex_labels, value = ex
                assert set(ex_labels) == {"trace_id", "span_id"}
                assert value <= hist.buckets[i]
                found = True
    assert found

    # content negotiation on the serving mux: an OpenMetrics Accept header
    # gets the exemplar exposition, the default scrape does not
    srv = ComponentServer(configz={}, registry=m.registry)
    port = srv.start()
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/metrics",
            headers={"Accept": "application/openmetrics-text; version=1.0.0"})
        with urllib.request.urlopen(req, timeout=5) as r:
            body = r.read().decode()
            assert "openmetrics-text" in r.headers["Content-Type"]
        assert body.rstrip().endswith("# EOF")
        assert any(ex_re.match(line) for line in body.splitlines())
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=5) as r:
            assert " # {" not in r.read().decode()
    finally:
        srv.stop()


def test_metrics_exposition_well_formed_over_http():
    """Satellite: scrape /metrics over HTTP after a mixed oracle+batched run;
    assert HELP/TYPE pairs, histogram bucket consistency, label escaping."""
    import urllib.request

    from kubernetes_tpu.backend import TPUScheduler
    from kubernetes_tpu.cmd.server import ComponentServer

    m = SchedulerMetrics()
    # oracle run
    store1 = ClusterStore()
    store1.create_node(make_node("n1").capacity(
        {"cpu": "4", "memory": "8Gi", "pods": 10}).obj())
    s1 = Scheduler(store1, metrics=m)
    store1.create_pod(make_pod("seq").req({"cpu": "100m"}).obj())
    store1.create_pod(make_pod("huge").req({"cpu": "64"}).obj())
    s1.run_until_settled()
    # batched run against the same metric set
    store2 = ClusterStore()
    for i in range(4):
        store2.create_node(make_node(f"b{i}").capacity(
            {"cpu": "4", "memory": "8Gi", "pods": 10}).obj())
    s2 = TPUScheduler(store2, metrics=m, batch_size=8)
    for i in range(6):
        store2.create_pod(make_pod(f"bp{i}").req({"cpu": "100m"}).obj())
    s2.run_until_settled()
    # escaping probe: a label value with quote, backslash, and newline
    m.queue_incoming_pods.inc('que"ue\\q\nx', "Probe")

    srv = ComponentServer(configz={}, registry=m.registry)
    port = srv.start()
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=5) as r:
            assert r.status == 200
            body = r.read().decode()
    finally:
        srv.stop()

    helps, types, samples = _parse_prom(body)
    assert samples
    # escaping round-trips (and never breaks line framing — _parse_prom
    # would already have choked on a raw newline)
    assert any(lab.get("queue") == 'que"ue\\q\nx' for _, lab, _ in samples)
    # every sample belongs to a family with a HELP and TYPE line
    for name, _labels, _v in samples:
        base = re.sub(r"_(bucket|sum|count)$", "", name)
        fam = base if base in types else name
        assert fam in types and fam in helps, f"no HELP/TYPE for {name}"
    # histogram consistency per labelset: cumulative buckets, +Inf == _count
    hists = [n for n, t in types.items() if t == "histogram"]
    checked = 0
    for fam in hists:
        series = {}
        for name, labels, v in samples:
            if not name.startswith(fam + "_"):
                continue
            key = tuple(sorted((k, v2) for k, v2 in labels.items() if k != "le"))
            series.setdefault(key, {"buckets": [], "sum": None, "count": None})
            if name == fam + "_bucket":
                le = labels["le"]
                series[key]["buckets"].append(
                    (float("inf") if le == "+Inf" else float(le), v))
            elif name == fam + "_sum":
                series[key]["sum"] = v
            elif name == fam + "_count":
                series[key]["count"] = v
        for key, d in series.items():
            assert d["sum"] is not None and d["count"] is not None, (fam, key)
            buckets = sorted(d["buckets"])
            counts = [c for _, c in buckets]
            assert counts == sorted(counts), f"{fam}{key}: non-cumulative"
            assert buckets[-1][0] == float("inf")
            assert buckets[-1][1] == d["count"], f"{fam}{key}: +Inf != _count"
            checked += 1
    assert checked > 0
    # the tentpole histograms made it to the wire with samples
    assert any(n.startswith("scheduler_framework_extension_point_duration_seconds")
               for n, _l, _v in samples)
    assert any(n.startswith("scheduler_plugin_execution_duration_seconds")
               for n, _l, _v in samples)


def test_trace_log_if_long():
    clock = [0.0]

    def now():
        return clock[0]

    t = Trace("Scheduling", now_fn=now, pod="default/p")
    clock[0] = 0.05
    t.step("predicates done")
    clock[0] = 0.2
    t.step("scoring done")
    out = t.log_if_long(0.1)
    assert out is not None and "predicates done" in out and "total=200.0ms" in out
    t2 = Trace("Scheduling", now_fn=now)
    assert t2.log_if_long(0.1) is None
