"""runtime.Scheme analog + core/v1 wire codecs (apimachinery
pkg/runtime/scheme.go:46, serializer/): reference-shaped camelCase
manifests decode to internal dataclasses, internal objects encode back,
defaulters run on decode, unknown GVKs error."""

import json

import pytest

from kubernetes_tpu.api import corev1
from kubernetes_tpu.api.scheme import (
    GroupVersionKind,
    Scheme,
    SchemeError,
    default_scheme,
)
from kubernetes_tpu.api.types import Pod

POD_MANIFEST = {
    "apiVersion": "v1",
    "kind": "Pod",
    "metadata": {
        "name": "web-0", "namespace": "prod",
        "labels": {"app": "web"},
        "annotations": {"team": "infra"},
    },
    "spec": {
        "containers": [{
            "name": "app", "image": "nginx:1.25",
            "resources": {"requests": {"cpu": "500m", "memory": "1Gi"},
                          "limits": {"cpu": "1", "memory": "2Gi"}},
            "ports": [{"hostPort": 8080, "containerPort": 80, "protocol": "TCP"}],
            "securityContext": {"runAsNonRoot": True,
                                "allowPrivilegeEscalation": False,
                                "capabilities": {"drop": ["ALL"]}},
        }],
        "nodeSelector": {"disktype": "ssd"},
        "priorityClassName": "high",
        "schedulerName": "default-scheduler",
        "serviceAccountName": "web",
        "tolerations": [{"key": "dedicated", "operator": "Equal",
                         "value": "web", "effect": "NoSchedule"}],
        "topologySpreadConstraints": [{
            "maxSkew": 1, "topologyKey": "topology.kubernetes.io/zone",
            "whenUnsatisfiable": "DoNotSchedule",
            "labelSelector": {"matchLabels": {"app": "web"}},
        }],
        "affinity": {
            "nodeAffinity": {
                "requiredDuringSchedulingIgnoredDuringExecution": {
                    "nodeSelectorTerms": [{
                        "matchExpressions": [{"key": "zone", "operator": "In",
                                              "values": ["z1", "z2"]}]}]},
                "preferredDuringSchedulingIgnoredDuringExecution": [{
                    "weight": 10,
                    "preference": {"matchExpressions": [
                        {"key": "disk", "operator": "In", "values": ["ssd"]}]}}],
            },
            "podAntiAffinity": {
                "requiredDuringSchedulingIgnoredDuringExecution": [{
                    "labelSelector": {"matchLabels": {"app": "web"}},
                    "topologyKey": "kubernetes.io/hostname"}],
            },
        },
        "volumes": [
            {"name": "data", "persistentVolumeClaim": {"claimName": "web-data"}},
            {"name": "scratch", "ephemeral": {"volumeClaimTemplate": {}}},
        ],
    },
}


class TestPodRoundTrip:
    def test_decode_full_manifest(self):
        pod = default_scheme().decode(json.dumps(POD_MANIFEST))
        assert isinstance(pod, Pod)
        assert pod.meta.name == "web-0" and pod.meta.namespace == "prod"
        c = pod.spec.containers[0]
        assert c.requests["cpu"] == "500m" and c.limits["memory"] == "2Gi"
        assert c.ports[0].host_port == 8080
        assert c.security_context.run_as_non_root is True
        assert "ALL" in c.security_context.capabilities_drop
        assert pod.spec.node_selector == {"disktype": "ssd"}
        assert pod.spec.priority_class_name == "high"
        assert pod.spec.service_account_name == "web"
        assert pod.spec.tolerations[0].key == "dedicated"
        tsc = pod.spec.topology_spread_constraints[0]
        assert tsc.max_skew == 1 and tsc.label_selector.match_labels == {"app": "web"}
        na = pod.spec.affinity.node_affinity
        assert na.required.terms[0].match_expressions[0].values == ("z1", "z2")
        assert na.preferred[0].weight == 10
        anti = pod.spec.affinity.pod_anti_affinity
        assert anti.required[0].topology_key == "kubernetes.io/hostname"
        assert pod.spec.volumes == ("web-data",)
        assert pod.spec.ephemeral_claims == ("scratch",)

    def test_encode_round_trip(self):
        scheme = default_scheme()
        pod = scheme.decode(json.dumps(POD_MANIFEST))
        wire = scheme.encode(pod)
        assert wire["apiVersion"] == "v1" and wire["kind"] == "Pod"
        pod2 = scheme.decode(json.dumps(wire))
        assert pod2.spec.node_selector == pod.spec.node_selector
        assert pod2.spec.tolerations == pod.spec.tolerations
        assert pod2.spec.topology_spread_constraints == \
            pod.spec.topology_spread_constraints
        assert corev1.affinity_to(pod2.spec.affinity) == \
            corev1.affinity_to(pod.spec.affinity)
        assert pod2.spec.volumes == pod.spec.volumes

    def test_defaulter_limits_become_requests(self):
        doc = {"apiVersion": "v1", "kind": "Pod",
               "metadata": {"name": "p"},
               "spec": {"containers": [{
                   "name": "c", "image": "x",
                   "resources": {"limits": {"cpu": "2"}}}]}}
        pod = default_scheme().decode(json.dumps(doc))
        assert pod.spec.containers[0].requests["cpu"] == "2"
        assert pod.resource_request()["cpu"] == 2000


class TestNodeRoundTrip:
    def test_node_manifest(self):
        doc = {"apiVersion": "v1", "kind": "Node",
               "metadata": {"name": "n1", "labels": {"zone": "z1"}},
               "spec": {"taints": [{"key": "gpu", "effect": "NoSchedule"}],
                        "podCIDR": "10.0.3.0/24"},
               "status": {"capacity": {"cpu": "8", "memory": "32Gi"},
                          "allocatable": {"cpu": "7500m", "memory": "30Gi"},
                          "conditions": [{"type": "Ready", "status": "True"}],
                          "images": [{"names": ["nginx:1.25"],
                                      "sizeBytes": 1000000}]}}
        node = default_scheme().decode(json.dumps(doc))
        assert node.spec.taints[0].key == "gpu"
        assert node.spec.pod_cidr == "10.0.3.0/24"
        assert node.status.allocatable["cpu"] == "7500m"
        assert node.status.images[0].size_bytes == 1000000
        wire = default_scheme().encode(node)
        node2 = default_scheme().decode(json.dumps(wire))
        assert node2.status.allocatable == node.status.allocatable
        assert node2.spec.taints == node.spec.taints

    def test_not_ready_condition(self):
        doc = {"apiVersion": "v1", "kind": "Node", "metadata": {"name": "n"},
               "status": {"conditions": [{"type": "Ready", "status": "False"}]}}
        node = default_scheme().decode(json.dumps(doc))
        assert node.status.ready is False


class TestOtherKinds:
    def test_pdb_and_priority_class(self):
        scheme = default_scheme()
        pdb = scheme.decode(json.dumps({
            "apiVersion": "policy/v1", "kind": "PodDisruptionBudget",
            "metadata": {"name": "pdb"},
            "spec": {"minAvailable": "50%",
                     "selector": {"matchLabels": {"app": "web"}}}}))
        assert pdb.min_available == "50%"
        pc = scheme.decode(json.dumps({
            "apiVersion": "scheduling.k8s.io/v1", "kind": "PriorityClass",
            "metadata": {"name": "high"}, "value": 1000}))
        assert pc.value == 1000

    def test_deployment_with_template(self):
        dep = default_scheme().decode(json.dumps({
            "apiVersion": "apps/v1", "kind": "Deployment",
            "metadata": {"name": "web", "namespace": "prod"},
            "spec": {"replicas": 3,
                     "selector": {"matchLabels": {"app": "web"}},
                     "template": {
                         "metadata": {"labels": {"app": "web"}},
                         "spec": {"containers": [{
                             "name": "c", "image": "nginx",
                             "resources": {"requests": {"cpu": "100m"}}}]}},
                     "strategy": {"type": "RollingUpdate",
                                  "rollingUpdate": {"maxSurge": 2,
                                                    "maxUnavailable": 0}}}}))
        assert dep.replicas == 3 and dep.max_surge == 2 and dep.max_unavailable == 0
        assert dep.template.meta.labels == {"app": "web"}
        assert dep.template.spec.containers[0].requests["cpu"] == "100m"

    def test_hpa_v2(self):
        hpa = default_scheme().decode(json.dumps({
            "apiVersion": "autoscaling/v2", "kind": "HorizontalPodAutoscaler",
            "metadata": {"name": "web"},
            "spec": {"scaleTargetRef": {"kind": "Deployment", "name": "web"},
                     "minReplicas": 2, "maxReplicas": 20,
                     "metrics": [{"type": "Resource", "resource": {
                         "name": "cpu",
                         "target": {"type": "Utilization",
                                    "averageUtilization": 60}}}]}}))
        assert hpa.max_replicas == 20 and hpa.target_cpu_utilization == 60


class TestSchemeMachinery:
    def test_unknown_gvk_errors(self):
        with pytest.raises(SchemeError, match="no kind registered"):
            default_scheme().decode(json.dumps(
                {"apiVersion": "example.com/v1", "kind": "Widget"}))

    def test_missing_kind_errors(self):
        with pytest.raises(SchemeError, match="missing kind"):
            default_scheme().decode(json.dumps({"apiVersion": "v1"}))

    def test_encode_wrong_type_errors(self):
        with pytest.raises(SchemeError):
            default_scheme().encode(
                Pod(), GroupVersionKind("", "v1", "Node"))

    def test_custom_registration(self):
        import dataclasses

        @dataclasses.dataclass
        class Widget:
            name: str = ""

        s = Scheme()
        gvk = GroupVersionKind("example.com", "v1", "Widget")
        s.add_known_type(gvk, Widget,
                         lambda d: Widget(name=d.get("spec", {}).get("name", "")),
                         lambda w: {"spec": {"name": w.name}})
        s.add_defaulter(Widget, lambda w: setattr(
            w, "name", w.name or "unnamed"))
        w = s.decode(json.dumps({"apiVersion": "example.com/v1",
                                 "kind": "Widget", "spec": {}}))
        assert w.name == "unnamed"
        assert s.encode(w)["spec"]["name"] == "unnamed"


class TestHTTPManifestIngestion:
    def test_post_k8s_manifest_over_http(self):
        import urllib.request

        from kubernetes_tpu.apiserver.http import serve_api, shutdown_api
        from kubernetes_tpu.apiserver.store import ClusterStore

        store = ClusterStore()
        server, port = serve_api(store)
        try:
            body = json.dumps(POD_MANIFEST).encode()
            # the manifest names namespace prod; create it first
            from kubernetes_tpu.api.types import Namespace, ObjectMeta

            store.create_namespace(Namespace(meta=ObjectMeta(name="prod")))
            from kubernetes_tpu.api.types import PriorityClass

            store.create_priority_class(PriorityClass(
                meta=ObjectMeta(name="high"), value=1000))
            store.create_object("ServiceAccount", __import__(
                "kubernetes_tpu.api.types", fromlist=["ServiceAccount"]
            ).ServiceAccount(meta=ObjectMeta(name="web", namespace="prod")))
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/api/v1/namespaces/prod/pods",
                data=body, method="POST",
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=10) as resp:
                assert resp.status == 201
            pod = store.get_pod("prod/web-0")
            assert pod is not None
            assert pod.spec.affinity.node_affinity.required is not None
            assert pod.spec.topology_spread_constraints[0].topology_key == \
                "topology.kubernetes.io/zone"
        finally:
            shutdown_api(server)
