"""Speculative decode (vectorized decide/repair rounds) vs the sequential
scan: EXACT placement parity — the prefix-stability acceptance must
reproduce the scan's per-pod choices, not just the same load shape."""

import numpy as np
import pytest

from kubernetes_tpu.api.wrappers import make_node, make_pod
from kubernetes_tpu.apiserver import ClusterStore
from kubernetes_tpu.backend import TPUScheduler
from kubernetes_tpu.backend.batch import build_schedule_batch_fn


def _mk_inputs(n_nodes, pods, batch):
    store = ClusterStore()
    sched = TPUScheduler(store, batch_size=batch)
    for i in range(n_nodes):
        store.create_node(
            make_node(f"n{i}")
            .capacity({"cpu": ["4", "8", "16"][i % 3], "memory": "16Gi",
                       "pods": 20})
            .label("zone", f"z{i % 3}").obj())
    sched._ensure_device()
    sched.cache.update_snapshot(sched.snapshot)
    sched.device.sync(sched.snapshot)
    pb, et = sched.device.encoder.encode_pods(pods)
    tb = sched.device.sig_table.encode_topo(pods)
    return sched, pb, et, tb


def _run(monkeypatch, flag, n_nodes, pods, batch):
    monkeypatch.setenv("KTPU_SPEC", flag)
    monkeypatch.setenv("KTPU_PALLAS", "0")
    sched, pb, et, tb = _mk_inputs(n_nodes, pods, batch)
    fn = build_schedule_batch_fn()
    r = fn(pb, et, sched.device.nt, sched.device.tc, tb, np.int32(7),
           topo_enabled=False)
    return (np.asarray(r.node_idx), np.asarray(r.any_feasible),
            np.asarray(r.final_requested), np.asarray(r.first_fail),
            np.asarray(r.final_class_req))


class TestExactParity:
    def _check(self, monkeypatch, pods, n_nodes=24, batch=32):
        idx_a, anyf_a, req_a, ff_a, cls_a = _run(
            monkeypatch, "0", n_nodes, pods, batch)
        idx_b, anyf_b, req_b, ff_b, cls_b = _run(
            monkeypatch, "1", n_nodes, pods, batch)
        np.testing.assert_array_equal(idx_a, idx_b)
        np.testing.assert_array_equal(anyf_a, anyf_b)
        np.testing.assert_array_equal(req_a, req_b)
        np.testing.assert_array_equal(cls_a, cls_b)
        # decision-time rows must match for every VALID pod — failures
        # (first_fail drives per-node failure attribution) AND winners
        # (mixed components are each pod's exact sequential view)
        valid = (idx_a >= 0) | ~anyf_a
        np.testing.assert_array_equal(ff_a[valid], ff_b[valid])

    def test_uniform_pods(self, monkeypatch):
        pods = [make_pod(f"p{i}").req({"cpu": "500m", "memory": "1Gi"}).obj()
                for i in range(24)]
        self._check(monkeypatch, pods)

    def test_mixed_sizes_with_conflicts(self, monkeypatch):
        # big pods force intra-batch capacity conflicts -> multiple rounds
        pods = [make_pod(f"p{i}").req(
            {"cpu": ["3500m", "7", "300m"][i % 3], "memory": "2Gi"}).obj()
            for i in range(30)]
        self._check(monkeypatch, pods)

    def test_unschedulable_pods(self, monkeypatch):
        pods = [make_pod(f"p{i}").req({"cpu": "500m"}).obj() for i in range(6)]
        pods.append(make_pod("huge").req({"cpu": "64"}).obj())
        pods.append(make_pod("huge2").req({"cpu": "64"}).obj())
        idx_a, anyf_a, *_ = _run(monkeypatch, "0", 8, pods, 16)
        idx_b, anyf_b, *_ = _run(monkeypatch, "1", 8, pods, 16)
        np.testing.assert_array_equal(idx_a, idx_b)
        np.testing.assert_array_equal(anyf_a, anyf_b)
        assert idx_a[6] == -1 and idx_a[7] == -1

    def test_host_ports_conflict(self, monkeypatch):
        pods = [make_pod(f"p{i}").req({"cpu": "100m"}).host_port(8080).obj()
                for i in range(6)]
        self._check(monkeypatch, pods, n_nodes=4, batch=8)

    def test_priorities_and_selectors(self, monkeypatch):
        pods = []
        for i in range(20):
            pw = make_pod(f"p{i}").req({"cpu": "800m"}).priority(i % 4)
            if i % 5 == 0:
                pw.node_selector({"zone": "z1"})
            if i % 7 == 0:
                pw.preferred_node_affinity(5, "zone", ["z2"])
            pods.append(pw.obj())
        self._check(monkeypatch, pods)

    def test_normalization_coupling_near_capacity(self, monkeypatch):
        # the stability hazard: preferred-affinity max nodes fill up mid
        # round, shrinking later pods' feasible sets and rescaling every
        # normalized score — the exact-mix check must keep parity
        pods = []
        for i in range(24):
            pw = make_pod(f"p{i}").req({"cpu": "3500m"})  # ~1 pod per 4-cpu node
            pw.preferred_node_affinity(10, "zone", ["z0"])
            pw.preferred_node_affinity(3, "zone", ["z1"])
            pods.append(pw.obj())
        self._check(monkeypatch, pods, n_nodes=12, batch=32)

    def test_interleaved_failures_and_commits(self, monkeypatch):
        # failing pods interleaved between winners exercise the fail-before-
        # first-winner prefix rule
        pods = []
        for i in range(16):
            if i % 3 == 2:
                pods.append(make_pod(f"big{i}").req({"cpu": "64"}).obj())
            else:
                pods.append(make_pod(f"p{i}").req({"cpu": "900m"}).obj())
        self._check(monkeypatch, pods, n_nodes=6, batch=16)

    def test_one_slot_node_capacity_conflict(self, monkeypatch):
        # the flagship conflict case: 3 identical pods, one 1-pod node
        store = ClusterStore()
        monkeypatch.setenv("KTPU_SPEC", "1")
        monkeypatch.setenv("KTPU_PALLAS", "0")
        sched = TPUScheduler(store, batch_size=8)
        store.create_node(make_node("only").capacity(
            {"cpu": "1", "memory": "2Gi", "pods": 10}).obj())
        for i in range(3):
            store.create_pod(make_pod(f"p{i}").req({"cpu": "900m"}).obj())
        sched.run_until_settled(max_no_progress=3)
        bound = [p for p in store.pods.values() if p.spec.node_name]
        assert len(bound) == 1


def _run_sched(monkeypatch, flag, build_workload, n_nodes=12, batch=16):
    """Full TPUScheduler run under KTPU_SPEC=flag; returns {pod: node}."""
    monkeypatch.setenv("KTPU_SPEC", flag)
    monkeypatch.setenv("KTPU_PALLAS", "0")
    store = ClusterStore()
    sched = TPUScheduler(store, batch_size=batch, comparer_every_n=1)
    for i in range(n_nodes):
        store.create_node(
            make_node(f"n{i}").capacity({"cpu": "8", "memory": "16Gi", "pods": 20})
            .label("zone", f"z{i % 3}").obj())
    build_workload(store)
    sched.run_until_settled(max_no_progress=5)
    assert sched.comparer_mismatches == 0
    return {p.meta.name: p.spec.node_name for p in store.pods.values()}


class TestHostModeTopologyParity:
    """Hostname-topology batches (the host fast path) through the
    speculative rounds: placements must match the scan exactly, with the
    oracle comparer checking every placement on both runs."""

    def _check(self, monkeypatch, build_workload, **kw):
        a = _run_sched(monkeypatch, "0", build_workload, **kw)
        b = _run_sched(monkeypatch, "1", build_workload, **kw)
        assert a == b

    def test_hostname_spread(self, monkeypatch):
        from kubernetes_tpu.api.types import LabelSelector

        def workload(store):
            for i in range(20):
                store.create_pod(
                    make_pod(f"p{i}").req({"cpu": "500m"}).label("app", "web")
                    .spread_constraint(
                        1, "kubernetes.io/hostname",
                        selector=LabelSelector(match_labels={"app": "web"}))
                    .obj())

        self._check(monkeypatch, workload)

    def test_hostname_anti_affinity(self, monkeypatch):
        from kubernetes_tpu.api.types import LabelSelector

        def workload(store):
            for i in range(14):
                store.create_pod(
                    make_pod(f"p{i}").req({"cpu": "500m"}).label("app", "db")
                    .pod_affinity("kubernetes.io/hostname",
                                  LabelSelector(match_labels={"app": "db"}),
                                  anti=True)
                    .obj())

        self._check(monkeypatch, workload, n_nodes=10)

    def test_hostname_anti_affinity_overflow_unschedulable(self, monkeypatch):
        # more exclusive pods than nodes: the tail must fail identically
        from kubernetes_tpu.api.types import LabelSelector

        def workload(store):
            for i in range(8):
                store.create_pod(
                    make_pod(f"p{i}").req({"cpu": "100m"}).label("app", "x")
                    .pod_affinity("kubernetes.io/hostname",
                                  LabelSelector(match_labels={"app": "x"}),
                                  anti=True)
                    .obj())

        a = _run_sched(monkeypatch, "0", workload, n_nodes=5, batch=8)
        b = _run_sched(monkeypatch, "1", workload, n_nodes=5, batch=8)
        assert a == b
        assert sum(1 for v in a.values() if v) == 5  # one per node

    def test_required_self_affinity_first_pod_rule(self, monkeypatch):
        # IPA's first-pod rule (total==0 & self-match ⇒ feasible anywhere)
        # flips globally once the first pod lands: a mid-round winner's
        # mixed view can collapse to all-infeasible — the stability check's
        # chosen-feasibility guard must defer it, keeping scan parity
        from kubernetes_tpu.api.types import LabelSelector

        def workload(store):
            for i in range(10):
                store.create_pod(
                    make_pod(f"p{i}").req({"cpu": "500m"}).label("app", "herd")
                    .pod_affinity("kubernetes.io/hostname",
                                  LabelSelector(match_labels={"app": "herd"}))
                    .obj())

        a = _run_sched(monkeypatch, "0", workload, n_nodes=6, batch=16)
        b = _run_sched(monkeypatch, "1", workload, n_nodes=6, batch=16)
        assert a == b
        # required colocation on hostname: everyone lands on ONE node
        nodes = {v for v in a.values() if v}
        assert len(nodes) == 1

    def test_mixed_spread_affinity_priorities(self, monkeypatch):
        from kubernetes_tpu.api.types import LabelSelector, SCHEDULE_ANYWAY

        def workload(store):
            for i in range(24):
                pw = (make_pod(f"p{i}").req({"cpu": ["250m", "1"][i % 2]})
                      .label("app", f"svc{i % 2}").priority(i % 3))
                if i % 2 == 0:
                    pw.spread_constraint(
                        2, "kubernetes.io/hostname",
                        when_unsatisfiable=SCHEDULE_ANYWAY,
                        selector=LabelSelector(match_labels={"app": "svc0"}))
                else:
                    pw.preferred_pod_affinity(
                        10, "kubernetes.io/hostname",
                        LabelSelector(match_labels={"app": "svc1"}))
                store.create_pod(pw.obj())

        self._check(monkeypatch, workload)


class TestGeneralModeTopologyParity:
    """Zone-keyed (general domain-aggregating) topology through the
    speculative rounds: exact scan parity, all placements oracle-checked."""

    def _check(self, monkeypatch, build_workload, **kw):
        a = _run_sched(monkeypatch, "0", build_workload, **kw)
        b = _run_sched(monkeypatch, "1", build_workload, **kw)
        assert a == b

    def test_zone_spread_do_not_schedule(self, monkeypatch):
        from kubernetes_tpu.api.types import LabelSelector

        def workload(store):
            for i in range(18):
                store.create_pod(
                    make_pod(f"p{i}").req({"cpu": "500m"}).label("app", "web")
                    .spread_constraint(
                        1, "zone",
                        selector=LabelSelector(match_labels={"app": "web"}))
                    .obj())

        self._check(monkeypatch, workload)

    def test_zone_anti_affinity(self, monkeypatch):
        from kubernetes_tpu.api.types import LabelSelector

        def workload(store):
            # 3 zones: only 3 of 5 exclusive pods can place
            for i in range(5):
                store.create_pod(
                    make_pod(f"p{i}").req({"cpu": "250m"}).label("app", "zdb")
                    .pod_affinity("zone",
                                  LabelSelector(match_labels={"app": "zdb"}),
                                  anti=True)
                    .obj())

        a = _run_sched(monkeypatch, "0", workload, n_nodes=9, batch=8)
        b = _run_sched(monkeypatch, "1", workload, n_nodes=9, batch=8)
        assert a == b
        assert sum(1 for v in a.values() if v) == 3

    def test_zone_required_affinity_colocates(self, monkeypatch):
        from kubernetes_tpu.api.types import LabelSelector

        def workload(store):
            for i in range(9):
                store.create_pod(
                    make_pod(f"p{i}").req({"cpu": "500m"}).label("app", "herd")
                    .pod_affinity("zone",
                                  LabelSelector(match_labels={"app": "herd"}))
                    .obj())

        a = _run_sched(monkeypatch, "0", workload, n_nodes=9, batch=16)
        b = _run_sched(monkeypatch, "1", workload, n_nodes=9, batch=16)
        assert a == b
        zones = {int(v[1:]) % 3 for v in a.values() if v}
        assert len(zones) == 1  # required zone colocation

    def test_mixed_zone_spread_preferred_affinity(self, monkeypatch):
        from kubernetes_tpu.api.types import LabelSelector, SCHEDULE_ANYWAY

        def workload(store):
            for i in range(24):
                pw = (make_pod(f"p{i}").req({"cpu": ["250m", "1"][i % 2]})
                      .label("app", f"svc{i % 2}"))
                if i % 2 == 0:
                    pw.spread_constraint(
                        2, "zone", when_unsatisfiable=SCHEDULE_ANYWAY,
                        selector=LabelSelector(match_labels={"app": "svc0"}))
                else:
                    pw.preferred_pod_affinity(
                        10, "zone", LabelSelector(match_labels={"app": "svc1"}))
                store.create_pod(pw.obj())

        self._check(monkeypatch, workload)

    def test_zone_spread_min_domains_and_self_anti(self, monkeypatch):
        from kubernetes_tpu.api.types import LabelSelector

        def workload(store):
            # spread + anti-affinity interactions across one batch
            for i in range(12):
                pw = (make_pod(f"p{i}").req({"cpu": "500m"})
                      .label("app", "mix"))
                pw.spread_constraint(
                    1, "zone",
                    selector=LabelSelector(match_labels={"app": "mix"}))
                if i % 4 == 0:
                    pw.pod_affinity("zone",
                                    LabelSelector(match_labels={"app": "mix"}),
                                    anti=True)
                store.create_pod(pw.obj())

        self._check(monkeypatch, workload)


class TestEndToEndForcedSpec:
    def test_full_scheduler_with_spec_decode(self, monkeypatch):
        monkeypatch.setenv("KTPU_SPEC", "1")
        monkeypatch.setenv("KTPU_PALLAS", "0")
        store = ClusterStore()
        sched = TPUScheduler(store, batch_size=64)
        for i in range(16):
            store.create_node(make_node(f"n{i}").capacity(
                {"cpu": "8", "memory": "16Gi", "pods": 30}).obj())
        for i in range(200):
            store.create_pod(make_pod(f"p{i}").req(
                {"cpu": "500m", "memory": "512Mi"}).obj())
        sched.run_until_settled()
        assert sched.metrics["scheduled"] == 200
        assert sched.comparer_mismatches == 0
