"""Round-3 kubectl verb breadth: -o yaml/json through the versioned scheme,
top (metrics seam), auth can-i (RBAC), rollout status/history."""

import json

import yaml

from kubernetes_tpu.api.types import Deployment, ObjectMeta, OwnerReference, ReplicaSet
from kubernetes_tpu.api.wrappers import make_node, make_pod
from kubernetes_tpu.apiserver.auth import ClusterRole, ClusterRoleBinding, PolicyRule, RBACAuthorizer
from kubernetes_tpu.apiserver.store import ClusterStore
from kubernetes_tpu.kubectl.cli import kubectl


def _cluster():
    store = ClusterStore()
    store.create_node(make_node("n1").capacity(
        {"cpu": "4", "memory": "8Gi", "pods": 10}).obj())
    pod = make_pod("web").req({"cpu": "500m"}).label("app", "web").obj()
    store.create_pod(pod)
    return store


class TestOutputFormats:
    def test_get_pod_o_yaml_is_versioned_manifest(self):
        store = _cluster()
        out = kubectl(store, "get pods web -o yaml")
        doc = yaml.safe_load(out)
        assert doc["apiVersion"] == "v1" and doc["kind"] == "Pod"
        assert doc["metadata"]["name"] == "web"
        assert doc["spec"]["containers"][0]["resources"]["requests"]["cpu"] == "500m"

    def test_get_o_json_list(self):
        store = _cluster()
        store.create_pod(make_pod("web2").req({"cpu": "100m"}).obj())
        doc = json.loads(kubectl(store, "get pods -o json"))
        assert doc["kind"] == "List" and len(doc["items"]) == 2

    def test_yaml_round_trips_through_apply(self, tmp_path):
        store = _cluster()
        out = kubectl(store, "get pods web -o yaml")
        f = tmp_path / "pod.yaml"
        f.write_text(out.replace("name: web", "name: web-copy"))
        store2 = ClusterStore()
        msg = kubectl(store2, f"create -f {f}")
        assert "created" in msg
        assert store2.get_pod("default/web-copy") is not None


class TestTop:
    def test_top_pods_and_nodes(self):
        store = _cluster()
        store.pod_metrics["default/web"] = 250
        # bind the pod so node aggregation sees it
        from kubernetes_tpu.api.types import Binding

        store.bind(Binding(pod_key="default/web", node_name="n1"))
        pods_out = kubectl(store, "top pods")
        assert "web" in pods_out and "250m" in pods_out
        nodes_out = kubectl(store, "top nodes")
        assert "n1" in nodes_out and "250m" in nodes_out and "6%" in nodes_out


class TestAuthCanI:
    def test_can_i_against_rbac(self):
        store = ClusterStore()
        store.create_object("ClusterRole", ClusterRole(
            meta=ObjectMeta(name="reader"),
            rules=(PolicyRule(verbs=("get", "list"), resources=("Pod",)),)))
        store.create_object("ClusterRoleBinding", ClusterRoleBinding(
            meta=ObjectMeta(name="rb"), role="reader", subjects=("user:alice",)))
        store.authorizer = RBACAuthorizer(store)
        assert kubectl(store, "auth can-i list pods --as alice") == "yes"
        assert kubectl(store, "auth can-i delete pods --as alice") == "no"
        assert kubectl(store, "auth can-i delete nodes") == "yes"  # admin/masters


class TestRollout:
    def _deployment(self, store):
        store.create_object("Deployment", Deployment(
            meta=ObjectMeta(name="web"), replicas=2))
        store.create_object("ReplicaSet", ReplicaSet(
            meta=ObjectMeta(
                name="web-1", annotations={"deployment.kubernetes.io/revision": "1"},
                owner_references=(OwnerReference(
                    kind="Deployment", name="web", controller=True),)),
            replicas=2))
        for i in range(2):
            p = make_pod(f"web-{i}").req({"cpu": "100m"}).obj()
            p.meta.owner_references = (OwnerReference(
                kind="ReplicaSet", name="web-1", controller=True),)
            store.create_pod(p)

    def test_status_waits_then_succeeds(self):
        store = ClusterStore()
        self._deployment(store)
        out = kubectl(store, "rollout status deployment web")
        assert "Waiting" in out
        from kubernetes_tpu.api.types import Binding

        store.create_node(make_node("n1").capacity(
            {"cpu": "4", "memory": "8Gi", "pods": 10}).obj())
        store.bind(Binding(pod_key="default/web-0", node_name="n1"))
        store.bind(Binding(pod_key="default/web-1", node_name="n1"))
        out = kubectl(store, "rollout status deployment web")
        assert "successfully rolled out" in out

    def test_history_lists_revisions(self):
        store = ClusterStore()
        self._deployment(store)
        out = kubectl(store, "rollout history deployment web")
        assert "REVISION" in out and "web-1" in out

    def test_status_waits_on_new_revision(self):
        # mid-rollout: old-revision pods bound, new revision empty -> waiting
        store = ClusterStore()
        self._deployment(store)
        store.create_node(make_node("n1").capacity(
            {"cpu": "4", "memory": "8Gi", "pods": 10}).obj())
        from kubernetes_tpu.api.types import Binding

        store.bind(Binding(pod_key="default/web-0", node_name="n1"))
        store.bind(Binding(pod_key="default/web-1", node_name="n1"))
        store.create_object("ReplicaSet", ReplicaSet(
            meta=ObjectMeta(
                name="web-2",
                annotations={"deployment.kubernetes.io/revision": "2"},
                owner_references=(OwnerReference(
                    kind="Deployment", name="web", controller=True),)),
            replicas=2))
        out = kubectl(store, "rollout status deployment web")
        assert "Waiting" in out
