"""API aggregation (kube-aggregator, SURVEY §2.6): a non-local APIService
claiming /apis/{group}/{version} makes the apiserver proxy those requests to
its backend apiserver verbatim and relay the response
(kube-aggregator pkg/apiserver/handler_proxy.go, reduced: plain HTTP).
"""

import json
import urllib.error
import urllib.request

from kubernetes_tpu.api.types import (
    APIService, CustomResourceDefinition, CustomResource, ObjectMeta,
)
from kubernetes_tpu.apiserver import ClusterStore
from kubernetes_tpu.apiserver.http import serve_api


def _backend_with_metrics_group():
    """A second (aggregated) apiserver serving metrics.k8s.io/v1beta1 via a
    CRD-backed kind — the metrics-server shape."""
    store = ClusterStore()
    store.create_crd(CustomResourceDefinition(
        meta=ObjectMeta(name="nodemetrics.metrics.k8s.io", namespace=""),
        group="metrics.k8s.io", version="v1beta1", kind="NodeMetrics",
        plural="nodemetrics", namespaced=False))
    server, port = serve_api(store)
    return store, server, port


def test_apiservice_proxies_group_to_backend():
    backend_store, backend, bport = _backend_with_metrics_group()
    front_store = ClusterStore()
    front, fport = serve_api(front_store)
    try:
        front_store.create_object("APIService", APIService(
            meta=ObjectMeta(name="v1beta1.metrics.k8s.io", namespace=""),
            group="metrics.k8s.io", version="v1beta1",
            service_endpoint=f"127.0.0.1:{bport}"))
        base = f"http://127.0.0.1:{fport}"
        # POST through the FRONT apiserver lands on the backend
        body = json.dumps({
            "apiVersion": "metrics.k8s.io/v1beta1", "kind": "NodeMetrics",
            "metadata": {"name": "node-1"}, "spec": {"cpu": "250m"},
        }).encode()
        req = urllib.request.Request(
            f"{base}/apis/metrics.k8s.io/v1beta1/nodemetrics", data=body,
            headers={"Content-Type": "application/json"}, method="POST")
        with urllib.request.urlopen(req) as resp:
            assert resp.status in (200, 201)
        assert backend_store.get_object("NodeMetrics", "node-1") is not None
        # GET through the front reads the backend's object
        with urllib.request.urlopen(
                f"{base}/apis/metrics.k8s.io/v1beta1/nodemetrics/node-1") as resp:
            doc = json.loads(resp.read())
        assert doc["spec"]["cpu"] == "250m"
        # unclaimed group still 404s at the front
        try:
            urllib.request.urlopen(f"{base}/apis/unclaimed.io/v1/things")
            raise AssertionError("expected 404")
        except urllib.error.HTTPError as e:
            assert e.code == 404
    finally:
        front.shutdown()
        backend.shutdown()


def test_dead_backend_is_503_not_hang():
    front_store = ClusterStore()
    front, fport = serve_api(front_store)
    try:
        front_store.create_object("APIService", APIService(
            meta=ObjectMeta(name="v1.dead.io", namespace=""),
            group="dead.io", version="v1",
            service_endpoint="127.0.0.1:1"))  # nothing listens there
        try:
            urllib.request.urlopen(
                f"http://127.0.0.1:{fport}/apis/dead.io/v1/things", timeout=40)
            raise AssertionError("expected 503")
        except urllib.error.HTTPError as e:
            assert e.code == 503
    finally:
        front.shutdown()


def test_local_groups_not_shadowed():
    """Built-in and CRD routes win before aggregation is consulted."""
    store = ClusterStore()
    server, port = serve_api(store)
    try:
        store.create_object("APIService", APIService(
            meta=ObjectMeta(name="v1.apps", namespace=""),
            group="apps", version="v1",
            service_endpoint="127.0.0.1:1"))  # would 503 if consulted
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/apis/apps/v1/deployments") as resp:
            assert resp.status == 200  # served locally
    finally:
        server.shutdown()
