"""Device-dispatch profiler (backend/telemetry.py DispatchLedger): the
dwell/exec/fetch decomposition of the blocking commit wait, the XLA cost
ledger, the /debug/dispatch surface, the Chrome-trace device track, the
wire-echoed per-batch device time, and the disabled contract — off by
default, one global read, zero placement drift."""

import types

import numpy as np
import pytest

from kubernetes_tpu.api.wrappers import make_node, make_pod
from kubernetes_tpu.apiserver import ClusterStore
from kubernetes_tpu.backend import TPUScheduler, telemetry
from kubernetes_tpu.backend.telemetry import DispatchLedger
from kubernetes_tpu.metrics.scheduler_metrics import SchedulerMetrics
from kubernetes_tpu.utils import tracing


@pytest.fixture(autouse=True)
def _clean_recorder():
    telemetry.disable()
    tracing.disable()
    yield
    telemetry.disable()
    tracing.disable()


# ------------------------------------------------------------ disabled cost


class TestDisabledContract:
    """Profiler off (the default): every hook returns after ONE module-
    global read, recording and allocating nothing."""

    def test_disabled_hooks_are_noops(self):
        assert telemetry.get() is None
        assert telemetry.dispatch_window(
            "p", t_submit=0.0, t_wait0=0.0, t_exec_done=1.0,
            t_wait_end=1.0) is None
        assert telemetry.dispatch_phases(
            "p", dwell_s=0.1, exec_s=0.2, fetch_s=0.0) is None
        assert telemetry.cost_probe("p", "b", lambda x: x) is None
        telemetry.emit_phase_spans(None)  # no-op, no error

    def test_disabled_materialize_profiled_is_materialize_result(self):
        from kubernetes_tpu.backend.commit_plane import materialize_profiled

        result = types.SimpleNamespace(packed=None,
                                       node_idx=np.arange(4, dtype=np.int32))
        (node_idx, ff, slice_words, quota_words,
         packed_ok), disp = materialize_profiled(
            result, 4, program="schedule_batch")
        assert disp is None
        assert ff is None and slice_words is None and not packed_ok
        assert quota_words is None
        np.testing.assert_array_equal(node_idx, np.arange(4))

    def test_program_names_registry_is_declared(self):
        # the attribution vocabulary the ktpu_check dispatch lint enforces
        assert "schedule_batch" in telemetry.PROGRAM_NAMES
        assert "wire_schedule_batch" in telemetry.PROGRAM_NAMES


# ------------------------------------------------------------- ledger math


class TestLedgerMath:
    """FakeClock-exact phase accumulation: hand the ledger raw timestamps
    and check every derived number."""

    def test_record_window_exact_phases_and_window_partition(self):
        m = SchedulerMetrics()
        led = DispatchLedger([m])
        rec = led.record_window(
            "prog", "8/off", t_submit=10.0, t_wait0=10.05,
            t_exec_done=10.2, t_wait_end=10.3, batch_id="b1", pods=7,
            fetch_bytes=512)
        # idle device: execution starts at submit
        assert rec["dwellS"] == 0.0
        assert rec["execS"] == pytest.approx(0.2)
        assert rec["fetchS"] == pytest.approx(0.1)
        assert rec["waitS"] == pytest.approx(0.25)
        # the wait-window partition sums to the wait EXACTLY
        w = rec["window"]
        assert w["dwell"] == pytest.approx(0.0)
        assert w["exec"] == pytest.approx(0.15)
        assert w["fetch"] == pytest.approx(0.1)
        assert w["dwell"] + w["exec"] + w["fetch"] == pytest.approx(
            rec["waitS"])
        st = led.stats[("prog", "8/off")]
        assert st["count"] == 1 and st["fetchBytes"] == 512
        assert st["execS"] == pytest.approx(0.2)
        # histogram fed once per phase
        for phase in ("dwell", "exec", "fetch"):
            assert m.device_dispatch_duration.count("prog", phase) == 1

    def test_pipelined_overlap_produces_dwell(self):
        """Ring depth 2: batch K+1 submitted while batch K still executes
        must dwell until the device frees — the horizon inference."""
        led = DispatchLedger()
        led.record_window("prog", t_submit=10.0, t_wait0=10.9,
                          t_exec_done=11.0, t_wait_end=11.05)
        rec2 = led.record_window("prog", t_submit=10.5, t_wait0=11.0,
                                 t_exec_done=11.4, t_wait_end=11.5)
        # exec could not start before batch 1's exec end at 11.0
        assert rec2["dwellS"] == pytest.approx(0.5)
        assert rec2["execS"] == pytest.approx(0.4)
        w = rec2["window"]
        assert w["dwell"] + w["exec"] + w["fetch"] == pytest.approx(
            rec2["waitS"])

    def test_record_phases_does_not_move_the_busy_horizon(self):
        """The wire client's phases live in the SERVER's clock domain —
        they must never push the local device-busy horizon forward."""
        led = DispatchLedger()
        led.record_phases("wire_schedule_batch", "64",
                          dwell_s=5.0, exec_s=100.0, fetch_s=1.0,
                          batch_id="w1", pods=3)
        rec = led.record_window("prog", t_submit=10.0, t_wait0=10.0,
                                t_exec_done=10.1, t_wait_end=10.1)
        assert rec["dwellS"] == 0.0  # horizon untouched by record_phases
        st = led.stats[("wire_schedule_batch", "64")]
        assert st["waitS"] == pytest.approx(106.0)  # defaulted to the sum

    def test_dump_programs_table_truncation_and_achieved_rates(self):
        led = DispatchLedger(capacity=4)
        for i in range(6):
            led.record_window("prog", "8", t_submit=float(i),
                              t_wait0=float(i), t_exec_done=i + 0.5,
                              t_wait_end=i + 0.6, batch_id=f"b{i}")
        led.costs[("prog", "8")] = {"flops": 1e6, "bytesAccessed": 4e3}
        body = led.dump(limit=0)
        assert body["enabled"] is True
        assert body["ring"] == {"capacity": 4, "recorded": 6, "held": 4}
        assert body["records"] == []
        assert body["truncated"] == {"records": 4}
        entry = body["programs"]["prog@8"]
        assert entry["count"] == 6
        # 6 dispatches x 1e6 flops over 3.0s exec == 2e6 flop/s
        assert entry["achievedFlopsPerS"] == pytest.approx(2e6)
        assert entry["achievedBytesPerS"] == pytest.approx(8e3)
        # uncapped dump returns the held tail in order
        full = led.dump()
        assert [r["batchId"] for r in full["records"]] == [
            "b2", "b3", "b4", "b5"]
        assert "truncated" not in full


# -------------------------------------------------------------- cost probe


class TestCostLedger:
    def test_slot_claimed_once_even_when_probe_fails(self):
        led = DispatchLedger()
        calls = []

        class Fn:
            def lower(self, *a, **k):
                calls.append(1)
                raise RuntimeError("no cost analysis here")

        fn = Fn()
        led.maybe_cost("prog", "8", fn)
        led.maybe_cost("prog", "8", fn)  # slot claimed: not probed again
        assert len(calls) == 1
        assert led.costs[("prog", "8")] == {}
        # a function without .lower is skipped without claiming an error
        led.maybe_cost("other", None, lambda x: x)
        assert led.costs[("other", "-")] == {}

    def test_real_probe_suppressed_from_compile_ledger(self):
        """The AOT cost probe compiles the program — that compile must NOT
        land in the CompileLedger (bench fences measured_compilations)."""
        import jax
        import jax.numpy as jnp

        t = telemetry.enable()

        @jax.jit
        def probe_fn(x):
            return (x * 2.0).sum()

        x = jnp.ones(4)  # argument build may itself compile helper jits
        before = t.ledger.total_compilations()
        telemetry.cost_probe("probe_prog", "4", probe_fn, (x,))
        assert t.ledger.total_compilations() == before
        cost = t.dispatch_ledger.costs[("probe_prog", "4")]
        # CPU XLA reports cost analysis; tolerate a backend that doesn't
        if cost:
            assert cost.get("flops", 0) > 0


# ------------------------------------------------- in-process parity + spans


def _run_small_cluster(n_nodes=12, n_pods=24):
    store = ClusterStore()
    sched = TPUScheduler(store, batch_size=8, comparer_every_n=1)
    for i in range(n_nodes):
        store.create_node(
            make_node(f"n{i}")
            .capacity({"cpu": str(4 + i % 5), "memory": "16Gi", "pods": 20})
            .label("zone", f"z{i % 3}").obj())
    for i in range(n_pods):
        store.create_pod(
            make_pod(f"p{i}").req({"cpu": "500m", "memory": "1Gi"}).obj())
    sched.run_until_settled()
    placements = {k: p.spec.node_name for k, p in store.pods.items()
                  if p.spec.node_name}
    return sched, placements


class TestProfiledCommitPath:
    def test_profiler_on_changes_no_placements_and_records_dispatches(self):
        telemetry.disable()
        sched_off, placements_off = _run_small_cluster()
        assert sched_off.comparer_mismatches == 0

        t = telemetry.enable(SchedulerMetrics())
        sched_on, placements_on = _run_small_cluster()
        assert sched_on.comparer_mismatches == 0
        assert placements_on == placements_off
        # the profiler observed every committed batch
        led = t.dispatch_ledger
        assert led.recorded > 0
        progs = {p for p, _b in led.stats}
        assert "schedule_batch" in progs
        for rec in led.dump()["records"]:
            w = rec["window"]
            assert w["dwell"] + w["exec"] + w["fetch"] == pytest.approx(
                rec["waitS"], abs=1e-9)
        # satellite: commit events carry the device/fetch attribution and
        # dispatch events the bucket signature
        commits = t.flight.events("commit")
        assert commits and all("device_ms" in e and "fetch_ms" in e
                               for e in commits)
        dispatches = t.flight.events("dispatch")
        assert dispatches and all("sig" in e for e in dispatches)

    def test_phase_spans_sum_to_commit_wait(self):
        """The waterfall invariant: device.dispatch.{dwell,exec,fetch}
        children partition device.commit.wait (within the span's own
        open/close overhead)."""
        telemetry.enable()
        exporter = tracing.enable(tracing.InMemoryExporter()).exporter
        try:
            _run_small_cluster(n_nodes=8, n_pods=16)
        finally:
            spans = list(exporter.spans)
            tracing.disable()
        by_id = {s.span_id: s for s in spans}
        waits = [s for s in spans if s.name == "device.commit.wait"]
        assert waits
        children = {}
        for s in spans:
            if s.name.startswith("device.dispatch."):
                children.setdefault(s.parent_id, []).append(s)
        covered = [w for w in waits if w.span_id in children]
        assert covered, "no commit.wait span has dispatch children"
        for w in covered:
            kids = children[w.span_id]
            assert {k.name for k in kids} == {
                "device.dispatch.dwell", "device.dispatch.exec",
                "device.dispatch.fetch"}
            ksum = sum(k.duration_s for k in kids)
            # children sum to the measured wait window, which the wait
            # span brackets with only record/emit overhead around it
            assert ksum <= w.duration_s + 0.005
            assert w.duration_s - ksum <= 0.1
            for k in kids:
                assert by_id[k.parent_id].name == "device.commit.wait"
                assert k.attributes["program"] == "schedule_batch"


# ----------------------------------------------------------- debug surfaces


class TestDebugSurfaces:
    def test_dispatch_handler_disabled_and_limit_zero(self):
        from kubernetes_tpu.cmd.server import build_debug_handlers

        handlers = build_debug_handlers(TPUScheduler(ClusterStore()))
        assert handlers["dispatch"]() == {"enabled": False}
        t = telemetry.enable()
        t.dispatch_ledger.record_window(
            "prog", t_submit=0.0, t_wait0=0.0, t_exec_done=0.1,
            t_wait_end=0.2, batch_id="b1")
        body = handlers["dispatch"]()
        assert body["enabled"] is True and len(body["records"]) == 1
        capped = handlers["dispatch"](limit=0)
        assert capped["records"] == []
        assert capped["truncated"] == {"records": 1}

    def test_timeline_device_track(self):
        from kubernetes_tpu.metrics.latency_ledger import chrome_trace

        led = DispatchLedger()
        rec = led.record_window("prog", "8", t_submit=1.0, t_wait0=1.0,
                                t_exec_done=1.2, t_wait_end=1.25,
                                batch_id="b9")
        doc = chrome_trace(dispatch=[rec])
        names = {ev["name"] for ev in doc["traceEvents"]
                 if ev.get("pid") == 4 and ev["ph"] == "X"}
        assert names == {"prog.dwell", "prog.exec", "prog.fetch"}
        meta = [ev for ev in doc["traceEvents"]
                if ev["ph"] == "M" and ev.get("pid") == 4]
        assert any(ev["args"]["name"] == "device dispatch" for ev in meta)
        slices = [ev for ev in doc["traceEvents"]
                  if ev.get("pid") == 4 and ev["ph"] == "X"]
        for ev in slices:
            assert ev["args"]["batchId"] == "b9"
            assert ev["dur"] >= 0


# ----------------------------------------------------------------- the wire


class TestWireDeviceTime:
    def test_proto_round_trip(self):
        from kubernetes_tpu.backend.grpc_service import (
            _device_time_from_proto, _device_time_to_proto)
        from kubernetes_tpu.native import ktpu_device_pb2 as pb

        resp = pb.ScheduleBatchResponse()
        assert _device_time_from_proto(resp) is None  # absent = profiler off
        _device_time_to_proto(resp, {})               # no deviceTime: no-op
        assert _device_time_from_proto(resp) is None
        out = {"deviceTime": {"dwellMs": 1.25, "execMs": 3.5,
                              "fetchMs": 0.75, "deviceMs": 4.25}}
        _device_time_to_proto(resp, out)
        assert _device_time_from_proto(resp) == out["deviceTime"]

    def test_wire_client_attributes_server_device_time(self):
        """HTTP round trip: the server echoes its dispatch decomposition,
        the client books transport dwell = rtt - device time under the
        wire_schedule_batch ledger program."""
        from kubernetes_tpu.backend.service import (
            DeviceService, WireScheduler, serve)

        t = telemetry.enable()
        service = DeviceService(batch_size=32)
        server, port = serve(service)
        try:
            store = ClusterStore()
            sched = WireScheduler(store,
                                  endpoint=f"http://127.0.0.1:{port}",
                                  batch_size=8)
            for i in range(4):
                store.create_node(make_node(f"n{i}").capacity(
                    {"cpu": "4", "memory": "8Gi", "pods": 10}).obj())
            for i in range(8):
                store.create_pod(
                    make_pod(f"p{i}").req({"cpu": "1"}).obj())
            sched.run_until_settled()
            assert sched.metrics["scheduled"] == 8
        finally:
            server.shutdown()
        led = t.dispatch_ledger
        progs = {p for p, _b in led.stats}
        # server half: the profiled commit; client half: the echo
        assert "schedule_batch" in progs
        assert "wire_schedule_batch" in progs
        wire = [r for r in led.dump()["records"]
                if r["program"] == "wire_schedule_batch"]
        assert wire
        for r in wire:
            # rtt >= server device time: transport dwell is non-negative
            assert r["dwellS"] >= 0.0
            assert r["waitS"] >= r["execS"] + r["fetchS"] - 1e-9
        events = t.flight.events("wire_device_time")
        assert events and all("transport_ms" in e for e in events)

    def test_note_device_time_degrades_on_missing_or_bad_echo(self):
        from kubernetes_tpu.backend.service import WireScheduler

        t = telemetry.enable()
        note = WireScheduler._note_device_time
        sized = types.SimpleNamespace(
            wire_sizer=types.SimpleNamespace(bucket_for=lambda n: 64))
        note(sized, {}, 8, "b1", 0.01)                       # no echo
        note(sized, {"deviceTime": "bogus"}, 8, "b1", 0.01)  # wrong shape
        note(sized, {"deviceTime": {"execMs": "NaNope"}}, 8, "b1", 0.01)
        assert t.dispatch_ledger.recorded == 0


# -------------------------------------------------------- bench attribution


def _load_bench():
    import importlib.util
    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "bench_t", os.path.join(repo, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestBenchWaterfall:
    def test_critical_path_table_has_the_phase_children(self):
        bench = _load_bench()
        for name in ("device.dispatch.dwell", "device.dispatch.exec",
                     "device.dispatch.fetch"):
            assert name in bench.CRITICAL_PATH_SPANS

    def test_commit_wait_breakdown_shares(self):
        bench = _load_bench()
        S = lambda name, dur: types.SimpleNamespace(name=name,  # noqa: E731
                                                    duration_s=dur)
        spans = [
            S("device.commit.wait", 0.010), S("device.commit.wait", 0.010),
            S("device.dispatch.dwell", 0.002), S("device.dispatch.exec", 0.012),
            S("device.dispatch.fetch", 0.006), S("scheduling.cycle", 0.05),
        ]
        out = bench._commit_wait_breakdown(spans)
        assert out["batches"] == 2
        assert out["commit_wait_ms_total"] == pytest.approx(20.0)
        assert out["phase_ms"] == {"dwell": 2.0, "exec": 12.0, "fetch": 6.0}
        # shares cover the whole wait: dwell+exec+fetch == 100%
        assert sum(out["share_pct"].values()) == pytest.approx(100.0)
        assert out["phase_ms_per_batch"]["exec"] == pytest.approx(6.0)
        # no wait spans -> no block (skip-when-absent for the trend fence)
        assert bench._commit_wait_breakdown([S("scheduling.cycle", 1.0)]) is None

    def test_device_program_table_ranks_by_exec(self):
        bench = _load_bench()
        t = telemetry.enable()
        led = t.dispatch_ledger
        led.record_window("hot", "8", t_submit=0.0, t_wait0=0.0,
                          t_exec_done=1.0, t_wait_end=1.1, fetch_bytes=64)
        led.record_window("cold", "8", t_submit=2.0, t_wait0=2.0,
                          t_exec_done=2.01, t_wait_end=2.02)
        led.costs[("hot", "8")] = {"flops": 5e6, "bytesAccessed": 1e3}
        table = bench._device_program_table(t)
        assert list(table) == ["hot@8", "cold@8"]
        assert table["hot@8"]["flops"] == 5e6
        assert table["hot@8"]["achieved_flops_per_s"] == pytest.approx(5e6)
        assert "flops" not in table["cold@8"]
        telemetry.disable()
        # empty ledger -> no table
        t2 = telemetry.enable()
        assert bench._device_program_table(t2) is None
