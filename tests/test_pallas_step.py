"""Pallas fused-step kernel: parity vs the XLA scan path (interpret mode).

The CPU test suite runs the kernel through the Pallas interpreter
(KTPU_PALLAS=interpret); on TPU the same kernel compiles via Mosaic.
"""

import os

import numpy as np
import pytest

from kubernetes_tpu.api.wrappers import make_node, make_pod
from kubernetes_tpu.backend.device_state import DeviceState
from kubernetes_tpu.framework.types import NodeInfo
from kubernetes_tpu.ops.schema import Capacities


def _cluster(n_nodes=128, taints=False):
    infos = []
    for i in range(n_nodes):
        nw = make_node(f"n{i}").capacity({"cpu": "8", "memory": "16Gi", "pods": 20})
        nw.label("zone", f"z{i % 3}")
        if taints and i % 4 == 0:
            nw.taint("dedicated", "x", "PreferNoSchedule")
        infos.append(NodeInfo(nw.obj()))
    return infos


def _pods(n):
    pods = []
    for i in range(n):
        pw = make_pod(f"p{i}").req({"cpu": f"{200 + i * 10}m", "memory": "1Gi"})
        if i % 3 == 0:
            pw.preferred_node_affinity(5, "zone", ["z1"])
        if i % 5 == 0:
            pw.host_port(8000 + i)
        pods.append(pw.obj())
    return pods


def _run(mode, infos, pods, caps):
    """Schedule the batch with KTPU_PALLAS set to ``mode``; returns node_idx."""
    import jax

    from kubernetes_tpu.backend.batch import schedule_batch_core, DEFAULT_WEIGHTS

    ds = DeviceState(caps)

    class _Snap:  # minimal snapshot shim for DeviceState.sync
        node_info_map = {ni.node.meta.name: ni for ni in infos}

    ds.sync(_Snap())
    pb, et = ds.encoder.encode_pods(pods)
    tb = ds.sig_table.encode_topo(pods)
    old = os.environ.get("KTPU_PALLAS")
    os.environ["KTPU_PALLAS"] = mode
    try:
        result = schedule_batch_core(
            pb, et, ds.nt, ds.tc, tb, jax.random.PRNGKey(7),
            tuple(sorted(DEFAULT_WEIGHTS.items())), topo_enabled=False)
    finally:
        if old is None:
            del os.environ["KTPU_PALLAS"]
        else:
            os.environ["KTPU_PALLAS"] = old
    return (np.asarray(result.node_idx), np.asarray(result.best_score),
            np.asarray(result.any_feasible), np.asarray(result.fit_ok))


class TestPallasParity:
    @pytest.mark.parametrize("taints", [False, True])
    def test_same_placement_as_xla_path(self, taints):
        caps = Capacities(nodes=128, pods=16)
        infos = _cluster(128, taints=taints)
        pods = _pods(16)
        xla_idx, xla_best, xla_anyf, xla_fit = _run("0", infos, pods, caps)
        pal_idx, pal_best, pal_anyf, pal_fit = _run("interpret", infos, pods, caps)
        np.testing.assert_array_equal(xla_idx, pal_idx)
        np.testing.assert_allclose(xla_best, pal_best, rtol=1e-6)
        np.testing.assert_array_equal(xla_anyf, pal_anyf)
        np.testing.assert_array_equal(xla_fit, pal_fit)

    def test_infeasible_pod_matches(self):
        caps = Capacities(nodes=128, pods=8)
        infos = _cluster(128)
        pods = _pods(4) + [make_pod("huge").req({"cpu": "100", "memory": "1Ti"}).obj()]
        xla = _run("0", infos, pods, caps)
        pal = _run("interpret", infos, pods, caps)
        np.testing.assert_array_equal(xla[0], pal[0])
        assert np.asarray(xla[0])[4] == -1  # the huge pod is unschedulable

    def test_intra_batch_capacity_conflicts_match(self):
        """Many pods that exhaust one node: commits must evolve identically."""
        caps = Capacities(nodes=128, pods=32)
        infos = _cluster(128)
        pods = [make_pod(f"big{i}").req({"cpu": "6", "memory": "12Gi"}).obj()
                for i in range(32)]
        xla = _run("0", infos, pods, caps)
        pal = _run("interpret", infos, pods, caps)
        np.testing.assert_array_equal(xla[0], pal[0])
        # each node fits exactly one 6-cpu pod: all 32 distinct nodes
        assert len(set(np.asarray(xla[0]).tolist())) == 32
