"""kubectl driving the REST apiserver over HTTP via RemoteStore — the
reference's CLI→apiserver seam."""

import pytest

from kubernetes_tpu.apiserver.http import serve_api, shutdown_api
from kubernetes_tpu.apiserver.store import ClusterStore
from kubernetes_tpu.kubectl.cli import kubectl
from kubernetes_tpu.kubectl.remote import RemoteStore


@pytest.fixture()
def remote():
    store = ClusterStore()
    server, port = serve_api(store)
    yield store, RemoteStore(f"http://127.0.0.1:{port}")
    shutdown_api(server)


NODE_YAML = """
kind: Node
metadata:
  name: n1
status:
  capacity: {cpu: "4", memory: 8Gi, pods: "10"}
"""

POD_YAML = """
kind: Pod
metadata:
  name: p1
spec:
  containers:
    - name: c
      resources:
        requests: {cpu: 500m, memory: 1Gi}
"""


def test_kubectl_crud_over_http(remote, tmp_path):
    store, rs = remote
    nf = tmp_path / "node.yaml"
    nf.write_text(NODE_YAML)
    pf = tmp_path / "pod.yaml"
    pf.write_text(POD_YAML)

    out = kubectl(rs, ["create", "-f", str(nf)])
    assert "created" in out
    assert "n1" in store.nodes  # landed in the real store via HTTP

    out = kubectl(rs, ["create", "-f", str(pf)])
    assert "created" in out
    assert store.get_pod("default/p1") is not None

    out = kubectl(rs, ["get", "pods"])
    assert "p1" in out
    out = kubectl(rs, ["get", "nodes"])
    assert "n1" in out
    out = kubectl(rs, ["describe", "pod", "p1"])
    assert "p1" in out
    out = kubectl(rs, ["describe", "node", "n1"])
    assert "n1" in out

    out = kubectl(rs, ["cordon", "n1"])
    assert store.nodes["n1"].spec.unschedulable
    out = kubectl(rs, ["uncordon", "n1"])
    assert not store.nodes["n1"].spec.unschedulable

    out = kubectl(rs, ["taint", "nodes", "n1", "dedicated=gpu:NoSchedule"])
    assert "tainted" in out
    assert store.nodes["n1"].spec.taints[0].key == "dedicated"
    out = kubectl(rs, ["taint", "nodes", "n1", "dedicated:NoSchedule-"])
    assert store.nodes["n1"].spec.taints == ()

    out = kubectl(rs, ["label", "node", "n1", "tier=gold"])
    assert store.nodes["n1"].meta.labels["tier"] == "gold"

    # drain: cordon + evict the bound pod, all over the wire
    from kubernetes_tpu.api.types import Binding
    store.bind(Binding(pod_key="default/p1", node_name="n1"))
    out = kubectl(rs, ["drain", "n1"])
    assert "drained (1 pods evicted)" in out
    assert store.get_pod("default/p1") is None
    assert store.nodes["n1"].spec.unschedulable
