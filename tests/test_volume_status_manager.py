"""Kubelet volume manager + status manager (pkg/kubelet/volumemanager,
pkg/kubelet/status): attach-gated mounts, unmount on pod departure,
no-op-suppressed status writes."""

from kubernetes_tpu.api.types import (
    ObjectMeta,
    PersistentVolume,
    PersistentVolumeClaim,
    PodStatus,
    VolumeAttachment,
)
from kubernetes_tpu.api.wrappers import make_node, make_pod
from kubernetes_tpu.apiserver.store import ClusterStore
from kubernetes_tpu.kubelet.volume_manager import StatusManager, VolumeManager


def _store_with_claim():
    store = ClusterStore()
    store.create_node(make_node("n1").capacity({"cpu": "8"}).obj())
    store.create_pv(PersistentVolume(meta=ObjectMeta(name="pv1"),
                                     capacity_bytes=1 << 30,
                                     bound_pvc="default/c1"))
    store.create_pvc(PersistentVolumeClaim(meta=ObjectMeta(name="c1"),
                                           bound_pv="pv1"))
    pod = make_pod("db").req({"cpu": "1"}).pvc("c1").obj()
    pod.spec.node_name = "n1"
    store.create_pod(pod)
    return store, store.get_pod("default/db")


class TestVolumeManager:
    def test_mount_gated_on_attachment(self):
        store, pod = _store_with_claim()
        vm = VolumeManager(store, "n1")
        assert not vm.wait_for_attach_and_mount(pod)  # not attached yet
        store.create_object("VolumeAttachment", VolumeAttachment(
            meta=ObjectMeta(name="va1"), pv_name="pv1", node_name="n1"))
        assert vm.wait_for_attach_and_mount(pod)
        assert vm.mounts_total == 1

    def test_unmount_when_pod_leaves(self):
        store, pod = _store_with_claim()
        vm = VolumeManager(store, "n1", require_attach=False)
        assert vm.wait_for_attach_and_mount(pod)
        store.delete_pod("default/db")
        vm.reconcile()
        assert vm.mounted == set()
        assert vm.unmounts_total == 1

    def test_attachment_on_other_node_does_not_count(self):
        store, pod = _store_with_claim()
        vm = VolumeManager(store, "n1")
        store.create_object("VolumeAttachment", VolumeAttachment(
            meta=ObjectMeta(name="va1"), pv_name="pv1", node_name="other"))
        assert not vm.wait_for_attach_and_mount(pod)


class TestStatusManager:
    def test_noop_updates_suppressed(self):
        store = ClusterStore()
        store.create_pod(make_pod("w").req({"cpu": "1"}).obj())
        pod = store.get_pod("default/w")
        sm = StatusManager(store)
        sm.set_pod_status(pod, PodStatus(phase="Running"))
        sm.set_pod_status(pod, PodStatus(phase="Running"))  # duplicate
        assert sm.sync() == 1
        assert sm.api_writes == 1
        assert store.get_pod("default/w").status.phase == "Running"
        assert sm.sync() == 0  # already synced

    def test_distinct_statuses_each_written_once(self):
        store = ClusterStore()
        store.create_pod(make_pod("w").req({"cpu": "1"}).obj())
        pod = store.get_pod("default/w")
        sm = StatusManager(store)
        sm.set_pod_status(pod, PodStatus(phase="Running"))
        sm.sync()
        sm.set_pod_status(pod, PodStatus(phase="Failed", reason="Evicted"))
        assert sm.sync() == 1
        got = store.get_pod("default/w").status
        assert (got.phase, got.reason) == ("Failed", "Evicted")

    def test_deleted_pod_entry_cleaned(self):
        store = ClusterStore()
        store.create_pod(make_pod("w").req({"cpu": "1"}).obj())
        pod = store.get_pod("default/w")
        sm = StatusManager(store)
        sm.set_pod_status(pod, PodStatus(phase="Running"))
        store.delete_pod("default/w")
        assert sm.sync() == 0
        assert sm._versions == {}


class TestKubeletVolumeGate:
    def test_pod_waits_for_attachment_then_runs(self):
        from kubernetes_tpu.kubelet.hollow import HollowKubelet

        store, pod = _store_with_claim()
        kubelet = HollowKubelet(store, store.nodes["n1"])
        kubelet.volume_manager = VolumeManager(store, "n1")
        kubelet.run_once()
        assert store.get_pod("default/db").status.phase == "Pending"  # gated
        store.create_object("VolumeAttachment", VolumeAttachment(
            meta=ObjectMeta(name="va1"), pv_name="pv1", node_name="n1"))
        kubelet.run_once()
        assert store.get_pod("default/db").status.phase == "Running"
        # pod deletion unmounts
        store.delete_pod("default/db")
        kubelet.run_once()
        assert kubelet.volume_manager.mounted == set()
