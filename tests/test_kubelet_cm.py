"""Kubelet resource managers (pkg/kubelet/cm/): static CPU policy,
device-plugin allocation with checkpoints, topology-manager hint merge."""

import pytest

from kubernetes_tpu.api.wrappers import make_pod
from kubernetes_tpu.kubelet.checkpoint import CheckpointManager
from kubernetes_tpu.kubelet.cm import (
    POLICY_BEST_EFFORT,
    POLICY_NONE,
    POLICY_SINGLE_NUMA,
    CPUManager,
    DeviceManager,
    TopologyAffinityError,
    TopologyManager,
)


def _guaranteed(name, cores):
    pw = make_pod(name)
    pw.pod.spec.containers[0].requests = {"cpu": str(cores)}
    pw.pod.spec.containers[0].limits = {"cpu": str(cores)}
    return pw.obj()


class TestCPUManager:
    def test_exclusive_cores_for_guaranteed_integer(self, tmp_path):
        cm = CPUManager(CheckpointManager(str(tmp_path)), cores_per_numa=(4, 4))
        cores = cm.allocate(_guaranteed("g", 2))
        assert len(cores) == 2
        # burstable pod (requests != limits): shared pool, no exclusives
        pw = make_pod("b")
        pw.pod.spec.containers[0].requests = {"cpu": "2"}
        pw.pod.spec.containers[0].limits = {"cpu": "4"}
        assert cm.allocate(pw.obj()) == []
        # fractional guaranteed: shared pool
        pw2 = make_pod("f").req({"cpu": "1500m"})
        pw2.pod.spec.containers[0].limits = {"cpu": "1500m"}
        assert cm.allocate(pw2.obj()) == []

    def test_assignments_survive_restart_via_checkpoint(self, tmp_path):
        ckpt_dir = str(tmp_path)
        cm = CPUManager(CheckpointManager(ckpt_dir), cores_per_numa=(4,))
        cores = cm.allocate(_guaranteed("g", 2))
        # "restart": a fresh manager over the same checkpoint dir
        cm2 = CPUManager(CheckpointManager(ckpt_dir), cores_per_numa=(4,))
        assert cm2.assignments["default/g"] == cores
        # the restored assignment blocks double-allocation of those cores
        with pytest.raises(TopologyAffinityError):
            cm2.allocate(_guaranteed("big", 3))
        cm2.release("default/g")
        assert cm2.allocate(_guaranteed("big", 3))

    def test_hints_prefer_single_numa(self, tmp_path):
        cm = CPUManager(CheckpointManager(str(tmp_path)), cores_per_numa=(2, 4))
        hints = cm.topology_hints(_guaranteed("g", 3))
        assert hints == [h for h in hints if h.numa_nodes == (1,)] \
            or any(h.numa_nodes == (1,) and h.preferred for h in hints)


class TestDeviceManager:
    def test_allocate_and_checkpoint(self, tmp_path):
        dm = DeviceManager(CheckpointManager(str(tmp_path)))
        dm.register_plugin("example.com/gpu", {"gpu0": 0, "gpu1": 0, "gpu2": 1})
        pod = make_pod("g").req({"cpu": "1", "example.com/gpu": "2"}).obj()
        alloc = dm.allocate(pod)
        assert len(alloc["example.com/gpu"]) == 2
        dm2 = DeviceManager(CheckpointManager(str(tmp_path)))
        dm2.register_plugin("example.com/gpu", {"gpu0": 0, "gpu1": 0, "gpu2": 1})
        assert dm2.allocations["default/g"] == alloc
        # only one device left
        pod2 = make_pod("h").req({"cpu": "1", "example.com/gpu": "2"}).obj()
        with pytest.raises(TopologyAffinityError):
            dm2.allocate(pod2)


class TestTopologyManager:
    def _managers(self, tmp_path):
        cm = CPUManager(CheckpointManager(str(tmp_path / "c")), cores_per_numa=(4, 4))
        dm = DeviceManager(CheckpointManager(str(tmp_path / "d")))
        dm.register_plugin("example.com/gpu", {"gpu0": 0, "gpu1": 1})
        return cm, dm

    def test_single_numa_aligns_cpu_and_device(self, tmp_path):
        cm, dm = self._managers(tmp_path)
        tm = TopologyManager(POLICY_SINGLE_NUMA, providers=[cm, dm])
        pod = make_pod("aligned")
        pod.pod.spec.containers[0].requests = {"cpu": "2", "example.com/gpu": "1"}
        pod.pod.spec.containers[0].limits = {"cpu": "2", "example.com/gpu": "1"}
        hint = tm.admit(pod.obj())
        assert len(hint.numa_nodes) == 1
        numa = hint.numa_nodes[0]
        cores = cm.assignments["default/aligned"]
        assert all(cm.numa_of[c] == numa for c in cores)
        [gpu] = dm.allocations["default/aligned"]["example.com/gpu"]
        assert dm.registry["example.com/gpu"][gpu] == numa

    def test_single_numa_rejects_unalignable(self, tmp_path):
        cm, dm = self._managers(tmp_path)
        tm = TopologyManager(POLICY_SINGLE_NUMA, providers=[cm, dm])
        pod = make_pod("wide")
        # 5 cores cannot fit one NUMA node (4+4 split)
        pod.pod.spec.containers[0].requests = {"cpu": "5", "example.com/gpu": "1"}
        pod.pod.spec.containers[0].limits = {"cpu": "5", "example.com/gpu": "1"}
        with pytest.raises(TopologyAffinityError):
            tm.admit(pod.obj())

    def test_best_effort_admits_unaligned(self, tmp_path):
        cm, dm = self._managers(tmp_path)
        tm = TopologyManager(POLICY_BEST_EFFORT, providers=[cm, dm])
        pod = make_pod("wide")
        pod.pod.spec.containers[0].requests = {"cpu": "5"}
        pod.pod.spec.containers[0].limits = {"cpu": "5"}
        tm.admit(pod.obj())  # no raise
        assert len(cm.assignments["default/wide"]) == 5

    def test_none_policy_skips_hints(self, tmp_path):
        cm, dm = self._managers(tmp_path)
        tm = TopologyManager(POLICY_NONE, providers=[cm, dm])
        assert tm.admit(_guaranteed("g", 2)) is None
        assert len(cm.assignments["default/g"]) == 2

    def test_release_frees_all_providers(self, tmp_path):
        cm, dm = self._managers(tmp_path)
        tm = TopologyManager(POLICY_BEST_EFFORT, providers=[cm, dm])
        pod = make_pod("r")
        pod.pod.spec.containers[0].requests = {"cpu": "2", "example.com/gpu": "1"}
        pod.pod.spec.containers[0].limits = {"cpu": "2", "example.com/gpu": "1"}
        tm.admit(pod.obj())
        tm.release("default/r")
        assert "default/r" not in cm.assignments
        assert "default/r" not in dm.allocations


class TestKubeletIntegration:
    def test_topology_rejection_fails_pod(self, tmp_path):
        from kubernetes_tpu.api.wrappers import make_node
        from kubernetes_tpu.apiserver.store import ClusterStore
        from kubernetes_tpu.kubelet.hollow import HollowKubelet

        store = ClusterStore()
        node = make_node("n1").capacity({"cpu": "8", "memory": "16Gi",
                                         "pods": 10}).obj()
        kubelet = HollowKubelet(store, node)
        cm = CPUManager(CheckpointManager(str(tmp_path)), cores_per_numa=(2, 2))
        kubelet.topology_manager = TopologyManager(POLICY_SINGLE_NUMA,
                                                   providers=[cm])
        ok = _guaranteed("fits", 2)
        ok.spec.node_name = "n1"
        store.create_pod(ok)
        wide = _guaranteed("toowide", 3)  # 3 cores never fit one 2-core node
        wide.spec.node_name = "n1"
        store.create_pod(wide)
        kubelet.run_once()
        assert store.get_pod("default/fits").status.phase == "Running"
        rejected = store.get_pod("default/toowide")
        assert rejected.status.phase == "Failed"
        assert rejected.status.reason == "TopologyAffinityError"
        # cores released when the failed pod is deleted
        store.delete_pod("default/toowide")
        store.delete_pod("default/fits")
        kubelet.run_once()
        assert cm.assignments == {}


def test_admit_rolls_back_earlier_providers_on_failure(tmp_path):
    """A later provider's rejection must release what earlier providers
    persisted — a Failed pod stays in the store and would pin cores."""
    cm = CPUManager(CheckpointManager(str(tmp_path / "c")), cores_per_numa=(4,))
    dm = DeviceManager(CheckpointManager(str(tmp_path / "d")))
    dm.register_plugin("example.com/gpu", {})  # no devices at all
    tm = TopologyManager(POLICY_NONE, providers=[cm, dm])
    pod = make_pod("leaky")
    pod.pod.spec.containers[0].requests = {"cpu": "2", "example.com/gpu": "1"}
    pod.pod.spec.containers[0].limits = {"cpu": "2", "example.com/gpu": "1"}
    with pytest.raises(TopologyAffinityError):
        tm.admit(pod.obj())
    assert cm.assignments == {}  # rolled back, not leaked
