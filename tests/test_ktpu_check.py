"""tools/ktpu_check.py — the unified static-analysis driver — as a tier-1
gate: ``--all`` over the real tree must be clean, and every pass must still
DETECT a seeded violation (negative controls per rule) while reporting zero
false positives on a clean fixture. The dynamic half (testing/locktrace.py)
gets the same treatment: a scripted lock-order inversion and a blocking
call under a held lock must be caught; a clean run must assert clean."""

import importlib.util
import json
import os
import subprocess
import sys
import threading

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOL = os.path.join(REPO, "tools", "ktpu_check.py")


def _load_tool():
    spec = importlib.util.spec_from_file_location("ktpu_check_t", TOOL)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


kc = _load_tool()


# ------------------------------------------------------------------ driver


def test_all_passes_clean_on_real_tree():
    """THE gate: every registered pass over the actual tree, exit 0. A new
    unguarded access, untyped raise, host sync in the traced region, dead
    metric, unattributed span, unmarked perf test, stale pb2, or reasonless
    suppression fails tier-1 right here."""
    p = subprocess.run([sys.executable, "-m", "tools.ktpu_check", "--all"],
                       cwd=REPO, capture_output=True, text=True, timeout=300)
    assert p.returncode == 0, p.stdout + p.stderr
    for name in ("locks", "jit", "errors", "metrics", "spans", "events",
                 "dispatch", "markers", "pb2-drift", "suppress"):
        assert f"ok   {name}" in p.stdout, p.stdout


def test_json_output_shape():
    p = subprocess.run([sys.executable, "-m", "tools.ktpu_check", "--all",
                        "--json"], cwd=REPO, capture_output=True, text=True,
                       timeout=300)
    assert p.returncode == 0, p.stdout + p.stderr
    out = json.loads(p.stdout)
    assert out["total"] == 0
    assert set(out["passes"]) == set(kc.PASSES)
    for body in out["passes"].values():
        assert body["count"] == 0 and body["findings"] == []


def test_selective_pass_and_bad_args():
    p = subprocess.run([sys.executable, "-m", "tools.ktpu_check",
                        "--pass", "errors"], cwd=REPO, capture_output=True,
                       text=True, timeout=120)
    assert p.returncode == 0 and "ok   errors" in p.stdout
    assert "locks" not in p.stdout
    p = subprocess.run([sys.executable, "-m", "tools.ktpu_check",
                        "--pass", "nonsense"], cwd=REPO, capture_output=True,
                       text=True, timeout=60)
    assert p.returncode == 2


def test_registry_covers_the_absorbed_gates():
    """The three pre-existing lint CLIs are registered passes now."""
    for absorbed in ("metrics", "spans", "markers", "pb2-drift"):
        assert absorbed in kc.PASSES


# ----------------------------------------------------------------- events


_FAKE_TELEMETRY = '''
EVENT_KINDS = frozenset({"dispatch", "commit", "poison"})
'''

EVENTS_BAD = '''
from . import telemetry

def f(t):
    telemetry.event("mystery_kind", batchId="b1")     # BAD: undeclared
    t.flight.record("another_unknown", pods=3)        # BAD: undeclared
    telemetry.event("dispatch", batchId="b2")         # declared: fine
'''

EVENTS_CLEAN = '''
from . import telemetry

def f(t, etype):
    telemetry.event("dispatch", batchId="b1")
    telemetry.event("commit", batchId="b1")
    t.flight.record("poison", batchId="b1")
    t.flight.record(etype, batchId="b1")   # pass-through: checked at the
                                           # forwarding call's literal site
    t.recorder.record("not-an-event")      # non-flight receiver: ignored
'''


def _events_fixture(tmp_path, pkg_text):
    pkg = _write_pkg(tmp_path, "pkg", pkg_text)
    tel = tmp_path / "telemetry.py"
    tel.write_text(_FAKE_TELEMETRY)
    return pkg, str(tel)


def test_events_pass_detects_seeded_violations(tmp_path):
    pkg, tel = _events_fixture(tmp_path, EVENTS_BAD)
    findings = kc.find_undeclared_events(pkg, tel)
    kinds = {f.message.split("'")[1] for f in findings}
    assert kinds == {"mystery_kind", "another_unknown"}


def test_events_pass_clean_fixture_has_zero_false_positives(tmp_path):
    pkg, tel = _events_fixture(tmp_path, EVENTS_CLEAN)
    assert kc.find_undeclared_events(pkg, tel) == []


def test_events_pass_missing_registry_is_a_finding(tmp_path):
    """An analysis that cannot find its registry must FAIL, not silently
    judge nothing (the entry-point-discovery guard, events edition)."""
    pkg = _write_pkg(tmp_path, "pkg", EVENTS_CLEAN)
    tel = tmp_path / "telemetry.py"
    tel.write_text("OTHER = 1\n")
    findings = kc.find_undeclared_events(pkg, str(tel))
    assert len(findings) == 1 and "EVENT_KINDS" in findings[0].message


def test_events_registry_matches_real_tree():
    """The real tree's emitted kinds EXACTLY equal the declared registry:
    an undeclared emission fails here (and the lint), and a kind whose
    last emission site was deleted must leave EVENT_KINDS too — the
    vocabulary never accumulates dead entries."""
    declared = kc.declared_event_kinds()
    emitted = {k for _p, _l, k in kc.emitted_event_kinds()}
    assert emitted, "entry-point discovery guard: no emission sites found?"
    assert emitted == declared, (
        f"undeclared: {sorted(emitted - declared)}; "
        f"stale: {sorted(declared - emitted)}")


# --------------------------------------------------------------- dispatch


_DISPATCH_TELEMETRY = '''
PROGRAM_NAMES = frozenset({"kernel_prog", "other_prog"})
'''

_DISPATCH_OPS = '''
from jax import jit

@jit
def kernel(x):
    return x

def warm():
    return kernel(1)   # same module as the entry: composition, exempt
'''

DISPATCH_BAD = '''
from . import telemetry
from .ops import kernel

def naked(x):
    return kernel(x)                       # BAD: no dispatch context

def misnamed(x):
    with telemetry.dispatch("mystery"):    # BAD: undeclared program
        return kernel(x)

def leaky(x):
    with telemetry.dispatch("kernel_prog"):
        def later():
            return kernel(x)               # BAD: runs after the with exits
    return later
'''

DISPATCH_CLEAN = '''
from jax import jit
from . import telemetry
from .ops import kernel

def attributed(x):
    with telemetry.dispatch("kernel_prog", bucket="8"):
        out = kernel(x)
    telemetry.cost_probe("kernel_prog", "8", kernel, (x,))
    return out

@jit
def composed(x):
    return kernel(x)      # traced composition inside another jit entry

def reviewed(x):
    return kernel(x)  # ktpu: dispatch-ok(warmup outside the profiled path)
'''


def _dispatch_fixture(tmp_path, caller_text):
    pkg = tmp_path / "pkg"
    pkg.mkdir(exist_ok=True)
    (pkg / "ops.py").write_text(_DISPATCH_OPS)
    (pkg / "callers.py").write_text(caller_text)
    tel = tmp_path / "telemetry.py"
    tel.write_text(_DISPATCH_TELEMETRY)
    return str(pkg), str(tel)


def test_dispatch_pass_detects_seeded_violations(tmp_path):
    pkg, tel = _dispatch_fixture(tmp_path, DISPATCH_BAD)
    findings = kc.find_unattributed_dispatches(pkg, tel)
    msgs = "\n".join(f.message for f in findings)
    assert len(findings) == 3, msgs
    assert "undeclared dispatch program 'mystery'" in msgs
    assert msgs.count("unattributed dispatch") == 2


def test_dispatch_pass_clean_fixture_has_zero_false_positives(tmp_path):
    pkg, tel = _dispatch_fixture(tmp_path, DISPATCH_CLEAN)
    assert kc.find_unattributed_dispatches(pkg, tel) == []


def test_dispatch_pass_missing_registry_is_a_finding(tmp_path):
    pkg, _tel = _dispatch_fixture(tmp_path, DISPATCH_CLEAN)
    tel = tmp_path / "empty.py"
    tel.write_text("OTHER = 1\n")
    findings = kc.find_unattributed_dispatches(pkg, str(tel))
    assert len(findings) == 1 and "PROGRAM_NAMES" in findings[0].message


def test_dispatch_registry_matches_real_tree():
    """Literal program names at real dispatch/cost-probe sites are a subset
    of PROGRAM_NAMES, and (minus the ledger-only wire program, recorded via
    record_phases on the client) every declared name is actually used — the
    attribution vocabulary carries no dead entries."""
    declared = kc.declared_program_names()
    used = {prog for _p, _l, prog in kc.dispatch_program_sites()}
    assert used, "entry-point discovery guard: no dispatch sites found?"
    assert used <= declared, f"undeclared: {sorted(used - declared)}"
    assert declared - used == set(), f"stale: {sorted(declared - used)}"


def test_dispatch_jit_alias_discovery_covers_assigned_entries():
    """The alias map sees both decorated entries and `x = jit(f)` bindings
    on the real tree — the discovery half of the unattributed-call rule."""
    aliases = kc._jit_entry_aliases(kc.PKG)
    assert "schedule_batch" in aliases
    assert any(n.endswith("_jit") or n != "schedule_batch"
               for n in aliases), aliases


# ----------------------------------------------------------------- locks


def _write_pkg(tmp_path, name, text):
    pkg = tmp_path / name
    pkg.mkdir(exist_ok=True)
    (pkg / "mod.py").write_text(text)
    return str(pkg)


LOCKY_BAD = '''
import threading

class Svc:
    def __init__(self):
        self._lock = threading.Lock()
        self.seq = 0
        self.items = {}

    def bump(self):
        with self._lock:
            self.seq += 1
            self.items["k"] = self.seq

    def leak(self):
        return self.seq          # BAD: unguarded read

    def stomp(self):
        self.items["x"] = 1      # BAD: unguarded write
'''

LOCKY_CLEAN = '''
import threading

class Svc:
    def __init__(self):
        self._lock = threading.Lock()
        self.seq = 0
        self.items = {}
        self.config = "init-only"   # never rebound later: exempt

    def bump(self):
        with self._lock:
            self.seq += 1
            self._bump_items()

    def _bump_items(self):  # ktpu: locked
        self.items["k"] = self.seq

    def _shrink_locked(self):
        self.items.clear()          # *_locked naming = caller holds it

    def read_config(self):
        return self.config

    def snapshot(self):
        return self.seq  # ktpu: unguarded-ok(torn read tolerated in the debug dump)
'''


def test_locks_pass_detects_seeded_violations(tmp_path):
    pkg = _write_pkg(tmp_path, "pkg", LOCKY_BAD)
    found = kc.find_lock_violations(pkg=pkg)
    msgs = "\n".join(f.message for f in found)
    assert len(found) == 2, msgs
    assert "unguarded read of Svc.seq in leak()" in msgs
    assert "unguarded write to Svc.items in stomp()" in msgs


def test_locks_pass_clean_fixture_has_zero_false_positives(tmp_path):
    pkg = _write_pkg(tmp_path, "pkg", LOCKY_CLEAN)
    assert kc.find_lock_violations(pkg=pkg) == []


def test_locks_pass_ignores_lockless_classes(tmp_path):
    pkg = _write_pkg(tmp_path, "pkg", '''
class Plain:
    def __init__(self):
        self.x = 0
    def bump(self):
        self.x += 1
''')
    assert kc.find_lock_violations(pkg=pkg) == []


def test_locks_suppression_without_reason_does_not_silence(tmp_path):
    pkg = _write_pkg(tmp_path, "pkg", '''
import threading

class Svc:
    def __init__(self):
        self._lock = threading.Lock()
        self.seq = 0
    def bump(self):
        with self._lock:
            self.seq += 1
    def leak(self):
        return self.seq  # ktpu: unguarded-ok()
''')
    # the empty-reason marker neither silences the locks finding...
    assert len(kc.find_lock_violations(pkg=pkg)) == 1
    # ...nor passes suppression hygiene
    sup = kc.pass_suppress(files=[os.path.join(pkg, "mod.py")])
    assert len(sup) == 1 and "no reason" in sup[0].message


# ------------------------------------------------------------------- jit


JIT_BAD = '''
import functools
import jax
import jax.numpy as jnp
import numpy as np


def helper(x):
    v = float(x)          # BAD: host sync in a reachable function
    if x > 0:             # BAD: python branch on traced
        return v
    return x.item()       # BAD


@functools.partial(jax.jit, static_argnames=("mode",))
def entry(x, mode="a", opts=[1]):
    arr = np.asarray(x)   # BAD: host materialization
    if mode == "b":       # fine: static arg
        return arr
    n = int(x.shape[0])   # fine: shape metadata
    w = np.asarray([1.0, 2.0])  # fine: literal
    return helper(x)
'''

JIT_BAD_STATIC_DEFAULT = '''
import functools
import jax


@functools.partial(jax.jit, static_argnames=("opts",))
def entry(x, opts=[1, 2]):
    return x
'''

JIT_CLEAN = '''
import functools
import jax
import jax.numpy as jnp
import numpy as np


def helper(x, flag):
    if flag:                       # static by propagation
        x = x + 1
    return jnp.where(x > 0, x, 0)  # traced branch done right


@functools.partial(jax.jit, static_argnames=("flag",))
def entry(x, flag=True):
    if x is None:                  # identity test: host bool
        return None
    n = int(x.shape[0])            # metadata
    return helper(x, flag)


def host_commit(result):
    # NOT reachable from a jit entry: host code may sync freely
    return int(np.asarray(result)[0])
'''


def test_jit_pass_detects_seeded_violations(tmp_path):
    pkg = _write_pkg(tmp_path, "pkg", JIT_BAD)
    found = kc.find_jit_violations(pkg=pkg)
    msgs = "\n".join(f.message for f in found)
    assert "float() on a traced value" in msgs
    assert ".item() on a traced value" in msgs
    assert "np.asarray() on a traced value" in msgs
    assert "Python branch on a traced value" in msgs
    # the static-arg branch and the shape/literal lines are NOT flagged
    assert "mode" not in msgs
    lines = {f.line for f in found}
    assert lines == {9, 10, 12, 17}, sorted(lines)


def test_jit_pass_detects_unhashable_static_default(tmp_path):
    pkg = _write_pkg(tmp_path, "pkg", JIT_BAD_STATIC_DEFAULT)
    found = kc.find_jit_violations(pkg=pkg)
    assert len(found) == 1
    assert "unhashable literal" in found[0].message


def test_jit_pass_clean_fixture_has_zero_false_positives(tmp_path):
    pkg = _write_pkg(tmp_path, "pkg", JIT_CLEAN)
    assert kc.find_jit_violations(pkg=pkg) == []


def test_jit_pass_discovers_the_real_entry_points():
    """The pass must actually see the five jitted programs — if discovery
    breaks, --all would go green by analyzing nothing."""
    _fns, entries, _sites = kc._collect_jit_functions(kc.PKG)
    for must in ("schedule_batch", "gang_verdicts", "claim_feasibility_mask",
                 "_screen_jit", "_apply_rows"):
        assert must in entries, sorted(entries)
    # schedule_batch's static surface is where retrace control lives
    assert "topo_enabled" in entries["schedule_batch"]
    assert "weights_key" in entries["schedule_batch"]


# ----------------------------------------------------------------- errors


ERRORS_BAD = '''
def send(conn, data):
    try:
        conn.post(data)
    except Exception:
        pass

def grow(dim):
    raise RuntimeError(f"unknown dimension {dim}")
'''

ERRORS_CLEAN = '''
from .errors import PermanentDeviceError, TransientDeviceError

def send(conn, data):
    try:
        conn.post(data)
    except Exception as e:  # reclassified below, so no comment needed
        raise TransientDeviceError(str(e)) from e

def send2(conn, data):
    try:
        conn.post(data)
    except Exception:  # noqa: BLE001 — hints are optional, scheduling continues
        return None

def grow(dim):
    raise PermanentDeviceError(f"unknown dimension {dim}")

def legacy(dim):
    raise RuntimeError("measured")  # ktpu: taxonomy-ok(pre-taxonomy contract pinned by a wire test)
'''


def test_errors_pass_detects_seeded_violations(tmp_path):
    backend = _write_pkg(tmp_path, "backend", ERRORS_BAD)
    found = kc.find_error_violations(backend=backend)
    msgs = "\n".join(f.message for f in found)
    assert len(found) == 2, msgs
    assert "untyped raise RuntimeError" in msgs
    assert "broad 'except Exception'" in msgs


def test_errors_pass_clean_fixture_has_zero_false_positives(tmp_path):
    backend = _write_pkg(tmp_path, "backend", ERRORS_CLEAN)
    assert kc.find_error_violations(backend=backend) == []


# ------------------------------------------------------------- locktrace


@pytest.fixture
def tracer(monkeypatch):
    from kubernetes_tpu.testing import locktrace

    monkeypatch.setenv("KTPU_LOCKTRACE", "1")
    locktrace.reset()
    yield locktrace
    locktrace.reset()


def test_factory_returns_plain_locks_when_disabled(monkeypatch):
    from kubernetes_tpu.testing import locktrace

    monkeypatch.delenv("KTPU_LOCKTRACE", raising=False)
    lk = locktrace.make_lock("X")
    assert type(lk) is type(threading.Lock())
    rl = locktrace.make_rlock("X")
    assert not isinstance(rl, locktrace.TracedLock)


def test_traced_lock_records_edges_and_detects_cycle(tracer):
    a = tracer.make_lock("A")
    b = tracer.make_lock("B")
    assert isinstance(a, tracer.TracedLock)
    with a:
        with b:
            pass
    assert tracer.tracer().cycles() == []  # A->B alone is fine
    with b:
        with a:                            # the inversion
            pass
    cycles = tracer.tracer().cycles()
    assert cycles == [["A", "B"]], cycles
    with pytest.raises(AssertionError, match="lock-order cycle: A -> B -> A"):
        tracer.assert_clean()


def test_cycle_detection_spans_threads(tracer):
    """The deadlock never fires (acquisitions are sequential), but the
    opposing edges from two different threads still form the cycle — the
    point of the graph: POTENTIAL deadlocks, not wedged runs."""
    a, b = tracer.make_lock("A"), tracer.make_lock("B")

    def t1():
        with a:
            with b:
                pass

    def t2():
        with b:
            with a:
                pass

    th1 = threading.Thread(target=t1)
    th1.start(); th1.join()
    th2 = threading.Thread(target=t2)
    th2.start(); th2.join()
    assert tracer.tracer().cycles() == [["A", "B"]]


def test_reentrant_acquisition_records_no_self_edge(tracer):
    r = tracer.make_rlock("R")
    with r:
        with r:
            pass
    assert tracer.tracer().cycles() == []
    assert tracer.tracer().edges == {}
    # the held stack balanced: nothing left on this thread
    assert tracer.tracer().held() == []


def test_blocking_under_lock_is_a_violation(tracer):
    lk = tracer.make_lock("Svc")
    with lk:
        tracer.note_blocking("http", "/v1/scheduleBatch")
    v = tracer.tracer().blocking_violations
    assert len(v) == 1 and v[0]["locks"] == ["Svc"]
    with pytest.raises(AssertionError, match="blocking under lock: http"):
        tracer.assert_clean()


def test_allowed_blocking_is_ledgered_not_flagged(tracer):
    lk = tracer.make_lock("Svc")
    with lk:
        tracer.note_blocking("device_sync", "sync",
                             allowed="mirror frozen until commit")
    assert tracer.tracer().blocking_violations == []
    assert len(tracer.tracer().blocking_allowed) == 1
    tracer.assert_clean()  # must not raise


def test_blocking_without_held_lock_records_nothing(tracer):
    tracer.note_blocking("sleep", "retry backoff")
    assert tracer.tracer().blocking_violations == []
    assert tracer.tracer().blocking_allowed == []


def test_note_blocking_disabled_is_a_noop(monkeypatch):
    from kubernetes_tpu.testing import locktrace

    monkeypatch.delenv("KTPU_LOCKTRACE", raising=False)
    locktrace.reset()
    locktrace.note_blocking("http", "x")
    assert locktrace.tracer().blocking_violations == []


def test_queue_cache_store_service_locks_come_from_the_factory(tracer):
    """The four concurrent-path components construct their locks through
    the factory: driving them under KTPU_LOCKTRACE=1 shows up in the
    acquisition ledger (the chaos suites rely on exactly this)."""
    from kubernetes_tpu.api.wrappers import make_node, make_pod
    from kubernetes_tpu.apiserver.store import ClusterStore
    from kubernetes_tpu.backend.service import DeviceService
    from kubernetes_tpu.cache.cache import Cache
    from kubernetes_tpu.queue.scheduling_queue import SchedulingQueue

    store = ClusterStore()
    store.create_node(make_node("n0").capacity(
        {"cpu": "4", "memory": "8Gi", "pods": 10}).obj())
    q = SchedulingQueue()
    q.add(make_pod("p0").req({"cpu": "100m"}).obj())
    assert q.pop() is not None
    c = Cache()
    c.add_node(store.nodes["n0"])
    assert c.node_count() == 1
    svc = DeviceService(batch_size=8)
    svc.health({})
    acq = tracer.tracer().acquisitions
    for name in ("ClusterStore", "SchedulingQueue", "Cache", "DeviceService"):
        assert acq.get(name, 0) > 0, (name, acq)
    tracer.assert_clean()


def test_wire_client_http_marks_blocking(tracer):
    """The WireClient's socket IO reports as a blocking op: held under any
    traced lock it would be a violation (negative control proving the real
    seam is instrumented, not just the unit fixture above)."""
    from kubernetes_tpu.backend.errors import TransientDeviceError
    from kubernetes_tpu.backend.service import WireClient

    guard = tracer.make_lock("TestGuard")
    client = WireClient("http://127.0.0.1:1",  # nothing listens: fails fast
                        connect_timeout=0.05, read_timeout=0.05)
    client.retry.max_retries = 0
    with guard:
        with pytest.raises(TransientDeviceError):
            client.apply_deltas({"apiVersion": "ktpu/v1"})
    v = tracer.tracer().blocking_violations
    assert any(ev["kind"] == "http" and "TestGuard" in ev["locks"]
               for ev in v), v


def test_reset_isolates_runs(tracer):
    lk = tracer.make_lock("A")
    with lk:
        pass
    assert tracer.tracer().acquisitions
    tracer.reset()
    assert tracer.tracer().acquisitions == {}
    # locks made before the reset keep reporting into the NEW tracer
    with lk:
        pass
    assert tracer.tracer().acquisitions == {"A": 1}
