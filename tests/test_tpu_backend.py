"""Tests for the batched TPU backend: device delta sync, the schedule_batch
kernel's sequential-commit semantics, and TPUScheduler end-to-end equivalence
with the sequential oracle scheduler."""

import numpy as np
import pytest

from kubernetes_tpu.api.types import LabelSelector
from kubernetes_tpu.api.wrappers import make_node, make_pod
from kubernetes_tpu.apiserver import ClusterStore
from kubernetes_tpu.backend import TPUScheduler, DeviceState, caps_for_cluster
from kubernetes_tpu.cache import Cache, Snapshot
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.utils.clock import FakeClock


def mk_tpu_cluster(n_nodes=8, batch_size=16, **node_kw):
    store = ClusterStore()
    clock = FakeClock()
    sched = TPUScheduler(store, now_fn=clock, batch_size=batch_size)
    sched.clock = clock
    for i in range(n_nodes):
        store.create_node(
            make_node(f"node-{i}")
            .capacity({"cpu": node_kw.get("cpu", "4"), "memory": node_kw.get("mem", "8Gi"), "pods": node_kw.get("pods", 110)})
            .label("zone", f"z{i % 2}").obj()
        )
    return store, sched


def bound_pods(store):
    return {k: p.spec.node_name for k, p in store.pods.items() if p.spec.node_name}


class TestDeviceState:
    def test_delta_sync_uploads_only_dirty(self):
        cache = Cache()
        for i in range(6):
            cache.add_node(make_node(f"n{i}").capacity({"cpu": "4", "pods": 10}).obj())
        snap = Snapshot()
        cache.update_snapshot(snap)
        dev = DeviceState(caps_for_cluster(6, batch=8))
        assert dev.sync(snap) == 6
        assert dev.sync(snap) == 0  # no changes
        cache.assume_pod(make_pod("p").req({"cpu": "1"}).obj().clone(), "n3")
        cache.update_snapshot(snap)
        assert dev.sync(snap) == 1  # only n3 re-uploaded
        slot = dev.encoder.node_slots["n3"]
        assert int(np.asarray(dev.nt.requested)[slot, 0]) == 1000

    def test_node_removal_invalidates_slot(self):
        cache = Cache()
        cache.add_node(make_node("n0").capacity({"cpu": "4", "pods": 10}).obj())
        cache.add_node(make_node("n1").capacity({"cpu": "4", "pods": 10}).obj())
        snap = Snapshot()
        cache.update_snapshot(snap)
        dev = DeviceState(caps_for_cluster(2, batch=8))
        dev.sync(snap)
        slot = dev.encoder.node_slots["n1"]
        cache.remove_node("n1")
        cache.update_snapshot(snap)
        dev.sync(snap)
        assert not bool(np.asarray(dev.nt.valid)[slot])


class TestBatchKernelCommit:
    def test_intra_batch_capacity_conflict_resolved(self):
        # 1 node with room for exactly one pod; a batch of 3 identical pods:
        # exactly one must win, on device, without host round-trips
        store, sched = mk_tpu_cluster(1, cpu="2", batch_size=8)
        for i in range(3):
            store.create_pod(make_pod(f"p{i}").req({"cpu": "2"}).obj())
        sched.run_until_settled()
        assert len(bound_pods(store)) == 1
        assert sched.batch_scheduled == 1

    def test_intra_batch_port_conflict_resolved(self):
        store, sched = mk_tpu_cluster(2, batch_size=8)
        for i in range(3):
            store.create_pod(make_pod(f"p{i}").req({"cpu": "100m"}).host_port(8080).obj())
        sched.run_until_settled()
        b = bound_pods(store)
        assert len(b) == 2  # one per node; third conflicts everywhere
        assert len(set(b.values())) == 2

    def test_batch_spreads_like_sequential(self):
        store, sched = mk_tpu_cluster(4, batch_size=16)
        for i in range(8):
            store.create_pod(make_pod(f"p{i}").req({"cpu": "1"}).obj())
        sched.run_until_settled()
        per_node = {}
        for _k, n in bound_pods(store).items():
            per_node[n] = per_node.get(n, 0) + 1
        assert sorted(per_node.values()) == [2, 2, 2, 2]


class TestTPUSchedulerE2E:
    def test_mixed_workload_with_fallback(self):
        store, sched = mk_tpu_cluster(4, batch_size=16)
        sel = LabelSelector(match_labels={"app": "web"})
        for i in range(6):
            store.create_pod(make_pod(f"gen-{i}").req({"cpu": "250m"}).obj())
        for i in range(4):
            store.create_pod(  # spread pods ride the device topology kernels
                make_pod(f"web-{i}").label("app", "web").req({"cpu": "100m"})
                .spread_constraint(1, "zone", selector=sel).obj()
            )
        sched.run_until_settled()
        assert len(bound_pods(store)) == 10
        assert sched.batch_scheduled == 10
        assert sched.fallback_scheduled == 0
        zones = {}
        for k, n in bound_pods(store).items():
            if k.startswith("default/web"):
                z = store.nodes[n].meta.labels["zone"]
                zones[z] = zones.get(z, 0) + 1
        assert zones == {"z0": 2, "z1": 2}

    def test_unschedulable_diagnosis_and_reactivation(self):
        store, sched = mk_tpu_cluster(2, cpu="2", batch_size=8)
        store.create_pod(make_pod("big").req({"cpu": "16"}).obj())
        sched.run_until_settled()
        assert bound_pods(store) == {}
        # diagnosis must gate reactivation on NodeResourcesFit events
        assert sched.queue.pending_pods()["unschedulable"] == 1
        store.create_node(make_node("xl").capacity({"cpu": "32", "memory": "64Gi", "pods": 10}).obj())
        sched.clock.advance(10.1)
        sched.run_until_settled()
        assert bound_pods(store) == {"default/big": "xl"}

    def test_taints_and_affinity_on_batch_path(self):
        store = ClusterStore()
        clock = FakeClock()
        sched = TPUScheduler(store, now_fn=clock, batch_size=8)
        sched.clock = clock
        store.create_node(make_node("tainted").capacity({"cpu": "4", "memory": "8Gi", "pods": 10})
                          .taint("dedicated", "gpu", "NoSchedule").label("zone", "z0").obj())
        store.create_node(make_node("open").capacity({"cpu": "4", "memory": "8Gi", "pods": 10})
                          .label("zone", "z1").obj())
        store.create_pod(make_pod("normal").req({"cpu": "1"}).obj())
        store.create_pod(make_pod("gpu-job").req({"cpu": "1"})
                         .toleration(key="dedicated", operator="Equal", value="gpu", effect="NoSchedule")
                         .node_affinity_in("zone", ["z0"]).obj())
        sched.run_until_settled()
        b = bound_pods(store)
        assert b["default/normal"] == "open"
        assert b["default/gpu-job"] == "tainted"
        assert sched.fallback_scheduled == 0  # all on the batch path

    def test_equivalence_with_sequential(self):
        """Same cluster + workload through both schedulers: identical
        feasibility outcomes and equally-optimal placements."""
        def workload(store):
            for i in range(12):
                store.create_pod(make_pod(f"p{i}").req({"cpu": ["250m", "1", "2"][i % 3]}).obj())
            store.create_pod(make_pod("huge").req({"cpu": "64"}).obj())

        store_a = ClusterStore()
        clock_a = FakeClock()
        seq = Scheduler(store_a, now_fn=clock_a)
        store_b, tpu = mk_tpu_cluster(4, batch_size=16)
        for i in range(4):
            store_a.create_node(make_node(f"node-{i}").capacity({"cpu": "4", "memory": "8Gi", "pods": 110})
                                .label("zone", f"z{i % 2}").obj())
        workload(store_a)
        workload(store_b)
        seq.run_until_settled()
        tpu.run_until_settled()
        a, b = bound_pods(store_a), bound_pods(store_b)
        assert set(a) == set(b)  # same pods scheduled / unschedulable
        # per-node load identical (placements may differ only within ties)
        load_a = sorted(list(a.values()).count(f"node-{i}") for i in range(4))
        load_b = sorted(list(b.values()).count(f"node-{i}") for i in range(4))
        assert load_a == load_b

    def test_capacity_growth_on_large_cluster(self):
        store, sched = mk_tpu_cluster(4, batch_size=8)
        for i in range(4, 200):  # outgrow the 128-slot default
            store.create_node(make_node(f"node-{i}").capacity({"cpu": "4", "memory": "8Gi", "pods": 110})
                              .label("zone", f"z{i % 2}").obj())
        for i in range(20):
            store.create_pod(make_pod(f"p{i}").req({"cpu": "500m"}).obj())
        sched.run_until_settled()
        assert len(bound_pods(store)) == 20
        assert sched.device.caps.nodes >= 200


class TestDeviceHostComparer:
    """SURVEY §5.2: sampled oracle recheck of device placements."""

    def test_comparer_validates_placements(self):
        from kubernetes_tpu.api.wrappers import make_node, make_pod
        from kubernetes_tpu.apiserver.store import ClusterStore
        from kubernetes_tpu.backend.tpu_scheduler import TPUScheduler

        store = ClusterStore()
        sched = TPUScheduler(store, batch_size=16, comparer_every_n=1)
        for i in range(8):
            store.create_node(
                make_node(f"n{i}").capacity({"cpu": "4", "memory": "8Gi", "pods": 10})
                .label("disk", "ssd" if i % 2 else "hdd").obj())
        for i in range(20):
            pw = make_pod(f"p{i}").req({"cpu": "200m", "memory": "512Mi"})
            if i % 3 == 0:
                pw.node_affinity_in("disk", ["ssd"])
            store.create_pod(pw.obj())
        sched.run_until_settled()
        assert sched.metrics["scheduled"] == 20
        assert sched.comparer_checks >= 20
        assert sched.comparer_mismatches == 0  # device and oracle agree

    def test_comparer_off_by_default(self):
        from kubernetes_tpu.apiserver.store import ClusterStore
        from kubernetes_tpu.backend.tpu_scheduler import TPUScheduler

        sched = TPUScheduler(ClusterStore())
        assert sched.comparer_every_n == 0


class TestCustomProfileFallsBack:
    def test_non_default_profile_uses_oracle_path(self):
        """A profile whose plugin set differs from the compiled program must
        schedule via the sequential path (semantics over speed)."""
        from kubernetes_tpu.api.wrappers import make_node, make_pod
        from kubernetes_tpu.apiserver.store import ClusterStore
        from kubernetes_tpu.backend.tpu_scheduler import TPUScheduler
        from kubernetes_tpu.config.factory import scheduler_from_config

        raw = {"profiles": [{
            "schedulerName": "default-scheduler",
            "plugins": {"score": {"disabled": [{"name": "*"}],
                                   "enabled": [{"name": "NodeResourcesFit", "weight": 5}]}},
        }]}
        store = ClusterStore()
        sched = scheduler_from_config(store, raw=raw, scheduler_cls=TPUScheduler)
        store.create_node(make_node("n1").capacity({"cpu": "4", "memory": "8Gi", "pods": 10}).obj())
        store.create_pod(make_pod("p").req({"cpu": "100m"}).obj())
        sched.run_until_settled()
        assert store.get_pod("default/p").spec.node_name == "n1"
        assert sched.fallback_scheduled == 1 and sched.batch_scheduled == 0

    def test_default_profile_batches(self):
        from kubernetes_tpu.api.wrappers import make_node, make_pod
        from kubernetes_tpu.apiserver.store import ClusterStore
        from kubernetes_tpu.backend.tpu_scheduler import TPUScheduler

        store = ClusterStore()
        sched = TPUScheduler(store)
        store.create_node(make_node("n1").capacity({"cpu": "4", "memory": "8Gi", "pods": 10}).obj())
        store.create_pod(make_pod("p").req({"cpu": "100m"}).obj())
        sched.run_until_settled()
        assert sched.batch_scheduled == 1 and sched.fallback_scheduled == 0


class TestCommitAdoption:
    def test_commit_only_rows_elided(self):
        """After a batch, the device adopts its own commits: the next sync
        uploads nothing for rows whose only change was those commits."""
        from kubernetes_tpu.api.wrappers import make_node, make_pod
        from kubernetes_tpu.apiserver.store import ClusterStore
        from kubernetes_tpu.backend.tpu_scheduler import TPUScheduler

        store = ClusterStore()
        sched = TPUScheduler(store, batch_size=16)
        for i in range(8):
            store.create_node(make_node(f"n{i}").capacity(
                {"cpu": "8", "memory": "16Gi", "pods": 20}).obj())
        for i in range(8):
            store.create_pod(make_pod(f"a{i}").req({"cpu": "200m", "memory": "256Mi"}).obj())
        sched.run_until_settled()
        uploaded_first = sched.device.rows_uploaded
        # second wave: the only prior-row changes are adopted commits
        for i in range(8):
            store.create_pod(make_pod(f"b{i}").req({"cpu": "200m", "memory": "256Mi"}).obj())
        sched.run_until_settled()
        assert sched.metrics["scheduled"] == 16
        assert sched.device.rows_elided >= 8  # commit-only rows skipped
        # and the second wave uploaded no rows at all (nothing else changed)
        assert sched.device.rows_uploaded == uploaded_first

    def test_adoption_survives_external_node_update(self):
        """A real node change after adoption still uploads (content diff)."""
        import dataclasses

        from kubernetes_tpu.api.wrappers import make_node, make_pod
        from kubernetes_tpu.apiserver.store import ClusterStore
        from kubernetes_tpu.backend.tpu_scheduler import TPUScheduler

        store = ClusterStore()
        sched = TPUScheduler(store, batch_size=8)
        store.create_node(make_node("n0").capacity(
            {"cpu": "8", "memory": "16Gi", "pods": 20}).obj())
        store.create_pod(make_pod("a").req({"cpu": "200m"}).obj())
        sched.run_until_settled()
        before = sched.device.rows_uploaded
        node = store.nodes["n0"]
        new = dataclasses.replace(node)
        new.meta = dataclasses.replace(node.meta, labels={**node.meta.labels, "new": "label"})
        store.update_node(new)
        store.create_pod(make_pod("b").req({"cpu": "200m"}).obj())
        sched.run_until_settled()
        assert sched.metrics["scheduled"] == 2
        assert sched.device.rows_uploaded > before  # label change uploaded
