"""Sharded (multi-chip) scheduling path on the 8-device virtual CPU mesh:
the sharded program must produce decisions equivalent to the single-device
program on the same inputs."""

import numpy as np
import jax

from kubernetes_tpu.api.types import LabelSelector
from kubernetes_tpu.api.wrappers import make_node, make_pod
from kubernetes_tpu.backend.batch import schedule_batch
from kubernetes_tpu.backend.sig_table import SigTable
from kubernetes_tpu.framework.types import NodeInfo
from kubernetes_tpu.ops.encode import ClusterEncoder
from kubernetes_tpu.ops.schema import Capacities
from kubernetes_tpu.parallel import (
    make_node_mesh,
    make_sharded_schedule_fn,
    shard_node_tensors,
    shard_topo_counts,
)


def build_inputs(n_nodes=32, n_pods=8, topo=False):
    infos = []
    for i in range(n_nodes):
        nw = make_node(f"node-{i}").capacity({"cpu": "4", "memory": "8Gi", "pods": 20}).label("zone", f"z{i % 4}")
        if i % 7 == 0:
            nw.taint("dedicated", "x", "NoSchedule")
        infos.append(NodeInfo(nw.obj()))
    enc = ClusterEncoder(Capacities(nodes=n_nodes, pods=n_pods, value_words=32))
    sig = SigTable(enc)
    nt = enc.encode_snapshot(infos)
    pods = []
    for i in range(n_pods):
        pw = make_pod(f"p{i}").req({"cpu": "1", "memory": "1Gi"}).label("app", f"a{i % 2}")
        if i % 3 == 0:
            pw.node_affinity_in("zone", [f"z{i % 4}"])
        if topo:
            pw.spread_constraint(1, "zone", selector=LabelSelector(match_labels={"app": f"a{i % 2}"}))
            if i % 2 == 0:
                pw.pod_affinity("zone", LabelSelector(match_labels={"app": "a1"}), anti=True)
        pods.append(pw.obj())
    pb, et = enc.encode_pods(pods)
    tb = sig.encode_topo(pods)
    tc = sig.topo_counts()
    return enc, nt, pb, et, tc, tb


def test_sharded_matches_single_device():
    assert len(jax.devices()) == 8, "conftest must force 8 CPU devices"
    enc, nt, pb, et, tc, tb = build_inputs()
    key = jax.random.PRNGKey(7)
    single = schedule_batch(pb, et, nt, tc, tb, key, topo_enabled=False)

    mesh = make_node_mesh()
    nt_sharded = shard_node_tensors(nt, mesh)
    fn = make_sharded_schedule_fn(mesh, topo_enabled=False)
    sharded = fn(pb, et, nt_sharded, shard_topo_counts(tc, mesh), tb, key)

    # feasibility identical; placements may differ only within score ties
    assert np.array_equal(np.asarray(single.any_feasible), np.asarray(sharded.any_feasible))
    np.testing.assert_allclose(
        np.asarray(single.best_score), np.asarray(sharded.best_score), atol=1.5
    )
    # chosen nodes must be feasible under the single-device masks
    fit = np.asarray(single.fit_ok)
    for p, slot in enumerate(np.asarray(sharded.node_idx)):
        if slot >= 0:
            assert fit[p, slot]
            for name, m in single.static_masks.items():
                assert np.asarray(m)[p, slot], name


def test_sharded_topology_matches_single_device():
    """Spread + anti-affinity kernels under shard_map: the sharded program's
    feasibility, scores, and per-plugin masks must match single-device."""
    enc, nt, pb, et, tc, tb = build_inputs(topo=True)
    key = jax.random.PRNGKey(3)
    single = schedule_batch(pb, et, nt, tc, tb, key, topo_enabled=True)

    mesh = make_node_mesh()
    fn = make_sharded_schedule_fn(mesh, topo_enabled=True)
    sharded = fn(pb, et, shard_node_tensors(nt, mesh), shard_topo_counts(tc, mesh), tb, key)

    # global-slot-keyed jitter makes the sharded program bit-identical in its
    # decision sequence, so the evolving topology state matches step for step
    assert np.array_equal(np.asarray(single.node_idx), np.asarray(sharded.node_idx))
    assert np.array_equal(np.asarray(single.any_feasible), np.asarray(sharded.any_feasible))
    np.testing.assert_allclose(
        np.asarray(single.best_score), np.asarray(sharded.best_score), atol=1e-4
    )
    for name in ("spread_ok", "ipa_ok", "fit_ok", "ports_ok"):
        s, m = np.asarray(getattr(single, name)), np.asarray(getattr(sharded, name))
        assert np.array_equal(s, m), name


def test_sharded_topo_carry_matches_single_device():
    """The evolved carry the host adopts after a batch (final_requested /
    final_sel_counts / final_seg_exist) must be identical between the sharded
    and single-device programs — adopt-time consistency across shards, not
    just matching decisions (VERDICT r2 weak #5)."""
    enc, nt, pb, et, tc, tb = build_inputs(topo=True)
    key = jax.random.PRNGKey(11)
    single = schedule_batch(pb, et, nt, tc, tb, key, topo_enabled=True)

    mesh = make_node_mesh()
    fn = make_sharded_schedule_fn(mesh, topo_enabled=True)
    sharded = fn(pb, et, shard_node_tensors(nt, mesh), shard_topo_counts(tc, mesh), tb, key)

    for name in ("final_requested", "final_nonzero", "final_ports",
                 "final_sel_counts", "final_seg_exist"):
        s = np.asarray(getattr(single, name))
        m = np.asarray(getattr(sharded, name))
        assert s.shape == m.shape, (name, s.shape, m.shape)
        assert np.array_equal(s, m), name


def test_sharded_sequential_commit_respects_capacity():
    # a single 1-pod-capacity node lives on ONE shard; the whole batch fights
    # for it and exactly one pod must win globally
    infos = [NodeInfo(make_node("only").capacity({"cpu": "2", "memory": "4Gi", "pods": 1}).obj())]
    for i in range(7):
        infos.append(NodeInfo(make_node(f"full-{i}").capacity({"cpu": "0", "memory": "0", "pods": 0}).obj()))
    enc = ClusterEncoder(Capacities(nodes=8, pods=4, value_words=32))
    sig = SigTable(enc)
    nt = enc.encode_snapshot(infos)
    pods = [make_pod(f"p{i}").req({"cpu": "1"}).obj() for i in range(4)]
    pb, et = enc.encode_pods(pods)
    tb = sig.encode_topo(pods)
    tc = sig.topo_counts()
    mesh = make_node_mesh()
    fn = make_sharded_schedule_fn(mesh, topo_enabled=False)
    res = fn(pb, et, shard_node_tensors(nt, mesh), shard_topo_counts(tc, mesh), tb, jax.random.PRNGKey(0))
    idx = np.asarray(res.node_idx)
    assert (idx >= 0).sum() == 1
    assert idx[(idx >= 0)][0] == enc.node_slots["only"]


def test_sharded_anti_affinity_cross_shard():
    """A pod's committed anti-affinity term must block later batch pods from
    the whole topology domain even when domain nodes live on OTHER shards."""
    infos = []
    for i in range(16):
        infos.append(NodeInfo(
            make_node(f"n{i}").capacity({"cpu": "8", "memory": "16Gi", "pods": 10})
            .label("zone", f"z{i % 2}").obj()))
    enc = ClusterEncoder(Capacities(nodes=16, pods=4, value_words=32))
    sig = SigTable(enc)
    nt = enc.encode_snapshot(infos)
    sel = LabelSelector(match_labels={"app": "x"})
    pods = [
        make_pod(f"p{i}").req({"cpu": "1"}).label("app", "x")
        .pod_affinity("zone", sel, anti=True).obj()
        for i in range(4)
    ]
    pb, et = enc.encode_pods(pods)
    tb = sig.encode_topo(pods)
    tc = sig.topo_counts()
    mesh = make_node_mesh()
    fn = make_sharded_schedule_fn(mesh, topo_enabled=True)
    res = fn(pb, et, shard_node_tensors(nt, mesh), shard_topo_counts(tc, mesh), tb, jax.random.PRNGKey(1))
    idx = np.asarray(res.node_idx)
    # 2 zones ⇒ exactly 2 of the 4 mutually-anti-affine pods can place,
    # and they must land in different zones
    placed = idx[idx >= 0]
    assert len(placed) == 2, idx
    zones = {int(i) % 2 for i in placed}
    assert len(zones) == 2


def test_sharded_spec_decode_matches_scan(monkeypatch):
    """Sharded SPECULATIVE decode (VERDICT r4 item 6): the decide/repair
    rounds under shard_map must reproduce the single-device SCAN's
    placements exactly — same winners, same feasibility, same scores — on
    the topology-off program (the flagship headline shape)."""
    monkeypatch.setenv("KTPU_SPEC", "1")
    enc, nt, pb, et, tc, tb = build_inputs(n_nodes=48, n_pods=16)
    key = jax.random.PRNGKey(11)
    # single-device sequential scan = ground truth
    scan = schedule_batch(pb, et, nt, tc, tb, key, topo_enabled=False,
                          spec_decode=False)

    mesh = make_node_mesh()
    nt_sharded = shard_node_tensors(nt, mesh)
    fn = make_sharded_schedule_fn(mesh, topo_enabled=False, spec_decode=True)
    spec = fn(pb, et, nt_sharded, shard_topo_counts(tc, mesh), tb, key)

    assert np.array_equal(np.asarray(scan.node_idx), np.asarray(spec.node_idx)), (
        np.asarray(scan.node_idx), np.asarray(spec.node_idx))
    assert np.array_equal(np.asarray(scan.any_feasible),
                          np.asarray(spec.any_feasible))
    np.testing.assert_allclose(np.asarray(scan.best_score),
                               np.asarray(spec.best_score), atol=1e-4)
    # evolved node state identical (concatenate the shards' windows)
    np.testing.assert_array_equal(np.asarray(scan.final_requested),
                                  np.asarray(spec.final_requested))
    np.testing.assert_array_equal(np.asarray(scan.final_ports),
                                  np.asarray(spec.final_ports))


def test_sharded_spec_decode_capacity_conflicts(monkeypatch):
    """Intra-batch capacity conflicts under sharded spec decode: 16 pods
    that each nearly fill a node, 8 tight nodes — rounds must serialize
    correctly (prefix rule) and the losers must fail exactly as the scan
    says."""
    monkeypatch.setenv("KTPU_SPEC", "1")
    infos = []
    for i in range(8):
        infos.append(NodeInfo(
            make_node(f"n{i}").capacity({"cpu": "2", "memory": "4Gi", "pods": 3}).obj()))
    enc = ClusterEncoder(Capacities(nodes=8, pods=16, value_words=32))
    sig = SigTable(enc)
    nt = enc.encode_snapshot(infos)
    pods = [make_pod(f"p{i}").req({"cpu": "1500m", "memory": "1Gi"}).obj()
            for i in range(16)]
    pb, et = enc.encode_pods(pods)
    tb = sig.encode_topo(pods)
    tc = sig.topo_counts()
    key = jax.random.PRNGKey(5)
    scan = schedule_batch(pb, et, nt, tc, tb, key, topo_enabled=False,
                          spec_decode=False)
    mesh = make_node_mesh()
    fn = make_sharded_schedule_fn(mesh, topo_enabled=False, spec_decode=True)
    spec = fn(pb, et, shard_node_tensors(nt, mesh), shard_topo_counts(tc, mesh),
              tb, key)
    assert np.array_equal(np.asarray(scan.node_idx), np.asarray(spec.node_idx))
    # exactly 8 place (one per node), 8 fail
    assert int((np.asarray(spec.node_idx) >= 0).sum()) == 8


def _hostname_topo_inputs(n_nodes=32, n_pods=16):
    """Cluster with node-unique hostname labels + pods carrying hostname-key
    spread and required anti-affinity — the hostname fast-path shapes."""
    from kubernetes_tpu.framework.plugins.podtopologyspread import HOSTNAME_KEY

    infos = []
    for i in range(n_nodes):
        infos.append(NodeInfo(
            make_node(f"node-{i}")
            .capacity({"cpu": "8", "memory": "16Gi", "pods": 20})
            .label(HOSTNAME_KEY, f"node-{i}")
            .obj()))
    enc = ClusterEncoder(Capacities(nodes=n_nodes, pods=n_pods, value_words=32))
    sig = SigTable(enc)
    nt = enc.encode_snapshot(infos)
    sel = LabelSelector(match_labels={"app": "web"})
    pods = []
    for i in range(n_pods):
        pw = make_pod(f"p{i}").req({"cpu": "1", "memory": "1Gi"}).label("app", "web")
        pw.spread_constraint(1, HOSTNAME_KEY, selector=sel)
        if i % 2 == 0:
            pw.pod_affinity(HOSTNAME_KEY,
                            LabelSelector(match_labels={"app": "web"}), anti=True)
        pods.append(pw.obj())
    pb, et = enc.encode_pods(pods)
    tb = sig.encode_topo(pods)
    tc = sig.topo_counts()
    host_key = enc.key_slot(HOSTNAME_KEY)
    return enc, nt, pb, et, tc, tb, host_key


def test_sharded_spec_decode_hostname_mode_matches_scan(monkeypatch):
    """Sharded speculative decode on the HOSTNAME topology fast path: the
    decide/repair rounds under shard_map must match the single-device scan
    exactly on spread + intra-batch anti-affinity workloads."""
    monkeypatch.setenv("KTPU_SPEC", "1")
    enc, nt, pb, et, tc, tb, host_key = _hostname_topo_inputs()
    key = jax.random.PRNGKey(13)
    scan = schedule_batch(pb, et, nt, tc, tb, key, topo_enabled=True,
                          topo_mode="host", host_key=host_key,
                          spec_decode=False)

    mesh = make_node_mesh()
    fn = make_sharded_schedule_fn(mesh, topo_enabled=True, spec_decode=True,
                                  topo_mode="host", host_key=host_key)
    spec = fn(pb, et, shard_node_tensors(nt, mesh),
              shard_topo_counts(tc, mesh), tb, key)

    assert np.array_equal(np.asarray(scan.node_idx), np.asarray(spec.node_idx)), (
        np.asarray(scan.node_idx), np.asarray(spec.node_idx))
    assert np.array_equal(np.asarray(scan.any_feasible),
                          np.asarray(spec.any_feasible))
    np.testing.assert_allclose(np.asarray(scan.best_score),
                               np.asarray(spec.best_score), atol=1e-4)
    # evolved topology carries identical (host mode: [S,N] sel + [T,N] term)
    np.testing.assert_array_equal(np.asarray(scan.final_sel_counts),
                                  np.asarray(spec.final_sel_counts))
    np.testing.assert_array_equal(np.asarray(scan.final_seg_exist),
                                  np.asarray(spec.final_seg_exist))
    # anti-affinity honored: no two anti pods share a node
    idx = np.asarray(spec.node_idx)
    anti = [idx[i] for i in range(16) if i % 2 == 0 and idx[i] >= 0]
    assert len(anti) == len(set(anti))


def test_sharded_spec_decode_general_mode_matches_scan(monkeypatch):
    """Sharded speculative decode on the GENERAL domain-aggregating mode
    (zone-keyed spread + inter-pod affinity: several nodes per domain, so
    the segment tables psum to a replicated global view and the term
    commits scatter identically on every shard) — exact parity with the
    single-device scan."""
    monkeypatch.setenv("KTPU_SPEC", "1")
    enc, nt, pb, et, tc, tb = build_inputs(n_nodes=48, n_pods=16, topo=True)
    key = jax.random.PRNGKey(21)
    scan = schedule_batch(pb, et, nt, tc, tb, key, topo_enabled=True,
                          spec_decode=False)

    mesh = make_node_mesh()
    fn = make_sharded_schedule_fn(mesh, topo_enabled=True, spec_decode=True,
                                  topo_mode="general")
    spec = fn(pb, et, shard_node_tensors(nt, mesh),
              shard_topo_counts(tc, mesh), tb, key)

    assert np.array_equal(np.asarray(scan.node_idx), np.asarray(spec.node_idx)), (
        np.asarray(scan.node_idx), np.asarray(spec.node_idx))
    assert np.array_equal(np.asarray(scan.any_feasible),
                          np.asarray(spec.any_feasible))
    np.testing.assert_allclose(np.asarray(scan.best_score),
                               np.asarray(spec.best_score), atol=1e-4)
    # evolved carries identical: node-sharded sel counts + the replicated
    # [T, Vd] domain table every shard must agree on
    np.testing.assert_array_equal(np.asarray(scan.final_sel_counts),
                                  np.asarray(spec.final_sel_counts))
    np.testing.assert_array_equal(np.asarray(scan.final_seg_exist),
                                  np.asarray(spec.final_seg_exist))
