"""Sharded (multi-chip) scheduling path on the 8-device virtual CPU mesh:
the sharded program must produce decisions equivalent to the single-device
program on the same inputs."""

import numpy as np
import jax

from kubernetes_tpu.api.wrappers import make_node, make_pod
from kubernetes_tpu.backend.batch import schedule_batch
from kubernetes_tpu.framework.types import NodeInfo
from kubernetes_tpu.ops.encode import ClusterEncoder
from kubernetes_tpu.ops.schema import Capacities
from kubernetes_tpu.parallel import make_node_mesh, make_sharded_schedule_fn, shard_node_tensors


def build_inputs(n_nodes=32, n_pods=8):
    infos = []
    for i in range(n_nodes):
        nw = make_node(f"node-{i}").capacity({"cpu": "4", "memory": "8Gi", "pods": 20}).label("zone", f"z{i % 4}")
        if i % 7 == 0:
            nw.taint("dedicated", "x", "NoSchedule")
        infos.append(NodeInfo(nw.obj()))
    enc = ClusterEncoder(Capacities(nodes=n_nodes, pods=n_pods, value_words=32))
    nt = enc.encode_snapshot(infos)
    pods = []
    for i in range(n_pods):
        pw = make_pod(f"p{i}").req({"cpu": "1", "memory": "1Gi"})
        if i % 3 == 0:
            pw.node_affinity_in("zone", [f"z{i % 4}"])
        pods.append(pw.obj())
    pb, et = enc.encode_pods(pods)
    return enc, nt, pb, et


def test_sharded_matches_single_device():
    assert len(jax.devices()) == 8, "conftest must force 8 CPU devices"
    enc, nt, pb, et = build_inputs()
    key = jax.random.PRNGKey(7)
    single = schedule_batch(pb, et, nt, key)

    mesh = make_node_mesh()
    nt_sharded = shard_node_tensors(nt, mesh)
    fn = make_sharded_schedule_fn(mesh)
    sharded = fn(pb, et, nt_sharded, key)

    # feasibility identical; placements may differ only within score ties
    assert np.array_equal(np.asarray(single.any_feasible), np.asarray(sharded.any_feasible))
    np.testing.assert_allclose(
        np.asarray(single.best_score), np.asarray(sharded.best_score), atol=1.5
    )
    # chosen nodes must be feasible under the single-device masks
    fit = np.asarray(single.fit_ok)
    for p, slot in enumerate(np.asarray(sharded.node_idx)):
        if slot >= 0:
            assert fit[p, slot]
            for name, m in single.static_masks.items():
                assert np.asarray(m)[p, slot], name


def test_sharded_sequential_commit_respects_capacity():
    # a single 1-pod-capacity node lives on ONE shard; the whole batch fights
    # for it and exactly one pod must win globally
    infos = [NodeInfo(make_node("only").capacity({"cpu": "2", "memory": "4Gi", "pods": 1}).obj())]
    for i in range(7):
        infos.append(NodeInfo(make_node(f"full-{i}").capacity({"cpu": "0", "memory": "0", "pods": 0}).obj()))
    enc = ClusterEncoder(Capacities(nodes=8, pods=4, value_words=32))
    nt = enc.encode_snapshot(infos)
    pb, et = enc.encode_pods([make_pod(f"p{i}").req({"cpu": "1"}).obj() for i in range(4)])
    mesh = make_node_mesh()
    fn = make_sharded_schedule_fn(mesh)
    res = fn(pb, et, shard_node_tensors(nt, mesh), jax.random.PRNGKey(0))
    idx = np.asarray(res.node_idx)
    assert (idx >= 0).sum() == 1
    assert idx[(idx >= 0)][0] == enc.node_slots["only"]
