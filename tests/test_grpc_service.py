"""gRPC binding of the batched device service: proto round-trips, pod
template dedup on the wire, e2e scheduling over a real gRPC channel, and
preemption hints riding back with failures (ROADMAP wire hardening)."""

import numpy as np
import pytest

# proto messages resolve vendored-first (tools/gen_pb2.py output, hash-gated
# against the .proto) and fall back to an on-demand protoc build; only when
# NEITHER is available would every test here error at the first pb2() call —
# skip the module with a reason instead of failing collection/run
from kubernetes_tpu.backend import grpc_service as _gs

if not _gs.pb2_available():
    pytest.skip("no vendored ktpu_device_pb2, no cached build, no protoc "
                "(run `python tools/gen_pb2.py`)", allow_module_level=True)

from kubernetes_tpu.api.codec import to_wire
from kubernetes_tpu.api.types import PriorityClass, ObjectMeta
from kubernetes_tpu.api.wrappers import make_node, make_pod
from kubernetes_tpu.apiserver import ClusterStore
from kubernetes_tpu.backend.grpc_service import (
    GrpcClient,
    _batch_from_proto,
    _batch_to_proto,
    pb2,
    serve_grpc,
)
from kubernetes_tpu.backend.service import DeviceService, WireScheduler


def _bound(store):
    objs, _rv = store.list_objects("Pod")
    return {p.meta.name: p.spec.node_name for p in objs if p.spec.node_name}


class TestProtoCodec:
    def test_template_dedup(self):
        pods = [make_pod(f"p{i}").req({"cpu": "500m"}).label("app", "web").obj()
                for i in range(50)]
        payload = {"pods": [to_wire(p) for p in pods]}
        req = _batch_to_proto(payload)
        assert len(req.templates) == 1  # 50 identical shapes -> one template
        assert len(req.pods) == 50
        back = _batch_from_proto(req)
        assert [p["meta"]["name"] for p in back["pods"]] == \
            [f"p{i}" for i in range(50)]
        assert back["pods"][0]["spec"] == payload["pods"][0]["spec"]

    def test_distinct_shapes_distinct_templates(self):
        pods = [make_pod("a").req({"cpu": "1"}).obj(),
                make_pod("b").req({"cpu": "2"}).obj(),
                make_pod("c").req({"cpu": "1"}).obj()]
        req = _batch_to_proto({"pods": [to_wire(p) for p in pods]})
        assert len(req.templates) == 2

    def test_wire_size_shrinks(self):
        import json

        pods = [make_pod(f"p{i}").req({"cpu": "500m", "memory": "1Gi"})
                .label("app", "web").obj() for i in range(256)]
        payload = {"pods": [to_wire(p) for p in pods]}
        json_size = len(json.dumps(payload).encode())
        proto_size = len(_batch_to_proto(payload).SerializeToString())
        assert proto_size < json_size / 5  # template dedup + binary framing


class TestGrpcEndToEnd:
    def test_schedule_over_grpc(self):
        service = DeviceService(batch_size=32)
        server, port = serve_grpc(service)
        try:
            store = ClusterStore()
            sched = WireScheduler(store, endpoint=f"127.0.0.1:{port}",
                                  batch_size=8, transport="grpc")
            for i in range(4):
                store.create_node(
                    make_node(f"n{i}")
                    .capacity({"cpu": "4", "memory": "8Gi", "pods": 10}).obj())
            for i in range(12):
                store.create_pod(
                    make_pod(f"p{i}").req({"cpu": "500m", "memory": "512Mi"}).obj())
            sched.run_until_settled()
            bound = _bound(store)
            assert len(bound) == 12
            assert set(bound.values()) <= {f"n{i}" for i in range(4)}
        finally:
            server.stop(0)

    def test_unschedulable_carries_preempt_hints(self):
        service = DeviceService(batch_size=16)
        server, port = serve_grpc(service)
        try:
            store = ClusterStore()
            store.create_priority_class(PriorityClass(
                meta=ObjectMeta(name="high"), value=1000))
            sched = WireScheduler(store, endpoint=f"127.0.0.1:{port}",
                                  batch_size=8, transport="grpc")
            store.create_node(
                make_node("n0").capacity({"cpu": "2", "memory": "4Gi", "pods": 10}).obj())
            # fill the node with a low-priority pod
            store.create_pod(make_pod("low").req({"cpu": "1800m"}).obj())
            sched.run_until_settled()
            assert _bound(store).get("low") == "n0"
            # a high-priority pod that does not fit -> preemption via hints
            hi = make_pod("hi").req({"cpu": "1500m"}).obj()
            hi.spec.priority = 1000
            store.create_pod(hi)
            sched.run_until_settled()
            bound = _bound(store)
            assert bound.get("hi") == "n0", bound
            # the victim was deleted or requeued unbound
            assert store.get_pod("default/low") is None \
                or not store.get_pod("default/low").spec.node_name
        finally:
            server.stop(0)

    def test_grpc_matches_http_placements(self):
        from kubernetes_tpu.backend.service import serve

        def run(transport):
            service = DeviceService(batch_size=32)
            if transport == "grpc":
                server, port = serve_grpc(service)
                endpoint = f"127.0.0.1:{port}"
            else:
                server, port = serve(service)
                endpoint = f"http://127.0.0.1:{port}"
            try:
                store = ClusterStore()
                sched = WireScheduler(store, endpoint=endpoint, batch_size=16,
                                      transport=transport)
                for i in range(6):
                    store.create_node(
                        make_node(f"n{i}")
                        .capacity({"cpu": "8", "memory": "16Gi", "pods": 20})
                        .label("zone", f"z{i % 2}").obj())
                for i in range(24):
                    store.create_pod(
                        make_pod(f"p{i}").req({"cpu": "900m", "memory": "1Gi"}).obj())
                sched.run_until_settled()
                return _bound(store)
            finally:
                if transport == "grpc":
                    server.stop(0)
                else:
                    server.shutdown()

        assert run("grpc") == run("http")


class TestGrpcSessionsAndConflicts:
    """HA session verbs and the conflict taxonomy over gRPC: ABORTED maps
    to ConflictError (distinct from FAILED_PRECONDITION's StaleEpochError),
    per-result conflict flags round-trip, and Heartbeat/Sessions serve the
    lease protocol (ISSUE 6, grpc half — behind the module protoc skip)."""

    def test_conflict_verdict_and_aborted_mapping(self):
        from kubernetes_tpu.backend.errors import ConflictError
        from kubernetes_tpu.utils.clock import FakeClock

        clock = FakeClock()
        service = DeviceService(batch_size=8, lease_ttl_s=5.0, now_fn=clock)
        server, port = serve_grpc(service)
        try:
            client = GrpcClient(f"127.0.0.1:{port}")
            assert client.supports_sessions
            node = make_node("n0").capacity(
                {"cpu": "4", "memory": "8Gi", "pods": 10}).obj()
            entry = {"gen": 1, "node": to_wire(node), "pods": []}
            out_a = client.apply_deltas({"clientId": "A", "nodes": [entry]})
            gen_a = out_a["sessionGen"]
            client.apply_deltas({"clientId": "B", "nodes": [entry]})

            # per-result conflict: B races A for the same pod and loses
            pod = to_wire(make_pod("raced").req({"cpu": "1"}).obj())
            first = client.schedule_batch(
                {"clientId": "A", "sessionGen": gen_a, "pods": [pod],
                 "batchId": "a-1"})
            assert first["results"][0]["nodeName"] == "n0"
            second = client.schedule_batch(
                {"clientId": "B", "pods": [pod], "batchId": "b-1"})
            assert second["results"][0]["nodeName"] is None
            assert second["results"][0]["conflict"] is True

            # heartbeat renews + reports; an expired lease fences A and the
            # zombie's next commit ABORTs -> typed ConflictError
            hb = client.heartbeat({"clientId": "B"})
            assert hb["sessions"] >= 2 and hb["leaseTtlS"] == 5.0
            clock.advance(3.0)
            client.heartbeat({"clientId": "B"})
            clock.advance(3.0)
            hb = client.heartbeat({"clientId": "B"})
            assert "A" in hb["fenced"]
            import pytest as _pytest

            with _pytest.raises(ConflictError):
                client.schedule_batch(
                    {"clientId": "A", "sessionGen": gen_a, "pods": [pod],
                     "batchId": "a-2"})

            # sessions dump rides the Sessions RPC
            dump = client.sessions_dump()
            table = {s["clientId"]: s for s in dump["sessions"]}
            assert table["A"]["fenced"] is True
            assert table["B"]["fenced"] is False
        finally:
            server.stop(0)

    def test_two_grpc_replicas_shared_service_no_oversubscription(self):
        from kubernetes_tpu.apiserver import ClusterStore as _Store

        service = DeviceService(batch_size=32)
        server, port = serve_grpc(service)
        try:
            store = _Store()
            for i in range(2):
                store.create_node(
                    make_node(f"n{i}").capacity(
                        {"cpu": "4", "memory": "8Gi", "pods": 10}).obj())
            # synchronous transport: the exact-fill race below counts on
            # each cycle's binds being visible to the peer's next pop (the
            # pipelined path defers processing by a cycle, which just means
            # more conflict/backoff rounds than this bounded loop runs)
            a = WireScheduler(store, endpoint=f"127.0.0.1:{port}",
                              batch_size=4, transport="grpc", client_id="A",
                              wire_pipeline_depth=0,
                              pod_initial_backoff=0.05, pod_max_backoff=0.1)
            b = WireScheduler(store, endpoint=f"127.0.0.1:{port}",
                              batch_size=4, transport="grpc", client_id="B",
                              wire_pipeline_depth=0,
                              pod_initial_backoff=0.05, pod_max_backoff=0.1)
            for i in range(8):  # 8 x 1cpu == 2 nodes x 4cpu: exact fill
                store.create_pod(make_pod(f"p{i}").req({"cpu": "1"}).obj())
            for _ in range(50):
                a.schedule_batch_cycle()
                b.schedule_batch_cycle()
                if len(_bound(store)) == 8:
                    break
                a.queue.flush_backoff_completed()
                b.queue.flush_backoff_completed()
            bound = _bound(store)
            assert len(bound) == 8
            per_node = {}
            for n in bound.values():
                per_node[n] = per_node.get(n, 0) + 1
            assert all(v <= 4 for v in per_node.values()), per_node
        finally:
            server.stop(0)


class TestVendoredPb2:
    """tools/gen_pb2.py vendoring: the no-protoc path that lets this whole
    module run on images without protoc/grpcio-tools (ISSUE 8 satellite)."""

    @staticmethod
    def _tool():
        import importlib.util
        import os

        tool = os.path.join(_gs._REPO_ROOT, "tools", "gen_pb2.py")
        spec = importlib.util.spec_from_file_location("gen_pb2", tool)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def test_vendored_module_is_fresh(self):
        # the CI drift gate: regenerating from the current .proto must
        # reproduce the vendored file byte-for-byte
        mod = self._tool()
        with open(mod.OUT, "r", encoding="utf-8") as f:
            assert f.read() == mod.generate(), (
                "vendored ktpu_device_pb2.py is stale — run "
                "`python tools/gen_pb2.py`")

    def test_vendored_staleness_checked_from_file_text(self, monkeypatch):
        """The hash gate must decide BEFORE importing the module: executing
        a stale module registers 'ktpu_device.proto' in the process-default
        descriptor pool and the protoc-built fallback then dies with
        duplicate-file instead of loading."""
        from kubernetes_tpu.native import ktpu_device_pb2 as vendored

        assert _gs._vendored_hash() == vendored.PROTO_SHA256
        monkeypatch.setattr(_gs, "_proto_sha256", lambda: "0" * 64)
        assert _gs._vendored_pb2() is None  # rejected, no import executed

    def test_pb2_prefers_fresh_vendored_module(self):
        from kubernetes_tpu.native import ktpu_device_pb2 as vendored

        assert _gs._vendored_pb2() is vendored
        assert _gs.pb2_available()
        # every PR-6 session/conflict field rides the vendored schema
        req = pb2().ScheduleBatchRequest()
        for field in ("client_id", "session_gen", "batch_id", "claims"):
            assert field in req.DESCRIPTOR.fields_by_name

    def test_parser_rejects_unsupported_constructs(self):
        mod = self._tool()
        with pytest.raises(ValueError, match="unsupported"):
            mod.parse_proto('syntax = "proto3"; package p;'
                            'message M { oneof k { int32 a = 1; } }')
        with pytest.raises(ValueError, match="unsupported"):
            mod.parse_proto('syntax = "proto3"; package p;'
                            'service S { }')
        # the supported subset round-trips
        pkg, msgs = mod.parse_proto(
            'syntax = "proto3"; package p.v1;'
            'message M { repeated string a = 1; map<string, bytes> b = 2; }')
        assert pkg == "p.v1" and msgs[0][0] == "M"
        fdp = mod.build_file_descriptor(pkg, msgs, "m.proto")
        entry = fdp.message_type[0].nested_type[0]
        assert entry.name == "BEntry" and entry.options.map_entry
