"""Out-of-tree plugin registration (VERDICT r3 item 10): the app.WithPlugin
analog — examples/out_of_tree_plugin.py's ZoneWeight registered through
scheduler_from_config(out_of_tree_registry=...)."""

import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from examples.out_of_tree_plugin import ZoneWeight  # noqa: E402
from kubernetes_tpu.api.wrappers import make_node, make_pod  # noqa: E402
from kubernetes_tpu.apiserver.store import ClusterStore  # noqa: E402
from kubernetes_tpu.config import scheduler_from_config  # noqa: E402


def _raw_config(forbidden=("z2",), weights=None):
    return {
        "apiVersion": "kubescheduler.config.k8s.io/v1beta3",
        "kind": "KubeSchedulerConfiguration",
        "profiles": [{
            "schedulerName": "zoned-scheduler",
            "plugins": {
                "filter": {"enabled": [{"name": ZoneWeight.NAME}]},
                "score": {"enabled": [{"name": ZoneWeight.NAME, "weight": 5}]},
            },
            "pluginConfig": [{
                "name": ZoneWeight.NAME,
                "args": {"forbidden": list(forbidden),
                         "weights": weights or {"z1": 100, "z0": 10}},
            }],
        }],
    }


def _cluster(store, n=6):
    for i in range(n):
        store.create_node(
            make_node(f"node-{i}")
            .capacity({"cpu": "8", "memory": "16Gi", "pods": 20})
            .label("zone", f"z{i % 3}").obj())


def test_out_of_tree_plugin_filters_and_scores():
    store = ClusterStore()
    _cluster(store)
    sched = scheduler_from_config(
        store, raw=_raw_config(),
        out_of_tree_registry={ZoneWeight.NAME: ZoneWeight})
    for i in range(4):
        pw = make_pod(f"pod-{i}").req({"cpu": "500m", "memory": "512Mi"})
        pw.scheduler_name("zoned-scheduler")
        store.create_pod(pw.obj())
    sched.run_until_settled()
    zones = {store.nodes[p.spec.node_name].meta.labels["zone"]
             for p in store.pods.values()}
    assert zones == {"z1"}  # weight 100 wins, z2 filtered


def test_out_of_tree_plugin_unschedulable_when_all_forbidden():
    store = ClusterStore()
    _cluster(store, n=3)
    sched = scheduler_from_config(
        store, raw=_raw_config(forbidden=("z0", "z1", "z2")),
        out_of_tree_registry={ZoneWeight.NAME: ZoneWeight})
    pw = make_pod("stuck").req({"cpu": "1"})
    pw.scheduler_name("zoned-scheduler")
    store.create_pod(pw.obj())
    sched.run_until_settled()
    assert not store.get_pod("default/stuck").spec.node_name


def test_name_collision_with_in_tree_plugin_raises():
    store = ClusterStore()
    with pytest.raises(ValueError, match="already registered"):
        scheduler_from_config(
            store, raw=_raw_config(),
            out_of_tree_registry={"NodeAffinity": ZoneWeight})


def test_custom_profile_takes_host_path_on_batched_scheduler():
    """A profile with an out-of-tree plugin must NOT be batched (the
    compiled program only implements the default set) — the sequential
    host path honors the plugin instead."""
    from kubernetes_tpu.backend.tpu_scheduler import TPUScheduler

    store = ClusterStore()
    _cluster(store)
    sched = scheduler_from_config(
        store, raw=_raw_config(),
        out_of_tree_registry={ZoneWeight.NAME: ZoneWeight},
        scheduler_cls=TPUScheduler)
    for i in range(4):
        pw = make_pod(f"pod-{i}").req({"cpu": "500m", "memory": "512Mi"})
        pw.scheduler_name("zoned-scheduler")
        store.create_pod(pw.obj())
    sched.run_until_settled()
    assert sched.fallback_scheduled == 4  # all via the host path
    zones = {store.nodes[p.spec.node_name].meta.labels["zone"]
             for p in store.pods.values()}
    assert zones == {"z1"}
