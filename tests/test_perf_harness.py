"""Perf harness tests at small sizes: ops run, throughput summary shape,
DataItems JSON schema matches util.go:331 (SchedulingThroughput DataItem),
both backends complete SchedulingBasic."""

import json

import pytest

from kubernetes_tpu.perf import TEST_CASES, data_items_to_json, run_workload
from kubernetes_tpu.perf.harness import ThroughputCollector


def test_throughput_collector_sampling():
    count = [0]
    col = ThroughputCollector(lambda: count[0], interval=1.0)
    t = 0.0
    col.start(t)
    for _ in range(5):
        count[0] += 100
        t += 1.0
        col.maybe_sample(t)
    s = col.summary()
    assert abs(s["Average"] - 100.0) < 1e-6
    assert s["Perc99"] >= s["Perc50"]


def test_scheduling_basic_oracle():
    tc = TEST_CASES["SchedulingBasic"](nodes=50, init_pods=20, measured=30)
    items = run_workload(tc, backend="oracle")
    tput = [it for it in items if it.labels["Name"] == "SchedulingThroughput"]
    assert len(tput) == 1
    assert tput[0].unit == "pods/s"
    assert tput[0].labels["TestCase"] == "SchedulingBasic/50Nodes"
    doc = json.loads(data_items_to_json(items))
    assert doc["version"] == "v1"
    assert "Average" in doc["dataItems"][0]["data"]
    # measured-phase attempt-latency percentiles ride along (util.go:204
    # metricsCollector analog)
    lat = [it for it in items
           if it.labels["Name"] == "scheduling_attempt_duration_seconds"
           and it.labels["result"] == "scheduled"]
    assert len(lat) == 1 and lat[0].unit == "s"
    assert lat[0].data["Perc99"] >= lat[0].data["Perc50"] > 0


def test_scheduling_basic_tpu_backend():
    tc = TEST_CASES["SchedulingBasic"](nodes=32, init_pods=10, measured=20)
    items = run_workload(tc, backend="tpu", batch_size=16)
    assert items and items[0].unit == "pods/s"
    lat = [it for it in items
           if it.labels["Name"] == "scheduling_attempt_duration_seconds"
           and it.labels["result"] == "scheduled"]
    assert len(lat) == 1 and lat[0].data["Perc99"] > 0  # batch path observes


def test_metrics_collector_per_phase_dataitems():
    """The generalized metricsCollector: extension-point and batch-phase
    percentiles ride along as DataItems without touching the headline
    SchedulingThroughput / attempt-duration items."""
    tc = TEST_CASES["SchedulingBasic"](nodes=16, init_pods=4, measured=12)
    items = run_workload(tc, backend="tpu", batch_size=8)
    tput = [it for it in items if it.labels["Name"] == "SchedulingThroughput"]
    assert len(tput) == 1  # headline untouched
    ext = [it for it in items
           if it.labels["Name"] == "framework_extension_point_duration_seconds"]
    assert ext, [it.labels for it in items]
    for it in ext:
        assert it.unit == "s"
        assert it.data["Perc99"] >= it.data["Perc50"] >= 0
        assert it.data["Count"] > 0
        assert {"extension_point", "status", "profile"} <= set(it.labels)
    # the batched path contributes its device phase histogram too
    batch = [it for it in items
             if it.labels["Name"] == "tpu_batch_duration_seconds"]
    assert batch and all("phase" in it.labels for it in batch)


def test_metrics_collector_scrape_delta():
    """Collector snapshots at start: pre-phase samples are excluded,
    labelsets first seen mid-phase delta against zero."""
    from kubernetes_tpu.metrics import Registry, Histogram
    from kubernetes_tpu.perf.harness import MetricsCollector

    reg = Registry()
    h = reg.register(Histogram(
        "scheduler_framework_extension_point_duration_seconds", "t",
        ["extension_point", "status", "profile"]))
    h.observe(5.0, "filter", "Success", "p")  # pre-phase outlier
    col = MetricsCollector(reg)
    col.start()
    for _ in range(10):
        h.observe(0.002, "filter", "Success", "p")
    h.observe(0.004, "bind", "Success", "p")  # new labelset mid-phase
    items = col.collect()
    by_point = {it.labels["extension_point"]: it for it in items}
    assert by_point["filter"].data["Count"] == 10
    assert by_point["filter"].data["Perc99"] < 1.0  # outlier excluded
    assert by_point["bind"].data["Count"] == 1


def test_pod_anti_affinity_workload_tpu():
    tc = TEST_CASES["SchedulingPodAntiAffinity"](nodes=24, init_pods=8, measured=12)
    items = run_workload(tc, backend="tpu", batch_size=8)
    tput = [it for it in items if it.labels["Name"] == "SchedulingThroughput"]
    assert tput and tput[0].data["Average"] > 0


def test_pod_affinity_workload_tpu():
    tc = TEST_CASES["SchedulingPodAffinity"](nodes=16, init_pods=6, measured=8)
    items = run_workload(tc, backend="tpu", batch_size=8)
    tput = [it for it in items if it.labels["Name"] == "SchedulingThroughput"]
    assert tput and tput[0].data["Average"] > 0


def test_preemption_workload():
    tc = TEST_CASES["PreemptionBasic"](nodes=8, init_pods=24, measured=4)
    items = run_workload(tc, backend="oracle")
    assert items  # preemptors scheduled via evictions


def test_unschedulable_workload_completes():
    # reference shape (performance-config.yaml:437): unschedulable INIT pods
    # clog the queue while default-shaped MEASURED pods are timed
    tc = TEST_CASES["Unschedulable"](nodes=16, init_pods=5, measured=10)
    items = run_workload(tc, backend="oracle")
    tput = [it for it in items if it.labels["Name"] == "SchedulingThroughput"]
    assert tput and tput[0].data["Average"] > 0


def test_scheduling_secrets_workload_batched():
    # secret volumes never force the host fallback (reference parity: no
    # volume plugin looks at secret volume sources)
    tc = TEST_CASES["SchedulingSecrets"](nodes=16, init_pods=6, measured=8)
    items = run_workload(tc, backend="tpu", batch_size=8)
    tput = [it for it in items if it.labels["Name"] == "SchedulingThroughput"]
    assert tput and tput[0].data["Average"] > 0


def test_scheduling_intree_pvs_workload():
    tc = TEST_CASES["SchedulingInTreePVs"](nodes=16, init_pods=6, measured=8)
    items = run_workload(tc, backend="tpu", batch_size=8)
    tput = [it for it in items if it.labels["Name"] == "SchedulingThroughput"]
    assert tput and tput[0].data["Average"] > 0


def test_scheduling_csi_pvs_workload():
    tc = TEST_CASES["SchedulingCSIPVs"](nodes=12, init_pods=5, measured=6)
    items = run_workload(tc, backend="tpu", batch_size=8)
    tput = [it for it in items if it.labels["Name"] == "SchedulingThroughput"]
    assert tput and tput[0].data["Average"] > 0


def test_mixed_scheduling_base_pod_workload():
    tc = TEST_CASES["MixedSchedulingBasePod"](nodes=24, init_pods=4, measured=8)
    items = run_workload(tc, backend="tpu", batch_size=8)
    tput = [it for it in items if it.labels["Name"] == "SchedulingThroughput"]
    assert tput and tput[0].data["Average"] > 0


def test_preferred_affinity_workloads():
    for case in ("SchedulingPreferredPodAffinity",
                 "SchedulingPreferredPodAntiAffinity"):
        tc = TEST_CASES[case](nodes=12, init_pods=4, measured=6)
        items = run_workload(tc, backend="tpu", batch_size=8)
        tput = [it for it in items if it.labels["Name"] == "SchedulingThroughput"]
        assert tput and tput[0].data["Average"] > 0, case


def test_churn_workload():
    tc = TEST_CASES["SchedulingWithChurn"](nodes=16, measured=20)
    items = run_workload(tc, backend="oracle")
    assert items
