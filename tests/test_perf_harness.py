"""Perf harness tests at small sizes: ops run, throughput summary shape,
DataItems JSON schema matches util.go:331 (SchedulingThroughput DataItem),
both backends complete SchedulingBasic."""

import json

import pytest

from kubernetes_tpu.perf import TEST_CASES, data_items_to_json, run_workload
from kubernetes_tpu.perf.harness import ThroughputCollector


def test_throughput_collector_sampling():
    count = [0]
    col = ThroughputCollector(lambda: count[0], interval=1.0)
    t = 0.0
    col.start(t)
    for _ in range(5):
        count[0] += 100
        t += 1.0
        col.maybe_sample(t)
    s = col.summary()
    assert abs(s["Average"] - 100.0) < 1e-6
    assert s["Perc99"] >= s["Perc50"]


def test_scheduling_basic_oracle():
    tc = TEST_CASES["SchedulingBasic"](nodes=50, init_pods=20, measured=30)
    items = run_workload(tc, backend="oracle")
    tput = [it for it in items if it.labels["Name"] == "SchedulingThroughput"]
    assert len(tput) == 1
    assert tput[0].unit == "pods/s"
    assert tput[0].labels["TestCase"] == "SchedulingBasic/50Nodes"
    doc = json.loads(data_items_to_json(items))
    assert doc["version"] == "v1"
    assert "Average" in doc["dataItems"][0]["data"]
    # measured-phase attempt-latency percentiles ride along (util.go:204
    # metricsCollector analog)
    lat = [it for it in items
           if it.labels["Name"] == "scheduling_attempt_duration_seconds"
           and it.labels["result"] == "scheduled"]
    assert len(lat) == 1 and lat[0].unit == "s"
    assert lat[0].data["Perc99"] >= lat[0].data["Perc50"] > 0


def test_scheduling_basic_tpu_backend():
    tc = TEST_CASES["SchedulingBasic"](nodes=32, init_pods=10, measured=20)
    items = run_workload(tc, backend="tpu", batch_size=16)
    assert items and items[0].unit == "pods/s"
    lat = [it for it in items
           if it.labels["Name"] == "scheduling_attempt_duration_seconds"
           and it.labels["result"] == "scheduled"]
    assert len(lat) == 1 and lat[0].data["Perc99"] > 0  # batch path observes


def test_pod_anti_affinity_workload_tpu():
    tc = TEST_CASES["SchedulingPodAntiAffinity"](nodes=24, init_pods=8, measured=12)
    items = run_workload(tc, backend="tpu", batch_size=8)
    tput = [it for it in items if it.labels["Name"] == "SchedulingThroughput"]
    assert tput and tput[0].data["Average"] > 0


def test_pod_affinity_workload_tpu():
    tc = TEST_CASES["SchedulingPodAffinity"](nodes=16, init_pods=6, measured=8)
    items = run_workload(tc, backend="tpu", batch_size=8)
    tput = [it for it in items if it.labels["Name"] == "SchedulingThroughput"]
    assert tput and tput[0].data["Average"] > 0


def test_preemption_workload():
    tc = TEST_CASES["PreemptionBasic"](nodes=8, init_pods=24, measured=4)
    items = run_workload(tc, backend="oracle")
    assert items  # preemptors scheduled via evictions


def test_unschedulable_workload_completes():
    # reference shape (performance-config.yaml:437): unschedulable INIT pods
    # clog the queue while default-shaped MEASURED pods are timed
    tc = TEST_CASES["Unschedulable"](nodes=16, init_pods=5, measured=10)
    items = run_workload(tc, backend="oracle")
    tput = [it for it in items if it.labels["Name"] == "SchedulingThroughput"]
    assert tput and tput[0].data["Average"] > 0


def test_scheduling_secrets_workload_batched():
    # secret volumes never force the host fallback (reference parity: no
    # volume plugin looks at secret volume sources)
    tc = TEST_CASES["SchedulingSecrets"](nodes=16, init_pods=6, measured=8)
    items = run_workload(tc, backend="tpu", batch_size=8)
    tput = [it for it in items if it.labels["Name"] == "SchedulingThroughput"]
    assert tput and tput[0].data["Average"] > 0


def test_scheduling_intree_pvs_workload():
    tc = TEST_CASES["SchedulingInTreePVs"](nodes=16, init_pods=6, measured=8)
    items = run_workload(tc, backend="tpu", batch_size=8)
    tput = [it for it in items if it.labels["Name"] == "SchedulingThroughput"]
    assert tput and tput[0].data["Average"] > 0


def test_scheduling_csi_pvs_workload():
    tc = TEST_CASES["SchedulingCSIPVs"](nodes=12, init_pods=5, measured=6)
    items = run_workload(tc, backend="tpu", batch_size=8)
    tput = [it for it in items if it.labels["Name"] == "SchedulingThroughput"]
    assert tput and tput[0].data["Average"] > 0


def test_mixed_scheduling_base_pod_workload():
    tc = TEST_CASES["MixedSchedulingBasePod"](nodes=24, init_pods=4, measured=8)
    items = run_workload(tc, backend="tpu", batch_size=8)
    tput = [it for it in items if it.labels["Name"] == "SchedulingThroughput"]
    assert tput and tput[0].data["Average"] > 0


def test_preferred_affinity_workloads():
    for case in ("SchedulingPreferredPodAffinity",
                 "SchedulingPreferredPodAntiAffinity"):
        tc = TEST_CASES[case](nodes=12, init_pods=4, measured=6)
        items = run_workload(tc, backend="tpu", batch_size=8)
        tput = [it for it in items if it.labels["Name"] == "SchedulingThroughput"]
        assert tput and tput[0].data["Average"] > 0, case


def test_churn_workload():
    tc = TEST_CASES["SchedulingWithChurn"](nodes=16, measured=20)
    items = run_workload(tc, backend="oracle")
    assert items
