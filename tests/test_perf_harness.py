"""Perf harness tests at small sizes: ops run, throughput summary shape,
DataItems JSON schema matches util.go:331 (SchedulingThroughput DataItem),
both backends complete SchedulingBasic."""

import json

import pytest

from kubernetes_tpu.perf import TEST_CASES, data_items_to_json, run_workload
from kubernetes_tpu.perf.harness import ThroughputCollector


def test_throughput_collector_sampling():
    count = [0]
    col = ThroughputCollector(lambda: count[0], interval=1.0)
    t = 0.0
    col.start(t)
    for _ in range(5):
        count[0] += 100
        t += 1.0
        col.maybe_sample(t)
    s = col.summary()
    assert abs(s["Average"] - 100.0) < 1e-6
    assert s["Perc99"] >= s["Perc50"]


def test_scheduling_basic_oracle():
    tc = TEST_CASES["SchedulingBasic"](nodes=50, init_pods=20, measured=30)
    items = run_workload(tc, backend="oracle")
    assert len(items) == 1
    assert items[0].unit == "pods/s"
    assert items[0].labels["TestCase"] == "SchedulingBasic/50Nodes"
    doc = json.loads(data_items_to_json(items))
    assert doc["version"] == "v1"
    assert "Average" in doc["dataItems"][0]["data"]


def test_scheduling_basic_tpu_backend():
    tc = TEST_CASES["SchedulingBasic"](nodes=32, init_pods=10, measured=20)
    items = run_workload(tc, backend="tpu", batch_size=16)
    assert items and items[0].unit == "pods/s"


def test_preemption_workload():
    tc = TEST_CASES["PreemptionBasic"](nodes=8, init_pods=24, measured=4)
    items = run_workload(tc, backend="oracle")
    assert items  # preemptors scheduled via evictions


def test_unschedulable_workload_completes():
    tc = TEST_CASES["Unschedulable"](nodes=16, measured=10)
    items = run_workload(tc, backend="oracle")
    assert items == [] or all(it.unit == "pods/s" for it in items)


def test_churn_workload():
    tc = TEST_CASES["SchedulingWithChurn"](nodes=16, measured=20)
    items = run_workload(tc, backend="oracle")
    assert items
